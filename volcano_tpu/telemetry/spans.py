"""Host-side span tracing for the steady cycle (ISSUE 8 tentpole).

BENCH_r05 put the steady cycle's total p50 at ~129 ms against ~42 ms of
raw loop time, and nothing in the repo could say where the gap goes: the
flight recorder (PR 3) keeps per-cycle COUNTERS, not time. This module is
the wall-clock attribution layer — a low-overhead monotonic-clock span
API instrumenting every real seam of the steady cycle (scheduler
drain/open/actions, session extras/dispatch/readback/digest/apply, the
delta kernels' pack/diff/route/dispatch, sidecar serve/drain, and the
chaos recovery/degradation paths) — feeding three surfaces:

- **Latency rings.** Every completed span lands its duration in a bounded
  per-phase ring; :func:`phase_stats` serves p50/p95/p99 per phase — the
  SLO surface the multi-tenant item will reuse.
- **Pipeline occupancy.** The owners of the one-deep pipeline record the
  in-flight DEVICE window (dispatch→drain) per cycle;
  :func:`occupancy` intersects the union of non-``wait`` host spans with
  those windows to compute ``pipeline_overlap_fraction`` (how much of the
  device's flight time the host spent doing useful work) and
  ``bubble_ms`` (flight time the host sat idle or blocked) — per shard
  when the cycle runs sharded.
- **Exporters.** :func:`export_chrome_trace` emits Chrome trace-event
  JSON (Perfetto-loadable, ``python -m volcano_tpu.telemetry --trace
  out.json``; mergeable with a device-side trace via ``merge=``), and
  :func:`log_event` keeps a structured JSONL-ready event log for
  degradation-ladder transitions, digest trips, and recoveries
  (write-through to ``$VOLCANO_EVENT_LOG`` when set).

The hard constraint, shared with the in-graph telemetry block: spans are
HOST-ONLY. Nothing here touches a traced function, so every compiled
entry point's jaxpr is bit-identical with tracing on or off, and so are
the decisions (tests/test_spans.py pins the sha on the sync, pipelined,
and sharded loops). Default-on cheap: a disabled ``span()`` returns a
shared no-op context; an enabled one costs two ``perf_counter`` reads and
one deque append under a lock. ``VOLCANO_SPANS=0`` disables at import.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import defaultdict, deque
from typing import Dict, Iterable, List, Optional

_ENABLED = os.environ.get("VOLCANO_SPANS", "1").lower() not in (
    "0", "false", "off")

#: bounded buffers — memory is O(cap), never O(uptime)
_MAX_EVENTS = int(os.environ.get("VOLCANO_SPAN_EVENTS", 8192))
_RING = int(os.environ.get("VOLCANO_SPAN_RING", 512))
_MAX_LOG = int(os.environ.get("VOLCANO_EVENT_LOG_CAP", 1024))

#: device-window events ride a dedicated trace track (tid) per shard so
#: Perfetto renders them as their own lane under the host threads
_DEVICE_TID = 900

_LOCK = threading.Lock()
_EVENTS: deque = deque(maxlen=_MAX_EVENTS)
_PHASES: Dict[str, deque] = defaultdict(lambda: deque(maxlen=_RING))
#: fleet (ISSUE 12): per-(tenant, phase) duration rings, fed by the fleet
#: scheduler's per-tenant cycle accounting — the SLO latency surface cut
#: by tenant, same bounded-memory rule as the global rings
_TENANT_PHASES: Dict[tuple, deque] = defaultdict(
    lambda: deque(maxlen=_RING))
_CYCLE_ACC: Dict[str, float] = defaultdict(float)
_EVENT_LOG: deque = deque(maxlen=_MAX_LOG)
_TIDS: Dict[int, int] = {}
_TID_NAMES: Dict[int, str] = {}

#: one monotonic epoch per process; the wall anchor lets exporters (and a
#: device-trace merge) map span timestamps back to wall time
_T0 = time.perf_counter()
_WALL0 = time.time()


def now() -> float:
    """Seconds on the span clock (monotonic, process epoch)."""
    return time.perf_counter() - _T0


def enabled() -> bool:
    return _ENABLED


def set_enabled(on: bool) -> bool:
    """Flip tracing at runtime (tests; ops kill-switch). Returns the
    previous state. Buffers are kept — call :func:`reset` to drop them."""
    global _ENABLED
    prev, _ENABLED = _ENABLED, bool(on)
    return prev


def _tid() -> int:
    ident = threading.get_ident()
    t = _TIDS.get(ident)
    if t is None:
        with _LOCK:
            t = _TIDS.setdefault(ident, len(_TIDS) + 1)
            _TID_NAMES.setdefault(t, threading.current_thread().name)
    return t


class _NullSpan:
    """The disabled-path singleton: a no-op context manager."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullSpan()


class _Span:
    __slots__ = ("name", "cat", "args", "t0")

    def __init__(self, name: str, cat: str, args):
        self.name = name
        self.cat = cat
        self.args = args or None
        self.t0 = 0.0

    def __enter__(self):
        self.t0 = time.perf_counter() - _T0
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter() - _T0
        dur = t1 - self.t0
        tid = _tid()
        ms = dur * 1000.0
        with _LOCK:
            _EVENTS.append({"name": self.name, "cat": self.cat,
                            "ts": self.t0, "dur": dur, "tid": tid,
                            "args": self.args})
            _PHASES[self.name].append(ms)
            _CYCLE_ACC[self.name] += ms
        return False


def span(name: str, cat: str = "host", **args):
    """A nestable, thread-aware timing span: ``with span("pack"): ...``.

    ``cat`` tags the occupancy treatment: ``"wait"`` marks time the host
    is BLOCKED (device readback, ``block_until_ready``) — subtracted from
    the host-work union so a synchronous loop honestly reports ~zero
    pipeline overlap; ``"device"`` is reserved for device windows. Any
    other category counts as host work."""
    if not _ENABLED:
        return _NULL
    return _Span(name, cat, args)


def device_window(t0: float, t1: float, shard: Optional[int] = None,
                  shards: int = 1, **args) -> None:
    """Record one cycle's in-flight DEVICE window (dispatch→drain), in
    span-clock seconds (:func:`now`). The window deliberately runs to the
    DRAIN, not to device completion — it is the interval the pipeline has
    available for host/device overlap, which is what the occupancy
    analyzer prices. With ``shards > 1`` the single GSPMD launch covers
    every shard, so one call records the common window; pass ``shard=``
    if a path ever gets genuinely per-shard windows."""
    if not _ENABLED:
        return
    dur = max(float(t1) - float(t0), 0.0)
    a = dict(args)
    if shards and shards > 1:
        a["shards"] = int(shards)
    with _LOCK:
        _EVENTS.append({"name": "device_window", "cat": "device",
                        "ts": float(t0), "dur": dur,
                        "tid": _DEVICE_TID + (shard or 0),
                        "shard": shard, "shards": int(shards or 1),
                        "args": a or None})
        _PHASES["device.window"].append(dur * 1000.0)


# --------------------------------------------------------------- accessors
def _pct(sorted_vals: List[float], q: float) -> float:
    return sorted_vals[min(len(sorted_vals) - 1,
                           int(round(q * (len(sorted_vals) - 1))))]


def phase_stats() -> Dict[str, Dict[str, float]]:
    """{phase: {count, p50, p95, p99, mean, last, total_ms}} over each
    phase's duration ring (ms) — the SLO latency surface."""
    with _LOCK:
        rings = {k: list(v) for k, v in _PHASES.items() if v}
    out = {}
    for k in sorted(rings):
        vals = rings[k]
        s = sorted(vals)
        out[k] = {"count": len(s),
                  "p50": round(_pct(s, 0.50), 3),
                  "p95": round(_pct(s, 0.95), 3),
                  "p99": round(_pct(s, 0.99), 3),
                  "mean": round(sum(s) / len(s), 3),
                  "last": round(vals[-1], 3),
                  "total_ms": round(sum(s), 3)}
    return out


def record_tenant_phase(tenant: str, phase: str, ms: float) -> None:
    """Land one per-tenant phase duration (ms) in the tenant's ring.
    Called by the fleet scheduler per served tenant per cycle; a plain
    deque append, so the fleet loop pays the same O(1) the global rings
    cost."""
    if not _ENABLED:
        return
    with _LOCK:
        _TENANT_PHASES[(str(tenant), str(phase))].append(float(ms))


def tenant_phase_stats() -> Dict[str, Dict[str, Dict[str, float]]]:
    """{tenant: {phase: {count, p50, p95, p99, mean, last}}} over the
    per-tenant duration rings — :func:`phase_stats` cut by tenant."""
    with _LOCK:
        rings = {k: list(v) for k, v in _TENANT_PHASES.items() if v}
    out: Dict[str, Dict[str, Dict[str, float]]] = {}
    for (tenant, phase) in sorted(rings):
        vals = rings[(tenant, phase)]
        s = sorted(vals)
        out.setdefault(tenant, {})[phase] = {
            "count": len(s),
            "p50": round(_pct(s, 0.50), 3),
            "p95": round(_pct(s, 0.95), 3),
            "p99": round(_pct(s, 0.99), 3),
            "mean": round(sum(s) / len(s), 3),
            "last": round(vals[-1], 3)}
    return out


def drain_cycle_summary() -> Optional[Dict[str, float]]:
    """Per-phase ms accumulated since the last drain, then reset — the
    flight-recorder's per-cycle span summary (plain floats: JSON- and
    pickle-safe by construction). Under the one-deep pipeline a cycle's
    summary covers the host work performed during ITS run_once, which
    mixes the tail of the previous cycle's drain — that is the honest
    attribution of what the loop actually paid that turn."""
    with _LOCK:
        if not _CYCLE_ACC:
            return None
        acc = {k: round(v, 3) for k, v in _CYCLE_ACC.items()}
        _CYCLE_ACC.clear()
    return acc


def events() -> List[dict]:
    """Copies of the structured event log entries (oldest first)."""
    with _LOCK:
        return [dict(e) for e in _EVENT_LOG]


def log_event(kind: str, **fields) -> Optional[dict]:
    """Append one structured event (degradation transition, digest trip,
    recovery; the scenario engine's per-cycle ``scenario_cycle`` and
    end-of-run ``scenario_done`` quality records) to the bounded log;
    write-through as one JSON line to ``$VOLCANO_EVENT_LOG`` when set
    (best-effort — the log must never take the cycle down)."""
    if not _ENABLED:
        return None
    entry = dict(fields)
    entry["kind"] = kind
    entry["ts_ms"] = round(now() * 1000.0, 3)
    entry["wall_ts"] = round(_WALL0 + entry["ts_ms"] / 1000.0, 6)
    with _LOCK:
        _EVENT_LOG.append(entry)
    path = os.environ.get("VOLCANO_EVENT_LOG")
    if path:
        try:
            with open(path, "a") as f:
                f.write(json.dumps(entry, default=str) + "\n")
        except OSError:
            pass
    return entry


# --------------------------------------------------------------- occupancy
def _merge(iv: List[tuple]) -> List[tuple]:
    """Coalesce [start, end) intervals into a sorted disjoint union."""
    out: List[List[float]] = []
    for s, e in sorted(iv):
        if e <= s:
            continue
        if out and s <= out[-1][1]:
            out[-1][1] = max(out[-1][1], e)
        else:
            out.append([s, e])
    return [tuple(x) for x in out]


def _subtract(a: List[tuple], b: List[tuple]) -> List[tuple]:
    """Disjoint-union ``a`` minus disjoint-union ``b``."""
    out = []
    for s, e in a:
        cur = s
        for bs, be in b:
            if be <= cur or bs >= e:
                continue
            if bs > cur:
                out.append((cur, bs))
            cur = max(cur, be)
            if cur >= e:
                break
        if cur < e:
            out.append((cur, e))
    return out


def _window_depth(w: dict) -> int:
    """Pipeline depth a device window was dispatched under (args tag from
    the session/sidecar drain; pre-depth events count as 1)."""
    try:
        return int((w.get("args") or {}).get("depth") or 1)
    except (TypeError, ValueError):
        return 1


def compute_occupancy(evts: Iterable[dict]) -> Dict[str, object]:
    """Pure occupancy math over span/window event dicts (unit-testable on
    synthetic inputs). Host work is computed PER THREAD — each thread's
    non-``wait``/non-``device`` span union minus its own ``wait`` union —
    then unioned across threads: the async pack worker's real work counts
    as overlap even while the main thread blocks in a drain, and one
    thread's wait never blanks another thread's work (the global merge
    the pre-depth analyzer did). Nesting never double-counts, and an
    outer span covering a blocked readback doesn't masquerade as overlap
    (the synchronous loop's window is ~all wait, so it honestly reports
    ~0). For each device window: ``overlap`` is the host-work time inside
    it, ``bubble`` the remainder. Windows tagged with a dispatch
    ``depth`` additionally group into ``per_depth`` (the depth-k
    acceptance surface: overlap fraction reported per pipeline depth)."""
    evts = list(evts)
    windows = [e for e in evts if e.get("cat") == "device"]
    host_by_tid: Dict[object, list] = {}
    wait_by_tid: Dict[object, list] = {}
    for e in evts:
        cat = e.get("cat")
        if cat == "device":
            continue
        dst = wait_by_tid if cat == "wait" else host_by_tid
        dst.setdefault(e.get("tid", 0), []).append(
            (e["ts"], e["ts"] + e["dur"]))
    busy = _merge([iv for tid, host in host_by_tid.items()
                   for iv in _subtract(_merge(host),
                                       _merge(wait_by_tid.get(tid, [])))])

    def analyze(ws):
        w_s = o_s = 0.0
        for w in ws:
            a, b = w["ts"], w["ts"] + w["dur"]
            w_s += b - a
            o_s += sum(min(b, e) - max(a, s)
                       for s, e in busy if e > a and s < b)
        return {"windows": len(ws),
                "window_ms": round(w_s * 1000.0, 3),
                "overlap_ms": round(o_s * 1000.0, 3),
                "bubble_ms": round((w_s - o_s) * 1000.0, 3),
                "pipeline_overlap_fraction":
                    (round(o_s / w_s, 4) if w_s > 0 else None)}

    out = analyze(windows)
    shard_ids = sorted({w.get("shard") for w in windows
                        if w.get("shard") is not None})
    n_shards = max([int(w.get("shards") or 1) for w in windows], default=1)
    per_shard = None
    if shard_ids or n_shards > 1:
        ids = shard_ids or list(range(n_shards))
        # a shard=None window is the common GSPMD launch: it covers every
        # shard, so it contributes to each shard's view
        per_shard = {str(s): analyze([w for w in windows
                                      if w.get("shard") in (None, s)])
                     for s in ids}
    out["per_shard"] = per_shard
    depths = sorted({_window_depth(w) for w in windows})
    out["per_depth"] = (
        {str(d): analyze([w for w in windows if _window_depth(w) == d])
         for d in depths}
        if depths and depths != [1] else None)
    return out


def occupancy() -> Dict[str, object]:
    """Occupancy analysis over the live event ring: how much of the
    in-flight device windows the host covered with real (non-wait) work,
    aggregate, per shard, and per pipeline depth, tagged with the JAX
    backend the windows ran on."""
    with _LOCK:
        evts = [dict(e) for e in _EVENTS]
    out = compute_occupancy(evts)
    try:
        import jax
        out["backend"] = jax.default_backend()
    except Exception:       # uninitialized/absent backend: tag stays None
        out["backend"] = None
    return out


# --------------------------------------------------------------- exporters
def export_chrome_trace(path: Optional[str] = None,
                        merge=None) -> Dict[str, object]:
    """The span + device-window rings as Chrome trace-event JSON
    (Perfetto / chrome://tracing loadable): complete ("X") events in
    microseconds on the span clock, with thread/track-name metadata.
    ``merge`` accepts another trace dict or a path to one (e.g. a
    converted ``jax.profiler`` device trace) whose ``traceEvents`` are
    appended under their own pid. Writes to ``path`` when given; returns
    the trace dict either way."""
    with _LOCK:
        evts = [dict(e) for e in _EVENTS]
        tid_names = dict(_TID_NAMES)
        log = [dict(e) for e in _EVENT_LOG]
    tev: List[dict] = [{"name": "process_name", "ph": "M", "pid": 1,
                        "args": {"name": "volcano_tpu host"}}]
    device_tids = {}
    for e in evts:
        ev = {"name": e["name"], "cat": e["cat"], "ph": "X",
              "ts": round(e["ts"] * 1e6, 3), "dur": round(e["dur"] * 1e6, 3),
              "pid": 1, "tid": e["tid"]}
        if e.get("args"):
            ev["args"] = e["args"]
        if e.get("cat") == "device":
            shard = e.get("shard")
            device_tids[e["tid"]] = ("device" if shard is None
                                     else f"device shard {shard}")
        tev.append(ev)
    # degradation / digest-trip / recovery events as instants on track 0
    for e in log:
        tev.append({"name": e.get("kind", "event"), "cat": "event",
                    "ph": "i", "s": "p",
                    "ts": round(e.get("ts_ms", 0.0) * 1e3, 3),
                    "pid": 1, "tid": 0,
                    "args": {k: v for k, v in e.items()
                             if k not in ("ts_ms",)}})
    for tid, name in tid_names.items():
        tev.append({"name": "thread_name", "ph": "M", "pid": 1,
                    "tid": tid, "args": {"name": name}})
    for tid, name in device_tids.items():
        tev.append({"name": "thread_name", "ph": "M", "pid": 1,
                    "tid": tid, "args": {"name": name}})
    trace = {"traceEvents": tev, "displayTimeUnit": "ms",
             "otherData": {"clock": "perf_counter",
                           "wall_epoch": round(_WALL0, 6)}}
    if merge is not None:
        try:
            if isinstance(merge, str):
                with open(merge) as f:
                    merge = json.load(f)
            extra = merge.get("traceEvents", merge) \
                if isinstance(merge, dict) else merge
            trace["traceEvents"] = list(trace["traceEvents"]) + list(extra)
        except Exception:  # merge is best-effort, never fatal
            pass
    if path:
        with open(path, "w") as f:
            json.dump(trace, f)
    return trace


def export_event_log(path: str) -> int:
    """Dump the structured event log as JSONL; returns the line count."""
    entries = events()
    with open(path, "w") as f:
        for e in entries:
            f.write(json.dumps(e, default=str) + "\n")
    return len(entries)


def publish_gauges(metrics=None, include_occupancy: bool = False) -> None:
    """Export the phase rings as ``span_phase_ms{phase=...,q=...}`` gauges
    (and, when asked, the occupancy numbers) into the METRICS registry.
    Occupancy is opt-in because it scans the whole event ring — the
    per-cycle scheduler publish sticks to the cheap phase stats; bench,
    the CLI, and the dashboard ask for the full picture."""
    if metrics is None:
        from ..metrics import METRICS as metrics
    for phase, st in phase_stats().items():
        for q in ("p50", "p95", "p99"):
            metrics.set_gauge("span_phase_ms",
                              {"phase": phase, "q": q}, st[q])
    for tenant, phases in tenant_phase_stats().items():
        for phase, st in phases.items():
            for q in ("p50", "p95", "p99"):
                metrics.set_gauge("span_phase_ms",
                                  {"phase": phase, "q": q,
                                   "tenant": tenant}, st[q])
    if include_occupancy:
        occ = occupancy()
        if occ.get("pipeline_overlap_fraction") is not None:
            metrics.set_gauge("pipeline_overlap_fraction", None,
                              occ["pipeline_overlap_fraction"])
            metrics.set_gauge("pipeline_bubble_ms", None, occ["bubble_ms"])


def reset() -> None:
    """Drop every buffer (tests / bench isolation). Thread-id mappings
    are kept — they are stable identities, not measurements."""
    with _LOCK:
        _EVENTS.clear()
        _PHASES.clear()
        _TENANT_PHASES.clear()
        _CYCLE_ACC.clear()
        _EVENT_LOG.clear()
