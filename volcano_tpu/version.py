"""Version stamping (pkg/version/version.go analog).

The reference stamps GitSHA/Built/Version at link time via ldflags and
prints them from every binary's --version flag; here the stamp is a module
constant plus a best-effort git probe, surfaced by ``vcctl version`` and
the v* shims' --version.
"""

from __future__ import annotations

import os
import subprocess

__version__ = "5.0.0"
API_VERSION = "v1alpha1"


def git_sha() -> str:
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, text=True, timeout=5)
        if out.returncode == 0:
            return out.stdout.strip()
    except (OSError, subprocess.SubprocessError):
        pass
    return "unknown"


def version_string() -> str:
    """Multi-line stamp like PrintVersionAndExit (version.go)."""
    import sys
    return (f"Version: {__version__}\n"
            f"GitSHA: {git_sha()}\n"
            f"API Version: {API_VERSION}\n"
            f"Python Version: {sys.version.split()[0]}")
