"""VCS4 binary snapshot wire format — serializer side.

The snapshot payload that crosses the API-layer boundary (SURVEY.md
section 5.8: cluster state serialized to the scheduling sidecar, decisions
returned).  ``serialize(ci)`` flattens a :class:`ClusterInfo` into one
little-endian buffer that the native packer (packer.cc) turns into dense
arrays; the layout keeps every derived encoding decision (resource-dimension
order, label/taint/toleration hash encodings, queue-hierarchy parent
pointers) on the producer side so consumers are dumb and fast.

VCS4 is COLUMNAR for the hot sections: the node/job/task data ship as
whole numpy columns (strings as a length-array + one joined blob,
fixed-width fields as one array each, variable-width hash sets as a
count-array + one flat array), so serialization is a single python pass
per entity filling preallocated arrays + bulk ``tobytes``, and the
decoders are straight ``memcpy``/``frombuffer`` column reads. The
record-per-entity VCS2 layout spent ~2 s in python struct packing at 10k
nodes / 100k tasks; this layout serializes the same snapshot in a few
hundred ms and parses faster too.

Record layouts are documented at the top of packer.cc; this module is the
single source of truth for producing them.
"""

from __future__ import annotations

import operator
import struct
from typing import List, Optional, Tuple

import numpy as np

from ..api import (GPU_MEMORY_RESOURCE, ClusterInfo, PodGroupPhase,
                   QueueState, as_node_term)
from ..arrays import labels as L
from ..arrays.pack import (_READY_STATUSES, _VALID_ONLY_STATUSES,
                           _toleration_rows, queue_capability_row,
                           queue_parent_depth, resource_dims)
from ..arrays.schema import IndexMaps

MAGIC = 0x34534356  # "VCS4"
EXTRAS_MAGIC = 0x31584356  # "VCX1"

#: extras-frame section tags (serialize_extras / decode side)
TAG_OR_GROUPS = 1
TAG_NA_GROUPS = 2
TAG_PORTS = 3
TAG_VOLUMES = 4

#: status partitions for the single-pass job counts (job_info.go:560-600),
#: shared with arrays/pack (the single source) as frozensets for the loop
_READY_SET = frozenset(_READY_STATUSES)
_VALID_ONLY_SET = frozenset(_VALID_ONLY_STATUSES)

_u32 = struct.Struct("<I").pack
_i32 = struct.Struct("<i").pack
_f32 = struct.Struct("<f").pack


def _s(out: List[bytes], s: str) -> None:
    b = s.encode("utf-8")
    out.append(_u32(len(b)))
    out.append(b)


def _fvec(out: List[bytes], vec) -> None:
    out.append(vec.astype("<f4").tobytes())


def _string_column(out: List[bytes], strings: List[str]) -> None:
    """u32 blob_len | u32[n] lens | bytes blob."""
    encoded = [s.encode("utf-8") for s in strings]
    blob = b"".join(encoded)
    out.append(_u32(len(blob)))
    out.append(np.fromiter((len(b) for b in encoded), dtype="<u4",
                           count=len(encoded)).tobytes())
    out.append(blob)


def _ragged_column(out: List[bytes], rows: List[list], per: int = 1,
                   dtype: str = "<i4") -> None:
    """u32 total | u32[n] counts | dtype[total*per] flat values.

    ``per`` is the arity of one logical entry (e.g. 3 for taint triples);
    counts are logical entries, the flat array carries per*total values."""
    import itertools
    counts = np.fromiter((len(r) for r in rows), dtype="<u4",
                         count=len(rows))
    flat_len = int(counts.sum())
    out.append(_u32(flat_len // per))
    out.append((counts // per).astype("<u4", copy=False).tobytes())
    if flat_len:
        flat = np.fromiter(itertools.chain.from_iterable(rows), dtype=dtype,
                           count=flat_len)
    else:
        flat = np.empty(0, dtype=dtype)
    out.append(flat.tobytes())


def _queue_ns_chunks(ci: ClusterInfo, queue_names: List[str],
                     ns_names: List[str], dims: List[str]) -> List[bytes]:
    """The queue + namespace record chunks (shared by serialize and the
    incremental patcher — Q/S are small, so these rebuild every cycle)."""
    out: List[bytes] = []
    parents, depths = queue_parent_depth(ci, queue_names)
    for i, name in enumerate(queue_names):
        q = ci.queues[name]
        _s(out, name)
        out.append(_f32(max(q.weight, 0)))
        _fvec(out, queue_capability_row(q, dims))
        out.append(bytes([1 if q.reclaimable else 0,
                          1 if q.state == QueueState.OPEN else 0]))
        out.append(_i32(parents[i]))
        out.append(_i32(depths[i]))
        hw = q.hierarchy_weight_values()
        out.append(_f32(hw[-1] if hw else 1.0))
        # full hdrf annotations: the receiver rebuilds the exact hierarchy
        # tree (arrays/hierarchy.build_from_specs) from these
        _s(out, q.hierarchy)
        _s(out, q.hierarchy_weights)
    for name in ns_names:
        _s(out, name)
        w = ci.namespaces[name].weight if name in ci.namespaces else 1
        out.append(_f32(max(w, 1)))
    return out


def serialize(ci: ClusterInfo,
              _capture: Optional[dict] = None) -> Tuple[bytes, IndexMaps]:
    """ClusterInfo -> (VCS4 buffer, host-side decode maps).

    ``_capture`` (IncrementalWire's hook) receives the chunk list, the
    dynamic column arrays, and the layout bookkeeping needed to patch
    later cycles in place."""
    dims = resource_dims(ci)
    R = len(dims)
    maps = IndexMaps(resource_names=dims)

    queue_names = sorted(ci.queues)
    node_names = sorted(ci.nodes)
    job_uids = sorted(ci.jobs)
    ns_names = sorted(ci.namespaces) or ["default"]
    maps.queue_names = queue_names
    maps.node_names = node_names
    maps.job_uids = job_uids
    maps.namespace_names = ns_names
    maps.queue_index = {n: i for i, n in enumerate(queue_names)}
    maps.node_index = {n: i for i, n in enumerate(node_names)}
    maps.job_index = {u: i for i, u in enumerate(job_uids)}
    ns_index = {n: i for i, n in enumerate(ns_names)}

    nn = len(node_names)
    nj = len(job_uids)
    nt = sum(len(ci.jobs[u].tasks) for u in job_uids)

    out: List[bytes] = [
        _u32(MAGIC), _u32(R), _u32(len(queue_names)), _u32(len(ns_names)),
        _u32(nn), _u32(nj), _u32(nt),
    ]
    for d in dims:
        _s(out, d)

    # ---- queues (per-record; Q is small) ---------------------------------
    _q_start = len(out)
    out.extend(_queue_ns_chunks(ci, queue_names, ns_names, dims))
    _q_end = len(out)     # queue+namespace records: [_q_start, _q_end)

    # ---- nodes (columnar) ------------------------------------------------
    res_mats = [np.empty((nn, R), dtype="<f4") for _ in range(6)]
    pod_count = np.empty(nn, dtype="<i4")
    max_pods = np.empty(nn, dtype="<i4")
    sched = np.empty(nn, dtype="u1")
    gpu_rows: List[List[float]] = []
    label_rows: List[List[int]] = []
    taint_rows: List[List[int]] = []
    dims_t = tuple(dims)
    for i, name in enumerate(node_names):
        node = ci.nodes[name]
        for m, res in zip(res_mats,
                          (node.idle, node.used, node.releasing,
                           node.pipelined, node.allocatable,
                           node.capability)):
            q = res.quantities
            m[i] = [q.get(d, 0.0) for d in dims_t]
        pod_count[i] = node.pod_count()
        max_pods[i] = node.max_pods
        sched[i] = 1 if (node.ready and not node.unschedulable) else 0
        row: List[float] = []
        for dev in node.gpu_devices:
            row.append(dev.memory)
            row.append(dev.used_memory())
        gpu_rows.append(row)
        label_rows.append(L.label_hashes(node.labels))
        trow: List[int] = []
        for t in node.taints:
            trow.extend((L.stable_hash(f"{t.key}={t.value}"),
                         L.stable_hash(t.key), L.effect_code(t.effect)))
        taint_rows.append(trow)
    _string_column(out, node_names)
    _node_dyn_start = len(out)
    for m in res_mats:
        out.append(m.tobytes())
    out.append(pod_count.tobytes())
    out.append(max_pods.tobytes())
    out.append(sched.tobytes())
    _ragged_column(out, gpu_rows, per=2, dtype="<f4")
    _ragged_column(out, label_rows)
    _ragged_column(out, taint_rows, per=3)

    # ---- jobs (columnar) -------------------------------------------------
    j_min = np.empty(nj, dtype="<i4")
    j_queue = np.empty(nj, dtype="<i4")
    j_ns = np.empty(nj, dtype="<i4")
    j_prio = np.empty(nj, dtype="<i4")
    j_ts = np.empty(nj, dtype="<f8")
    j_ready = np.empty(nj, dtype="<i4")
    j_alloc = np.empty((nj, R), dtype="<f4")
    j_minres = np.empty((nj, R), dtype="<f4")
    j_flags = np.empty((nj, 3), dtype="u1")   # pending, gang_valid, preempt
    qidx_get = maps.queue_index.get
    nsidx_get = ns_index.get
    pending_phase = PodGroupPhase.PENDING
    for i, uid in enumerate(job_uids):
        job = ci.jobs[uid]
        j_min[i] = job.min_available
        j_queue[i] = qidx_get(job.queue, -1)
        j_ns[i] = nsidx_get(job.namespace, 0)
        j_prio[i] = job.priority
        j_ts[i] = job.creation_timestamp
        # one pass over the status index instead of the ready/valid
        # accessor pair re-walking it (ready_task_num/is_valid semantics,
        # job_info.go:560-600 + gang.go:52-81)
        ready = valid = 0
        for s, tasks_of in job.task_status_index.items():
            n = len(tasks_of)
            if s in _READY_SET:
                ready += n
                valid += n
            elif s in _VALID_ONLY_SET:
                valid += n
        j_ready[i] = ready
        q = job.allocated.quantities
        j_alloc[i] = [q.get(d, 0.0) for d in dims_t]
        q = job.min_resources.quantities
        j_minres[i] = [q.get(d, 0.0) for d in dims_t]
        gang_valid = (valid >= job.min_available
                      and job.check_task_min_available())
        j_flags[i, 0] = job.pod_group_phase == pending_phase
        j_flags[i, 1] = gang_valid
        j_flags[i, 2] = job.preemptable
    _string_column(out, job_uids)
    _job_dyn_start = len(out)
    for arr in (j_min, j_queue, j_ns, j_prio, j_ts, j_ready, j_alloc,
                j_minres, j_flags):
        out.append(arr.tobytes())

    # ---- tasks (columnar) ------------------------------------------------
    # Column lists + one bulk numpy conversion per column: the per-task
    # numpy scalar stores and per-task np.array(_vec) calls were the
    # serialize bottleneck at 100k tasks (VERDICT round 3, 1 s cycle
    # budget item).
    t_uids: List[str] = []
    job_task_counts = np.fromiter(
        (len(ci.jobs[u].tasks) for u in job_uids), dtype="<i4", count=nj)
    resreq_rows: List[list] = []
    status_col: List[int] = []
    prio_col: List[int] = []
    node_col: List[int] = []
    flag_col: List[int] = []      # interleaved best_effort, preemptable
    gpu_col: List[float] = []
    sel_rows: List[List[int]] = []
    tol_rows: List[List[int]] = []
    nakey_col: List[int] = []     # preferred-affinity template split key
    _nakey_cache: dict = {}
    node_index_get = maps.node_index.get
    task_index = maps.task_index
    gpu_dim = GPU_MEMORY_RESOURCE
    stable_hash = L.stable_hash
    # one C-level bulk fetch per task instead of ~10 LOAD_ATTRs
    fields_of = operator.attrgetter(
        "uid", "resreq.quantities", "status", "priority", "node_name",
        "best_effort", "preemptable", "node_selector", "affinity_required",
        "tolerations", "affinity_preferred")
    uid_append = t_uids.append
    resreq_append = resreq_rows.append
    status_append = status_col.append
    prio_append = prio_col.append
    node_append = node_col.append
    flag_append = flag_col.append
    gpu_append = gpu_col.append
    sel_append = sel_rows.append
    tol_append = tol_rows.append
    empty: List[int] = []
    ti = 0
    for uid in job_uids:
        for task in ci.jobs[uid].tasks.values():
            (tuid, q, status, prio, node_name, best_effort, preemptable,
             node_selector, affinity_required, tolerations,
             affinity_preferred) = fields_of(task)
            uid_append(tuid)
            task_index[tuid] = ti
            resreq_append([q.get(d, 0.0) for d in dims_t])
            status_append(status)
            prio_append(prio)
            node_append(node_index_get(node_name, -1))
            flag_append(best_effort)
            flag_append(preemptable)
            gpu_append(q.get(gpu_dim, 0.0))
            if node_selector or affinity_required:
                required = dict(node_selector)
                if len(affinity_required) == 1:
                    lone = as_node_term(affinity_required[0])
                    if lone.is_pure_labels():
                        required.update(lone.match_labels)
                # multi-term OR affinity and expression terms: see
                # arrays/pack.py (the packed row carries the nodeSelector
                # conjunction only; the rest rides the VCS4 extras frame)
                sel_append(sorted(
                    stable_hash(f"{k}={v}") for k, v in required.items()))
            else:
                sel_append(empty)
            if tolerations:
                h, e, m = _toleration_rows(tolerations)
                trow: List[int] = []
                for hh, ee, mm in zip(h, e, m):
                    trow.extend((hh, ee, mm))
                tol_append(trow)
            else:
                tol_append(empty)
            if affinity_preferred:
                # preferred terms split predicate templates (their score
                # rows gather by template id): ship a stable signature
                # hash for the packer's template key — the hashed analog
                # of arrays/pack.py's na_sig component
                sig = tuple(sorted((as_node_term(m).signature(), w)
                                   for m, w in affinity_preferred))
                k = _nakey_cache.get(sig)
                if k is None:
                    k = stable_hash(repr(sig))
                    _nakey_cache[sig] = k
                nakey_col.append(k)
            else:
                nakey_col.append(0)
            ti += 1
    t_job = np.repeat(np.arange(nj, dtype="<i4"), job_task_counts)
    t_resreq = np.array(resreq_rows, dtype="<f4").reshape(nt, R)
    t_status = np.fromiter(status_col, dtype="<i4", count=nt)
    t_prio = np.fromiter(prio_col, dtype="<i4", count=nt)
    t_node = np.fromiter(node_col, dtype="<i4", count=nt)
    t_flags = np.fromiter(flag_col, dtype="u1", count=2 * nt).reshape(nt, 2)
    t_gpu = np.fromiter(gpu_col, dtype="<f4", count=nt)
    maps.task_uids = t_uids
    _string_column(out, t_uids)
    _task_dyn_start = len(out)
    for arr in (t_job, t_resreq, t_status, t_prio, t_node, t_flags, t_gpu):
        out.append(arr.tobytes())
    _ragged_column(out, sel_rows)
    _ragged_column(out, tol_rows, per=3)
    out.append(np.fromiter(nakey_col, dtype="<i4", count=nt).tobytes())

    if _capture is not None:
        # per-job contiguous task ranges + uid tuples (validity checks)
        ranges = {}
        off = 0
        for i, uid in enumerate(job_uids):
            cnt = int(job_task_counts[i])
            ranges[uid] = (off, tuple(t_uids[off:off + cnt]))
            off += cnt
        _capture.update(
            out=out, maps=maps, dims=dims_t,
            counts=(len(queue_names), len(ns_names), nn, nj, nt),
            q_range=(_q_start, _q_end),
            node_dyn_start=_node_dyn_start,
            job_dyn_start=_job_dyn_start,
            task_dyn_start=_task_dyn_start,
            res_mats=res_mats, pod_count=pod_count, max_pods=max_pods,
            sched=sched,
            job_cols=dict(j_min=j_min, j_queue=j_queue, j_ns=j_ns,
                          j_prio=j_prio, j_ts=j_ts, j_ready=j_ready,
                          j_alloc=j_alloc, j_minres=j_minres,
                          j_flags=j_flags),
            task_cols=dict(t_resreq=t_resreq, t_status=t_status,
                           t_prio=t_prio, t_node=t_node, t_flags=t_flags,
                           t_gpu=t_gpu),
            task_ranges=ranges,
            gpu_nodes={n for n in node_names if ci.nodes[n].gpu_devices},
        )
    return b"".join(out), maps


def serialize_extras(ci: ClusterInfo, maps: IndexMaps, conf=None) -> bytes:
    """Host-computed session extras -> VCX1 frame (the wire half of
    framework/host_extras.py). Ships the node-affinity OR-group masks,
    preferred-score group rows, and port/volume sections so the sidecar's
    served cycle makes bit-identical decisions to an in-process Session
    running the same conf — one full-fidelity production path, like the
    reference's (cache.go:712-811). Returns b"" when the conf needs none
    of it (the sidecar then runs with neutral extras, exactly as the
    session would)."""
    from ..framework.host_extras import (conf_na_weight,
                                         node_affinity_sections,
                                         port_volume_sections)
    w, pred = conf_na_weight(conf)
    if not (w or pred):
        return b""
    nt = len(maps.task_uids)
    nn = len(maps.node_names)
    sections: List[bytes] = []

    def add(tag: int, payload: bytes) -> None:
        sections.append(_u32(tag) + _u32(len(payload)) + payload)

    aff = node_affinity_sections(ci, maps.node_names, maps.task_index,
                                 w, pred)
    if aff["or_masks"].shape[0]:
        add(TAG_OR_GROUPS,
            _u32(aff["or_masks"].shape[0])
            + aff["task_or_group"].astype("<i4").tobytes()
            + aff["or_masks"].astype("u1").tobytes())
    if aff["na_rows"].shape[0]:
        add(TAG_NA_GROUPS,
            _u32(aff["na_rows"].shape[0])
            + aff["task_na_group"].astype("<i4").tobytes()
            + aff["na_rows"].astype("<f4").tobytes())
    if pred:
        pv = port_volume_sections(ci, maps.node_index, maps.task_index)
        if pv["task_ports"] or pv["node_ports"]:
            buf: List[bytes] = [_u32(pv["n_pending_ports"])]
            tp_rows = [pv["task_ports"].get(ti, []) for ti in range(nt)]
            np_rows = [pv["node_ports"].get(ni, []) for ni in range(nn)]
            _ragged_column(buf, tp_rows)
            _ragged_column(buf, np_rows)
            add(TAG_PORTS, b"".join(buf))
        if (not pv["vol_ok"].all()) or (pv["vol_node"] >= 0).any():
            add(TAG_VOLUMES,
                pv["vol_ok"].astype("u1").tobytes()
                + pv["vol_node"].astype("<i4").tobytes())
    if not sections:
        return b""
    return b"".join([_u32(EXTRAS_MAGIC), _u32(len(sections))] + sections)


class IncrementalWire:
    """Steady-state wire serializer — refresh_snapshot's analog at the
    wire boundary (VERDICT r4 #1, the served half).

    First call performs a full :func:`serialize`, capturing the chunk list
    and the dynamic column arrays; later calls patch only the dirty
    entities' rows and re-join, so a 5% churn cycle pays tens of
    milliseconds instead of the full object walk. Exact under the same
    contract as Session.refresh_snapshot: unchanged entity sets, unchanged
    per-job task uid lists, and immutable task/node specs (selectors,
    tolerations, affinity, labels, taints, GPU devices — the job-update
    webhook's immutability rules); anything else falls back to a full
    serialize. Produces byte-identical buffers to :func:`serialize`
    (tests/test_native_pack.py::TestIncrementalWire).
    """

    _JOB_COL_ORDER = ("j_min", "j_queue", "j_ns", "j_prio", "j_ts",
                      "j_ready", "j_alloc", "j_minres", "j_flags")
    _TASK_COL_ORDER = ("t_resreq", "t_status", "t_prio", "t_node",
                       "t_flags", "t_gpu")

    def __init__(self):
        self._c: Optional[dict] = None
        self.full_serializes = 0
        self.incremental_serializes = 0

    def _full(self, ci: ClusterInfo) -> Tuple[bytes, IndexMaps]:
        cap: dict = {}
        buf, maps = serialize(ci, _capture=cap)
        self._c = cap
        self.full_serializes += 1
        return buf, maps

    def serialize(self, ci: ClusterInfo, dirty_jobs=(), dirty_nodes=(),
                  structural: bool = False) -> Tuple[bytes, IndexMaps]:
        c = self._c
        if structural or c is None:
            return self._full(ci)
        maps = c["maps"]
        nq, ns_c, nn, nj, _nt = c["counts"]
        ns_names = sorted(ci.namespaces) or ["default"]
        if (len(ci.queues) != nq or len(ci.nodes) != nn
                or len(ci.jobs) != nj or len(ns_names) != ns_c
                or ns_names != maps.namespace_names
                or any(q not in maps.queue_index for q in ci.queues)
                or any(u not in maps.job_index for u in dirty_jobs)
                or any(n not in maps.node_index for n in dirty_nodes)):
            return self._full(ci)
        out = c["out"]
        dims_t = c["dims"]

        # queue + namespace records: rebuilt wholesale (small); any length
        # drift (renames, annotation edits) forces the full path
        qchunks = _queue_ns_chunks(ci, maps.queue_names, ns_names,
                                   list(dims_t))
        qs, qe = c["q_range"]
        if len(qchunks) != qe - qs or any(
                len(b) != len(out[qs + i]) for i, b in enumerate(qchunks)):
            return self._full(ci)
        for i, b in enumerate(qchunks):
            out[qs + i] = b

        # ---- dirty node rows --------------------------------------------
        for name in dirty_nodes:
            node = ci.nodes.get(name)
            if node is None:
                return self._full(ci)
            if node.gpu_devices or name in c["gpu_nodes"]:
                return self._full(ci)   # gpu usage lives in a ragged column
            i = maps.node_index[name]
            for m, res in zip(c["res_mats"],
                              (node.idle, node.used, node.releasing,
                               node.pipelined, node.allocatable,
                               node.capability)):
                q = res.quantities
                m[i] = [q.get(d, 0.0) for d in dims_t]
            c["pod_count"][i] = node.pod_count()
            c["max_pods"][i] = node.max_pods
            c["sched"][i] = 1 if (node.ready
                                  and not node.unschedulable) else 0
        if dirty_nodes:
            nds = c["node_dyn_start"]
            for k, m in enumerate(c["res_mats"]):
                out[nds + k] = m.tobytes()
            out[nds + 6] = c["pod_count"].tobytes()
            out[nds + 7] = c["max_pods"].tobytes()
            out[nds + 8] = c["sched"].tobytes()

        # ---- dirty job + task rows --------------------------------------
        jc = c["job_cols"]
        tc = c["task_cols"]
        gpu_dim = GPU_MEMORY_RESOURCE
        pending_phase = PodGroupPhase.PENDING
        node_index_get = maps.node_index.get
        for uid in dirty_jobs:
            job = ci.jobs.get(uid)
            if job is None:
                return self._full(ci)
            start, uids = c["task_ranges"][uid]
            if tuple(job.tasks.keys()) != uids:
                return self._full(ci)   # task-set change: full rebuild
            i = maps.job_index[uid]
            jc["j_min"][i] = job.min_available
            jc["j_queue"][i] = maps.queue_index.get(job.queue, -1)
            jc["j_ns"][i] = ns_names.index(job.namespace) \
                if job.namespace in ns_names else 0
            jc["j_prio"][i] = job.priority
            jc["j_ts"][i] = job.creation_timestamp
            ready = valid = 0
            for st, tasks_of in job.task_status_index.items():
                n = len(tasks_of)
                if st in _READY_SET:
                    ready += n
                    valid += n
                elif st in _VALID_ONLY_SET:
                    valid += n
            jc["j_ready"][i] = ready
            q = job.allocated.quantities
            jc["j_alloc"][i] = [q.get(d, 0.0) for d in dims_t]
            q = job.min_resources.quantities
            jc["j_minres"][i] = [q.get(d, 0.0) for d in dims_t]
            gang_valid = (valid >= job.min_available
                          and job.check_task_min_available())
            jc["j_flags"][i, 0] = job.pod_group_phase == pending_phase
            jc["j_flags"][i, 1] = gang_valid
            jc["j_flags"][i, 2] = job.preemptable
            for off, task in enumerate(job.tasks.values()):
                ti = start + off
                q = task.resreq.quantities
                tc["t_resreq"][ti] = [q.get(d, 0.0) for d in dims_t]
                tc["t_status"][ti] = task.status
                tc["t_prio"][ti] = task.priority
                tc["t_node"][ti] = node_index_get(task.node_name, -1)
                tc["t_flags"][ti, 0] = task.best_effort
                tc["t_flags"][ti, 1] = task.preemptable
                tc["t_gpu"][ti] = q.get(gpu_dim, 0.0)
        if dirty_jobs:
            jds = c["job_dyn_start"]
            for k, name in enumerate(self._JOB_COL_ORDER):
                out[jds + k] = jc[name].tobytes()
            tds = c["task_dyn_start"]       # +0 is the static t_job column
            for k, name in enumerate(self._TASK_COL_ORDER):
                out[tds + 1 + k] = tc[name].tobytes()
        self.incremental_serializes += 1
        return b"".join(out), maps
