"""VCS2 binary snapshot wire format — serializer side.

The snapshot payload that crosses the API-layer boundary (SURVEY.md
section 5.8: cluster state serialized to the scheduling sidecar, decisions
returned).  ``serialize(ci)`` flattens a :class:`ClusterInfo` into one
little-endian buffer that the native packer (packer.cc) turns into dense
arrays; the layout keeps every derived encoding decision (resource-dimension
order, label/taint/toleration hash encodings, queue-hierarchy parent
pointers) on the producer side so consumers are dumb and fast.

Record layouts are documented at the top of packer.cc; this module is the
single source of truth for producing them.
"""

from __future__ import annotations

import struct
from typing import List, Tuple

import numpy as np

from ..api import ClusterInfo, PodGroupPhase, QueueState, gpu_request_of
from ..arrays import labels as L
from ..arrays.pack import (_toleration_rows, _vec, queue_capability_row,
                           queue_parent_depth, resource_dims)
from ..arrays.schema import IndexMaps

MAGIC = 0x32534356  # "VCS2"

_u32 = struct.Struct("<I").pack
_i32 = struct.Struct("<i").pack
_f32 = struct.Struct("<f").pack
_f64 = struct.Struct("<d").pack


def _s(out: List[bytes], s: str) -> None:
    b = s.encode("utf-8")
    out.append(_u32(len(b)))
    out.append(b)


def _fvec(out: List[bytes], vec) -> None:
    out.append(vec.astype("<f4").tobytes())


def _ivec(out: List[bytes], vals) -> None:
    out.append(struct.pack(f"<{len(vals)}i", *vals) if vals else b"")


def serialize(ci: ClusterInfo) -> Tuple[bytes, IndexMaps]:
    """ClusterInfo -> (VCS2 buffer, host-side decode maps)."""
    dims = resource_dims(ci)
    R = len(dims)
    maps = IndexMaps(resource_names=dims)

    queue_names = sorted(ci.queues)
    node_names = sorted(ci.nodes)
    job_uids = sorted(ci.jobs)
    ns_names = sorted(ci.namespaces) or ["default"]
    maps.queue_names = queue_names
    maps.node_names = node_names
    maps.job_uids = job_uids
    maps.namespace_names = ns_names
    maps.queue_index = {n: i for i, n in enumerate(queue_names)}
    maps.node_index = {n: i for i, n in enumerate(node_names)}
    maps.job_index = {u: i for i, u in enumerate(job_uids)}
    ns_index = {n: i for i, n in enumerate(ns_names)}

    task_count = sum(len(ci.jobs[u].tasks) for u in job_uids)

    out: List[bytes] = [
        _u32(MAGIC), _u32(R), _u32(len(queue_names)), _u32(len(ns_names)),
        _u32(len(node_names)), _u32(len(job_uids)), _u32(task_count),
    ]
    for d in dims:
        _s(out, d)

    parents, depths = queue_parent_depth(ci, queue_names)
    for i, name in enumerate(queue_names):
        q = ci.queues[name]
        _s(out, name)
        out.append(_f32(max(q.weight, 0)))
        _fvec(out, queue_capability_row(q, dims))
        out.append(bytes([1 if q.reclaimable else 0,
                          1 if q.state == QueueState.OPEN else 0]))
        out.append(_i32(parents[i]))
        out.append(_i32(depths[i]))
        hw = q.hierarchy_weight_values()
        out.append(_f32(hw[-1] if hw else 1.0))
        # full hdrf annotations (VCS2): the receiver rebuilds the exact
        # hierarchy tree (arrays/hierarchy.build_from_specs) from these
        _s(out, q.hierarchy)
        _s(out, q.hierarchy_weights)

    for name in ns_names:
        _s(out, name)
        w = ci.namespaces[name].weight if name in ci.namespaces else 1
        out.append(_f32(max(w, 1)))

    for name in node_names:
        node = ci.nodes[name]
        _s(out, name)
        for res in (node.idle, node.used, node.releasing, node.pipelined,
                    node.allocatable, node.capability):
            _fvec(out, _vec(res, dims))
        out.append(_i32(node.pod_count()))
        out.append(_i32(node.max_pods))
        out.append(bytes([1 if (node.ready and not node.unschedulable) else 0]))
        out.append(_u32(len(node.gpu_devices)))
        for dev in node.gpu_devices:
            out.append(_f32(dev.memory))
            out.append(_f32(dev.used_memory()))
        lh = L.label_hashes(node.labels)
        out.append(_u32(len(lh)))
        _ivec(out, lh)
        out.append(_u32(len(node.taints)))
        for t in node.taints:
            _ivec(out, [L.stable_hash(f"{t.key}={t.value}"),
                        L.stable_hash(t.key), L.effect_code(t.effect)])

    for uid in job_uids:
        job = ci.jobs[uid]
        _s(out, uid)
        out.append(_i32(job.min_available))
        out.append(_i32(maps.queue_index.get(job.queue, -1)))
        out.append(_i32(ns_index.get(job.namespace, 0)))
        out.append(_i32(job.priority))
        out.append(_f64(job.creation_timestamp))
        out.append(_i32(job.ready_task_num()))
        _fvec(out, _vec(job.allocated, dims))
        _fvec(out, _vec(job.min_resources, dims))
        gang_valid, _ = job.is_valid()
        out.append(bytes([
            1 if job.pod_group_phase == PodGroupPhase.PENDING else 0,
            1 if gang_valid else 0,
            1 if job.preemptable else 0,
        ]))

    maps.task_uids = []
    for ji, uid in enumerate(job_uids):
        for task in ci.jobs[uid].tasks.values():
            ti = len(maps.task_uids)
            maps.task_uids.append(task.uid)
            maps.task_index[task.uid] = ti
            _s(out, task.uid)
            out.append(_i32(ji))
            _fvec(out, _vec(task.resreq, dims))
            out.append(_i32(int(task.status)))
            out.append(_i32(task.priority))
            out.append(_i32(maps.node_index.get(task.node_name, -1)))
            out.append(bytes([1 if task.best_effort else 0,
                              1 if task.preemptable else 0]))
            out.append(_f32(gpu_request_of(task.resreq)))
            required = dict(task.node_selector)
            for term in task.affinity_required:
                required.update(term)
            sel = sorted(L.stable_hash(f"{k}={v}") for k, v in required.items())
            out.append(_u32(len(sel)))
            _ivec(out, sel)
            h, e, m = _toleration_rows(task.tolerations)
            out.append(_u32(len(h)))
            for hh, ee, mm in zip(h, e, m):
                _ivec(out, [hh, ee, mm])

    return b"".join(out), maps
