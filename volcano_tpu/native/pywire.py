"""Pure-Python VCS4 parser: wire buffer -> SnapshotArrays.

The fallback half of the native packing runtime (packer.cc is the fast
path): keeps the scheduling sidecar usable on hosts without g++, and acts
as a second, independent implementation of the wire contract for parity
tests. Mirrors packer.cc record-for-record — bucket sizes, derived
aggregates (job request/queue allocated, predicate templates, pending-task
tables, creation ranks), padding and defaults all match so the two paths
produce bit-identical SnapshotArrays.

Reference moment: SchedulerCache.Snapshot building the cluster mirror
(pkg/scheduler/cache/cache.go:712-811); wire layout doc at the top of
packer.cc / native/wire.py.
"""

from __future__ import annotations

import struct

import numpy as np

from ..arrays.schema import (JobArrays, NodeArrays, QueueArrays,
                             SnapshotArrays, TaskArrays)

MAGIC = 0x34534356  # "VCS4"

# TaskStatus codes (volcano_tpu/api/types.py; pkg/scheduler/api/types.go:29-96)
_STATUS_PENDING = 0
_COUNTS_FOR_REQUEST = frozenset((0, 1, 3, 4, 5))


def _bucket(n: int, minimum: int) -> int:
    # mirror of arrays/schema.bucket (graded grid): powers of two up to
    # 1024, then multiples of next_pow2(n)/8
    b = minimum
    while b < n and b < 1024:
        b *= 2
    if n <= b:
        return b
    p = 1 << (int(n) - 1).bit_length()
    g = max(1024, p // 8)
    return ((int(n) + g - 1) // g) * g


class _Reader:
    __slots__ = ("buf", "off")

    def __init__(self, buf: bytes):
        self.buf = buf
        self.off = 0

    def u32(self) -> int:
        (v,) = struct.unpack_from("<I", self.buf, self.off)
        self.off += 4
        return v

    def i32(self) -> int:
        (v,) = struct.unpack_from("<i", self.buf, self.off)
        self.off += 4
        return v

    def u8(self) -> int:
        v = self.buf[self.off]
        self.off += 1
        return v

    def f32(self) -> float:
        (v,) = struct.unpack_from("<f", self.buf, self.off)
        self.off += 4
        return v

    def f64(self) -> float:
        (v,) = struct.unpack_from("<d", self.buf, self.off)
        self.off += 8
        return v

    def skip_string(self) -> None:
        n = self.u32()
        self.off += n

    def string(self) -> str:
        n = self.u32()
        v = self.buf[self.off:self.off + n].decode("utf-8", "replace")
        self.off += n
        return v

    def f32vec(self, n: int) -> np.ndarray:
        v = np.frombuffer(self.buf, "<f4", n, self.off)
        self.off += 4 * n
        return v

    def i32vec(self, n: int) -> np.ndarray:
        v = np.frombuffer(self.buf, "<i4", n, self.off)
        self.off += 4 * n
        return v


def pack_wire_py(buf: bytes) -> SnapshotArrays:
    """Parse a VCS4 buffer into SnapshotArrays (pure Python/numpy)."""
    try:
        return _parse(buf)
    except (struct.error, IndexError, ValueError) as e:
        # columnar reads fail as numpy ValueErrors (short frombuffer,
        # counts/flat mismatches); normalize them all
        raise ValueError(f"truncated or corrupt VCS4 buffer: {e}") from None


def _parse(buf: bytes) -> SnapshotArrays:
    r = _Reader(buf)
    if r.u32() != MAGIC:
        raise ValueError("bad magic (not a VCS4 buffer)")
    R = r.u32()
    nq, ns, nn, nj, nt = (r.u32() for _ in range(5))
    if R == 0 or R > 1024:
        raise ValueError("corrupt header")
    # Sanity-bound the entity counts against the buffer size before any
    # allocation: every queue/node/job/task record is at least a few bytes,
    # so a corrupt header with valid magic fails fast with ValueError
    # instead of driving a huge np.zeros into MemoryError.
    if max(nq, ns, nn, nj, nt) > len(buf):
        raise ValueError("corrupt header (entity count exceeds buffer size)")
    for _ in range(R):
        r.skip_string()

    Q = _bucket(max(nq, 1), 4)
    S = _bucket(max(ns, 1), 4)
    N = _bucket(max(nn, 1), 8)
    J = _bucket(max(nj, 1), 4)
    T = _bucket(max(nt, 1), 8)
    f32, i32 = np.float32, np.int32

    # ------------------------------------------------------------- queues
    q_weight = np.zeros(Q, f32)
    q_cap = np.full((Q, R), np.inf, f32)
    q_reclaimable = np.zeros(Q, bool)
    q_open = np.zeros(Q, bool)
    q_parent = np.full(Q, -1, i32)
    q_depth = np.zeros(Q, i32)
    q_hier_weight = np.ones(Q, f32)
    q_valid = np.zeros(Q, bool)
    for i in range(nq):
        r.skip_string()
        q_weight[i] = max(r.f32(), 0.0)
        q_cap[i] = r.f32vec(R)
        q_reclaimable[i] = bool(r.u8())
        q_open[i] = bool(r.u8())
        q_parent[i] = r.i32()
        q_depth[i] = r.i32()
        q_hier_weight[i] = r.f32()
        r.skip_string()   # hierarchy annotation (decode_hierarchy reads it)
        r.skip_string()   # hierarchy weights annotation
        q_valid[i] = True

    # --------------------------------------------------------- namespaces
    ns_weight = np.ones(S, f32)
    for i in range(ns):
        r.skip_string()
        ns_weight[i] = max(r.f32(), 1.0)

    def skip_string_column(n):
        blob_len = r.u32()
        r.off += 4 * n + blob_len

    def ragged(n, dtype, per=1):
        """u32 total | u32[n] counts | dtype[total*per] -> (counts, flat)."""
        total = r.u32()
        counts = np.frombuffer(r.buf, "<u4", n, r.off).astype(np.int64)
        r.off += 4 * n
        if counts.sum() != total or (n and counts.max() > total):
            raise ValueError("ragged column counts do not match total")
        if dtype == f32:
            flat = r.f32vec(total * per)
        else:
            flat = r.i32vec(total * per)
        return counts, flat

    def pad_from_flat(counts, flat, width, total_rows, dtype):
        out = np.zeros((total_rows, width), dtype)
        if len(flat):
            row_idx = np.repeat(np.arange(len(counts)), counts)
            offs = np.concatenate(([0], np.cumsum(counts)[:-1]))
            col_idx = np.arange(len(flat) // 1) - np.repeat(offs, counts)
            out[row_idx, col_idx] = flat
        return out

    # ------------------------------------------------ nodes (columnar)
    n_res = np.zeros((6, N, R), f32)  # idle/used/releasing/pipelined/alloc/cap
    n_pod_count = np.zeros(N, i32)
    n_max_pods = np.zeros(N, i32)
    n_schedulable = np.zeros(N, bool)
    n_valid = np.zeros(N, bool)
    skip_string_column(nn)
    for k in range(6):
        n_res[k, :nn] = r.f32vec(nn * R).reshape(nn, R)
    n_pod_count[:nn] = r.i32vec(nn)
    n_max_pods[:nn] = r.i32vec(nn)
    n_schedulable[:nn] = np.frombuffer(r.buf, "u1", nn, r.off) != 0
    r.off += nn
    n_valid[:nn] = True
    gcounts, gflat = ragged(nn, f32, per=2)
    gpairs = gflat.reshape(-1, 2) if len(gflat) else np.zeros((0, 2), f32)
    lcounts, lflat = ragged(nn, i32)
    tcounts, tflat = ragged(nn, i32, per=3)
    ttrip = tflat.reshape(-1, 3) if len(tflat) else np.zeros((0, 3), i32)

    L = max(int(lcounts.max()) if nn else 0, 1)
    E = max(int(tcounts.max()) if nn else 0, 1)
    G = _bucket(max(int(gcounts.max()) if nn else 0, 1), 1)

    n_labels = pad_from_flat(lcounts, lflat, L, N, i32)
    n_taint_kv = pad_from_flat(tcounts, ttrip[:, 0], E, N, i32)
    n_taint_key = pad_from_flat(tcounts, ttrip[:, 1], E, N, i32)
    n_taint_effect = pad_from_flat(tcounts, ttrip[:, 2], E, N, i32)
    n_gpu_memory = pad_from_flat(gcounts, gpairs[:, 0], G, N, f32)
    n_gpu_used = pad_from_flat(gcounts, gpairs[:, 1], G, N, f32)

    # --------------------------------------------------------------- jobs
    j_min_available = np.zeros(J, i32)
    j_queue = np.zeros(J, i32)
    j_namespace = np.zeros(J, i32)
    j_priority = np.zeros(J, i32)
    j_creation_rank = np.zeros(J, i32)
    j_ready_num = np.zeros(J, i32)
    j_allocated = np.zeros((J, R), f32)
    j_total_request = np.zeros((J, R), f32)
    j_min_resources = np.zeros((J, R), f32)
    j_schedulable = np.zeros(J, bool)
    j_inqueue = np.zeros(J, bool)
    j_pending_phase = np.zeros(J, bool)
    j_preemptable = np.zeros(J, bool)
    j_valid = np.zeros(J, bool)
    skip_string_column(nj)
    j_min_available[:nj] = r.i32vec(nj)
    job_queue_raw = r.i32vec(nj).copy()
    j_namespace[:nj] = r.i32vec(nj)
    j_priority[:nj] = r.i32vec(nj)
    job_ts = np.frombuffer(r.buf, "<f8", nj, r.off).copy()
    r.off += 8 * nj
    j_ready_num[:nj] = r.i32vec(nj)
    j_allocated[:nj] = r.f32vec(nj * R).reshape(nj, R)
    j_min_resources[:nj] = r.f32vec(nj * R).reshape(nj, R)
    jflags = np.frombuffer(r.buf, "u1", nj * 3, r.off).reshape(nj, 3)
    r.off += 3 * nj
    j_pending_phase[:nj] = jflags[:, 0] != 0
    gang_valid = jflags[:, 1] != 0
    j_preemptable[:nj] = jflags[:, 2] != 0
    j_valid[:nj] = True
    j_queue[:nj] = np.maximum(job_queue_raw, 0)
    j_inqueue[:nj] = ~j_pending_phase[:nj]
    queue_open = ((job_queue_raw >= 0) & (job_queue_raw < nq)
                  & q_open[np.clip(job_queue_raw, 0, max(Q - 1, 0))])
    j_schedulable[:nj] = gang_valid & queue_open & j_inqueue[:nj]
    # creation_rank: stable sort of uid-sorted jobs by creation timestamp
    order = np.argsort(job_ts[:nj], kind="stable")
    j_creation_rank[order] = np.arange(nj, dtype=i32)

    # -------------------------------------------------------------- tasks
    t_resreq = np.zeros((T, R), f32)
    t_job = np.full(T, -1, i32)
    t_status = np.zeros(T, i32)
    t_priority = np.zeros(T, i32)
    t_node = np.full(T, -1, i32)
    t_best_effort = np.zeros(T, bool)
    t_gpu_request = np.zeros(T, f32)
    t_preemptable = np.zeros(T, bool)
    t_valid = np.zeros(T, bool)
    skip_string_column(nt)
    t_job[:nt] = r.i32vec(nt)
    t_resreq[:nt] = r.f32vec(nt * R).reshape(nt, R)
    t_status[:nt] = r.i32vec(nt)
    t_priority[:nt] = r.i32vec(nt)
    t_node[:nt] = r.i32vec(nt)
    tflags2 = np.frombuffer(r.buf, "u1", nt * 2, r.off).reshape(nt, 2)
    r.off += 2 * nt
    t_best_effort[:nt] = tflags2[:, 0] != 0
    t_preemptable[:nt] = tflags2[:, 1] != 0
    t_gpu_request[:nt] = r.f32vec(nt)
    t_valid[:nt] = True
    scounts, sflat = ragged(nt, i32)
    ocounts, oflat = ragged(nt, i32, per=3)
    otrip = oflat.reshape(-1, 3) if len(oflat) else np.zeros((0, 3), i32)
    # VCS4: per-task preferred-affinity template split key
    t_nakey = r.i32vec(nt).astype(i32)

    K = max(int(scounts.max()) if nt else 0, 1)
    O = max(int(ocounts.max()) if nt else 0, 1)
    t_selector = pad_from_flat(scounts, sflat, K, T, i32)
    t_tol_hash = pad_from_flat(ocounts, otrip[:, 0], O, T, i32)
    t_tol_effect = pad_from_flat(ocounts, otrip[:, 1], O, T, i32)
    t_tol_mode = pad_from_flat(ocounts, otrip[:, 2], O, T, i32)

    # Job request accumulation (proportion request statuses) — np.add.at
    # applies updates in ascending task order, matching the record loop.
    in_job = (t_job[:nt] >= 0) & (t_job[:nt] < nj)
    counts_mask = in_job & np.isin(t_status[:nt], list(_COUNTS_FOR_REQUEST))
    np.add.at(j_total_request, t_job[:nt][counts_mask],
              t_resreq[:nt][counts_mask])

    # Predicate templates: identical selector/toleration rows share one id,
    # first-occurrence order (packer.cc template dedupe;
    # predicates/cache.go:42-67). Padded rows are unambiguous keys: counts
    # differ only when a row holds trailing zero hashes, and 0 is the pad /
    # invalid hash in this encoding.
    sig = np.concatenate(
        [t_selector[:nt], scounts[:, None].astype(i32),
         t_tol_hash[:nt], t_tol_effect[:nt], t_tol_mode[:nt],
         ocounts[:, None].astype(i32),
         t_nakey[:nt, None] if t_nakey.ndim == 1 else t_nakey], axis=1)
    _u, first_idx, inv = np.unique(sig, axis=0, return_index=True,
                                   return_inverse=True)
    rank = np.empty(len(first_idx), i32)
    rank[np.argsort(first_idx, kind="stable")] = np.arange(len(first_idx),
                                                           dtype=i32)
    t_template = np.zeros(T, i32)
    t_template[:nt] = rank[inv.reshape(-1)]
    reps = np.sort(first_idx).astype(i32)
    P = _bucket(max(len(reps), 1), 4)
    template_rep = np.full(P, -1, i32)
    template_rep[:len(reps)] = reps

    # Pending-task tables: priority desc, insertion order within priority
    # (lexsort keys are last-major: job, then -priority, then index).
    pend_idx = np.nonzero(in_job & (t_status[:nt] == _STATUS_PENDING))[0]
    order2 = pend_idx[np.lexsort(
        (pend_idx, -t_priority[pend_idx].astype(np.int64),
         t_job[pend_idx]))]
    per_job = np.bincount(t_job[order2], minlength=nj) if len(order2) \
        else np.zeros(nj, np.int64)
    maxp = int(per_job.max()) if nj else 0
    M = _bucket(maxp, 4)
    j_task_table = np.full((J, M), -1, i32)
    j_n_pending = np.zeros(J, i32)
    j_n_pending[:nj] = per_job
    if len(order2):
        offs = np.concatenate(([0], np.cumsum(per_job)[:-1]))
        row_idx = t_job[order2]
        col_idx = np.arange(len(order2)) - offs[row_idx]
        j_task_table[row_idx, col_idx] = order2

    # Queue aggregates over member jobs (packer.cc:601-615).
    q_allocated = np.zeros((Q, R), f32)
    q_request = np.zeros((Q, R), f32)
    q_inqueue_minres = np.zeros((Q, R), f32)
    for ji in range(nj):
        qi = int(job_queue_raw[ji])
        if not (0 <= qi < nq):
            continue
        q_allocated[qi] += j_allocated[ji]
        q_request[qi] += j_total_request[ji]
        if j_inqueue[ji]:
            q_inqueue_minres[qi] += j_min_resources[ji]

    cluster_capacity = n_res[4, :nn].sum(axis=0).astype(f32) if nn else \
        np.zeros(R, f32)

    nodes = NodeArrays(
        idle=n_res[0], used=n_res[1], releasing=n_res[2], pipelined=n_res[3],
        allocatable=n_res[4], capability=n_res[5],
        labels=n_labels, taint_kv=n_taint_kv, taint_key=n_taint_key,
        taint_effect=n_taint_effect, pod_count=n_pod_count,
        max_pods=n_max_pods, gpu_memory=n_gpu_memory, gpu_used=n_gpu_used,
        schedulable=n_schedulable, valid=n_valid)
    tasks = TaskArrays(
        resreq=t_resreq, job=t_job, status=t_status, priority=t_priority,
        node=t_node, selector=t_selector, tol_hash=t_tol_hash,
        tol_effect=t_tol_effect, tol_mode=t_tol_mode, template=t_template,
        best_effort=t_best_effort, gpu_request=t_gpu_request,
        preemptable=t_preemptable, valid=t_valid)
    jobs = JobArrays(
        min_available=j_min_available, queue=j_queue, namespace=j_namespace,
        priority=j_priority, creation_rank=j_creation_rank,
        ready_num=j_ready_num, allocated=j_allocated,
        total_request=j_total_request, min_resources=j_min_resources,
        task_table=j_task_table, n_pending=j_n_pending,
        schedulable=j_schedulable, inqueue=j_inqueue,
        pending_phase=j_pending_phase, preemptable=j_preemptable,
        valid=j_valid)
    queues = QueueArrays(
        weight=q_weight, capability=q_cap, reclaimable=q_reclaimable,
        open=q_open, allocated=q_allocated, request=q_request,
        inqueue_minres=q_inqueue_minres, parent=q_parent, depth=q_depth,
        hier_weight=q_hier_weight, valid=q_valid)
    return SnapshotArrays(
        nodes=nodes, tasks=tasks, jobs=jobs, queues=queues,
        namespace_weight=ns_weight, cluster_capacity=cluster_capacity,
        template_rep=template_rep)


def decode_hierarchy(buf: bytes, job_queue, job_valid):
    """VCS4 buffer -> HierarchyArrays, parsing only the (early) header and
    queue records. ``job_queue``/``job_valid`` come from the already-decoded
    SnapshotArrays (the job section sits late in the buffer; its queue
    indices are all the tree needs for job leaves)."""
    from ..arrays.hierarchy import build_from_specs
    r = _Reader(buf)
    if r.u32() != MAGIC:
        raise ValueError("bad magic (not a VCS4 buffer)")
    R = r.u32()
    nq = r.u32()
    for _ in range(4):
        r.u32()
    for _ in range(R):
        r.skip_string()
    specs = []
    for _ in range(nq):
        r.skip_string()                  # name
        r.f32()                          # weight
        r.off += 4 * R                   # capability vector
        r.off += 2                       # reclaimable, open
        r.off += 8                       # parent, depth
        r.f32()                          # leaf hier weight
        hierarchy = r.string()
        weights = r.string()
        specs.append((hierarchy, weights))
    Q = _bucket(max(nq, 1), 4)
    specs += [("", "")] * (Q - len(specs))
    jq = np.asarray(job_queue, np.int32)
    jv = np.asarray(job_valid, bool)
    return build_from_specs(specs, Q, jq, jv & (jq >= 0))


def decode_extras(buf: bytes, nt: int, nn: int):
    """VCX1 extras frame -> (affinity_sections, port_volume_sections),
    the dict shapes framework/host_extras.py appliers consume. Either half
    is None when its sections are absent. Unknown section tags are skipped
    (forward compatibility)."""
    from ..native.wire import (EXTRAS_MAGIC, TAG_NA_GROUPS, TAG_OR_GROUPS,
                               TAG_PORTS, TAG_VOLUMES)
    if not buf:
        return None, None
    r = _Reader(buf)
    if r.u32() != EXTRAS_MAGIC:
        raise ValueError("bad magic (not a VCX1 extras frame)")
    n_sections = r.u32()
    aff = None
    pv = None

    def _aff():
        nonlocal aff
        if aff is None:
            aff = dict(task_or_group=np.full(nt, -1, np.int32),
                       or_masks=np.zeros((0, nn), bool),
                       task_na_group=np.full(nt, -1, np.int32),
                       na_rows=np.zeros((0, nn), np.float32))
        return aff

    def _pv():
        nonlocal pv
        if pv is None:
            pv = dict(task_ports={}, node_ports={}, n_pending_ports=0,
                      vol_ok=np.ones(nt, bool),
                      vol_node=np.full(nt, -1, np.int32))
        return pv

    def _ragged_dict(rd, count):
        total = rd.u32()
        counts = np.frombuffer(rd.buf, "<u4", count, rd.off)
        rd.off += 4 * count
        flat = np.frombuffer(rd.buf, "<i4", total, rd.off)
        rd.off += 4 * total
        out = {}
        off = 0
        for i in range(count):
            c = int(counts[i])
            if c:
                out[i] = flat[off:off + c].tolist()
            off += c
        return out

    for _ in range(n_sections):
        tag = r.u32()
        ln = r.u32()
        end = r.off + ln
        if tag == TAG_OR_GROUPS:
            g = r.u32()
            a = _aff()
            a["task_or_group"] = np.frombuffer(
                r.buf, "<i4", nt, r.off).astype(np.int32)
            r.off += 4 * nt
            a["or_masks"] = np.frombuffer(
                r.buf, "u1", g * nn, r.off).reshape(g, nn).astype(bool)
            r.off += g * nn
        elif tag == TAG_NA_GROUPS:
            g = r.u32()
            a = _aff()
            a["task_na_group"] = np.frombuffer(
                r.buf, "<i4", nt, r.off).astype(np.int32)
            r.off += 4 * nt
            a["na_rows"] = np.frombuffer(
                r.buf, "<f4", g * nn, r.off).reshape(g, nn).astype(np.float32)
            r.off += 4 * g * nn
        elif tag == TAG_PORTS:
            p = _pv()
            p["n_pending_ports"] = r.u32()
            p["task_ports"] = _ragged_dict(r, nt)
            p["node_ports"] = _ragged_dict(r, nn)
        elif tag == TAG_VOLUMES:
            p = _pv()
            p["vol_ok"] = np.frombuffer(r.buf, "u1", nt, r.off).astype(bool)
            r.off += nt
            p["vol_node"] = np.frombuffer(
                r.buf, "<i4", nt, r.off).astype(np.int32)
            r.off += 4 * nt
        r.off = end
    return aff, pv
