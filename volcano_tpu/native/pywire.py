"""Pure-Python VCS2 parser: wire buffer -> SnapshotArrays.

The fallback half of the native packing runtime (packer.cc is the fast
path): keeps the scheduling sidecar usable on hosts without g++, and acts
as a second, independent implementation of the wire contract for parity
tests. Mirrors packer.cc record-for-record — bucket sizes, derived
aggregates (job request/queue allocated, predicate templates, pending-task
tables, creation ranks), padding and defaults all match so the two paths
produce bit-identical SnapshotArrays.

Reference moment: SchedulerCache.Snapshot building the cluster mirror
(pkg/scheduler/cache/cache.go:712-811); wire layout doc at the top of
packer.cc / native/wire.py.
"""

from __future__ import annotations

import struct

import numpy as np

from ..arrays.schema import (JobArrays, NodeArrays, QueueArrays,
                             SnapshotArrays, TaskArrays)

MAGIC = 0x32534356  # "VCS2"

# TaskStatus codes (volcano_tpu/api/types.py; pkg/scheduler/api/types.go:29-96)
_STATUS_PENDING = 0
_COUNTS_FOR_REQUEST = frozenset((0, 1, 3, 4, 5))


def _bucket(n: int, minimum: int) -> int:
    b = minimum
    while b < n:
        b *= 2
    return b


class _Reader:
    __slots__ = ("buf", "off")

    def __init__(self, buf: bytes):
        self.buf = buf
        self.off = 0

    def u32(self) -> int:
        (v,) = struct.unpack_from("<I", self.buf, self.off)
        self.off += 4
        return v

    def i32(self) -> int:
        (v,) = struct.unpack_from("<i", self.buf, self.off)
        self.off += 4
        return v

    def u8(self) -> int:
        v = self.buf[self.off]
        self.off += 1
        return v

    def f32(self) -> float:
        (v,) = struct.unpack_from("<f", self.buf, self.off)
        self.off += 4
        return v

    def f64(self) -> float:
        (v,) = struct.unpack_from("<d", self.buf, self.off)
        self.off += 8
        return v

    def skip_string(self) -> None:
        n = self.u32()
        self.off += n

    def string(self) -> str:
        n = self.u32()
        v = self.buf[self.off:self.off + n].decode("utf-8", "replace")
        self.off += n
        return v

    def f32vec(self, n: int) -> np.ndarray:
        v = np.frombuffer(self.buf, "<f4", n, self.off)
        self.off += 4 * n
        return v

    def i32vec(self, n: int) -> np.ndarray:
        v = np.frombuffer(self.buf, "<i4", n, self.off)
        self.off += 4 * n
        return v


def pack_wire_py(buf: bytes) -> SnapshotArrays:
    """Parse a VCS2 buffer into SnapshotArrays (pure Python/numpy)."""
    try:
        return _parse(buf)
    except (struct.error, IndexError) as e:
        raise ValueError(f"truncated or corrupt VCS2 buffer: {e}") from None


def _parse(buf: bytes) -> SnapshotArrays:
    r = _Reader(buf)
    if r.u32() != MAGIC:
        raise ValueError("bad magic (not a VCS2 buffer)")
    R = r.u32()
    nq, ns, nn, nj, nt = (r.u32() for _ in range(5))
    if R == 0 or R > 1024:
        raise ValueError("corrupt header")
    # Sanity-bound the entity counts against the buffer size before any
    # allocation: every queue/node/job/task record is at least a few bytes,
    # so a corrupt header with valid magic fails fast with ValueError
    # instead of driving a huge np.zeros into MemoryError.
    if max(nq, ns, nn, nj, nt) > len(buf):
        raise ValueError("corrupt header (entity count exceeds buffer size)")
    for _ in range(R):
        r.skip_string()

    Q = _bucket(max(nq, 1), 4)
    S = _bucket(max(ns, 1), 4)
    N = _bucket(max(nn, 1), 8)
    J = _bucket(max(nj, 1), 4)
    T = _bucket(max(nt, 1), 8)
    f32, i32 = np.float32, np.int32

    # ------------------------------------------------------------- queues
    q_weight = np.zeros(Q, f32)
    q_cap = np.full((Q, R), np.inf, f32)
    q_reclaimable = np.zeros(Q, bool)
    q_open = np.zeros(Q, bool)
    q_parent = np.full(Q, -1, i32)
    q_depth = np.zeros(Q, i32)
    q_hier_weight = np.ones(Q, f32)
    q_valid = np.zeros(Q, bool)
    for i in range(nq):
        r.skip_string()
        q_weight[i] = max(r.f32(), 0.0)
        q_cap[i] = r.f32vec(R)
        q_reclaimable[i] = bool(r.u8())
        q_open[i] = bool(r.u8())
        q_parent[i] = r.i32()
        q_depth[i] = r.i32()
        q_hier_weight[i] = r.f32()
        r.skip_string()   # hierarchy annotation (decode_hierarchy reads it)
        r.skip_string()   # hierarchy weights annotation
        q_valid[i] = True

    # --------------------------------------------------------- namespaces
    ns_weight = np.ones(S, f32)
    for i in range(ns):
        r.skip_string()
        ns_weight[i] = max(r.f32(), 1.0)

    # -------------------------------------------------------------- nodes
    n_res = np.zeros((6, N, R), f32)  # idle/used/releasing/pipelined/alloc/cap
    n_pod_count = np.zeros(N, i32)
    n_max_pods = np.zeros(N, i32)
    n_schedulable = np.zeros(N, bool)
    n_valid = np.zeros(N, bool)
    labels, tkv, tkey, teff, gmem, gused = ([], [], [], [], [], [])
    for i in range(nn):
        r.skip_string()
        for k in range(6):
            n_res[k, i] = r.f32vec(R)
        n_pod_count[i] = r.i32()
        n_max_pods[i] = r.i32()
        n_schedulable[i] = bool(r.u8())
        n_valid[i] = True
        ng = r.u32()
        gm = np.zeros(ng, f32)
        gu = np.zeros(ng, f32)
        for g in range(ng):
            gm[g] = r.f32()
            gu[g] = r.f32()
        gmem.append(gm)
        gused.append(gu)
        nl = r.u32()
        labels.append(r.i32vec(nl))
        ntn = r.u32()
        trow = r.i32vec(3 * ntn).reshape(ntn, 3) if ntn else np.zeros((0, 3), i32)
        tkv.append(trow[:, 0])
        tkey.append(trow[:, 1])
        teff.append(trow[:, 2])

    L = max(max((len(v) for v in labels), default=0), 1)
    E = max(max((len(v) for v in tkv), default=0), 1)
    G = _bucket(max(max((len(v) for v in gmem), default=0), 1), 1)

    def _pad_rows(rows, width, dtype, total):
        out = np.zeros((total, width), dtype)
        for i, v in enumerate(rows):
            out[i, :len(v)] = v
        return out

    n_labels = _pad_rows(labels, L, i32, N)
    n_taint_kv = _pad_rows(tkv, E, i32, N)
    n_taint_key = _pad_rows(tkey, E, i32, N)
    n_taint_effect = _pad_rows(teff, E, i32, N)
    n_gpu_memory = _pad_rows(gmem, G, f32, N)
    n_gpu_used = _pad_rows(gused, G, f32, N)

    # --------------------------------------------------------------- jobs
    j_min_available = np.zeros(J, i32)
    j_queue = np.zeros(J, i32)
    j_namespace = np.zeros(J, i32)
    j_priority = np.zeros(J, i32)
    j_creation_rank = np.zeros(J, i32)
    j_ready_num = np.zeros(J, i32)
    j_allocated = np.zeros((J, R), f32)
    j_total_request = np.zeros((J, R), f32)
    j_min_resources = np.zeros((J, R), f32)
    j_schedulable = np.zeros(J, bool)
    j_inqueue = np.zeros(J, bool)
    j_pending_phase = np.zeros(J, bool)
    j_preemptable = np.zeros(J, bool)
    j_valid = np.zeros(J, bool)
    job_queue_raw = np.full(nj, -1, i32)
    job_ts = np.zeros(nj, np.float64)
    for i in range(nj):
        r.skip_string()
        j_min_available[i] = r.i32()
        job_queue_raw[i] = r.i32()
        j_namespace[i] = r.i32()
        j_priority[i] = r.i32()
        job_ts[i] = r.f64()
        j_ready_num[i] = r.i32()
        j_allocated[i] = r.f32vec(R)
        j_min_resources[i] = r.f32vec(R)
        j_pending_phase[i] = bool(r.u8())
        gang_valid = bool(r.u8())
        j_preemptable[i] = bool(r.u8())
        j_valid[i] = True
        j_queue[i] = max(int(job_queue_raw[i]), 0)
        j_inqueue[i] = not j_pending_phase[i]
        queue_open = (0 <= job_queue_raw[i] < nq
                      and bool(q_open[job_queue_raw[i]]))
        j_schedulable[i] = gang_valid and queue_open and j_inqueue[i]
    # creation_rank: stable sort of uid-sorted jobs by creation timestamp
    order = np.argsort(job_ts[:nj], kind="stable")
    j_creation_rank[order] = np.arange(nj, dtype=i32)

    # -------------------------------------------------------------- tasks
    t_resreq = np.zeros((T, R), f32)
    t_job = np.full(T, -1, i32)
    t_status = np.zeros(T, i32)
    t_priority = np.zeros(T, i32)
    t_node = np.full(T, -1, i32)
    t_best_effort = np.zeros(T, bool)
    t_gpu_request = np.zeros(T, f32)
    t_preemptable = np.zeros(T, bool)
    t_valid = np.zeros(T, bool)
    sel, tolh, tole, tolm = [], [], [], []
    pending = [[] for _ in range(nj)]
    for i in range(nt):
        r.skip_string()
        t_job[i] = r.i32()
        t_resreq[i] = r.f32vec(R)
        t_status[i] = r.i32()
        t_priority[i] = r.i32()
        t_node[i] = r.i32()
        t_best_effort[i] = bool(r.u8())
        t_preemptable[i] = bool(r.u8())
        t_gpu_request[i] = r.f32()
        t_valid[i] = True
        nsel = r.u32()
        sel.append(r.i32vec(nsel))
        ntol = r.u32()
        trow = r.i32vec(3 * ntol).reshape(ntol, 3) if ntol else np.zeros((0, 3), i32)
        tolh.append(trow[:, 0])
        tole.append(trow[:, 1])
        tolm.append(trow[:, 2])
        ji = int(t_job[i])
        if 0 <= ji < nj:
            if int(t_status[i]) == _STATUS_PENDING:
                pending[ji].append(i)
            if int(t_status[i]) in _COUNTS_FOR_REQUEST:
                j_total_request[ji] += t_resreq[i]

    K = max(max((len(v) for v in sel), default=0), 1)
    O = max(max((len(v) for v in tolh), default=0), 1)
    t_selector = _pad_rows(sel, K, i32, T)
    t_tol_hash = _pad_rows(tolh, O, i32, T)
    t_tol_effect = _pad_rows(tole, O, i32, T)
    t_tol_mode = _pad_rows(tolm, O, i32, T)

    # Predicate templates: identical selector/toleration rows share one id,
    # first-occurrence order (packer.cc:543-579; predicates/cache.go:42-67).
    t_template = np.zeros(T, i32)
    template_of = {}
    reps = []
    for i in range(nt):
        key = (tuple(sel[i]), tuple(tolh[i]), tuple(tole[i]), tuple(tolm[i]))
        tid = template_of.get(key)
        if tid is None:
            tid = len(reps)
            template_of[key] = tid
            reps.append(i)
        t_template[i] = tid
    P = _bucket(max(len(reps), 1), 4)
    template_rep = np.full(P, -1, i32)
    template_rep[:len(reps)] = reps

    # Pending-task tables: priority desc, insertion order within priority.
    maxp = max((len(p) for p in pending), default=0)
    M = _bucket(maxp, 4)
    j_task_table = np.full((J, M), -1, i32)
    j_n_pending = np.zeros(J, i32)
    for ji, p in enumerate(pending):
        p = sorted(p, key=lambda t: (-int(t_priority[t]), t))
        j_n_pending[ji] = len(p)
        j_task_table[ji, :len(p)] = p

    # Queue aggregates over member jobs (packer.cc:601-615).
    q_allocated = np.zeros((Q, R), f32)
    q_request = np.zeros((Q, R), f32)
    q_inqueue_minres = np.zeros((Q, R), f32)
    for ji in range(nj):
        qi = int(job_queue_raw[ji])
        if not (0 <= qi < nq):
            continue
        q_allocated[qi] += j_allocated[ji]
        q_request[qi] += j_total_request[ji]
        if j_inqueue[ji]:
            q_inqueue_minres[qi] += j_min_resources[ji]

    cluster_capacity = n_res[4, :nn].sum(axis=0).astype(f32) if nn else \
        np.zeros(R, f32)

    nodes = NodeArrays(
        idle=n_res[0], used=n_res[1], releasing=n_res[2], pipelined=n_res[3],
        allocatable=n_res[4], capability=n_res[5],
        labels=n_labels, taint_kv=n_taint_kv, taint_key=n_taint_key,
        taint_effect=n_taint_effect, pod_count=n_pod_count,
        max_pods=n_max_pods, gpu_memory=n_gpu_memory, gpu_used=n_gpu_used,
        schedulable=n_schedulable, valid=n_valid)
    tasks = TaskArrays(
        resreq=t_resreq, job=t_job, status=t_status, priority=t_priority,
        node=t_node, selector=t_selector, tol_hash=t_tol_hash,
        tol_effect=t_tol_effect, tol_mode=t_tol_mode, template=t_template,
        best_effort=t_best_effort, gpu_request=t_gpu_request,
        preemptable=t_preemptable, valid=t_valid)
    jobs = JobArrays(
        min_available=j_min_available, queue=j_queue, namespace=j_namespace,
        priority=j_priority, creation_rank=j_creation_rank,
        ready_num=j_ready_num, allocated=j_allocated,
        total_request=j_total_request, min_resources=j_min_resources,
        task_table=j_task_table, n_pending=j_n_pending,
        schedulable=j_schedulable, inqueue=j_inqueue,
        pending_phase=j_pending_phase, preemptable=j_preemptable,
        valid=j_valid)
    queues = QueueArrays(
        weight=q_weight, capability=q_cap, reclaimable=q_reclaimable,
        open=q_open, allocated=q_allocated, request=q_request,
        inqueue_minres=q_inqueue_minres, parent=q_parent, depth=q_depth,
        hier_weight=q_hier_weight, valid=q_valid)
    return SnapshotArrays(
        nodes=nodes, tasks=tasks, jobs=jobs, queues=queues,
        namespace_weight=ns_weight, cluster_capacity=cluster_capacity,
        template_rep=template_rep)


def decode_hierarchy(buf: bytes, job_queue, job_valid):
    """VCS2 buffer -> HierarchyArrays, parsing only the (early) header and
    queue records. ``job_queue``/``job_valid`` come from the already-decoded
    SnapshotArrays (the job section sits late in the buffer; its queue
    indices are all the tree needs for job leaves)."""
    from ..arrays.hierarchy import build_from_specs
    r = _Reader(buf)
    if r.u32() != MAGIC:
        raise ValueError("bad magic (not a VCS2 buffer)")
    R = r.u32()
    nq = r.u32()
    for _ in range(4):
        r.u32()
    for _ in range(R):
        r.skip_string()
    specs = []
    for _ in range(nq):
        r.skip_string()                  # name
        r.f32()                          # weight
        r.off += 4 * R                   # capability vector
        r.off += 2                       # reclaimable, open
        r.off += 8                       # parent, depth
        r.f32()                          # leaf hier weight
        hierarchy = r.string()
        weights = r.string()
        specs.append((hierarchy, weights))
    Q = _bucket(max(nq, 1), 4)
    specs += [("", "")] * (Q - len(specs))
    jq = np.asarray(job_queue, np.int32)
    jv = np.asarray(job_valid, bool)
    return build_from_specs(specs, Q, jq, jv & (jq >= 0))
