"""Native (C++) snapshot packing runtime.

Builds ``packer.cc`` into a shared library on first use (g++, no external
deps) and exposes:

- :func:`pack_wire` — VCS4 buffer -> (SnapshotArrays, dims) via the C++
  packer; the fast path for snapshots arriving over the API boundary.
- :func:`pack_native` — ClusterInfo -> (SnapshotArrays, IndexMaps), i.e.
  serialize + pack_wire; drop-in for :func:`volcano_tpu.arrays.pack`.
- :func:`available` — whether the native library could be built/loaded.

Falls back cleanly: callers should guard with ``available()`` or use
``pack_best_effort`` which silently falls back to the pure-Python packer.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
import threading
from typing import Optional, Tuple

import numpy as np

from ..arrays.schema import (IndexMaps, JobArrays, NodeArrays, QueueArrays,
                             SnapshotArrays, TaskArrays)

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "packer.cc")
_LIB_NAME = "_vcpack.so"

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_error: Optional[str] = None


class _VCArrays(ctypes.Structure):
    _fields_ = (
        [(n, ctypes.c_int32) for n in
         ("R", "Q", "S", "N", "J", "T", "M", "L", "E", "K", "O", "G", "P",
          "nq", "ns", "nn", "nj", "nt")]
        + [(n, ctypes.POINTER(ctypes.c_float)) for n in ("q_weight", "q_cap")]
        + [(n, ctypes.POINTER(ctypes.c_uint8))
           for n in ("q_reclaimable", "q_open")]
        + [(n, ctypes.POINTER(ctypes.c_float))
           for n in ("q_allocated", "q_request", "q_inqueue_minres")]
        + [(n, ctypes.POINTER(ctypes.c_int32)) for n in ("q_parent", "q_depth")]
        + [("q_hier_weight", ctypes.POINTER(ctypes.c_float)),
           ("q_valid", ctypes.POINTER(ctypes.c_uint8)),
           ("ns_weight", ctypes.POINTER(ctypes.c_float))]
        + [(n, ctypes.POINTER(ctypes.c_float))
           for n in ("n_idle", "n_used", "n_releasing", "n_pipelined",
                     "n_allocatable", "n_capability")]
        + [(n, ctypes.POINTER(ctypes.c_int32))
           for n in ("n_labels", "n_taint_kv", "n_taint_key", "n_taint_effect",
                     "n_pod_count", "n_max_pods")]
        + [(n, ctypes.POINTER(ctypes.c_float))
           for n in ("n_gpu_memory", "n_gpu_used")]
        + [(n, ctypes.POINTER(ctypes.c_uint8))
           for n in ("n_schedulable", "n_valid")]
        + [("t_resreq", ctypes.POINTER(ctypes.c_float))]
        + [(n, ctypes.POINTER(ctypes.c_int32))
           for n in ("t_job", "t_status", "t_priority", "t_node", "t_selector",
                     "t_tol_hash", "t_tol_effect", "t_tol_mode", "t_template",
                     "template_rep")]
        + [("t_best_effort", ctypes.POINTER(ctypes.c_uint8)),
           ("t_gpu_request", ctypes.POINTER(ctypes.c_float))]
        + [(n, ctypes.POINTER(ctypes.c_uint8))
           for n in ("t_preemptable", "t_valid")]
        + [(n, ctypes.POINTER(ctypes.c_int32))
           for n in ("j_min_available", "j_queue", "j_namespace", "j_priority",
                     "j_creation_rank", "j_ready_num")]
        + [(n, ctypes.POINTER(ctypes.c_float))
           for n in ("j_allocated", "j_total_request", "j_min_resources")]
        + [(n, ctypes.POINTER(ctypes.c_int32))
           for n in ("j_task_table", "j_n_pending")]
        + [(n, ctypes.POINTER(ctypes.c_uint8))
           for n in ("j_schedulable", "j_inqueue", "j_pending_phase",
                     "j_preemptable", "j_valid")]
        + [("cluster_capacity", ctypes.POINTER(ctypes.c_float)),
           ("error", ctypes.c_char_p)]
    )


def _user_cache_dir() -> str:
    base = os.environ.get("XDG_CACHE_HOME",
                          os.path.join(os.path.expanduser("~"), ".cache"))
    return os.path.join(base, "volcano_tpu")


def _build_lib() -> Optional[str]:
    """Compile packer.cc -> _vcpack.so; returns the library path or None.

    The compile goes to a unique temp file and is os.replace()d into place so
    concurrent builders (e.g. parallel test workers) never load a half-written
    library, and the fallback lives in a per-user cache dir, not a
    world-writable /tmp path.
    """
    global _build_error
    for target_dir in (_HERE, _user_cache_dir()):
        lib_path = os.path.join(target_dir, _LIB_NAME)
        if (os.path.exists(lib_path)
                and os.path.getmtime(lib_path) >= os.path.getmtime(_SRC)):
            _build_error = None
            return lib_path
        tmp_path = None
        try:
            os.makedirs(target_dir, exist_ok=True)
            fd, tmp_path = tempfile.mkstemp(suffix=".so", dir=target_dir)
            os.close(fd)
            subprocess.run(
                ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", _SRC,
                 "-o", tmp_path],
                check=True, capture_output=True, timeout=120)
            os.replace(tmp_path, lib_path)
            _build_error = None
            return lib_path
        except (OSError, subprocess.SubprocessError) as e:
            _build_error = str(e)
            if tmp_path is not None:
                try:
                    os.unlink(tmp_path)
                except OSError:
                    pass
    return None


def _load() -> Optional[ctypes.CDLL]:
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        path = _build_lib()
        if path is None:
            return None
        lib = ctypes.CDLL(path)
        lib.vc_pack.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                ctypes.POINTER(_VCArrays)]
        lib.vc_pack.restype = ctypes.c_int
        lib.vc_free.argtypes = [ctypes.POINTER(_VCArrays)]
        lib.vc_free.restype = None
        _lib = lib
        return lib


def available() -> bool:
    return _load() is not None


def build_error() -> Optional[str]:
    return _build_error


def _np(ptr, shape, dtype):
    n = int(np.prod(shape))
    if n == 0:
        return np.zeros(shape, dtype)
    arr = np.ctypeslib.as_array(ptr, shape=(n,))
    return arr.view(dtype).reshape(shape).copy()


def pack_wire(buf: bytes) -> SnapshotArrays:
    """Parse a VCS4 buffer into SnapshotArrays using the C++ packer."""
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native packer unavailable: {_build_error}")
    out = _VCArrays()
    rc = lib.vc_pack(buf, len(buf), ctypes.byref(out))
    try:
        if rc != 0:
            raise ValueError(
                f"vc_pack failed: {(out.error or b'?').decode()}")
        R, Q, S, N, J, T = out.R, out.Q, out.S, out.N, out.J, out.T
        M, L, E, K, O, G = out.M, out.L, out.E, out.K, out.O, out.G
        b = np.bool_
        nodes = NodeArrays(
            idle=_np(out.n_idle, (N, R), np.float32),
            used=_np(out.n_used, (N, R), np.float32),
            releasing=_np(out.n_releasing, (N, R), np.float32),
            pipelined=_np(out.n_pipelined, (N, R), np.float32),
            allocatable=_np(out.n_allocatable, (N, R), np.float32),
            capability=_np(out.n_capability, (N, R), np.float32),
            labels=_np(out.n_labels, (N, L), np.int32),
            taint_kv=_np(out.n_taint_kv, (N, E), np.int32),
            taint_key=_np(out.n_taint_key, (N, E), np.int32),
            taint_effect=_np(out.n_taint_effect, (N, E), np.int32),
            pod_count=_np(out.n_pod_count, (N,), np.int32),
            max_pods=_np(out.n_max_pods, (N,), np.int32),
            gpu_memory=_np(out.n_gpu_memory, (N, G), np.float32),
            gpu_used=_np(out.n_gpu_used, (N, G), np.float32),
            schedulable=_np(out.n_schedulable, (N,), np.uint8).astype(b),
            valid=_np(out.n_valid, (N,), np.uint8).astype(b))
        tasks = TaskArrays(
            resreq=_np(out.t_resreq, (T, R), np.float32),
            job=_np(out.t_job, (T,), np.int32),
            status=_np(out.t_status, (T,), np.int32),
            priority=_np(out.t_priority, (T,), np.int32),
            node=_np(out.t_node, (T,), np.int32),
            selector=_np(out.t_selector, (T, K), np.int32),
            tol_hash=_np(out.t_tol_hash, (T, O), np.int32),
            tol_effect=_np(out.t_tol_effect, (T, O), np.int32),
            tol_mode=_np(out.t_tol_mode, (T, O), np.int32),
            template=_np(out.t_template, (T,), np.int32),
            best_effort=_np(out.t_best_effort, (T,), np.uint8).astype(b),
            gpu_request=_np(out.t_gpu_request, (T,), np.float32),
            preemptable=_np(out.t_preemptable, (T,), np.uint8).astype(b),
            valid=_np(out.t_valid, (T,), np.uint8).astype(b))
        jobs = JobArrays(
            min_available=_np(out.j_min_available, (J,), np.int32),
            queue=_np(out.j_queue, (J,), np.int32),
            namespace=_np(out.j_namespace, (J,), np.int32),
            priority=_np(out.j_priority, (J,), np.int32),
            creation_rank=_np(out.j_creation_rank, (J,), np.int32),
            ready_num=_np(out.j_ready_num, (J,), np.int32),
            allocated=_np(out.j_allocated, (J, R), np.float32),
            total_request=_np(out.j_total_request, (J, R), np.float32),
            min_resources=_np(out.j_min_resources, (J, R), np.float32),
            task_table=_np(out.j_task_table, (J, M), np.int32),
            n_pending=_np(out.j_n_pending, (J,), np.int32),
            schedulable=_np(out.j_schedulable, (J,), np.uint8).astype(b),
            inqueue=_np(out.j_inqueue, (J,), np.uint8).astype(b),
            pending_phase=_np(out.j_pending_phase, (J,), np.uint8).astype(b),
            preemptable=_np(out.j_preemptable, (J,), np.uint8).astype(b),
            valid=_np(out.j_valid, (J,), np.uint8).astype(b))
        queues = QueueArrays(
            weight=_np(out.q_weight, (Q,), np.float32),
            capability=_np(out.q_cap, (Q, R), np.float32),
            reclaimable=_np(out.q_reclaimable, (Q,), np.uint8).astype(b),
            open=_np(out.q_open, (Q,), np.uint8).astype(b),
            allocated=_np(out.q_allocated, (Q, R), np.float32),
            request=_np(out.q_request, (Q, R), np.float32),
            inqueue_minres=_np(out.q_inqueue_minres, (Q, R), np.float32),
            parent=_np(out.q_parent, (Q,), np.int32),
            depth=_np(out.q_depth, (Q,), np.int32),
            hier_weight=_np(out.q_hier_weight, (Q,), np.float32),
            valid=_np(out.q_valid, (Q,), np.uint8).astype(b))
        return SnapshotArrays(
            nodes=nodes, tasks=tasks, jobs=jobs, queues=queues,
            namespace_weight=_np(out.ns_weight, (S,), np.float32),
            cluster_capacity=_np(out.cluster_capacity, (R,), np.float32),
            template_rep=_np(out.template_rep, (out.P,), np.int32))
    finally:
        lib.vc_free(ctypes.byref(out))


def pack_native(ci) -> Tuple[SnapshotArrays, IndexMaps]:
    """ClusterInfo -> arrays through the wire + native packer path."""
    from .wire import serialize
    buf, maps = serialize(ci)
    return pack_wire(buf), maps


def pack_best_effort(ci) -> Tuple[SnapshotArrays, IndexMaps]:
    """Native path when buildable, pure-Python ``pack`` otherwise."""
    if available():
        return pack_native(ci)
    from ..arrays.pack import pack
    return pack(ci)
