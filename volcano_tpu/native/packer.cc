// Native snapshot packer: VCS4 wire buffer -> dense scheduling arrays.
//
// This is the framework's native runtime component: the host-side hot path
// that turns a serialized cluster snapshot (the payload that crosses the
// API-layer boundary, SURVEY.md section 5.8) into the struct-of-array tensors
// consumed by the compiled TPU cycle.  It mirrors, loop for loop, the
// semantics of volcano_tpu/arrays/pack.py (which remains the pure-Python
// fallback and the equivalence oracle in tests/test_native_pack.py); the
// reference's equivalent moment is SchedulerCache.Snapshot deep-copying the
// cluster mirror (pkg/scheduler/cache/cache.go:712-811).
//
// Wire format VCS4 (little-endian; see volcano_tpu/native/wire.py):
//   u32 magic 'VCS4' (0x34534356), u32 R, nq, ns, nn, nj, nt
//   R   x string            resource dimension names (informational)
//   nq  x queue record      (sorted by name; per-record, Q is small)
//   ns  x namespace record  (sorted by name)
//   node section            COLUMNAR (sorted by name)
//   job section             COLUMNAR (sorted by uid)
//   task section            COLUMNAR (job-major, insertion order in job)
// Columnar sections: a string column (u32 blob_len | u32[n] lens | blob),
// then one array per fixed-width field ([n] or [n,R], row-major), then
// ragged sets as u32 total | u32[n] counts | flat values.  Strings are
// u32 length + UTF-8 bytes; label/taint/selector/toleration sets carry
// precomputed 31-bit hashes (arrays/labels.py encoding).

#include <algorithm>
#include <cstdint>
#include <map>
#include <cstdlib>
#include <cstring>
#include <limits>
#include <numeric>
#include <string>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0x34534356u;  // "VCS4"

// TaskStatus codes (volcano_tpu/api/types.py:14-36; reference
// pkg/scheduler/api/types.go:29-96).
constexpr int32_t kStatusPending = 0;
inline bool CountsForRequest(int32_t status) {
  // Pending or AllocatedStatus (Allocated/Binding/Bound/Running).
  return status == 0 || status == 1 || status == 3 || status == 4 ||
         status == 5;
}

struct Reader {
  const uint8_t* p;
  const uint8_t* end;
  bool ok = true;

  bool Need(size_t n) {
    if (!ok || static_cast<size_t>(end - p) < n) {
      ok = false;
      return false;
    }
    return true;
  }
  uint32_t U32() {
    if (!Need(4)) return 0;
    uint32_t v;
    std::memcpy(&v, p, 4);
    p += 4;
    return v;
  }
  int32_t I32() { return static_cast<int32_t>(U32()); }
  uint8_t U8() {
    if (!Need(1)) return 0;
    return *p++;
  }
  float F32() {
    if (!Need(4)) return 0;
    float v;
    std::memcpy(&v, p, 4);
    p += 4;
    return v;
  }
  double F64() {
    if (!Need(8)) return 0;
    double v;
    std::memcpy(&v, p, 8);
    p += 8;
    return v;
  }
  void Skip(size_t n) {
    if (Need(n)) p += n;
  }
  void SkipString() { Skip(U32()); }
  void F32Vec(float* dst, uint32_t n) {
    if (!Need(4ull * n)) return;
    std::memcpy(dst, p, 4ull * n);
    p += 4ull * n;
  }
  void I32Vec(int32_t* dst, uint32_t n) {
    if (!Need(4ull * n)) return;
    std::memcpy(dst, p, 4ull * n);
    p += 4ull * n;
  }
};

int32_t Bucket(int64_t n, int32_t minimum) {
  // Mirror of arrays/schema.bucket (graded grid): powers of two up to
  // 1024, then multiples of next_pow2(n)/8.
  int64_t b = minimum;
  while (b < n && b < 1024) b *= 2;
  if (n <= b) return static_cast<int32_t>(b);
  int64_t p = 1;
  while (p < n) p *= 2;
  int64_t g = p / 8 > 1024 ? p / 8 : 1024;
  return static_cast<int32_t>((n + g - 1) / g * g);
}

}  // namespace

extern "C" {

// Pointers are malloc'd by vc_pack and released by vc_free.  Row-major.
struct VCArrays {
  // Bucketed dims and real counts.
  int32_t R, Q, S, N, J, T, M, L, E, K, O, G, P;
  int32_t nq, ns, nn, nj, nt;
  // Queues.
  float* q_weight;
  float* q_cap;
  uint8_t* q_reclaimable;
  uint8_t* q_open;
  float* q_allocated;
  float* q_request;
  float* q_inqueue_minres;
  int32_t* q_parent;
  int32_t* q_depth;
  float* q_hier_weight;
  uint8_t* q_valid;
  float* ns_weight;
  // Nodes.
  float* n_idle;
  float* n_used;
  float* n_releasing;
  float* n_pipelined;
  float* n_allocatable;
  float* n_capability;
  int32_t* n_labels;
  int32_t* n_taint_kv;
  int32_t* n_taint_key;
  int32_t* n_taint_effect;
  int32_t* n_pod_count;
  int32_t* n_max_pods;
  float* n_gpu_memory;  // [N, G] per shared-GPU card
  float* n_gpu_used;    // [N, G]
  uint8_t* n_schedulable;
  uint8_t* n_valid;
  // Tasks.
  float* t_resreq;
  int32_t* t_job;
  int32_t* t_status;
  int32_t* t_priority;
  int32_t* t_node;
  int32_t* t_selector;
  int32_t* t_tol_hash;
  int32_t* t_tol_effect;
  int32_t* t_tol_mode;
  int32_t* t_template;      // predicate-template id (cache.go analog)
  int32_t* template_rep;    // [P] representative task per template, -1 pad
  uint8_t* t_best_effort;
  float* t_gpu_request;
  uint8_t* t_preemptable;
  uint8_t* t_valid;
  // Jobs.
  int32_t* j_min_available;
  int32_t* j_queue;
  int32_t* j_namespace;
  int32_t* j_priority;
  int32_t* j_creation_rank;
  int32_t* j_ready_num;
  float* j_allocated;
  float* j_total_request;
  float* j_min_resources;
  int32_t* j_task_table;
  int32_t* j_n_pending;
  uint8_t* j_schedulable;
  uint8_t* j_inqueue;
  uint8_t* j_pending_phase;
  uint8_t* j_preemptable;
  uint8_t* j_valid;
  float* cluster_capacity;
  const char* error;  // static string; NULL on success
};

void vc_free(VCArrays* a) {
  if (!a) return;
  float** fptrs[] = {&a->q_weight,        &a->q_cap,
                     &a->q_allocated,     &a->q_request,
                     &a->q_inqueue_minres, &a->q_hier_weight,
                     &a->ns_weight,
                     &a->n_idle,          &a->n_used,
                     &a->n_releasing,     &a->n_pipelined,
                     &a->n_allocatable,   &a->n_capability,
                     &a->t_resreq,        &a->t_gpu_request,
                     &a->n_gpu_memory,    &a->n_gpu_used,
                     &a->j_allocated,
                     &a->j_total_request, &a->j_min_resources,
                     &a->cluster_capacity};
  for (auto** f : fptrs) {
    std::free(*f);
    *f = nullptr;
  }
  int32_t** iptrs[] = {&a->q_parent,    &a->q_depth,       &a->n_labels,
                       &a->n_taint_kv,  &a->n_taint_key,   &a->n_taint_effect,
                       &a->n_pod_count, &a->n_max_pods,    &a->t_job,
                       &a->t_status,    &a->t_priority,    &a->t_node,
                       &a->t_selector,  &a->t_tol_hash,    &a->t_tol_effect,
                       &a->t_tol_mode,  &a->t_template,    &a->template_rep,
                       &a->j_min_available, &a->j_queue,
                       &a->j_namespace, &a->j_priority,    &a->j_creation_rank,
                       &a->j_ready_num, &a->j_task_table,  &a->j_n_pending};
  for (auto** i : iptrs) {
    std::free(*i);
    *i = nullptr;
  }
  uint8_t** bptrs[] = {&a->q_reclaimable, &a->q_open,        &a->q_valid,
                       &a->n_schedulable, &a->n_valid,       &a->t_best_effort,
                       &a->t_preemptable, &a->t_valid,       &a->j_schedulable,
                       &a->j_inqueue,     &a->j_pending_phase,
                       &a->j_preemptable, &a->j_valid};
  for (auto** b : bptrs) {
    std::free(*b);
    *b = nullptr;
  }
}

int vc_pack(const uint8_t* buf, uint64_t len, VCArrays* a) {
  std::memset(a, 0, sizeof(*a));
  Reader r{buf, buf + len};
  if (r.U32() != kMagic) {
    a->error = "bad magic (not a VCS4 buffer)";
    return 1;
  }
  const uint32_t R = r.U32();
  const uint32_t nq = r.U32(), ns = r.U32(), nn = r.U32(), nj = r.U32(),
                 nt = r.U32();
  if (!r.ok || R == 0 || R > 1024) {
    a->error = "corrupt header";
    return 1;
  }
  // Sanity-bound every count against the bytes actually present before any
  // allocation sized by it: a crafted header must fail as ValueError on the
  // Python side, never as bad_alloc/OOM in here.  Minimum record sizes:
  // queue 4+4+4R+2+8+4+8, namespace 4+4, node 4+24R+8+1+4+8, job 4+16+8+4+8R+3,
  // task 4+4+4R+12+2+4+8.
  const uint64_t remaining = static_cast<uint64_t>(r.end - r.p);
  const uint64_t min_bytes = uint64_t(nq) * (30 + 4ull * R) + uint64_t(ns) * 8 +
                             uint64_t(nn) * (17 + 24ull * R) +
                             uint64_t(nj) * (35 + 8ull * R) +
                             uint64_t(nt) * (34 + 4ull * R);
  if (min_bytes > remaining) {
    a->error = "corrupt header: counts exceed buffer size";
    return 1;
  }
  for (uint32_t i = 0; i < R; ++i) r.SkipString();

  const float inf = std::numeric_limits<float>::infinity();
  const int32_t Q = Bucket(std::max<int64_t>(nq, 1), 4);
  const int32_t S = Bucket(std::max<int64_t>(ns, 1), 4);
  const int32_t N = Bucket(std::max<int64_t>(nn, 1), 8);
  const int32_t J = Bucket(std::max<int64_t>(nj, 1), 4);
  const int32_t T = Bucket(std::max<int64_t>(nt, 1), 8);

  bool oom = false;
  auto fmalloc = [&oom](int64_t n) {
    auto* p = static_cast<float*>(std::calloc(std::max<int64_t>(n, 1), 4));
    if (!p) oom = true;
    return p;
  };
  auto imalloc = [&oom](int64_t n) {
    auto* p = static_cast<int32_t*>(std::calloc(std::max<int64_t>(n, 1), 4));
    if (!p) oom = true;
    return p;
  };
  auto bmalloc = [&oom](int64_t n) {
    auto* p = static_cast<uint8_t*>(std::calloc(std::max<int64_t>(n, 1), 1));
    if (!p) oom = true;
    return p;
  };
#define VC_CHECK_ALLOC()            \
  if (oom) {                        \
    a->error = "allocation failed"; \
    return 1;                       \
  }

  a->R = R;
  a->Q = Q;
  a->S = S;
  a->N = N;
  a->J = J;
  a->T = T;
  a->nq = nq;
  a->ns = ns;
  a->nn = nn;
  a->nj = nj;
  a->nt = nt;

  // ------------------------------------------------------------- queues
  a->q_weight = fmalloc(Q);
  a->q_cap = fmalloc(int64_t(Q) * R);
  for (int64_t i = 0; i < int64_t(Q) * R; ++i) a->q_cap[i] = inf;
  a->q_reclaimable = bmalloc(Q);
  a->q_open = bmalloc(Q);
  a->q_allocated = fmalloc(int64_t(Q) * R);
  a->q_request = fmalloc(int64_t(Q) * R);
  a->q_inqueue_minres = fmalloc(int64_t(Q) * R);
  a->q_parent = imalloc(Q);
  a->q_depth = imalloc(Q);
  a->q_hier_weight = fmalloc(Q);
  a->q_valid = bmalloc(Q);
  VC_CHECK_ALLOC();
  for (int32_t i = 0; i < Q; ++i) {
    a->q_parent[i] = -1;
    a->q_hier_weight[i] = 1.0f;
  }
  for (uint32_t i = 0; i < nq; ++i) {
    r.SkipString();
    a->q_weight[i] = std::max(r.F32(), 0.0f);
    r.F32Vec(a->q_cap + int64_t(i) * R, R);
    a->q_reclaimable[i] = r.U8();
    a->q_open[i] = r.U8();
    a->q_parent[i] = r.I32();
    a->q_depth[i] = r.I32();
    a->q_hier_weight[i] = r.F32();
    r.SkipString();  // hierarchy annotation (decoded python-side, pywire)
    r.SkipString();  // hierarchy weights annotation
    a->q_valid[i] = 1;
  }

  // --------------------------------------------------------- namespaces
  a->ns_weight = fmalloc(S);
  for (int32_t i = 0; i < S; ++i) a->ns_weight[i] = 1.0f;
  for (uint32_t i = 0; i < ns; ++i) {
    r.SkipString();
    a->ns_weight[i] = std::max(r.F32(), 1.0f);
  }

  // -------------------------------------------------------------- nodes
  a->n_idle = fmalloc(int64_t(N) * R);
  a->n_used = fmalloc(int64_t(N) * R);
  a->n_releasing = fmalloc(int64_t(N) * R);
  a->n_pipelined = fmalloc(int64_t(N) * R);
  a->n_allocatable = fmalloc(int64_t(N) * R);
  a->n_capability = fmalloc(int64_t(N) * R);
  a->n_pod_count = imalloc(N);
  a->n_max_pods = imalloc(N);
  a->n_schedulable = bmalloc(N);
  a->n_valid = bmalloc(N);
  VC_CHECK_ALLOC();
  // Columnar node section (VCS4): bulk memcpy reads; variable-width sets
  // arrive as a count column + one flat array.
  auto SkipStringColumn = [&](uint32_t n) {
    uint32_t blob = r.U32();
    r.Skip(4ull * n);
    r.Skip(blob);
  };
  auto ReadCounts = [&](uint32_t n, std::vector<uint32_t>* counts,
                        uint32_t* total) -> bool {
    *total = r.U32();
    counts->assign(n, 0);
    if (n && r.Need(4ull * n)) {
      std::memcpy(counts->data(), r.p, 4ull * n);
      r.p += 4ull * n;
    }
    // the demux loops below trust the per-row counts, so a corrupt column
    // must fail HERE: every count bounded by the total, and the counts
    // summing exactly to it (the VCS2 reader Need()-checked per record;
    // this is the columnar equivalent of that discipline)
    uint64_t sum = 0;
    for (uint32_t v : *counts) {
      if (v > *total) return false;
      sum += v;
    }
    return r.ok && sum == *total;
  };
  SkipStringColumn(nn);
  // six [nn, R] matrices land in the first nn rows of the padded arrays
  r.F32Vec(a->n_idle, nn * R);
  r.F32Vec(a->n_used, nn * R);
  r.F32Vec(a->n_releasing, nn * R);
  r.F32Vec(a->n_pipelined, nn * R);
  r.F32Vec(a->n_allocatable, nn * R);
  r.F32Vec(a->n_capability, nn * R);
  r.I32Vec(a->n_pod_count, nn);
  r.I32Vec(a->n_max_pods, nn);
  if (nn && r.Need(nn)) {
    std::memcpy(a->n_schedulable, r.p, nn);
    r.p += nn;
  }
  for (uint32_t i = 0; i < nn; ++i) a->n_valid[i] = 1;
  uint32_t gtotal = 0, ltotal = 0, tntotal = 0;
  std::vector<uint32_t> gcnt, lcnt, tcnt;
  if (!ReadCounts(nn, &gcnt, &gtotal) || !r.Need(8ull * gtotal)) {
    a->error = "truncated buffer";
    return 1;
  }
  std::vector<float> gflat(2ull * gtotal);
  r.F32Vec(gflat.data(), 2 * gtotal);
  if (!ReadCounts(nn, &lcnt, &ltotal) || !r.Need(4ull * ltotal)) {
    a->error = "truncated buffer";
    return 1;
  }
  std::vector<int32_t> lflat(ltotal);
  r.I32Vec(lflat.data(), ltotal);
  if (!ReadCounts(nn, &tcnt, &tntotal) || !r.Need(12ull * tntotal)) {
    a->error = "truncated buffer";
    return 1;
  }
  std::vector<int32_t> tflat(3ull * tntotal);
  r.I32Vec(tflat.data(), 3 * tntotal);
  uint32_t maxl = 0, maxe = 0, maxg = 0;
  for (auto v : lcnt) maxl = std::max(maxl, v);
  for (auto v : tcnt) maxe = std::max(maxe, v);
  for (auto v : gcnt) maxg = std::max(maxg, v);
  const int32_t L = std::max<int32_t>(static_cast<int32_t>(maxl), 1);
  const int32_t E = std::max<int32_t>(static_cast<int32_t>(maxe), 1);
  // Power-of-two bucketed like arrays/pack.py (buckets.get("G", 1)).
  const int32_t G = Bucket(std::max<int64_t>(static_cast<int64_t>(maxg), 1), 1);
  a->L = L;
  a->E = E;
  a->G = G;
  a->n_labels = imalloc(int64_t(N) * L);
  a->n_taint_kv = imalloc(int64_t(N) * E);
  a->n_taint_key = imalloc(int64_t(N) * E);
  a->n_taint_effect = imalloc(int64_t(N) * E);
  a->n_gpu_memory = fmalloc(int64_t(N) * G);
  a->n_gpu_used = fmalloc(int64_t(N) * G);
  VC_CHECK_ALLOC();
  {
    uint64_t go = 0, lo = 0, to = 0;
    for (uint32_t i = 0; i < nn; ++i) {
      for (uint32_t g = 0; g < gcnt[i]; ++g, ++go) {
        a->n_gpu_memory[int64_t(i) * G + g] = gflat[2 * go];
        a->n_gpu_used[int64_t(i) * G + g] = gflat[2 * go + 1];
      }
      for (uint32_t l2 = 0; l2 < lcnt[i]; ++l2, ++lo)
        a->n_labels[int64_t(i) * L + l2] = lflat[lo];
      for (uint32_t t = 0; t < tcnt[i]; ++t, ++to) {
        a->n_taint_kv[int64_t(i) * E + t] = tflat[3 * to];
        a->n_taint_key[int64_t(i) * E + t] = tflat[3 * to + 1];
        a->n_taint_effect[int64_t(i) * E + t] = tflat[3 * to + 2];
      }
    }
  }

  // --------------------------------------------------------------- jobs
  a->j_min_available = imalloc(J);
  a->j_queue = imalloc(J);
  a->j_namespace = imalloc(J);
  a->j_priority = imalloc(J);
  a->j_creation_rank = imalloc(J);
  a->j_ready_num = imalloc(J);
  a->j_allocated = fmalloc(int64_t(J) * R);
  a->j_total_request = fmalloc(int64_t(J) * R);
  a->j_min_resources = fmalloc(int64_t(J) * R);
  a->j_n_pending = imalloc(J);
  a->j_schedulable = bmalloc(J);
  a->j_inqueue = bmalloc(J);
  a->j_pending_phase = bmalloc(J);
  a->j_preemptable = bmalloc(J);
  a->j_valid = bmalloc(J);
  VC_CHECK_ALLOC();
  std::vector<int32_t> job_queue_raw(nj, -1);
  std::vector<double> job_ts(nj, 0.0);
  SkipStringColumn(nj);
  r.I32Vec(a->j_min_available, nj);
  r.I32Vec(job_queue_raw.data(), nj);
  r.I32Vec(a->j_namespace, nj);
  r.I32Vec(a->j_priority, nj);
  if (nj && r.Need(8ull * nj)) {
    std::memcpy(job_ts.data(), r.p, 8ull * nj);
    r.p += 8ull * nj;
  }
  r.I32Vec(a->j_ready_num, nj);
  r.F32Vec(a->j_allocated, nj * R);
  r.F32Vec(a->j_min_resources, nj * R);
  std::vector<uint8_t> jflags(3ull * nj, 0);
  if (nj && r.Need(3ull * nj)) {
    std::memcpy(jflags.data(), r.p, 3ull * nj);
    r.p += 3ull * nj;
  }
  for (uint32_t i = 0; i < nj; ++i) {
    a->j_pending_phase[i] = jflags[3ull * i];
    const uint8_t gang_valid = jflags[3ull * i + 1];
    a->j_preemptable[i] = jflags[3ull * i + 2];
    a->j_valid[i] = 1;
    a->j_queue[i] = std::max(job_queue_raw[i], 0);
    a->j_inqueue[i] = !a->j_pending_phase[i];
    bool queue_open = job_queue_raw[i] >= 0 &&
                      job_queue_raw[i] < static_cast<int32_t>(nq) &&
                      a->q_open[job_queue_raw[i]];
    a->j_schedulable[i] = gang_valid && queue_open && a->j_inqueue[i];
  }
  // creation_rank: stable sort of uid-sorted jobs by creation timestamp
  // (arrays/pack.py:239-240).
  {
    std::vector<int32_t> idx(nj);
    std::iota(idx.begin(), idx.end(), 0);
    std::stable_sort(idx.begin(), idx.end(), [&](int32_t x, int32_t y) {
      return job_ts[x] < job_ts[y];
    });
    for (uint32_t rk = 0; rk < nj; ++rk) a->j_creation_rank[idx[rk]] = rk;
  }

  // -------------------------------------------------------------- tasks
  a->t_resreq = fmalloc(int64_t(T) * R);
  a->t_job = imalloc(T);
  a->t_status = imalloc(T);
  a->t_priority = imalloc(T);
  a->t_node = imalloc(T);
  a->t_best_effort = bmalloc(T);
  a->t_gpu_request = fmalloc(T);
  a->t_preemptable = bmalloc(T);
  a->t_valid = bmalloc(T);
  VC_CHECK_ALLOC();
  for (int32_t i = 0; i < T; ++i) {
    a->t_job[i] = -1;
    a->t_node[i] = -1;
  }
  SkipStringColumn(nt);
  r.I32Vec(a->t_job, nt);
  r.F32Vec(a->t_resreq, nt * R);
  r.I32Vec(a->t_status, nt);
  r.I32Vec(a->t_priority, nt);
  r.I32Vec(a->t_node, nt);
  std::vector<uint8_t> tflags(2ull * nt, 0);
  if (nt && r.Need(2ull * nt)) {
    std::memcpy(tflags.data(), r.p, 2ull * nt);
    r.p += 2ull * nt;
  }
  r.F32Vec(a->t_gpu_request, nt);
  uint32_t stotal = 0, ototal = 0;
  std::vector<uint32_t> scnt, ocnt;
  if (!ReadCounts(nt, &scnt, &stotal) || !r.Need(4ull * stotal)) {
    a->error = "truncated buffer";
    return 1;
  }
  std::vector<int32_t> sflat(stotal);
  r.I32Vec(sflat.data(), stotal);
  if (!ReadCounts(nt, &ocnt, &ototal) || !r.Need(12ull * ototal)) {
    a->error = "truncated buffer";
    return 1;
  }
  std::vector<int32_t> oflat(3ull * ototal);
  r.I32Vec(oflat.data(), 3 * ototal);
  // preferred-affinity template split key (VCS4): one i32 signature hash
  // per task, folded into the template key below so tasks with different
  // preferred terms never share a score row (arrays/pack.py na_sig analog)
  std::vector<int32_t> nakey(nt, 0);
  r.I32Vec(nakey.data(), nt);
  if (!r.ok) {
    a->error = "truncated buffer";
    return 1;
  }
  std::vector<uint64_t> soff(nt + 1, 0), ooff(nt + 1, 0);
  for (uint32_t i = 0; i < nt; ++i) {
    soff[i + 1] = soff[i] + scnt[i];
    ooff[i + 1] = ooff[i] + ocnt[i];
  }
  std::vector<std::vector<int32_t>> pending(nj);
  for (uint32_t i = 0; i < nt; ++i) {
    a->t_best_effort[i] = tflags[2ull * i];
    a->t_preemptable[i] = tflags[2ull * i + 1];
    a->t_valid[i] = 1;
    const int32_t ji = a->t_job[i];
    if (ji >= 0 && ji < static_cast<int32_t>(nj)) {
      if (a->t_status[i] == kStatusPending) pending[ji].push_back(i);
      if (CountsForRequest(a->t_status[i])) {
        float* req = a->j_total_request + int64_t(ji) * R;
        const float* res = a->t_resreq + int64_t(i) * R;
        for (uint32_t d = 0; d < R; ++d) req[d] += res[d];
      }
    }
  }
  uint32_t maxk = 0, maxo = 0;
  for (auto v : scnt) maxk = std::max(maxk, v);
  for (auto v : ocnt) maxo = std::max(maxo, v);
  const int32_t K = std::max<int32_t>(static_cast<int32_t>(maxk), 1);
  const int32_t O = std::max<int32_t>(static_cast<int32_t>(maxo), 1);
  a->K = K;
  a->O = O;
  a->t_selector = imalloc(int64_t(T) * K);
  a->t_tol_hash = imalloc(int64_t(T) * O);
  a->t_tol_effect = imalloc(int64_t(T) * O);
  a->t_tol_mode = imalloc(int64_t(T) * O);
  VC_CHECK_ALLOC();
  for (uint32_t i = 0; i < nt; ++i) {
    for (uint32_t k = 0; k < scnt[i]; ++k)
      a->t_selector[int64_t(i) * K + k] = sflat[soff[i] + k];
    for (uint32_t o = 0; o < ocnt[i]; ++o) {
      const uint64_t src = 3ull * (ooff[i] + o);
      a->t_tol_hash[int64_t(i) * O + o] = oflat[src];
      a->t_tol_effect[int64_t(i) * O + o] = oflat[src + 1];
      a->t_tol_mode[int64_t(i) * O + o] = oflat[src + 2];
    }
  }

  // Predicate templates: tasks with identical selector/toleration rows share
  // one id, first-occurrence order (arrays/pack.py template dedupe; the
  // predicate-cache key of plugins/predicates/cache.go:42-67).
  a->t_template = imalloc(T);
  VC_CHECK_ALLOC();
  {
    std::map<std::vector<int32_t>, int32_t> template_of;
    std::vector<int32_t> reps;
    for (uint32_t i = 0; i < nt; ++i) {
      std::vector<int32_t> key;
      key.reserve(scnt[i] + 3ull * ocnt[i] + 4);
      for (uint32_t k = 0; k < scnt[i]; ++k)
        key.push_back(sflat[soff[i] + k]);
      key.push_back(std::numeric_limits<int32_t>::min());
      for (uint32_t o = 0; o < ocnt[i]; ++o)
        key.push_back(oflat[3ull * (ooff[i] + o)]);
      key.push_back(std::numeric_limits<int32_t>::min());
      for (uint32_t o = 0; o < ocnt[i]; ++o)
        key.push_back(oflat[3ull * (ooff[i] + o) + 1]);
      key.push_back(std::numeric_limits<int32_t>::min());
      for (uint32_t o = 0; o < ocnt[i]; ++o)
        key.push_back(oflat[3ull * (ooff[i] + o) + 2]);
      key.push_back(std::numeric_limits<int32_t>::min());
      key.push_back(nakey[i]);
      auto it = template_of.find(key);
      int32_t tid;
      if (it == template_of.end()) {
        tid = static_cast<int32_t>(reps.size());
        template_of.emplace(std::move(key), tid);
        reps.push_back(static_cast<int32_t>(i));
      } else {
        tid = it->second;
      }
      a->t_template[i] = tid;
    }
    const int32_t P =
        Bucket(std::max<int64_t>(static_cast<int64_t>(reps.size()), 1), 4);
    a->P = P;
    a->template_rep = imalloc(P);
    VC_CHECK_ALLOC();
    for (int32_t i = 0; i < P; ++i) a->template_rep[i] = -1;
    std::copy(reps.begin(), reps.end(), a->template_rep);
  }

  // Pending-task tables: task order = priority desc, insertion order
  // (arrays/pack.py:262-265; reference priority plugin TaskOrderFn).
  size_t maxp = 0;
  for (auto& p : pending) maxp = std::max(maxp, p.size());
  const int32_t M = Bucket(static_cast<int64_t>(std::max<size_t>(maxp, 0)), 4);
  a->M = M;
  a->j_task_table = imalloc(int64_t(J) * M);
  VC_CHECK_ALLOC();
  for (int64_t i = 0; i < int64_t(J) * M; ++i) a->j_task_table[i] = -1;
  for (uint32_t ji = 0; ji < nj; ++ji) {
    auto& p = pending[ji];
    std::stable_sort(p.begin(), p.end(), [&](int32_t x, int32_t y) {
      if (a->t_priority[x] != a->t_priority[y])
        return a->t_priority[x] > a->t_priority[y];
      return x < y;
    });
    a->j_n_pending[ji] = static_cast<int32_t>(p.size());
    std::copy(p.begin(), p.end(), a->j_task_table + int64_t(ji) * M);
  }

  // Queue aggregates over member jobs (arrays/pack.py:291-303; reference
  // proportion.OnSessionOpen, proportion.go:95-139).  Jobs whose queue was
  // unknown to the serializer (raw index -1) are skipped.
  for (uint32_t ji = 0; ji < nj; ++ji) {
    const int32_t qi = job_queue_raw[ji];
    if (qi < 0 || qi >= static_cast<int32_t>(nq)) continue;
    for (uint32_t d = 0; d < R; ++d) {
      a->q_allocated[int64_t(qi) * R + d] += a->j_allocated[int64_t(ji) * R + d];
      a->q_request[int64_t(qi) * R + d] +=
          a->j_total_request[int64_t(ji) * R + d];
      if (a->j_inqueue[ji])
        a->q_inqueue_minres[int64_t(qi) * R + d] +=
            a->j_min_resources[int64_t(ji) * R + d];
    }
  }

  a->cluster_capacity = fmalloc(R);
  VC_CHECK_ALLOC();
  for (uint32_t i = 0; i < nn; ++i)
    for (uint32_t d = 0; d < R; ++d)
      a->cluster_capacity[d] += a->n_allocatable[int64_t(i) * R + d];

  if (!r.ok) {
    a->error = "truncated buffer";
    return 1;
  }
  return 0;
}

}  // extern "C"
