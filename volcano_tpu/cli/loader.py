"""YAML <-> batch Job conversion, accepting the reference's manifest shape
(example/job.yaml style, batch.volcano.sh/v1alpha1) so existing Volcano
manifests submit unchanged."""

from __future__ import annotations

from typing import Dict, List, Optional

import yaml

from ..api.batch import (Job, LifecyclePolicy, PodTemplate, TaskSpec,
                         VolumeSpec)
from ..api.job_info import Toleration
from ..api.types import BusAction, BusEvent


def _policies(raw: Optional[List[Dict]]) -> List[LifecyclePolicy]:
    out = []
    for p in raw or []:
        out.append(LifecyclePolicy(
            action=BusAction(p["action"]),
            event=BusEvent(p["event"]) if p.get("event") else None,
            events=[BusEvent(e) for e in p.get("events", [])],
            exit_code=p.get("exitCode"),
            timeout_seconds=p.get("timeout")))
    return out


def _template(raw: Optional[Dict]) -> PodTemplate:
    raw = raw or {}
    spec = raw.get("spec", raw)
    meta = raw.get("metadata", {})
    # container requests SUM across containers (kube pod-request semantics)
    from ..api.resource import CPU, Resource, parse_quantity
    summed: Dict[str, float] = {}
    for c in spec.get("containers", []) or []:
        reqs = (c.get("resources") or {}).get("requests") or {}
        for k, v in reqs.items():
            summed[k] = summed.get(k, 0.0) + parse_quantity(v, is_cpu=(k == CPU))
    resources: Dict[str, object] = {
        k: (v / 1000.0 if k == CPU else v) for k, v in summed.items()}
    tolerations = [Toleration(key=t.get("key", ""),
                              operator=t.get("operator", "Equal"),
                              value=t.get("value", ""),
                              effect=t.get("effect", ""))
                   for t in spec.get("tolerations", []) or []]

    # k8s affinity.nodeAffinity: required OR-of-terms + weighted preferred,
    # with full matchExpressions operator semantics (api.NodeSelectorTerm)
    def _term(raw_term):
        from ..api import NodeSelectorTerm
        return NodeSelectorTerm(
            match_labels=dict(raw_term.get("matchLabels") or {}),
            match_expressions=[
                (e.get("key", ""), e.get("operator", "In"),
                 tuple(e.get("values") or ()))
                for e in raw_term.get("matchExpressions") or []])

    na = (spec.get("affinity") or {}).get("nodeAffinity") or {}
    req = (na.get("requiredDuringSchedulingIgnoredDuringExecution")
           or {}).get("nodeSelectorTerms") or []
    pref = na.get("preferredDuringSchedulingIgnoredDuringExecution") or []
    affinity_required = [_term(t) for t in req]
    affinity_preferred = [
        (_term(p.get("preference") or {}), float(p.get("weight", 1)))
        for p in pref]
    return PodTemplate(
        resources=resources,
        labels=dict(meta.get("labels") or {}),
        annotations=dict(meta.get("annotations") or {}),
        node_selector=dict(spec.get("nodeSelector") or {}),
        tolerations=tolerations,
        affinity_required=affinity_required,
        affinity_preferred=affinity_preferred,
        priority=int(spec.get("priority", 0)),
        restart_policy=spec.get("restartPolicy", "OnFailure"))


def job_from_dict(data: Dict) -> Job:
    meta = data.get("metadata", {})
    spec = data.get("spec", {})
    tasks = []
    for t in spec.get("tasks", []) or []:
        tasks.append(TaskSpec(
            name=t.get("name", ""),
            replicas=int(t.get("replicas", 0)),
            template=_template(t.get("template")),
            policies=_policies(t.get("policies")),
            min_available=t.get("minAvailable"),
            max_retry=int(t.get("maxRetry", 0))))
    volumes = [VolumeSpec(mount_path=v.get("mountPath", ""),
                          volume_claim_name=v.get("volumeClaimName", ""),
                          storage=v.get("storage", ""))
               for v in spec.get("volumes", []) or []]
    return Job(
        name=meta.get("name", ""),
        namespace=meta.get("namespace", "default"),
        annotations=dict(meta.get("annotations") or {}),
        labels=dict(meta.get("labels") or {}),
        scheduler_name=spec.get("schedulerName", ""),
        min_available=int(spec.get("minAvailable", 0)),
        min_success=spec.get("minSuccess"),
        volumes=volumes,
        tasks=tasks,
        policies=_policies(spec.get("policies")),
        plugins={k: list(v or []) for k, v in
                 (spec.get("plugins") or {}).items()},
        queue=spec.get("queue", ""),
        max_retry=int(spec.get("maxRetry", 0)),
        ttl_seconds_after_finished=spec.get("ttlSecondsAfterFinished"),
        priority_class_name=spec.get("priorityClassName", ""))


def job_from_yaml(text: str) -> Job:
    return job_from_dict(yaml.safe_load(text))
