"""python -m volcano_tpu.cli.vjobs — see vbin.vjobs."""
import sys
from .vbin import vjobs

if __name__ == "__main__":
    sys.exit(vjobs())
