"""Standalone single-purpose CLI binaries.

Reference: cmd/cli/{vsub,vcancel,vjobs,vqueues,vsuspend,vresume}/main.go —
thin entrypoints that each wrap one vcctl command so batch users get the
familiar qsub-style verbs.  Each maps argv onto the corresponding vcctl
subcommand and delegates to :func:`volcano_tpu.cli.vcctl.main`.

Run as modules: ``python -m volcano_tpu.cli.vsub --state /tmp/vc.pkl -f job.yaml``.
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

from . import vcctl


def _run(argv_for_vcctl: List[str], system=None) -> int:
    from ..webhooks import AdmissionError
    try:
        print(vcctl.main(argv_for_vcctl, system=system))
        return 0
    except (vcctl.VcctlError, AdmissionError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1


def _base_parser(prog: str, desc: str) -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog=prog, description=desc)
    p.add_argument("--state", help="pickled VolcanoSystem state file")
    from ..version import version_string
    p.add_argument("--version", action="version",
                   version=version_string())
    return p


def _state_args(args) -> List[str]:
    return ["--state", args.state] if args.state else []


def vsub(argv: Optional[List[str]] = None, system=None) -> int:
    """Submit a job from a YAML manifest (reference cmd/cli/vsub)."""
    p = _base_parser("vsub", "submit a volcano job")
    p.add_argument("-f", "--filename", required=True)
    p.add_argument("-q", "--queue", default="")
    a = p.parse_args(argv)
    cmd = _state_args(a) + ["job", "run", "-f", a.filename]
    if a.queue:
        cmd += ["-q", a.queue]
    return _run(cmd, system)


def vcancel(argv: Optional[List[str]] = None, system=None) -> int:
    """Delete a job (reference cmd/cli/vcancel)."""
    p = _base_parser("vcancel", "cancel (delete) a volcano job")
    p.add_argument("-N", "--name", required=True)
    p.add_argument("-n", "--namespace", default="default")
    a = p.parse_args(argv)
    return _run(_state_args(a) + ["job", "delete", "-N", a.name,
                                  "-n", a.namespace], system)


def vjobs(argv: Optional[List[str]] = None, system=None) -> int:
    """List jobs (reference cmd/cli/vjobs)."""
    p = _base_parser("vjobs", "list volcano jobs")
    p.add_argument("-n", "--namespace", default="")
    a = p.parse_args(argv)
    cmd = _state_args(a) + ["job", "list"]
    if a.namespace:
        cmd += ["-n", a.namespace]
    return _run(cmd, system)


def vqueues(argv: Optional[List[str]] = None, system=None) -> int:
    """List queues (reference cmd/cli/vqueues)."""
    p = _base_parser("vqueues", "list volcano queues")
    a = p.parse_args(argv)
    return _run(_state_args(a) + ["queue", "list"], system)


def vsuspend(argv: Optional[List[str]] = None, system=None) -> int:
    """Suspend a job via a bus AbortJob Command (reference cmd/cli/vsuspend)."""
    p = _base_parser("vsuspend", "suspend a volcano job")
    p.add_argument("-N", "--name", required=True)
    p.add_argument("-n", "--namespace", default="default")
    a = p.parse_args(argv)
    return _run(_state_args(a) + ["job", "suspend", "-N", a.name,
                                  "-n", a.namespace], system)


def vresume(argv: Optional[List[str]] = None, system=None) -> int:
    """Resume a suspended job (reference cmd/cli/vresume)."""
    p = _base_parser("vresume", "resume a volcano job")
    p.add_argument("-N", "--name", required=True)
    p.add_argument("-n", "--namespace", default="default")
    a = p.parse_args(argv)
    return _run(_state_args(a) + ["job", "resume", "-N", a.name,
                                  "-n", a.namespace], system)
