"""python -m volcano_tpu.cli.vresume — see vbin.vresume."""
import sys
from .vbin import vresume

if __name__ == "__main__":
    sys.exit(vresume())
