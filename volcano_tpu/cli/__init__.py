"""CLI (reference: pkg/cli + cmd/cli)."""

from .loader import job_from_dict, job_from_yaml
from .vcctl import VcctlError, main

__all__ = ["job_from_dict", "job_from_yaml", "VcctlError", "main"]
