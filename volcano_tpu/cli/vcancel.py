"""python -m volcano_tpu.cli.vcancel — see vbin.vcancel."""
import sys
from .vbin import vcancel

if __name__ == "__main__":
    sys.exit(vcancel())
