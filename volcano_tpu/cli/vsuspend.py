"""python -m volcano_tpu.cli.vsuspend — see vbin.vsuspend."""
import sys
from .vbin import vsuspend

if __name__ == "__main__":
    sys.exit(vsuspend())
