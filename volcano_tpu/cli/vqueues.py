"""python -m volcano_tpu.cli.vqueues — see vbin.vqueues."""
import sys
from .vbin import vqueues

if __name__ == "__main__":
    sys.exit(vqueues())
