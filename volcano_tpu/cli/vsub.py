"""python -m volcano_tpu.cli.vsub — see vbin.vsub."""
import sys
from .vbin import vsub

if __name__ == "__main__":
    sys.exit(vsub())
