"""vcctl — the CLI surface.

Reference: pkg/cli/{job,queue}/ + cmd/cli (cobra commands ``vcctl job
run/list/view/suspend/resume/delete`` and ``vcctl queue
create/delete/operate/list/get``, cmd/cli/job.go:11-73,
cmd/cli/queue.go:27-79). suspend/resume create bus Command objects exactly
like the reference (pkg/cli/job/{suspend,resume}.go).

Run against a live in-process VolcanoSystem (tests) or a pickled state file
(standalone: ``python -m volcano_tpu.cli.vcctl --state /tmp/vc.pkl job list``).
"""

from __future__ import annotations

import argparse
import pickle
import sys
import time
from typing import List, Optional

from ..api.batch import Command
from ..api.queue_info import QueueInfo
from ..api.types import BusAction, QueueState
from .loader import job_from_yaml


def _fmt_table(rows: List[List[str]], headers: List[str]) -> str:
    widths = [max(len(str(r[i])) for r in [headers] + rows)
              for i in range(len(headers))]
    lines = ["  ".join(str(h).ljust(w) for h, w in zip(headers, widths))]
    for r in rows:
        lines.append("  ".join(str(c).ljust(w) for c, w in zip(r, widths)))
    return "\n".join(lines)


class VcctlError(Exception):
    pass


def cmd_job_run(system, args) -> str:
    with open(args.filename) as f:
        job = job_from_yaml(f.read())
    if args.queue:
        job.queue = args.queue
    system.submit_job(job)
    return f"run job {job.namespace}/{job.name} successfully"


def cmd_job_list(system, args) -> str:
    rows = []
    for job in system.api.list("jobs"):
        if args.namespace and job.namespace != args.namespace:
            continue
        s = job.status
        rows.append([job.name, s.state.phase.value, str(job.min_available),
                     str(s.pending), str(s.running), str(s.succeeded),
                     str(s.failed), str(s.retry_count)])
    return _fmt_table(rows, ["Name", "Phase", "MinAvailable", "Pending",
                             "Running", "Succeeded", "Failed", "RetryCount"])


def cmd_job_view(system, args) -> str:
    job = system.api.get("jobs", f"{args.namespace}/{args.name}")
    if job is None:
        raise VcctlError(f"job {args.namespace}/{args.name} not found")
    lines = [f"Name:        {job.name}",
             f"Namespace:   {job.namespace}",
             f"Queue:       {job.queue}",
             f"Phase:       {job.status.state.phase.value}",
             f"MinAvailable: {job.min_available}",
             f"RetryCount:  {job.status.retry_count}",
             "Tasks:"]
    for t in job.tasks:
        lines.append(f"  - {t.name}: replicas={t.replicas}")
    pods = system.api.pods_of_job(job.key)
    if pods:
        lines.append("Pods:")
        for p in sorted(pods, key=lambda p: p.name):
            lines.append(f"  - {p.name}: {p.phase} node={p.node_name or '-'}")
    return "\n".join(lines)


def _check_job(system, args) -> None:
    if system.api.get("jobs", f"{args.namespace}/{args.name}") is None:
        raise VcctlError(f"job {args.namespace}/{args.name} not found")


def cmd_job_suspend(system, args) -> str:
    _check_job(system, args)
    system.suspend_job(args.name, args.namespace)
    return f"AbortJob job {args.namespace}/{args.name}"


def cmd_job_resume(system, args) -> str:
    _check_job(system, args)
    system.resume_job(args.name, args.namespace)
    return f"ResumeJob job {args.namespace}/{args.name}"


def cmd_job_delete(system, args) -> str:
    if system.api.delete("jobs", f"{args.namespace}/{args.name}") is None:
        raise VcctlError(f"job {args.namespace}/{args.name} not found")
    return f"delete job {args.namespace}/{args.name} successfully"


def cmd_queue_create(system, args) -> str:
    queue = QueueInfo(args.name, weight=args.weight,
                      reclaimable=not args.no_reclaimable)
    system.api.create("queues", queue)
    return f"create queue {args.name} successfully"


def cmd_queue_list(system, args) -> str:
    rows = []
    for q in system.api.list("queues"):
        rows.append([q.name, str(q.weight), q.state.value,
                     str(q.reclaimable)])
    return _fmt_table(rows, ["Name", "Weight", "State", "Reclaimable"])


def cmd_queue_get(system, args) -> str:
    q = system.api.get("queues", args.name)
    if q is None:
        raise VcctlError(f"queue {args.name} not found")
    counts = {k.replace("status.", ""): v for k, v in q.annotations.items()
              if k.startswith("status.")}
    return (f"Name: {q.name}\nWeight: {q.weight}\nState: {q.state.value}\n"
            f"Reclaimable: {q.reclaimable}\nPodGroups: {counts}")


def cmd_queue_operate(system, args) -> str:
    """vcctl queue operate --action open|close (bus Command path,
    SURVEY.md section 3.5)."""
    action = {"open": BusAction.OPEN_QUEUE,
              "close": BusAction.CLOSE_QUEUE}.get(args.action)
    if action is None:
        raise VcctlError(f"invalid action {args.action!r}; use open|close")
    if system.api.get("queues", args.name) is None:
        raise VcctlError(f"queue {args.name} not found")
    system.submit_command(Command(
        name=f"{args.action}-{args.name}-{time.time()}",
        action=action, target_name=args.name, target_kind="Queue"))
    return f"{args.action} queue {args.name}"


def cmd_queue_delete(system, args) -> str:
    if system.api.get("queues", args.name) is None:
        raise VcctlError(f"queue {args.name} not found")
    system.api.delete("queues", args.name)
    return f"delete queue {args.name} successfully"


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="vcctl",
                                description="volcano_tpu batch CLI")
    p.add_argument("--state", help="pickled VolcanoSystem state file")
    sub = p.add_subparsers(dest="group", required=True)

    job = sub.add_parser("job").add_subparsers(dest="cmd", required=True)
    run = job.add_parser("run")
    run.add_argument("-f", "--filename", required=True)
    run.add_argument("-q", "--queue", default="")
    run.set_defaults(fn=cmd_job_run)
    ls = job.add_parser("list")
    ls.add_argument("-n", "--namespace", default="")
    ls.set_defaults(fn=cmd_job_list)
    for name, fn in (("view", cmd_job_view), ("suspend", cmd_job_suspend),
                     ("resume", cmd_job_resume), ("delete", cmd_job_delete)):
        sp = job.add_parser(name)
        sp.add_argument("-N", "--name", required=True)
        sp.add_argument("-n", "--namespace", default="default")
        sp.set_defaults(fn=fn)

    queue = sub.add_parser("queue").add_subparsers(dest="cmd", required=True)
    qc = queue.add_parser("create")
    qc.add_argument("-N", "--name", required=True)
    qc.add_argument("-w", "--weight", type=int, default=1)
    qc.add_argument("--no-reclaimable", action="store_true")
    qc.set_defaults(fn=cmd_queue_create)
    queue.add_parser("list").set_defaults(fn=cmd_queue_list)
    qg = queue.add_parser("get")
    qg.add_argument("-N", "--name", required=True)
    qg.set_defaults(fn=cmd_queue_get)
    qo = queue.add_parser("operate")
    qo.add_argument("-N", "--name", required=True)
    qo.add_argument("-a", "--action", required=True)
    qo.set_defaults(fn=cmd_queue_operate)
    qd = queue.add_parser("delete")
    qd.add_argument("-N", "--name", required=True)
    qd.set_defaults(fn=cmd_queue_delete)

    # version stamp (cmd/cli/vcctl version, pkg/version analog);
    # dispatched by main()'s stateless early return
    sub.add_parser("version")
    return p


def main(argv: Optional[List[str]] = None, system=None) -> str:
    args = build_parser().parse_args(argv)
    if args.group == "version":     # stateless: no system needed
        from ..version import version_string
        return version_string()
    persist = False
    if system is None:
        if not args.state:
            raise VcctlError("--state required when no in-process system")
        try:
            with open(args.state, "rb") as f:
                system = pickle.load(f)
        except FileNotFoundError:
            from ..runtime.system import VolcanoSystem
            system = VolcanoSystem()
        persist = True
    out = args.fn(system, args)
    if persist:
        # standalone mode: drive a full control-plane step so submitted work
        # makes progress between invocations (reconcile + schedule + kubelet)
        if system.api.stores["nodes"]:
            system.tick()
        else:
            system.reconcile()
        with open(args.state, "wb") as f:
            pickle.dump(system, f)
    return out


if __name__ == "__main__":
    from ..webhooks import AdmissionError
    try:
        print(main())
    except (VcctlError, AdmissionError) as e:
        print(f"error: {e}", file=sys.stderr)
        sys.exit(1)
