"""Enqueue action (reference: pkg/scheduler/actions/enqueue/enqueue.go:43-102)."""

from __future__ import annotations

from .base import Action


class EnqueueAction(Action):
    name = "enqueue"

    def execute(self, ssn) -> None:
        ssn.stats["enqueued"] = ssn.run_enqueue()
