"""Reserve action: lock nodes for the elected target job.

Reference: pkg/scheduler/actions/reserve/reserve.go:43-77 — while the target
job stays unready, lock one more node per cycle (the emptiest unlocked one,
reservation.go:56-63); locked nodes reject every other job in the allocate
kernel via AllocateExtras.node_locked.
"""

from __future__ import annotations

from .base import Action


class ReserveAction(Action):
    name = "reserve"

    def execute(self, ssn) -> None:
        plugin = ssn.plugin("reservation")
        if plugin is None or plugin.state.target_job_uid is None:
            return
        job = ssn.cluster.jobs.get(plugin.state.target_job_uid)
        if job is None or job.is_ready():
            plugin.state.reset()
            return
        node = plugin.reserve_node(ssn)
        if node is not None:
            plugin.state.locked_nodes.add(node)
        # per-cycle effect attribution: the node locked THIS cycle and the
        # running lock total, for the flight ring / scenario scorecards
        ssn.last_telemetry.setdefault("actions", {})["reserve"] = {
            "locked_node": node,
            "locked_total": len(plugin.state.locked_nodes)}
