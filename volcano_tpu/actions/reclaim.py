"""Reclaim action (reference: pkg/scheduler/actions/reclaim/reclaim.go:40-191):
cross-queue eviction of reclaimable, over-served queues' tasks in favor of
starving jobs in underserved queues."""

from __future__ import annotations

import numpy as np

from .base import Action


class ReclaimAction(Action):
    name = "reclaim"

    def execute(self, ssn) -> None:
        result = ssn.run_preempt(mode="reclaim")
        ssn.stats["reclaim_evictions"] = int(
            np.asarray(result.evicted).sum()) if result is not None else 0
