"""Reclaim action (reference: pkg/scheduler/actions/reclaim/reclaim.go:40-191):
cross-queue eviction of reclaimable, over-served queues' tasks in favor of
starving jobs in underserved queues."""

from __future__ import annotations

import numpy as np

from .base import Action


class ReclaimAction(Action):
    name = "reclaim"

    def execute(self, ssn) -> None:
        result = ssn.run_preempt(mode="reclaim")
        evicted = int(np.asarray(result.evicted).sum()) \
            if result is not None else 0
        ssn.stats["reclaim_evictions"] = evicted
        # per-cycle effect attribution for the flight ring / scenario
        # scorecards: WHICH tasks this action evicted, not just how many
        victims = []
        if result is not None and evicted:
            uids = ssn.maps.task_uids
            for ti in np.nonzero(np.asarray(result.evicted))[0]:
                victims.append(uids[int(ti)])
        ssn.last_telemetry.setdefault("actions", {})["reclaim"] = {
            "evictions": evicted, "victims": sorted(victims)}
