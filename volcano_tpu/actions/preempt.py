"""Preempt action (reference: pkg/scheduler/actions/preempt/preempt.go:42-291).

Runs the compiled intra-queue preemption pass, applies evictions and
pipelined placements, then performs the victimTasks sweep (tdm's periodic
eviction of preemptable tasks outside their revocable window,
preempt.go:280-291).
"""

from __future__ import annotations

import numpy as np

from .base import Action


class PreemptAction(Action):
    name = "preempt"

    def execute(self, ssn) -> None:
        result = ssn.run_preempt(mode="preempt")
        ssn.stats["preempt_evictions"] = int(
            np.asarray(result.evicted).sum()) if result is not None else 0

        # phase 2: preemption between tasks within a job
        # (preempt.go:145-186), committed per preemptor task
        intra = ssn.run_preempt(mode="preempt_intra")
        ssn.stats["preempt_intra_evictions"] = int(
            np.asarray(intra.evicted).sum()) if intra is not None else 0

        # victimTasks sweep: unconditional evictions requested by plugins
        victims = ssn.victim_tasks_mask()
        count = 0
        for uid, ti in ssn.maps.task_index.items():
            if victims[ti]:
                ssn.evict_task(uid, reason="tdm revocable window closed")
                count += 1
        ssn.stats["victim_sweep"] = count
