"""Elect action: choose the reservation target job.

Reference: pkg/scheduler/actions/elect/elect.go:29-50 — the highest-priority,
longest-waiting pending job becomes the reservation target via the
reservation plugin's TargetJobFn.
"""

from __future__ import annotations

from .base import Action


class ElectAction(Action):
    name = "elect"

    def execute(self, ssn) -> None:
        plugin = ssn.plugin("reservation")
        if plugin is None:
            return
        state = plugin.state
        if state.target_job_uid:
            job = ssn.cluster.jobs.get(state.target_job_uid)
            if job is None or job.is_ready():
                # target scheduled or deleted: release everything
                state.reset()
        if state.target_job_uid is None:
            state.target_job_uid = plugin.elect_target(ssn)
        # per-cycle effect attribution: the elected target (held or fresh)
        # for the flight ring / scenario scorecards
        ssn.last_telemetry.setdefault("actions", {})["elect"] = {
            "elected_job": state.target_job_uid}
