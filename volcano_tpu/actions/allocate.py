"""Allocate action (reference: pkg/scheduler/actions/allocate/allocate.go:43-281).

The whole pass — ordering, predicates, scoring, placement, gang
commit/discard — is the compiled kernel in ops/allocate_scan.py; this driver
just runs it and reads out decisions.
"""

from __future__ import annotations

import numpy as np

from .base import Action


class AllocateAction(Action):
    name = "allocate"

    def execute(self, ssn) -> None:
        result = ssn.run_allocate()
        ssn.stats["allocated_binds"] = int(
            sum(1 for _ in ssn.binds))
        ssn.stats["jobs_ready"] = int(np.asarray(result.job_ready).sum())
        ssn.stats["jobs_pipelined"] = int(
            np.asarray(result.job_pipelined).sum())
