"""Backfill action (reference: pkg/scheduler/actions/backfill/backfill.go:40-93)."""

from __future__ import annotations

from .base import Action


class BackfillAction(Action):
    name = "backfill"

    def execute(self, ssn) -> None:
        ssn.stats["backfilled"] = ssn.run_backfill()
