"""Action interface (reference: framework.Action, pkg/scheduler/framework/
interface.go:20-33)."""

from __future__ import annotations

from typing import TYPE_CHECKING

if TYPE_CHECKING:  # pragma: no cover
    from ..framework.session import Session


class Action:
    name: str = ""

    def execute(self, ssn: "Session") -> None:
        raise NotImplementedError
