"""The action pass pipeline (reference: pkg/scheduler/actions/factory.go:31-39).

Each action is a thin host-side driver around a compiled pass; the Session
holds the state they mutate. Execution order comes from the conf's
``actions`` string, exactly like the reference scheduler loop
(pkg/scheduler/scheduler.go:105).
"""

from __future__ import annotations

from typing import Dict, Type

from .allocate import AllocateAction
from .backfill import BackfillAction
from .base import Action
from .elect import ElectAction
from .enqueue import EnqueueAction
from .preempt import PreemptAction
from .reclaim import ReclaimAction
from .reserve import ReserveAction

_ACTIONS: Dict[str, Type[Action]] = {}


def register_action(cls: Type[Action]) -> None:
    """Reference: framework.RegisterAction (framework/plugins.go:107)."""
    _ACTIONS[cls.name] = cls


def get_action(name: str) -> Action:
    if name not in _ACTIONS:
        raise KeyError(f"unknown action {name!r}; registered: {sorted(_ACTIONS)}")
    return _ACTIONS[name]()


def registered_actions():
    return sorted(_ACTIONS)


for _cls in (EnqueueAction, AllocateAction, BackfillAction, PreemptAction,
             ReclaimAction, ElectAction, ReserveAction):
    register_action(_cls)
