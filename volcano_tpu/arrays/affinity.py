"""Inter-pod affinity tensor encoding — the host-side half of the
InterPodAffinity predicate and batch scorer.

The reference wraps the k8s InterPodAffinity plugin for both filtering
(pkg/scheduler/plugins/predicates/predicates.go:196-200, dispatch 261-273)
and batch node scoring (pkg/scheduler/plugins/nodeorder/nodeorder.go:273-306).
Those are pointer-chasing pod-list walks; the TPU re-design encodes the same
semantics as dense tensors (SURVEY.md section 7 hard part 3):

- a *topology domain* is a (topology_key, node label value) pair; every node
  maps to at most one domain per key (``node_domain[TK, N]``);
- every distinct term selector becomes a row of a host-evaluated match
  matrix ``task_match[SEL, T]`` (full k8s selector semantics — expressions,
  namespaces — run in Python once per cycle, so the kernel only does
  integer gathers);
- cluster state becomes *counts*: ``cnt0[SEL, DM]`` = matching pods per
  domain, ``anti_cnt0[ETA, DM]`` = placed pods carrying a given required
  anti-affinity term per domain. The allocate kernel carries both as scan
  state so in-cycle placements constrain later tasks exactly like the
  reference's event-handler-updated pod lister (predicates.go:116-160),
  and gang discard rolls them back.

Scoring: preferred terms of the incoming task are dynamic (count gathers
against the live ``cnt`` state); preferred terms of existing pods toward
the incoming task are folded into the static ``static_pref[SEL, DM]`` map.
In-cycle placements therefore do not update the symmetric half — a
documented divergence (the reference recomputes it per session only too).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Tuple

import jax
import numpy as np

from ..api import ClusterInfo, PodAffinityTerm
from .schema import IndexMaps, _register, bucket


@_register
@dataclass
class AffinityArrays:
    """Device-side inter-pod affinity encoding. Axis legend: TK topology
    keys, DM domains, SEL selectors, ETA required anti-affinity terms,
    A/B/PP per-task term slots."""

    node_domain: jax.Array    # i32[TK, N] domain id of node per key, -1 none
    domain_key: jax.Array     # i32[DM] key index of each domain, -1 pad
    task_match: jax.Array     # bool[SEL, T] selector matches task's labels
    cnt0: jax.Array           # f32[SEL, DM] snapshot matching-pod counts
    task_aff_sel: jax.Array   # i32[T, A] required affinity selector, -1 pad
    task_aff_key: jax.Array   # i32[T, A] required affinity topo key
    task_anti_term: jax.Array  # i32[T, B] own required anti term (eta), -1 pad
    eta_sel: jax.Array        # i32[ETA] anti term selector, -1 pad
    eta_key: jax.Array        # i32[ETA] anti term topo key
    anti_cnt0: jax.Array      # f32[ETA, DM] snapshot pods carrying term
    task_pref_sel: jax.Array  # i32[T, PP] preferred term selector, -1 pad
    task_pref_key: jax.Array  # i32[T, PP]
    task_pref_w: jax.Array    # f32[T, PP] term weight (negative = anti)
    static_pref: jax.Array    # f32[SEL, DM] symmetric preferred score map

    @property
    def has_terms(self) -> bool:
        """Whether any task carries any term (host-side, pre-trace)."""
        return bool(
            np.any(np.asarray(self.task_aff_sel) >= 0)
            or np.any(np.asarray(self.task_anti_term) >= 0)
            or np.any(np.asarray(self.eta_sel) >= 0)
            or np.any(np.asarray(self.task_pref_sel) >= 0))

    @classmethod
    def neutral(cls, n_nodes: int, n_tasks: int) -> "AffinityArrays":
        i32, f32 = np.int32, np.float32
        return cls(
            node_domain=np.full((1, n_nodes), -1, i32),
            domain_key=np.full(1, -1, i32),
            task_match=np.zeros((1, n_tasks), bool),
            cnt0=np.zeros((1, 1), f32),
            task_aff_sel=np.full((n_tasks, 1), -1, i32),
            task_aff_key=np.full((n_tasks, 1), -1, i32),
            task_anti_term=np.full((n_tasks, 1), -1, i32),
            eta_sel=np.full(1, -1, i32),
            eta_key=np.full(1, -1, i32),
            anti_cnt0=np.zeros((1, 1), f32),
            task_pref_sel=np.full((n_tasks, 1), -1, i32),
            task_pref_key=np.full((n_tasks, 1), -1, i32),
            task_pref_w=np.zeros((n_tasks, 1), f32),
            static_pref=np.zeros((1, 1), f32),
        )


def _canon_term(term: PodAffinityTerm, own_ns: str) -> Tuple:
    """Canonical selector identity: labels + expressions + resolved
    namespace set (terms with no namespaces match the task's own)."""
    ns = tuple(sorted(term.namespaces)) if term.namespaces else (own_ns,)
    return (
        tuple(sorted(term.match_labels.items())),
        tuple((k, op, tuple(sorted(v)) if isinstance(v, (list, tuple)) else (v,))
              for k, op, v in term.match_expressions),
        ns,
    )


def build_affinity(ci: ClusterInfo, maps: IndexMaps,
                   n_nodes: int, n_tasks: int) -> AffinityArrays:
    """Encode every task's inter-pod (anti-)affinity terms for the cycle.

    ``n_nodes``/``n_tasks`` are the bucketed axis sizes of the packed
    snapshot (arrays/pack.py) so the tensors align with it.
    """
    tasks = []          # (task index, TaskInfo) in packed order
    for job in ci.jobs.values():
        for uid, t in job.tasks.items():
            ti = maps.task_index.get(uid)
            if ti is not None:
                tasks.append((ti, t))
    has_any = any(
        t.pod_affinity or t.pod_anti_affinity or t.pod_affinity_preferred
        or t.pod_anti_affinity_preferred for _, t in tasks)
    if not has_any:
        return AffinityArrays.neutral(n_nodes, n_tasks)

    # ---- term tables -----------------------------------------------------
    sel_index: Dict[Tuple, int] = {}
    sel_terms: List[Tuple[PodAffinityTerm, str]] = []  # (term, own_ns)
    key_index: Dict[str, int] = {}

    def sel_id(term: PodAffinityTerm, own_ns: str) -> int:
        c = _canon_term(term, own_ns)
        if c not in sel_index:
            sel_index[c] = len(sel_terms)
            sel_terms.append((term, own_ns))
        return sel_index[c]

    def key_id(k: str) -> int:
        if k not in key_index:
            key_index[k] = len(key_index)
        return key_index[k]

    eta_index: Dict[Tuple[int, int], int] = {}   # (sel, key) -> eta

    def eta_id(s: int, k: int) -> int:
        if (s, k) not in eta_index:
            eta_index[(s, k)] = len(eta_index)
        return eta_index[(s, k)]

    per_task_aff: Dict[int, List[Tuple[int, int]]] = {}
    per_task_anti: Dict[int, List[int]] = {}
    per_task_pref: Dict[int, List[Tuple[int, int, float]]] = {}
    for ti, t in tasks:
        for term in t.pod_affinity:
            per_task_aff.setdefault(ti, []).append(
                (sel_id(term, t.namespace), key_id(term.topology_key)))
        for term in t.pod_anti_affinity:
            per_task_anti.setdefault(ti, []).append(
                eta_id(sel_id(term, t.namespace), key_id(term.topology_key)))
        for term in t.pod_affinity_preferred:
            per_task_pref.setdefault(ti, []).append(
                (sel_id(term, t.namespace), key_id(term.topology_key),
                 float(term.weight or 1)))
        for term in t.pod_anti_affinity_preferred:
            per_task_pref.setdefault(ti, []).append(
                (sel_id(term, t.namespace), key_id(term.topology_key),
                 -float(term.weight or 1)))

    # ---- domains ---------------------------------------------------------
    TK = bucket(max(len(key_index), 1), 1)
    dom_index: Dict[Tuple[int, str], int] = {}
    node_domain = np.full((TK, n_nodes), -1, np.int32)
    for name, ni in maps.node_index.items():
        node = ci.nodes[name]
        for k, ki in key_index.items():
            v = node.labels.get(k)
            if v is None:
                continue
            d = dom_index.setdefault((ki, v), len(dom_index))
            node_domain[ki, ni] = d
    DM = bucket(max(len(dom_index), 1), 1)
    domain_key = np.full(DM, -1, np.int32)
    for (ki, _v), d in dom_index.items():
        domain_key[d] = ki

    # ---- match matrix + snapshot counts ----------------------------------
    SEL = bucket(max(len(sel_terms), 1), 1)
    task_match = np.zeros((SEL, n_tasks), bool)
    for s, (term, own_ns) in enumerate(sel_terms):
        for ti, t in tasks:
            task_match[s, ti] = term.matches(t.labels, t.namespace, own_ns)

    cnt0 = np.zeros((SEL, DM), np.float32)
    ETA = bucket(max(len(eta_index), 1), 1)
    eta_sel = np.full(ETA, -1, np.int32)
    eta_key = np.full(ETA, -1, np.int32)
    for (s, k), e in eta_index.items():
        eta_sel[e] = s
        eta_key[e] = k
    anti_cnt0 = np.zeros((ETA, DM), np.float32)
    static_pref = np.zeros((SEL, DM), np.float32)

    for ti, t in tasks:
        ni = maps.node_index.get(t.node_name, -1)
        if ni < 0:
            continue
        # a placed pod counts toward every selector it matches, in its
        # domain under every topology key
        for s in range(len(sel_terms)):
            if not task_match[s, ti]:
                continue
            for ki in key_index.values():
                d = node_domain[ki, ni]
                if d >= 0:
                    cnt0[s, d] += 1.0
        # a placed pod's own required anti-affinity terms constrain
        # incoming pods matching them (symmetric anti-affinity)
        for e in per_task_anti.get(ti, ()):
            d = node_domain[eta_key[e], ni]
            if d >= 0:
                anti_cnt0[e, d] += 1.0
        # a placed pod's preferred terms score incoming pods matching them
        # (symmetric preferred, static over the cycle)
        for s, ki, w in per_task_pref.get(ti, ()):
            d = node_domain[ki, ni]
            if d >= 0:
                static_pref[s, d] += w

    # ---- per-task slot tables --------------------------------------------
    A = bucket(max(max((len(v) for v in per_task_aff.values()), default=0), 1), 1)
    B = bucket(max(max((len(v) for v in per_task_anti.values()), default=0), 1), 1)
    PP = bucket(max(max((len(v) for v in per_task_pref.values()), default=0), 1), 1)
    task_aff_sel = np.full((n_tasks, A), -1, np.int32)
    task_aff_key = np.full((n_tasks, A), -1, np.int32)
    task_anti_term = np.full((n_tasks, B), -1, np.int32)
    task_pref_sel = np.full((n_tasks, PP), -1, np.int32)
    task_pref_key = np.full((n_tasks, PP), -1, np.int32)
    task_pref_w = np.zeros((n_tasks, PP), np.float32)
    for ti, rows in per_task_aff.items():
        for a, (s, k) in enumerate(rows):
            task_aff_sel[ti, a] = s
            task_aff_key[ti, a] = k
    for ti, rows in per_task_anti.items():
        for b, e in enumerate(rows):
            task_anti_term[ti, b] = e
    for ti, rows in per_task_pref.items():
        for p, (s, k, w) in enumerate(rows):
            task_pref_sel[ti, p] = s
            task_pref_key[ti, p] = k
            task_pref_w[ti, p] = w

    return AffinityArrays(
        node_domain=node_domain, domain_key=domain_key,
        task_match=task_match, cnt0=cnt0,
        task_aff_sel=task_aff_sel, task_aff_key=task_aff_key,
        task_anti_term=task_anti_term, eta_sel=eta_sel, eta_key=eta_key,
        anti_cnt0=anti_cnt0, task_pref_sel=task_pref_sel,
        task_pref_key=task_pref_key, task_pref_w=task_pref_w,
        static_pref=static_pref)
