"""Inter-pod affinity encoding — node-space, gather-free.

The array program of the k8s InterPodAffinity plugin the reference wraps
(pkg/scheduler/plugins/predicates/predicates.go:196-200 filter dispatch,
261-273; pkg/scheduler/plugins/nodeorder/nodeorder.go:273-306 batch
scorer). Terms select existing pods by label selector and constrain
placement relative to the topology DOMAIN (nodes sharing a label value)
those pods occupy.

Encoding design (TPU-first): all live state is DENORMALIZED to the node
axis. Counts live as ``cnt[SK, N]`` — "matching pods within node n's
domain" — rather than per-domain cells, so the hot path is pure vector
compares/adds over [.., N] rows with NO per-element gathers (TPU gathers
serialize to ~1 element/cycle and dominated the per-task cost in the
domain-indexed encoding). A placement update adds a domain-membership
mask row (``sk_domain == sk_domain[:, node]``) instead of scattering into
a domain cell. SK indexes the distinct (selector, topology-key) pairs the
terms actually use; column N of ``cnt`` carries the cluster-wide matching
count on keyed nodes (the k8s first-pod-escape test).

The incoming pod's PREFERRED terms read the same live counts; symmetric
preferred contributions of already-placed pods toward the incoming task
are folded into the static ``static_pref[SEL, N]`` map. In-cycle
placements therefore do not update the symmetric half — a documented
divergence (the reference recomputes it per session only too).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Tuple

import jax
import numpy as np

from ..api import ClusterInfo, PodAffinityTerm
from .schema import IndexMaps, _register, bucket


@_register
@dataclass
class AffinityArrays:
    """Device-side inter-pod affinity encoding. Axis legend: SK distinct
    (selector, topology-key) pairs, SEL selectors, ETA required
    anti-affinity terms, A/B/PP per-task term slots, N nodes."""

    sk_sel: jax.Array         # i32[SK] selector of each pair, -1 pad
    sk_domain: jax.Array      # i32[SK, N] node's domain id under the
    #                           pair's key, -1 = node lacks the key
    cnt0: jax.Array           # f32[SK, N+1] snapshot matching-pod counts in
    #                           node n's domain; column N = cluster total on
    #                           keyed nodes (first-pod escape)
    task_match: jax.Array     # bool[SEL, T] selector matches task's labels
    task_aff_sk: jax.Array    # i32[T, A] required affinity pair, -1 pad
    task_anti_term: jax.Array  # i32[T, B] own required anti term (eta), -1
    eta_sel: jax.Array        # i32[ETA] anti term selector, -1 pad
    eta_sk: jax.Array         # i32[ETA] anti term (sel,key) pair id
    eta_domain: jax.Array     # i32[ETA, N] node's domain under the term's key
    anti_cnt0: jax.Array      # f32[ETA, N] pods carrying the term in node
    #                           n's domain
    task_pref_sk: jax.Array   # i32[T, PP] preferred term pair, -1 pad
    task_pref_w: jax.Array    # f32[T, PP] term weight (negative = anti)
    static_pref: jax.Array    # f32[SEL, N] symmetric preferred score map

    @property
    def has_terms(self) -> bool:
        """Whether any task carries any term (host-side, pre-trace)."""
        return bool(
            np.any(np.asarray(self.task_aff_sk) >= 0)
            or np.any(np.asarray(self.task_anti_term) >= 0)
            or np.any(np.asarray(self.eta_sel) >= 0)
            or np.any(np.asarray(self.task_pref_sk) >= 0))

    @classmethod
    def neutral(cls, n_nodes: int, n_tasks: int) -> "AffinityArrays":
        i32, f32 = np.int32, np.float32
        return cls(
            sk_sel=np.full(1, -1, i32),
            sk_domain=np.full((1, n_nodes), -1, i32),
            cnt0=np.zeros((1, n_nodes + 1), f32),
            task_match=np.zeros((1, n_tasks), bool),
            task_aff_sk=np.full((n_tasks, 1), -1, i32),
            task_anti_term=np.full((n_tasks, 1), -1, i32),
            eta_sel=np.full(1, -1, i32),
            eta_sk=np.full(1, -1, i32),
            eta_domain=np.full((1, n_nodes), -1, i32),
            anti_cnt0=np.zeros((1, n_nodes), f32),
            task_pref_sk=np.full((n_tasks, 1), -1, i32),
            task_pref_w=np.zeros((n_tasks, 1), f32),
            static_pref=np.zeros((1, n_nodes), f32),
        )


def _canon_term(term: PodAffinityTerm, own_ns: str) -> Tuple:
    """Canonical selector identity: labels + expressions + resolved
    namespace set (terms with no namespaces match the task's own)."""
    ns = tuple(sorted(term.namespaces)) if term.namespaces else (own_ns,)
    return (
        tuple(sorted(term.match_labels.items())),
        tuple((k, op, tuple(sorted(v)) if isinstance(v, (list, tuple)) else (v,))
              for k, op, v in term.match_expressions),
        ns,
    )


def build_affinity(ci: ClusterInfo, maps: IndexMaps,
                   n_nodes: int, n_tasks: int) -> AffinityArrays:
    """Encode every task's inter-pod (anti-)affinity terms for the cycle.

    ``n_nodes``/``n_tasks`` are the bucketed axis sizes of the packed
    snapshot (arrays/pack.py) so the tensors align with it.
    """
    # cheap term scan first: the overwhelmingly common no-terms snapshot
    # must not pay the indexed task-list build (it showed up in the 1 s
    # cycle budget at 100k tasks)
    import operator
    terms_of = operator.attrgetter(
        "pod_affinity", "pod_anti_affinity", "pod_affinity_preferred",
        "pod_anti_affinity_preferred")
    has_any = False
    for job in ci.jobs.values():
        for t in job.tasks.values():
            a, b, c, d = terms_of(t)
            if a or b or c or d:
                has_any = True
                break
        if has_any:
            break
    if not has_any:
        return AffinityArrays.neutral(n_nodes, n_tasks)
    tasks = []          # (task index, TaskInfo) in packed order
    for job in ci.jobs.values():
        for uid, t in job.tasks.items():
            ti = maps.task_index.get(uid)
            if ti is not None:
                tasks.append((ti, t))

    # ---- term tables -----------------------------------------------------
    sel_index: Dict[Tuple, int] = {}
    sel_terms: List[Tuple[PodAffinityTerm, str]] = []  # (term, own_ns)

    def sel_id(term: PodAffinityTerm, own_ns: str) -> int:
        c = _canon_term(term, own_ns)
        if c not in sel_index:
            sel_index[c] = len(sel_terms)
            sel_terms.append((term, own_ns))
        return sel_index[c]

    sk_index: Dict[Tuple[int, str], int] = {}    # (sel, key) -> sk

    def sk_id(s: int, key: str) -> int:
        if (s, key) not in sk_index:
            sk_index[(s, key)] = len(sk_index)
        return sk_index[(s, key)]

    eta_index: Dict[Tuple[int, str], int] = {}   # (sel, key) -> eta

    def eta_id(s: int, key: str) -> int:
        if (s, key) not in eta_index:
            eta_index[(s, key)] = len(eta_index)
        return eta_index[(s, key)]

    per_task_aff: Dict[int, List[int]] = {}
    per_task_anti: Dict[int, List[int]] = {}
    per_task_pref: Dict[int, List[Tuple[int, float]]] = {}
    for ti, t in tasks:
        for term in t.pod_affinity:
            per_task_aff.setdefault(ti, []).append(
                sk_id(sel_id(term, t.namespace), term.topology_key))
        for term in t.pod_anti_affinity:
            s = sel_id(term, t.namespace)
            sk_id(s, term.topology_key)      # own-anti reads live counts too
            per_task_anti.setdefault(ti, []).append(
                eta_id(s, term.topology_key))
        for term in t.pod_affinity_preferred:
            per_task_pref.setdefault(ti, []).append(
                (sk_id(sel_id(term, t.namespace), term.topology_key),
                 float(term.weight or 1)))
        for term in t.pod_anti_affinity_preferred:
            per_task_pref.setdefault(ti, []).append(
                (sk_id(sel_id(term, t.namespace), term.topology_key),
                 -float(term.weight or 1)))

    # ---- per-key node domains (host-side only) ---------------------------
    keys = sorted({k for (_s, k) in sk_index} | {k for (_s, k) in eta_index}
                  | {t.topology_key
                     for _ti, task in tasks
                     for t in (task.pod_affinity_preferred
                               + task.pod_anti_affinity_preferred
                               + task.pod_affinity + task.pod_anti_affinity)})
    dom_of_key: Dict[str, np.ndarray] = {}
    for k in keys:
        vals: Dict[str, int] = {}
        row = np.full(n_nodes, -1, np.int32)
        for name, ni in maps.node_index.items():
            v = ci.nodes[name].labels.get(k)
            if v is not None:
                row[ni] = vals.setdefault(v, len(vals))
        dom_of_key[k] = row

    # ---- match matrix ----------------------------------------------------
    SEL = bucket(max(len(sel_terms), 1), 1)
    task_match = np.zeros((SEL, n_tasks), bool)
    for s, (term, own_ns) in enumerate(sel_terms):
        for ti, t in tasks:
            task_match[s, ti] = term.matches(t.labels, t.namespace, own_ns)

    # ---- node-space snapshot counts --------------------------------------
    SK = bucket(max(len(sk_index), 1), 1)
    sk_sel = np.full(SK, -1, np.int32)
    sk_domain = np.full((SK, n_nodes), -1, np.int32)
    cnt0 = np.zeros((SK, n_nodes + 1), np.float32)
    # per-selector placed-pod node lists (existing pods on nodes)
    placed_nodes: Dict[int, List[int]] = {}
    for ti, t in tasks:
        ni = maps.node_index.get(t.node_name, -1)
        if ni < 0:
            continue
        for s in range(len(sel_terms)):
            if task_match[s, ti]:
                placed_nodes.setdefault(s, []).append(ni)
    for (s, key), p in sk_index.items():
        sk_sel[p] = s
        dom = dom_of_key[key]
        sk_domain[p] = dom
        for ni in placed_nodes.get(s, ()):
            d = dom[ni]
            if d >= 0:
                cnt0[p, :n_nodes][dom == d] += 1.0
                cnt0[p, n_nodes] += 1.0

    ETA = bucket(max(len(eta_index), 1), 1)
    eta_sel = np.full(ETA, -1, np.int32)
    eta_sk = np.full(ETA, -1, np.int32)
    eta_domain = np.full((ETA, n_nodes), -1, np.int32)
    anti_cnt0 = np.zeros((ETA, n_nodes), np.float32)
    for (s, key), e in eta_index.items():
        eta_sel[e] = s
        eta_sk[e] = sk_index[(s, key)]
        eta_domain[e] = dom_of_key[key]

    static_pref = np.zeros((SEL, n_nodes), np.float32)
    sk_rev = {p: (s, key) for (s, key), p in sk_index.items()}
    for ti, t in tasks:
        ni = maps.node_index.get(t.node_name, -1)
        if ni < 0:
            continue
        # a placed pod's own required anti-affinity terms constrain
        # incoming pods matching them (symmetric anti-affinity)
        for e in per_task_anti.get(ti, ()):
            dom = eta_domain[e]
            d = dom[ni]
            if d >= 0:
                anti_cnt0[e][dom == d] += 1.0
        # a placed pod's preferred terms score incoming pods matching them
        # (symmetric preferred, static over the cycle)
        for p, w in per_task_pref.get(ti, ()):
            s, key = sk_rev[p]
            dom = dom_of_key[key]
            d = dom[ni]
            if d >= 0:
                static_pref[s][dom == d] += w

    # ---- per-task slot tables --------------------------------------------
    A = bucket(max(max((len(v) for v in per_task_aff.values()), default=0), 1), 1)
    B = bucket(max(max((len(v) for v in per_task_anti.values()), default=0), 1), 1)
    PP = bucket(max(max((len(v) for v in per_task_pref.values()), default=0), 1), 1)
    task_aff_sk = np.full((n_tasks, A), -1, np.int32)
    task_anti_term = np.full((n_tasks, B), -1, np.int32)
    task_pref_sk = np.full((n_tasks, PP), -1, np.int32)
    task_pref_w = np.zeros((n_tasks, PP), np.float32)
    for ti, rows in per_task_aff.items():
        for a, p in enumerate(rows):
            task_aff_sk[ti, a] = p
    for ti, rows in per_task_anti.items():
        for b, e in enumerate(rows):
            task_anti_term[ti, b] = e
    for ti, rows in per_task_pref.items():
        for i, (p, w) in enumerate(rows):
            task_pref_sk[ti, i] = p
            task_pref_w[ti, i] = w

    return AffinityArrays(
        sk_sel=sk_sel, sk_domain=sk_domain, cnt0=cnt0,
        task_match=task_match, task_aff_sk=task_aff_sk,
        task_anti_term=task_anti_term, eta_sel=eta_sel, eta_sk=eta_sk,
        eta_domain=eta_domain, anti_cnt0=anti_cnt0,
        task_pref_sk=task_pref_sk, task_pref_w=task_pref_w,
        static_pref=static_pref)
