"""Packed hdrf hierarchy tree: queue paths + job leaves as dense arrays.

The fork's hierarchical DRF builds an explicit tree from each queue's
``volcano.sh/hierarchy`` annotation — root, one node per path component, and
one leaf per JOB attached under its queue's final path node
(pkg/scheduler/plugins/drf/drf.go:641-690 buildHierarchy). The repo's
QueueArrays parent pointers cannot express this: intermediate path
components that are not themselves declared queues ("eng" in
"root/eng/dev") vanish, and job leaves do not exist at all.

This module materializes the full tree host-side as static arrays that ride
:class:`~volcano_tpu.ops.allocate_scan.AllocateExtras` (the tree shape only
changes when queues change, never during a cycle):

- one tree node per unique path prefix across all queues (root included),
- ``queue_path[q, d]`` = the tree node at depth ``d`` along queue ``q``'s
  path (-1 beyond the path end), which is exactly the walk
  ``compareQueues`` performs (drf.go:182-218),
- ``job_leaf[j]`` = the node under which job ``j``'s drf attribute hangs.

Node weights come from ``volcano.sh/hierarchy-weights`` with the reference's
rules: parsed per level, floored at 1, first declaring queue wins
(drf.go:648-674); the root keeps weight 1 (drf.go:141-147). A queue with no
hierarchy annotation attaches its jobs directly under root, matching
``strings.Split("", "/")`` producing a single-element path in Go.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Dict, List, Tuple

import jax
import numpy as np

from .schema import IndexMaps, bucket


def _register(cls):
    fields = [f.name for f in dataclasses.fields(cls)]
    jax.tree_util.register_dataclass(cls, data_fields=fields, meta_fields=[])
    return cls


@_register
@dataclass
class HierarchyArrays:
    """Static hdrf tree topology (H tree nodes, D depth levels)."""

    parent: jax.Array      # i32[H] parent node, -1 for root
    depth: jax.Array       # i32[H] root = 0
    weight: jax.Array      # f32[H] hierarchy weight, >= 1
    valid: jax.Array       # bool[H]
    queue_path: jax.Array  # i32[Q, D] node at each depth along the queue's
    #                        path, -1 past the end (compareQueues walk)
    job_leaf: jax.Array    # i32[J] attach node per job, -1 = not in tree

    @property
    def h(self) -> int:
        return self.parent.shape[0]

    @property
    def d(self) -> int:
        return self.queue_path.shape[1]

    @classmethod
    def neutral(cls, Q: int, J: int) -> "HierarchyArrays":
        """Root-only tree: every queue sits at root, no job leaves."""
        path = np.full((Q, 2), -1, np.int32)
        path[:, 0] = 0
        return cls(
            parent=np.array([-1] + [-1] * 3, np.int32),
            depth=np.zeros(4, np.int32),
            weight=np.ones(4, np.float32),
            valid=np.array([True, False, False, False]),
            queue_path=path,
            job_leaf=np.full(J, -1, np.int32),
        )


def build_from_specs(specs: List[Tuple[str, str]], Q: int,
                     job_queue: np.ndarray,
                     job_in_tree: np.ndarray) -> HierarchyArrays:
    """(hierarchy, weights) annotation strings per queue -> HierarchyArrays.

    ``specs`` is ordered like the packed queue axis; ``job_queue`` is the
    packed i32[J] queue index per job and ``job_in_tree`` masks jobs whose
    queue is real (others get leaf -1). This is the core builder shared by
    the in-process session (from ClusterInfo) and the sidecar's wire
    decoder (native/pywire.py), which only has the raw strings.
    """
    paths: List[List[str]] = []
    weights: List[List[float]] = []
    for hierarchy, wstr in specs:
        p = [c for c in hierarchy.split("/") if c]
        paths.append(p[1:] if p else [])          # components after root
        try:
            w = [float(x) for x in wstr.split("/") if x]
        except ValueError:
            w = []
        weights.append(w[1:] if len(w) > 1 else [])

    # materialize nodes: root + every unique prefix, in queue order so the
    # first declaring queue's weight wins (buildHierarchy first-create,
    # drf.go:648-674)
    node_of: Dict[Tuple[str, ...], int] = {(): 0}
    node_parent = [-1]
    node_depth = [0]
    node_weight = [1.0]                            # root weight (drf.go:146)
    for comps, wvals in zip(paths, weights):
        for i in range(len(comps)):
            key = tuple(comps[: i + 1])
            if key in node_of:
                continue
            w = wvals[i] if i < len(wvals) else 1.0
            node_of[key] = len(node_parent)
            node_parent.append(node_of[tuple(comps[:i])])
            node_depth.append(i + 1)
            node_weight.append(max(w, 1.0))

    nH = len(node_parent)
    H = bucket(nH, 4)
    parent = np.full(H, -1, np.int32)
    depth = np.zeros(H, np.int32)
    weight = np.ones(H, np.float32)
    valid = np.zeros(H, bool)
    parent[:nH] = node_parent
    depth[:nH] = node_depth
    weight[:nH] = node_weight
    valid[:nH] = True

    D = max((len(p) for p in paths), default=0) + 1
    D = max(D, 2)
    queue_path = np.full((Q, D), -1, np.int32)
    leaf_of_queue = np.full(Q, 0, np.int32)
    for qi, comps in enumerate(paths):
        queue_path[qi, 0] = 0
        for i in range(len(comps)):
            queue_path[qi, i + 1] = node_of[tuple(comps[: i + 1])]
        leaf_of_queue[qi] = queue_path[qi, len(comps)]

    J = job_queue.shape[0]
    job_leaf = np.full(J, -1, np.int32)
    sel = np.asarray(job_in_tree, bool)
    job_leaf[sel] = leaf_of_queue[np.clip(job_queue[sel], 0, Q - 1)]

    return HierarchyArrays(parent=parent, depth=depth, weight=weight,
                           valid=valid, queue_path=queue_path,
                           job_leaf=job_leaf)


def build_hierarchy(ci, maps: IndexMaps, Q: int, J: int) -> HierarchyArrays:
    """ClusterInfo -> HierarchyArrays on the packed queue/job index maps.

    ``Q``/``J`` are the bucketed dims of the snapshot so the result composes
    with the same compiled cycle.
    """
    specs = [(ci.queues[n].hierarchy, ci.queues[n].hierarchy_weights)
             for n in maps.queue_names]
    specs += [("", "")] * (Q - len(specs))
    job_queue = np.zeros(J, np.int32)
    job_in_tree = np.zeros(J, bool)
    for uid, ji in maps.job_index.items():
        qi = maps.queue_index.get(ci.jobs[uid].queue, -1)
        if qi >= 0:
            job_queue[ji] = qi
            job_in_tree[ji] = True
    return build_from_specs(specs, Q, job_queue, job_in_tree)
