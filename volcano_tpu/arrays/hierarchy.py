"""Packed hdrf hierarchy tree: queue paths + job leaves as dense arrays.

The fork's hierarchical DRF builds an explicit tree from each queue's
``volcano.sh/hierarchy`` annotation — root, one node per path component, and
one leaf per JOB attached under its queue's final path node
(pkg/scheduler/plugins/drf/drf.go:641-690 buildHierarchy). The repo's
QueueArrays parent pointers cannot express this: intermediate path
components that are not themselves declared queues ("eng" in
"root/eng/dev") vanish, and job leaves do not exist at all.

This module materializes the full tree host-side as static arrays that ride
:class:`~volcano_tpu.ops.allocate_scan.AllocateExtras` (the tree shape only
changes when queues change, never during a cycle):

- one tree node per unique path prefix across all queues (root included),
- ``queue_path[q, d]`` = the tree node at depth ``d`` along queue ``q``'s
  path (-1 beyond the path end), which is exactly the walk
  ``compareQueues`` performs (drf.go:182-218),
- ``job_leaf[j]`` = the node under which job ``j``'s drf attribute hangs.

Node weights come from ``volcano.sh/hierarchy-weights`` with the reference's
rules: parsed per level, floored at 1, first declaring queue wins
(drf.go:648-674); the root keeps weight 1 (drf.go:141-147). A queue with no
hierarchy annotation attaches its jobs directly under root, matching
``strings.Split("", "/")`` producing a single-element path in Go.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Dict, List, Tuple

import jax
import numpy as np

from .schema import IndexMaps, bucket


def _register(cls):
    fields = [f.name for f in dataclasses.fields(cls)]
    jax.tree_util.register_dataclass(cls, data_fields=fields, meta_fields=[])
    return cls


@_register
@dataclass
class HierarchyArrays:
    """Static hdrf tree topology (H tree nodes, D depth levels)."""

    parent: jax.Array      # i32[H] parent node, -1 for root
    depth: jax.Array       # i32[H] root = 0
    weight: jax.Array      # f32[H] hierarchy weight, >= 1
    valid: jax.Array       # bool[H]
    queue_path: jax.Array  # i32[Q, D] node at each depth along the queue's
    #                        path, -1 past the end (compareQueues walk)
    job_leaf: jax.Array    # i32[J] attach node per job, -1 = not in tree

    @property
    def h(self) -> int:
        return self.parent.shape[0]

    @property
    def d(self) -> int:
        return self.queue_path.shape[1]

    @classmethod
    def neutral(cls, Q: int, J: int) -> "HierarchyArrays":
        """Root-only tree: every queue sits at root, no job leaves."""
        path = np.full((Q, 2), -1, np.int32)
        path[:, 0] = 0
        return cls(
            parent=np.array([-1] + [-1] * 3, np.int32),
            depth=np.zeros(4, np.int32),
            weight=np.ones(4, np.float32),
            valid=np.array([True, False, False, False]),
            queue_path=path,
            job_leaf=np.full(J, -1, np.int32),
        )


def build_hierarchy(ci, maps: IndexMaps, Q: int, J: int) -> HierarchyArrays:
    """ClusterInfo -> HierarchyArrays on the packed queue/job index maps.

    ``Q``/``J`` are the bucketed dims of the snapshot so the result composes
    with the same compiled cycle.
    """
    queue_names = maps.queue_names
    # path per queue: [root, comp1, comp2, ...]; no annotation -> [root]
    paths: Dict[str, List[str]] = {}
    weights: Dict[str, List[float]] = {}
    for name in queue_names:
        q = ci.queues[name]
        p = q.hierarchy_path()
        paths[name] = p[1:] if p else []          # components after root
        w = q.hierarchy_weight_values()
        weights[name] = w[1:] if len(w) > 1 else []

    # materialize nodes: root + every unique prefix, in sorted-queue order so
    # the first declaring queue's weight wins (buildHierarchy first-create,
    # drf.go:648-674)
    node_of: Dict[Tuple[str, ...], int] = {(): 0}
    node_parent = [-1]
    node_depth = [0]
    node_weight = [1.0]                            # root weight (drf.go:146)
    for name in queue_names:
        comps = paths[name]
        wvals = weights[name]
        for i in range(len(comps)):
            key = tuple(comps[: i + 1])
            if key in node_of:
                continue
            w = wvals[i] if i < len(wvals) else 1.0
            node_of[key] = len(node_parent)
            node_parent.append(node_of[tuple(comps[:i])])
            node_depth.append(i + 1)
            node_weight.append(max(w, 1.0))

    nH = len(node_parent)
    H = bucket(nH, 4)
    parent = np.full(H, -1, np.int32)
    depth = np.zeros(H, np.int32)
    weight = np.ones(H, np.float32)
    valid = np.zeros(H, bool)
    parent[:nH] = node_parent
    depth[:nH] = node_depth
    weight[:nH] = node_weight
    valid[:nH] = True

    D = max((len(paths[n]) for n in queue_names), default=0) + 1
    D = max(D, 2)
    queue_path = np.full((Q, D), -1, np.int32)
    leaf_of_queue = np.full(Q, -1, np.int32)
    for qi, name in enumerate(queue_names):
        comps = paths[name]
        queue_path[qi, 0] = 0
        for i in range(len(comps)):
            queue_path[qi, i + 1] = node_of[tuple(comps[: i + 1])]
        leaf_of_queue[qi] = queue_path[qi, len(comps)]

    job_leaf = np.full(J, -1, np.int32)
    for uid, ji in maps.job_index.items():
        qi = maps.queue_index.get(ci.jobs[uid].queue, -1)
        if qi >= 0:
            job_leaf[ji] = leaf_of_queue[qi]

    return HierarchyArrays(parent=parent, depth=depth, weight=weight,
                           valid=valid, queue_path=queue_path,
                           job_leaf=job_leaf)
