"""Stable string hashing for label / taint / selector tensor encodings.

The reference's predicates walk Go maps of labels and taint structs
(pkg/scheduler/plugins/predicates/predicates.go:201-288). On TPU, pointer
chasing is replaced by fixed-width integer hash sets: every label ``key=value``
becomes a nonzero int32; membership tests become vectorized equality scans
(SURVEY.md section 7, array schema / hard part 3).
"""

from __future__ import annotations

import zlib
from typing import Dict, Iterable, List

import numpy as np

#: Taint-effect codes used in the packed arrays.
EFFECT_NONE = 0
EFFECT_NO_SCHEDULE = 1
EFFECT_PREFER_NO_SCHEDULE = 2
EFFECT_NO_EXECUTE = 3

_EFFECTS = {
    "": EFFECT_NONE,
    "NoSchedule": EFFECT_NO_SCHEDULE,
    "PreferNoSchedule": EFFECT_PREFER_NO_SCHEDULE,
    "NoExecute": EFFECT_NO_EXECUTE,
}

#: Toleration match modes.
TOL_EQUAL = 0        # match key=value hash
TOL_EXISTS_KEY = 1   # match key hash
TOL_EXISTS_ALL = 2   # tolerates everything


_HASH_CACHE: Dict[str, int] = {}


def stable_hash(s: str) -> int:
    """Deterministic nonzero 31-bit hash of a string (0 is the empty slot).

    Memoized: label/selector strings repeat across thousands of entities in
    one snapshot, and the encode+crc per call dominated serialize at scale.
    The cache is unbounded but keyed by label strings, whose population is
    small and stable in practice; reset if it ever exceeds a safety cap."""
    h = _HASH_CACHE.get(s)
    if h is None:
        if len(_HASH_CACHE) > (1 << 20):
            _HASH_CACHE.clear()
        h = zlib.crc32(s.encode("utf-8")) & 0x7FFFFFFF
        if h == 0:
            h = 1
        _HASH_CACHE[s] = h
    return h


def label_hashes(labels: Dict[str, str]) -> List[int]:
    return sorted(stable_hash(f"{k}={v}") for k, v in labels.items())


def effect_code(effect: str) -> int:
    return _EFFECTS.get(effect, EFFECT_NONE)


def pack_hash_rows(rows: Iterable[List[int]], width: int | None = None,
                   dtype=np.int32) -> np.ndarray:
    """Pack variable-length hash lists into a zero-padded [n, width] matrix."""
    rows = [list(r) for r in rows]
    if width is None:
        width = max((len(r) for r in rows), default=0)
    width = max(width, 1)
    out = np.zeros((len(rows), width), dtype=dtype)
    for i, r in enumerate(rows):
        r = r[:width]
        out[i, : len(r)] = r
    return out
