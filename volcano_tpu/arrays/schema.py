"""Dense array schema of a cluster snapshot — the device-side world state.

This is the TPU re-design of the reference's per-cycle Snapshot
(pkg/scheduler/cache/cache.go:712-811 producing api.ClusterInfo): instead of
maps of pointers, the session operates on struct-of-array tensors with validity
masks. All shapes are static per bucket so XLA compiles the cycle once per
(N, T, J, Q, R) bucket (SURVEY.md section 7).

Axis legend: N nodes, T tasks, J jobs, Q queues, S namespaces, R resource dims,
L label slots, K selector slots, E taint slots, O toleration slots, M max
pending tasks per job, G GPU cards per node (shared-GPU predicate).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Dict, List

import jax
import numpy as np


def _register(cls):
    """Register a dataclass as a JAX pytree (all fields are children)."""
    fields = [f.name for f in dataclasses.fields(cls)]
    jax.tree_util.register_dataclass(cls, data_fields=fields, meta_fields=[])
    return cls


@_register
@dataclass
class NodeArrays:
    """Per-node accounting tensors (reference: api.NodeInfo, node_info.go:28-437)."""

    idle: jax.Array          # f32[N, R]
    used: jax.Array          # f32[N, R]
    releasing: jax.Array     # f32[N, R]
    pipelined: jax.Array     # f32[N, R]
    allocatable: jax.Array   # f32[N, R]
    capability: jax.Array    # f32[N, R]
    labels: jax.Array        # i32[N, L]  label key=value hashes, 0 pad
    taint_kv: jax.Array      # i32[N, E]  taint key=value hashes, 0 pad
    taint_key: jax.Array     # i32[N, E]  taint key hashes
    taint_effect: jax.Array  # i32[N, E]  effect codes (labels.EFFECT_*)
    pod_count: jax.Array     # i32[N]
    max_pods: jax.Array      # i32[N]
    gpu_memory: jax.Array    # f32[N, G]  per-card memory, 0 = no card
    gpu_used: jax.Array      # f32[N, G]  per-card used memory
    schedulable: jax.Array   # bool[N]  ready && !unschedulable
    valid: jax.Array         # bool[N]

    @property
    def n(self) -> int:
        return self.idle.shape[0]

    def future_idle(self) -> jax.Array:
        """idle + releasing - pipelined, floored at 0 (node_info.go:62-65)."""
        import jax.numpy as jnp
        return jnp.maximum(self.idle + self.releasing - self.pipelined, 0.0)


@_register
@dataclass
class TaskArrays:
    """Per-task tensors (reference: api.TaskInfo, job_info.go:70-171)."""

    resreq: jax.Array        # f32[T, R]
    job: jax.Array           # i32[T] job index
    status: jax.Array        # i32[T] TaskStatus codes
    priority: jax.Array      # i32[T]
    node: jax.Array          # i32[T] current node index, -1 unassigned
    selector: jax.Array      # i32[T, K] required label hashes, 0 pad
    tol_hash: jax.Array      # i32[T, O] toleration match hashes
    tol_effect: jax.Array    # i32[T, O] effect codes (0 = all effects)
    tol_mode: jax.Array      # i32[T, O] labels.TOL_* modes
    best_effort: jax.Array   # bool[T] empty resreq (backfill targets)
    gpu_request: jax.Array   # f32[T] single-card GPU memory request
    template: jax.Array      # i32[T] predicate-template id (tasks with equal
    #                          selector/toleration rows share one; the
    #                          predicate-cache key, predicates/cache.go:42-67)
    preemptable: jax.Array   # bool[T]
    valid: jax.Array         # bool[T]

    @property
    def t(self) -> int:
        return self.resreq.shape[0]


@_register
@dataclass
class JobArrays:
    """Per-gang-job tensors (reference: api.JobInfo, job_info.go:181-613)."""

    min_available: jax.Array  # i32[J]
    queue: jax.Array          # i32[J] queue index
    namespace: jax.Array      # i32[J]
    priority: jax.Array       # i32[J]
    creation_rank: jax.Array  # i32[J] older = smaller (FIFO tie-break)
    ready_num: jax.Array      # i32[J] tasks already in ready statuses
    allocated: jax.Array      # f32[J, R] resources of allocated-status tasks
    total_request: jax.Array  # f32[J, R]
    min_resources: jax.Array  # f32[J, R] PodGroup MinResources (enqueue gate)
    task_table: jax.Array     # i32[J, M] pending task indices sorted by task
    #                           order (priority desc, creation), -1 pad
    n_pending: jax.Array      # i32[J]
    schedulable: jax.Array    # bool[J] gang-valid && queue open && inqueue
    inqueue: jax.Array        # bool[J] PodGroup phase is Inqueue/Running
    pending_phase: jax.Array  # bool[J] PodGroup phase is Pending (enqueue input)
    preemptable: jax.Array    # bool[J]
    valid: jax.Array          # bool[J]

    @property
    def j(self) -> int:
        return self.min_available.shape[0]

    @property
    def m(self) -> int:
        return self.task_table.shape[1]


@_register
@dataclass
class QueueArrays:
    """Per-queue tensors (reference: api.QueueInfo + proportion queueAttr,
    pkg/scheduler/plugins/proportion/proportion.go:59-90)."""

    weight: jax.Array       # f32[Q]
    capability: jax.Array   # f32[Q, R] +inf where unset
    reclaimable: jax.Array  # bool[Q]
    open: jax.Array         # bool[Q]
    allocated: jax.Array    # f32[Q, R] sum of member jobs' allocated
    request: jax.Array      # f32[Q, R] sum of member jobs' total_request
    inqueue_minres: jax.Array  # f32[Q, R] sum of MinResources of inqueue jobs
    # Hierarchical fairness (fork's hdrf): parent pointer tree, root = self.
    parent: jax.Array       # i32[Q] parent queue index (-1 for roots)
    depth: jax.Array        # i32[Q]
    hier_weight: jax.Array  # f32[Q] leaf weight from volcano.sh/
    #                         hierarchy-weights (drf.go hdrf), 1 when unset
    valid: jax.Array        # bool[Q]

    @property
    def q(self) -> int:
        return self.weight.shape[0]


@_register
@dataclass
class SnapshotArrays:
    """The full device-side snapshot consumed by the compiled cycle."""

    nodes: NodeArrays
    tasks: TaskArrays
    jobs: JobArrays
    queues: QueueArrays
    namespace_weight: jax.Array   # f32[S]
    cluster_capacity: jax.Array   # f32[R] sum of node allocatable
    template_rep: jax.Array       # i32[P] representative task per predicate
    #                               template, -1 pad (cache.go analog)


@dataclass
class IndexMaps:
    """Host-side decode tables (NOT a pytree; never crosses to device)."""

    node_names: List[str] = field(default_factory=list)
    task_uids: List[str] = field(default_factory=list)
    job_uids: List[str] = field(default_factory=list)
    queue_names: List[str] = field(default_factory=list)
    namespace_names: List[str] = field(default_factory=list)
    resource_names: List[str] = field(default_factory=list)
    node_index: Dict[str, int] = field(default_factory=dict)
    task_index: Dict[str, int] = field(default_factory=dict)
    job_index: Dict[str, int] = field(default_factory=dict)
    queue_index: Dict[str, int] = field(default_factory=dict)


def bucket(n: int, minimum: int = 8) -> int:
    """Round up to the static-shape bucket grid (SURVEY section 7 hard
    part 2): powers of two up to 1024, then 8 buckets per octave
    (multiples of next_pow2(n)/8). Pure power-of-two padding wasted up to
    ~2x device time on the node axis at scale (10k nodes -> 16384; this
    grid gives 10240) while the finer grid keeps the jit-cache bucket
    count per octave bounded at 8. Every value stays a multiple of 1024
    above 1024, so lane (128) and virtual-mesh (8-way) divisibility hold.
    Mirrored in native/pywire._bucket and packer.cc Bucket()."""
    b = minimum
    while b < n and b < 1024:
        b *= 2
    if n <= b:
        return b
    p = 1 << (int(n) - 1).bit_length()   # next power of two >= n
    g = max(1024, p // 8)
    return ((int(n) + g - 1) // g) * g


def pad_rows(a: np.ndarray, n: int) -> np.ndarray:
    """Zero-pad axis 0 of ``a`` to length n."""
    if a.shape[0] == n:
        return a
    pad = [(0, n - a.shape[0])] + [(0, 0)] * (a.ndim - 1)
    return np.pad(a, pad)
