"""Dense snapshot arrays — device-side world state (reference: cache.Snapshot)."""

from .labels import (EFFECT_NO_EXECUTE, EFFECT_NO_SCHEDULE, EFFECT_NONE,
                     EFFECT_PREFER_NO_SCHEDULE, TOL_EQUAL, TOL_EXISTS_ALL,
                     TOL_EXISTS_KEY, effect_code, label_hashes, stable_hash)
from .pack import pack, resource_dims
from .schema import (IndexMaps, JobArrays, NodeArrays, QueueArrays,
                     SnapshotArrays, TaskArrays, bucket)

__all__ = [
    "pack", "resource_dims", "IndexMaps", "JobArrays", "NodeArrays",
    "QueueArrays", "SnapshotArrays", "TaskArrays", "bucket", "stable_hash",
    "label_hashes", "effect_code", "EFFECT_NONE", "EFFECT_NO_SCHEDULE",
    "EFFECT_PREFER_NO_SCHEDULE", "EFFECT_NO_EXECUTE", "TOL_EQUAL",
    "TOL_EXISTS_KEY", "TOL_EXISTS_ALL",
]
