"""ClusterInfo -> dense SnapshotArrays packing.

The host-side half of the cycle: flatten the object snapshot into the
struct-of-array schema. The reference's equivalent moment is
SchedulerCache.Snapshot deep-copying maps (cache.go:712-811); here the copy IS
the pack, and the result is what gets shipped to the device.

Node-affinity encoding (SURVEY section 7 hard part 3): a lone pure-labels
required term folds into the packed all-of selector row (hash equality);
multi-term OR-of-terms and any matchExpressions term (full k8s operator set
In/NotIn/Exists/DoesNotExist/Gt/Lt, api/job_info.py NodeSelectorTerm) ride
host-computed per-task OR-group node masks (extras.task_or_group /
or_feasible, Session._node_affinity_extras) — exact on the session path and
shipped to the sidecar in the VCS4 extras frame.
(InterPodAffinity has its own exact encoding, arrays/affinity.py.)
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..api import (CPU, MEMORY, ClusterInfo, JobInfo, PodGroupPhase,
                   QueueState, TaskStatus, as_node_term, gpu_request_of,
                   is_allocated_status)
from ..api.job_info import Toleration
from . import labels as L
from .schema import (IndexMaps, JobArrays, NodeArrays, QueueArrays,
                     SnapshotArrays, TaskArrays, bucket, pad_rows)

#: Statuses whose resreq counts as ready/occupying (api/types.go:87-96 + Succeeded).
_READY_STATUSES = (TaskStatus.ALLOCATED, TaskStatus.BINDING, TaskStatus.BOUND,
                   TaskStatus.RUNNING, TaskStatus.SUCCEEDED)

#: Additional statuses counted by ValidTaskNum but not ReadyTaskNum
#: (job_info.go:577-595); the single source for the wire serializer's
#: one-pass job counts too.
_VALID_ONLY_STATUSES = (TaskStatus.PENDING, TaskStatus.PIPELINED)


def resource_dims(ci: ClusterInfo) -> List[str]:
    """Stable resource-dimension order: cpu, memory, then sorted scalars."""
    names = {CPU, MEMORY}
    upd = names.update
    for node in ci.nodes.values():
        upd(node.allocatable.quantities)
    for job in ci.jobs.values():
        upd(job.min_resources.quantities)
        for task in job.tasks.values():
            upd(task.resreq.quantities)
    for queue in ci.queues.values():
        upd(queue.capability.quantities)
    scalars = sorted(n for n in names if n not in (CPU, MEMORY))
    return [CPU, MEMORY] + scalars


def _vec(res, dims: List[str]) -> np.ndarray:
    return np.array([res.get(d) for d in dims], dtype=np.float32)


def queue_capability_row(q, dims: List[str]) -> np.ndarray:
    """Queue capability vector with +inf for undeclared dims (proportion.go
    clamps by capability only where declared)."""
    inf = np.float32(np.inf)
    if not q.capability.quantities:
        return np.full(len(dims), inf, np.float32)
    cap = _vec(q.capability, dims)
    declared = np.array([d in q.capability.quantities for d in dims])
    return np.where(declared, cap, inf).astype(np.float32)


def queue_parent_depth(ci: ClusterInfo,
                       queue_names: List[str]) -> Tuple[List[int], List[int]]:
    """Hierarchy parent pointers + depths from the fork's hdrf path
    annotations: parent is the queue whose path is path[:-1], else root."""
    path_of = {n: ci.queues[n].hierarchy_path() for n in queue_names}
    parents, depths = [], []
    for name in queue_names:
        path = path_of[name]
        depths.append(max(len(path) - 1, 0))
        parent = -1
        if len(path) > 1:
            for j, other in enumerate(queue_names):
                if path_of[other] == path[:-1]:
                    parent = j
                    break
        parents.append(parent)
    return parents, depths


def _toleration_rows(tols: List[Toleration]) -> Tuple[List[int], List[int], List[int]]:
    hashes, effects, modes = [], [], []
    for t in tols:
        eff = L.effect_code(t.effect)
        if t.operator == "Exists":
            if not t.key:
                hashes.append(1); effects.append(eff); modes.append(L.TOL_EXISTS_ALL)
            else:
                hashes.append(L.stable_hash(t.key)); effects.append(eff)
                modes.append(L.TOL_EXISTS_KEY)
        else:
            hashes.append(L.stable_hash(f"{t.key}={t.value}"))
            effects.append(eff); modes.append(L.TOL_EQUAL)
    return hashes, effects, modes


def pack(ci: ClusterInfo,
         buckets: Optional[Dict[str, int]] = None) -> Tuple[SnapshotArrays, IndexMaps]:
    """Flatten a ClusterInfo into padded, masked device arrays."""
    buckets = buckets or {}
    dims = resource_dims(ci)
    R = len(dims)
    inf = np.float32(np.inf)

    maps = IndexMaps(resource_names=dims)

    # ---------------------------------------------------------------- queues
    queue_names = sorted(ci.queues)
    maps.queue_names = queue_names
    maps.queue_index = {n: i for i, n in enumerate(queue_names)}
    nq = len(queue_names)
    Q = bucket(max(nq, 1), buckets.get("Q", 4))
    q_weight = np.zeros(Q, np.float32)
    q_cap = np.full((Q, R), inf, np.float32)
    q_reclaimable = np.zeros(Q, bool)
    q_open = np.zeros(Q, bool)
    q_hier_w = np.ones(Q, np.float32)
    for i, name in enumerate(queue_names):
        q = ci.queues[name]
        q_weight[i] = max(q.weight, 0)
        q_cap[i] = queue_capability_row(q, dims)
        q_reclaimable[i] = q.reclaimable
        q_open[i] = q.state == QueueState.OPEN
        hw = q.hierarchy_weight_values()
        if hw:
            q_hier_w[i] = hw[-1]

    # hierarchy tree (fork's hdrf): build parent pointers from paths
    q_parent = np.full(Q, -1, np.int32)
    q_depth = np.zeros(Q, np.int32)
    parents, depths = queue_parent_depth(ci, queue_names)
    q_parent[: len(parents)] = parents
    q_depth[: len(depths)] = depths

    # ------------------------------------------------------------ namespaces
    ns_names = sorted(ci.namespaces) or ["default"]
    maps.namespace_names = ns_names
    ns_index = {n: i for i, n in enumerate(ns_names)}
    S = bucket(len(ns_names), buckets.get("S", 4))
    ns_weight = np.ones(S, np.float32)
    for i, n in enumerate(ns_names):
        ns_weight[i] = max(ci.namespaces[n].weight if n in ci.namespaces else 1, 1)

    # ----------------------------------------------------------------- nodes
    node_names = sorted(ci.nodes)
    maps.node_names = node_names
    maps.node_index = {n: i for i, n in enumerate(node_names)}
    nn = len(node_names)
    N = bucket(max(nn, 1), buckets.get("N", 8))
    n_idle = np.zeros((N, R), np.float32)
    n_used = np.zeros((N, R), np.float32)
    n_rel = np.zeros((N, R), np.float32)
    n_pip = np.zeros((N, R), np.float32)
    n_alloc = np.zeros((N, R), np.float32)
    n_capab = np.zeros((N, R), np.float32)
    n_podcount = np.zeros(N, np.int32)
    n_maxpods = np.zeros(N, np.int32)
    n_sched = np.zeros(N, bool)
    n_valid = np.zeros(N, bool)
    # shared-GPU cards (GPUDevices, node_info.go:54; device_info.go:24-53)
    G = bucket(max((len(ci.nodes[n].gpu_devices) for n in node_names),
                   default=1) or 1, buckets.get("G", 1))
    n_gpu_mem = np.zeros((N, G), np.float32)
    n_gpu_used = np.zeros((N, G), np.float32)
    label_rows, taint_kv_rows, taint_key_rows, taint_eff_rows = [], [], [], []
    for i, name in enumerate(node_names):
        node = ci.nodes[name]
        n_idle[i] = _vec(node.idle, dims)
        n_used[i] = _vec(node.used, dims)
        n_rel[i] = _vec(node.releasing, dims)
        n_pip[i] = _vec(node.pipelined, dims)
        n_alloc[i] = _vec(node.allocatable, dims)
        n_capab[i] = _vec(node.capability, dims)
        n_podcount[i] = node.pod_count()
        n_maxpods[i] = node.max_pods
        n_sched[i] = node.ready and not node.unschedulable
        n_valid[i] = True
        for dev in node.gpu_devices[:G]:
            n_gpu_mem[i, dev.id] = dev.memory
            n_gpu_used[i, dev.id] = dev.used_memory()
        label_rows.append(L.label_hashes(node.labels))
        taint_kv_rows.append([L.stable_hash(f"{t.key}={t.value}") for t in node.taints])
        taint_key_rows.append([L.stable_hash(t.key) for t in node.taints])
        taint_eff_rows.append([L.effect_code(t.effect) for t in node.taints])
    n_labels = pad_rows(L.pack_hash_rows(label_rows or [[]]), N)
    n_taint_kv = pad_rows(L.pack_hash_rows(taint_kv_rows or [[]]), N)
    n_taint_key = pad_rows(L.pack_hash_rows(taint_key_rows or [[]]), N)
    n_taint_eff = pad_rows(L.pack_hash_rows(taint_eff_rows or [[]]), N)

    nodes = NodeArrays(
        idle=n_idle, used=n_used, releasing=n_rel, pipelined=n_pip,
        allocatable=n_alloc, capability=n_capab, labels=n_labels,
        taint_kv=n_taint_kv, taint_key=n_taint_key, taint_effect=n_taint_eff,
        pod_count=n_podcount, max_pods=n_maxpods,
        gpu_memory=n_gpu_mem, gpu_used=n_gpu_used, schedulable=n_sched,
        valid=n_valid)

    # ------------------------------------------------------- jobs and tasks
    job_uids = sorted(ci.jobs)
    maps.job_uids = job_uids
    maps.job_index = {u: i for i, u in enumerate(job_uids)}
    nj = len(job_uids)
    J = bucket(max(nj, 1), buckets.get("J", 4))

    task_entries = []  # (job_idx, TaskInfo, insertion_rank)
    for ji, uid in enumerate(job_uids):
        for rank, task in enumerate(ci.jobs[uid].tasks.values()):
            task_entries.append((ji, task, rank))
    nt = len(task_entries)
    T = bucket(max(nt, 1), buckets.get("T", 8))

    t_resreq = np.zeros((T, R), np.float32)
    t_job = np.full(T, -1, np.int32)
    t_status = np.zeros(T, np.int32)
    t_priority = np.zeros(T, np.int32)
    t_node = np.full(T, -1, np.int32)
    t_best_effort = np.zeros(T, bool)
    t_gpu_req = np.zeros(T, np.float32)
    t_preempt = np.zeros(T, bool)
    t_valid = np.zeros(T, bool)
    sel_rows, tolh_rows, tole_rows, tolm_rows = [], [], [], []
    maps.task_uids = []
    for ti, (ji, task, _rank) in enumerate(task_entries):
        maps.task_uids.append(task.uid)
        maps.task_index[task.uid] = ti
        t_resreq[ti] = _vec(task.resreq, dims)
        t_job[ti] = ji
        t_status[ti] = int(task.status)
        t_priority[ti] = task.priority
        t_node[ti] = maps.node_index.get(task.node_name, -1)
        t_best_effort[ti] = task.best_effort
        t_gpu_req[ti] = gpu_request_of(task.resreq)
        t_preempt[ti] = task.preemptable
        t_valid[ti] = True
        required = dict(task.node_selector)
        terms = [as_node_term(m) for m in task.affinity_required]
        if len(terms) == 1 and terms[0].is_pure_labels():
            required.update(terms[0].match_labels)
        # multi-term required node affinity is OR-of-terms (k8s
        # NodeSelectorTerms), and matchExpressions operators
        # (In/NotIn/Exists/DoesNotExist/Gt/Lt) cannot ride the hash-equality
        # row: the packed row keeps only the nodeSelector conjunction (plus
        # a lone pure-labels term); everything else travels as per-task
        # OR-group masks (extras.or_feasible, Session._node_affinity_extras,
        # carried over the VCS4 wire extras section)
        sel_rows.append(sorted(L.stable_hash(f"{k}={v}")
                               for k, v in required.items()))
        h, e, m = _toleration_rows(task.tolerations)
        tolh_rows.append(h); tole_rows.append(e); tolm_rows.append(m)
    t_selector = pad_rows(L.pack_hash_rows(sel_rows or [[]]), T)
    t_tol_hash = pad_rows(L.pack_hash_rows(tolh_rows or [[]]), T)
    t_tol_eff = pad_rows(L.pack_hash_rows(tole_rows or [[]]), T)
    t_tol_mode = pad_rows(L.pack_hash_rows(tolm_rows or [[]]), T)

    # predicate templates: tasks with identical selector/toleration rows share
    # the static (capacity-independent) predicate result; the kernel computes
    # one mask row per template instead of per task (the TLRU predicate-cache
    # analog, plugins/predicates/cache.go:42-90, keyed per pod template).
    t_template = np.zeros(T, np.int32)
    template_of: Dict[tuple, int] = {}
    rep_tasks: List[int] = []
    for ti in range(nt):
        task = task_entries[ti][1]
        na_sig = tuple(sorted((as_node_term(m).signature(), w)
                              for m, w in task.affinity_preferred))
        sig = (tuple(sel_rows[ti]), tuple(tolh_rows[ti]),
               tuple(tole_rows[ti]), tuple(tolm_rows[ti]), na_sig)
        tid = template_of.get(sig)
        if tid is None:
            tid = len(rep_tasks)
            template_of[sig] = tid
            rep_tasks.append(ti)
        t_template[ti] = tid
    P = bucket(max(len(rep_tasks), 1), buckets.get("P", 4))
    template_rep = np.full(P, -1, np.int32)
    template_rep[: len(rep_tasks)] = rep_tasks

    tasks = TaskArrays(
        resreq=t_resreq, job=t_job, status=t_status, priority=t_priority,
        node=t_node, selector=t_selector, tol_hash=t_tol_hash,
        tol_effect=t_tol_eff, tol_mode=t_tol_mode, best_effort=t_best_effort,
        gpu_request=t_gpu_req, template=t_template, preemptable=t_preempt,
        valid=t_valid)

    j_minavail = np.zeros(J, np.int32)
    j_queue = np.zeros(J, np.int32)
    j_ns = np.zeros(J, np.int32)
    j_priority = np.zeros(J, np.int32)
    j_created = np.zeros(J, np.int32)
    j_ready = np.zeros(J, np.int32)
    j_allocated = np.zeros((J, R), np.float32)
    j_request = np.zeros((J, R), np.float32)
    j_minres = np.zeros((J, R), np.float32)
    j_npending = np.zeros(J, np.int32)
    j_sched = np.zeros(J, bool)
    j_inqueue = np.zeros(J, bool)
    j_pending_phase = np.zeros(J, bool)
    j_preempt = np.zeros(J, bool)
    j_valid = np.zeros(J, bool)

    order = {u: r for r, u in enumerate(
        sorted(job_uids, key=lambda u: ci.jobs[u].creation_timestamp))}
    pending_lists: List[List[int]] = [[] for _ in range(J)]
    for ti, (ji, task, _rank) in enumerate(task_entries):
        if task.status == TaskStatus.PENDING:
            pending_lists[ji].append(ti)
        # fair-share "request" counts allocated-status + pending tasks only
        # (proportion.OnSessionOpen, proportion.go:100-110)
        if task.status == TaskStatus.PENDING or is_allocated_status(
                TaskStatus(task.status)):
            j_request[ji] += t_resreq[ti]
    j_queue_known = np.zeros(J, bool)
    for ji, uid in enumerate(job_uids):
        job = ci.jobs[uid]
        j_minavail[ji] = job.min_available
        j_queue[ji] = maps.queue_index.get(job.queue, 0)
        j_queue_known[ji] = job.queue in maps.queue_index
        j_ns[ji] = ns_index.get(job.namespace, 0)
        j_priority[ji] = job.priority
        j_created[ji] = order[uid]
        j_ready[ji] = job.ready_task_num()
        j_allocated[ji] = _vec(job.allocated, dims)
        j_minres[ji] = _vec(job.min_resources, dims)
        # task order within job: priority desc, then insertion order
        # (reference: priority plugin TaskOrderFn, priority.go:63)
        pending_lists[ji].sort(key=lambda ti: (-t_priority[ti], ti))
        j_npending[ji] = len(pending_lists[ji])
        gang_valid, _ = job.is_valid()
        qi = maps.queue_index.get(job.queue)
        queue_open = qi is not None and bool(q_open[qi])
        j_pending_phase[ji] = job.pod_group_phase == PodGroupPhase.PENDING
        j_inqueue[ji] = not j_pending_phase[ji]
        j_sched[ji] = gang_valid and queue_open and j_inqueue[ji]
        j_preempt[ji] = job.preemptable
        j_valid[ji] = True

    M = bucket(max((len(p) for p in pending_lists), default=1),
               buckets.get("M", 4))
    j_table = np.full((J, M), -1, np.int32)
    for ji, plist in enumerate(pending_lists):
        j_table[ji, : len(plist)] = plist[:M]

    jobs = JobArrays(
        min_available=j_minavail, queue=j_queue, namespace=j_ns,
        priority=j_priority, creation_rank=j_created, ready_num=j_ready,
        allocated=j_allocated, total_request=j_request, min_resources=j_minres,
        task_table=j_table, n_pending=j_npending, schedulable=j_sched,
        inqueue=j_inqueue, pending_phase=j_pending_phase,
        preemptable=j_preempt, valid=j_valid)

    # queue aggregates (reference: proportion.OnSessionOpen sums member jobs,
    # proportion.go:95-139)
    q_allocated = np.zeros((Q, R), np.float32)
    q_request = np.zeros((Q, R), np.float32)
    q_inqueue_minres = np.zeros((Q, R), np.float32)
    for ji in range(nj):
        if not j_queue_known[ji]:
            # jobs in unknown/deleted queues are unschedulable (pack leaves
            # j_sched False above) and must not pollute queue aggregates
            continue
        qi = j_queue[ji]
        q_allocated[qi] += j_allocated[ji]
        q_request[qi] += j_request[ji]
        if j_inqueue[ji]:
            q_inqueue_minres[qi] += j_minres[ji]
    q_valid = np.zeros(Q, bool)
    q_valid[:nq] = True

    queues = QueueArrays(
        weight=q_weight, capability=q_cap, reclaimable=q_reclaimable,
        open=q_open, allocated=q_allocated, request=q_request,
        inqueue_minres=q_inqueue_minres, parent=q_parent, depth=q_depth,
        hier_weight=q_hier_w, valid=q_valid)

    snap = SnapshotArrays(
        nodes=nodes, tasks=tasks, jobs=jobs, queues=queues,
        namespace_weight=ns_weight,
        cluster_capacity=n_alloc[:nn].sum(axis=0) if nn else np.zeros(R, np.float32),
        template_rep=template_rep,
    )
    return snap, maps
