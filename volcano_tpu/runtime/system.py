"""The assembled control plane: API server + controllers + scheduler + a
kubelet simulator — the single-process equivalent of the reference's three
binaries (vc-scheduler, vc-controller-manager, vc-webhook-manager) against
one API server (SURVEY.md section 1 layer map), used for full-stack e2e
tests the way the reference uses a kind cluster (hack/run-e2e-kind.sh).
"""

from __future__ import annotations

import time
from typing import List, Optional

from ..api.batch import Command, Job
from ..api.core import Pod, PodPhase
from ..api.node_info import NodeInfo
from ..api.resource import Resource
from ..api.types import BusAction
from ..controllers import build_controllers
from ..framework.conf import SchedulerConfiguration, parse_conf
from ..framework.session import Session
from .apiserver import APIServer
from .cache import SchedulerCache


class VolcanoSystem:
    def __init__(self, conf: Optional[SchedulerConfiguration] = None):
        from .scheduler import Scheduler
        self.api = APIServer()
        self.controllers = build_controllers(self.api)
        self.cache = SchedulerCache(self.api)
        self.conf = conf or parse_conf()
        self.scheduler = Scheduler(self.cache, conf=self.conf)
        self._webhook_manager = None

    def start_webhook_manager(self, host: str = "127.0.0.1", port: int = 0):
        """Serve the admission webhooks over HTTP and self-register their
        configurations into the store — the vc-webhook-manager binary
        (cmd/webhook-manager/app/server.go:72-150). The in-process
        interception on api.create stays active either way; this exposes
        the NETWORK surface an external apiserver would call."""
        from ..webhooks.server import WebhookManager
        if self._webhook_manager is not None:
            bound = self._webhook_manager.address
            if (host, port) not in ((bound[0], bound[1]),
                                    (bound[0], 0), ("127.0.0.1", 0)):
                raise RuntimeError(
                    f"webhook manager already serving on {bound}; "
                    f"cannot rebind to {(host, port)}")
            return self._webhook_manager
        self._webhook_manager = WebhookManager(host, port, apiserver=self.api)
        self._webhook_manager.serve_in_thread()
        self._webhook_manager.register_webhooks()
        return self._webhook_manager

    def __getstate__(self):
        # the live HTTP server (sockets, thread locks) must not ride the
        # pickled state file (vcctl --state persistence)
        state = dict(self.__dict__)
        state["_webhook_manager"] = None
        return state

    # ------------------------------------------------------------ cluster
    def add_node(self, name: str, cpu="8", memory="16Gi", pods="110",
                 **kw) -> NodeInfo:
        node = NodeInfo(name, allocatable=Resource.from_resource_list(
            {"cpu": cpu, "memory": memory, "pods": pods}), **kw)
        self.api.create("nodes", node)
        return node

    # --------------------------------------------------------------- user
    def submit_job(self, job: Job) -> Job:
        """vcctl job run -> POST Job (admission webhooks run in create)."""
        return self.api.create("jobs", job)

    def submit_command(self, command: Command) -> None:
        self.api.create("commands", command)

    def suspend_job(self, name: str, namespace: str = "default") -> None:
        """vcctl job suspend -> bus Command AbortJob (pkg/cli/job/suspend.go)."""
        self.submit_command(Command(name=f"suspend-{name}-{time.time()}",
                                    namespace=namespace,
                                    action=BusAction.ABORT_JOB,
                                    target_name=name))

    def resume_job(self, name: str, namespace: str = "default") -> None:
        self.submit_command(Command(name=f"resume-{name}-{time.time()}",
                                    namespace=namespace,
                                    action=BusAction.RESUME_JOB,
                                    target_name=name))

    # ------------------------------------------------------------- engine
    def reconcile(self, rounds: int = 256) -> None:
        """Drain controller queues to empty (events cascade across
        controllers, so sweep until a full pass finds every queue empty).
        ``rounds`` is only a runaway-cascade backstop; hitting it warns
        instead of silently stalling mid-cascade."""
        for _ in range(rounds):
            busy = False
            for c in self.controllers:
                before = len(getattr(c, "queue", []) or [])
                c.process_all()
                busy = busy or before > 0
            if not busy:
                return
        import warnings
        warnings.warn(
            f"reconcile: controller queues still busy after {rounds} sweeps "
            "(event cascade did not converge)", stacklevel=2)

    @property
    def cycles(self) -> int:
        return self.scheduler.cycles

    def schedule_once(self) -> Session:
        """One scheduler cycle against the live store (runOnce)."""
        return self.scheduler.run_once()

    def kubelet_tick(self) -> int:
        """Bound pods start running (the kubelet's job)."""
        started = 0
        for pod in list(self.api.stores["pods"].values()):
            if pod.node_name and pod.phase == PodPhase.PENDING:
                pod.phase = PodPhase.RUNNING
                self.api.update("pods", pod)
                started += 1
        return started

    def finish_pod(self, pod_key: str, exit_code: int = 0) -> None:
        """Workload finishes: Succeeded on 0, Failed otherwise."""
        pod = self.api.get("pods", pod_key)
        if pod is None:
            return
        pod.exit_code = exit_code
        pod.phase = PodPhase.SUCCEEDED if exit_code == 0 else PodPhase.FAILED
        self.api.update("pods", pod)

    def tick(self) -> Session:
        """One full control-plane step: reconcile, schedule, kubelet,
        reconcile."""
        self.reconcile()
        ssn = self.schedule_once()
        self.kubelet_tick()
        self.reconcile()
        return ssn

    # -------------------------------------------------------------- views
    def job(self, name: str, namespace: str = "default") -> Optional[Job]:
        return self.api.get("jobs", f"{namespace}/{name}")

    def pods_of(self, name: str, namespace: str = "default") -> List[Pod]:
        return self.api.pods_of_job(f"{namespace}/{name}")
