"""The TPU scheduling sidecar: snapshot-in / decisions-out over a socket.

SURVEY.md section 5.8's distributed backbone for the north star: the
API-layer process (the Go-equivalent control plane) serializes its cluster
snapshot to this sidecar over the host network; the sidecar packs it with the
native C++ packer (native/packer.cc, VCS3 wire format), runs the compiled
TPU cycle, and streams the decision arrays back on the same connection. The
reference needs no such component because its scheduler computes in-process
(pkg/scheduler/scheduler.go:91 runOnce); here the compute lives on the TPU
host, so the cycle boundary is a wire protocol.

Framing (little-endian):
    request:  u32 magic 'VCR1' | u32 main_len | u32 extras_len |
              VCS4 snapshot buffer
              (native/wire.py serialize) | optional VCX1 extras frame
              (native/wire.py serialize_extras — host-computed session
              extras: node-affinity OR-group masks, preferred score rows,
              ports, volumes — so the served cycle is bit-identical to an
              in-process Session on the same conf)
    response: u32 status (0 ok) | u32 len | payload
        ok payload: u32 magic 'VCD1' | u32 T | u32 J |
                    i32[T] task_node | i32[T] task_mode | i32[T] task_gpu |
                    u8[J] job_ready | u8[J] job_pipelined
        error payload: u32 magic 'VCE1' | u32 code | UTF-8 message
                    (codes distinguish retryable from fatal; pre-VCE1
                    servers sent the bare message and clients still
                    accept that)

Pipelined rounds ('VCRQ') prepend an idempotency header (u32 epoch |
u32 seq) so a round replayed after a reconnect is served from the
server's response cache instead of double-dispatching; see
docs/architecture.md "Fault tolerance & degradation ladder".

One request per connection round; connections persist for many cycles.
"""

from __future__ import annotations

import socket
import socketserver
import struct
import threading
from collections import OrderedDict
from typing import Dict, Optional, Tuple

import numpy as np

from ..ops.allocate_scan import MODE_ALLOCATED, AllocateConfig, AllocateExtras
from ..telemetry import spans as _spans

DECISION_MAGIC = 0x31444356  # "VCD1"
REQUEST_MAGIC = 0x31524356   # "VCR1" — leads every request frame so a
#                              version-skewed peer fails fast instead of
#                              blocking on a misread length prefix
PIPELINE_MAGIC = 0x50524356  # "VCRP" — one-deep pipelined round: the
#                              response carries the PREVIOUS dispatched
#                              snapshot's decisions (T=0, J=0 primes the
#                              pipeline on the first round)
DRAIN_MAGIC = 0x44524356     # "VCRD" — drain the pending pipelined cycle
#                              (no snapshot payload)
FENCED_MAGIC = 0x46524356    # "VCRF" — HA fencing prefix (ISSUE 11):
#                              u32 magic | u32 lease_generation, followed
#                              by an ordinary request frame. The server
#                              admits the round only if the generation is
#                              >= the highest it has seen (admission
#                              ratchets the fence forward); an older token
#                              is a deposed leader's in-flight write and
#                              is answered ERR_NOT_LEADER without
#                              dispatching — the split-brain window can
#                              never double-dispatch a cycle.
SEQ_PIPELINE_MAGIC = 0x51524356  # "VCRQ" — pipelined round with an
#                              idempotency header (u32 epoch | u32 seq)
#                              ahead of the VCRP payload: the server caches
#                              the last response per client epoch, so a
#                              round REPLAYED after a reconnect (the client
#                              never saw the response) is served from cache
#                              instead of double-dispatching — the
#                              one-deep stream survives socket loss intact
ERROR_MAGIC = 0x31454356     # "VCE1" — structured error payload on
#                              status=1 frames: u32 magic | u32 code |
#                              utf-8 message. Lets clients distinguish
#                              retryable from fatal (the bare stringified
#                              exception of the old protocol could not).
# error codes (SidecarError.code)
ERR_BAD_REQUEST = 2      # fatal: framing/protocol/snapshot decode error
ERR_INTERNAL = 3         # retryable: the handler failed, state rolled back
ERR_BACKEND = 4          # retryable after degrade: the accelerator is gone
ERR_EMPTY_PIPELINE = 5   # benign: VCRD with nothing in flight
ERR_EPOCH_RESTORED = 6   # retryable: a seq>1 round named a stream epoch
#                          this (restarted) server never served — the
#                          client must adopt a fresh epoch and re-prime.
#                          Structured, so a restart storm costs each
#                          client one extra roundtrip instead of a
#                          timeout discovery per restart.
ERR_NOT_LEADER = 7       # structured, like ERR_EPOCH_RESTORED: a VCRF
#                          round presented a lease generation below the
#                          server's fence — the caller was deposed. The
#                          correct reaction is to stop writing (step
#                          down), not to resend with the same token; a
#                          RE-ELECTED caller retries with its new,
#                          higher generation and is admitted.
TENANT_MAGIC = 0x54524356  # "VCRT" — fleet tenancy prefix (ISSUE 12):
#                          u32 magic | u32 tenant_id, composable with the
#                          VCRF fence prefix (either order), followed by
#                          an ordinary request frame. Each tenant id gets
#                          its OWN serving stream — pipeline slot, VCRQ
#                          replay cache, known-epoch set — so interleaved
#                          tenants' one-deep streams can never hand one
#                          tenant another tenant's decisions. Absent
#                          prefix = tenant 0, the single-tenant protocol
#                          unchanged.
_u32 = struct.Struct("<I")


def tenant_wire_id(name: str) -> int:
    """Stable u32 wire id for a tenant name (sha256 prefix). 0 is
    reserved for the un-prefixed single-tenant stream; a name that
    hashes to 0 is nudged to 1."""
    import hashlib
    wid = int.from_bytes(
        hashlib.sha256(name.encode()).digest()[:4], "little")
    return wid or 1


class _TenantStream:
    """One tenant's serving stream: the depth-k pipeline ring (oldest
    first), the VCRQ replay cache, and the bounded known-epoch LRU. The
    sidecar keys these by the VCRT tenant word (0 = the legacy
    un-prefixed stream)."""

    __slots__ = ("ring", "staged", "round_cache", "known_epochs")

    def __init__(self):
        #: dispatched-but-unread cycles, oldest first; conf
        #: ``pipeline_depth`` bounds its length (1 = the one-deep slot)
        self.ring: list = []
        #: payloads of cycles retired EARLY (checkpoint, sibling-tenant
        #: dispatch), oldest first — always older than any ring entry,
        #: and handed out before the ring drains
        self.staged: list = []
        #: (epoch, seq, (status, payload)) of the last served VCRQ round
        self.round_cache: Optional[tuple] = None
        #: epoch -> True, LRU order (ISSUE 12 satellite: the unbounded
        #: set became a per-tenant LRU — evictions are counted, and a
        #: client whose idle epoch aged out simply re-primes, the same
        #: ERR_EPOCH_RESTORED path a restart takes)
        self.known_epochs: "OrderedDict[int, bool]" = OrderedDict()

    # depth-1 era compat: tests and the server's introspection keep the
    # single-slot names; "the pending cycle" is the ring's oldest entry
    @property
    def pending(self) -> Optional[dict]:
        return self.ring[0] if self.ring else None

    @pending.setter
    def pending(self, value: Optional[dict]) -> None:
        self.ring = [] if value is None else [value]

    @property
    def staged_payload(self) -> Optional[bytes]:
        return self.staged[0] if self.staged else None

    @staged_payload.setter
    def staged_payload(self, value: Optional[bytes]) -> None:
        self.staged = [] if value is None else [value]


class SidecarError(RuntimeError):
    """A status!=0 reply, decoded. ``retryable`` is the client's contract:
    resending the same round is safe (VCRQ rounds are idempotent via the
    server's replay cache; VCR1 rounds are value-idempotent)."""

    def __init__(self, code: int, message: str):
        super().__init__(f"sidecar error[{code}]: {message}")
        self.code = code
        self.message = message

    @property
    def retryable(self) -> bool:
        return self.code != ERR_BAD_REQUEST


def _error_payload(code: int, message: str) -> bytes:
    return (_u32.pack(ERROR_MAGIC) + _u32.pack(code)
            + message.encode("utf-8", "replace"))


def _classify_error(e: BaseException) -> int:
    """Map a handler exception to a wire error code."""
    from ..chaos.inject import ChaosError
    if isinstance(e, ChaosError) and e.kind == "backend_loss":
        return ERR_BACKEND
    name = type(e).__name__
    if name in ("XlaRuntimeError",) or "backend" in str(e).lower():
        return ERR_BACKEND
    if isinstance(e, (struct.error, ValueError, KeyError, IndexError)):
        return ERR_BAD_REQUEST
    return ERR_INTERNAL


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        buf += chunk
    return buf


def _send_frame(sock: socket.socket, status: int, payload: bytes) -> None:
    sock.sendall(_u32.pack(status) + _u32.pack(len(payload)) + payload)


class SchedulerSidecar:
    """Owns the jitted cycle; one instance per TPU process.

    With ``conf`` (a scheduler policy YAML, see conf/*.conf) the whole
    session policy — proportion/drf/hdrf extras included — compiles into the
    served program (framework/compiled_session.py); otherwise a bare
    allocate cycle with neutral extras runs under ``cfg``.
    """

    def __init__(self, cfg: Optional[AllocateConfig] = None,
                 conf: Optional[str] = None,
                 delta_uploads: Optional[bool] = None):
        import jax
        if cfg is not None and conf is not None:
            raise ValueError(
                "pass either cfg (bare allocate cycle) or conf (full "
                "compiled session policy), not both — conf carries its own "
                "action configuration")
        self._conf_mode = conf is not None
        if conf is not None:
            from ..framework.compiled_session import make_conf_cycle
            self._cycle = make_conf_cycle(conf)
        else:
            from ..ops.allocate_scan import make_allocate_cycle
            self.cfg = cfg or AllocateConfig(binpack_weight=1.0)
            self._cycle = make_allocate_cycle(self.cfg)
        #: shape signature -> (jitted fused fn, fuse) — the 3-buffer upload
        #: + single packed readback (ops/fused_io); per-leaf uploads cost
        #: ~tens of ms EACH over the axon tunnel, dominating the served
        #: cycle before compute even starts
        self._fused: Dict[tuple, tuple] = {}
        import os
        # device-resident delta path (ops/fused_io.DeltaKernel): the fused
        # buffers stay on the TPU across served cycles; each request ships
        # only the packed (indices, values) diff vs the mirror. Conf mode
        # honors the policy's `delta_uploads:` key; env
        # VOLCANO_SIDECAR_DELTA=0 and the constructor arg override.
        if delta_uploads is None:
            delta_uploads = os.environ.get("VOLCANO_SIDECAR_DELTA",
                                           "1") != "0"
            if conf is not None:
                from ..framework.conf import parse_conf as _pc
                delta_uploads = delta_uploads and _pc(conf).delta_uploads
        self.delta_uploads = bool(delta_uploads)
        # node-axis sharded serving (ISSUE 7): conf ``sharding: true`` (or
        # env VOLCANO_SIDECAR_SHARDING=1 in bare-cfg mode) runs the served
        # cycle as a ShardedDeltaKernel over a device mesh. Rides the
        # resident delta path, so delta_uploads off disables it too.
        self.sharding = os.environ.get("VOLCANO_SIDECAR_SHARDING") == "1"
        self._sharding_devices = None
        #: depth-k pipelined serving (conf ``pipeline_depth``, or
        #: $VOLCANO_SIDECAR_DEPTH in bare-cfg mode): up to k VCRP rounds
        #: in flight per tenant stream before a round's response carries
        #: a drained predecessor. Served rounds are never speculative —
        #: every dispatch consumes the client's own snapshot — so depth
        #: only moves WHEN readbacks happen, never what they contain;
        #: entries behind the head carry their dispatch-time mirror
        #: digest so the integrity check verifies each cycle against the
        #: mirror it actually ran against.
        self._pipeline_depth = max(1, int(os.environ.get(
            "VOLCANO_SIDECAR_DEPTH", "1")))
        if conf is not None:
            from ..framework.conf import parse_conf as _pcs
            _sc = _pcs(conf)
            self.sharding = self.sharding or bool(
                getattr(_sc, "sharding", False))
            self._sharding_devices = getattr(_sc, "sharding_devices", None)
            self._pipeline_depth = max(self._pipeline_depth,
                                       int(getattr(_sc, "pipeline_depth",
                                                   1) or 1))
        self.sharding = self.sharding and self.delta_uploads
        self._cycle_sharded_factory = None
        if self.sharding:
            # mesh-parameterized cycle factory: the mesh is picked per
            # shape bucket in _sharded_kernel, and the mesh-aware cycle
            # honors use_pallas via the shard-local candidate launch
            # (allocate_scan's sharded-pallas path) — no force-disable
            if conf is not None:
                from ..framework.compiled_session import make_conf_cycle \
                    as _mcc
                self._cycle_sharded_factory = (
                    lambda mesh: _mcc(conf, mesh=mesh))
            else:
                from ..ops.allocate_scan import make_allocate_cycle as _mac
                self._cycle_sharded_factory = (
                    lambda mesh: _mac(self.cfg, mesh=mesh))
        #: shape+mesh signature -> ShardedDeltaKernel (same residency and
        #: invalidation contract as _delta, per-shard residents)
        self._sharded_delta: Dict[tuple, object] = {}
        #: elastic-mesh bookkeeping (ISSUE 20): the health-registry
        #: generation the sharded caches were built under, and the last
        #: served mesh width (width-change event/gauge edge detector)
        self._health_gen_seen = 0
        self._mesh_width_served: Optional[int] = None
        #: shape signature -> DeltaKernel, plus per-kernel ResidentState —
        #: the sidecar owns the returned (donated) buffers; nothing may
        #: re-read a handle after a cycle consumed it (graphcheck donation
        #: family). Serialized by _serve_lock: resident buffers are
        #: process state, so concurrent connections must not interleave
        #: delta cycles.
        self._delta: Dict[tuple, object] = {}
        self._states: Dict[int, object] = {}
        self._serve_lock = threading.Lock()
        #: per-tenant serving streams (ISSUE 12), keyed by the VCRT wire
        #: word; tenant 0 is the legacy un-prefixed stream. Each stream
        #: carries the pipelined ring (the dispatched-but-unread cycles,
        #: up to conf ``pipeline_depth`` of them, whose decisions later
        #: rounds' responses carry in dispatch order), the
        #: VCRQ replay cache — (epoch, seq, (status, payload)) so a
        #: reconnected client resending the same seq gets the cached
        #: response instead of a double-dispatch — a bounded known-epoch
        #: LRU, and the staged payload slot (set when a checkpoint or a
        #: sibling tenant's dispatch retires the in-flight cycle early —
        #: early readback is decision-neutral; the payloads must still
        #: reach the client, oldest first). Only ONE TENANT holds
        #: dispatched-unread cycles at a time: any dispatch first retires
        #: every other stream's ring into its staged queue, and ring
        #: entries behind the head freeze their dispatch-time mirror
        #: digest, preserving the resident digest invariant the
        #: single-slot protocol had.
        self._streams: Dict[int, _TenantStream] = {0: _TenantStream()}
        #: per-tenant known-epoch LRU bound (satellite: the epoch set no
        #: longer grows without bound under client churn)
        self._epoch_cap = max(1, int(os.environ.get(
            "VOLCANO_SIDECAR_EPOCH_CAP", "64")))
        self._seq_lock = threading.Lock()
        #: served-round counter, arming per-round chaos faults
        self._rounds_served = 0
        #: HA fence (ISSUE 11): the highest lease generation any VCRF
        #: round has presented. Unfenced rounds (no VCRF prefix — the
        #: single-replica deployment) bypass the check entirely.
        self._fence_generation = 0
        #: digest-verified pre-crash mirrors (shape key -> host buffers)
        #: awaiting adoption by their shape bucket's first dispatch
        self._restored_mirrors: Dict[tuple, tuple] = {}
        #: policy identity stamped into checkpoints — a checkpoint taken
        #: under a different policy must not restore into this process
        from .checkpoint import conf_fingerprint
        self._ckpt_fingerprint = conf_fingerprint(
            conf if conf is not None else self.cfg)
        # opt-in persistent compilation cache ($VOLCANO_JAX_CACHE_DIR or
        # the conf's compilation_cache_dir): restarts stop paying compile_s
        from ..framework.compile_cache import enable_compilation_cache
        cache_dir = None
        if conf is not None:
            from ..framework.conf import parse_conf as _pc2
            cache_dir = _pc2(conf).compilation_cache_dir
        enable_compilation_cache(cache_dir)
        #: bounded ring of the last N served cycles (host timestamps,
        #: buffer sizes, cycle latency, in-graph telemetry when the conf
        #: enables it) — the sidecar half of the flight recorder
        import os
        from ..telemetry import FlightRecorder
        self.flight = FlightRecorder(
            capacity=int(os.environ.get("VOLCANO_FLIGHT_CYCLES", 64)))
        if conf is not None:
            from ..framework.conf import parse_conf
            self._conf_telemetry = bool(parse_conf(conf).telemetry)
        else:
            self._conf_telemetry = bool(self.cfg.telemetry)

    # --------------------------------------------- per-tenant streams
    def _stream(self, tenant: int) -> _TenantStream:
        """Get-or-create the serving stream for a VCRT tenant word.
        Caller holds _seq_lock or _serve_lock (or is single-threaded
        setup code)."""
        st = self._streams.get(tenant)
        if st is None:
            st = self._streams[tenant] = _TenantStream()
        return st

    def _note_epoch(self, st: _TenantStream, epoch: int) -> None:
        """Record a stream epoch in the tenant's LRU; evictions past the
        cap are counted, and an evicted epoch takes its replay-cache
        entry with it (a replay of an aged-out round must re-prime, not
        silently dispatch fresh under a stale seq)."""
        if epoch in st.known_epochs:
            st.known_epochs.move_to_end(epoch)
        else:
            st.known_epochs[epoch] = True
        while len(st.known_epochs) > self._epoch_cap:
            old, _ = st.known_epochs.popitem(last=False)
            from ..metrics import METRICS
            METRICS.inc("sidecar_replay_evictions_total")
            if st.round_cache is not None and st.round_cache[0] == old:
                st.round_cache = None

    # tenant-0 views: the single-tenant deployment's introspection
    # surface (tests, tooling) predates the VCRT streams and keeps
    # reading these names
    @property
    def _pending(self) -> Optional[dict]:
        return self._streams[0].pending

    @_pending.setter
    def _pending(self, value: Optional[dict]) -> None:
        self._streams[0].pending = value

    @property
    def _round_cache(self) -> Optional[tuple]:
        return self._streams[0].round_cache

    @_round_cache.setter
    def _round_cache(self, value: Optional[tuple]) -> None:
        self._streams[0].round_cache = value

    @property
    def _staged_payload(self) -> Optional[bytes]:
        return self._streams[0].staged_payload

    @_staged_payload.setter
    def _staged_payload(self, value: Optional[bytes]) -> None:
        self._streams[0].staged_payload = value

    @property
    def _known_epochs(self) -> set:
        return set(self._streams[0].known_epochs)

    def _build_tree(self, buf: bytes, extras_buf: bytes):
        """Wire buffers -> the cycle's argument tree + (snap, T, J)."""
        from ..native import available, pack_wire
        if available():
            snap = pack_wire(buf)
        else:  # pure-Python fallback keeps the sidecar usable without g++
            from ..native.pywire import pack_wire_py
            snap = pack_wire_py(buf)
        T = int(np.asarray(snap.tasks.status).shape[0])
        J = int(np.asarray(snap.jobs.min_available).shape[0])
        base = AllocateExtras.neutral(snap)
        if extras_buf:
            from ..framework.host_extras import (apply_affinity_sections,
                                                 apply_port_volume_sections)
            from ..native.pywire import decode_extras
            nt = int(np.asarray(snap.tasks.valid).sum())
            nn = int(np.asarray(snap.nodes.valid).sum())
            aff, pv = decode_extras(extras_buf, nt, nn)
            if aff is not None:
                apply_affinity_sections(base, aff, snap, nn)
            if pv is not None:
                apply_port_volume_sections(base, pv, snap)
        if self._conf_mode:
            # hdrf tree from the wire's queue annotations (tiny, early in
            # the buffer) — jobs attach via the decoded queue indices
            from ..native.pywire import decode_hierarchy
            second = decode_hierarchy(buf, np.asarray(snap.jobs.queue),
                                      np.asarray(snap.jobs.valid))
            tree_in = (snap, second, base)
        else:
            tree_in = (snap, base)
        return tree_in, snap, T, J

    def _sharded_kernel(self, tree_in):
        """The ShardedDeltaKernel serving this snapshot's shape bucket:
        mesh sized per the bucket's node axis (parallel/sharding
        .mesh_for_nodes), NamedShardings threaded through the served
        cycle with out_shardings == in_shardings across rounds.

        mesh_for_nodes consults the device-health registry (ISSUE 20), so
        after a quarantine or probation regrow this naturally serves on
        the survivors' mesh; what does NOT happen naturally is cleanup —
        kernels and residents compiled for the retired mesh would pin
        buffers on a quarantined device. On a registry generation change
        every sharded kernel + residency is pruned (the per-tenant client
        streams in self._streams keep their epochs: re-meshing is a
        serving-side detail, decision-neutral by the re-fuse-from-source
        argument). Caller holds _serve_lock."""
        from ..ops.fused_io import sharded_delta_cycle_cached
        from ..parallel.health import HEALTH
        from ..parallel.sharding import mesh_for_nodes, node_leaf_mask
        if self._health_gen_seen != HEALTH.generation:
            for k in self._sharded_delta.values():
                self._states.pop(id(k), None)
            self._sharded_delta.clear()
            self._health_gen_seen = HEALTH.generation
        n_nodes = int(np.asarray(tree_in[0].nodes.valid).shape[0])
        mesh = mesh_for_nodes(n_nodes, self._sharding_devices)
        width = int(mesh.devices.size)
        if width != self._mesh_width_served:
            if self._mesh_width_served is not None:
                from ..metrics import METRICS
                METRICS.set_gauge("mesh_width", None, width)
                _spans.log_event(
                    "mesh", source="sidecar", action="width_change",
                    mesh_devices=width, was=self._mesh_width_served)
            self._mesh_width_served = width
        return sharded_delta_cycle_cached(
            self._cycle_sharded_factory(mesh), tree_in, mesh,
            node_leaf_mask(tree_in), self._sharded_delta)

    def _dispatch_cycle(self, tree_in):
        """Dispatch the compiled cycle over the fused tree WITHOUT reading
        the decisions back, taking the device-resident delta path when
        enabled. Returns (packed device handle, "delta"|"full"|None,
        upload bytes|None, kernel|None, state|None) — kernel/state are the
        integrity-recovery context for the drain side. Caller holds
        _serve_lock."""
        from ..chaos.inject import seam
        seam("sidecar.dispatch", sidecar=self)
        if self.delta_uploads:
            from ..ops.fused_io import ResidentState, delta_cycle_cached
            if self.sharding:
                kernel = self._sharded_kernel(tree_in)
            else:
                kernel = delta_cycle_cached(self._cycle, tree_in,
                                            self._delta)
            state = self._states.get(id(kernel))
            if state is None:
                state = self._states[id(kernel)] = ResidentState()
                if self._restored_mirrors and not self.sharding:
                    # warm restart (runtime/checkpoint): a digest-verified
                    # pre-crash mirror for this shape bucket becomes the
                    # residency, so the first restored round ships a delta
                    # instead of the cold full upload. Sharded residents
                    # always cold-fuse (mesh placement isn't checkpointed).
                    from ..ops.fused_io import _shape_key
                    mir = self._restored_mirrors.pop(_shape_key(tree_in),
                                                     None)
                    if mir is not None:
                        from .checkpoint import adopt_mirror
                        adopt_mirror(state, mir)
            with _spans.span("sidecar.dispatch", cat="dispatch"):
                packed = kernel.run(state, tree_in)
            return (packed, state.last_kind, state.last_upload_bytes,
                    kernel, state)
        from ..ops.fused_io import fused_cycle_cached
        fn, fuse = fused_cycle_cached(self._cycle, tree_in, self._fused)
        with _spans.span("sidecar.dispatch", cat="dispatch"):
            return fn(*fuse(tree_in)), None, None, None, None

    def _verify_integrity(self, packed: np.ndarray, kernel, state, tree_in,
                          kind, upload, frozen_digest=None):
        """Strip + check the in-graph integrity digest against the host
        mirror; on mismatch recover in place (full re-fuse from the round's
        tree + recompute — decision-neutral). Caller holds _serve_lock.
        Returns (decisions, kind, upload).

        ``frozen_digest`` is the depth-k ring's mirror-identity rule: an
        entry that was dispatched behind other in-flight cycles verifies
        against the digest of the mirror AS OF ITS DISPATCH (later
        dispatches advanced the live mirror past it); the head-of-line /
        synchronous case passes None and keeps the live-mirror check,
        which is what lets the chaos mirror-drift fault trip at drain."""
        if kernel is None or not kernel.digest_words:
            return packed, kind, upload
        from ..chaos.inject import seam
        from ..metrics import METRICS
        seam("sidecar.complete", state=state)
        with _spans.span("sidecar.digest"):
            dec, dev_digest = kernel.split_digest(packed)
            host_digest = (frozen_digest if frozen_digest is not None
                           else kernel.mirror_digest(state))
        if host_digest is None or np.array_equal(dev_digest, host_digest):
            return dec, kind, upload
        METRICS.inc("resident_digest_mismatch_total")
        _spans.log_event("digest_trip", source="sidecar")
        with _spans.span("sidecar.recovery", cat="recovery"):
            packed = np.asarray(kernel.recover(state, tree_in),
                                dtype=np.int32)
            dec, _dig = kernel.split_digest(packed)
        METRICS.inc("cycle_recoveries_total",
                    labels={"reason": "digest", "mode": "refuse"})
        _spans.log_event("recovery", source="sidecar", reason="digest",
                         mode="refuse")
        return dec, "recovery", state.last_upload_bytes

    def _run_cycle(self, tree_in):
        """_dispatch_cycle + synchronous readback + integrity verify (the
        VCR1 path)."""
        packed, kind, upload, kernel, state = self._dispatch_cycle(tree_in)
        t_d = _spans.now()
        with _spans.span("sidecar.readback", cat="wait"):
            packed = np.asarray(packed, dtype=np.int32)
        _spans.device_window(t_d, _spans.now())
        return self._verify_integrity(packed, kernel, state, tree_in,
                                      kind, upload)

    @staticmethod
    def _decisions_payload(packed: np.ndarray, T: int, J: int) -> bytes:
        task_node = packed[:T]
        task_mode = packed[T:2 * T]
        task_gpu = packed[2 * T:3 * T]
        job_ready = packed[3 * T:3 * T + J].astype(np.uint8)
        job_pipelined = packed[3 * T + J:3 * T + 2 * J].astype(np.uint8)
        return b"".join([
            _u32.pack(DECISION_MAGIC), _u32.pack(T), _u32.pack(J),
            task_node.astype("<i4").tobytes(),
            task_mode.astype("<i4").tobytes(),
            task_gpu.astype("<i4").tobytes(),
            job_ready.tobytes(), job_pipelined.tobytes(),
        ])

    def warmup(self, buf: bytes, extras_buf: bytes = b"") -> None:
        """AOT warmup hook: compile the served cycle for this wire
        snapshot's shape bucket WITHOUT serving a decision round. With the
        persistent compilation cache enabled a restarted sidecar answers
        its first request at steady-state latency."""
        tree_in, _snap, _T, _J = self._build_tree(buf, extras_buf)
        with self._serve_lock:
            if self.delta_uploads and self.sharding:
                self._sharded_kernel(tree_in).warm()
            elif self.delta_uploads:
                from ..ops.fused_io import delta_cycle_cached
                delta_cycle_cached(self._cycle, tree_in, self._delta).warm()
            else:
                from ..ops.fused_io import (_TARGETS, fuse_spec,
                                            fused_cycle_cached, group_sizes)
                import jax
                fn, _fz = fused_cycle_cached(self._cycle, tree_in,
                                             self._fused)
                _td, spec = fuse_spec(tree_in)
                avals = tuple(jax.ShapeDtypeStruct((n,), _TARGETS[g])
                              for g, n in zip(("f", "i", "b"),
                                              group_sizes(spec)))
                fn.lower(*avals).compile()

    def schedule_buffer(self, buf: bytes, extras_buf: bytes = b"",
                        tenant: int = 0) -> bytes:
        """VCS4 snapshot buffer (+ optional VCX1 extras frame) -> VCD1
        decision payload. Every served cycle lands one snapshot in the
        flight-recorder ring (telemetry included when the conf enables
        it); the wire response stays the fixed-layout decision prefix, so
        version-skewed clients are unaffected."""
        payload, finish = self.schedule_buffer_deferred(buf, extras_buf,
                                                        tenant=tenant)
        finish()
        return payload

    def schedule_buffer_deferred(self, buf: bytes, extras_buf: bytes = b"",
                                 tenant: int = 0):
        """Like :meth:`schedule_buffer`, but returns ``(payload, finish)``
        so the server handler can SEND the decisions first and run
        ``finish()`` — the flight-recorder append and telemetry-tail decode
        — off the response critical path. ``finish`` must be called exactly
        once per served round."""
        import time as _time
        from ..chaos.inject import seam
        t_start = _time.time()
        self._rounds_served += 1
        seam("sidecar.round", round=self._rounds_served)
        with _spans.span("sidecar.build"):
            tree_in, snap, T, J = self._build_tree(buf, extras_buf)
        with self._serve_lock:
            # the tenant's own VCRP rounds must not be orphaned; sibling
            # tenants' in-flight cycles are retired into their staged
            # slots so their streams still receive them
            while self._drain_locked(self._stream(tenant)) is not None:
                pass
            self._retire_others_locked(tenant)
            packed, cycle_kind, upload_bytes = self._run_cycle(tree_in)
        cycle_ms = round((_time.time() - t_start) * 1000, 3)
        payload = self._decisions_payload(packed, T, J)

        def finish():
            tel = None
            if self._conf_telemetry and packed.shape[0] > 3 * T + 2 * J:
                # conf cycles pack job_attempted too (3T+3J prefix); the
                # telemetry tail follows it
                tail = 3 * T + 3 * J
                if packed.shape[0] > tail:
                    from ..telemetry import unpack_cycle_telemetry
                    R = int(np.asarray(snap.nodes.idle).shape[1])
                    tel = unpack_cycle_telemetry(packed[tail:], R)
            self.flight.record(
                buffer_bytes=len(buf) + len(extras_buf), tasks=T, jobs=J,
                cycle_ms=cycle_ms, cycle_kind=cycle_kind,
                upload_bytes=upload_bytes, telemetry=tel,
                spans=_spans.drain_cycle_summary())

        return payload, finish

    # ------------------------------------------- depth-k pipelined serving
    def _drain_entry_locked(self, st: _TenantStream) -> bytes:
        """Read back, verify, and payload the stream's OLDEST in-flight
        ring entry (caller holds _serve_lock, ring non-empty)."""
        pending = st.ring.pop(0)
        import time as _time
        with _spans.span("sidecar.drain", cat="wait"):
            packed = np.asarray(pending["packed"], dtype=np.int32)
        if pending.get("dispatched_at"):
            _spans.device_window(pending["dispatched_at"], _spans.now(),
                                 depth=pending.get("depth", 1))
        packed, kind, upload = self._verify_integrity(
            packed, pending["kernel"], pending["state"], pending["tree"],
            pending["kind"], pending["upload"],
            frozen_digest=pending.get("host_digest"))
        payload = self._decisions_payload(packed, pending["T"],
                                          pending["J"])
        self.flight.record(
            buffer_bytes=pending["buffer_bytes"], tasks=pending["T"],
            jobs=pending["J"], pipelined_round=True,
            cycle_ms=round((_time.time() - pending["t0"]) * 1000, 3),
            cycle_kind=kind, upload_bytes=upload,
            recovered=(kind == "recovery") or None,
            spans=_spans.drain_cycle_summary())
        return payload

    def _drain_locked(self, st: Optional[_TenantStream] = None) \
            -> Optional[bytes]:
        """Hand out the stream's oldest outstanding payload (caller holds
        _serve_lock): a staged payload first — a checkpoint, restore, or
        sibling tenant's dispatch retired those cycles early, so they
        predate everything in the ring — else the oldest ring entry's
        drain. Returns None when nothing is outstanding."""
        if st is None:
            st = self._streams[0]
        if st.staged:
            return st.staged.pop(0)
        if st.ring:
            return self._drain_entry_locked(st)
        return None

    def _retire_others_locked(self, tenant: int) -> None:
        """Early-readback every OTHER tenant's in-flight cycles before a
        dispatch, staging each payload for its own stream's next rounds
        (caller holds _serve_lock). Decision-neutral — a pending cycle's
        decisions were fixed at dispatch. Ring entries carry their
        dispatch-time mirror digest, but cross-tenant retirement also
        keeps the single-dispatched-unread invariant the head-of-line
        (live-digest) entries rely on."""
        for tid, st in self._streams.items():
            if tid != tenant:
                while st.ring:
                    st.staged.append(self._drain_entry_locked(st))

    def schedule_buffer_pipelined(self, buf: bytes,
                                  extras_buf: bytes = b"",
                                  tenant: int = 0) -> bytes:
        """Pipelined round (VCRP): dispatch THIS snapshot's cycle and
        return the decisions of the oldest outstanding round — the
        sidecar half of the cycle pipeline. With the default depth 1 that
        is the PREVIOUS dispatched snapshot's decisions; with conf
        ``pipeline_depth: k`` up to k rounds ride in flight, so the first
        k rounds prime the pipeline and return empty VCD1 payloads (T=0,
        J=0) and the caller runs k cycles behind. Call
        :meth:`drain_pending` (VCRD) repeatedly to retire the final
        in-flight cycles. Unlike the scheduler loop's depth-k ring these
        rounds are never speculative — each dispatch consumes the
        client's own snapshot — so depth changes only when decisions come
        back, never what they are."""
        import time as _time
        from ..chaos.inject import seam
        self._rounds_served += 1
        seam("sidecar.round", round=self._rounds_served)
        with _spans.span("sidecar.build"):
            tree_in, _snap, T, J = self._build_tree(buf, extras_buf)
        with self._serve_lock:
            st = self._stream(tenant)
            prev_payload = None
            if len(st.ring) + len(st.staged) >= self._pipeline_depth:
                prev_payload = self._drain_locked(st)
            self._retire_others_locked(tenant)
            packed, kind, upload, kernel, state = \
                self._dispatch_cycle(tree_in)
            # mirror-identity rule: an entry that will sit behind other
            # in-flight cycles freezes the digest of the mirror it ran
            # against; the depth-1 slot keeps None -> live-mirror check
            hdig = None
            if self._pipeline_depth > 1 and kernel is not None \
                    and getattr(kernel, "digest_words", 0):
                hdig = kernel.mirror_digest(state)
            st.ring.append(dict(packed=packed, T=T, J=J, kind=kind,
                                upload=upload, t0=_time.time(),
                                buffer_bytes=len(buf) + len(extras_buf),
                                kernel=kernel, state=state, tree=tree_in,
                                dispatched_at=_spans.now(),
                                host_digest=hdig,
                                depth=self._pipeline_depth))
        if prev_payload is None:
            # priming round: an explicit empty decision payload
            prev_payload = self._decisions_payload(
                np.zeros(0, np.int32), 0, 0)
        return prev_payload

    def schedule_buffer_seq(self, epoch: int, seq: int, buf: bytes,
                            extras_buf: bytes = b"",
                            tenant: int = 0) -> Tuple[int, bytes]:
        """One idempotent pipelined round (VCRQ): like
        :meth:`schedule_buffer_pipelined`, but keyed by the client's
        (epoch, seq) within the tenant's stream. Returns
        ``(status, payload)``.

        - A REPLAYED round (same epoch+seq as the cached one) is served
          from the cache without touching the pipeline — the reconnect
          contract: a client that never read its response resends the
          same seq and the stream continues exactly where it was.
        - A NEW epoch means a new client stream: the previous stream's
          pending cycle is retired (drained and discarded) first, so the
          fresh stream primes cleanly instead of inheriting a stale
          cycle (the drain-on-reconnect rule).
        - A failed round caches its error frame too, so the replay of a
          failed round reports the same failure instead of
          double-dispatching."""
        with self._seq_lock:
            st = self._stream(tenant)
            cached = st.round_cache
            if cached is not None and cached[0] == epoch \
                    and cached[1] == seq:
                from ..metrics import METRICS
                METRICS.inc("sidecar_replayed_rounds_total")
                return cached[2]
            if cached is not None and cached[0] != epoch:
                # retire the stale stream's cycles (drain-on-reconnect)
                while self.drain_pending(tenant) is not None:
                    pass
            if seq > 1 and epoch not in st.known_epochs:
                # mid-stream round from a stream this process never
                # served: we restarted without checkpoint state under the
                # client's feet (or the epoch aged out of the LRU). Say
                # so in-band (retryable) — the client adopts a fresh
                # epoch and re-primes in one roundtrip. Not cached: the
                # client abandons this epoch.
                from ..metrics import METRICS
                METRICS.inc("sidecar_epoch_restored_total",
                            labels={"side": "server"})
                return (1, _error_payload(
                    ERR_EPOCH_RESTORED,
                    f"stream epoch {epoch} unknown after restart; "
                    f"re-prime with a new epoch"))
            self._note_epoch(st, epoch)
            try:
                payload = self.schedule_buffer_pipelined(buf, extras_buf,
                                                         tenant=tenant)
                resp = (0, payload)
            except Exception as e:  # cache the failure for the replay
                resp = (1, _error_payload(_classify_error(e), str(e)))
            st.round_cache = (epoch, seq, resp)
            return resp

    def drain_pending(self, tenant: int = 0) -> Optional[bytes]:
        """Retire the tenant's in-flight pipelined cycle (VCRD). Returns
        its VCD1 payload, or None when the pipeline is empty."""
        with self._serve_lock:
            return self._drain_locked(self._stream(tenant))

    # ----------------------------------------- crash-consistent restarts
    def checkpoint(self, path: str) -> dict:
        """Serialize the sidecar's host-side truth to ``path`` (atomic
        tmp+fsync+rename; runtime/checkpoint.py): the VCRQ replay cache
        and seq watermarks, known stream epochs, the in-flight cycle's
        decisions, cumulative metrics, and the digest-stamped resident
        mirrors. The pending cycle is read back early — decision-neutral
        (its decisions were fixed at dispatch) — and its payload STAGED,
        both in the checkpoint and in-process, so the client's next round
        still receives it."""
        from . import checkpoint as ckpt
        with self._seq_lock:
            with self._serve_lock:
                for st in self._streams.values():
                    # retire the whole ring, oldest first, behind any
                    # payloads staged earlier (they predate the ring)
                    while st.ring:
                        st.staged.append(self._drain_entry_locked(st))
                mirrors = ckpt.mirror_records(self._delta, self._states)
            st0 = self._streams[0]
            # tenant 0 keeps the legacy top-level keys, so pre-fleet
            # checkpoints restore unchanged and pre-fleet readers of a
            # fleet checkpoint still see the un-prefixed stream (its
            # oldest staged payload; staged_payloads carries the rest of
            # a depth-k ring)
            state = dict(
                conf_fingerprint=self._ckpt_fingerprint,
                round_cache=st0.round_cache,
                rounds_served=self._rounds_served,
                known_epochs=sorted(st0.known_epochs),
                pending_payload=st0.staged_payload,
                staged_payloads=list(st0.staged),
                fence_generation=self._fence_generation,
                tenant_streams={
                    tid: dict(round_cache=st.round_cache,
                              known_epochs=sorted(st.known_epochs),
                              pending_payload=st.staged_payload,
                              staged_payloads=list(st.staged))
                    for tid, st in self._streams.items() if tid != 0},
                metrics=ckpt.metrics_snapshot(),
            )
        return ckpt.write_checkpoint(path, "sidecar", state,
                                     mirrors=mirrors)

    def restore(self, path: str) -> str:
        """Reload a checkpoint into this (fresh) sidecar. Returns the
        restore-ladder outcome (``restored`` | ``cold`` | ``fallback`` —
        the latter two leave this process a correct fresh-fuse cold
        start; clients discover it via ERR_EPOCH_RESTORED and re-prime).
        On success the replay cache, epoch set, and staged decisions
        resume the stream exactly where the crash cut it, and each
        resident mirror is re-verified against its stamped PR 5 digest
        words before the next dispatch adopts it onto the device."""
        import time as _time
        from . import checkpoint as ckpt
        t0 = _time.time()
        with _spans.span("cycle.restore", cat="recovery"):
            env, reason = ckpt.load_checkpoint(path, "sidecar")
            if env is None:
                outcome = "cold" if reason == "missing" else "fallback"
                ckpt.record_restore(outcome, reason, "sidecar",
                                    (_time.time() - t0) * 1000)
                return outcome
            state = env["state"]
            if state.get("conf_fingerprint") != self._ckpt_fingerprint:
                ckpt.record_restore("fallback", "conf_mismatch", "sidecar",
                                    (_time.time() - t0) * 1000)
                return "fallback"
            with self._seq_lock:
                with self._serve_lock:
                    self._streams = {0: _TenantStream()}

                    def _staged(rec):
                        # depth-k checkpoints list every retired payload;
                        # pre-depth ones carry at most the single slot
                        sp = rec.get("staged_payloads")
                        if sp is not None:
                            return list(sp)
                        pp = rec.get("pending_payload")
                        return [pp] if pp is not None else []

                    st0 = self._streams[0]
                    st0.round_cache = state["round_cache"]
                    st0.staged = _staged(state)
                    for e in state["known_epochs"]:
                        st0.known_epochs[e] = True
                    # pre-fleet checkpoints carry no tenant_streams key;
                    # they restore as the bare tenant-0 stream
                    for tid, rec in (state.get("tenant_streams")
                                     or {}).items():
                        st = self._stream(int(tid))
                        st.round_cache = rec.get("round_cache")
                        st.staged = _staged(rec)
                        for e in rec.get("known_epochs", ()):
                            st.known_epochs[e] = True
                    self._rounds_served = int(state["rounds_served"])
                    # pre-fence checkpoints restore with the fence open
                    self._fence_generation = int(
                        state.get("fence_generation", 0))
                    self._restored_mirrors = ckpt.verify_mirrors(
                        env.get("mirrors"))
                    ckpt.merge_metrics(state.get("metrics"))
        ckpt.record_restore("restored", "ok", "sidecar",
                            (_time.time() - t0) * 1000)
        return "restored"

    def fence_admit(self, generation: int) -> bool:
        """Admit-or-reject a VCRF round's fencing token. Admission
        ratchets the fence forward (the newly elected leader's first
        round deposes every older token); rejection is the permanent
        ERR_NOT_LEADER verdict for that token."""
        with self._seq_lock:
            if generation < self._fence_generation:
                from ..metrics import METRICS
                METRICS.inc("sidecar_not_leader_total")
                _spans.log_event("sidecar_fence_reject",
                                 presented=int(generation),
                                 fence=int(self._fence_generation))
                return False
            self._fence_generation = int(generation)
            return True

    def wait_idle(self) -> bool:
        """Block until every in-flight pipelined cycle's device work is
        done WITHOUT draining it. Production serving gets this wait for
        free from the API layer's schedule period; bench calls it
        explicitly so the measured round isolates the serving path from
        raw compute."""
        pendings = [e for st in self._streams.values() for e in st.ring]
        if not pendings:
            return False
        import jax
        for pending in pendings:
            jax.block_until_ready(pending["packed"])
        return True


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        while True:
            try:
                (magic,) = _u32.unpack(_recv_exact(self.request, 4))
            except (ConnectionError, OSError):
                return
            fence_ok = True
            tenant = 0
            # prefix words (composable, either order): VCRF carries the HA
            # fencing generation, VCRT the fleet tenant id; each reads one
            # u32 operand, then the real frame follows. The inner frame is
            # ALWAYS read fully (framing must stay aligned); a stale fence
            # token skips the dispatch, not the read.
            while magic in (FENCED_MAGIC, TENANT_MAGIC):
                prefix = magic
                try:
                    (word,) = _u32.unpack(_recv_exact(self.request, 4))
                    (magic,) = _u32.unpack(_recv_exact(self.request, 4))
                except (ConnectionError, OSError):
                    return
                if prefix == FENCED_MAGIC:
                    fence_ok = self.server.sidecar.fence_admit(word)
                else:
                    tenant = word
            if magic == DRAIN_MAGIC:
                if not fence_ok:
                    _send_frame(self.request, 1, _error_payload(
                        ERR_NOT_LEADER, "fencing token superseded"))
                    continue
                # drain-only round: retire the tenant's pending cycle
                try:
                    payload = self.server.sidecar.drain_pending(tenant)
                except Exception as e:
                    _send_frame(self.request, 1, _error_payload(
                        _classify_error(e), str(e)))
                    continue
                if payload is None:
                    _send_frame(self.request, 1, _error_payload(
                        ERR_EMPTY_PIPELINE, "pipeline empty"))
                else:
                    _send_frame(self.request, 0, payload)
                continue
            if magic not in (REQUEST_MAGIC, PIPELINE_MAGIC,
                             SEQ_PIPELINE_MAGIC):
                # old/foreign framing: reply with a structured fatal error
                # and drop the connection rather than misreading lengths
                # and hanging
                _send_frame(self.request, 1, _error_payload(
                    ERR_BAD_REQUEST,
                    "bad request magic (expected VCR1 framing)"))
                return
            try:
                epoch = seq = None
                if magic == SEQ_PIPELINE_MAGIC:
                    (epoch,) = _u32.unpack(_recv_exact(self.request, 4))
                    (seq,) = _u32.unpack(_recv_exact(self.request, 4))
                (n,) = _u32.unpack(_recv_exact(self.request, 4))
                (nx,) = _u32.unpack(_recv_exact(self.request, 4))
                buf = _recv_exact(self.request, n)
                extras = _recv_exact(self.request, nx) if nx else b""
                if not fence_ok:
                    # deposed leader: the frame was consumed, the round is
                    # NOT dispatched — the structured verdict replaces a
                    # would-be split-brain double-dispatch
                    _send_frame(self.request, 1, _error_payload(
                        ERR_NOT_LEADER, "fencing token superseded"))
                    continue
                if magic == SEQ_PIPELINE_MAGIC:
                    status, payload = self.server.sidecar \
                        .schedule_buffer_seq(epoch, seq, buf, extras,
                                             tenant=tenant)
                    _send_frame(self.request, status, payload)
                    continue
                if magic == PIPELINE_MAGIC:
                    payload = self.server.sidecar \
                        .schedule_buffer_pipelined(buf, extras,
                                                   tenant=tenant)
                    _send_frame(self.request, 0, payload)
                    continue
                # send the decisions first; the flight-recorder append and
                # telemetry decode run after the client is unblocked
                payload, finish = self.server.sidecar \
                    .schedule_buffer_deferred(buf, extras, tenant=tenant)
                _send_frame(self.request, 0, payload)
                finish()
            except (ConnectionError, OSError):
                return
            except Exception as e:
                # report a STRUCTURED error and keep serving: the handler
                # never leaks partial state onto the wire, and the client
                # can tell a retryable failure from a fatal one
                _send_frame(self.request, 1, _error_payload(
                    _classify_error(e), str(e)))


class SidecarServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 cfg: Optional[AllocateConfig] = None,
                 conf: Optional[str] = None):
        self.sidecar = SchedulerSidecar(cfg, conf=conf)
        super().__init__((host, port), _Handler)

    @property
    def address(self) -> Tuple[str, int]:
        return self.server_address[:2]

    def serve_in_thread(self) -> threading.Thread:
        t = threading.Thread(target=self.serve_forever, daemon=True)
        t.start()
        return t


_CLIENT_EPOCHS = __import__("itertools").count(1)


class SidecarClient:
    """The API-layer half: ships ClusterInfo snapshots, maps decisions back
    to task/job uids (the Binder seam's input).

    Hardened (ISSUE 5): connection establishment and reconnects go through
    a capped-exponential-backoff-with-jitter helper (runtime/backoff); a
    socket failure mid-round reconnects and RESENDS the same frame —
    synchronous rounds are value-idempotent (the delta diff of an
    unchanged snapshot is empty), and pipelined rounds use the VCRQ
    idempotency header so the server replays the cached response instead
    of double-dispatching. ``call_timeout`` bounds each send/recv
    separately from the (long) connect timeout, so a hung sidecar
    surfaces as a timeout instead of a stuck API layer.
    """

    def __init__(self, host: str, port: int, timeout: float = 120.0,
                 conf=None, call_timeout: Optional[float] = None,
                 backoff=None, reconnect: bool = True,
                 epoch: Optional[int] = None,
                 endpoints=None, fence_token: Optional[int] = None,
                 tenant_id=None):
        """``conf`` (YAML text or SchedulerConfiguration) should match the
        server's --scheduler-conf: the client computes the host extras the
        conf needs (affinity masks, ports, volumes) and ships them in the
        VCX1 frame — the API-layer process owns the objects, so it owns
        the object-walking half of the cycle.

        HA (ISSUE 11): ``endpoints`` is an ordered ``[(host, port), ...]``
        list of replica sidecars; a connect failure rotates to the next
        endpoint (``sidecar_failovers_total``) and, because the new
        server holds none of the old stream's state, adopts a fresh
        epoch and re-primes — a sidecar failover costs the stream one
        priming round, the same bill as a server restart. ``fence_token``
        (the caller's lease generation) wraps every frame in a VCRF
        prefix; a deposed caller's rounds come back ERR_NOT_LEADER.

        Fleet tenancy (ISSUE 12): ``tenant_id`` — a u32 wire id, or a
        tenant name hashed through :func:`tenant_wire_id` — wraps every
        frame in a VCRT prefix, so this client's pipelined stream, replay
        cache, and epochs live in the server's per-tenant stream instead
        of the shared tenant-0 slot. None speaks the single-tenant
        protocol unchanged."""
        from ..framework.conf import parse_conf
        from .backoff import Backoff
        self.conf = (parse_conf(conf) if isinstance(conf, str) else conf)
        self.endpoints = ([(h, int(p)) for h, p in endpoints]
                          if endpoints else [(host, port)])
        self._endpoint_i = 0
        self.fence_token = fence_token
        self.tenant_id = (tenant_wire_id(tenant_id)
                          if isinstance(tenant_id, str) else tenant_id)
        self.host, self.port = self.endpoints[0]
        self.connect_timeout = timeout
        #: per-call send/recv timeout; None keeps the connect timeout
        self.call_timeout = call_timeout
        self.backoff = backoff if backoff is not None else Backoff()
        self.reconnect = reconnect
        #: client stream epoch for the VCRQ idempotency header: unique per
        #: client instance, so the server can tell a reconnecting client
        #: (same epoch: replay) from a new one (new epoch: drain the stale
        #: pipelined cycle first)
        self._epoch = (int(epoch) if epoch is not None
                       else ((__import__("os").getpid() << 16)
                             ^ next(_CLIENT_EPOCHS)) & 0xFFFFFFFF)
        self._seq = 0
        self.sock = self._connect()
        #: uid maps of the snapshot whose decisions the NEXT pipelined
        #: response will carry (the client-side half of the one-deep
        #: pipeline: decisions arrive one round late, so they decode with
        #: the maps of the round that produced them)
        self._pipeline_maps = None

    def _connect(self) -> socket.socket:
        """Establish the connection through the backoff helper (a refused
        or flaky endpoint is retried with capped exponential delays +
        jitter instead of failing the constructor on the first miss).
        With a multi-endpoint list, each failed attempt ROTATES to the
        next endpoint, so the backoff retries walk the replica set; a
        connection landing on a DIFFERENT endpoint than the last live one
        is a failover — the new server holds none of the old stream's
        pipelined state, so the client adopts a fresh epoch and lets the
        next pipelined round re-prime (one round lost, never a
        double-dispatch)."""
        def connect_once():
            host, port = self.endpoints[self._endpoint_i
                                        % len(self.endpoints)]
            try:
                sock = socket.create_connection(
                    (host, port), timeout=self.connect_timeout)
            except OSError:
                self._endpoint_i += 1   # next attempt, next replica
                raise
            sock.settimeout(self.call_timeout
                            if self.call_timeout is not None
                            else self.connect_timeout)
            if (host, port) != (self.host, self.port):
                from ..metrics import METRICS
                METRICS.inc("sidecar_failovers_total")
                _spans.log_event("sidecar_failover",
                                 endpoint=f"{host}:{port}",
                                 prev=f"{self.host}:{self.port}")
                self.host, self.port = host, port
                self._epoch = ((__import__("os").getpid() << 16)
                               ^ next(_CLIENT_EPOCHS)) & 0xFFFFFFFF
                self._seq = 0
                self._pipeline_maps = None
            return sock
        return self.backoff.call(connect_once)

    def _reconnect(self) -> None:
        from ..metrics import METRICS
        try:
            self.sock.close()
        except OSError:
            pass
        self.sock = self._connect()
        METRICS.inc("sidecar_reconnects_total")

    def _roundtrip(self, frame: bytes) -> bytes:
        """Send one framed request and read the reply; on socket failure
        reconnect with backoff and resend the SAME frame. A structured
        server error (SidecarError) is NOT a socket failure and
        propagates immediately."""
        from ..chaos.inject import seam
        attempt = 0
        while True:
            try:
                seam("sidecar.client_send", client=self, frame=frame)
                self.sock.sendall(frame)
                seam("sidecar.client_recv", client=self)
                return self._recv_payload()
            except SidecarError:
                raise
            except (OSError, ConnectionError) as e:
                attempt += 1
                if not self.reconnect or attempt >= self.backoff.attempts:
                    raise
                import time as _time
                _time.sleep(self.backoff.delay(attempt - 1))
                try:
                    self._reconnect()
                except OSError as e2:
                    raise ConnectionError(
                        f"sidecar unreachable after {attempt} tries: "
                        f"{e2}") from e

    def close(self) -> None:
        self.sock.close()

    def _recv_payload(self) -> bytes:
        (status,) = _u32.unpack(_recv_exact(self.sock, 4))
        (n,) = _u32.unpack(_recv_exact(self.sock, 4))
        payload = _recv_exact(self.sock, n)
        if status != 0:
            if len(payload) >= 8 \
                    and _u32.unpack(payload[:4])[0] == ERROR_MAGIC:
                (code,) = _u32.unpack(payload[4:8])
                raise SidecarError(code, payload[8:].decode("utf-8",
                                                            "replace"))
            # pre-VCE1 server: a bare stringified exception
            raise SidecarError(ERR_INTERNAL, payload.decode("utf-8",
                                                            "replace"))
        return payload

    @staticmethod
    def _decode(payload: bytes, maps) -> Dict[str, object]:
        (magic,) = _u32.unpack(payload[:4])
        if magic != DECISION_MAGIC:
            raise ValueError("bad decision magic")
        T, J = struct.unpack("<II", payload[4:12])
        off = 12
        task_node = np.frombuffer(payload, "<i4", T, off); off += 4 * T
        task_mode = np.frombuffer(payload, "<i4", T, off); off += 4 * T
        task_gpu = np.frombuffer(payload, "<i4", T, off); off += 4 * T
        job_ready = np.frombuffer(payload, "u1", J, off).astype(bool)
        off += J
        job_pipelined = np.frombuffer(payload, "u1", J, off).astype(bool)
        binds = {}
        for uid, ti in maps.task_index.items():
            if task_mode[ti] == MODE_ALLOCATED:
                binds[uid] = (maps.node_names[task_node[ti]],
                              int(task_gpu[ti]))
        return {
            "binds": binds,
            "task_node": task_node, "task_mode": task_mode,
            "task_gpu": task_gpu, "job_ready": job_ready,
            "job_pipelined": job_pipelined, "maps": maps,
        }

    def _fence_prefix(self) -> bytes:
        """The VCRF wrapper for every frame when a fencing token is set
        (the HA deployment); empty otherwise — single-replica clients
        speak the unfenced protocol unchanged."""
        if self.fence_token is None:
            return b""
        return _u32.pack(FENCED_MAGIC) + _u32.pack(
            int(self.fence_token) & 0xFFFFFFFF)

    def _tenant_prefix(self) -> bytes:
        """The VCRT wrapper for every frame when a tenant id is set (the
        fleet deployment); empty otherwise — single-tenant clients speak
        the un-prefixed protocol unchanged."""
        if self.tenant_id is None:
            return b""
        return _u32.pack(TENANT_MAGIC) + _u32.pack(
            int(self.tenant_id) & 0xFFFFFFFF)

    def _prefixes(self) -> bytes:
        return self._fence_prefix() + self._tenant_prefix()

    def _snapshot_frame(self, ci, magic: int, header: bytes = b""):
        from ..native.wire import serialize, serialize_extras
        buf, maps = serialize(ci)
        extras = (serialize_extras(ci, maps, self.conf)
                  if self.conf is not None else b"")
        frame = (self._prefixes() + _u32.pack(magic) + header
                 + _u32.pack(len(buf)) + _u32.pack(len(extras))
                 + buf + extras)
        return frame, maps

    def schedule(self, ci) -> Dict[str, object]:
        frame, maps = self._snapshot_frame(ci, REQUEST_MAGIC)
        return self._decode(self._roundtrip(frame), maps)

    def schedule_pipelined(self, ci) -> Optional[Dict[str, object]]:
        """One-deep pipelined round: ship this snapshot, receive the
        PREVIOUS round's decisions (decoded with the maps of the round
        that produced them). Returns None on the priming round; finish a
        stream with :meth:`drain_pipelined`.

        Rounds go out as VCRQ (epoch + monotonically increasing seq): a
        round resent after a reconnect is replayed from the server's
        cache, so the one-deep stream survives socket loss with no
        double-applied cycle. If the SERVER lost its pipeline (restart:
        the cache is cold and the pipeline empty), the response degrades
        to a priming empty payload — this round returns None and the
        stream re-primes, which is the drain-on-reconnect rule's client
        half."""
        self._seq += 1
        frame, maps = self._snapshot_frame(
            ci, SEQ_PIPELINE_MAGIC,
            header=_u32.pack(self._epoch) + _u32.pack(self._seq))
        try:
            payload = self._roundtrip(frame)
        except SidecarError as e:
            if e.code != ERR_EPOCH_RESTORED:
                raise
            # the server restarted without our stream's state: adopt a
            # fresh epoch and re-prime with this same snapshot NOW — one
            # extra roundtrip per restart instead of an error surfaced to
            # the caller or a timeout discovery. The in-flight cycle's
            # decisions died with the old server (drain-on-reconnect).
            from ..metrics import METRICS
            METRICS.inc("sidecar_epoch_restored_total",
                        labels={"side": "client"})
            self._epoch = ((__import__("os").getpid() << 16)
                           ^ next(_CLIENT_EPOCHS)) & 0xFFFFFFFF
            self._seq = 1
            frame, maps = self._snapshot_frame(
                ci, SEQ_PIPELINE_MAGIC,
                header=_u32.pack(self._epoch) + _u32.pack(self._seq))
            self._roundtrip(frame)
            self._pipeline_maps = maps
            return None
        prev_maps, self._pipeline_maps = self._pipeline_maps, maps
        T, J = struct.unpack("<II", payload[4:12])
        if prev_maps is None or (T == 0 and J == 0):
            return None
        return self._decode(payload, prev_maps)

    def drain_pipelined(self) -> Optional[Dict[str, object]]:
        """Retire the in-flight pipelined round (VCRD). Returns None when
        nothing is in flight — including a server that lost its pipeline
        (restart), which the structured ERR_EMPTY_PIPELINE code makes
        distinguishable from a real failure."""
        if self._pipeline_maps is None:
            return None
        try:
            payload = self._roundtrip(self._prefixes()
                                      + _u32.pack(DRAIN_MAGIC))
        except SidecarError as e:
            if e.code == ERR_EMPTY_PIPELINE:
                self._pipeline_maps = None
                return None
            raise
        maps, self._pipeline_maps = self._pipeline_maps, None
        return self._decode(payload, maps)


def main(argv=None) -> int:
    """`python -m volcano_tpu.runtime.sidecar` — the standalone binary the
    API layer points its scheduling cycle at."""
    import argparse
    parser = argparse.ArgumentParser(description="TPU scheduling sidecar")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=9099)
    parser.add_argument("--binpack-weight", type=float, default=1.0)
    parser.add_argument("--scheduler-conf", default=None,
                        help="policy YAML (conf/*.conf); compiles the full "
                             "session policy into the served program")
    parser.add_argument("--checkpoint-path", default=None,
                        help="crash-consistent checkpoint file: restored "
                             "at startup, written every --checkpoint-every "
                             "seconds and at clean shutdown")
    parser.add_argument("--checkpoint-every", type=float, default=30.0,
                        help="seconds between periodic checkpoints "
                             "(0 disables the periodic writer)")
    parser.add_argument("--supervise", type=int, default=0, metavar="N",
                        help="crash-loop supervisor: restart a crashed "
                             "serve loop up to N times with capped "
                             "backoff, restoring from --checkpoint-path")
    args = parser.parse_args(argv)
    conf_text = None
    if args.scheduler_conf:
        with open(args.scheduler_conf) as f:
            conf_text = f.read()
    # conf carries the whole policy, so --binpack-weight only applies to the
    # bare-cycle mode (passing both would silently drop the flag otherwise)
    cfg = (None if conf_text is not None
           else AllocateConfig(binpack_weight=args.binpack_weight))

    def serve_once():
        server = SidecarServer(args.host, args.port, cfg, conf=conf_text)
        if args.checkpoint_path:
            server.sidecar.restore(args.checkpoint_path)
        stop = threading.Event()
        if args.checkpoint_path and args.checkpoint_every > 0:
            def periodic():
                while not stop.wait(args.checkpoint_every):
                    try:
                        server.sidecar.checkpoint(args.checkpoint_path)
                    except Exception:
                        pass  # fail-soft: a failed write must not stop serving
            threading.Thread(target=periodic, daemon=True).start()
        print(f"sidecar listening on "
              f"{server.address[0]}:{server.address[1]}")
        try:
            server.serve_forever()
        finally:
            stop.set()
            if args.checkpoint_path:  # clean-shutdown checkpoint
                try:
                    server.sidecar.checkpoint(args.checkpoint_path)
                except Exception:
                    pass
            server.server_close()

    try:
        if args.supervise > 0:
            from .checkpoint import CrashLoopSupervisor
            CrashLoopSupervisor(serve_once,
                                max_restarts=args.supervise).run()
        else:
            serve_once()
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
