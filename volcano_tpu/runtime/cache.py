"""Scheduler cache: project API-server objects into a ClusterInfo snapshot.

Reference: pkg/scheduler/cache/cache.go:71-917 + event_handlers.go:43-740 —
the informer-fed mirror whose Snapshot() the session consumes. Two paths:

- ``snapshot()`` rebuilds the projection from the stores (the deep-copy
  Snapshot semantics, cache.go:712-811) — the oracle.
- ``live_view()`` + ``drain_dirty()`` serve the scheduler's persistent
  session from a mirror ClusterInfo that watch event handlers patch in
  place, exactly like AddPod/UpdatePod/DeletePod and friends maintain the
  reference's cache between cycles (event_handlers.go:43-740). Entity-set
  or node-gating changes mark the mirror structural, forcing a rebuild —
  the safe analog of the reference re-listing on informer resync.

bind/evict write back to pods exactly like the defaultBinder/defaultEvictor
REST calls (cache.go:123-175).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..api import (ClusterInfo, JobInfo, NodeInfo, QueueInfo, Resource,
                   TaskInfo, TaskStatus)
from ..api.core import Pod, PodGroup, PodPhase
from ..api.queue_info import NamespaceInfo
from ..api.types import DEFAULT_QUEUE, DEFAULT_SCHEDULER_NAME, QueueState
from ..framework.session import BindIntent, EvictIntent
from .apiserver import APIServer

#: Fork feature: when any node carries this label with value "true", the
#: snapshot only includes dedicated nodes (cache.go:719-745).
DEDICATED_NODE_LABEL = "volcano.sh/dedicated-node"

_POD_PHASE_TO_STATUS = {
    PodPhase.PENDING: TaskStatus.PENDING,
    PodPhase.RUNNING: TaskStatus.RUNNING,
    PodPhase.SUCCEEDED: TaskStatus.SUCCEEDED,
    PodPhase.FAILED: TaskStatus.FAILED,
    PodPhase.UNKNOWN: TaskStatus.UNKNOWN,
}


def _pod_status(pod: Pod) -> TaskStatus:
    """Pod phase -> TaskStatus projection (getTaskStatus,
    event_handlers.go analog used by both snapshot paths)."""
    status = _POD_PHASE_TO_STATUS.get(pod.phase, TaskStatus.UNKNOWN)
    if pod.deletion_timestamp and status == TaskStatus.RUNNING:
        status = TaskStatus.RELEASING
    if status == TaskStatus.PENDING and pod.node_name:
        status = TaskStatus.BOUND
    return status


def _project_task(pod: Pod) -> TaskInfo:
    task = TaskInfo(
        uid=pod.key, name=pod.name, namespace=pod.namespace,
        task_role=pod.task_role, resreq=pod.resreq(),
        status=_pod_status(pod), priority=pod.priority,
        gpu_index=pod.gpu_index,
        node_selector=dict(pod.node_selector),
        tolerations=list(pod.tolerations))
    task.affinity_required = list(pod.affinity_required)
    task.affinity_preferred = list(pod.affinity_preferred)
    task.node_name = pod.node_name
    return task


_ACCOUNTED = (TaskStatus.SUCCEEDED, TaskStatus.FAILED, TaskStatus.UNKNOWN)


class SchedulerCache:
    """The scheduler's view of the store, plus the bind/evict seam."""

    def __init__(self, api: APIServer):
        self.api = api
        self.binds: List[Tuple[str, str]] = []
        self.evictions: List[str] = []
        self._ensure_default_queue()
        # ---- incremental mirror state (event_handlers.go analog) ----
        self._mirror: Optional[ClusterInfo] = None
        self._task_owner: Dict[str, str] = {}
        self._shadow_nodes: Dict[str, NodeInfo] = {}  # incl. gated-out
        self._has_dedicated = False
        self._needs_rebuild = True
        self.dirty_jobs: set = set()
        self.dirty_nodes: set = set()
        self.structural: bool = True
        #: total structural marks ever raised — each one forces the
        #: scheduler onto a fresh Session (and therefore a full re-fuse of
        #: the device-resident buffers), so the counter is the ground truth
        #: for "full upload only on structural change" claims
        self.structural_epochs: int = 1
        api.watch("pods", self._on_pod)
        api.watch("podgroups", self._on_podgroup)
        api.watch("nodes", self._on_node)
        api.watch("queues", self._on_queue)

    def _ensure_default_queue(self) -> None:
        """The cache creates the default queue at startup (cache.go:448-455)."""
        if self.api.get("queues", DEFAULT_QUEUE) is None:
            self.api.admission_enabled = False
            try:
                self.api.create("queues", QueueInfo(DEFAULT_QUEUE, weight=1))
            finally:
                self.api.admission_enabled = True

    # ------------------------------------------------------------- snapshot
    def _project(self) -> Tuple[ClusterInfo, Dict[str, NodeInfo], bool]:
        """Full projection of the stores: (ci-with-gated-nodes,
        all-nodes-shadow, has_dedicated)."""
        ci = ClusterInfo()
        shadow: Dict[str, NodeInfo] = {}
        for node in self.api.stores["nodes"].values():
            cl = node.clone()
            shadow[cl.name] = cl
            ci.add_node(cl)
        for queue in self.api.stores["queues"].values():
            ci.add_queue(queue.clone())

        for pg in self.api.stores["podgroups"].values():
            job = JobInfo(
                uid=pg.key, name=pg.name, namespace=pg.namespace,
                queue=pg.queue or DEFAULT_QUEUE,
                min_available=pg.min_member,
                min_resources=pg.min_resources_res(),
                creation_timestamp=pg.creation_timestamp,
                pod_group_phase=pg.phase)
            ci.add_job(job)

        for pod in self.api.stores["pods"].values():
            if pod.scheduler_name != DEFAULT_SCHEDULER_NAME:
                continue
            pg_name = pod.pod_group
            if not pg_name:
                continue
            job = ci.jobs.get(f"{pod.namespace}/{pg_name}")
            if job is None:
                continue
            task = _project_task(pod)
            job.add_task(task)
            if pod.node_name and pod.node_name in ci.nodes and \
                    task.status not in _ACCOUNTED:
                # forced ingestion: running pods are accounted even if the
                # node shrank; sync_state below then flags it OutOfSync
                ci.nodes[pod.node_name].add_task(task, force=True)

        # Node gating (Snapshot, cache.go:712-750): drop nodes that are
        # NotReady/OutOfSync, nodes with in-flight binding tasks (fork:
        # cache.go:735-738), and — when any node carries the dedicated label
        # — every non-dedicated node.
        has_dedicated = any(
            n.labels.get(DEDICATED_NODE_LABEL) == "true"
            for n in ci.nodes.values())
        for name in list(ci.nodes):
            node = ci.nodes[name]
            node.sync_state()
            if not self._gated_in(node, has_dedicated):
                del ci.nodes[name]
        return ci, shadow, has_dedicated

    @staticmethod
    def _gated_in(node: NodeInfo, has_dedicated: bool) -> bool:
        if not node.ready:
            return False
        if node.binding_tasks:
            return False
        if has_dedicated and node.labels.get(DEDICATED_NODE_LABEL) != "true":
            return False
        return True

    def snapshot(self) -> ClusterInfo:
        ci, _, _ = self._project()
        return ci

    # ------------------------------------------ incremental mirror (live)
    def live_view(self) -> ClusterInfo:
        """The mirror ClusterInfo for a persistent session. Maintained by
        the watch handlers below; rebuilt from the stores whenever an event
        the handlers don't patch in place arrives (structural)."""
        if self._mirror is None or self._needs_rebuild:
            self._mirror, self._shadow_nodes, self._has_dedicated = \
                self._project()
            # the volume-binder seam reads pvcs live (the reference queries
            # the API at bind time, cache.go:265-272); share the store dict
            self._mirror.pvcs = self.api.stores["pvcs"]
            # task uid -> owning job key: detects pods whose group (or
            # scheduler) annotation changed, which must re-project
            self._task_owner = {
                uid: job.uid for job in self._mirror.jobs.values()
                for uid in job.tasks}
            self._needs_rebuild = False
        return self._mirror

    def drain_dirty(self) -> Tuple[set, set, bool]:
        dj, dn, st = self.dirty_jobs, self.dirty_nodes, self.structural
        self.dirty_jobs, self.dirty_nodes = set(), set()
        self.structural = False
        return dj, dn, st

    def mark_dirty(self, job_uid: Optional[str] = None,
                   node_name: Optional[str] = None,
                   structural: bool = False) -> None:
        if job_uid is not None:
            self.dirty_jobs.add(job_uid)
        if node_name is not None:
            self.dirty_nodes.add(node_name)
        if structural:
            if not self.structural:
                self.structural_epochs += 1
            self.structural = True
            self._needs_rebuild = True

    def _regate(self, name: str) -> None:
        """Re-evaluate one node's snapshot membership after accounting
        changed (the OutOfSync half of setNodeState, node_info.go:143-149).
        A flip is structural: the mirror rebuilds from the stores, keeping
        packing order identical to a fresh projection."""
        mirror = self._mirror
        node = self._shadow_nodes.get(name)
        if mirror is None or node is None:
            return
        node.sync_state()
        now_in = self._gated_in(node, self._has_dedicated)
        was_in = name in mirror.nodes
        if now_in != was_in:
            # the node SET changed: rebuild the projection in store order
            # (structural also forces the scheduler onto a fresh Session)
            self.mark_dirty(structural=True)

    def _on_pod(self, event: str, pod: Pod, old) -> None:
        if self._mirror is None or self._needs_rebuild:
            return                      # next live_view rebuilds anyway
        owner = self._task_owner.get(pod.key)
        if pod.scheduler_name != DEFAULT_SCHEDULER_NAME or not pod.pod_group:
            if owner is not None:
                # a pod the mirror tracks stopped being ours (scheduler or
                # group annotation cleared): re-project
                self.mark_dirty(structural=True)
            return
        mirror = self._mirror
        key = f"{pod.namespace}/{pod.pod_group}"
        if owner is not None and owner != key:
            # the pod moved between groups: the old job still holds it —
            # only a rebuild removes the stale twin exactly
            self.mark_dirty(structural=True)
            return
        job = mirror.jobs.get(key)
        if job is None:
            # pod before its podgroup: the rebuild will pick it up once the
            # group exists (the reference holds it in schedulingQueue)
            self.mark_dirty(structural=True)
            return
        task = job.tasks.get(pod.key)
        if event == "deleted":
            self._task_owner.pop(pod.key, None)
            if task is not None:
                node = mirror.nodes.get(task.node_name) \
                    or self._shadow_nodes.get(task.node_name)
                if node is not None and task.uid in node.tasks:
                    node.remove_task(task)
                    self.mark_dirty(node_name=node.name)
                    self._regate(node.name)
                job.delete_task(task)
                # task-set change: refresh_snapshot repacks from the mirror
                self.mark_dirty(job_uid=job.uid)
            return
        if task is None:                    # added (or update for unseen)
            task = _project_task(pod)
            job.add_task(task)
            self._task_owner[pod.key] = job.uid
            if pod.node_name and task.status not in _ACCOUNTED:
                node = self._shadow_nodes.get(pod.node_name)
                if node is not None:
                    node.add_task(task, force=True)
                    self.mark_dirty(node_name=node.name)
                    self._regate(node.name)
            self.mark_dirty(job_uid=job.uid)
            return
        # updated: reconcile the mirror task to the pod (updateTask,
        # event_handlers.go:170-232) — remove old accounting, patch fields,
        # re-add. add/remove are commutative sums, so the result equals a
        # fresh projection.
        old_node = self._shadow_nodes.get(task.node_name)
        if old_node is not None and task.uid in old_node.tasks:
            old_node.remove_task(task)
            self.mark_dirty(node_name=old_node.name)
        new_req = pod.resreq()
        if new_req.quantities != task.resreq.quantities:
            # job sums ride the stored resreq (add_task/update_task_status,
            # job_info.go:300-420): swap it with the accounting kept exact
            from ..api.types import is_allocated_status
            job.total_request.sub_floored(task.resreq)
            if is_allocated_status(task.status):
                job.allocated.sub_floored(task.resreq)
            task.resreq = new_req
            job.total_request.add(new_req)
            if is_allocated_status(task.status):
                job.allocated.add(new_req)
        task.priority = pod.priority
        task.gpu_index = pod.gpu_index
        task.node_selector = dict(pod.node_selector)
        task.tolerations = list(pod.tolerations)
        task.affinity_required = list(pod.affinity_required)
        task.affinity_preferred = list(pod.affinity_preferred)
        job.update_task_status(task, _pod_status(pod))
        task.node_name = pod.node_name
        if pod.node_name and task.status not in _ACCOUNTED:
            node = self._shadow_nodes.get(pod.node_name)
            if node is not None:
                node.add_task(task, force=True)
                self.mark_dirty(node_name=node.name)
        self.mark_dirty(job_uid=job.uid)
        if old_node is not None:
            self._regate(old_node.name)
        if pod.node_name and (old_node is None
                              or pod.node_name != old_node.name):
            self._regate(pod.node_name)

    def _on_podgroup(self, event: str, pg: PodGroup, old) -> None:
        if self._mirror is None or self._needs_rebuild:
            return
        mirror = self._mirror
        if event == "added":
            # new job: entity-set change -> session repack; membership of
            # already-stored pods needs the full projection order
            self.mark_dirty(structural=True)
            return
        job = mirror.jobs.get(pg.key)
        if job is None:
            self.mark_dirty(structural=True)
            return
        if event == "deleted":
            self.mark_dirty(structural=True)
            return
        job.queue = pg.queue or DEFAULT_QUEUE
        job.min_available = pg.min_member
        job.min_resources = pg.min_resources_res()
        job.pod_group_phase = pg.phase
        self.mark_dirty(job_uid=job.uid)

    def _on_node(self, event: str, node: NodeInfo, old) -> None:
        # node spec changes are rare and interact with gating + dedicated
        # mode: rebuild (the reference's informer hands whole NodeInfo
        # updates to SetNode similarly, event_handlers.go:430-470)
        self.mark_dirty(structural=True)

    def _on_queue(self, event: str, queue: QueueInfo, old) -> None:
        if self._mirror is None or self._needs_rebuild:
            return
        if event == "updated" and queue.name in self._mirror.queues:
            # refresh_snapshot re-encodes every queue row each cycle; the
            # mirror object just needs the new spec
            self._mirror.queues[queue.name] = queue.clone()
            return
        self.mark_dirty(structural=True)

    # ----------------------------------------------------------- bind/evict
    def bind(self, intent: BindIntent) -> bool:
        pod: Optional[Pod] = self.api.get("pods", intent.task_uid)
        node = self.api.get("nodes", intent.node_name)
        if pod is None or node is None:
            return False
        # mark the in-flight bind so concurrent snapshots skip this node
        # (cache.go:585-595); cleared once the pod write lands. With this
        # synchronous store the window closes immediately, but async
        # backends inherit the seam.
        node.add_binding_task(intent.task_uid)
        try:
            pod.node_name = intent.node_name
            pod.gpu_index = intent.gpu_index
            self.api.update("pods", pod)
        finally:
            node.remove_binding_task(intent.task_uid)
        self.binds.append((intent.task_uid, intent.node_name))
        return True

    def evict(self, intent: EvictIntent) -> bool:
        pod: Optional[Pod] = self.api.get("pods", intent.task_uid)
        if pod is None:
            return False
        # the evictor deletes the pod; the job controller recreates it
        # pending (cache.go:145-175). A truthy deletion timestamp is what
        # classifies the transition as PodEvicted rather than PodFailed.
        import time
        pod.phase = PodPhase.FAILED
        pod.deletion_timestamp = pod.deletion_timestamp or time.time()
        self.api.update("pods", pod)
        self.api.delete("pods", pod.key)
        self.evictions.append(intent.task_uid)
        return True

    def hold_binding(self, intent: BindIntent) -> None:
        """Failed bind dispatch: the mirror task keeps its Binding state
        (the session's UpdateTaskStatus persisting until syncTask,
        cache.go:549-560) — with the persistent session that state is
        already in the mirror, so nothing to do; a rebuilt mirror re-reads
        the store where the pod is still unplaced, which is the
        re-decide-after-resync behavior."""

    def resync_task(self, task_uid: str) -> None:
        """Give-up resync (syncTask discovering the pod never bound,
        cache.go:690-709): reset the mirror task to Pending off-node so the
        next cycle re-decides it."""
        if self._mirror is None:
            return
        for job in self._mirror.jobs.values():
            task = job.tasks.get(task_uid)
            if task is None:
                continue
            if task.status == TaskStatus.BINDING:
                node = self._shadow_nodes.get(task.node_name)
                if node is not None and task.uid in node.tasks:
                    node.remove_task(task)
                    self.mark_dirty(node_name=node.name)
                    self._regate(node.name)
                task.node_name = ""
                task.gpu_index = -1
                job.update_task_status(task, TaskStatus.PENDING)
                self.mark_dirty(job_uid=job.uid)
            return

    # ------------------------------------------------- status write-back
    def update_podgroup_phases(self, phase_updates: Dict[str, object]) -> None:
        for uid, phase in phase_updates.items():
            pg = self.api.get("podgroups", uid)
            if pg is not None:
                pg.phase = phase
                self.api.update("podgroups", pg)
