"""Scheduler cache: project API-server objects into a ClusterInfo snapshot.

Reference: pkg/scheduler/cache/cache.go:71-917 + event_handlers.go:43-740 —
the informer-fed mirror whose Snapshot() the session consumes. Here the
projection is rebuilt from the store each cycle (the store IS the local
cache; a deep-copy clone per cycle matches the reference's snapshot
semantics), and bind/evict write back to pods exactly like the
defaultBinder/defaultEvictor REST calls (cache.go:123-175).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..api import (ClusterInfo, JobInfo, NodeInfo, QueueInfo, Resource,
                   TaskInfo, TaskStatus)
from ..api.core import Pod, PodGroup, PodPhase
from ..api.queue_info import NamespaceInfo
from ..api.types import DEFAULT_QUEUE, DEFAULT_SCHEDULER_NAME, QueueState
from ..framework.session import BindIntent, EvictIntent
from .apiserver import APIServer

#: Fork feature: when any node carries this label with value "true", the
#: snapshot only includes dedicated nodes (cache.go:719-745).
DEDICATED_NODE_LABEL = "volcano.sh/dedicated-node"

_POD_PHASE_TO_STATUS = {
    PodPhase.PENDING: TaskStatus.PENDING,
    PodPhase.RUNNING: TaskStatus.RUNNING,
    PodPhase.SUCCEEDED: TaskStatus.SUCCEEDED,
    PodPhase.FAILED: TaskStatus.FAILED,
    PodPhase.UNKNOWN: TaskStatus.UNKNOWN,
}


class SchedulerCache:
    """The scheduler's view of the store, plus the bind/evict seam."""

    def __init__(self, api: APIServer):
        self.api = api
        self.binds: List[Tuple[str, str]] = []
        self.evictions: List[str] = []
        self._ensure_default_queue()

    def _ensure_default_queue(self) -> None:
        """The cache creates the default queue at startup (cache.go:448-455)."""
        if self.api.get("queues", DEFAULT_QUEUE) is None:
            self.api.admission_enabled = False
            try:
                self.api.create("queues", QueueInfo(DEFAULT_QUEUE, weight=1))
            finally:
                self.api.admission_enabled = True

    # ------------------------------------------------------------- snapshot
    def snapshot(self) -> ClusterInfo:
        ci = ClusterInfo()
        for node in self.api.stores["nodes"].values():
            ci.add_node(node.clone())
        for queue in self.api.stores["queues"].values():
            ci.add_queue(queue.clone())

        for pg in self.api.stores["podgroups"].values():
            job = JobInfo(
                uid=pg.key, name=pg.name, namespace=pg.namespace,
                queue=pg.queue or DEFAULT_QUEUE,
                min_available=pg.min_member,
                min_resources=pg.min_resources_res(),
                creation_timestamp=pg.creation_timestamp,
                pod_group_phase=pg.phase)
            ci.add_job(job)

        for pod in self.api.stores["pods"].values():
            if pod.scheduler_name != DEFAULT_SCHEDULER_NAME:
                continue
            pg_name = pod.pod_group
            if not pg_name:
                continue
            job = ci.jobs.get(f"{pod.namespace}/{pg_name}")
            if job is None:
                continue
            status = _POD_PHASE_TO_STATUS.get(pod.phase, TaskStatus.UNKNOWN)
            if pod.deletion_timestamp and status == TaskStatus.RUNNING:
                status = TaskStatus.RELEASING
            if status == TaskStatus.PENDING and pod.node_name:
                status = TaskStatus.BOUND
            task = TaskInfo(
                uid=pod.key, name=pod.name, namespace=pod.namespace,
                task_role=pod.task_role, resreq=pod.resreq(),
                status=status, priority=pod.priority,
                gpu_index=pod.gpu_index,
                node_selector=dict(pod.node_selector),
                tolerations=list(pod.tolerations))
            task.node_name = pod.node_name
            job.add_task(task)
            if pod.node_name and pod.node_name in ci.nodes and status not in (
                    TaskStatus.SUCCEEDED, TaskStatus.FAILED,
                    TaskStatus.UNKNOWN):
                # forced ingestion: running pods are accounted even if the
                # node shrank; sync_state below then flags it OutOfSync
                ci.nodes[pod.node_name].add_task(task, force=True)

        # Node gating (Snapshot, cache.go:712-750): drop nodes that are
        # NotReady/OutOfSync, nodes with in-flight binding tasks (fork:
        # cache.go:735-738), and — when any node carries the dedicated label
        # — every non-dedicated node.
        has_dedicated = any(
            n.labels.get(DEDICATED_NODE_LABEL) == "true"
            for n in ci.nodes.values())
        for name in list(ci.nodes):
            node = ci.nodes[name]
            node.sync_state()
            if not node.ready:
                del ci.nodes[name]
            elif node.binding_tasks:
                del ci.nodes[name]
            elif has_dedicated and \
                    node.labels.get(DEDICATED_NODE_LABEL) != "true":
                del ci.nodes[name]
        return ci

    # ----------------------------------------------------------- bind/evict
    def bind(self, intent: BindIntent) -> bool:
        pod: Optional[Pod] = self.api.get("pods", intent.task_uid)
        node = self.api.get("nodes", intent.node_name)
        if pod is None or node is None:
            return False
        # mark the in-flight bind so concurrent snapshots skip this node
        # (cache.go:585-595); cleared once the pod write lands. With this
        # synchronous store the window closes immediately, but async
        # backends inherit the seam.
        node.add_binding_task(intent.task_uid)
        try:
            pod.node_name = intent.node_name
            pod.gpu_index = intent.gpu_index
            self.api.update("pods", pod)
        finally:
            node.remove_binding_task(intent.task_uid)
        self.binds.append((intent.task_uid, intent.node_name))
        return True

    def evict(self, intent: EvictIntent) -> bool:
        pod: Optional[Pod] = self.api.get("pods", intent.task_uid)
        if pod is None:
            return False
        # the evictor deletes the pod; the job controller recreates it
        # pending (cache.go:145-175). A truthy deletion timestamp is what
        # classifies the transition as PodEvicted rather than PodFailed.
        import time
        pod.phase = PodPhase.FAILED
        pod.deletion_timestamp = pod.deletion_timestamp or time.time()
        self.api.update("pods", pod)
        self.api.delete("pods", pod.key)
        self.evictions.append(intent.task_uid)
        return True

    # ------------------------------------------------- status write-back
    def update_podgroup_phases(self, phase_updates: Dict[str, object]) -> None:
        for uid, phase in phase_updates.items():
            pg = self.api.get("podgroups", uid)
            if pg is not None:
                pg.phase = phase
                self.api.update("podgroups", pg)
