"""Sequential CPU reference of the allocate pass.

An independent numpy re-implementation of the reference Go scheduler's
allocate loop (pkg/scheduler/actions/allocate/allocate.go:43-281 +
statement.go commit/discard), kept deliberately loop-structured the way the Go
code is. Two roles:

1. Decision-equivalence oracle for the compiled TPU path (SURVEY.md section 4:
   "JAX-vs-reference decision-equivalence tests") — both implementations must
   produce identical bind decisions on the same packed snapshot.
2. The CPU baseline bench.py measures against (BASELINE.md north star), since
   the Go toolchain is not available in this image.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np

from ..arrays.labels import (EFFECT_NO_EXECUTE, EFFECT_NO_SCHEDULE,
                             EFFECT_PREFER_NO_SCHEDULE, TOL_EQUAL,
                             TOL_EXISTS_ALL, TOL_EXISTS_KEY)
from ..arrays.schema import SnapshotArrays
from ..ops.allocate_scan import (MODE_ALLOCATED, MODE_NONE, MODE_PIPELINED,
                                 AllocateConfig, AllocateExtras)

_EPS = 1e-5


def _np(x):
    return np.asarray(x)


def _as_np(nodes):
    """One-time numpy view of the node tensors (hoisted out of the hot
    loop so the CPU baseline is not penalized by per-call conversions)."""
    from types import SimpleNamespace
    return SimpleNamespace(
        valid=np.asarray(nodes.valid), schedulable=np.asarray(nodes.schedulable),
        pod_count=np.asarray(nodes.pod_count), max_pods=np.asarray(nodes.max_pods),
        labels=np.asarray(nodes.labels), taint_kv=np.asarray(nodes.taint_kv),
        taint_key=np.asarray(nodes.taint_key),
        taint_effect=np.asarray(nodes.taint_effect),
        allocatable=np.asarray(nodes.allocatable),
        gpu_memory=np.asarray(nodes.gpu_memory),
        gpu_used=np.asarray(nodes.gpu_used))


def _feasible_one(nodes, resreq, sel, th, te, tm, avail, pods_extra,
                  gpu_req=0.0, gpu_extra=None):
    N = avail.shape[0]
    ok = nodes.valid & nodes.schedulable
    ok &= (nodes.pod_count + pods_extra) < nodes.max_pods
    ok &= np.all(resreq[None, :] <= avail + _EPS, axis=-1)
    if gpu_req > 0:
        gidle = nodes.gpu_memory - nodes.gpu_used
        if gpu_extra is not None:
            gidle = gidle - gpu_extra
        ok &= np.any(gidle >= gpu_req - _EPS, axis=-1)
    labels = nodes.labels
    for s in sel:
        if s != 0:
            ok &= np.any(labels == s, axis=-1)
    kv, key, eff = nodes.taint_kv, nodes.taint_key, nodes.taint_effect
    has_hard = np.isin(eff, (EFFECT_NO_SCHEDULE, EFFECT_NO_EXECUTE)).any(axis=-1)
    for n in range(N):
        if not ok[n] or not has_hard[n]:
            continue
        for e in range(kv.shape[1]):
            if eff[n, e] not in (EFFECT_NO_SCHEDULE, EFFECT_NO_EXECUTE):
                continue
            tolerated = False
            for o in range(len(th)):
                if tm[o] == TOL_EXISTS_ALL and th[o] != 0:
                    match = True
                elif tm[o] == TOL_EXISTS_KEY:
                    match = key[n, e] == th[o]
                else:
                    match = kv[n, e] == th[o] and th[o] != 0
                if match and (te[o] == 0 or te[o] == eff[n, e]):
                    tolerated = True
                    break
            if not tolerated:
                ok[n] = False
                break
    return ok


def _score_one(cfg: AllocateConfig, nodes, resreq, idle, th, te, tm):
    allocatable = nodes.allocatable
    used = allocatable - idle
    N = idle.shape[0]
    score = np.zeros(N)
    if cfg.binpack_weight:
        applicable = (resreq > 0)[None, :] & (allocatable > 0)
        frac = np.divide(used + resreq[None, :], allocatable,
                         out=np.zeros_like(used), where=allocatable > 0)
        w = np.ones_like(resreq)[None, :] * applicable
        wsum = np.maximum(w.sum(-1), 1e-9)
        raw = (np.where(applicable, frac, 0) * w).sum(-1) / wsum
        raw = np.where((np.where(applicable, frac, 0) > 1 + 1e-6).any(-1), 0, raw)
        score += cfg.binpack_weight * raw * 100
    if cfg.least_allocated_weight:
        cap = np.maximum(allocatable, 1e-9)
        free = np.clip((allocatable - used - resreq[None, :]) / cap, 0, 1)
        counted = allocatable > 0
        n = np.maximum(counted.sum(-1), 1)
        score += cfg.least_allocated_weight * (free * counted).sum(-1) / n * 100
    if cfg.most_allocated_weight:
        cap = np.maximum(allocatable, 1e-9)
        uf = np.clip((used + resreq[None, :]) / cap, 0, 1)
        counted = allocatable > 0
        n = np.maximum(counted.sum(-1), 1)
        score += cfg.most_allocated_weight * (uf * counted).sum(-1) / n * 100
    if cfg.balanced_weight:
        cap = np.maximum(allocatable, 1e-9)
        frac = np.clip((used + resreq[None, :]) / cap, 0, 1)
        counted = (allocatable > 0).astype(float)
        n = np.maximum(counted.sum(-1), 1.0)
        mean = (frac * counted).sum(-1) / n
        var = (((frac - mean[:, None]) ** 2) * counted).sum(-1) / n
        score += cfg.balanced_weight * (1.0 - np.sqrt(var)) * 100
    if cfg.taint_prefer_weight:
        kv, key, eff = nodes.taint_kv, nodes.taint_key, nodes.taint_effect
        intol = np.zeros(N)
        has_prefer = (eff == EFFECT_PREFER_NO_SCHEDULE).any(axis=-1)
        for n in range(N):
            if not has_prefer[n]:
                continue
            for e in range(kv.shape[1]):
                if eff[n, e] != EFFECT_PREFER_NO_SCHEDULE:
                    continue
                tolerated = False
                for o in range(len(th)):
                    if tm[o] == TOL_EXISTS_ALL and th[o] != 0:
                        match = True
                    elif tm[o] == TOL_EXISTS_KEY:
                        match = key[n, e] == th[o]
                    else:
                        match = kv[n, e] == th[o] and th[o] != 0
                    if match and (te[o] == 0 or te[o] == eff[n, e]):
                        tolerated = True
                        break
                if not tolerated:
                    intol[n] += 1
        mx = max(intol.max(), 1)
        score += cfg.taint_prefer_weight * (1.0 - intol / mx) * 100
    return score


def _affinity_state(extras):
    """Mutable affinity-count state mirroring the kernel's scan carry
    (node-space encoding, arrays/affinity.py)."""
    aff = extras.affinity
    return {
        "sk_sel": np.asarray(aff.sk_sel),
        "sk_domain": np.asarray(aff.sk_domain),
        "task_match": np.asarray(aff.task_match),
        "aff_cnt": np.asarray(aff.cnt0, np.float64).copy(),
        "anti_cnt": np.asarray(aff.anti_cnt0, np.float64).copy(),
        "t_aff_sk": np.asarray(aff.task_aff_sk),
        "t_anti": np.asarray(aff.task_anti_term),
        "eta_sel": np.asarray(aff.eta_sel),
        "eta_sk": np.asarray(aff.eta_sk),
        "eta_domain": np.asarray(aff.eta_domain),
        "t_pref_sk": np.asarray(aff.task_pref_sk),
        "t_pref_w": np.asarray(aff.task_pref_w),
        "static_pref": np.asarray(aff.static_pref),
    }


def _affinity_one(st, t, valid_nodes):
    """Sequential mirror of ops.allocate_scan._affinity_terms: per-node
    feasibility + 0..100 normalized preferred score for task ``t``."""
    N = st["sk_domain"].shape[1]
    feas = np.ones(N, bool)
    # required affinity (with the k8s first-pod escape)
    for a in range(st["t_aff_sk"].shape[1]):
        p = st["t_aff_sk"][t, a]
        if p < 0:
            continue
        dom = st["sk_domain"][p]
        have = st["aff_cnt"][p, :N]
        ok = (have > 0) & (dom >= 0)
        if st["aff_cnt"][p, N] == 0 and st["task_match"][st["sk_sel"][p], t]:
            ok = ok | (dom >= 0)
        feas &= ok
    # own required anti-affinity
    for b in range(st["t_anti"].shape[1]):
        e = st["t_anti"][t, b]
        if e < 0:
            continue
        dom = st["eta_domain"][e]
        have = st["aff_cnt"][st["eta_sk"][e], :N]
        feas &= ~((have > 0) & (dom >= 0))
    # placed pods' anti terms vs this task (symmetric)
    for e in range(len(st["eta_sel"])):
        s = st["eta_sel"][e]
        if s < 0 or not st["task_match"][s, t]:
            continue
        dom = st["eta_domain"][e]
        feas &= ~((st["anti_cnt"][e] > 0) & (dom >= 0))
    # preferred terms
    raw = np.zeros(N)
    for i in range(st["t_pref_sk"].shape[1]):
        p = st["t_pref_sk"][t, i]
        if p < 0:
            continue
        dom = st["sk_domain"][p]
        raw += st["t_pref_w"][t, i] * np.where(
            dom >= 0, st["aff_cnt"][p, :N], 0)
    for s in range(st["task_match"].shape[0]):
        if st["task_match"][s, t]:
            raw += st["static_pref"][s]
    mx = np.max(np.where(valid_nodes, raw, -np.inf))
    mn = np.min(np.where(valid_nodes, raw, np.inf))
    span = mx - mn
    norm = ((raw - mn) * (100.0 / max(span, 1e-9))
            if np.isfinite(span) and span > 0 else np.zeros(N))
    return feas, norm


def _affinity_place(st, t, node):
    """Mirror of _affinity_place_update: account a placement by adding
    domain-membership mask rows."""
    N = st["sk_domain"].shape[1]
    for p in range(len(st["sk_sel"])):
        s = st["sk_sel"][p]
        if s < 0 or not st["task_match"][s, t]:
            continue
        d = st["sk_domain"][p, node]
        if d < 0:
            continue
        st["aff_cnt"][p, :N][st["sk_domain"][p] == d] += 1.0
        st["aff_cnt"][p, N] += 1.0
    for b in range(st["t_anti"].shape[1]):
        e = st["t_anti"][t, b]
        if e < 0:
            continue
        dom = st["eta_domain"][e]
        d = dom[node]
        if d >= 0:
            st["anti_cnt"][e][dom == d] += 1.0


def _hdrf_keys(hier, job_alloc, job_req, job_valid, total):
    """Per-queue hdrf level keys for the current live job allocations.

    Delegates to ops.fairshare.hdrf_level_keys (run on host arrays) so the
    oracle's ordering keys are BIT-identical to the kernel's — the key
    VALUES are independently validated against a recursive transliteration
    of drf.go in tests/test_hdrf.py; what this oracle checks is the pop
    loop's mechanics around them."""
    from ..ops.fairshare import hdrf_level_keys
    return np.asarray(hdrf_level_keys(
        hier, np.asarray(job_alloc, np.float32), job_req, job_valid, total))


def allocate_cpu(snap: SnapshotArrays, extras: AllocateExtras = None,
                 cfg: AllocateConfig = AllocateConfig()) -> Dict[str, np.ndarray]:
    """Run the allocate pass sequentially on the host. Returns the same
    decision arrays as ops.allocate_scan (task_node, task_mode, job_ready,
    job_pipelined)."""
    if extras is None:
        extras = AllocateExtras.neutral(snap)
    job_share = np.asarray(extras.job_share)
    queue_deserved = np.asarray(extras.queue_deserved)
    ns_share = np.asarray(extras.ns_share)
    queue_share_extra = np.asarray(extras.queue_share_extra)
    block_nonrevocable = np.asarray(extras.block_nonrevocable)
    block_all = np.asarray(extras.block_all)
    task_revocable = np.asarray(extras.task_revocable)
    tdm_bonus = np.asarray(extras.tdm_bonus)
    template_na = np.asarray(extras.template_na_score)
    task_or_group = np.asarray(extras.task_or_group)
    or_feasible = np.asarray(extras.or_feasible)
    task_ports_a = np.asarray(extras.task_ports)
    node_ports_a = np.asarray(extras.node_ports)
    vol_ok = np.asarray(extras.task_volume_ok)
    vol_node = np.asarray(extras.task_volume_node)
    ports_placed: List[Tuple[int, int]] = []    # (node, port) this cycle
    task_pref_node = np.asarray(extras.task_pref_node)
    node_locked = np.asarray(extras.node_locked)
    target_job = int(extras.target_job)
    nodes, tasks, jobs, queues = snap.nodes, snap.tasks, snap.jobs, snap.queues
    N, R = np.array(nodes.idle).shape
    T = np.array(tasks.resreq).shape[0]
    J, M = np.array(jobs.task_table).shape

    idle = np.array(nodes.idle, dtype=np.float64).copy()
    pipe_extra = np.zeros((N, R))
    pods_extra = np.zeros(N, np.int64)
    G = np.array(nodes.gpu_memory).shape[1]
    gpu_extra = np.zeros((N, G))
    queue_allocated = np.array(queues.allocated, dtype=np.float64).copy()
    task_node = np.full(T, -1, np.int64)
    task_mode = np.zeros(T, np.int64)
    task_gpu = np.full(T, -1, np.int64)
    job_done = np.zeros(J, bool)
    job_ready = np.zeros(J, bool)
    job_pipelined = np.zeros(J, bool)

    jns = np.array(jobs.namespace)
    jvalid_all = np.array(jobs.valid)
    jvalid = jvalid_all & np.array(jobs.schedulable)
    n_pending = np.array(jobs.n_pending)
    jqueue = np.array(jobs.queue)
    jprio = np.array(jobs.priority)
    jrank = np.array(jobs.creation_rank)
    jready0 = np.array(jobs.ready_num)
    jmin = np.array(jobs.min_available)
    table = np.array(jobs.task_table)
    jreq32 = np.array(jobs.total_request, np.float32)
    total_cap = np.array(snap.cluster_capacity, np.float32)
    resreq32 = np.array(tasks.resreq, np.float32)
    ns_weight = np.array(snap.namespace_weight, np.float32)
    # live drf state (event-handler analog): committed allocations +
    # ReadyTaskNum, float32 accumulated in kernel order for bit-equality
    job_cursor = np.zeros(J, np.int64)
    job_alloc_count = np.zeros(J, np.int64)
    job_alloc_dyn = np.array(jobs.allocated, np.float32).copy()
    releasing = np.array(nodes.releasing)
    pipelined0 = np.array(nodes.pipelined)
    resreq = np.array(tasks.resreq, dtype=np.float64)
    best_effort = np.array(tasks.best_effort)
    tjob = np.array(tasks.job)
    t_selector = np.array(tasks.selector)
    t_tol_hash = np.array(tasks.tol_hash)
    t_tol_effect = np.array(tasks.tol_effect)
    t_tol_mode = np.array(tasks.tol_mode)
    t_template = np.array(tasks.template)
    t_preemptable = np.array(tasks.preemptable)
    t_gpu_req = np.array(tasks.gpu_request, dtype=np.float64)
    nodes_np = _as_np(nodes)
    aff_st = _affinity_state(extras) if cfg.enable_pod_affinity else None
    valid_sched = nodes_np.valid & nodes_np.schedulable

    def _pick_gpu(node, req):
        """Lowest fitting card on the node (predicateGPU, gpu.go:41-56)."""
        if req <= 0:
            return -1
        gidle = (nodes_np.gpu_memory[node] - nodes_np.gpu_used[node]
                 - gpu_extra[node])
        for g in range(G):
            if gidle[g] >= req - _EPS:
                return g
        return -1

    while True:
        overused = np.any(queue_allocated > queue_deserved + 1e-6, axis=-1)
        elig = jvalid & ~job_done & (job_cursor < n_pending) & ~overused[jqueue]
        if not elig.any():
            break
        qshare = np.max(
            np.where(np.isfinite(queue_deserved) & (queue_deserved > 0),
                     queue_allocated / np.maximum(queue_deserved, 1e-9), 0.0),
            axis=-1) + queue_share_extra
        # drf keys from live allocations (event-handler analog,
        # drf.go:511-536) — delegated to ops.fairshare on host arrays so
        # the oracle's keys stay BIT-identical to the kernel's (same
        # delegation rationale as _hdrf_keys above)
        if cfg.drf_ns_order:
            from ..ops.fairshare import namespace_shares
            ns_share_k = np.asarray(namespace_shares(
                job_alloc_dyn, jns, jvalid_all, ns_weight, total_cap))
        else:
            ns_share_k = np.asarray(ns_share, float)
        if cfg.drf_job_order:
            from ..ops.fairshare import drf_job_shares
            job_share_k = np.asarray(drf_job_shares(
                job_alloc_dyn, total_cap, jvalid_all))
        else:
            job_share_k = np.asarray(job_share, float)
        ready_dyn = jready0 + job_alloc_count
        ready_now = (ready_dyn >= jmin) & (jmin > 0)
        key_rows = [ns_share_k[jns], jns.astype(float), qshare[jqueue]]
        if cfg.enable_hdrf:
            hcols = _hdrf_keys(extras.hierarchy, job_alloc_dyn, jreq32,
                               jvalid_all, total_cap)
            key_rows += [hcols[jqueue, c] for c in range(hcols.shape[1])]
        key_rows += [jqueue.astype(float), -jprio.astype(float)]
        if cfg.tdm_job_order:
            key_rows.append(np.array(jobs.preemptable).astype(float))
        if cfg.sla_job_order:
            key_rows.append(np.asarray(extras.job_deadline, float))
        key_rows += [ready_now.astype(float), job_share_k,
                     jrank.astype(float)]
        keys = np.stack(key_rows)
        best_ji, best_key = -1, None
        for ji in range(J):
            if not elig[ji]:
                continue
            k = tuple(keys[:, ji])
            if best_key is None or k < best_key:
                best_key, best_ji = k, ji
        ji = best_ji

        saved = (idle.copy(), pipe_extra.copy(), pods_extra.copy(),
                 gpu_extra.copy())
        saved_ports = list(ports_placed)
        # exact re-pop fusion (see ops/allocate_scan.py body): with fully
        # static ordering keys the same ready job wins every following pop,
        # so the single-task yields batch into one pass
        keys_static = not (cfg.drf_job_order or cfg.drf_ns_order
                           or cfg.enable_hdrf)
        # ANY finite deserved (a 0 counts: zero-quota queues flip overused
        # on the first commit) breaks the static-keys argument
        des_row = queue_deserved[jqueue[ji]]
        can_batch = keys_static and not bool(
            np.any(np.isfinite(des_row)))
        if aff_st is not None:
            saved_aff = (aff_st["aff_cnt"].copy(), aff_st["anti_cnt"].copy())
        placed: List[int] = []
        placed_sum32 = np.zeros(len(total_cap), np.float32)
        n_alloc = n_pipe = 0
        ready0_dyn = int(jready0[ji] + job_alloc_count[ji])
        stopped = False
        slot = int(job_cursor[ji])
        while slot < M:
            t = table[ji, slot]
            if t < 0:
                break               # past the row's real entries
            slot += 1               # the task is popped (consumed)
            if best_effort[t]:
                continue            # never queued (allocate.go:186-195)
            sel = t_selector[t]
            th = t_tol_hash[t]
            te = t_tol_effect[t]
            tm = t_tol_mode[t]
            req = resreq[t]
            greq = t_gpu_req[t]
            node_ok = (~(block_nonrevocable & ~task_revocable[t])
                       & ~block_all
                       & (or_feasible[task_or_group[t]][:len(block_all)]
                          if task_or_group[t] >= 0 else True)
                       & vol_ok[t]
                       & ((vol_node[t] < 0)
                          | (np.arange(N) == vol_node[t]))
                       & (~node_locked | (ji == target_job)))
            if cfg.enable_host_ports:
                tports = [p for p in task_ports_a[t] if p > 0]
                if tports:
                    conf_mask = np.zeros(N, bool)
                    for p in tports:
                        conf_mask |= (node_ports_a == p).any(axis=-1)
                    for pn, pp in ports_placed:
                        if pp in tports:
                            conf_mask[pn] = True
                    node_ok &= ~conf_mask
            feas_now = node_ok & _feasible_one(nodes_np, req, sel, th, te, tm,
                                               idle, pods_extra,
                                               greq, gpu_extra)
            score = _score_one(cfg, nodes_np, req, idle, th, te, tm)
            score = score + (template_na[t_template[t]]
                             + (tdm_bonus if task_revocable[t]
                                else np.float32(0.0)))
            if task_pref_node[t] >= 0:
                score = score + 100.0 * (np.arange(len(score)) == task_pref_node[t])
            if aff_st is not None:
                aff_feas, aff_score = _affinity_one(aff_st, t, valid_sched)
                feas_now &= aff_feas
                score = score + cfg.pod_affinity_weight * aff_score
            did_place = False
            if feas_now.any():
                node = int(np.argmax(np.where(feas_now, score, -np.inf)))
                idle[node] -= req
                pods_extra[node] += 1
                card = _pick_gpu(node, greq)
                if card >= 0:
                    gpu_extra[node, card] += greq
                    task_gpu[t] = card
                task_node[t] = node
                task_mode[t] = MODE_ALLOCATED
                placed.append(t)
                placed_sum32 = placed_sum32 + resreq32[t]
                n_alloc += 1
                did_place = True
                if aff_st is not None:
                    _affinity_place(aff_st, t, node)
                if cfg.enable_host_ports:
                    ports_placed.extend(
                        (node, p) for p in task_ports_a[t] if p > 0)
            elif cfg.enable_pipelining:
                future = np.maximum(idle + releasing - pipelined0 - pipe_extra, 0)
                feas_fut = node_ok & _feasible_one(nodes_np, req, sel, th, te, tm, future,
                                         pods_extra, greq, gpu_extra)
                if aff_st is not None:
                    feas_fut &= aff_feas
                if feas_fut.any():
                    node = int(np.argmax(np.where(feas_fut, score, -np.inf)))
                    pipe_extra[node] += req
                    pods_extra[node] += 1
                    card = _pick_gpu(node, greq)
                    if card >= 0:
                        gpu_extra[node, card] += greq
                        task_gpu[t] = card
                    task_node[t] = node
                    task_mode[t] = MODE_PIPELINED
                    placed.append(t)
                    placed_sum32 = placed_sum32 + resreq32[t]
                    n_pipe += 1
                    did_place = True
                    if aff_st is not None:
                        _affinity_place(aff_st, t, node)
                    if cfg.enable_host_ports:
                        ports_placed.extend(
                            (node, p) for p in task_ports_a[t] if p > 0)
            if not did_place:
                # no node can take the task at all -> the job breaks
                # (allocate.go:210-214 PredicateNodes empty)
                break
            # yield: a ready job with tasks still queued re-enters the
            # job queue (allocate.go:262-265)
            ready_aft = (not cfg.enable_gang
                         or (ready0_dyn + n_alloc) >= jmin[ji])
            remaining = any(table[ji, s] >= 0 and not best_effort[table[ji, s]]
                            for s in range(slot, M))
            if ready_aft and remaining and not can_batch:
                stopped = True
                break
        job_cursor[ji] = slot

        ready = (ready0_dyn + n_alloc) >= jmin[ji]
        pipelined = (ready0_dyn + n_alloc + n_pipe) >= jmin[ji]
        if not cfg.enable_gang:
            ready = True
        if ready or pipelined:
            queue_allocated[jqueue[ji]] += resreq[placed].sum(axis=0) if placed else 0
            job_alloc_dyn[ji] = job_alloc_dyn[ji] + placed_sum32
            job_alloc_count[ji] += n_alloc
            job_ready[ji] = bool(ready)
            job_pipelined[ji] = bool(pipelined and not ready)
            if not ready:
                # kept-but-unready gang: capacity held, no binds
                for t in placed:
                    task_mode[t] = MODE_PIPELINED
        else:
            idle, pipe_extra, pods_extra, gpu_extra = saved
            if aff_st is not None:
                aff_st["aff_cnt"], aff_st["anti_cnt"] = saved_aff
            ports_placed = saved_ports
            for t in placed:
                task_node[t] = -1
                task_mode[t] = MODE_NONE
                task_gpu[t] = -1
        job_done[ji] = not stopped

    return dict(task_node=task_node, task_mode=task_mode, task_gpu=task_gpu,
                job_ready=job_ready,
                job_pipelined=job_pipelined, idle=idle,
                queue_allocated=queue_allocated)
