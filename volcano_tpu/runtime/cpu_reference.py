"""Sequential CPU reference of the allocate pass.

An independent numpy re-implementation of the reference Go scheduler's
allocate loop (pkg/scheduler/actions/allocate/allocate.go:43-281 +
statement.go commit/discard), kept deliberately loop-structured the way the Go
code is. Two roles:

1. Decision-equivalence oracle for the compiled TPU path (SURVEY.md section 4:
   "JAX-vs-reference decision-equivalence tests") — both implementations must
   produce identical bind decisions on the same packed snapshot.
2. The CPU baseline bench.py measures against (BASELINE.md north star), since
   the Go toolchain is not available in this image.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np

from ..arrays.labels import (EFFECT_NO_EXECUTE, EFFECT_NO_SCHEDULE,
                             EFFECT_PREFER_NO_SCHEDULE, TOL_EQUAL,
                             TOL_EXISTS_ALL, TOL_EXISTS_KEY)
from ..arrays.schema import SnapshotArrays
from ..ops.allocate_scan import (MODE_ALLOCATED, MODE_NONE, MODE_PIPELINED,
                                 AllocateConfig, AllocateExtras,
                                 normalize_wave, wave_candidate_depth)

_EPS = 1e-5


def _np(x):
    return np.asarray(x)


def _as_np(nodes):
    """One-time numpy view of the node tensors (hoisted out of the hot
    loop so the CPU baseline is not penalized by per-call conversions)."""
    from types import SimpleNamespace
    return SimpleNamespace(
        valid=np.asarray(nodes.valid), schedulable=np.asarray(nodes.schedulable),
        pod_count=np.asarray(nodes.pod_count), max_pods=np.asarray(nodes.max_pods),
        labels=np.asarray(nodes.labels), taint_kv=np.asarray(nodes.taint_kv),
        taint_key=np.asarray(nodes.taint_key),
        taint_effect=np.asarray(nodes.taint_effect),
        allocatable=np.asarray(nodes.allocatable),
        gpu_memory=np.asarray(nodes.gpu_memory),
        gpu_used=np.asarray(nodes.gpu_used))


def _feasible_one(nodes, resreq, sel, th, te, tm, avail, pods_extra,
                  gpu_req=0.0, gpu_extra=None):
    N = avail.shape[0]
    ok = nodes.valid & nodes.schedulable
    ok &= (nodes.pod_count + pods_extra) < nodes.max_pods
    ok &= np.all(resreq[None, :] <= avail + _EPS, axis=-1)
    if gpu_req > 0:
        gidle = nodes.gpu_memory - nodes.gpu_used
        if gpu_extra is not None:
            gidle = gidle - gpu_extra
        ok &= np.any(gidle >= gpu_req - _EPS, axis=-1)
    labels = nodes.labels
    for s in sel:
        if s != 0:
            ok &= np.any(labels == s, axis=-1)
    kv, key, eff = nodes.taint_kv, nodes.taint_key, nodes.taint_effect
    has_hard = np.isin(eff, (EFFECT_NO_SCHEDULE, EFFECT_NO_EXECUTE)).any(axis=-1)
    for n in range(N):
        if not ok[n] or not has_hard[n]:
            continue
        for e in range(kv.shape[1]):
            if eff[n, e] not in (EFFECT_NO_SCHEDULE, EFFECT_NO_EXECUTE):
                continue
            tolerated = False
            for o in range(len(th)):
                if tm[o] == TOL_EXISTS_ALL and th[o] != 0:
                    match = True
                elif tm[o] == TOL_EXISTS_KEY:
                    match = key[n, e] == th[o]
                else:
                    match = kv[n, e] == th[o] and th[o] != 0
                if match and (te[o] == 0 or te[o] == eff[n, e]):
                    tolerated = True
                    break
            if not tolerated:
                ok[n] = False
                break
    return ok


def _score_one(cfg: AllocateConfig, nodes, resreq, idle, th, te, tm):
    allocatable = nodes.allocatable
    used = allocatable - idle
    N = idle.shape[0]
    score = np.zeros(N)
    if cfg.binpack_weight:
        applicable = (resreq > 0)[None, :] & (allocatable > 0)
        frac = np.divide(used + resreq[None, :], allocatable,
                         out=np.zeros_like(used), where=allocatable > 0)
        w = np.ones_like(resreq)[None, :] * applicable
        wsum = np.maximum(w.sum(-1), 1e-9)
        raw = (np.where(applicable, frac, 0) * w).sum(-1) / wsum
        raw = np.where((np.where(applicable, frac, 0) > 1 + 1e-6).any(-1), 0, raw)
        score += cfg.binpack_weight * raw * 100
    if cfg.least_allocated_weight:
        cap = np.maximum(allocatable, 1e-9)
        free = np.clip((allocatable - used - resreq[None, :]) / cap, 0, 1)
        counted = allocatable > 0
        n = np.maximum(counted.sum(-1), 1)
        score += cfg.least_allocated_weight * (free * counted).sum(-1) / n * 100
    if cfg.most_allocated_weight:
        cap = np.maximum(allocatable, 1e-9)
        uf = np.clip((used + resreq[None, :]) / cap, 0, 1)
        counted = allocatable > 0
        n = np.maximum(counted.sum(-1), 1)
        score += cfg.most_allocated_weight * (uf * counted).sum(-1) / n * 100
    if cfg.balanced_weight:
        cap = np.maximum(allocatable, 1e-9)
        frac = np.clip((used + resreq[None, :]) / cap, 0, 1)
        counted = (allocatable > 0).astype(float)
        n = np.maximum(counted.sum(-1), 1.0)
        mean = (frac * counted).sum(-1) / n
        var = (((frac - mean[:, None]) ** 2) * counted).sum(-1) / n
        score += cfg.balanced_weight * (1.0 - np.sqrt(var)) * 100
    if cfg.taint_prefer_weight:
        kv, key, eff = nodes.taint_kv, nodes.taint_key, nodes.taint_effect
        intol = np.zeros(N)
        has_prefer = (eff == EFFECT_PREFER_NO_SCHEDULE).any(axis=-1)
        for n in range(N):
            if not has_prefer[n]:
                continue
            for e in range(kv.shape[1]):
                if eff[n, e] != EFFECT_PREFER_NO_SCHEDULE:
                    continue
                tolerated = False
                for o in range(len(th)):
                    if tm[o] == TOL_EXISTS_ALL and th[o] != 0:
                        match = True
                    elif tm[o] == TOL_EXISTS_KEY:
                        match = key[n, e] == th[o]
                    else:
                        match = kv[n, e] == th[o] and th[o] != 0
                    if match and (te[o] == 0 or te[o] == eff[n, e]):
                        tolerated = True
                        break
                if not tolerated:
                    intol[n] += 1
        mx = max(intol.max(), 1)
        score += cfg.taint_prefer_weight * (1.0 - intol / mx) * 100
    return score


def _affinity_state(extras):
    """Mutable affinity-count state mirroring the kernel's scan carry
    (node-space encoding, arrays/affinity.py)."""
    aff = extras.affinity
    return {
        "sk_sel": np.asarray(aff.sk_sel),
        "sk_domain": np.asarray(aff.sk_domain),
        "task_match": np.asarray(aff.task_match),
        "aff_cnt": np.asarray(aff.cnt0, np.float64).copy(),
        "anti_cnt": np.asarray(aff.anti_cnt0, np.float64).copy(),
        "t_aff_sk": np.asarray(aff.task_aff_sk),
        "t_anti": np.asarray(aff.task_anti_term),
        "eta_sel": np.asarray(aff.eta_sel),
        "eta_sk": np.asarray(aff.eta_sk),
        "eta_domain": np.asarray(aff.eta_domain),
        "t_pref_sk": np.asarray(aff.task_pref_sk),
        "t_pref_w": np.asarray(aff.task_pref_w),
        "static_pref": np.asarray(aff.static_pref),
    }


def _affinity_one(st, t, valid_nodes):
    """Sequential mirror of ops.allocate_scan._affinity_terms: per-node
    feasibility + 0..100 normalized preferred score for task ``t``."""
    N = st["sk_domain"].shape[1]
    feas = np.ones(N, bool)
    # required affinity (with the k8s first-pod escape)
    for a in range(st["t_aff_sk"].shape[1]):
        p = st["t_aff_sk"][t, a]
        if p < 0:
            continue
        dom = st["sk_domain"][p]
        have = st["aff_cnt"][p, :N]
        ok = (have > 0) & (dom >= 0)
        if st["aff_cnt"][p, N] == 0 and st["task_match"][st["sk_sel"][p], t]:
            ok = ok | (dom >= 0)
        feas &= ok
    # own required anti-affinity
    for b in range(st["t_anti"].shape[1]):
        e = st["t_anti"][t, b]
        if e < 0:
            continue
        dom = st["eta_domain"][e]
        have = st["aff_cnt"][st["eta_sk"][e], :N]
        feas &= ~((have > 0) & (dom >= 0))
    # placed pods' anti terms vs this task (symmetric)
    for e in range(len(st["eta_sel"])):
        s = st["eta_sel"][e]
        if s < 0 or not st["task_match"][s, t]:
            continue
        dom = st["eta_domain"][e]
        feas &= ~((st["anti_cnt"][e] > 0) & (dom >= 0))
    # preferred terms
    raw = np.zeros(N)
    for i in range(st["t_pref_sk"].shape[1]):
        p = st["t_pref_sk"][t, i]
        if p < 0:
            continue
        dom = st["sk_domain"][p]
        raw += st["t_pref_w"][t, i] * np.where(
            dom >= 0, st["aff_cnt"][p, :N], 0)
    for s in range(st["task_match"].shape[0]):
        if st["task_match"][s, t]:
            raw += st["static_pref"][s]
    mx = np.max(np.where(valid_nodes, raw, -np.inf))
    mn = np.min(np.where(valid_nodes, raw, np.inf))
    span = mx - mn
    norm = ((raw - mn) * (100.0 / max(span, 1e-9))
            if np.isfinite(span) and span > 0 else np.zeros(N))
    return feas, norm


def _affinity_place(st, t, node):
    """Mirror of _affinity_place_update: account a placement by adding
    domain-membership mask rows."""
    N = st["sk_domain"].shape[1]
    for p in range(len(st["sk_sel"])):
        s = st["sk_sel"][p]
        if s < 0 or not st["task_match"][s, t]:
            continue
        d = st["sk_domain"][p, node]
        if d < 0:
            continue
        st["aff_cnt"][p, :N][st["sk_domain"][p] == d] += 1.0
        st["aff_cnt"][p, N] += 1.0
    for b in range(st["t_anti"].shape[1]):
        e = st["t_anti"][t, b]
        if e < 0:
            continue
        dom = st["eta_domain"][e]
        d = dom[node]
        if d >= 0:
            st["anti_cnt"][e][dom == d] += 1.0


def _hdrf_keys(hier, job_alloc, job_req, job_valid, total):
    """Per-queue hdrf level keys for the current live job allocations.

    Delegates to ops.fairshare.hdrf_level_keys (run on host arrays) so the
    oracle's ordering keys are BIT-identical to the kernel's — the key
    VALUES are independently validated against a recursive transliteration
    of drf.go in tests/test_hdrf.py; what this oracle checks is the pop
    loop's mechanics around them."""
    from ..ops.fairshare import hdrf_level_keys
    return np.asarray(hdrf_level_keys(
        hier, np.asarray(job_alloc, np.float32), job_req, job_valid, total))


def _tmpl_ok(nodes, sel, th, te, tm) -> np.ndarray:
    """bool[N]: the selector+taints static template row alone (the
    'template' telemetry family) — predicates.static_feasible minus the
    valid/schedulable gate, loop-structured like the rest of the oracle."""
    N = nodes.labels.shape[0]
    ok = np.ones(N, bool)
    for s in sel:
        if s != 0:
            ok &= np.any(nodes.labels == s, axis=-1)
    kv, key, eff = nodes.taint_kv, nodes.taint_key, nodes.taint_effect
    has_hard = np.isin(eff, (EFFECT_NO_SCHEDULE,
                             EFFECT_NO_EXECUTE)).any(axis=-1)
    for n in range(N):
        if not has_hard[n]:
            continue
        for e in range(kv.shape[1]):
            if eff[n, e] not in (EFFECT_NO_SCHEDULE, EFFECT_NO_EXECUTE):
                continue
            tolerated = False
            for o in range(len(th)):
                if tm[o] == TOL_EXISTS_ALL and th[o] != 0:
                    match = True
                elif tm[o] == TOL_EXISTS_KEY:
                    match = key[n, e] == th[o]
                else:
                    match = kv[n, e] == th[o] and th[o] != 0
                if match and (te[o] == 0 or te[o] == eff[n, e]):
                    tolerated = True
                    break
            if not tolerated:
                ok[n] = False
                break
    return ok


def _tie_count(score, feas) -> int:
    """Sequential mirror of ops.select.tie_count on the chosen view."""
    if not feas.any():
        return 0
    msk = np.where(feas, score, -np.inf)
    return int(((msk == msk.max()) & feas).sum()) - 1


def allocate_cpu(snap: SnapshotArrays, extras: AllocateExtras = None,
                 cfg: AllocateConfig = AllocateConfig(),
                 collect_telemetry: bool = False) -> Dict[str, np.ndarray]:
    """Run the allocate pass sequentially on the host. Returns the same
    decision arrays as ops.allocate_scan (task_node, task_mode, job_ready,
    job_pipelined).

    ``collect_telemetry`` additionally mirrors the kernel's in-graph
    CycleTelemetry block (telemetry/cycle.py) — per-family rejection
    counts, attempts, placements, discards, ties, rounds/pops, committed
    f32 sums, unplaced-reason histogram — under "telemetry" in the result.
    The mirror also replays the kernel's capacity-give-up short-circuit
    (hopeless jobs batch-finish after a stalled round WITHOUT being
    evaluated), which is decision-neutral but counter-relevant; with the
    flag off the oracle's historical behavior is byte-identical."""
    if extras is None:
        extras = AllocateExtras.neutral(snap)
    # wavefront width (ISSUE 16): normalize_wave is the single authority
    # for legal widths (pod-affinity / host-ports force W back to 1, like
    # the kernel); W > 1 swaps the section walk for the wave mirror below
    cfg = normalize_wave(cfg)
    wave_w = int(cfg.wave_width)
    wave_c = wave_candidate_depth(wave_w)
    job_share = np.asarray(extras.job_share)
    queue_deserved = np.asarray(extras.queue_deserved)
    ns_share = np.asarray(extras.ns_share)
    queue_share_extra = np.asarray(extras.queue_share_extra)
    block_nonrevocable = np.asarray(extras.block_nonrevocable)
    block_all = np.asarray(extras.block_all)
    task_revocable = np.asarray(extras.task_revocable)
    tdm_bonus = np.asarray(extras.tdm_bonus)
    template_na = np.asarray(extras.template_na_score)
    task_or_group = np.asarray(extras.task_or_group)
    or_feasible = np.asarray(extras.or_feasible)
    task_ports_a = np.asarray(extras.task_ports)
    node_ports_a = np.asarray(extras.node_ports)
    vol_ok = np.asarray(extras.task_volume_ok)
    vol_node = np.asarray(extras.task_volume_node)
    ports_placed: List[Tuple[int, int]] = []    # (node, port) this cycle
    task_pref_node = np.asarray(extras.task_pref_node)
    node_locked = np.asarray(extras.node_locked)
    target_job = int(extras.target_job)
    nodes, tasks, jobs, queues = snap.nodes, snap.tasks, snap.jobs, snap.queues
    N, R = np.array(nodes.idle).shape
    T = np.array(tasks.resreq).shape[0]
    J, M = np.array(jobs.task_table).shape

    idle = np.array(nodes.idle, dtype=np.float64).copy()
    pipe_extra = np.zeros((N, R))
    pods_extra = np.zeros(N, np.int64)
    G = np.array(nodes.gpu_memory).shape[1]
    gpu_extra = np.zeros((N, G))
    queue_allocated = np.array(queues.allocated, dtype=np.float64).copy()
    task_node = np.full(T, -1, np.int64)
    task_mode = np.zeros(T, np.int64)
    task_gpu = np.full(T, -1, np.int64)
    job_done = np.zeros(J, bool)
    job_ready = np.zeros(J, bool)
    job_pipelined = np.zeros(J, bool)
    job_popped = np.zeros(J, bool)

    jns = np.array(jobs.namespace)
    jvalid_all = np.array(jobs.valid)
    jvalid = jvalid_all & np.array(jobs.schedulable)
    n_pending = np.array(jobs.n_pending)
    jqueue = np.array(jobs.queue)
    jprio = np.array(jobs.priority)
    jrank = np.array(jobs.creation_rank)
    jready0 = np.array(jobs.ready_num)
    jmin = np.array(jobs.min_available)
    table = np.array(jobs.task_table)
    jreq32 = np.array(jobs.total_request, np.float32)
    total_cap = np.array(snap.cluster_capacity, np.float32)
    resreq32 = np.array(tasks.resreq, np.float32)
    ns_weight = np.array(snap.namespace_weight, np.float32)
    # live drf state (event-handler analog): committed allocations +
    # ReadyTaskNum, float32 accumulated in kernel order for bit-equality
    job_cursor = np.zeros(J, np.int64)
    job_alloc_count = np.zeros(J, np.int64)
    job_alloc_dyn = np.array(jobs.allocated, np.float32).copy()
    releasing = np.array(nodes.releasing)
    pipelined0 = np.array(nodes.pipelined)
    resreq = np.array(tasks.resreq, dtype=np.float64)
    best_effort = np.array(tasks.best_effort)
    tjob = np.array(tasks.job)
    t_selector = np.array(tasks.selector)
    t_tol_hash = np.array(tasks.tol_hash)
    t_tol_effect = np.array(tasks.tol_effect)
    t_tol_mode = np.array(tasks.tol_mode)
    t_template = np.array(tasks.template)
    t_preemptable = np.array(tasks.preemptable)
    t_gpu_req = np.array(tasks.gpu_request, dtype=np.float64)
    nodes_np = _as_np(nodes)
    aff_st = _affinity_state(extras) if cfg.enable_pod_affinity else None
    valid_sched = nodes_np.valid & nodes_np.schedulable

    def _pick_gpu(node, req):
        """Lowest fitting card on the node (predicateGPU, gpu.go:41-56)."""
        if req <= 0:
            return -1
        gidle = (nodes_np.gpu_memory[node] - nodes_np.gpu_used[node]
                 - gpu_extra[node])
        for g in range(G):
            if gidle[g] >= req - _EPS:
                return g
        return -1

    def _wave_eval(ji, t, idle_v, pipe_v, pods_v, gpux_v):
        """Both feasibility views + score of task t against an arbitrary
        capacity state (the wave mirror evaluates every slot twice: the
        window-start snapshot for the candidate lists / TEL rows, the
        live state for the actual commit decision)."""
        sel, th = t_selector[t], t_tol_hash[t]
        te, tm = t_tol_effect[t], t_tol_mode[t]
        req, greq = resreq[t], t_gpu_req[t]
        node_ok = (~(block_nonrevocable & ~task_revocable[t])
                   & ~block_all
                   & (or_feasible[task_or_group[t]][:N]
                      if task_or_group[t] >= 0 else True)
                   & vol_ok[t]
                   & ((vol_node[t] < 0) | (np.arange(N) == vol_node[t]))
                   & (~node_locked | (ji == target_job)))
        f_now = node_ok & _feasible_one(nodes_np, req, sel, th, te, tm,
                                        idle_v, pods_v, greq, gpux_v)
        fut_v = np.maximum(idle_v + releasing - pipelined0 - pipe_v, 0.0)
        f_fut = node_ok & _feasible_one(nodes_np, req, sel, th, te, tm,
                                        fut_v, pods_v, greq, gpux_v)
        score = _score_one(cfg, nodes_np, req, idle_v, th, te, tm)
        score = score + (template_na[t_template[t]]
                         + (tdm_bonus if task_revocable[t]
                            else np.float32(0.0)))
        if task_pref_node[t] >= 0:
            score = score + 100.0 * (np.arange(N) == task_pref_node[t])
        return f_now, f_fut, score

    def _wave_tel_row(ji, t, idle_v, pipe_v, pods_v, gpux_v):
        """The sequential loop's per-family rejection block, against the
        wave's window-start snapshot (kernel _wave_rej1: a replayed slot
        is counted in the wave that finally processes it, vs THAT wave's
        start state). Ports/affinity slots are structurally 0: both
        features force wave_width back to 1 (normalize_wave)."""
        sel, th = t_selector[t], t_tol_hash[t]
        te, tm = t_tol_effect[t], t_tol_mode[t]
        req, greq = resreq[t], t_gpu_req[t]
        live = valid_sched
        tmpl = _tmpl_ok(nodes_np, sel, th, te, tm)
        blk = (block_nonrevocable & ~task_revocable[t]) | block_all
        orr = (or_feasible[task_or_group[t]][:N]
               if task_or_group[t] >= 0 else np.ones(N, bool))
        volr = vol_ok[t] & ((vol_node[t] < 0)
                            | (np.arange(N) == vol_node[t]))
        lockr = node_locked & ~(ji == target_job)
        pcf = (nodes_np.pod_count + pods_v) < nodes_np.max_pods
        gidle2 = nodes_np.gpu_memory - nodes_np.gpu_used - gpux_v
        gfit = (greq <= 0) | (gidle2 >= greq - _EPS).any(axis=-1)
        fit_n = np.all(req[None, :] <= idle_v + _EPS, axis=-1)
        fut_v = np.maximum(idle_v + releasing - pipelined0 - pipe_v, 0.0)
        fit_f = np.all(req[None, :] <= fut_v + _EPS, axis=-1)
        tel["pred_reject"] += np.asarray([
            int((live & ~tmpl).sum()), int((live & blk).sum()),
            int((live & ~orr).sum()), int((live & ~volr).sum()),
            int((live & lockr).sum()), 0,
            int((live & ~pcf).sum()), int((live & ~gfit).sum()),
            int((live & ~fit_n).sum()), int((live & ~fit_f).sum()), 0])
        tel["attempts"] += 1

    def _wave_section(ji, slot0, ready0_dyn, can_batch, placed):
        """Wavefront transliteration of one popped job's section walk
        (ISSUE 16). Decision-wise this IS the sequential walk: the wave
        commit rule is order-preserving by construction (capacity is
        monotone non-increasing inside a section, so untouched rows keep
        their window-start feasibility/score exactly; touched nodes are
        rescored at the live state; a slot whose pre-wave top-C list is
        exhausted by same-wave commits truncates the wave and replays),
        which lets the mirror commit via the plain live-state argmax.
        What the wave structure adds is the COUNTERS: waves / commits /
        truncations / replays / the per-wave histogram exist only here,
        and TEL rows are counted in the wave that finally processes a
        slot, against that wave's window-start snapshot — exactly like
        the kernel's _wave_body. Returns the new absolute cursor plus
        the section tallies the gang finalize consumes."""
        placed_sum32 = np.zeros(len(total_cap), np.float32)
        n_alloc = n_pipe = 0
        stopped = broke = False
        wpos = slot0
        n_adv = 0
        while wpos < M and not stopped and not broke:
            idle0 = idle.copy()
            pipe0 = pipe_extra.copy()
            pods0 = pods_extra.copy()
            gpux0 = gpu_extra.copy()
            touched: List[int] = []
            trunc = False
            trunc_pos = wave_w
            commits = 0
            for w in range(wave_w):
                s_abs = wpos + w
                if s_abs >= M or stopped or broke:
                    continue
                t = int(table[ji, s_abs])
                if t < 0:
                    continue
                if best_effort[t]:
                    if not trunc:
                        n_adv += 1     # consumed, never queued
                    continue
                if trunc:
                    # deferred: replays at the next wave's window head
                    if collect_telemetry:
                        tel["wave_replays"] += 1
                    continue
                # pre-wave candidate lists vs the window-start snapshot:
                # feasible nodes by (score desc, index asc), top-C kept
                f_n0, f_f0, sc0 = _wave_eval(ji, t, idle0, pipe0,
                                             pods0, gpux0)
                order = np.lexsort((np.arange(N), -sc0))
                lst_n = [int(i) for i in order if f_n0[i]]
                tset = set(touched)
                dec_n = (any(e not in tset for e in lst_n[:wave_c])
                         or len(lst_n) <= wave_c)
                if cfg.enable_pipelining:
                    lst_f = [int(i) for i in order if f_f0[i]]
                    dec_f = (any(e not in tset for e in lst_f[:wave_c])
                             or len(lst_f) <= wave_c)
                # live-state views (== the kernel's list resolve: first
                # untouched entry vs every touched node rescored)
                f_nc, f_fc, scc = _wave_eval(ji, t, idle, pipe_extra,
                                             pods_extra, gpu_extra)
                fnd_n = bool(f_nc.any())
                if cfg.enable_pipelining:
                    conflict = (not dec_n) or (not fnd_n and not dec_f)
                else:
                    conflict = not dec_n
                if conflict:
                    trunc = True
                    trunc_pos = w
                    if collect_telemetry:
                        tel["wave_replays"] += 1
                    continue
                do_alloc = fnd_n
                do_pipe = (not fnd_n and cfg.enable_pipelining
                           and bool(f_fc.any()))
                if collect_telemetry:
                    _wave_tel_row(ji, t, idle0, pipe0, pods0, gpux0)
                n_adv += 1
                if not (do_alloc or do_pipe):
                    broke = True        # allocate.go:210-214
                    continue
                req, greq = resreq[t], t_gpu_req[t]
                feas_c = f_nc if do_alloc else f_fc
                node = int(np.argmax(np.where(feas_c, scc, -np.inf)))
                if do_alloc:
                    idle[node] -= req
                    task_mode[t] = MODE_ALLOCATED
                    n_alloc += 1
                else:
                    pipe_extra[node] += req
                    task_mode[t] = MODE_PIPELINED
                    n_pipe += 1
                pods_extra[node] += 1
                card = _pick_gpu(node, greq)
                if card >= 0:
                    gpu_extra[node, card] += greq
                    task_gpu[t] = card
                task_node[t] = node
                placed.append(t)
                placed_sum32 = placed_sum32 + resreq32[t]
                touched.append(node)
                commits += 1
                if collect_telemetry:
                    # ties of the fired view, pre-wave raw count (the
                    # kernel reports the sweep's count; exact at the
                    # window head, a cheap upper bound after commits)
                    if do_alloc:
                        tel["placed_now"] += 1
                        tel["argmax_ties"] += _tie_count(sc0, f_n0)
                    else:
                        tel["placed_future"] += 1
                        tel["argmax_ties"] += _tie_count(sc0, f_f0)
                ready_aft = (not cfg.enable_gang
                             or (ready0_dyn + n_alloc) >= jmin[ji])
                remaining = any(table[ji, s] >= 0
                                and not best_effort[table[ji, s]]
                                for s in range(s_abs + 1, M))
                if ready_aft and remaining and not can_batch:
                    stopped = True      # yield (allocate.go:262-265)
            if collect_telemetry:
                tel["wave_hist"][min(commits,
                                     len(tel["wave_hist"]) - 1)] += 1
                tel["wave_commits"] += commits
                if trunc:
                    tel["wave_truncations"] += 1
                tel["waves"] += 1
            wpos += trunc_pos if trunc else wave_w
        return slot0 + n_adv, stopped, placed_sum32, n_alloc, n_pipe

    # telemetry mirror state (telemetry/cycle.CycleTelemetry, kernel order)
    tel = None
    progressed = True
    if collect_telemetry:
        from ..telemetry.cycle import PRED_FAMILIES, WAVE_BINS
        tel = dict(pred_reject=np.zeros(len(PRED_FAMILIES), np.int64),
                   attempts=0, placed_now=0, placed_future=0,
                   gang_discarded=0, argmax_ties=0, rounds=0, pops=0,
                   committed=np.zeros(len(total_cap), np.float32),
                   wave_hist=np.zeros(WAVE_BINS, np.int64),
                   wave_commits=0, wave_truncations=0, wave_replays=0,
                   waves=0)
        # cheapest pending request per job per dim (the kernel's
        # jobs_min_req): min over ALL real table slots, f32
        jobs_min_req = np.where(
            (table >= 0)[:, :, None], resreq32[np.maximum(table, 0)],
            np.inf).min(axis=1)

    while True:
        overused = np.any(queue_allocated > queue_deserved + 1e-6, axis=-1)
        elig = jvalid & ~job_done & (job_cursor < n_pending) & ~overused[jqueue]
        if not elig.any():
            break
        # capacity-give-up mirror (kernel hopeless_jobs): after a stalled
        # round, eligible jobs whose cheapest pending request exceeds every
        # node's idle AND future idle batch-finish without being evaluated
        # — decision-identical, but their pops/attempts never happen, so
        # the telemetry mirror must replay it
        hopeless = np.zeros(J, bool)
        if collect_telemetry and not progressed:
            fut_all = np.maximum(idle + releasing - pipelined0 - pipe_extra,
                                 0.0)
            bound = np.max(np.where(valid_sched[:, None],
                                    np.maximum(idle, fut_all), -np.inf),
                           axis=0)
            hopeless = elig & (jobs_min_req > bound + 1e-5).any(axis=-1)
        qshare = np.max(
            np.where(np.isfinite(queue_deserved) & (queue_deserved > 0),
                     queue_allocated / np.maximum(queue_deserved, 1e-9), 0.0),
            axis=-1) + queue_share_extra
        # drf keys from live allocations (event-handler analog,
        # drf.go:511-536) — delegated to ops.fairshare on host arrays so
        # the oracle's keys stay BIT-identical to the kernel's (same
        # delegation rationale as _hdrf_keys above)
        if cfg.drf_ns_order:
            from ..ops.fairshare import namespace_shares
            ns_share_k = np.asarray(namespace_shares(
                job_alloc_dyn, jns, jvalid_all, ns_weight, total_cap))
        else:
            ns_share_k = np.asarray(ns_share, float)
        if cfg.drf_job_order:
            from ..ops.fairshare import drf_job_shares
            job_share_k = np.asarray(drf_job_shares(
                job_alloc_dyn, total_cap, jvalid_all))
        else:
            job_share_k = np.asarray(job_share, float)
        ready_dyn = jready0 + job_alloc_count
        ready_now = (ready_dyn >= jmin) & (jmin > 0)
        key_rows = [ns_share_k[jns], jns.astype(float), qshare[jqueue]]
        if cfg.enable_hdrf:
            hcols = _hdrf_keys(extras.hierarchy, job_alloc_dyn, jreq32,
                               jvalid_all, total_cap)
            key_rows += [hcols[jqueue, c] for c in range(hcols.shape[1])]
        key_rows += [jqueue.astype(float), -jprio.astype(float)]
        if cfg.tdm_job_order:
            key_rows.append(np.array(jobs.preemptable).astype(float))
        if cfg.sla_job_order:
            key_rows.append(np.asarray(extras.job_deadline, float))
        key_rows += [ready_now.astype(float), job_share_k,
                     jrank.astype(float)]
        keys = np.stack(key_rows)
        best_ji, best_key = -1, None
        for ji in range(J):
            if not elig[ji]:
                continue
            k = tuple(keys[:, ji])
            if best_key is None or k < best_key:
                best_key, best_ji = k, ji
        ji = best_ji
        # hopeless jobs (minus the popped one, whose fate the evaluation
        # below decides) finish without evaluation, like the kernel's
        # give_up OR into job_done/job_popped before the .at[ji].set
        job_done |= hopeless
        job_popped |= hopeless

        saved = (idle.copy(), pipe_extra.copy(), pods_extra.copy(),
                 gpu_extra.copy())
        saved_ports = list(ports_placed)
        # exact re-pop fusion (see ops/allocate_scan.py body): with fully
        # static ordering keys the same ready job wins every following pop,
        # so the single-task yields batch into one pass
        keys_static = not (cfg.drf_job_order or cfg.drf_ns_order
                           or cfg.enable_hdrf)
        # ANY finite deserved (a 0 counts: zero-quota queues flip overused
        # on the first commit) breaks the static-keys argument
        des_row = queue_deserved[jqueue[ji]]
        can_batch = keys_static and not bool(
            np.any(np.isfinite(des_row)))
        if aff_st is not None:
            saved_aff = (aff_st["aff_cnt"].copy(), aff_st["anti_cnt"].copy())
        placed: List[int] = []
        placed_sum32 = np.zeros(len(total_cap), np.float32)
        n_alloc = n_pipe = 0
        ready0_dyn = int(jready0[ji] + job_alloc_count[ji])
        stopped = False
        slot = int(job_cursor[ji])
        if wave_w > 1:
            (slot, stopped, placed_sum32,
             n_alloc, n_pipe) = _wave_section(ji, slot, ready0_dyn,
                                              can_batch, placed)
        while wave_w == 1 and slot < M:
            t = table[ji, slot]
            if t < 0:
                break               # past the row's real entries
            slot += 1               # the task is popped (consumed)
            if best_effort[t]:
                continue            # never queued (allocate.go:186-195)
            sel = t_selector[t]
            th = t_tol_hash[t]
            te = t_tol_effect[t]
            tm = t_tol_mode[t]
            req = resreq[t]
            greq = t_gpu_req[t]
            node_ok = (~(block_nonrevocable & ~task_revocable[t])
                       & ~block_all
                       & (or_feasible[task_or_group[t]][:len(block_all)]
                          if task_or_group[t] >= 0 else True)
                       & vol_ok[t]
                       & ((vol_node[t] < 0)
                          | (np.arange(N) == vol_node[t]))
                       & (~node_locked | (ji == target_job)))
            if cfg.enable_host_ports:
                tports = [p for p in task_ports_a[t] if p > 0]
                if tports:
                    conf_mask = np.zeros(N, bool)
                    for p in tports:
                        conf_mask |= (node_ports_a == p).any(axis=-1)
                    for pn, pp in ports_placed:
                        if pp in tports:
                            conf_mask[pn] = True
                    node_ok &= ~conf_mask
            feas_now = node_ok & _feasible_one(nodes_np, req, sel, th, te, tm,
                                               idle, pods_extra,
                                               greq, gpu_extra)
            score = _score_one(cfg, nodes_np, req, idle, th, te, tm)
            score = score + (template_na[t_template[t]]
                             + (tdm_bonus if task_revocable[t]
                                else np.float32(0.0)))
            if task_pref_node[t] >= 0:
                score = score + 100.0 * (np.arange(len(score)) == task_pref_node[t])
            if aff_st is not None:
                aff_feas, aff_score = _affinity_one(aff_st, t, valid_sched)
                feas_now &= aff_feas
                score = score + cfg.pod_affinity_weight * aff_score
            if collect_telemetry:
                # per-family rejection counts over live nodes, families
                # independent, pre-placement capacity view — the kernel's
                # task_step TEL block, loop-structured
                live = valid_sched
                tmpl = _tmpl_ok(nodes_np, sel, th, te, tm)
                blk = (block_nonrevocable & ~task_revocable[t]) | block_all
                orr = (or_feasible[task_or_group[t]][:N]
                       if task_or_group[t] >= 0 else np.ones(N, bool))
                volr = vol_ok[t] & ((vol_node[t] < 0)
                                    | (np.arange(N) == vol_node[t]))
                lockr = node_locked & ~(ji == target_job)
                ports_rej = 0
                if cfg.enable_host_ports:
                    tp2 = [p for p in task_ports_a[t] if p > 0]
                    conf2 = np.zeros(N, bool)
                    for p in tp2:
                        conf2 |= (node_ports_a == p).any(axis=-1)
                    for pn, pp in ports_placed:
                        if pp in tp2:
                            conf2[pn] = True
                    ports_rej = int((live & conf2).sum())
                pcf = (nodes_np.pod_count + pods_extra) < nodes_np.max_pods
                gidle2 = (nodes_np.gpu_memory - nodes_np.gpu_used
                          - gpu_extra)
                gfit = (greq <= 0) | (gidle2 >= greq - _EPS).any(axis=-1)
                fit_n = np.all(req[None, :] <= idle + _EPS, axis=-1)
                fut_v = np.maximum(
                    idle + releasing - pipelined0 - pipe_extra, 0.0)
                fit_f = np.all(req[None, :] <= fut_v + _EPS, axis=-1)
                aff_rej = (int((live & ~aff_feas).sum())
                           if aff_st is not None else 0)
                tel["pred_reject"] += np.asarray([
                    int((live & ~tmpl).sum()), int((live & blk).sum()),
                    int((live & ~orr).sum()), int((live & ~volr).sum()),
                    int((live & lockr).sum()), ports_rej,
                    int((live & ~pcf).sum()), int((live & ~gfit).sum()),
                    int((live & ~fit_n).sum()), int((live & ~fit_f).sum()),
                    aff_rej])
                tel["attempts"] += 1
            did_place = False
            if feas_now.any():
                node = int(np.argmax(np.where(feas_now, score, -np.inf)))
                idle[node] -= req
                pods_extra[node] += 1
                card = _pick_gpu(node, greq)
                if card >= 0:
                    gpu_extra[node, card] += greq
                    task_gpu[t] = card
                task_node[t] = node
                task_mode[t] = MODE_ALLOCATED
                placed.append(t)
                placed_sum32 = placed_sum32 + resreq32[t]
                n_alloc += 1
                did_place = True
                if collect_telemetry:
                    tel["placed_now"] += 1
                    tel["argmax_ties"] += _tie_count(score, feas_now)
                if aff_st is not None:
                    _affinity_place(aff_st, t, node)
                if cfg.enable_host_ports:
                    ports_placed.extend(
                        (node, p) for p in task_ports_a[t] if p > 0)
            elif cfg.enable_pipelining:
                future = np.maximum(idle + releasing - pipelined0 - pipe_extra, 0)
                feas_fut = node_ok & _feasible_one(nodes_np, req, sel, th, te, tm, future,
                                         pods_extra, greq, gpu_extra)
                if aff_st is not None:
                    feas_fut &= aff_feas
                if feas_fut.any():
                    node = int(np.argmax(np.where(feas_fut, score, -np.inf)))
                    pipe_extra[node] += req
                    pods_extra[node] += 1
                    card = _pick_gpu(node, greq)
                    if card >= 0:
                        gpu_extra[node, card] += greq
                        task_gpu[t] = card
                    task_node[t] = node
                    task_mode[t] = MODE_PIPELINED
                    placed.append(t)
                    placed_sum32 = placed_sum32 + resreq32[t]
                    n_pipe += 1
                    did_place = True
                    if collect_telemetry:
                        tel["placed_future"] += 1
                        tel["argmax_ties"] += _tie_count(score, feas_fut)
                    if aff_st is not None:
                        _affinity_place(aff_st, t, node)
                    if cfg.enable_host_ports:
                        ports_placed.extend(
                            (node, p) for p in task_ports_a[t] if p > 0)
            if not did_place:
                # no node can take the task at all -> the job breaks
                # (allocate.go:210-214 PredicateNodes empty)
                break
            # yield: a ready job with tasks still queued re-enters the
            # job queue (allocate.go:262-265)
            ready_aft = (not cfg.enable_gang
                         or (ready0_dyn + n_alloc) >= jmin[ji])
            remaining = any(table[ji, s] >= 0 and not best_effort[table[ji, s]]
                            for s in range(slot, M))
            if ready_aft and remaining and not can_batch:
                stopped = True
                break
        job_cursor[ji] = slot

        ready = (ready0_dyn + n_alloc) >= jmin[ji]
        pipelined = (ready0_dyn + n_alloc + n_pipe) >= jmin[ji]
        if not cfg.enable_gang:
            ready = True
        if ready or pipelined:
            queue_allocated[jqueue[ji]] += resreq[placed].sum(axis=0) if placed else 0
            job_alloc_dyn[ji] = job_alloc_dyn[ji] + placed_sum32
            job_alloc_count[ji] += n_alloc
            job_ready[ji] = bool(ready)
            job_pipelined[ji] = bool(pipelined and not ready)
            if not ready:
                # kept-but-unready gang: capacity held, no binds
                for t in placed:
                    task_mode[t] = MODE_PIPELINED
            if collect_telemetry:
                tel["committed"] = tel["committed"] + placed_sum32
        else:
            idle, pipe_extra, pods_extra, gpu_extra = saved
            if aff_st is not None:
                aff_st["aff_cnt"], aff_st["anti_cnt"] = saved_aff
            ports_placed = saved_ports
            for t in placed:
                task_node[t] = -1
                task_mode[t] = MODE_NONE
                task_gpu[t] = -1
            if collect_telemetry:
                tel["gang_discarded"] += len(placed)
        job_done[ji] = not stopped
        job_popped[ji] = True
        progressed = (n_alloc > 0) or bool(pipelined) or bool(ready)
        if collect_telemetry:
            tel["rounds"] += 1
            tel["pops"] += 1

    out = dict(task_node=task_node, task_mode=task_mode, task_gpu=task_gpu,
               job_ready=job_ready,
               job_pipelined=job_pipelined, job_attempted=job_popped,
               idle=idle,
               queue_allocated=queue_allocated)
    if collect_telemetry:
        from ..api.types import TaskStatus
        from ..telemetry.cycle import PRED_FAMILIES, UNPLACED_REASONS
        t_status = np.array(tasks.status)
        t_valid = np.array(tasks.valid)
        pend = (t_valid & ~best_effort & (tjob >= 0)
                & (t_status == int(TaskStatus.PENDING)))
        unplaced = pend & (task_mode == MODE_NONE)
        popped_t = job_popped[np.maximum(tjob, 0)]
        kept_t = (job_ready | job_pipelined)[np.maximum(tjob, 0)]
        reason = np.where(~popped_t, 0, np.where(kept_t, 2, 1))
        hist = np.zeros(len(UNPLACED_REASONS), np.int64)
        for r in reason[unplaced]:
            hist[r] += 1
        out["telemetry"] = {
            "pred_reject": {f: int(v) for f, v in
                            zip(PRED_FAMILIES, tel["pred_reject"])},
            "unplaced": {r: int(v) for r, v in
                         zip(UNPLACED_REASONS, hist)},
            "committed": [float(v) for v in tel["committed"]],
            "attempts": tel["attempts"],
            "placed_now": tel["placed_now"],
            "placed_future": tel["placed_future"],
            "gang_discarded": tel["gang_discarded"],
            "argmax_ties": tel["argmax_ties"],
            "rounds": tel["rounds"], "pops": tel["pops"],
            "dyn_launches": 0, "dyn_pops": 0, "dyn_early_stops": 0,
            "wave_commits": int(tel["wave_commits"]),
            "wave_truncations": int(tel["wave_truncations"]),
            "wave_replays": int(tel["wave_replays"]),
            "waves": int(tel["waves"]),
            "wave_hist": [int(v) for v in tel["wave_hist"]],
        }
    return out


def preempt_cpu(snap: SnapshotArrays, extras: AllocateExtras,
                victim_veto, skip_tasks=None, pcfg=None
                ) -> Dict[str, np.ndarray]:
    """Sequential CPU reference of the preempt/reclaim pass.

    Independent loop-structured mirror of the reference's preempt action
    (pkg/scheduler/actions/preempt/preempt.go:42-291: pop starving
    preemptors in job order, PredicateNodes, build the frozen per-node
    victim set through the tiered Preemptable dispatch
    (session_plugins.go:131-215), evict lowest-task-priority-first until
    the preemptor fits FutureIdle, pipeline, commit/discard per gang) and
    of reclaim.go:40-191 (mode="reclaim"). Decision oracle for
    ops.preempt.make_preempt_cycle: victim set, pipelined placements, and
    gang outcomes must be bit-identical. Shares recompute per eviction
    exactly like the kernel's carried f32 state (AllocateFunc/
    DeallocateFunc, drf.go:511-561, proportion.go:281-325).
    """
    from ..ops.fairshare import hdrf_level_keys
    from ..ops.preempt import PreemptConfig
    from ..api.types import TaskStatus

    if pcfg is None:
        pcfg = PreemptConfig()
    reclaim = pcfg.mode == "reclaim"
    intra = pcfg.mode == "preempt_intra"
    use_budget = "tdm" in [r for tier in pcfg.tiers for r in tier]
    cfg = pcfg.scoring

    nodes, tasks, jobs, queues = snap.nodes, snap.tasks, snap.jobs, snap.queues
    N, R = np.asarray(nodes.idle).shape
    T = np.asarray(tasks.resreq).shape[0]
    J, M = np.asarray(jobs.task_table).shape
    S = np.asarray(snap.namespace_weight).shape[0]

    veto = np.asarray(victim_veto, bool)
    skip = (np.zeros(T, bool) if skip_tasks is None
            else np.asarray(skip_tasks, bool))
    resreq32 = np.asarray(tasks.resreq, np.float32)
    t_status = np.asarray(tasks.status)
    t_node0 = np.asarray(tasks.node)
    t_prio = np.asarray(tasks.priority)
    t_best_effort = np.asarray(tasks.best_effort)
    t_valid = np.asarray(tasks.valid)
    t_preempt = np.asarray(tasks.preemptable)
    t_template = np.asarray(tasks.template)
    t_gpu_req = np.asarray(tasks.gpu_request, np.float64)
    t_selector = np.asarray(tasks.selector)
    t_tol_hash = np.asarray(tasks.tol_hash)
    t_tol_effect = np.asarray(tasks.tol_effect)
    t_tol_mode = np.asarray(tasks.tol_mode)
    tjob = np.asarray(tasks.job)
    vjob = np.maximum(tjob, 0)
    jqueue = np.asarray(jobs.queue)
    jns = np.asarray(jobs.namespace)
    jprio = np.asarray(jobs.priority)
    jrank = np.asarray(jobs.creation_rank)
    jvalid = np.asarray(jobs.valid)
    jmin = np.asarray(jobs.min_available)
    jready0 = np.asarray(jobs.ready_num)
    jnpend = np.asarray(jobs.n_pending)
    jsched = np.asarray(jobs.schedulable)
    jpreempt = np.asarray(jobs.preemptable)
    jreq32 = np.asarray(jobs.total_request, np.float32)
    table = np.asarray(jobs.task_table)
    vqueue = jqueue[vjob]
    vprio = jprio[vjob]
    vns = jns[vjob]
    total_cap = np.asarray(snap.cluster_capacity, np.float32)
    queue_deserved = np.asarray(extras.queue_deserved)
    vdes = queue_deserved[vqueue]
    q_reclaimable = np.asarray(queues.reclaimable)
    vreclaimable = q_reclaimable[vqueue]
    vrevocable = np.asarray(extras.revocable_node)[np.maximum(t_node0, 0)]
    ns_weight = np.asarray(snap.namespace_weight, np.float32)
    task_or_group = np.asarray(extras.task_or_group)
    or_feasible = np.asarray(extras.or_feasible)
    nodes_np = _as_np(nodes)

    def share32(alloc):
        """f32 dominant share (ops.fairshare.dominant_share formula)."""
        a = np.asarray(alloc, np.float32)
        frac = np.where(total_cap > 0,
                        a / np.maximum(total_cap, np.float32(1e-6)),
                        np.float32(0.0)).astype(np.float32)
        return frac.max(axis=-1)

    running = ((t_status == int(TaskStatus.RUNNING)) & t_valid
               & (t_node0 >= 0) & ~t_best_effort)
    waiting0 = np.zeros(J, np.int64)
    np.add.at(waiting0, vjob[(t_status == int(TaskStatus.PIPELINED))], 1)

    q_alloc0 = np.asarray(queues.allocated, np.float32)
    qshare = np.max(
        np.where(np.isfinite(queue_deserved) & (queue_deserved > 0),
                 q_alloc0 / np.maximum(queue_deserved, 1e-9), 0.0), axis=-1)
    overused = np.any(q_alloc0 > queue_deserved + 1e-6, axis=-1)

    if reclaim:
        starving = jvalid & jsched & (jnpend > 0) & ~overused[jqueue]
    else:
        starving = (jvalid & jsched
                    & (jready0 + waiting0 < jmin) & (jnpend > 0))
        if pcfg.tdm_starving:
            starving = starving & ~jpreempt

    future0 = np.asarray(snap.nodes.future_idle(), np.float32)

    # live f32 state, kernel-order accumulation
    extra_idle = np.zeros((N, R), np.float32)
    pipe_extra = np.zeros((N, R), np.float32)
    evicted = np.zeros(T, bool)
    task_node = np.full(T, -1, np.int64)
    task_mode = np.zeros(T, np.int64)
    job_done = np.zeros(J, bool)
    job_pipelined = np.zeros(J, bool)
    job_alloc_dyn = np.asarray(jobs.allocated, np.float32).copy()
    queue_alloc_dyn = q_alloc0.copy()
    ns_alloc_dyn = np.zeros((S, R), np.float32)
    for ji in range(J):
        if jvalid[ji] and 0 <= jns[ji] < S:
            ns_alloc_dyn[jns[ji]] += job_alloc_dyn[ji].astype(np.float32)
    # tdm disruption budget (maxVictims, tdm.go:219-229 + 304-340)
    budget_left = np.asarray(extras.job_victim_budget, np.int64).copy()

    extras_ns_share = np.asarray(extras.ns_share)
    extras_q_extra = np.asarray(extras.queue_share_extra)
    extras_job_share = np.asarray(extras.job_share)

    def victim_rule(name, t, ji):
        if name == "priority" and intra:
            return t_prio < t_prio[t]
        if name in ("priority", "gang"):
            return vprio < jprio[ji]
        if name == "conformance":
            return ~veto
        if name == "tdm":
            if t_preempt[t]:
                return np.zeros(T, bool)
            return t_preempt & ~vrevocable
        if name == "drf":
            ls = share32(job_alloc_dyn[ji] + resreq32[t])
            rs = share32(job_alloc_dyn[vjob] - resreq32)
            job_rule = (ls < rs) | (np.abs(ls - rs) <= _DELTA_PREEMPT)
            if not cfg.drf_ns_order:
                return job_rule
            nsw = np.maximum(ns_weight, np.float32(1.0))
            p_ns = jns[ji]
            lns = share32(ns_alloc_dyn[p_ns] + resreq32[t]) / nsw[p_ns]
            rns = share32(ns_alloc_dyn[vns] - resreq32) / nsw[vns]
            same_ns = vns == p_ns
            return np.where(same_ns, job_rule,
                            (lns < rns) | (((lns - rns) <= _DELTA_PREEMPT)
                                           & job_rule))
        if name == "proportion":
            q_alloc = queue_alloc_dyn[vqueue]
            after = q_alloc - resreq32
            has = ~np.all(q_alloc < resreq32, axis=-1)
            covered = np.all(
                np.where(np.isfinite(vdes), vdes <= after + 1e-6, True),
                axis=-1)
            return has & covered
        raise ValueError(f"unknown victim rule {name!r}")

    def hdrf_rule(t, ji, pre):
        K = min(64, T)
        base_alloc = job_alloc_dyn.copy()
        base_alloc[ji] += resreq32[t]
        lq = jqueue[ji]
        order = np.argsort(np.where(pre, t_prio.astype(np.float32), np.inf),
                           kind="stable")
        idx = order[:K]
        ok = np.zeros(T, bool)
        for v in idx:
            if not pre[v]:
                continue
            alloc_v = base_alloc.copy()
            alloc_v[tjob[v]] -= resreq32[v]
            keys = np.asarray(hdrf_level_keys(
                extras.hierarchy, alloc_v, jreq32, jvalid, total_cap))
            kl, kr = keys[lq], keys[jqueue[v]]
            neq = kl != kr
            if neq.any():
                first = int(np.argmax(neq))
                ok[v] = kl[first] < kr[first]
        return ok

    def victim_tier_masks(t, ji):
        vbase = running & ~evicted
        if reclaim:
            vbase = vbase & (vqueue != jqueue[ji]) & vreclaimable
        elif intra:
            vbase = vbase & (tjob == ji)
        else:
            vbase = vbase & (vqueue == jqueue[ji]) & (tjob != ji)
        if not any(len(tier) for tier in pcfg.tiers):
            return [np.zeros(T, bool)]
        out = []
        for tier in pcfg.tiers:
            if not tier:
                continue
            m = vbase.copy()
            for name in tier:
                if name == "drf_hdrf":
                    continue
                m = m & victim_rule(name, t, ji)
            if "drf_hdrf" in tier:
                m = hdrf_rule(t, ji, m)
            out.append(m)
        return out

    rounds = 0
    while rounds < J:
        elig = starving & ~job_done
        if not elig.any():
            break
        key_rows = [extras_ns_share[jns], jns.astype(np.float32),
                    (qshare[jqueue] + extras_q_extra[jqueue])]
        if pcfg.enable_hdrf:
            hcols = np.asarray(hdrf_level_keys(
                extras.hierarchy, job_alloc_dyn, jreq32, jvalid, total_cap))
            key_rows += [hcols[jqueue, c] for c in range(hcols.shape[1])]
        key_rows += [jqueue.astype(np.float32), -jprio.astype(np.float32),
                     extras_job_share, jrank.astype(np.float32)]
        keys = np.stack(key_rows)
        ji = -1
        best = None
        for j in range(J):
            if not elig[j]:
                continue
            k = tuple(keys[:, j])
            if best is None or k < best:
                best, ji = k, j
        rounds += 1

        saved = (extra_idle.copy(), pipe_extra.copy(), evicted.copy(),
                 task_node.copy(), task_mode.copy(), job_alloc_dyn.copy(),
                 queue_alloc_dyn.copy(), ns_alloc_dyn.copy(),
                 budget_left.copy())
        n_pipe = 0
        broke = False
        for t_idx in table[ji]:
            if t_idx < 0 or t_best_effort[t_idx] or skip[t_idx]:
                continue
            if intra and broke:
                continue
            if not reclaim and not intra:
                if jready0[ji] + waiting0[ji] + n_pipe >= jmin[ji]:
                    break          # no longer starving (preempt.go:99-101)
            t = int(t_idx)
            resreq = resreq32[t]
            avail = future0 + extra_idle - pipe_extra
            base = _feasible_one(
                nodes_np, np.zeros(R), t_selector[t], t_tol_hash[t],
                t_tol_effect[t], t_tol_mode[t],
                future0 + extra_idle, 0, gpu_req=float(t_gpu_req[t]))
            g = task_or_group[t]
            if g >= 0:
                base = base & or_feasible[g][:N]
            tiers = victim_tier_masks(t, ji)
            # per-node first-non-empty-tier victim set + evictable sums
            node_of = t_node0
            chosen = np.zeros(T, bool)
            evictable = np.zeros((N, R), np.float32)
            tier_has = np.zeros((len(tiers), N), bool)
            for k_t, mask in enumerate(tiers):
                on = mask & (node_of >= 0)
                np.logical_or.at(tier_has[k_t], node_of[on], True)
            first_tier = np.argmax(tier_has, axis=0)
            has_any = tier_has.any(axis=0)
            for k_t, mask in enumerate(tiers):
                sel = mask & (node_of >= 0)
                sel = sel & has_any[np.maximum(node_of, 0)] \
                    & (first_tier[np.maximum(node_of, 0)] == k_t)
                chosen |= sel
            on = chosen & (node_of >= 0)
            np.add.at(evictable, node_of[on], resreq32[on])
            enough = np.all(resreq[None, :] <= avail + evictable + 1e-5,
                            axis=-1)
            feas = base & enough
            if not feas.any():
                continue
            score = _score_one(cfg, nodes_np, np.asarray(resreq, np.float64),
                               np.asarray(snap.nodes.idle, np.float64),
                               t_tol_hash[t], t_tol_effect[t], t_tol_mode[t])
            node = int(np.argmax(np.where(feas, score, -np.inf)))
            # evict lowest task priority first until the preemptor fits
            k_ev = 0
            while k_ev < pcfg.max_victims_per_task:
                if np.all(resreq <= (extra_idle - pipe_extra
                                     + future0)[node] + 1e-5):
                    break
                cand = chosen & ~evicted & (node_of == node)
                if use_budget:
                    cand = cand & (budget_left[tjob] > 0)
                if not cand.any():
                    break
                order = np.lexsort((np.arange(T),
                                    np.where(cand, t_prio, 2 ** 31 - 1)))
                vt = int(order[0])
                if not cand[vt]:
                    break
                dres = resreq32[vt]
                extra_idle[node] += dres
                evicted[vt] = True
                budget_left[tjob[vt]] -= 1
                job_alloc_dyn[tjob[vt]] -= dres
                queue_alloc_dyn[vqueue[vt]] -= dres
                ns_alloc_dyn[jns[max(tjob[vt], 0)]] -= dres
                k_ev += 1
            fits = np.all(resreq <= (extra_idle - pipe_extra
                                     + future0)[node] + 1e-5)
            if fits:
                pipe_extra[node] += resreq
                job_alloc_dyn[ji] += resreq
                queue_alloc_dyn[jqueue[ji]] += resreq
                ns_alloc_dyn[jns[ji]] += resreq
                task_node[t] = node
                task_mode[t] = MODE_PIPELINED
                n_pipe += 1
            else:
                broke = True

        pipelined = bool(jready0[ji] + waiting0[ji] + n_pipe >= jmin[ji])
        keep = True if intra else pipelined
        if not keep:
            job_tasks = tjob == ji
            (extra_idle, pipe_extra, evicted, s_node, s_mode,
             job_alloc_dyn, queue_alloc_dyn, ns_alloc_dyn,
             budget_left) = saved
            # placements of THIS job's tasks revert; global arrays restore
            task_node, task_mode = s_node, s_mode
        job_done[ji] = True
        job_pipelined[ji] = pipelined

    return dict(task_node=task_node, task_mode=task_mode, evicted=evicted,
                job_pipelined=job_pipelined, job_attempted=job_done)


_DELTA_PREEMPT = np.float32(1e-6)
