"""Sequential CPU reference of the allocate pass.

An independent numpy re-implementation of the reference Go scheduler's
allocate loop (pkg/scheduler/actions/allocate/allocate.go:43-281 +
statement.go commit/discard), kept deliberately loop-structured the way the Go
code is. Two roles:

1. Decision-equivalence oracle for the compiled TPU path (SURVEY.md section 4:
   "JAX-vs-reference decision-equivalence tests") — both implementations must
   produce identical bind decisions on the same packed snapshot.
2. The CPU baseline bench.py measures against (BASELINE.md north star), since
   the Go toolchain is not available in this image.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np

from ..arrays.labels import (EFFECT_NO_EXECUTE, EFFECT_NO_SCHEDULE,
                             EFFECT_PREFER_NO_SCHEDULE, TOL_EQUAL,
                             TOL_EXISTS_ALL, TOL_EXISTS_KEY)
from ..arrays.schema import SnapshotArrays
from ..ops.allocate_scan import (MODE_ALLOCATED, MODE_NONE, MODE_PIPELINED,
                                 AllocateConfig)

_EPS = 1e-5


def _np(x):
    return np.asarray(x)


def _feasible_one(nodes, resreq, sel, th, te, tm, avail, pods_extra):
    N = avail.shape[0]
    ok = np.array(nodes.valid) & np.array(nodes.schedulable)
    ok &= (np.array(nodes.pod_count) + pods_extra) < np.array(nodes.max_pods)
    ok &= np.all(resreq[None, :] <= avail + _EPS, axis=-1)
    labels = np.array(nodes.labels)
    for s in sel:
        if s != 0:
            ok &= np.any(labels == s, axis=-1)
    kv, key, eff = (np.array(nodes.taint_kv), np.array(nodes.taint_key),
                    np.array(nodes.taint_effect))
    for n in range(N):
        if not ok[n]:
            continue
        for e in range(kv.shape[1]):
            if eff[n, e] not in (EFFECT_NO_SCHEDULE, EFFECT_NO_EXECUTE):
                continue
            tolerated = False
            for o in range(len(th)):
                if tm[o] == TOL_EXISTS_ALL and th[o] != 0:
                    match = True
                elif tm[o] == TOL_EXISTS_KEY:
                    match = key[n, e] == th[o]
                else:
                    match = kv[n, e] == th[o] and th[o] != 0
                if match and (te[o] == 0 or te[o] == eff[n, e]):
                    tolerated = True
                    break
            if not tolerated:
                ok[n] = False
                break
    return ok


def _score_one(cfg: AllocateConfig, nodes, resreq, idle, th, te, tm):
    allocatable = np.array(nodes.allocatable)
    used = allocatable - idle
    N = idle.shape[0]
    score = np.zeros(N)
    if cfg.binpack_weight:
        applicable = (resreq > 0)[None, :] & (allocatable > 0)
        frac = np.divide(used + resreq[None, :], allocatable,
                         out=np.zeros_like(used), where=allocatable > 0)
        w = np.ones_like(resreq)[None, :] * applicable
        wsum = np.maximum(w.sum(-1), 1e-9)
        raw = (np.where(applicable, frac, 0) * w).sum(-1) / wsum
        raw = np.where((np.where(applicable, frac, 0) > 1 + 1e-6).any(-1), 0, raw)
        score += cfg.binpack_weight * raw * 100
    if cfg.least_allocated_weight:
        cap = np.maximum(allocatable, 1e-9)
        free = np.clip((allocatable - used - resreq[None, :]) / cap, 0, 1)
        counted = allocatable > 0
        n = np.maximum(counted.sum(-1), 1)
        score += cfg.least_allocated_weight * (free * counted).sum(-1) / n * 100
    if cfg.most_allocated_weight:
        cap = np.maximum(allocatable, 1e-9)
        uf = np.clip((used + resreq[None, :]) / cap, 0, 1)
        counted = allocatable > 0
        n = np.maximum(counted.sum(-1), 1)
        score += cfg.most_allocated_weight * (uf * counted).sum(-1) / n * 100
    if cfg.balanced_weight:
        cap = np.maximum(allocatable, 1e-9)
        frac = np.clip((used + resreq[None, :]) / cap, 0, 1)
        counted = (allocatable > 0).astype(float)
        n = np.maximum(counted.sum(-1), 1.0)
        mean = (frac * counted).sum(-1) / n
        var = (((frac - mean[:, None]) ** 2) * counted).sum(-1) / n
        score += cfg.balanced_weight * (1.0 - np.sqrt(var)) * 100
    if cfg.taint_prefer_weight:
        kv, key, eff = (np.array(nodes.taint_kv), np.array(nodes.taint_key),
                        np.array(nodes.taint_effect))
        intol = np.zeros(N)
        for n in range(N):
            for e in range(kv.shape[1]):
                if eff[n, e] != EFFECT_PREFER_NO_SCHEDULE:
                    continue
                tolerated = False
                for o in range(len(th)):
                    if tm[o] == TOL_EXISTS_ALL and th[o] != 0:
                        match = True
                    elif tm[o] == TOL_EXISTS_KEY:
                        match = key[n, e] == th[o]
                    else:
                        match = kv[n, e] == th[o] and th[o] != 0
                    if match and (te[o] == 0 or te[o] == eff[n, e]):
                        tolerated = True
                        break
                if not tolerated:
                    intol[n] += 1
        mx = max(intol.max(), 1)
        score += cfg.taint_prefer_weight * (1.0 - intol / mx) * 100
    return score


def allocate_cpu(snap: SnapshotArrays, job_share: np.ndarray,
                 queue_deserved: np.ndarray, ns_share: np.ndarray = None,
                 cfg: AllocateConfig = AllocateConfig()) -> Dict[str, np.ndarray]:
    """Run the allocate pass sequentially on the host. Returns the same
    decision arrays as ops.allocate_scan (task_node, task_mode, job_ready,
    job_pipelined)."""
    nodes, tasks, jobs, queues = snap.nodes, snap.tasks, snap.jobs, snap.queues
    N, R = np.array(nodes.idle).shape
    T = np.array(tasks.resreq).shape[0]
    J, M = np.array(jobs.task_table).shape

    idle = np.array(nodes.idle, dtype=np.float64).copy()
    pipe_extra = np.zeros((N, R))
    pods_extra = np.zeros(N, np.int64)
    queue_allocated = np.array(queues.allocated, dtype=np.float64).copy()
    task_node = np.full(T, -1, np.int64)
    task_mode = np.zeros(T, np.int64)
    job_done = np.zeros(J, bool)
    job_ready = np.zeros(J, bool)
    job_pipelined = np.zeros(J, bool)

    jns = np.array(jobs.namespace)
    if ns_share is None:
        ns_share = np.zeros(int(jns.max(initial=0)) + 1, np.float32)
    jvalid = np.array(jobs.valid) & np.array(jobs.schedulable)
    n_pending = np.array(jobs.n_pending)
    jqueue = np.array(jobs.queue)
    jprio = np.array(jobs.priority)
    jrank = np.array(jobs.creation_rank)
    jready0 = np.array(jobs.ready_num)
    jmin = np.array(jobs.min_available)
    table = np.array(jobs.task_table)
    releasing = np.array(nodes.releasing)
    pipelined0 = np.array(nodes.pipelined)
    resreq = np.array(tasks.resreq, dtype=np.float64)
    best_effort = np.array(tasks.best_effort)
    tjob = np.array(tasks.job)

    while True:
        overused = np.all(queue_allocated >= queue_deserved - 1e-6, axis=-1)
        elig = jvalid & ~job_done & (n_pending > 0) & ~overused[jqueue]
        if not elig.any():
            break
        qshare = np.max(
            np.where(np.isfinite(queue_deserved) & (queue_deserved > 0),
                     queue_allocated / np.maximum(queue_deserved, 1e-9), 0.0),
            axis=-1)
        ready_now = (jready0 >= jmin) & (jmin > 0)
        keys = np.stack([
            np.asarray(ns_share, float)[jns], jns.astype(float),
            qshare[jqueue], jqueue.astype(float), -jprio.astype(float),
            ready_now.astype(float), np.asarray(job_share, float),
            jrank.astype(float)])
        best_ji, best_key = -1, None
        for ji in range(J):
            if not elig[ji]:
                continue
            k = tuple(keys[:, ji])
            if best_key is None or k < best_key:
                best_key, best_ji = k, ji
        ji = best_ji

        saved = (idle.copy(), pipe_extra.copy(), pods_extra.copy())
        placed: List[int] = []
        n_alloc = n_pipe = 0
        for slot in range(M):
            t = table[ji, slot]
            if t < 0 or best_effort[t]:
                continue
            sel = np.array(tasks.selector)[t]
            th = np.array(tasks.tol_hash)[t]
            te = np.array(tasks.tol_effect)[t]
            tm = np.array(tasks.tol_mode)[t]
            req = resreq[t]
            feas_now = _feasible_one(nodes, req, sel, th, te, tm, idle, pods_extra)
            score = _score_one(cfg, nodes, req, idle, th, te, tm)
            if feas_now.any():
                node = int(np.argmax(np.where(feas_now, score, -np.inf)))
                idle[node] -= req
                pods_extra[node] += 1
                task_node[t] = node
                task_mode[t] = MODE_ALLOCATED
                placed.append(t)
                n_alloc += 1
            elif cfg.enable_pipelining:
                future = np.maximum(idle + releasing - pipelined0 - pipe_extra, 0)
                feas_fut = _feasible_one(nodes, req, sel, th, te, tm, future,
                                         pods_extra)
                if feas_fut.any():
                    node = int(np.argmax(np.where(feas_fut, score, -np.inf)))
                    pipe_extra[node] += req
                    pods_extra[node] += 1
                    task_node[t] = node
                    task_mode[t] = MODE_PIPELINED
                    placed.append(t)
                    n_pipe += 1

        ready = (jready0[ji] + n_alloc) >= jmin[ji]
        pipelined = (jready0[ji] + n_alloc + n_pipe) >= jmin[ji]
        if not cfg.enable_gang:
            ready = True
        if ready or pipelined:
            queue_allocated[jqueue[ji]] += resreq[placed].sum(axis=0) if placed else 0
            job_ready[ji] = bool(ready)
            job_pipelined[ji] = bool(pipelined and not ready)
        else:
            idle, pipe_extra, pods_extra = saved
            for t in placed:
                task_node[t] = -1
                task_mode[t] = MODE_NONE
        job_done[ji] = True

    return dict(task_node=task_node, task_mode=task_mode, job_ready=job_ready,
                job_pipelined=job_pipelined, idle=idle,
                queue_allocated=queue_allocated)
