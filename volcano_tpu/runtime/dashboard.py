"""Dashboard: HTTP UI over the control plane's object stores.

Reference: cmd/dashboard/app/server.go:59-233 — an HTTP server that
periodically polls cluster + volcano objects into a cached ``Page`` of
tables (jobs, podgroups, queues, pods) behind a static frontend.  Here the
page is built straight from the in-memory API server, cached with a TTL
(the reference's poll interval), and served as server-rendered HTML plus a
JSON API (``/api/page``), a Prometheus exposition passthrough
(``/metrics``), the scheduler's flight-recorder ring as JSON
(``/api/telemetry`` — per-cycle snapshots; /metrics stays cumulative),
``/healthz``, the span tracer's Chrome trace-event export
(``/api/trace`` — load it in Perfetto; the ``latency``/``pipeline``
tables below render the same rings server-side), the scenario
quality registry (``/api/scenarios`` — one scorecard per scenario run,
mirrored by the ``scenarios`` table and the ``volcano_quality_*``
gauges), and the fleet tenant roster (``/api/fleet`` — per-tenant
bucket, serving counters, and degradation rung when the system serves
a multi-tenant fleet; mirrored by the ``fleet`` table).
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

from ..metrics import METRICS
from ..telemetry import spans as _spans

DEFAULT_REFRESH_SECONDS = 5.0


@dataclass
class Page:
    """One consistent snapshot of every dashboard table."""

    built_at: float = 0.0
    tables: Dict[str, Dict[str, List]] = field(default_factory=dict)

    def to_json(self) -> str:
        return json.dumps({"built_at": self.built_at, "tables": self.tables})


def build_page(system, now: Optional[float] = None) -> Page:
    """Poll the API server's stores into display tables.

    ``system`` is normally a VolcanoSystem; anything duck-typed works —
    the API-store tables need ``system.api``, and a system without one
    (e.g. a bare FleetScheduler) still gets the telemetry / latency /
    scenario / fleet / HA tables its surfaces feed."""
    api = getattr(system, "api", None)
    page = Page(built_at=now if now is not None else time.time())
    if api is None:
        return _build_runtime_tables(system, page)

    jobs = []
    for job in sorted(api.list("jobs"), key=lambda j: j.key):
        s = job.status
        jobs.append([job.namespace, job.name, job.queue,
                     s.state.phase.value, job.min_available, s.pending,
                     s.running, s.succeeded, s.failed, s.retry_count])
    page.tables["jobs"] = {
        "headers": ["Namespace", "Name", "Queue", "Phase", "MinAvailable",
                    "Pending", "Running", "Succeeded", "Failed", "Retries"],
        "rows": jobs}

    pgs = []
    for pg in sorted(api.list("podgroups"), key=lambda g: (g.namespace, g.name)):
        pgs.append([pg.namespace, pg.name, pg.queue, pg.phase.value,
                    pg.min_member, pg.running, pg.succeeded, pg.failed])
    page.tables["podgroups"] = {
        "headers": ["Namespace", "Name", "Queue", "Phase", "MinMember",
                    "Running", "Succeeded", "Failed"],
        "rows": pgs}

    queues = []
    for q in sorted(api.list("queues"), key=lambda q: q.name):
        counts = {k.replace("status.", ""): v for k, v in q.annotations.items()
                  if k.startswith("status.")}
        queues.append([q.name, q.weight, q.state.value, q.reclaimable,
                       json.dumps(counts) if counts else "-"])
    page.tables["queues"] = {
        "headers": ["Name", "Weight", "State", "Reclaimable", "PodGroups"],
        "rows": queues}

    pods = []
    for p in sorted(api.list("pods"), key=lambda p: (p.namespace, p.name)):
        pods.append([p.namespace, p.name, str(p.phase), p.node_name or "-"])
    page.tables["pods"] = {
        "headers": ["Namespace", "Name", "Phase", "Node"],
        "rows": pods}

    nodes = []
    for n in sorted(api.list("nodes"), key=lambda n: n.name):
        nodes.append([n.name,
                      f"{n.idle.get('cpu') / 1000:g}/{n.allocatable.get('cpu') / 1000:g}",
                      f"{n.idle.get('memory') / 2**30:.1f}Gi/"
                      f"{n.allocatable.get('memory') / 2**30:.1f}Gi",
                      len(n.tasks), "Ready" if n.ready else "NotReady"])
    page.tables["nodes"] = {
        "headers": ["Name", "CPU idle/alloc", "Mem idle/alloc", "Pods",
                    "Status"],
        "rows": nodes}

    return _build_runtime_tables(system, page)


def _build_runtime_tables(system, page: Page) -> Page:
    """The tables fed by runtime surfaces rather than API stores:
    flight-recorder telemetry, scenario scorecards, fleet roster, HA
    signals, and span-ring latency/occupancy. Shared by the full
    VolcanoSystem page and the api-less (fleet-only) page."""
    # ---- cycle telemetry (flight-recorder ring, newest first) ------------
    flight = _flight_of(system)
    if flight is not None:
        rows = []
        for e in reversed(flight.snapshots()[-16:]):
            tel = e.get("telemetry") or {}
            alloc = tel.get("allocate") or {}
            rej = alloc.get("pred_reject") or {}
            unp = alloc.get("unplaced") or {}
            # sharded-cycle / fault-ladder columns (PR 7): None -> "-"
            mesh = e.get("mesh_devices")
            reshard = e.get("resharding_copies")
            degr = e.get("degradation")
            rows.append([
                e.get("cycle", "-"),
                e.get("tenant", "-"),
                time.strftime("%H:%M:%S",
                              time.localtime(e.get("wall_ts", 0))),
                e.get("cycle_ms", "-"), e.get("binds", "-"),
                e.get("evictions", "-"), e.get("result", "-"),
                alloc.get("rounds", "-"), alloc.get("pops", "-"),
                sum(rej.values()) if rej else "-",
                sum(unp.values()) if unp else "-",
                alloc.get("argmax_ties", "-"),
                mesh if mesh is not None else "-",
                reshard if reshard is not None else "-",
                degr if degr is not None else "-",
            ])
        page.tables["telemetry"] = {
            "headers": ["Cycle", "Tenant", "Time", "ms", "Binds",
                        "Evictions", "Result", "Rounds", "Pops",
                        "PredRejects", "Unplaced", "ArgmaxTies", "Mesh",
                        "Reshard", "Degr"],
            "rows": rows}

    # ---- scheduling-quality scorecards (volcano_tpu/scenarios) ----------
    cards = _scenario_results()
    if cards:
        rows = []
        for c in reversed(cards[-16:]):
            waits = c.get("wait_cycles") or {}
            rows.append([
                c.get("scenario", "-"), c.get("tenant") or "-",
                c.get("seed", "-"),
                c.get("cycles", "-"),
                c.get("jobs_completed", "-"),
                c.get("makespan_cycles", "-"),
                c.get("drf_share_error", "-"),
                c.get("node_utilization", "-"),
                c.get("preemption_churn_total", "-"),
                waits.get("p50", "-"), waits.get("p95", "-"),
                waits.get("p99", "-"),
                f"{c.get('drift_checks', 0) - c.get('drift_failures', 0)}"
                f"/{c.get('drift_checks', 0)}",
                c.get("event_sha", "-"),
            ])
        page.tables["scenarios"] = {
            "headers": ["Scenario", "Tenant", "Seed", "Cycles",
                        "Completed", "Makespan", "DRF err", "Util",
                        "Churn", "Wait p50", "Wait p95", "Wait p99",
                        "Drift ok", "Event sha"],
            "rows": rows}

    # ---- fleet serving (multi-tenant batched cycle) ---------------------
    fleet = _fleet_snapshot(system)
    if fleet and fleet.get("tenants"):
        rows = []
        for t in fleet["tenants"]:
            rows.append([t["tenant"], t["weight"], t["cycles"],
                         t["served"], t["bucket"] or "-",
                         t["bucket_width"], t["cycle_kind"] or "-",
                         t["full_cycles"], t["delta_cycles"],
                         t["degradation"], t["resync_pending"],
                         t["resync_dead_letter"]])
        page.tables["fleet"] = {
            "headers": ["Tenant", "Weight", "Cycles", "Served", "Bucket",
                        "Width", "Kind", "Full", "Delta", "Degr",
                        "Resync", "DeadLetter"],
            "rows": rows}

    # ---- high availability (leader lease / replication / failover) ------
    ha_rows = _ha_rows()
    if ha_rows:
        page.tables["ha"] = {
            "headers": ["Signal", "Value"],
            "rows": ha_rows}

    # ---- latency breakdown (span rings) + pipeline occupancy -------------
    stats = _spans.phase_stats()
    if stats:
        lat_rows = [["-", ph, st["count"], st["p50"], st["p95"],
                     st["p99"], st["last"]] for ph, st in stats.items()]
        for tenant, phases in _spans.tenant_phase_stats().items():
            lat_rows.extend([tenant, ph, st["count"], st["p50"],
                             st["p95"], st["p99"], st["last"]]
                            for ph, st in phases.items())
        page.tables["latency"] = {
            "headers": ["Tenant", "Phase", "Count", "p50 ms", "p95 ms",
                        "p99 ms", "Last ms"],
            "rows": lat_rows}
        occ = _spans.occupancy()
        if occ.get("windows"):
            occ_rows = [["all", occ["windows"], occ["window_ms"],
                         occ["overlap_ms"], occ["bubble_ms"],
                         occ["pipeline_overlap_fraction"]]]
            for shard, o in (occ.get("per_shard") or {}).items():
                occ_rows.append([f"shard {shard}", o["windows"],
                                 o["window_ms"], o["overlap_ms"],
                                 o["bubble_ms"],
                                 o["pipeline_overlap_fraction"]])
            page.tables["pipeline"] = {
                "headers": ["Scope", "Windows", "Window ms", "Overlap ms",
                            "Bubble ms", "Overlap fraction"],
                "rows": occ_rows}
    return page


def _ha_rows():
    """The high-availability surface: leader lease state, checkpoint-
    stream health, failover ladder outcomes, fence rejections. Empty
    (table omitted) until any HA signal has ever fired — a single-replica
    deployment's dashboard stays unchanged."""
    g = METRICS.gauges
    rows = [
        ["is_leader", g.get(("is_leader", ""), "-")],
        ["leader transitions (to leader)", METRICS.counter_value(
            "leader_transitions_total", {"to": "leader"})],
        ["leader transitions (to follower)", METRICS.counter_value(
            "leader_transitions_total", {"to": "follower"})],
        ["replication envelopes applied", METRICS.counter_value(
            "replication_envelopes_total", {"result": "applied"})],
        ["replication envelopes lost", METRICS.counter_value(
            "replication_envelopes_total", {"result": "lost"})],
        ["replication lag (seq)", g.get(("replication_lag_seq", ""), "-")],
        ["promotions (warm)", METRICS.counter_value(
            "failover_promotions_total", {"outcome": "warm"})],
        ["promotions (cold)", METRICS.counter_value(
            "failover_promotions_total", {"outcome": "cold"})],
        ["promotions (fallback)", METRICS.counter_value(
            "failover_promotions_total", {"outcome": "fallback"})],
        ["fenced writes rejected", METRICS.counter_total(
            "fenced_writes_rejected_total")],
        ["sidecar endpoint failovers", METRICS.counter_value(
            "sidecar_failovers_total")],
        ["sidecar rounds fenced (ERR_NOT_LEADER)", METRICS.counter_value(
            "sidecar_not_leader_total")],
    ]
    live = any(v not in ("-", 0.0) for _, v in rows)
    return rows if live else []


def _scenario_results():
    """The scenario quality registry (bounded), empty when the scenarios
    package never ran. Function-local import: the dashboard must not pull
    the scenario engine (and its scheduler import) at module load."""
    try:
        from ..scenarios import quality as _quality
        return _quality.results()
    except Exception:  # noqa: BLE001 — observability must not 500 the page
        return []


def _fleet_snapshot(system):
    """The fleet scheduler's snapshot behind a system-ish object: a
    FleetScheduler itself, or anything exposing one as ``.fleet`` /
    ``.scheduler`` — empty dict when nothing fleet-shaped is present
    (single-cluster dashboards are unchanged)."""
    for obj in (system, getattr(system, "fleet", None),
                getattr(system, "scheduler", None)):
        fn = getattr(obj, "fleet_snapshot", None)
        if callable(fn):
            try:
                return fn()
            except Exception:  # noqa: BLE001 — observability must not 500
                return {}
    return {}


def _flight_of(system):
    """The flight recorder behind a system-ish object: a VolcanoSystem
    (``.scheduler.flight``), a bare Scheduler (``.flight``), or anything
    exposing a FlightRecorder-shaped ``flight`` attribute."""
    sched = getattr(system, "scheduler", system)
    flight = getattr(sched, "flight", None)
    return flight if flight is not None and hasattr(flight, "snapshots") \
        else None


def render_html(page: Page) -> str:
    parts = ["<!doctype html><html><head><title>volcano_tpu dashboard</title>",
             "<style>body{font-family:sans-serif;margin:2em}"
             "table{border-collapse:collapse;margin-bottom:2em}"
             "th,td{border:1px solid #999;padding:4px 10px;text-align:left}"
             "th{background:#eee}h2{margin-bottom:.3em}</style></head><body>",
             "<h1>volcano_tpu</h1>",
             f"<p>page built {time.strftime('%H:%M:%S', time.localtime(page.built_at))}"
             " &middot; auto-refresh 5s <script>setTimeout(()=>location.reload(),5000)"
             "</script></p>"]
    for name, tbl in page.tables.items():
        parts.append(f"<h2>{name}</h2><table><tr>")
        parts.extend(f"<th>{h}</th>" for h in tbl["headers"])
        parts.append("</tr>")
        for row in tbl["rows"]:
            parts.append("<tr>" + "".join(f"<td>{c}</td>" for c in row)
                         + "</tr>")
        parts.append("</table>")
    parts.append("</body></html>")
    return "".join(parts)


class Dashboard:
    """Cached-page dashboard server over a VolcanoSystem."""

    def __init__(self, system, refresh_seconds: float = DEFAULT_REFRESH_SECONDS):
        self.system = system
        self.refresh_seconds = refresh_seconds
        self._page: Optional[Page] = None
        self._lock = threading.Lock()
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def page(self, now: Optional[float] = None) -> Page:
        """The cached page, rebuilt when older than refresh_seconds."""
        now = now if now is not None else time.time()
        with self._lock:
            if (self._page is None
                    or now - self._page.built_at >= self.refresh_seconds):
                self._page = build_page(self.system, now=now)
            return self._page

    # ------------------------------------------------------------- serving
    def serve(self, host: str = "127.0.0.1", port: int = 8080) -> int:
        """Start serving in a daemon thread; returns the bound port."""
        dashboard = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _send(self, body: str, ctype: str, code: int = 200):
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                if self.path == "/healthz":
                    self._send("ok", "text/plain")
                elif self.path == "/metrics":
                    self._send(METRICS.exposition(), "text/plain")
                elif self.path == "/api/page":
                    self._send(dashboard.page().to_json(), "application/json")
                elif self.path == "/api/telemetry":
                    # the flight-recorder ring, always live (no page TTL):
                    # per-cycle snapshots are the whole point of the ring
                    flight = _flight_of(dashboard.system)
                    body = (flight.to_json() if flight is not None
                            else json.dumps({"capacity": 0,
                                             "recorded_total": 0,
                                             "cycles": []}))
                    self._send(body, "application/json")
                elif self.path == "/api/scenarios":
                    # the scenario quality registry, always live: one
                    # scorecard per run, same numbers as the
                    # volcano_quality_* gauges on /metrics
                    self._send(json.dumps(
                        {"scorecards": _scenario_results()}),
                        "application/json")
                elif self.path == "/api/fleet":
                    # the fleet scheduler's tenant roster, always live:
                    # per-tenant bucket, serving counters, degradation
                    self._send(json.dumps(
                        _fleet_snapshot(dashboard.system)),
                        "application/json")
                elif self.path == "/api/trace":
                    # the span tracer's Chrome trace-event export, always
                    # live — save it and load in Perfetto/chrome://tracing
                    self._send(json.dumps(_spans.export_chrome_trace()),
                               "application/json")
                elif self.path in ("/", "/index.html"):
                    self._send(render_html(dashboard.page()), "text/html")
                else:
                    self._send("not found", "text/plain", 404)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self._server.server_address[1]

    def shutdown(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
