"""Runtime: cluster I/O seam, scheduler loop, CPU reference oracle."""

from .fake_cluster import FakeCluster
from .scheduler import Scheduler
from .sidecar import SidecarClient, SidecarServer

__all__ = ["FakeCluster", "Scheduler", "SidecarClient", "SidecarServer"]
