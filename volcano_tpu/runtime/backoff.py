"""Capped exponential backoff with jitter — the call-site retry helper.

The per-ITEM shape (ItemExponentialFailureRateLimiter) already lives in
:class:`..runtime.scheduler.ResyncQueue`; this is the per-CALL shape the
sidecar client uses for connection establishment and reconnect-and-resend
(client-go's wait.Backoff). Jitter decorrelates a thundering herd of
replicas reconnecting to a restarted sidecar; tests pin ``jitter=0``
and/or ``seed`` for determinism.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass, field
from typing import Callable, Optional, TypeVar

T = TypeVar("T")


@dataclass
class Backoff:
    base: float = 0.05          # first retry delay, seconds
    cap: float = 2.0            # per-delay ceiling
    factor: float = 2.0
    attempts: int = 6           # total tries (first one immediate)
    jitter: float = 0.1         # +- fraction of the delay
    seed: Optional[int] = None  # pin for deterministic tests
    _rng: random.Random = field(init=False, repr=False, default=None)
    _attempt: int = field(init=False, repr=False, default=0)

    def __post_init__(self):
        self._rng = random.Random(self.seed)
        self._attempt = 0

    def delay(self, attempt: int) -> float:
        """Delay before retry ``attempt`` (0-based: the delay AFTER the
        first failure)."""
        d = min(self.cap, self.base * (self.factor ** attempt))
        if self.jitter:
            d *= 1.0 + self.jitter * (2.0 * self._rng.random() - 1.0)
        return max(d, 0.0)

    # -- stateful interval (the health registry's probation timer) --------

    def next(self) -> float:
        """Current interval, then escalate: the first call after
        ``reset()`` returns ``base`` (jittered), each later call one
        factor step higher, capped at ``cap``. Unlike :meth:`delay` the
        position is carried by the instance, so callers that react to
        spaced-out events (a flapping device re-failing its probation)
        get the escalating schedule without threading a counter."""
        d = self.delay(self._attempt)
        self._attempt += 1
        return d

    def peek(self) -> float:
        """The interval :meth:`next` would return, without escalating or
        consuming jitter (the undithered value)."""
        return min(self.cap, self.base * (self.factor ** self._attempt))

    def reset(self) -> None:
        """Restore the initial interval: the next :meth:`next` returns
        ``base`` again."""
        self._attempt = 0

    def call(self, fn: Callable[[], T],
             retry_on=(OSError,),
             sleep: Callable[[float], None] = time.sleep) -> T:
        """Run ``fn`` up to ``attempts`` times, sleeping the backoff
        schedule between failures; the final failure propagates."""
        for attempt in range(self.attempts):
            try:
                return fn()
            except retry_on:
                if attempt >= self.attempts - 1:
                    raise
                sleep(self.delay(attempt))
        raise RuntimeError("unreachable")  # pragma: no cover
