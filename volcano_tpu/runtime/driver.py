"""Shared lockstep driver step for the scheduler loop.

Every in-repo driver of :class:`runtime.scheduler.Scheduler` — the chaos
probe, the parallel sha-matrix CLI, the telemetry trace demo, tests —
used to hand-roll the same idiom::

    out = sched.run_once(now=wall)
    rec = (sched.drain(now=wall) or out) if pipeline else out

which bakes the pipeline's drain contract into every call site. With the
depth-k ring that contract lives here instead: :func:`step_cycle` runs
one cycle and retires WHATEVER the pipeline owes (the whole ring under
lockstep driving), so a depth change never touches the drivers again.

Overlap-measuring drivers that deliberately leave cycles in flight
(chaos/spec.py's depth-k legs, bench's pipelined rows) keep calling
``run_once``/``drain`` directly — lockstep is this helper's one job.
"""

from __future__ import annotations

from typing import Callable, Optional


def step_cycle(sched, now: Optional[float] = None,
               ingest: Optional[Callable[[], None]] = None):
    """One lockstep driver step: ``run_once`` then the pipeline's drain.

    ``ingest`` (optional) runs between dispatch and drain — host event
    ingestion placed exactly where the pipeline overlaps it with the
    in-flight device cycle. Returns the completed record for THIS cycle:
    the drained :class:`CompletedCycle` when the loop is pipelined, the
    live session otherwise (both carry binds/evictions/pipelined/
    phase_updates, the decision surface drivers digest)."""
    out = sched.run_once(now=now)
    if ingest is not None:
        ingest()
    if getattr(sched, "pipeline", False):
        return sched.drain(now=now) or out
    return out
