"""In-memory cluster: the test/bench stand-in for the Kubernetes API server.

Reference seam: the Cache interface with FakeBinder/FakeEvictor/
FakeStatusUpdater (pkg/scheduler/cache/interface.go:29-86,
pkg/scheduler/util/test_utils.go:95-176). The FakeCluster owns the
authoritative ClusterInfo, serves deep-copy snapshots to sessions, and
applies bind/evict intents the way the real binder/evictor REST calls would,
recording them for assertions.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..api import (ClusterInfo, JobInfo, NodeInfo, QueueInfo, TaskInfo,
                   TaskStatus)
from ..chaos.inject import seam
from ..framework.session import BindIntent, EvictIntent


class FakeCluster:
    def __init__(self, ci: Optional[ClusterInfo] = None):
        self.ci = ci or ClusterInfo()
        self.binds: List[Tuple[str, str]] = []      # (task uid, node)
        self.evictions: List[str] = []              # task uid
        # HA fencing (ISSUE 11): the highest lease generation any writer
        # has presented. A bind/evict stamped with an OLDER token comes
        # from a deposed leader — reject it structurally (the split-brain
        # window can never double-bind). None-fenced writes (tests, the
        # single-replica loop) bypass the check entirely.
        self.fence_generation: int = 0
        #: rejected stale writes, for assertions: (kind, task_uid,
        #: presented_generation, fence_generation)
        self.fenced_rejections: List[Tuple[str, str, int, int]] = []
        self.bind_failures: Dict[str, str] = {}     # task uid -> error to inject
        self.volume_bind_failures: set = set()      # claim names failing
        #                                             BindVolumes at dispatch
        # dirty marks for the scheduler's persistent session (the informer
        # event-handler analog, event_handlers.go:43-740): every mutator
        # records what it touched; direct ClusterInfo edits must call
        # mark_dirty (entity ADD/REMOVE is caught structurally by
        # refresh_snapshot's count checks either way)
        self.dirty_jobs: set = set()
        self.dirty_nodes: set = set()
        self.structural: bool = False
        #: total structural marks ever raised (see SchedulerCache) — the
        #: expected full-re-fuse count of a delta-upload steady loop
        self.structural_epochs: int = 0

    # ------------------------------------------------------------- snapshot
    def snapshot(self) -> ClusterInfo:
        """Deep copy, like SchedulerCache.Snapshot (cache.go:712-811)."""
        return self.ci.clone()

    def live_view(self) -> ClusterInfo:
        """The authoritative ClusterInfo itself, for a persistent session
        maintained across cycles by dirty marks + refresh_snapshot (the
        reference's cache is likewise one live structure patched by event
        handlers; Snapshot's deep copy exists only for cycle isolation the
        synchronous loop doesn't need)."""
        return self.ci

    def mark_dirty(self, job_uid: Optional[str] = None,
                   node_name: Optional[str] = None,
                   structural: bool = False) -> None:
        if job_uid is not None:
            self.dirty_jobs.add(job_uid)
        if node_name is not None:
            self.dirty_nodes.add(node_name)
        if structural:
            if not self.structural:
                self.structural_epochs += 1
            self.structural = True

    def drain_dirty(self) -> Tuple[set, set, bool]:
        dj, dn, st = self.dirty_jobs, self.dirty_nodes, self.structural
        self.dirty_jobs, self.dirty_nodes = set(), set()
        self.structural = False
        return dj, dn, st

    # -------------------------------------------------------------- fencing
    def advance_fence(self, generation: Optional[int]) -> None:
        """Explicit fence announcement — the promoted leader's FIRST act
        (runtime/replication.WarmStandby.promote). Ratchets the fence
        without a data write, closing the window where a deposed leader's
        late write could land before the new leader's first bind."""
        if generation is not None:
            self.fence_generation = max(self.fence_generation,
                                        int(generation))

    def fence_admits(self, generation: Optional[int]) -> bool:
        """Read-only fence probe: would a write stamped ``generation`` be
        admitted right now? (None = unfenced caller, always admitted.)"""
        return generation is None or generation >= self.fence_generation

    def _check_fence(self, kind: str, task_uid: str,
                     generation: Optional[int]) -> bool:
        """Admit-or-reject a fenced write. Admission ratchets the fence
        forward (the new leader's first write deposes every older token);
        rejection is counted and logged — it is a permanent verdict for
        that token, not a retryable flake."""
        if generation is None:
            return True
        if generation < self.fence_generation:
            from ..metrics import METRICS
            METRICS.inc("fenced_writes_rejected_total",
                        labels={"kind": kind})
            self.fenced_rejections.append(
                (kind, task_uid, int(generation),
                 int(self.fence_generation)))
            return False
        self.fence_generation = int(generation)
        return True

    # ----------------------------------------------------------- bind/evict
    def bind(self, intent: BindIntent,
             fence: Optional[int] = None) -> bool:
        """Apply a bind: task becomes Bound on the node (defaultBinder.Bind,
        cache.go:123-143). Injectable failures exercise the resync path: a
        string value fails every attempt, an int value fails that many
        attempts then succeeds."""
        # fault-injection seam: a chaos bind_fail fault is a one-shot API
        # rejection, landing the intent in the scheduler's resync path
        if seam("cluster.bind", intent=intent) == "fail":
            return False
        if not self._check_fence("bind", intent.task_uid, fence):
            return False
        fail = self.bind_failures.get(intent.task_uid)
        if fail is not None:
            if isinstance(fail, int):
                if fail > 0:
                    self.bind_failures[intent.task_uid] = fail - 1
                    return False
                del self.bind_failures[intent.task_uid]
            else:
                return False
        job = self.ci.jobs.get(intent.job_uid)
        node = self.ci.nodes.get(intent.node_name)
        if job is None or node is None:
            return False
        task = job.tasks.get(intent.task_uid)
        if task is None:
            return False
        # BindVolumes precedes the pod bind (ssn.dispatch, session.go:330-338
        # -> defaultVolumeBinder.BindVolumes, cache.go:265-272): an
        # unbindable claim fails the whole bind into the resync path
        for claim in task.pvcs:
            pvc = self.ci.pvcs.get(claim)
            if (pvc is None or not pvc.bindable
                    or claim in self.volume_bind_failures):
                return False
        for claim in task.pvcs:
            self.ci.pvcs[claim].bound = True
        old_status, old_gpu = task.status, task.gpu_index
        removed_from = None
        if task.uid in self.ci.nodes.get(task.node_name, node).tasks:
            removed_from = self.ci.nodes[task.node_name]
            removed_from.remove_task(task)
        job.update_task_status(task, TaskStatus.BOUND)
        # apply the shared-GPU card chosen by the cycle before accounting,
        # like the GPU-index pod patch ahead of AddPod (predicates.go:140-151)
        task.gpu_index = intent.gpu_index
        try:
            node.add_task(task)
        except ValueError:
            # boundary exact-fit rejected by the host float64 check (the
            # device admits with float32 slack): a failed bind, like
            # defaultBinder.Bind returning an error (cache.go:123-143) —
            # the caller's resync path retries it. Restore the prior
            # placement exactly: same status, same node accounting.
            job.update_task_status(task, old_status)
            task.gpu_index = old_gpu
            if removed_from is not None:
                removed_from.add_task(task, force=True)
            else:
                task.node_name = ""
            return False
        self.binds.append((intent.task_uid, intent.node_name))
        self.dirty_jobs.add(job.uid)
        self.dirty_nodes.add(node.name)
        if removed_from is not None and removed_from is not node:
            self.dirty_nodes.add(removed_from.name)
        return True

    def evict(self, intent: EvictIntent,
              fence: Optional[int] = None) -> bool:
        """Apply an eviction: task goes back to Pending off-node
        (defaultEvictor.Evict, cache.go:145-175)."""
        if seam("cluster.evict", intent=intent) == "fail":
            return False
        if not self._check_fence("evict", intent.task_uid, fence):
            return False
        job = self.ci.jobs.get(intent.job_uid)
        if job is None:
            return False
        task = job.tasks.get(intent.task_uid)
        if task is None:
            return False
        node = self.ci.nodes.get(task.node_name)
        if node is not None and task.uid in node.tasks:
            node.remove_task(task)
        task.node_name = ""
        job.update_task_status(task, TaskStatus.PENDING)
        self.evictions.append(intent.task_uid)
        self.dirty_jobs.add(job.uid)
        if node is not None:
            self.dirty_nodes.add(node.name)
        return True

    def hold_binding(self, intent: BindIntent) -> None:
        """After a failed bind dispatch the cache keeps the task in Binding
        holding its decided node (the session's UpdateTaskStatus persists
        until syncTask resets it, cache.go:549-560 + 687-709), so later
        cycles do not re-decide it while the retry queue works."""
        job = self.ci.jobs.get(intent.job_uid)
        node = self.ci.nodes.get(intent.node_name)
        if job is None or node is None:
            return
        task = job.tasks.get(intent.task_uid)
        if task is None or task.status != TaskStatus.PENDING:
            return
        job.update_task_status(task, TaskStatus.BINDING)
        task.gpu_index = intent.gpu_index
        try:
            node.add_task(task)
            self.dirty_nodes.add(node.name)
        except ValueError:
            job.update_task_status(task, TaskStatus.PENDING)
            task.gpu_index = -1
        self.dirty_jobs.add(job.uid)

    def resync_task(self, task_uid: str) -> None:
        """Give-up resync: reset a Binding task to Pending off-node — the
        syncTask refetch discovering the pod never scheduled
        (cache.go:690-709)."""
        for job in self.ci.jobs.values():
            task = job.tasks.get(task_uid)
            if task is None:
                continue
            if task.status == TaskStatus.BINDING:
                node = self.ci.nodes.get(task.node_name)
                if node is not None and task.uid in node.tasks:
                    node.remove_task(task)
                    self.dirty_nodes.add(node.name)
                task.node_name = ""
                task.gpu_index = -1
                job.update_task_status(task, TaskStatus.PENDING)
                self.dirty_jobs.add(job.uid)
            return

    def update_podgroup_phases(self, phase_updates) -> None:
        for uid, phase in phase_updates.items():
            job = self.ci.jobs.get(uid)
            if job is not None and job.pod_group_phase != phase:
                job.pod_group_phase = phase
                self.dirty_jobs.add(uid)

    # --------------------------------------------------- lifecycle helpers
    def add_node(self, node) -> None:
        """Autoscaler-style node arrival: register + structural mark."""
        self.ci.add_node(node)
        self.mark_dirty(node_name=node.name, structural=True)

    def remove_node(self, name: str) -> bool:
        """Autoscaler-style node departure. Refuses a node still carrying
        tasks (a real autoscaler drains first); returns whether removed."""
        node = self.ci.nodes.get(name)
        if node is None or node.tasks:
            return False
        del self.ci.nodes[name]
        self.mark_dirty(structural=True)
        return True

    def remove_job(self, job_uid: str) -> bool:
        """Retire a job: free its tasks' node accounting, drop the job,
        raise the structural mark. Returns whether the job existed."""
        job = self.ci.jobs.get(job_uid)
        if job is None:
            return False
        for task in job.tasks.values():
            node = self.ci.nodes.get(task.node_name)
            if node is not None and task.uid in node.tasks:
                node.remove_task(task)
                self.mark_dirty(node_name=node.name)
        del self.ci.jobs[job_uid]
        self.mark_dirty(job_uid=job_uid, structural=True)
        return True

    def run_task(self, task_uid: str) -> None:
        """Kubelet-style transition Bound -> Running."""
        for job in self.ci.jobs.values():
            task = job.tasks.get(task_uid)
            if task is not None:
                node = self.ci.nodes.get(task.node_name)
                if node is not None and task.uid in node.tasks:
                    node.remove_task(task)
                    job.update_task_status(task, TaskStatus.RUNNING)
                    node.add_task(task)
                    self.dirty_nodes.add(node.name)
                else:
                    job.update_task_status(task, TaskStatus.RUNNING)
                self.dirty_jobs.add(job.uid)
                return
