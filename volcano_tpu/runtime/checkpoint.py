"""Crash-consistent checkpoint/restore for the scheduler and sidecar.

PR 5 made the cycle runtime survive every *in-process* fault; a process
death still lost the host-side truth that is NOT re-derivable from the
cluster source: the sidecar's per-epoch replay cache and seq watermarks,
the ResyncQueue's pending retries and dead letters, cumulative metrics,
and the resident-state mirrors that make the first post-restart cycle a
delta instead of a full re-fuse. This module serializes exactly that
state — and nothing the runtime can rebuild cheaper than it can reload
(device buffers, compiled programs, flight rings) — as an atomic
tmp+fsync+rename file:

    VCKP | u32 schema | sha256(body) | body (pickle of the envelope)

The envelope is stamped twice: the content sha over the whole body
(truncation/flip detection) and the PR 5 integrity-digest words of every
checkpointed resident mirror (``ops/fused_io.host_digest`` — the same
3-word formula the in-graph digest computes), verified again at restore
before a mirror is re-adopted onto the device.

Restore ladder (``checkpoint_restore_total{outcome=...}``):

- valid file, matching conf  -> ``restored`` — warm restart: state
  reloaded, residents re-fused from restored truth, the stream resumes
  decision-identically to an uninterrupted run;
- no file                    -> ``cold`` — the ordinary fresh start;
- truncated / flipped byte / version skew / conf mismatch ->
  ``fallback`` — degrade gracefully to the fresh-fuse cold start. Still
  decision-identical: the authoritative cluster state lives OUTSIDE the
  process (the reference's API-server posture, PAPER.md §1), so re-fuse
  from source truth is always a correct recovery primitive; the
  checkpoint only buys back warmth and stream continuity.

:class:`CrashLoopSupervisor` is the serve-loop half: capped-backoff
restarts of a crashing target, so a sidecar wedged in a crash loop
flaps with bounded frequency and eventually surfaces the error instead
of burning the host.
"""

from __future__ import annotations

import hashlib
import os
import pickle
import struct
import tempfile
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..metrics import METRICS
from ..telemetry import spans

#: file magic — fails fast on foreign files instead of unpickling them
MAGIC = b"VCKP"
#: bump on envelope layout changes; a FUTURE schema restores as fallback
#: (an older binary must never guess at a newer layout)
SCHEMA_VERSION = 1
_HEADER = struct.Struct("<4sI32s")  # magic | schema | sha256(body)


# --------------------------------------------------------------- envelope
def conf_fingerprint(conf) -> str:
    """Stable fingerprint of a SchedulerConfiguration (or AllocateConfig):
    a checkpoint taken under one policy must not resume under another —
    the decision stream would silently diverge."""
    try:
        blob = pickle.dumps(conf, protocol=4)
    except Exception:  # unpicklable conf: fall back to its repr
        blob = repr(conf).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


def fold_digest(records: List[dict]) -> List[int]:
    """XOR-fold of the per-mirror integrity-digest words — the envelope's
    PR 5 stamp (order-independent, so record ordering can't perturb it)."""
    from ..ops.fused_io import DIGEST_WORDS
    out = np.zeros(DIGEST_WORDS, np.uint32)
    for r in records:
        out ^= np.asarray(r["digest"], np.uint32)
    return [int(x) for x in out]


def write_checkpoint(path: str, kind: str, state: dict,
                     mirrors: Optional[List[dict]] = None) -> dict:
    """Atomically write a checkpoint file.

    tmp file in the SAME directory (rename must not cross filesystems),
    flush + fsync before the rename, rename over the destination, then a
    best-effort directory fsync — a crash at any point leaves either the
    old complete file or the new complete file, never a torn one."""
    mirrors = mirrors or []
    envelope = {
        "kind": kind,
        "state": state,
        "mirrors": mirrors,
        "digest_words": fold_digest(mirrors),
        "written_at": time.time(),
    }
    body = pickle.dumps(envelope, protocol=4)
    sha = hashlib.sha256(body).digest()
    directory = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(prefix=".vckp.", dir=directory)
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(_HEADER.pack(MAGIC, SCHEMA_VERSION, sha))
            f.write(body)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    try:  # persist the rename itself (best-effort: not all FSes allow it)
        dfd = os.open(directory, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:
        pass
    METRICS.inc("checkpoint_write_total", labels={"kind": kind})
    spans.log_event("checkpoint", ckpt_kind=kind, path=path,
                    bytes=len(body) + _HEADER.size,
                    sha=sha.hex()[:16], mirrors=len(mirrors))
    return {"path": path, "sha": sha.hex(), "bytes": len(body) + _HEADER.size}


def load_checkpoint(path: str, kind: str) -> Tuple[Optional[dict], str]:
    """Read + verify a checkpoint file. Returns ``(envelope, "ok")`` or
    ``(None, reason)`` where reason is one of ``missing | truncated |
    bad_magic | version_skew | sha_mismatch | corrupt | kind_mismatch``.
    Never raises on a damaged file — a bad checkpoint must degrade to a
    cold start, not take the restart down."""
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except FileNotFoundError:
        return None, "missing"
    except OSError:
        return None, "corrupt"
    if len(raw) < _HEADER.size:
        return None, "truncated"
    magic, schema, sha = _HEADER.unpack_from(raw)
    if magic != MAGIC:
        return None, "bad_magic"
    if schema > SCHEMA_VERSION:
        return None, "version_skew"
    body = raw[_HEADER.size:]
    if hashlib.sha256(body).digest() != sha:
        return None, "sha_mismatch"
    try:
        envelope = pickle.loads(body)
    except Exception:
        return None, "corrupt"
    if envelope.get("kind") != kind:
        return None, "kind_mismatch"
    return envelope, "ok"


def tenant_checkpoint_path(directory: str, tenant: str) -> str:
    """Per-tenant fleet envelope path: ``<dir>/tenant-<name>.vckp``, one
    file per tenant so a corrupt restore is contained to its owner (the
    fleet restore ladder treats each file independently). Tenant names are
    sanitized to a filename-safe subset; collisions after sanitization are
    disambiguated with a short content hash."""
    safe = "".join(c if c.isalnum() or c in "-_." else "_" for c in tenant)
    if safe != tenant:
        safe += "-" + hashlib.sha256(tenant.encode()).hexdigest()[:8]
    return os.path.join(directory, f"tenant-{safe}.vckp")


def record_restore(outcome: str, reason: str, source: str,
                   restore_ms: Optional[float] = None) -> None:
    """The one place the restore ladder lands: the labeled counter plus a
    ``restore`` event in the JSONL log / event ring."""
    METRICS.inc("checkpoint_restore_total", labels={"outcome": outcome})
    spans.log_event("restore", outcome=outcome, reason=reason,
                    source=source,
                    restore_ms=(round(restore_ms, 3)
                                if restore_ms is not None else None))


# ------------------------------------------------------- cumulative metrics
def metrics_snapshot() -> List[list]:
    """Serializable view of the cumulative counters: [name, labelstr,
    value] triples (the registry's native key shape)."""
    return [[name, labels, float(v)]
            for (name, labels), v in sorted(METRICS.counters.items())]


def merge_metrics(saved: List[list]) -> None:
    """Resume cumulative counters from the checkpointed watermark. A fresh
    process starts at zero, so the saved value wins; an in-process restore
    (tests, the restart-storm engine) keeps whichever is larger — counters
    are monotonic and must never step backwards."""
    for name, labels, v in saved or []:
        key = (str(name), str(labels))
        if float(v) > METRICS.counters.get(key, 0.0):
            METRICS.counters[key] = float(v)


# ------------------------------------------------- resident mirror records
def mirror_records(kernels: Dict[tuple, object],
                   states: Dict[int, object]) -> List[dict]:
    """Snapshot the host mirrors of device truth for every flat DeltaKernel
    shape bucket: (shape key, copied mirror buffers, integrity-digest
    words). Sharded residents are deliberately NOT checkpointed — their
    per-shard placement is mesh-dependent, and a restarted process
    re-fuses them from source truth in one full upload."""
    from ..ops.fused_io import host_digest
    out = []
    for key, kernel in kernels.items():
        state = states.get(id(kernel))
        if state is None or state.mirror is None:
            continue
        mirror = tuple(np.array(b, copy=True) for b in state.mirror)
        out.append({"key": key, "mirror": mirror,
                    "digest": [int(x) for x in host_digest(mirror)]})
    return out


def verify_mirrors(records: List[dict]) -> Dict[tuple, tuple]:
    """Re-verify each checkpointed mirror against its stamped digest words
    (the PR 5 formula, recomputed over the rehydrated buffers). A record
    that fails verification is dropped — that shape bucket cold-fuses —
    and counted, never adopted."""
    from ..ops.fused_io import host_digest
    out: Dict[tuple, tuple] = {}
    for r in records or []:
        mirror = r["mirror"]
        if [int(x) for x in host_digest(mirror)] != list(r["digest"]):
            METRICS.inc("checkpoint_mirror_invalid_total")
            spans.log_event("restore_mirror_invalid")
            continue
        out[_freeze_key(r["key"])] = mirror
    return out


def _freeze_key(key):
    """Shape keys round-trip through pickle as nested tuples already; this
    normalizes any list contamination so dict lookups match _shape_key."""
    if isinstance(key, (list, tuple)):
        return tuple(_freeze_key(k) for k in key)
    return key


def adopt_mirror(state, mirror) -> None:
    """Warm re-fuse: put the verified mirror back on the device and adopt
    it as residency, so the next :meth:`DeltaKernel.run` diffs against it
    and ships O(churn) — the warm-restart payoff. device == mirror exactly
    by construction, so the next in-graph digest check still holds."""
    import jax
    state.mirror = tuple(np.asarray(b) for b in mirror)
    state.device = tuple(jax.device_put(b) for b in state.mirror)
    state.scratch = None
    state.retiring = ()
    METRICS.inc("checkpoint_warm_refuse_total")


# ------------------------------------------------------ crash-loop policy
class CrashLoopSupervisor:
    """Capped-backoff restart policy for a serve loop.

    Runs ``target()`` until it returns cleanly. When it raises, the
    supervisor restarts it after a capped-exponential backoff delay
    (runtime/backoff.Backoff — the same discipline the sidecar client
    reconnect uses), up to ``max_restarts`` times; then the last error
    propagates, because a crash loop must eventually surface instead of
    flapping forever. KeyboardInterrupt and SystemExit always propagate —
    a clean shutdown is not a crash."""

    def __init__(self, target, max_restarts: int = 5, backoff=None,
                 sleep=time.sleep):
        from .backoff import Backoff
        self.target = target
        self.max_restarts = int(max_restarts)
        self.backoff = backoff if backoff is not None \
            else Backoff(base=0.5, cap=30.0, attempts=max_restarts + 1)
        self.restarts = 0
        self._sleep = sleep

    def run(self):
        while True:
            try:
                return self.target()
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception as e:
                self.restarts += 1
                if self.restarts > self.max_restarts:
                    raise
                delay = self.backoff.delay(self.restarts - 1)
                METRICS.inc("crash_loop_restarts_total")
                spans.log_event("restart", source="supervisor",
                                error=f"{type(e).__name__}: {e}",
                                restarts=self.restarts,
                                delay_s=round(delay, 3))
                self._sleep(delay)
