"""Lease-based leader election for HA scheduler / controller-manager.

Reference: both vc-scheduler and vc-controller-manager run leader-elected
against a coordination lease so only one replica acts at a time
(cmd/scheduler/app/server.go:100-148, cmd/controller-manager/app/
server.go:78-120, client-go leaderelection).  Here the lock object lives in
the in-memory API server's ``leases`` store; replicas call :meth:`tick`
periodically (the retry loop) and consult :attr:`is_leader` before running
their cycle.  Timing is injectable so tests drive expiry deterministically;
the default is :func:`time.monotonic` — lease arithmetic is pure intervals,
and a wall clock stepping backwards (NTP slew) must never un-expire a
lease.

HA fencing (ISSUE 11): every holder transition bumps the lease's
``generation`` — a monotonically increasing fencing token. The current
leader threads its generation into every external write (cluster
bind/evict, sidecar rounds); the write target rejects any token below the
highest it has seen, so a deposed leader's in-flight writes land as
structured rejections (``ERR_NOT_LEADER`` / ``fenced_writes_rejected``)
instead of split-brain double-binds. See docs/architecture.md "High
availability & failover".
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Optional

#: client-go defaults used by the reference binaries.
DEFAULT_LEASE_DURATION = 15.0
DEFAULT_RENEW_DEADLINE = 10.0
DEFAULT_RETRY_PERIOD = 5.0


@dataclass
class Lease:
    """A coordination.k8s.io/Lease-shaped lock record."""

    name: str
    namespace: str = "volcano-system"
    holder: str = ""
    acquire_time: float = 0.0
    renew_time: float = 0.0
    lease_duration: float = DEFAULT_LEASE_DURATION
    transitions: int = 0
    #: fencing token: strictly increases on every holder transition
    #: (acquire, steal, re-acquire). Writes stamped with an older
    #: generation are stale by construction and must be rejected.
    generation: int = 0

    def expired(self, now: float) -> bool:
        return now >= self.renew_time + self.lease_duration


@dataclass
class LeaderElector:
    """One replica's view of an election.

    Usage::

        el = LeaderElector(api, identity="scheduler-0", lock_name="vc-scheduler")
        while True:
            el.tick()
            if el.is_leader:
                run_cycle()
            sleep(el.retry_period)
    """

    api: object
    identity: str
    lock_name: str = "vc-scheduler"
    namespace: str = "volcano-system"
    lease_duration: float = DEFAULT_LEASE_DURATION
    renew_deadline: float = DEFAULT_RENEW_DEADLINE
    retry_period: float = DEFAULT_RETRY_PERIOD
    on_started_leading: Optional[Callable[[], None]] = None
    on_stopped_leading: Optional[Callable[[], None]] = None
    clock: Callable[[], float] = time.monotonic
    is_leader: bool = field(default=False, init=False)
    _last_renew: float = field(default=0.0, init=False)
    #: the generation of the last lease this replica HELD — its fencing
    #: token. Deliberately kept after a step-down: a deposed leader's
    #: late writes must present the OLD token so the fence rejects them.
    generation: int = field(default=0, init=False)

    @property
    def _key(self) -> str:
        return f"{self.namespace}/{self.lock_name}"

    def _lease(self) -> Optional[Lease]:
        return self.api.get("leases", self._key)

    def tick(self) -> bool:
        """Try to acquire or renew the lease; returns is_leader."""
        from ..chaos.inject import seam
        now = self.clock()
        lease = self._lease()
        # fault-injection seam: a chaos lease_expiry fault hands the lease
        # to a rival that never renews — this replica must step down now
        # and win it back once the rival's lease expires
        seam("leader.tick", elector=self, lease=lease)
        if lease is None:
            lease = Lease(name=self.lock_name, namespace=self.namespace,
                          holder=self.identity, acquire_time=now,
                          renew_time=now, lease_duration=self.lease_duration,
                          generation=1)
            self.api.create("leases", lease)
            self._become_leader(now, lease.generation)
            return True
        if lease.holder == self.identity:
            # Renew; if we could not renew within renew_deadline we must
            # step down even though no one else took the lock yet.
            if self.is_leader and now - self._last_renew > self.renew_deadline:
                self._step_down()
                return False
            lease.renew_time = now
            self.api.update("leases", lease)
            if not self.is_leader:
                self._become_leader(now, lease.generation)
            self._last_renew = now
            return True
        if lease.expired(now):
            lease.holder = self.identity
            lease.acquire_time = now
            lease.renew_time = now
            lease.transitions += 1
            lease.generation += 1
            self.api.update("leases", lease)
            self._become_leader(now, lease.generation)
            return True
        if self.is_leader:
            # someone else holds a live lease (we lost it)
            self._step_down()
        return False

    def release(self) -> None:
        """Voluntary step-down (graceful shutdown releases the lock)."""
        lease = self._lease()
        if lease is not None and lease.holder == self.identity:
            lease.holder = ""
            lease.renew_time = 0.0
            self.api.update("leases", lease)
        if self.is_leader:
            self._step_down()

    def _become_leader(self, now: float,
                       generation: Optional[int] = None) -> None:
        self.is_leader = True
        self._last_renew = now
        if generation is not None:
            self.generation = int(generation)
        if self.on_started_leading:
            self.on_started_leading()

    def _step_down(self) -> None:
        self.is_leader = False
        if self.on_stopped_leading:
            self.on_stopped_leading()
