"""The scheduler loop: snapshot -> session -> actions -> bind.

Reference: pkg/scheduler/scheduler.go:54-171 (Scheduler.Run / runOnce with
the 1s wait.Until cycle, conf hot-reload) and cmd/scheduler/app/server.go.
The loop is synchronous here; bind/evict intents flush to the cluster source
at the end of each cycle (the reference fires them as goroutines mid-cycle —
same external effect, recorded in order).
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional

from ..framework.conf import SchedulerConfiguration, parse_conf
from ..framework.session import Session
from ..metrics import METRICS
from .fake_cluster import FakeCluster


class ResyncQueue:
    """Rate-limited retry queue for failed bind/evict dispatches.

    The errTasks workqueue analog (cache.go:687-709): per-item exponential
    backoff (AddRateLimited's ItemExponentialFailureRateLimiter shape),
    retries the SAME intent on later cycles without a fresh scheduling
    decision, and after ``max_attempts`` gives up and resyncs the task back
    to Pending (the syncTask refetch discovering the pod never bound)."""

    def __init__(self, base_delay: float = 0.005, max_delay: float = 10.0,
                 max_attempts: int = 15):
        self.base_delay = base_delay
        self.max_delay = max_delay
        self.max_attempts = max_attempts
        self.entries: List[dict] = []

    def __len__(self) -> int:
        return len(self.entries)

    def add(self, intent, kind: str, now: float, attempts: int = 1) -> None:
        delay = min(self.base_delay * (2 ** (attempts - 1)), self.max_delay)
        self.entries.append(dict(intent=intent, kind=kind, attempts=attempts,
                                 next_try=now + delay))

    def process(self, cluster, now: float) -> Dict[str, int]:
        """Retry every due entry against the cluster. Returns counters."""
        due = [e for e in self.entries if e["next_try"] <= now]
        self.entries = [e for e in self.entries if e["next_try"] > now]
        stats = dict(retried=0, succeeded=0, dropped=0)
        for e in due:
            stats["retried"] += 1
            ok = (cluster.bind(e["intent"]) if e["kind"] == "bind"
                  else cluster.evict(e["intent"]))
            if ok:
                stats["succeeded"] += 1
            elif e["attempts"] >= self.max_attempts:
                stats["dropped"] += 1
                if e["kind"] == "bind":
                    cluster.resync_task(e["intent"].task_uid)
            else:
                self.add(e["intent"], e["kind"], now, e["attempts"] + 1)
        return stats


class Scheduler:
    def __init__(self, cluster: FakeCluster,
                 conf: Optional[SchedulerConfiguration] = None,
                 conf_path: Optional[str] = None,
                 schedule_period: float = 1.0,
                 incremental: bool = True):
        self.cluster = cluster
        self.conf_path = conf_path
        self._conf_mtime = 0.0
        self.conf = conf or self._load_conf() or parse_conf()
        self.schedule_period = schedule_period
        self._plugin_state: Dict[str, object] = {}
        self.cycles = 0
        self.resync = ResyncQueue()
        # the persistent session (VERDICT r4 #1): built over the cluster's
        # live view on the first cycle, then re-opened each cycle via
        # refresh_snapshot from the cluster's dirty marks — the steady-state
        # path that skips the full re-pack. incremental=False restores the
        # fresh-Session-per-cycle behavior (the oracle for equality tests).
        self.incremental = incremental and hasattr(cluster, "live_view")
        self._session: Optional[Session] = None
        #: cycles that paid a full pack (first cycle, structural change, or
        #: a refresh fallback) vs cycles served by the incremental patch —
        #: the steady-state claim is checkable: full_packs stays at 1
        self.full_packs = 0
        self.incremental_cycles = 0
        #: bounded flight recorder: the last N cycle snapshots (host
        #: timestamps, latency, bind/evict counts, in-graph telemetry when
        #: the conf enables it), served by the dashboard's /api/telemetry
        from ..telemetry import FlightRecorder
        self.flight = FlightRecorder(
            capacity=int(os.environ.get("VOLCANO_FLIGHT_CYCLES", 64)))

    def _load_conf(self) -> Optional[SchedulerConfiguration]:
        """Conf hot-reload (fsnotify watcher, scheduler.go:146-171 — here a
        cheap mtime poll at cycle start)."""
        if not self.conf_path or not os.path.exists(self.conf_path):
            return None
        mtime = os.path.getmtime(self.conf_path)
        if mtime == self._conf_mtime:
            return None
        self._conf_mtime = mtime
        with open(self.conf_path) as f:
            return parse_conf(f.read())

    def _persistent_plugins(self) -> Dict[str, object]:
        """Plugins with cross-cycle state: the reservation singleton and
        tdm's lastEvictAt rate limiter (tdm.go:232-236)."""
        from ..plugins.reservation import ReservationPlugin
        from ..plugins.tdm import TDMPlugin
        overrides = {}
        for name, cls in (("reservation", ReservationPlugin),
                          ("tdm", TDMPlugin)):
            if self.conf.plugin_option(name) is not None:
                if name not in self._plugin_state:
                    self._plugin_state[name] = cls(
                        self.conf.plugin_option(name))
                overrides[name] = self._plugin_state[name]
        return overrides

    def _open_session(self, now: Optional[float]) -> Session:
        """Open this cycle's session.

        Steady state holds ONE session across cycles and re-opens it with an
        incremental snapshot refresh fed by the cluster's dirty marks — the
        analog of the reference's incrementally maintained cache
        (event_handlers.go:43-740) feeding runOnce (scheduler.go:91). A full
        Session build (deep pack) happens only on the first cycle, on
        structural cluster changes, or when refresh_snapshot takes one of
        its documented repack fallbacks (then inside the same session)."""
        overrides = self._persistent_plugins()
        if not self.incremental:
            return Session(self.cluster.snapshot(), self.conf, now=now,
                           plugin_overrides=overrides)
        dj, dn, structural = self.cluster.drain_dirty()
        ssn = self._session
        if ssn is None or structural:
            # a fresh full pack absorbs any dirty backlog
            ssn = Session(self.cluster.live_view(), self.conf, now=now,
                          plugin_overrides=overrides)
            self._session = ssn
            self.full_packs += 1
            return ssn
        for uid in dj:
            ssn._dirty_jobs.add(uid)
        for name in dn:
            ssn._dirty_nodes.add(name)
        if ssn.reopen(now=now, conf=self.conf, plugin_overrides=overrides):
            self.incremental_cycles += 1
        else:
            self.full_packs += 1
        return ssn

    def run_once(self, now: Optional[float] = None) -> Session:
        """One scheduling cycle (runOnce, scheduler.go:91-120)."""
        reloaded = self._load_conf()
        if reloaded is not None:
            self.conf = reloaded
        t0 = time.time()
        wall = now if now is not None else t0
        # drain due resync retries BEFORE snapshotting so the cycle sees
        # their outcomes (the errTasks worker runs alongside the loop,
        # cache.go:687-709)
        if len(self.resync):
            rs = self.resync.process(self.cluster, wall)
            METRICS.inc("resync_retried", rs["retried"])
            METRICS.inc("resync_succeeded", rs["succeeded"])
            METRICS.inc("resync_dropped", rs["dropped"])
        ssn = self._open_session(now)
        from ..actions import get_action
        for name in self.conf.actions:
            ta = time.time()
            get_action(name).execute(ssn)
            METRICS.observe_action(name, time.time() - ta)
        ssn.close()

        # PodGroup status write-back at session close (the jobUpdater's
        # parallel UpdatePodGroup flush, framework/job_updater.go:66-108)
        self.cluster.update_podgroup_phases(ssn.phase_updates)

        for intent in ssn.evictions:
            if not self.cluster.evict(intent):
                METRICS.inc("resync_tasks")
                self.resync.add(intent, "evict", wall)
        for intent in ssn.binds:
            if not self.cluster.bind(intent):
                METRICS.inc("resync_tasks")
                # hold the Binding state so later cycles don't re-decide
                # while the rate-limited retry works (cache.go:549-560)
                self.cluster.hold_binding(intent)
                self.resync.add(intent, "bind", wall)
        cycle_s = time.time() - t0
        METRICS.observe_cycle(cycle_s)
        METRICS.inc("schedule_attempts")
        # reference vocabulary: schedule_attempts_total{result=...}
        # (metrics.go:92-100 scheduleAttempts) — "error" when a bind
        # degraded to a recorded error, else by whether anything placed
        result = ("error" if ssn.bind_errors
                  else "scheduled" if (ssn.binds or ssn.pipelined)
                  else "unschedulable")
        METRICS.inc("schedule_attempts_total", labels={"result": result})
        # jit trace-vs-call gauges (telemetry/tracecount): a moving
        # volcano_jit_traces{entry=...} on the steady-state path is a
        # retrace incident
        from ..telemetry import publish_gauges
        publish_gauges(METRICS)
        self.cycles += 1
        self.flight.record(
            now=wall, cycle=self.cycles, cycle_ms=round(cycle_s * 1000, 3),
            binds=len(ssn.binds), evictions=len(ssn.evictions),
            pipelined=len(ssn.pipelined), bind_errors=len(ssn.bind_errors),
            resync_pending=len(self.resync), result=result,
            stats={k: round(float(v), 3) for k, v in ssn.stats.items()},
            telemetry=ssn.last_telemetry or None)
        return ssn

    def run(self, cycles: int = 1, sleep: bool = False) -> List[Session]:
        out = []
        for _ in range(cycles):
            out.append(self.run_once())
            if sleep:
                time.sleep(self.schedule_period)
        return out
