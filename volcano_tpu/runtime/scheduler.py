"""The scheduler loop: snapshot -> session -> actions -> bind.

Reference: pkg/scheduler/scheduler.go:54-171 (Scheduler.Run / runOnce with
the 1s wait.Until cycle, conf hot-reload) and cmd/scheduler/app/server.go.
The loop is synchronous here; bind/evict intents flush to the cluster source
at the end of each cycle (the reference fires them as goroutines mid-cycle —
same external effect, recorded in order).
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional

from ..framework.conf import SchedulerConfiguration, parse_conf
from ..framework.session import Session
from ..metrics import METRICS
from ..telemetry import spans
from .fake_cluster import FakeCluster


class ResyncQueue:
    """Rate-limited retry queue for failed bind/evict dispatches.

    The errTasks workqueue analog (cache.go:687-709): per-item exponential
    backoff (AddRateLimited's ItemExponentialFailureRateLimiter shape),
    retries the SAME intent on later cycles without a fresh scheduling
    decision, and after ``max_attempts`` gives up and resyncs the task back
    to Pending (the syncTask refetch discovering the pod never bound)."""

    def __init__(self, base_delay: float = 0.005, max_delay: float = 10.0,
                 max_attempts: int = 15):
        self.base_delay = base_delay
        self.max_delay = max_delay
        self.max_attempts = max_attempts
        self.entries: List[dict] = []
        #: attempts-exhausted intents, kept (bounded by workload, not
        #: uptime: an intent dead-letters at most once) instead of being
        #: dropped silently — surfaced through METRICS
        #: ``resync_dead_letter_total`` and the flight recorder so an
        #: operator can see WHAT the scheduler gave up on, the way the
        #: reference's Forget + event log does
        self.dead: List[dict] = []

    def __len__(self) -> int:
        return len(self.entries)

    def add(self, intent, kind: str, now: float, attempts: int = 1) -> None:
        delay = min(self.base_delay * (2 ** (attempts - 1)), self.max_delay)
        self.entries.append(dict(intent=intent, kind=kind, attempts=attempts,
                                 next_try=now + delay))

    def dead_letter(self) -> List[dict]:
        """Copies of the attempts-exhausted entries (intent, kind,
        attempts, gave_up_at). Never mutated by later processing."""
        return [dict(e) for e in self.dead]

    def redrive(self, now: float = 0.0) -> int:
        """Dead letters back to pending with attempts reset — the second
        life a restart grants: the crash that stranded these intents also
        reset whatever condition exhausted their retries (a wedged node
        agent, a stale hold). Called once after a successful restore;
        counted as ``resync_redrive_total``."""
        dead, self.dead = self.dead, []
        for e in dead:
            self.add(e["intent"], e["kind"], now, attempts=1)
        if dead:
            METRICS.inc("resync_redrive_total", len(dead))
            spans.log_event("resync_redrive", count=len(dead))
        return len(dead)

    def process(self, cluster, now: float,
                fence: Optional[int] = None) -> Dict[str, int]:
        """Retry every due entry against the cluster. Returns counters.
        An entry that exhausts ``max_attempts`` is never dropped silently:
        it moves to the dead-letter list (and a bind additionally resyncs
        the task back to Pending, the syncTask give-up). A ``fence`` that
        the cluster no longer admits (this replica was deposed) drops the
        due entries outright — a deposed leader must not keep retrying
        writes the fencing token already rejected."""
        due = [e for e in self.entries if e["next_try"] <= now]
        self.entries = [e for e in self.entries if e["next_try"] > now]
        stats = dict(retried=0, succeeded=0, dropped=0, dead_lettered=0,
                     fenced=0)
        for e in due:
            if fence is not None and not cluster.fence_admits(fence):
                stats["fenced"] += 1
                continue
            stats["retried"] += 1
            ok = ((cluster.bind(e["intent"], fence=fence)
                   if e["kind"] == "bind"
                   else cluster.evict(e["intent"], fence=fence))
                  if fence is not None
                  else (cluster.bind(e["intent"]) if e["kind"] == "bind"
                        else cluster.evict(e["intent"])))
            if ok:
                stats["succeeded"] += 1
            elif e["attempts"] >= self.max_attempts:
                stats["dropped"] += 1
                stats["dead_lettered"] += 1
                self.dead.append(dict(e, gave_up_at=now))
                if e["kind"] == "bind":
                    cluster.resync_task(e["intent"].task_uid)
            else:
                self.add(e["intent"], e["kind"], now, e["attempts"] + 1)
        return stats


class _InFlight:
    """One pending-ring slot: a dispatched-but-undrained cycle. The ring
    generalizes the depth-1 ``_pending`` tuple — slot 0 is always the
    oldest in-flight cycle and the next to drain."""

    __slots__ = ("ssn", "pending", "host_s", "wall", "invalid")

    def __init__(self, ssn, pending, host_s, wall, invalid=False):
        self.ssn = ssn
        self.pending = pending
        self.host_s = host_s
        self.wall = wall
        #: a drained predecessor applied decisions (or faulted) after this
        #: cycle dispatched — its speculative input epoch is stale, so its
        #: drain replays the cycle synchronously instead of applying it
        self.invalid = invalid


class Scheduler:
    def __init__(self, cluster: FakeCluster,
                 conf: Optional[SchedulerConfiguration] = None,
                 conf_path: Optional[str] = None,
                 schedule_period: float = 1.0,
                 incremental: bool = True,
                 pipeline: Optional[bool] = None,
                 elector=None):
        self.cluster = cluster
        # HA leader election (ISSUE 11): when an elector is attached the
        # scheduler OWNS the leadership check — run_once ticks it, skips
        # dispatch as a follower (the silent-lease-loss fix: callers no
        # longer have to poll tick() themselves), surfaces transitions as
        # leader_transitions_total + a JSONL `leadership` event, and
        # stamps every cluster write with the lease generation (the
        # fencing token).
        self.elector = elector
        self._was_leader = bool(elector.is_leader) if elector else False
        self.conf_path = conf_path
        self._conf_mtime = 0.0
        self.conf = conf or self._load_conf() or parse_conf()
        self.schedule_period = schedule_period
        # one-deep pipelined loop (conf `pipeline: true` or constructor
        # override): run_once dispatches the compiled cycle and defers the
        # packed readback; the NEXT run_once drains it first — decisions
        # are always applied before their input buffers can be
        # overwritten (depth 1), and before the next cycle's snapshot is
        # refreshed, so the decision sequence matches the synchronous loop
        self.pipeline = (bool(getattr(self.conf, "pipeline", False))
                         if pipeline is None else bool(pipeline))
        #: the pending ring: dispatched-but-undrained cycles, oldest
        #: first; bounded by the effective pipeline depth (conf
        #: ``pipeline_depth``, default 1 — the legacy one-deep contract)
        self._ring: List[_InFlight] = []
        #: monotonic dispatch sequence — per-slot device windows in the
        #: occupancy trace
        self._slot_seq = 0
        #: speculation ladder state: depth clamps to 1 until this cycle
        #: count after a speculation fault; a repeat inside the hold
        #: degrades to fully synchronous (level 1)
        self._spec_disabled_until = 0
        # opt-in persistent XLA compilation cache (conf/env) — restarts
        # stop paying compile_s for already-seen shape buckets
        from ..framework.compile_cache import enable_compilation_cache
        enable_compilation_cache(
            getattr(self.conf, "compilation_cache_dir", None))
        self._plugin_state: Dict[str, object] = {}
        self.cycles = 0
        self.resync = ResyncQueue()
        # the persistent session (VERDICT r4 #1): built over the cluster's
        # live view on the first cycle, then re-opened each cycle via
        # refresh_snapshot from the cluster's dirty marks — the steady-state
        # path that skips the full re-pack. incremental=False restores the
        # fresh-Session-per-cycle behavior (the oracle for equality tests).
        self.incremental = incremental and hasattr(cluster, "live_view")
        self._session: Optional[Session] = None
        #: cycles that paid a full pack (first cycle, structural change, or
        #: a refresh fallback) vs cycles served by the incremental patch —
        #: the steady-state claim is checkable: full_packs stays at 1
        self.full_packs = 0
        self.incremental_cycles = 0
        #: (dirty job count, dirty node count) the last session open drained
        #: from the cluster — the raw material the delta upload packs, so
        #: the flight recorder can correlate dirty-mark volume with
        #: upload_bytes per cycle
        self._last_dirty = (0, 0)
        #: bounded flight recorder: the last N cycle snapshots (host
        #: timestamps, latency, bind/evict counts, in-graph telemetry when
        #: the conf enables it), served by the dashboard's /api/telemetry
        from ..telemetry import FlightRecorder
        self.flight = FlightRecorder(
            capacity=int(os.environ.get("VOLCANO_FLIGHT_CYCLES", 64)))
        # ---- fault tolerance (ISSUE 5) --------------------------------
        #: per-cycle watchdog deadline for the dispatch/drain halves, in
        #: seconds (conf ``cycle_deadline_ms``; None = off). A blown
        #: deadline retires the cycle synchronously and drops out of
        #: pipelining for the cooldown window.
        ddl = getattr(self.conf, "cycle_deadline_ms", None)
        self.cycle_deadline_s = (float(ddl) / 1000.0) if ddl else None
        #: degradation ladder: 0 = pipelined (when configured), 1 = sync
        #: (a fault was recovered; pipelining suspended), 2 = elastic-mesh
        #: (persistent device loss — the sharded cycle serves on a shrunk
        #: mesh over the surviving devices, parallel/health.py), 3 =
        #: cpu-oracle (the compiled dispatch is gone entirely).
        #: De-escalates to 0 after ``fault_cooldown`` clean cycles.
        self.degradation_level = 0
        self.fault_cooldown = int(os.environ.get("VOLCANO_FAULT_COOLDOWN",
                                                 4))
        self._degrade_until = 0
        self._cycle_faults: List[dict] = []
        # ---- elastic mesh (ISSUE 20) ----------------------------------
        #: serving mesh width observed at the last finished cycle — the
        #: reference point for mesh JSONL events and the mesh_width gauge
        self._last_mesh_devices: Optional[int] = None
        #: the health-registry generation this scheduler last re-meshed
        #: at; a newer generation means the device set changed under us
        self._health_gen_seen = 0

    def _load_conf(self) -> Optional[SchedulerConfiguration]:
        """Conf hot-reload (fsnotify watcher, scheduler.go:146-171 — here a
        cheap mtime poll at cycle start)."""
        if not self.conf_path or not os.path.exists(self.conf_path):
            return None
        mtime = os.path.getmtime(self.conf_path)
        if mtime == self._conf_mtime:
            return None
        self._conf_mtime = mtime
        with open(self.conf_path) as f:
            return parse_conf(f.read())

    @property
    def _pending(self):
        """Depth-1 compatibility view of the pending ring: the oldest
        in-flight entry as the legacy ``(ssn, pending, host_s, wall)``
        tuple, or None when nothing is in flight."""
        if not self._ring:
            return None
        e = self._ring[0]
        return (e.ssn, e.pending, e.host_s, e.wall)

    @_pending.setter
    def _pending(self, value) -> None:
        if value is None:
            self._ring.clear()
        else:
            ssn, pending, host_s, wall = value
            self._ring = [_InFlight(ssn, pending, host_s, wall)]

    def _effective_depth(self) -> int:
        """How many cycles may be in flight after this run_once's
        dispatch. Depth > 1 (speculation) requires the full steady-state
        stack: pipelined mode, a clean ladder, the persistent incremental
        session (replay reopens it in place), an unsharded kernel, and no
        active speculation hold."""
        if not self.pipeline or self.degradation_level:
            return 1
        depth = max(1, int(getattr(self.conf, "pipeline_depth", 1) or 1))
        if depth == 1:
            return 1
        if not self.incremental or getattr(self.conf, "sharding", False):
            return 1
        if self.cycles < self._spec_disabled_until:
            return 1
        return depth

    def _spec_penalty(self) -> None:
        """Speculation ladder: the first failure clamps the depth to 1
        for the cooldown window; a repeat inside the hold drops to the
        synchronous rung of the main ladder."""
        if self.cycles < self._spec_disabled_until:
            self._degrade(1)
        else:
            spans.log_event("speculation", action="disabled",
                            cycle=self.cycles,
                            until=self.cycles + self.fault_cooldown)
        self._spec_disabled_until = self.cycles + self.fault_cooldown

    def _invalidate_ring(self) -> None:
        """A drained cycle applied decisions (or faulted): every still-
        in-flight speculative cycle consumed a snapshot that predates
        them — mark for decision-neutral replay at drain."""
        for e in self._ring:
            e.invalid = True

    def _resolve_ring(self) -> None:
        """Join every outstanding pack-thread future. Must run before
        anything refreshes the session snapshot in place (reopen/replay)
        — the worker reads the packed arrays it was handed at dispatch.
        A worker failure invalidates its entry (replayed at drain) and
        walks the speculation ladder."""
        for e in self._ring:
            if e.pending.future is not None:
                try:
                    e.ssn.resolve_pending(e.pending)
                except Exception as ex:
                    self._note_fault("pack_thread", ex)
                    self._spec_penalty()
                    e.invalid = True

    def _persistent_plugins(self) -> Dict[str, object]:
        """Plugins with cross-cycle state: the reservation singleton and
        tdm's lastEvictAt rate limiter (tdm.go:232-236)."""
        from ..plugins.reservation import ReservationPlugin
        from ..plugins.tdm import TDMPlugin
        overrides = {}
        for name, cls in (("reservation", ReservationPlugin),
                          ("tdm", TDMPlugin)):
            if self.conf.plugin_option(name) is not None:
                if name not in self._plugin_state:
                    self._plugin_state[name] = cls(
                        self.conf.plugin_option(name))
                overrides[name] = self._plugin_state[name]
        return overrides

    def _open_session(self, now: Optional[float]) -> Session:
        """Open this cycle's session.

        Steady state holds ONE session across cycles and re-opens it with an
        incremental snapshot refresh fed by the cluster's dirty marks — the
        analog of the reference's incrementally maintained cache
        (event_handlers.go:43-740) feeding runOnce (scheduler.go:91). A full
        Session build (deep pack) happens only on the first cycle, on
        structural cluster changes, or when refresh_snapshot takes one of
        its documented repack fallbacks (then inside the same session)."""
        overrides = self._persistent_plugins()
        if not self.incremental:
            return Session(self.cluster.snapshot(), self.conf, now=now,
                           plugin_overrides=overrides)
        dj, dn, structural = self.cluster.drain_dirty()
        self._last_dirty = (len(dj), len(dn))
        ssn = self._session
        if ssn is None or structural:
            # a fresh full pack absorbs any dirty backlog
            ssn = Session(self.cluster.live_view(), self.conf, now=now,
                          plugin_overrides=overrides)
            self._session = ssn
            self.full_packs += 1
            warm = getattr(self, "_restored_mirrors", None)
            if warm:
                # warm restart: the freshly packed session adopts the
                # checkpointed (digest-verified) mirrors, so its first
                # allocate ships a delta against pre-crash residency
                # instead of the full cold upload
                ssn._warm_mirrors = warm
                self._restored_mirrors = None
            return ssn
        for uid in dj:
            ssn._dirty_jobs.add(uid)
        for name in dn:
            ssn._dirty_nodes.add(name)
        if ssn.reopen(now=now, conf=self.conf, plugin_overrides=overrides):
            self.incremental_cycles += 1
        else:
            self.full_packs += 1
        return ssn

    def warmup(self, now: Optional[float] = None) -> None:
        """AOT warmup hook: open the persistent session for the cluster's
        current shape bucket and compile the allocate entry ahead of the
        first real cycle. With the persistent compilation cache enabled
        (conf ``compilation_cache_dir`` / $VOLCANO_JAX_CACHE_DIR) a
        restarted scheduler pays a disk read instead of ``compile_s``."""
        self._open_session(now).warm_allocate()

    def run_once(self, now: Optional[float] = None) -> Session:
        """One scheduling cycle (runOnce, scheduler.go:91-120).

        Synchronous mode (default): dispatch + readback + apply + flush in
        this call; returns this cycle's Session.

        Pipelined mode (conf ``pipeline: true``): FIRST drain the previous
        cycle's deferred readback — apply its decisions and flush its
        intents — THEN refresh the snapshot and dispatch this cycle,
        returning without reading it back (device compute overlaps the
        host's inter-cycle event ingestion). Depth is bounded at 1, so a
        cycle's decisions are always applied before the resident input
        buffers can be overwritten by the next delta upload, and before
        the next snapshot refresh — the decision sequence is bit-identical
        to the synchronous loop (see docs/architecture.md "Steady-state
        pipeline"). Returns the just-COMPLETED cycle's record (None-like
        first call returns the in-flight session); call :meth:`drain` to
        retire the final in-flight cycle."""
        reloaded = self._load_conf()
        if reloaded is not None:
            self.conf = reloaded
        t0 = time.time()
        wall = now if now is not None else t0
        # fault-injection seam: arms this cycle's scheduled faults
        from ..chaos.inject import seam
        seam("scheduler.cycle", cycle=self.cycles, scheduler=self)
        if self.elector is not None:
            leader = self.elector.tick()
            if leader != self._was_leader:
                self._note_leadership(leader)
            if not leader:
                # follower: no dispatch, and cycles left in flight from
                # our leader tenure are DISCARDED unapplied — their writes
                # would be fenced off anyway; the new leader re-decides
                # from the same external truth
                if self._ring:
                    dropped = len(self._ring)
                    self._resolve_ring()  # join workers before discarding
                    self._ring.clear()
                    METRICS.inc("cycle_dropped_total", dropped)
                    spans.log_event("leadership", action="pending_dropped",
                                    identity=self.elector.identity,
                                    count=dropped, cycle=self.cycles)
                return None
        # degradation de-escalation probe: after the cooldown window of
        # clean cycles, climb back to the configured mode
        if self.degradation_level and self.cycles >= self._degrade_until:
            spans.log_event("degradation", level_from=self.degradation_level,
                            level_to=0, cycle=self.cycles,
                            mesh_devices=self._last_mesh_devices)
            self.degradation_level = 0
            METRICS.set_gauge("degradation_level", None, 0)
        # elastic-mesh probation clock: after a quiet probation interval
        # the health registry lifts the shrink cap a pow2 step and
        # releases quarantined devices on probation; dropping the sharded
        # residency makes the next dispatch re-fuse from source truth on
        # the regrown mesh (decision-neutral, like the shrink was)
        if getattr(self.conf, "sharding", False):
            from ..parallel.health import HEALTH
            regrow = HEALTH.tick(self.cycles)
            if regrow is not None:
                if self._session is not None:
                    self._session.drop_sharded_residency()
                self._health_gen_seen = HEALTH.generation
                METRICS.inc("mesh_regrow_total")
                spans.log_event("mesh", action="regrow", cycle=self.cycles,
                                width_cap=regrow["width_cap"],
                                released=regrow["released"],
                                probation_interval=regrow["interval"])
        actions = list(self.conf.actions)

        def _will_pipeline() -> bool:
            # the pipeline defers the allocate readback across run_once
            # boundaries, so it requires allocate to be the cycle's LAST
            # action (anything after it would need the decisions applied);
            # other action lists fall back to the synchronous path, as
            # does a degraded scheduler until the cooldown expires
            return (self.pipeline and self.degradation_level == 0
                    and bool(actions) and actions[-1] == "allocate")

        # drain until the ring has room for this cycle's dispatch (depth-1
        # keeps today's drain-exactly-one; sync cycles drain everything).
        # Drains can walk the ladder (integrity trips), which shrinks the
        # effective depth — hence the recomputation inside the loop.
        completed = None
        while self._ring and len(self._ring) > (
                self._effective_depth() - 1 if _will_pipeline() else 0):
            completed = self._drain_pending(wall) or completed
        pipelined = _will_pipeline()
        # join any still-outstanding pack thread BEFORE the snapshot
        # refresh below mutates the arrays it is reading
        self._resolve_ring()
        # drain due resync retries BEFORE snapshotting so the cycle sees
        # their outcomes (the errTasks worker runs alongside the loop,
        # cache.go:687-709)
        if len(self.resync):
            rs = self.resync.process(self.cluster, wall,
                                     fence=self._fence())
            METRICS.inc("resync_retried", rs["retried"])
            METRICS.inc("resync_succeeded", rs["succeeded"])
            METRICS.inc("resync_dropped", rs["dropped"])
            if rs["dead_lettered"]:
                METRICS.inc("resync_dead_letter_total", rs["dead_lettered"])
        with spans.span("cycle.open"):
            ssn = self._open_session(now)
        from ..actions import get_action
        for name in (actions[:-1] if pipelined else actions):
            ta = time.time()
            with spans.span(f"action.{name}"):
                try:
                    get_action(name).execute(ssn)
                except Exception as e:
                    if name != "allocate":
                        raise
                    # the compiled allocate failed mid-action: walk the
                    # ladder
                    self._note_fault("allocate", e)
                    self._note_device_fault(ssn, e)
                    self._allocate_degraded(ssn)
            METRICS.observe_action(name, time.time() - ta)
        if pipelined:
            depth = self._effective_depth()
            ta = time.time()
            # predecessors still in flight make this dispatch speculative:
            # it consumes the freshest refreshed snapshot but NOT the
            # undrained predecessors' decisions, and it must keep its own
            # scratch (their mirror captures are still referenced)
            spec = bool(self._ring)
            try:
                pending = ssn.dispatch_allocate(speculative=spec,
                                                async_pack=True)
            except Exception as e:
                # dispatch failed on the calling thread (nothing went out
                # for this cycle): retire any in-flight work first — the
                # sync fallback below re-dispatches, and the decisions
                # chain must stay in device order — then walk the ladder
                self._note_fault("dispatch", e)
                self._note_device_fault(ssn, e)
                if self._ring:
                    self.drain(now=wall)
                self._allocate_degraded(ssn)
                return self._finish_cycle(ssn, time.time() - t0, wall)
            pending.slot = self._slot_seq
            self._slot_seq += 1
            pending.depth = depth
            took = time.time() - ta
            METRICS.observe_action("allocate_dispatch", took)
            if self.cycle_deadline_s is not None \
                    and took > self.cycle_deadline_s:
                # watchdog: the dispatch blew the cycle deadline — retire
                # the pending cycle synchronously NOW (its decisions are
                # unaffected; only the overlap is lost) and drop out of
                # pipelining for the cooldown window
                self._note_fault("deadline", TimeoutError(
                    f"dispatch took {took * 1000:.0f} ms "
                    f"(deadline {self.cycle_deadline_s * 1000:.0f} ms)"))
                self._degrade(1)
                self._ring.append(
                    _InFlight(ssn, pending, time.time() - t0, wall))
                completed_now = self.drain(now=wall)
                return completed if completed is not None else completed_now
            self._ring.append(
                _InFlight(ssn, pending, time.time() - t0, wall))
            return completed if completed is not None else ssn
        return self._finish_cycle(ssn, time.time() - t0, wall)

    # ------------------------------------------------ HA leadership / fence
    def _fence(self) -> Optional[int]:
        """The fencing token this scheduler stamps on cluster writes: the
        generation of the last lease its elector held. Deliberately NOT
        refreshed on step-down — a deposed leader keeps presenting its
        old token so the fence rejects its late writes. None (no elector)
        keeps every legacy caller unfenced."""
        return None if self.elector is None else self.elector.generation

    def _note_leadership(self, leader: bool) -> None:
        """A leadership transition observed by run_once: counter, gauge,
        and a JSONL ``leadership`` event (the PR 8 event log)."""
        self._was_leader = leader
        METRICS.inc("leader_transitions_total",
                    labels={"to": "leader" if leader else "follower"})
        METRICS.set_gauge("is_leader", None, 1 if leader else 0)
        spans.log_event("leadership", leader=leader,
                        identity=self.elector.identity,
                        generation=self.elector.generation,
                        transitions=METRICS.counter_total(
                            "leader_transitions_total"),
                        cycle=self.cycles)

    # -------------------------------------------- fault handling / ladder
    def _note_fault(self, stage: str, exc: BaseException) -> None:
        """Record a recovered fault: METRICS counter, the per-cycle fault
        list the flight recorder snapshots, and a log-ready string."""
        METRICS.inc("cycle_faults_total", labels={"stage": stage})
        self._cycle_faults.append(
            dict(stage=stage, error=f"{type(exc).__name__}: {exc}"))

    def _degrade(self, level: int) -> None:
        """Escalate the degradation ladder and (re)start the cooldown."""
        prev = self.degradation_level
        self.degradation_level = max(self.degradation_level, level)
        if self.degradation_level != prev:
            spans.log_event("degradation", level_from=prev,
                            level_to=self.degradation_level,
                            cycle=self.cycles,
                            mesh_devices=self._last_mesh_devices)
        self._degrade_until = self.cycles + self.fault_cooldown
        METRICS.set_gauge("degradation_level", None, self.degradation_level)

    def _note_device_fault(self, ssn: Session, exc: BaseException) -> None:
        """Feed a dispatch failure's device attribution (if any) to the
        health registry: strikes accumulate per device and N-in-a-window
        quarantines, which halves the serving-width cap and invalidates
        the mesh cache — the next ``_sharding_mesh()`` call anywhere in
        the process lands on the shrunk survivor mesh."""
        if not getattr(self.conf, "sharding", False):
            return
        from ..parallel.health import HEALTH, failed_devices
        if not failed_devices(exc):
            return
        width = None
        try:
            mesh = ssn._sharding_mesh()
            width = int(mesh.devices.size) if mesh is not None else None
        except Exception:
            pass
        newly = HEALTH.note_failure(exc, self.cycles, serving_width=width)
        if newly:
            METRICS.inc("mesh_shrink_total",
                        labels={"reason": "quarantine"})
            spans.log_event("mesh", action="shrink", cycle=self.cycles,
                            quarantined=list(newly),
                            width_from=width, width_cap=HEALTH.width_cap,
                            mesh_devices=self._last_mesh_devices)

    def _try_remesh(self, ssn: Session):
        """The elastic-mesh rung: if the health registry quarantined
        devices since we last re-meshed, drop the sharded residency and
        retry the compiled dispatch — ``_sharding_mesh()`` now resolves
        to the shrunk mesh over the survivors and the residents re-fuse
        from source truth on it (the ISSUE 10 recovery primitive, so the
        retry is decision-neutral by construction). Returns the allocate
        result, or None when there is nothing to re-mesh (no sharding, no
        new quarantine) or the shrunk mesh failed too."""
        if not getattr(self.conf, "sharding", False):
            return None
        from ..parallel.health import HEALTH
        for _ in range(3):          # a flap can kill the shrunk mesh too
            if HEALTH.generation == self._health_gen_seen:
                return None
            self._health_gen_seen = HEALTH.generation
            t0 = time.time()
            try:
                with spans.span("cycle.remesh", cat="recovery"):
                    ssn.drop_sharded_residency()
                    result = ssn.run_allocate()
            except Exception as e:
                self._note_fault("remesh", e)
                self._note_device_fault(ssn, e)
                continue
            remesh_ms = (time.time() - t0) * 1000
            ssn.stats["remesh_ms"] = remesh_ms
            width = ssn.stats.get("mesh_devices")
            spans.log_event("mesh", action="serve_shrunk",
                            cycle=self.cycles,
                            mesh_devices=(int(width) if width is not None
                                          else None),
                            remesh_ms=round(remesh_ms, 3))
            return result
        return None

    def _allocate_degraded(self, ssn: Session) -> None:
        """The compiled allocate dispatch raised: walk the degradation
        ladder — one synchronous retry (a transient fault; the delta path
        reset itself to a clean full upload), then the elastic-mesh rung
        (persistent device loss: quarantine the attributed devices,
        rebuild the mesh at the next pow2 width over the survivors,
        re-fuse from source truth, serve sharded), then the pure-host CPU
        oracle if no mesh can serve at all. Decisions stay bit-identical
        on every rung (the oracle is the kernel suites' equality
        reference; the shrunk mesh re-fuses from the same source truth),
        so a recovered fault is decision-neutral."""
        import numpy as np
        t0 = time.time()
        with spans.span("cycle.recovery", cat="recovery"):
            try:
                result = ssn.run_allocate()
                mode = "sync"
                self._degrade(1)
            except Exception as e:
                self._note_fault("sync_retry", e)
                self._note_device_fault(ssn, e)
                result = self._try_remesh(ssn)
                if result is not None:
                    mode = "remesh"
                    self._degrade(2)
                else:
                    result = ssn.run_allocate_oracle()
                    mode = "cpu_oracle"
                    self._degrade(3)
        ssn.stats["allocated_binds"] = len(ssn.binds)
        ssn.stats["jobs_ready"] = int(np.asarray(result.job_ready).sum())
        ssn.stats["jobs_pipelined"] = int(
            np.asarray(result.job_pipelined).sum())
        ssn.stats.setdefault("recovery_ms", (time.time() - t0) * 1000)
        METRICS.inc("cycle_recoveries_total",
                    labels={"reason": "dispatch", "mode": mode})
        spans.log_event("recovery", stage="dispatch", mode=mode,
                        cycle=self.cycles,
                        recovery_ms=round((time.time() - t0) * 1000, 3))

    def _drain_pending(self, wall: float):
        """Drain the OLDEST in-flight cycle: read its packed decisions
        back (or replay it synchronously if a predecessor invalidated its
        input epoch), apply them, and flush its intents. Returns a
        detached record of the completed cycle (the live Session object is
        re-opened for the next cycle right after, which resets its intent
        lists) or None when nothing was in flight."""
        if not self._ring:
            return None
        import numpy as np
        entry = self._ring.pop(0)
        ssn, pending, host_s = entry.ssn, entry.pending, entry.host_s
        if getattr(ssn, "_cycle_state_dirty", False):
            # a second drain of the same session without an intervening
            # reopen (drain-all, depth shrink): clear the previous drain's
            # intents so this cycle's record is its own
            ssn._reset_cycle_state()
        ssn._cycle_state_dirty = True
        t0 = time.time()
        replayed = False
        try:
            with spans.span("cycle.drain"):
                if entry.invalid:
                    # the dispatched work is discarded, but the worker must
                    # be joined first — the replay below redispatches on
                    # the same kernel state
                    try:
                        ssn.resolve_pending(pending)
                    except Exception:
                        pass
                    result = self._replay_entry(entry, wall)
                    replayed = True
                else:
                    try:
                        ssn.resolve_pending(pending)
                    except Exception as e:
                        # the pack thread failed: nothing reached the
                        # device for this cycle — replay it synchronously
                        self._note_fault("pack_thread", e)
                        self._spec_penalty()
                        result = self._replay_entry(entry, wall)
                        replayed = True
                    else:
                        result = ssn.complete_allocate(pending)
        except Exception as e:
            # complete_allocate already walked re-fuse -> cpu-oracle; if it
            # STILL raised the cycle is unrecoverable. Keep serving: retire
            # it with no decisions applied instead of crashing the loop.
            self._note_fault("drain", e)
            self._degrade(3)
            self._invalidate_ring()
            METRICS.inc("cycle_dropped_total")
            ssn.stats["cycle_dropped"] = 1.0
            self._finish_cycle(ssn, host_s + (time.time() - t0), wall)
            return CompletedCycle(ssn)
        took = time.time() - t0
        integ = ssn.last_telemetry.get("integrity")
        if integ is not None:
            # the drain recovered in place (digest trip / dead readback):
            # drop to the matching ladder rung for the cooldown window
            self._note_fault("integrity:" + str(integ.get("reason")),
                             RuntimeError(str(integ.get("mode"))))
            self._degrade(3 if integ.get("mode") == "cpu_oracle" else 1)
        if self.cycle_deadline_s is not None \
                and pending.dispatch_ms / 1000.0 > self.cycle_deadline_s \
                and not replayed:
            # the pack thread's own dispatch blew the deadline (the
            # main-thread watchdog in run_once no longer sees worker time)
            self._note_fault("deadline", TimeoutError(
                f"dispatch took {pending.dispatch_ms:.0f} ms "
                f"(deadline {self.cycle_deadline_s * 1000:.0f} ms)"))
            self._degrade(1)
        if self.cycle_deadline_s is not None and took > self.cycle_deadline_s:
            self._note_fault("deadline_drain", TimeoutError(
                f"drain took {took * 1000:.0f} ms"))
            self._degrade(1)
        if replayed:
            ssn.stats["cycle_replayed"] = 1.0
        # epoch invalidation for the still-in-flight speculative cycles:
        # only EFFECTIVE outputs count — binds, evictions, bind errors, or
        # a phase transition that actually changed cluster truth. Pure
        # structural churn never invalidates (a speculative dispatch
        # already consumed every dirty mark at its own reopen).
        if (ssn.binds or ssn.evictions or ssn.bind_errors
                or ssn.phase_changes):
            self._invalidate_ring()
        # the AllocateAction readouts the synchronous path records
        ssn.stats["allocated_binds"] = len(ssn.binds)
        ssn.stats["jobs_ready"] = int(np.asarray(result.job_ready).sum())
        ssn.stats["jobs_pipelined"] = int(
            np.asarray(result.job_pipelined).sum())
        self._finish_cycle(ssn, host_s + took, wall)
        return CompletedCycle(ssn)

    def _replay_entry(self, entry: _InFlight, wall: float):
        """Decision-neutral replay of an invalidated speculative cycle:
        re-decide synchronously at the cycle's drain slot. The replay
        merges any cluster churn, reopens the session, re-runs the cycle's
        actions, and dispatches + completes in one breath — bit-identical
        to the synchronous loop whenever the cluster stayed quiet during
        the flight (the speculation probe's construction); otherwise it
        sees strictly fresher truth than the discarded speculation did."""
        ssn, pending = entry.ssn, entry.pending
        METRICS.inc("cycle_replays_total")
        spans.log_event("replay", cycle=self.cycles, slot=pending.slot,
                        speculative=bool(pending.speculative))
        state = pending.state
        if state is not None:
            # the discarded dispatch already advanced the device decisions
            # chain, and the replay advances it again: new lineage — every
            # older in-flight tail drains full, and the replay's own full
            # readback reseeds the mirror for the dispatches that follow
            state.dec_epoch = getattr(state, "dec_epoch", 0) + 1
            state.dec_mirror = None
        # join outstanding workers before the reopen mutates the snapshot
        # arrays they read
        self._resolve_ring()
        dj, dn, _structural = self.cluster.drain_dirty()
        for uid in dj:
            ssn._dirty_jobs.add(uid)
        for name in dn:
            ssn._dirty_nodes.add(name)
        self._last_dirty = (len(dj), len(dn))
        overrides = self._persistent_plugins()
        if ssn.reopen(now=entry.wall, conf=self.conf,
                      plugin_overrides=overrides):
            self.incremental_cycles += 1
        else:
            self.full_packs += 1
        from ..actions import get_action
        for name in list(self.conf.actions)[:-1]:
            with spans.span(f"action.{name}"):
                get_action(name).execute(ssn)
        try:
            rp = ssn.dispatch_allocate(speculative=bool(self._ring))
            rp.slot = pending.slot
            rp.depth = pending.depth
            return ssn.complete_allocate(rp)
        except Exception as e:
            self._note_fault("replay", e)
            self._allocate_degraded(ssn)
            return ssn.last_allocate

    def _finish_cycle(self, ssn: Session, host_s: float,
                      wall: float) -> Session:
        """Everything after the last action: close, write back, flush
        intents, metrics, flight record — shared by the synchronous path
        and the pipelined drain."""
        with spans.span("cycle.finish"):
            ssn.close()

            fence = self._fence()

            def _fenced_off() -> bool:
                # the cluster refused our token: this replica was deposed
                # mid-flight. The rejection is permanent for this token —
                # never resync it (the new leader owns the decision now).
                return fence is not None \
                    and not self.cluster.fence_admits(fence)

            # PodGroup status write-back at session close (the jobUpdater's
            # parallel UpdatePodGroup flush, framework/job_updater.go:66-108)
            # — a deposed leader's late flush must not touch phases either
            if not _fenced_off():
                self.cluster.update_podgroup_phases(ssn.phase_updates)

            for intent in ssn.evictions:
                ok = (self.cluster.evict(intent, fence=fence)
                      if fence is not None else self.cluster.evict(intent))
                if not ok:
                    if _fenced_off():
                        continue
                    METRICS.inc("resync_tasks")
                    self.resync.add(intent, "evict", wall)
            for intent in ssn.binds:
                ok = (self.cluster.bind(intent, fence=fence)
                      if fence is not None else self.cluster.bind(intent))
                if not ok:
                    if _fenced_off():
                        continue
                    METRICS.inc("resync_tasks")
                    # hold the Binding state so later cycles don't
                    # re-decide while the rate-limited retry works
                    # (cache.go:549-560)
                    self.cluster.hold_binding(intent)
                    self.resync.add(intent, "bind", wall)
        METRICS.observe_cycle(host_s)
        METRICS.inc("schedule_attempts")
        # reference vocabulary: schedule_attempts_total{result=...}
        # (metrics.go:92-100 scheduleAttempts) — "error" when a bind
        # degraded to a recorded error, else by whether anything placed
        result = ("error" if ssn.bind_errors
                  else "scheduled" if (ssn.binds or ssn.pipelined)
                  else "unschedulable")
        METRICS.inc("schedule_attempts_total", labels={"result": result})
        # jit trace-vs-call gauges (telemetry/tracecount): a moving
        # volcano_jit_traces{entry=...} on the steady-state path is a
        # retrace incident
        from ..telemetry import publish_gauges
        publish_gauges(METRICS)
        spans.publish_gauges(METRICS)
        self.cycles += 1
        stats = ssn.stats
        faults, self._cycle_faults = self._cycle_faults, []
        # mesh width transitions observed at the point of truth (what this
        # cycle actually served on), for the mesh_width gauge and the
        # post-mortem JSONL narrative correlating rung changes with
        # re-meshes
        if "mesh_devices" in stats:
            width = int(stats["mesh_devices"])
            if width != self._last_mesh_devices:
                METRICS.set_gauge("mesh_width", None, width)
                if self._last_mesh_devices is not None:
                    spans.log_event("mesh", action="width_change",
                                    cycle=self.cycles,
                                    width_from=self._last_mesh_devices,
                                    width_to=width)
                self._last_mesh_devices = width
        self.flight.record(
            now=wall, cycle=self.cycles, cycle_ms=round(host_s * 1000, 3),
            binds=len(ssn.binds), evictions=len(ssn.evictions),
            pipelined=len(ssn.pipelined), bind_errors=len(ssn.bind_errors),
            resync_pending=len(self.resync), result=result,
            # fault-tolerance observability: recovered faults this cycle,
            # the current ladder rung, and the resync dead-letter depth
            faults=faults or None,
            degradation=self.degradation_level,
            resync_dead_letter=len(self.resync.dead),
            # delta-upload observability: what this cycle actually shipped
            # vs what a full upload would have, and which path it took
            cycle_kind=("delta" if stats.get("delta_cycle") else
                        "full" if "delta_cycle" in stats else None),
            upload_bytes=stats.get("upload_bytes"),
            upload_bytes_full=stats.get("upload_bytes_full"),
            # sharded-cycle observability (conf sharding: true): mesh
            # width and the live resharding probe — a nonzero copy count
            # means a pjit input lost its declared sharding, i.e. the
            # zero-copy steady-loop contract broke this cycle
            mesh_devices=(int(stats["mesh_devices"])
                          if "mesh_devices" in stats else None),
            resharding_copies=(int(stats["resharding_copies"])
                               if "resharding_copies" in stats else None),
            dirty_jobs=self._last_dirty[0], dirty_nodes=self._last_dirty[1],
            stats={k: round(float(v), 3) for k, v in stats.items()},
            telemetry=ssn.last_telemetry or None,
            # per-cycle span summary (plain {phase: ms} dict — pickle- and
            # JSON-safe for vcctl --state)
            spans=spans.drain_cycle_summary())
        return ssn

    def drain(self, now: Optional[float] = None):
        """Retire EVERY in-flight pipelined cycle, oldest first: readback
        (or replay), apply, flush. Returns the newest completed cycle's
        record, or None when nothing was in flight. Safe to call twice —
        the second call is a no-op returning None."""
        wall = now if now is not None else time.time()
        out = None
        while self._ring:
            out = self._drain_pending(wall) or out
        return out

    # ----------------------------------------- crash-consistent restarts
    def checkpoint(self, path: str, now: Optional[float] = None) -> dict:
        """Serialize the scheduler's host-side truth to ``path``
        (atomic tmp+fsync+rename; see runtime/checkpoint.py).

        The in-flight pipelined ring is DRAINED first, oldest to newest —
        every in-flight cycle's decisions apply to the cluster before the
        snapshot is cut, so a restore can never replay a half-applied
        bind (the depth-1 contract, generalized: the k-slot drain is
        decision-neutral because invalidated slots replay synchronously).
        Cluster state itself is not checkpointed: the cluster source is
        external authoritative truth that survives the process, exactly
        like the reference's API server."""
        from . import checkpoint as ckpt
        wall = now if now is not None else time.time()
        self.drain(now=wall)
        state, mirrors = self.checkpoint_state()
        return ckpt.write_checkpoint(path, "scheduler", state,
                                     mirrors=mirrors)

    def checkpoint_state(self) -> tuple:
        """The (state, mirror records) pair a checkpoint or replication
        envelope serializes — the single authority for WHAT host-side
        truth leaves the process. Does NOT drain the pipeline; callers
        that need the depth-1 drain-first rule (checkpoint files) drain
        before calling."""
        from . import checkpoint as ckpt
        mirrors = []
        if self._session is not None:
            # resident mirrors of the persistent session's flat kernels
            # (kernels are shared in the module cache; residency is per
            # session): lets a warm restore skip the full re-upload — the
            # re-fuse from truth still happens, as deltas against these
            # mirrors
            from ..framework.session import _DELTA_CACHE
            mirrors = ckpt.mirror_records(_DELTA_CACHE,
                                          self._session._resident)
        state = dict(
            cycles=self.cycles,
            full_packs=self.full_packs,
            incremental_cycles=self.incremental_cycles,
            degradation_level=self.degradation_level,
            degrade_until=self._degrade_until,
            conf_fingerprint=ckpt.conf_fingerprint(self.conf),
            resync_entries=[dict(e) for e in self.resync.entries],
            resync_dead=[dict(e) for e in self.resync.dead],
            metrics=ckpt.metrics_snapshot(),
        )
        if getattr(self.conf, "sharding", False):
            # device quarantines and the shrink cap survive a restart: a
            # restored process must not re-serve on hardware the crashed
            # one already classified as persistently lost
            from ..parallel.health import HEALTH
            state["device_health"] = HEALTH.snapshot()
        return state, mirrors

    def restore(self, path: str, now: Optional[float] = None) -> str:
        """Reload a checkpoint into this (fresh) scheduler and resume
        decision-identically. Returns the restore-ladder outcome:
        ``restored`` | ``cold`` (no file) | ``fallback`` (damaged or
        mismatched file — this scheduler simply stays a fresh-fuse cold
        start, which is itself decision-correct because the cluster
        source is the authority; the checkpoint only restores warmth,
        counters, and retry state)."""
        from . import checkpoint as ckpt
        wall = now if now is not None else time.time()
        t0 = time.time()
        with spans.span("cycle.restore", cat="recovery"):
            env, reason = ckpt.load_checkpoint(path, "scheduler")
            if env is None:
                outcome = "cold" if reason == "missing" else "fallback"
                ckpt.record_restore(outcome, reason, "scheduler",
                                    (time.time() - t0) * 1000)
                return outcome
            state = env["state"]
            if state.get("conf_fingerprint") != \
                    ckpt.conf_fingerprint(self.conf):
                ckpt.record_restore("fallback", "conf_mismatch",
                                    "scheduler", (time.time() - t0) * 1000)
                return "fallback"
            self.cycles = int(state["cycles"])
            self.full_packs = int(state["full_packs"])
            self.incremental_cycles = int(state["incremental_cycles"])
            self.degradation_level = int(state["degradation_level"])
            self._degrade_until = int(state["degrade_until"])
            self.resync.entries = [dict(e)
                                   for e in state["resync_entries"]]
            self.resync.dead = [dict(e) for e in state["resync_dead"]]
            ckpt.merge_metrics(state.get("metrics"))
            if state.get("device_health"):
                from ..parallel.health import HEALTH
                HEALTH.restore(state["device_health"])
                self._health_gen_seen = HEALTH.generation
            # the next _open_session full-packs from the cluster's live
            # view — re-fuse from truth is the recovery primitive; the
            # checkpointed mirrors make that re-fuse warm (delta, not
            # full upload) once the session's kernels come back up
            self._session = None
            self._ring.clear()
            self._restored_mirrors = ckpt.verify_mirrors(
                env.get("mirrors"))
            # intents stranded by the crash get a second life
            self.resync.redrive(wall)
        ckpt.record_restore("restored", "ok", "scheduler",
                            (time.time() - t0) * 1000)
        return "restored"

    def wait_pending(self) -> bool:
        """Block until every in-flight cycle's DEVICE work has finished,
        without draining (no readback, no apply — state unchanged). Joins
        the pack thread first: device work it hadn't submitted yet cannot
        be waited on otherwise. In production the 1 s schedule period
        provides this wait for free; bench and shutdown paths call it
        explicitly. Returns True when something was in flight."""
        if not self._ring:
            return False
        import jax
        self._resolve_ring()
        with spans.span("cycle.wait_device", cat="wait"):
            for e in self._ring:
                if e.pending.packed is not None:
                    jax.block_until_ready(e.pending.packed)
        return True

    def run(self, cycles: int = 1, sleep: bool = False) -> List[Session]:
        out = []
        for _ in range(cycles):
            out.append(self.run_once())
            if sleep:
                time.sleep(self.schedule_period)
        return out


class CompletedCycle:
    """Detached readout of a pipelined cycle, snapshotted at finish time —
    the live Session is reopened (intents reset) before the next run_once
    returns, so pipelined callers get this stable copy instead."""

    __slots__ = ("binds", "evictions", "pipelined", "bind_errors",
                 "phase_updates", "stats", "last_telemetry")

    def __init__(self, ssn: Session):
        self.binds = list(ssn.binds)
        self.evictions = list(ssn.evictions)
        self.pipelined = dict(ssn.pipelined)
        self.bind_errors = list(ssn.bind_errors)
        self.phase_updates = dict(ssn.phase_updates)
        self.stats = dict(ssn.stats)
        self.last_telemetry = dict(ssn.last_telemetry)
