"""The scheduler loop: snapshot -> session -> actions -> bind.

Reference: pkg/scheduler/scheduler.go:54-171 (Scheduler.Run / runOnce with
the 1s wait.Until cycle, conf hot-reload) and cmd/scheduler/app/server.go.
The loop is synchronous here; bind/evict intents flush to the cluster source
at the end of each cycle (the reference fires them as goroutines mid-cycle —
same external effect, recorded in order).
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional

from ..framework.conf import SchedulerConfiguration, parse_conf
from ..framework.session import Session
from ..metrics import METRICS
from .fake_cluster import FakeCluster


class Scheduler:
    def __init__(self, cluster: FakeCluster,
                 conf: Optional[SchedulerConfiguration] = None,
                 conf_path: Optional[str] = None,
                 schedule_period: float = 1.0):
        self.cluster = cluster
        self.conf_path = conf_path
        self._conf_mtime = 0.0
        self.conf = conf or self._load_conf() or parse_conf()
        self.schedule_period = schedule_period
        self._plugin_state: Dict[str, object] = {}
        self.cycles = 0

    def _load_conf(self) -> Optional[SchedulerConfiguration]:
        """Conf hot-reload (fsnotify watcher, scheduler.go:146-171 — here a
        cheap mtime poll at cycle start)."""
        if not self.conf_path or not os.path.exists(self.conf_path):
            return None
        mtime = os.path.getmtime(self.conf_path)
        if mtime == self._conf_mtime:
            return None
        self._conf_mtime = mtime
        with open(self.conf_path) as f:
            return parse_conf(f.read())

    def _persistent_plugins(self) -> Dict[str, object]:
        """Plugins with cross-cycle state (the reservation singleton)."""
        from ..plugins.reservation import ReservationPlugin
        overrides = {}
        if self.conf.plugin_option("reservation") is not None:
            if "reservation" not in self._plugin_state:
                self._plugin_state["reservation"] = ReservationPlugin(
                    self.conf.plugin_option("reservation"))
            overrides["reservation"] = self._plugin_state["reservation"]
        return overrides

    def run_once(self, now: Optional[float] = None) -> Session:
        """One scheduling cycle (runOnce, scheduler.go:91-120)."""
        reloaded = self._load_conf()
        if reloaded is not None:
            self.conf = reloaded
        t0 = time.time()
        ssn = Session(self.cluster.snapshot(), self.conf, now=now,
                      plugin_overrides=self._persistent_plugins())
        from ..actions import get_action
        for name in self.conf.actions:
            ta = time.time()
            get_action(name).execute(ssn)
            METRICS.observe_action(name, time.time() - ta)
        ssn.close()

        # PodGroup status write-back at session close (the jobUpdater's
        # parallel UpdatePodGroup flush, framework/job_updater.go:66-108)
        self.cluster.update_podgroup_phases(ssn.phase_updates)

        for intent in ssn.evictions:
            self.cluster.evict(intent)
        for intent in ssn.binds:
            ok = self.cluster.bind(intent)
            if not ok:
                METRICS.inc("resync_tasks")
        METRICS.observe_cycle(time.time() - t0)
        METRICS.inc("schedule_attempts")
        self.cycles += 1
        return ssn

    def run(self, cycles: int = 1, sleep: bool = False) -> List[Session]:
        out = []
        for _ in range(cycles):
            out.append(self.run_once())
            if sleep:
                time.sleep(self.schedule_period)
        return out
