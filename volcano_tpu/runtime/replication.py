"""Warm-standby replication: checkpoint streaming + lease-fenced promotion.

PR 10 made a single process crash-consistent; the runtime was still a
single point of failure — leader death stops serving until a restart
finishes. This module closes that gap with an HA replica pair, the
ROADMAP's "checkpoint streaming to a warm standby with ``LeaderElector``
handoff so failover costs at most one cycle":

- :class:`ReplicationSender` — the active leader's half. After each
  cycle it cuts the same (state, mirror-records) envelope a checkpoint
  file would hold (``Scheduler.checkpoint_state`` — single authority)
  and streams it as an INCREMENTAL envelope: mirror records become
  ``since``-sequence deltas — per-buffer (index, value) edits against
  the last envelope the standby acknowledged — each still stamped with
  the PR 5 integrity-digest words of the FULL resulting mirror, so the
  receiving side re-verifies end-state integrity, not just the edits.
- :class:`WarmStandby` — the passive half: continuously applies
  envelopes (digest-verified, ``since``/``seq``-disciplined — a gap or
  tamper is reported back and repaired with a full resync, never
  silently applied) and keeps a promotion-ready copy of the leader's
  host truth.
- :meth:`WarmStandby.promote` — on leader loss the standby wins the
  lease (its elector's tick past ``lease_duration``; the new lease
  generation IS the fencing token) and builds a fresh Scheduler whose
  first ``_open_session`` full pack adopts the replicated mirrors via
  ``adopt_mirror`` — the first post-failover cycle ships a delta, not a
  cold upload (``cycles_to_steady == 0``).
- :class:`ReplicationLink` — the in-memory transport, with the
  ``replication.send`` chaos seam: a ``replication_partition`` fault
  drops envelopes on the floor. Loss is tolerated by construction —
  deltas are built against the last ACKED envelope, so the next
  envelope still applies cleanly and a kill during the partition
  promotes from a slightly stale mirror, which the first delta cycle's
  value diff self-heals against external truth.

Decision correctness never depends on replication: the cluster source is
external authoritative truth (the PR 10 posture), so a cold or stale
standby re-fuses from truth and decides identically. Replication buys
back WARMTH (first cycle on the delta path) and continuity (counters,
resync retries, dead letters). Everything here is host-side — zero
in-graph ops — so decisions are bit-identical with replication on or
off (graphcheck stays CLEAN; chaos/failover.py proves the sha).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..metrics import METRICS
from ..telemetry import spans
from . import checkpoint as ckpt

#: envelope kind tag — replication envelopes reuse the checkpoint
#: envelope shape (kind/state/mirrors/digest_words) with stream fields
REPL_KIND = "scheduler-repl"


# ----------------------------------------------------------- delta records
def _as_flat_u32(buf: np.ndarray) -> np.ndarray:
    """Bit-level flat view of a 4-byte buffer (f32/i32): delta compare and
    apply are done on raw bits, so NaN payloads round-trip exactly and a
    NaN==NaN position is not eternally re-sent."""
    return buf.reshape(-1).view(np.uint32)


def _copy_mirror(mirror) -> Tuple[np.ndarray, ...]:
    return tuple(np.array(b, copy=True) for b in mirror)


def _compatible(prev, cur) -> bool:
    return (prev is not None and len(prev) == len(cur)
            and all(p.shape == c.shape and p.dtype == c.dtype
                    for p, c in zip(prev, cur)))


def delta_record(key, prev, cur, digest: List[int]) -> Optional[dict]:
    """One mirror record for the stream: a full copy when the standby has
    no compatible base, else per-buffer (index, value) edits. Returns
    None when nothing changed (the standby's copy is already current).
    ``digest`` is always the host-digest of the FULL current mirror — the
    apply side verifies the reconstructed end state, not the edit list."""
    if not _compatible(prev, cur):
        return {"key": key, "mirror": _copy_mirror(cur), "delta": None,
                "digest": digest}
    edits = []
    changed = 0
    for p, c in zip(prev, cur):
        if p.dtype == np.bool_:
            pf, cf = p.reshape(-1), c.reshape(-1)
        else:
            pf, cf = _as_flat_u32(p), _as_flat_u32(c)
        idx = np.flatnonzero(pf != cf).astype(np.int32)
        edits.append((idx, np.array(cf[idx], copy=True)))
        changed += int(idx.size)
    if not changed:
        return None
    return {"key": key, "mirror": None, "delta": tuple(edits),
            "digest": digest}


def apply_delta(prev, edits) -> Tuple[np.ndarray, ...]:
    """Rebuild the current mirror from the standby's base copy + edits."""
    mirror = _copy_mirror(prev)
    for buf, (idx, vals) in zip(mirror, edits):
        if idx.size == 0:
            continue
        if buf.dtype == np.bool_:
            buf.reshape(-1)[idx] = vals
        else:
            _as_flat_u32(buf)[idx] = vals
    return mirror


# ------------------------------------------------------------ leader half
class ReplicationSender:
    """The leader's streaming half: cut an envelope after each cycle and
    push it down the link; track what the standby ACKED so the next
    envelope's deltas have the right base (a lost envelope simply leaves
    the base where it was — the stream self-repairs without a gap)."""

    def __init__(self, scheduler, link: "ReplicationLink"):
        self.scheduler = scheduler
        self.link = link
        self.seq = 0
        self._acked_seq = 0
        #: per shape-key copy of the mirror as of the last ACKED envelope
        self._acked: Dict[tuple, tuple] = {}

    def envelope(self) -> dict:
        """The next incremental envelope: PR 10's checkpoint shape plus
        the stream fields (``seq``, ``since``) and delta-form mirrors."""
        self.seq += 1
        state, records = self.scheduler.checkpoint_state()
        mirrors = []
        for r in records:
            key = ckpt._freeze_key(r["key"])
            rec = delta_record(key, self._acked.get(key), r["mirror"],
                               r["digest"])
            if rec is not None:
                mirrors.append(rec)
        return {
            "kind": REPL_KIND,
            "seq": self.seq,
            "since": self._acked_seq,
            "state": state,
            "mirrors": mirrors,
            "digest_words": ckpt.fold_digest(mirrors),
        }

    def _ack(self, env: dict) -> None:
        self._acked_seq = env["seq"]
        for rec in env["mirrors"]:
            key = ckpt._freeze_key(rec["key"])
            if rec["mirror"] is not None:
                self._acked[key] = _copy_mirror(rec["mirror"])
            else:
                self._acked[key] = apply_delta(self._acked[key],
                                               rec["delta"])

    def stream(self) -> str:
        """Send one envelope; returns the delivery result
        (``applied | lost | gap | invalid``). A ``gap`` (standby lost
        its position) or ``invalid`` (a record failed its digest check)
        is repaired immediately with one full resync envelope; ``lost``
        (partition) needs no repair — the un-advanced ack base keeps the
        next delta applicable."""
        env = self.envelope()
        result = self.link.deliver(env)
        METRICS.inc("replication_envelopes_total",
                    labels={"result": result})
        if result == "applied":
            self._ack(env)
            return result
        if result in ("gap", "invalid"):
            # full resync: forget the acked base so every record ships
            # whole, and mark since=0 so the standby accepts it at any
            # position
            self._acked, self._acked_seq = {}, 0
            full = self.envelope()
            full["since"] = 0
            retry = self.link.deliver(full)
            METRICS.inc("replication_envelopes_total",
                        labels={"result": "resync_" + retry})
            if retry == "applied":
                self._ack(full)
            return retry
        return result


# ----------------------------------------------------------- standby half
class WarmStandby:
    """The passive replica: applies the leader's envelope stream and holds
    a promotion-ready copy of its host truth."""

    def __init__(self, conf=None):
        self.conf = conf
        self.applied_seq = 0
        self.state: Optional[dict] = None
        self.mirrors: Dict[tuple, tuple] = {}
        self.envelopes_applied = 0
        self.last_outcome: Optional[str] = None   # set by promote()

    # -------------------------------------------------------------- apply
    def apply(self, env: dict) -> str:
        """Apply one envelope. Returns ``applied``, or ``gap`` when the
        envelope's ``since`` does not match our position (a dropped
        full-resync or a restarted standby), or ``invalid`` when a record
        fails its integrity digest — tampered or desynced payloads are
        counted and NEVER adopted; the sender answers both with a full
        resync."""
        if env.get("kind") != REPL_KIND:
            return "invalid"
        since = int(env.get("since", 0))
        if since not in (0, self.applied_seq):
            return "gap"
        if since == 0:
            # full resync replaces our world (mirror keys the leader no
            # longer tracks must not linger)
            staged: Dict[tuple, tuple] = {}
        else:
            staged = dict(self.mirrors)
        from ..ops.fused_io import host_digest
        for rec in env.get("mirrors", []):
            key = ckpt._freeze_key(rec["key"])
            if rec.get("mirror") is not None:
                mirror = _copy_mirror(rec["mirror"])
            else:
                base = staged.get(key)
                if base is None or len(base) != len(rec["delta"]):
                    # delta against a base we don't hold — our position
                    # desynced from the sender's ack view
                    return "gap"
                mirror = apply_delta(base, rec["delta"])
            if [int(x) for x in host_digest(mirror)] != list(rec["digest"]):
                METRICS.inc("replication_mirror_invalid_total")
                spans.log_event("replication_mirror_invalid")
                return "invalid"
            staged[key] = mirror
        # all records verified: commit atomically (a failed record above
        # must not leave a half-applied envelope behind)
        self.mirrors = staged
        self.state = env["state"]
        self.applied_seq = int(env["seq"])
        self.envelopes_applied += 1
        return "applied"

    @property
    def lag(self) -> Optional[int]:
        """Envelopes the standby is behind the last seq it saw applied —
        0 in the steady state (published as ``replication_lag_seq``)."""
        return self.applied_seq

    # ------------------------------------------------------------ promote
    def promote(self, cluster, conf=None, pipeline: bool = True,
                now: Optional[float] = None, elector=None):
        """Leader loss: build the new active Scheduler from the replica
        state. Promotion ladder (``failover_promotions_total``):

        - ``warm``     — replicated state + verified mirrors adopted; the
                         first cycle ships a delta (cycles_to_steady=0),
        - ``cold``     — nothing replicated yet: fresh cold start,
        - ``fallback`` — replicated state was cut under a different conf
                         fingerprint: refuse it, fresh cold start.

        When ``elector`` is given it is ticked once first — the natural
        call site is AFTER the dead leader's lease expired, so this tick
        wins the lease and bumps the generation (the fencing token the
        promoted scheduler stamps on every write). Returns the new
        Scheduler."""
        from .scheduler import Scheduler
        conf = conf if conf is not None else self.conf
        t0 = time.time()
        wall = now if now is not None else t0
        if elector is not None:
            elector.tick()
            # announce the new fencing token to the write target BEFORE
            # the first cycle: the deposed leader's late writes are
            # rejected from this instant, not from our first bind
            if hasattr(cluster, "advance_fence"):
                cluster.advance_fence(elector.generation)
        sched = Scheduler(cluster, conf=conf, pipeline=pipeline,
                          elector=elector)
        outcome = "warm"
        st = self.state
        if st is None:
            outcome = "cold"
        elif st.get("conf_fingerprint") != ckpt.conf_fingerprint(conf):
            outcome = "fallback"
        if outcome == "warm":
            sched.cycles = int(st["cycles"])
            sched.full_packs = int(st["full_packs"])
            sched.incremental_cycles = int(st["incremental_cycles"])
            sched.degradation_level = int(st["degradation_level"])
            sched._degrade_until = int(st["degrade_until"])
            sched.resync.entries = [dict(e) for e in st["resync_entries"]]
            sched.resync.dead = [dict(e) for e in st["resync_dead"]]
            ckpt.merge_metrics(st.get("metrics"))
            if st.get("device_health"):
                # the dead leader's quarantine picture: serve on the
                # same shrunk mesh instead of re-striking the dead
                # devices from scratch
                from ..parallel.health import HEALTH
                HEALTH.restore(st["device_health"])
                sched._health_gen_seen = HEALTH.generation
            sched._restored_mirrors = {k: m for k, m in
                                       self.mirrors.items()}
            # intents stranded by the dead leader get a second life, the
            # same redrive rule a file restore applies
            sched.resync.redrive(wall)
        promote_ms = (time.time() - t0) * 1000
        #: which ladder rung the promotion landed on, for callers that
        #: surface it (the failover-storm scenario event)
        self.last_outcome = outcome
        METRICS.inc("failover_promotions_total",
                    labels={"outcome": outcome})
        METRICS.set_gauge("replication_lag_seq", None, 0)
        spans.log_event("promotion", outcome=outcome,
                        seq=self.applied_seq,
                        mirrors=len(self.mirrors),
                        leader=bool(elector.is_leader) if elector else None,
                        generation=(elector.generation if elector
                                    else None),
                        promote_ms=round(promote_ms, 3))
        return sched


# --------------------------------------------------------------- transport
class ReplicationLink:
    """In-memory leader->standby transport. A real deployment would put a
    socket here; the protocol contract (deliver -> applied/gap/invalid,
    loss possible) is what the sender is written against. The
    ``replication.send`` seam lets chaos drop envelopes
    (``replication_partition``)."""

    def __init__(self, standby: WarmStandby):
        self.standby = standby
        self.delivered = 0
        self.lost = 0

    def deliver(self, env: dict) -> str:
        from ..chaos.inject import seam
        if seam("replication.send", envelope=env, link=self) == "drop":
            self.lost += 1
            return "lost"
        self.delivered += 1
        result = self.standby.apply(env)
        METRICS.set_gauge("replication_lag_seq", None,
                          max(0, int(env["seq"])
                              - self.standby.applied_seq))
        return result


def replica_pair(scheduler, conf=None) -> Tuple[ReplicationSender,
                                                WarmStandby]:
    """Wire a leader scheduler to a fresh warm standby; returns
    (sender, standby). The caller streams after each drained cycle:
    ``sender.stream()``."""
    standby = WarmStandby(conf if conf is not None else scheduler.conf)
    return ReplicationSender(scheduler, ReplicationLink(standby)), standby
