"""In-memory API server: the object store + watch bus every component talks to.

Reference architecture: the Kubernetes API server is Volcano's sole
communication backbone (SURVEY.md section 1) — controllers and scheduler
coordinate exclusively through watches and status updates on shared objects.
This class provides the same seam: typed object stores, admission hooks on
writes (the webhook interception point), and synchronous watch callbacks
(the informer event-handler seam, cache.go:337-429).
"""

from __future__ import annotations

from collections import defaultdict
from typing import Callable, Dict, List, Optional

from ..api.batch import Command, Job
from ..api.core import Pod, PodGroup
from ..api.node_info import NodeInfo
from ..api.queue_info import QueueInfo

KINDS = ("jobs", "pods", "podgroups", "queues", "nodes", "commands",
         "pvcs", "secrets", "services", "configmaps", "leases",
         "numatopologies", "networkpolicies")


class APIServer:
    def __init__(self):
        self.stores: Dict[str, Dict[str, object]] = {k: {} for k in KINDS}
        self.watchers: Dict[str, List[Callable]] = defaultdict(list)
        self._rv = 0          # resourceVersion counter (picklable)
        self.admission_enabled = True

    # ------------------------------------------------------------- helpers
    @staticmethod
    def _key(obj) -> str:
        ns = getattr(obj, "namespace", "")
        name = getattr(obj, "name", "")
        return f"{ns}/{name}" if ns else name

    def watch(self, kind: str, callback: Callable[[str, object, Optional[object]], None]) -> None:
        """Register callback(event, obj, old) for 'added'/'updated'/'deleted'."""
        self.watchers[kind].append(callback)

    def _notify(self, kind: str, event: str, obj, old=None) -> None:
        for cb in self.watchers[kind]:
            cb(event, obj, old)

    def _admit(self, kind: str, obj, old=None) -> None:
        if not self.admission_enabled:
            return
        from ..webhooks import (mutate_job, mutate_podgroup, mutate_queue,
                                validate_job_create, validate_job_update,
                                validate_queue)
        if kind == "jobs":
            if old is None:
                mutate_job(obj)
                validate_job_create(obj, queues=self.stores["queues"])
            else:
                validate_job_update(old, obj)
        elif kind == "queues":
            mutate_queue(obj)
            validate_queue(obj)
        elif kind == "podgroups":
            mutate_podgroup(obj) if hasattr(obj, "queue") else None

    # ---------------------------------------------------------------- CRUD
    def create(self, kind: str, obj) -> object:
        key = self._key(obj)
        if key in self.stores[kind]:
            raise KeyError(f"{kind}/{key} already exists")
        self._admit(kind, obj)
        self.stores[kind][key] = obj
        self._notify(kind, "added", obj)
        return obj

    def update(self, kind: str, obj) -> object:
        key = self._key(obj)
        old = self.stores[kind].get(key)
        if old is None:
            raise KeyError(f"{kind}/{key} not found")
        if old is not obj:
            self._admit(kind, obj, old)
        self.stores[kind][key] = obj
        self._notify(kind, "updated", obj, old)
        return obj

    def delete(self, kind: str, key: str) -> Optional[object]:
        obj = self.stores[kind].pop(key, None)
        if obj is not None:
            from ..webhooks import validate_queue_delete
            if kind == "queues" and self.admission_enabled:
                try:
                    validate_queue_delete(obj)
                except Exception:
                    self.stores[kind][key] = obj
                    raise
            self._notify(kind, "deleted", obj)
        return obj

    def get(self, kind: str, key: str):
        return self.stores[kind].get(key)

    def list(self, kind: str, selector: Optional[Callable] = None) -> List:
        objs = list(self.stores[kind].values())
        if selector:
            objs = [o for o in objs if selector(o)]
        return objs

    # --------------------------------------------------------- conveniences
    def pods_of_job(self, job_key: str) -> List[Pod]:
        ns, name = job_key.split("/", 1)
        from ..api.core import JOB_NAME_LABEL
        return self.list("pods", lambda p: p.namespace == ns
                         and p.labels.get(JOB_NAME_LABEL) == name)

    def podgroup_of_job(self, job_key: str) -> Optional[PodGroup]:
        for pg in self.stores["podgroups"].values():
            if pg.owner_job == job_key:
                return pg
        return None
