"""Bare-pod admission: gate scheduling against closed queues.

Reference: pkg/webhooks/admission/pods/admit_pod.go:42-214 — a pod using the
volcano scheduler whose PodGroup's queue is not open is rejected.
"""

from __future__ import annotations

from typing import Dict, Optional

from ..api import DEFAULT_SCHEDULER_NAME, QueueState
from .jobs import AdmissionError


def validate_pod(pod, queues: Optional[Dict[str, object]] = None,
                 podgroup_queue: Optional[str] = None) -> None:
    if getattr(pod, "scheduler_name", "") != DEFAULT_SCHEDULER_NAME:
        return
    if queues is None or podgroup_queue is None:
        return
    queue = queues.get(podgroup_queue)
    if queue is not None and queue.state != QueueState.OPEN:
        raise AdmissionError(
            f"pod rejected: queue {podgroup_queue!r} is "
            f"{queue.state.value}, not Open")
