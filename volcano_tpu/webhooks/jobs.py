"""Job admission: validation and defaulting.

Reference: pkg/webhooks/admission/jobs/validate/admit_job.go:46-410 +
util.go:1-187 (create/update validation matrices) and
pkg/webhooks/admission/jobs/mutate/mutate_job.go:49-200 (defaults). The
tests mirror admit_job_test.go:1-1351 case families.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional

from ..api.batch import Job, LifecyclePolicy
from ..api.types import BusAction, BusEvent, DEFAULT_QUEUE, DEFAULT_SCHEDULER_NAME, QueueState

_DNS1123 = re.compile(r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?$")

#: Events a policy may react to (policyEventMap, validate/util.go:32-41:
#: OutOfSync and CommandIssued are internal-only and rejected).
_VALID_POLICY_EVENTS = {
    BusEvent.ANY, BusEvent.POD_FAILED, BusEvent.POD_EVICTED,
    BusEvent.JOB_UNKNOWN, BusEvent.TASK_COMPLETED,
}

#: Actions a policy may request (policyActionMap, validate/util.go:43-52:
#: SyncJob and Enqueue are internal-only and rejected).
_VALID_POLICY_ACTIONS = {
    BusAction.ABORT_JOB, BusAction.RESTART_JOB, BusAction.RESTART_TASK,
    BusAction.TERMINATE_JOB, BusAction.COMPLETE_JOB, BusAction.RESUME_JOB,
}


class AdmissionError(ValueError):
    pass


def _validate_policies(policies: List[LifecyclePolicy], where: str) -> List[str]:
    """Reference: validatePolicies, validate/util.go:54-116."""
    errs = []
    seen_events = set()
    seen_exit_codes = set()
    for p in policies:
        events = set(p.events)
        if p.event is not None:
            events.add(p.event)
        if events and p.exit_code is not None:
            errs.append(f"{where}: must not specify event and exitCode simultaneously")
        if not events and p.exit_code is None:
            errs.append(f"{where}: either event or exitCode must be specified")
        if events:
            for e in events:
                if e not in _VALID_POLICY_EVENTS:
                    errs.append(f"{where}: invalid policy event {e.value}")
                elif p.action not in _VALID_POLICY_ACTIONS:
                    errs.append(f"{where}: invalid policy action {p.action}")
                elif e in seen_events:
                    errs.append(f"{where}: duplicate event {e.value} across "
                                "different policy")
                else:
                    seen_events.add(e)
        elif p.exit_code is not None:
            if p.exit_code == 0:
                errs.append(f"{where}: 0 is not a valid error code")
            elif p.exit_code in seen_exit_codes:
                errs.append(f"{where}: duplicate exitCode {p.exit_code}")
            else:
                seen_exit_codes.add(p.exit_code)
        if p.timeout_seconds is not None and p.timeout_seconds <= 0:
            errs.append(f"{where}: policy timeout must be positive")
    # "if there's * here, no other policy should be here" (util.go:111-113)
    if BusEvent.ANY in seen_events and len(seen_events) > 1:
        errs.append(f"{where}: if there's * here, no other policy should be here")
    return errs


def validate_job_create(job: Job,
                        queues: Optional[Dict[str, object]] = None) -> None:
    """Raise AdmissionError on an invalid Job (admit_job.go:46-220)."""
    errs: List[str] = []
    if job.min_available < 0:
        errs.append("job 'minAvailable' must be >= 0")
    if job.max_retry < 0:
        errs.append("'maxRetry' cannot be less than zero")
    if (job.ttl_seconds_after_finished is not None
            and job.ttl_seconds_after_finished < 0):
        errs.append("'ttlSecondsAfterFinished' cannot be less than zero")
    if not job.tasks:
        errs.append("no task specified in job spec")

    total_replicas = 0
    names = set()
    for task in job.tasks:
        if task.replicas < 0:
            errs.append(f"'replicas' < 0 in task: {task.name}")
        if task.min_available is not None:
            if task.min_available < 0:
                errs.append(f"'minAvailable' < 0 in task: {task.name}")
            elif task.min_available > task.replicas:
                errs.append(
                    f"'minAvailable' is greater than 'replicas' in task: {task.name}")
        if task.name in names:
            errs.append(f"duplicated task name {task.name}")
        names.add(task.name)
        if task.name and not _DNS1123.match(task.name):
            errs.append(f"task name {task.name} is not a valid DNS-1123 label")
        total_replicas += max(task.replicas, 0)
        errs.extend(_validate_policies(task.policies, f"task {task.name}"))

    if total_replicas < job.min_available:
        errs.append("job 'minAvailable' should not be greater than total "
                    "replicas in tasks")
    if job.min_success is not None and job.min_success < 1:
        errs.append("job 'minSuccess' must be >= 1")
    errs.extend(_validate_policies(job.policies, "job"))

    seen_mounts = set()
    for v in job.volumes:
        if v.mount_path in seen_mounts:
            errs.append(f"duplicated mountPath: {v.mount_path}")
        seen_mounts.add(v.mount_path)
        if not v.volume_claim_name and not v.storage:
            errs.append(f"volume {v.mount_path}: either volumeClaimName or "
                        "storage must be specified")

    if queues is not None:
        queue = queues.get(job.queue or DEFAULT_QUEUE)
        if queue is None:
            errs.append(f"job queue {job.queue!r} does not exist")
        elif getattr(queue, "state", QueueState.OPEN) != QueueState.OPEN:
            errs.append(f"can only submit job to queue with state Open; "
                        f"queue {job.queue!r} is {queue.state.value}")

    if errs:
        raise AdmissionError("; ".join(errs))


def validate_job_update(old: Job, new: Job) -> None:
    """Only minAvailable and task replicas may change
    (admit_job.go:300-360)."""
    errs: List[str] = []
    if new.min_available < 0:
        errs.append("job 'minAvailable' must be >= 0")
    total = 0
    for task in new.tasks:
        if (task.min_available is not None
                and task.min_available > task.replicas):
            errs.append(f"'minAvailable' must be <= 'replicas' in task: {task.name}")
        total += task.replicas
    if new.min_available > total:
        errs.append("job 'minAvailable' must not be greater than total replicas")

    if len(old.tasks) != len(new.tasks):
        errs.append("job updates may not add or remove tasks")
    else:
        for o, n in zip(old.tasks, new.tasks):
            if o.name != n.name or o.template != n.template:
                errs.append("job updates may not change fields other than "
                            "'minAvailable' and 'tasks[*].replicas'")
                break
    for attr in ("queue", "scheduler_name", "max_retry",
                 "priority_class_name"):
        if getattr(old, attr) != getattr(new, attr):
            errs.append(f"job updates may not change spec.{attr}")
    if errs:
        raise AdmissionError("; ".join(errs))


def mutate_job(job: Job) -> Job:
    """Apply defaults in place and return the job (mutate_job.go:49-200)."""
    if not job.queue:
        job.queue = DEFAULT_QUEUE
    if not job.scheduler_name:
        job.scheduler_name = DEFAULT_SCHEDULER_NAME
    if job.max_retry == 0:
        job.max_retry = 3
    for i, task in enumerate(job.tasks):
        if not task.name:
            task.name = f"default{i}"
        if task.min_available is None:
            task.min_available = task.replicas
    if job.min_available == 0:
        job.min_available = job.total_replicas()
    return job
