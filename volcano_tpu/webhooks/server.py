"""Webhook manager: AdmissionReview-over-HTTP serving + self-registration.

Reference: cmd/webhook-manager/app/server.go:72-150 — every registered
AdmissionService path becomes an HTTP handler consuming
``admission.k8s.io/v1 AdmissionReview`` JSON and answering with an
AdmissionResponse (allowed / status.message / JSONPatch for mutations), and
the manager self-registers Validating/MutatingWebhookConfiguration objects
for its paths (registerWebhookConfig, cmd/webhook-manager/app/util.go).

The in-process interception (webhooks/router.py) stays the fast path for
the embedded runtime; this module is the NETWORK surface a real API server
(or the e2e tests) talks to. TLS is the deployment's concern (the
reference reads cert files from flags); the HTTP handler itself is
transport-agnostic.
"""

from __future__ import annotations

import base64
import copy
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional, Tuple

from ..api import QueueInfo, QueueState
from .jobs import AdmissionError
from .router import get_service, registered_paths

#: path -> (kind, operations) for the self-registration records, mirroring
#: the reference's per-service webhook rules
_MUTATING = {"/jobs/mutate": ("jobs", ["CREATE"]),
             "/podgroups/mutate": ("podgroups", ["CREATE"]),
             "/queues/mutate": ("queues", ["CREATE"])}
_VALIDATING = {"/jobs/validate": ("jobs", ["CREATE"]),
               "/jobs/validate-update": ("jobs", ["UPDATE"]),
               "/queues/validate": ("queues", ["CREATE", "UPDATE"]),
               "/queues/validate-delete": ("queues", ["DELETE"]),
               "/pods/validate": ("pods", ["CREATE"])}


def _queue_from_manifest(data: Dict) -> QueueInfo:
    meta = data.get("metadata", {}) or {}
    spec = data.get("spec", {}) or {}
    state = (data.get("status", {}) or {}).get("state", "")
    q = QueueInfo(
        name=meta.get("name", ""),
        weight=int(spec.get("weight", 0)),
        reclaimable=bool(spec.get("reclaimable", True)),
        annotations=dict(meta.get("annotations", {}) or {}))
    q.state = QueueState(state) if state else ""
    return q


def _queue_to_patch(original: Dict, q: QueueInfo) -> List[Dict]:
    ops = []
    spec = original.get("spec", {}) or {}
    if int(spec.get("weight", 0)) != q.weight:
        ops.append({"op": "add" if "weight" not in spec else "replace",
                    "path": "/spec/weight", "value": q.weight})
    state = (original.get("status", {}) or {}).get("state", "")
    if q.state and state != str(q.state.value):
        ops.append({"op": "add", "path": "/status",
                    "value": {"state": q.state.value}})
    anns = (original.get("metadata", {}) or {}).get("annotations", {}) or {}
    if q.annotations != anns:
        ops.append({"op": "add", "path": "/metadata/annotations",
                    "value": q.annotations})
    return ops


def _job_to_patch(original: Dict, job) -> List[Dict]:
    """JSONPatch for the fields mutate_job defaults (mutate_job.go:49-200)."""
    ops = []
    spec = original.get("spec", {}) or {}

    def spec_field(key, value):
        ops.append({"op": "add" if key not in spec else "replace",
                    "path": f"/spec/{key}", "value": value})

    if spec.get("queue", "") != job.queue:
        spec_field("queue", job.queue)
    if spec.get("schedulerName", "") != job.scheduler_name:
        spec_field("schedulerName", job.scheduler_name)
    if int(spec.get("maxRetry", 0)) != job.max_retry:
        spec_field("maxRetry", job.max_retry)
    if int(spec.get("minAvailable", 0)) != job.min_available:
        spec_field("minAvailable", job.min_available)
    raw_tasks = spec.get("tasks", []) or []
    for i, (raw, task) in enumerate(zip(raw_tasks, job.tasks)):
        if raw.get("name", "") != task.name:
            ops.append({"op": "add", "path": f"/spec/tasks/{i}/name",
                        "value": task.name})
        if raw.get("minAvailable") is None and task.min_available is not None:
            ops.append({"op": "add", "path": f"/spec/tasks/{i}/minAvailable",
                        "value": task.min_available})
    return ops


class _PodShim:
    def __init__(self, data: Dict):
        spec = data.get("spec", {}) or {}
        self.scheduler_name = spec.get("schedulerName", "")
        self.annotations = dict(
            (data.get("metadata", {}) or {}).get("annotations", {}) or {})


class _PGShim:
    def __init__(self, data: Dict):
        self.queue = (data.get("spec", {}) or {}).get("queue", "")


def handle_review(path: str, review: Dict) -> Dict:
    """AdmissionReview request dict -> AdmissionReview response dict.

    The dispatch half of server.go:106-120: decode the embedded object for
    the path's service, run it, translate AdmissionError -> denied and
    mutations -> a base64 JSONPatch.
    """
    req = review.get("request", {}) or {}
    uid = req.get("uid", "")
    obj = req.get("object") or {}
    old = req.get("oldObject") or {}

    def respond(allowed: bool, message: str = "",
                patch: Optional[List[Dict]] = None) -> Dict:
        response: Dict = {"uid": uid, "allowed": allowed}
        if message:
            response["status"] = {"message": message}
        if patch:
            response["patchType"] = "JSONPatch"
            response["patch"] = base64.b64encode(
                json.dumps(patch).encode()).decode()
        return {"apiVersion": "admission.k8s.io/v1",
                "kind": "AdmissionReview", "response": response}

    try:
        service = get_service(path)
    except KeyError:
        return respond(False, f"no admission service at {path!r}")

    try:
        if path in ("/jobs/validate", "/jobs/mutate"):
            from ..cli.loader import job_from_dict
            job = job_from_dict(obj)
            if path == "/jobs/validate":
                service(job)
                return respond(True)
            mutated = service(job)
            return respond(True, patch=_job_to_patch(obj, mutated))
        if path == "/jobs/validate-update":
            from ..cli.loader import job_from_dict
            service(job_from_dict(old), job_from_dict(obj))
            return respond(True)
        if path in ("/queues/validate", "/queues/mutate"):
            q = _queue_from_manifest(obj)
            if path == "/queues/validate":
                service(q)
                return respond(True)
            mutated = service(copy.deepcopy(q))
            return respond(True, patch=_queue_to_patch(obj, mutated))
        if path == "/queues/validate-delete":
            service(_queue_from_manifest(old or obj))
            return respond(True)
        if path == "/podgroups/mutate":
            pg = service(_PGShim(obj))
            patch = []
            if pg.queue != ((obj.get("spec", {}) or {}).get("queue", "")):
                patch.append({"op": "add", "path": "/spec/queue",
                              "value": pg.queue})
            return respond(True, patch=patch)
        if path == "/pods/validate":
            service(_PodShim(obj))
            return respond(True)
        # custom service registered via router.register: treat as a
        # validator over the raw object dict
        service(obj)
        return respond(True)
    except AdmissionError as e:
        return respond(False, str(e))
    except Exception as e:  # malformed object: deny, keep serving
        return respond(False, f"{type(e).__name__}: {e}")


class _Handler(BaseHTTPRequestHandler):
    def do_POST(self):  # noqa: N802 (http.server API)
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length)
        try:
            review = json.loads(body or b"{}")
        except json.JSONDecodeError:
            self.send_response(400)
            self.end_headers()
            return
        out = json.dumps(handle_review(self.path, review)).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(out)))
        self.end_headers()
        self.wfile.write(out)

    def log_message(self, fmt, *args):  # quiet test output
        pass


class WebhookManager:
    """The vc-webhook-manager binary: serve + self-register.

    ``apiserver`` (runtime/apiserver.APIServer-like, optional) receives the
    webhook configuration objects the way registerWebhookConfig writes them
    to the cluster.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 apiserver=None):
        self.server = ThreadingHTTPServer((host, port), _Handler)
        self.apiserver = apiserver
        self.registrations: List[Dict] = []

    @property
    def address(self) -> Tuple[str, int]:
        return self.server.server_address[:2]

    def url(self, path: str) -> str:
        host, port = self.address
        return f"http://{host}:{port}{path}"

    def register_webhooks(self) -> List[Dict]:
        """Build (and optionally store) the self-registration records
        (registerWebhookConfig): one webhook entry per served path."""
        self.registrations = []
        for kind, table in (("MutatingWebhookConfiguration", _MUTATING),
                            ("ValidatingWebhookConfiguration", _VALIDATING)):
            for path in registered_paths():
                if path not in table:
                    continue
                resource, operations = table[path]
                self.registrations.append({
                    "apiVersion": "admissionregistration.k8s.io/v1",
                    "kind": kind,
                    "metadata": {"name": "volcano-admission-service"
                                         + path.replace("/", "-")},
                    "webhooks": [{
                        "name": path.strip("/").replace("/", ".")
                                + ".volcano.sh",
                        "clientConfig": {"url": self.url(path)},
                        "rules": [{"operations": operations,
                                   "resources": [resource]}],
                        "failurePolicy": "Fail",
                    }],
                })
        store = None
        if self.apiserver is not None:
            # runtime/apiserver.APIServer keeps per-kind stores; fall back
            # to a flat `store` dict for simpler fakes
            if hasattr(self.apiserver, "stores"):
                store = self.apiserver.stores.setdefault(
                    "webhookconfigurations", {})
            elif hasattr(self.apiserver, "store"):
                store = self.apiserver.store.setdefault(
                    "webhookconfigurations", {})
        if store is not None:
            for reg in self.registrations:
                store[reg["metadata"]["name"]] = reg
        return self.registrations

    def serve_in_thread(self) -> threading.Thread:
        t = threading.Thread(target=self.server.serve_forever, daemon=True)
        t.start()
        return t

    def shutdown(self) -> None:
        self.server.shutdown()


def submit_review(url: str, operation: str, obj: Optional[Dict] = None,
                  old: Optional[Dict] = None, uid: str = "test-uid") -> Dict:
    """Client helper: POST an AdmissionReview and decode the response."""
    import urllib.request
    review = {"apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
              "request": {"uid": uid, "operation": operation,
                          "object": obj, "oldObject": old}}
    data = json.dumps(review).encode()
    req = urllib.request.Request(
        url, data=data, headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30) as resp:
        return json.loads(resp.read())


def apply_patch(obj: Dict, response: Dict) -> Dict:
    """Apply a JSONPatch from an AdmissionResponse (add/replace only — the
    subset the mutators emit) to a manifest copy."""
    out = copy.deepcopy(obj)
    patch_b64 = response.get("response", {}).get("patch")
    if not patch_b64:
        return out
    for op in json.loads(base64.b64decode(patch_b64)):
        assert op["op"] in ("add", "replace"), op
        parts = [p for p in op["path"].split("/") if p]
        cur = out
        for p in parts[:-1]:
            key = int(p) if isinstance(cur, list) else p
            if isinstance(cur, dict) and key not in cur:
                cur[key] = {}
            cur = cur[key]
        last = parts[-1]
        if isinstance(cur, list):
            cur[int(last)] = op["value"]
        else:
            cur[last] = op["value"]
    return out
