"""Admission service registry.

Reference: pkg/webhooks/router/{interface.go:25-47, admission.go, server.go}
— AdmissionService{Path, Func} entries served over HTTPS by the
webhook-manager. Here the registry maps the same paths to Python callables;
the runtime API server invokes them on create/update/delete, which is the
same interception point the real webhook configuration gives.
"""

from __future__ import annotations

from typing import Callable, Dict

_SERVICES: Dict[str, Callable] = {}


def register(path: str):
    def deco(fn):
        _SERVICES[path] = fn
        return fn
    return deco


def get_service(path: str) -> Callable:
    return _SERVICES[path]


def registered_paths():
    return sorted(_SERVICES)


def _install_builtin():
    from .jobs import mutate_job, validate_job_create, validate_job_update
    from .podgroups import mutate_podgroup
    from .pods import validate_pod
    from .queues import mutate_queue, validate_queue, validate_queue_delete

    register("/jobs/validate")(validate_job_create)
    register("/jobs/validate-update")(validate_job_update)
    register("/jobs/mutate")(mutate_job)
    register("/queues/validate")(validate_queue)
    register("/queues/validate-delete")(validate_queue_delete)
    register("/queues/mutate")(mutate_queue)
    register("/podgroups/mutate")(mutate_podgroup)
    register("/pods/validate")(validate_pod)


_install_builtin()
