"""Admission webhooks (reference: pkg/webhooks)."""

from .jobs import (AdmissionError, mutate_job, validate_job_create,
                   validate_job_update)
from .podgroups import mutate_podgroup
from .pods import validate_pod
from .queues import (mutate_queue, validate_queue, validate_queue_delete)
from .router import get_service, register, registered_paths

__all__ = [
    "AdmissionError", "mutate_job", "validate_job_create",
    "validate_job_update", "mutate_podgroup", "validate_pod", "mutate_queue",
    "validate_queue", "validate_queue_delete", "get_service", "register",
    "registered_paths",
]
