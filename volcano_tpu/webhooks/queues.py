"""Queue admission: validation and defaulting.

Reference: pkg/webhooks/admission/queues/validate/validate_queue.go:42-215
(weight bounds, hierarchical-annotation consistency for hdrf, delete/state
rules; test matrix validate_queue_test.go:1-918) and
mutate/mutate_queue.go:40-140 (defaults).
"""

from __future__ import annotations

from typing import Optional

from ..api import (DEFAULT_QUEUE, HIERARCHY_ANNOTATION,
                   HIERARCHY_WEIGHTS_ANNOTATION, QueueInfo, QueueState)
from .jobs import AdmissionError


def validate_queue(queue: QueueInfo) -> None:
    errs = []
    if queue.weight < 1 or queue.weight > 65535:
        errs.append(f"queue weight must be in [1, 65535]; got {queue.weight}")

    hierarchy = queue.annotations.get(HIERARCHY_ANNOTATION, queue.hierarchy)
    weights = queue.annotations.get(HIERARCHY_WEIGHTS_ANNOTATION,
                                    queue.hierarchy_weights)
    if hierarchy or weights:
        path = [p for p in hierarchy.split("/") if p]
        wparts = [w for w in weights.split("/") if w]
        if len(path) != len(wparts):
            errs.append(
                f"hierarchy {hierarchy!r} and weights {weights!r} must have "
                "the same depth")
        if path and path[0] != "root":
            errs.append("hierarchy must start at 'root'")
        for w in wparts:
            try:
                if float(w) <= 0:
                    errs.append(f"hierarchy weight {w} must be positive")
            except ValueError:
                errs.append(f"unparseable hierarchy weight {w!r}")
    if errs:
        raise AdmissionError("; ".join(errs))


def validate_queue_delete(queue: QueueInfo) -> None:
    """Only closed, non-default queues may be deleted
    (validate_queue.go delete path)."""
    if queue.name == DEFAULT_QUEUE:
        raise AdmissionError("default queue can not be deleted")
    if queue.state != QueueState.CLOSED:
        raise AdmissionError(
            f"only queue with state {QueueState.CLOSED.value} can be deleted; "
            f"queue {queue.name} state is {queue.state.value}")


def mutate_queue(queue: QueueInfo) -> QueueInfo:
    """Defaults: weight 1, open state, hierarchy annotations normalized
    (mutate_queue.go:40-140)."""
    if queue.weight <= 0:
        queue.weight = 1
    if not queue.state:
        queue.state = QueueState.OPEN
    if queue.hierarchy and not queue.annotations.get(HIERARCHY_ANNOTATION):
        queue.annotations[HIERARCHY_ANNOTATION] = queue.hierarchy
    if queue.hierarchy_weights and not queue.annotations.get(
            HIERARCHY_WEIGHTS_ANNOTATION):
        queue.annotations[HIERARCHY_WEIGHTS_ANNOTATION] = queue.hierarchy_weights
    return queue
