"""PodGroup mutation: default queue injection.

Reference: pkg/webhooks/admission/podgroups/mutate/mutate_podgroup.go:39-110.
"""

from __future__ import annotations

from ..api import DEFAULT_QUEUE
from ..api.job_info import JobInfo


def mutate_podgroup(pg: JobInfo) -> JobInfo:
    if not pg.queue:
        pg.queue = DEFAULT_QUEUE
    return pg
