"""volcano_tpu — a TPU-native batch-scheduling framework.

A ground-up re-design of the capabilities of Volcano (the CNCF Kubernetes batch
scheduler, reference at /root/reference) for TPU execution: the per-cycle
scheduling Session (snapshot -> predicates -> scoring -> placement -> gang
commit) is a batched JAX/XLA array program instead of a goroutine fan-out.

Layering (mirrors SURVEY.md section 1, re-designed TPU-first):

- ``volcano_tpu.api``        — in-memory data model (Resource algebra, TaskInfo,
                               JobInfo, NodeInfo, QueueInfo, ClusterInfo);
                               reference: pkg/scheduler/api.
- ``volcano_tpu.arrays``     — dense array schema + snapshot packing (the
                               device-side mirror of cache.Snapshot);
                               reference: pkg/scheduler/cache/cache.go:712.
- ``volcano_tpu.ops``        — jittable kernels: feasibility masks, score
                               terms, argmax selection, the allocate scan,
                               fair-share solvers, victim selection.
- ``volcano_tpu.plugins``    — policy plugins contributing kernel terms and
                               ordering keys; reference: pkg/scheduler/plugins.
- ``volcano_tpu.actions``    — the pass pipeline (enqueue, allocate, backfill,
                               preempt, reclaim, elect, reserve);
                               reference: pkg/scheduler/actions.
- ``volcano_tpu.framework``  — Session/conf/registries gluing plugins into the
                               compiled cycle; reference: pkg/scheduler/framework.
- ``volcano_tpu.parallel``   — device-mesh sharding of the node axis (pjit /
                               shard_map + collectives).
- ``volcano_tpu.controllers``— job/queue/podgroup lifecycle state machines and
                               garbage collection; reference: pkg/controllers.
- ``volcano_tpu.webhooks``   — admission validation/mutation;
                               reference: pkg/webhooks.
- ``volcano_tpu.cli``        — vcctl-equivalent CLI; reference: pkg/cli.
- ``volcano_tpu.runtime``    — the cluster I/O seam: in-memory API server,
                               binder/evictor sinks, scheduler loop driver;
                               reference: pkg/scheduler/cache + cmd/scheduler.
"""

__version__ = "0.1.0"
