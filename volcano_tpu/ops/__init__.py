"""Jittable scheduling kernels (the compute path of the framework)."""

from .allocate_scan import (MODE_ALLOCATED, MODE_NONE, MODE_PIPELINED,
                            AllocateConfig, AllocateResult, make_allocate_cycle)
from .select import best_node, lex_argmin, sort_order

__all__ = [
    "AllocateConfig", "AllocateResult", "make_allocate_cycle",
    "MODE_NONE", "MODE_ALLOCATED", "MODE_PIPELINED",
    "best_node", "lex_argmin", "sort_order",
]
