"""The allocate action as one compiled array program.

TPU re-design of the reference's dominant pass
(pkg/scheduler/actions/allocate/allocate.go:43-281 plus the Statement
commit/discard transaction, framework/statement.go:27-395):

- The four nested priority queues (namespace -> queue -> job -> task,
  allocate.go:60-118) become a lexicographic masked argmin over key vectors
  recomputed every outer iteration — queue share ordering stays *dynamic*
  exactly like the reference, where proportion's event handlers bump queue
  share as tasks place (proportion.go:281-325).
- PredicateNodes + PrioritizeNodes + SelectBestNode
  (util/scheduler_helper.go:74-228) become a fused feasibility-mask ->
  score-sum -> argmax step over the node axis.
- Statement.Allocate/Pipeline with gang Commit/Discard (statement.go:229-395)
  becomes: the inner scan mutates capacity arrays; after a job's tasks are
  tried, JobReady commits by promoting the working state to the saved state,
  JobPipelined keeps capacity held without emitting binds, and Discard is a
  copy-back of the saved state (pure-functional undo).

Semantics preserved: a task allocates when it fits current idle, pipelines
when it fits future idle (idle + releasing - pipelined, allocate.go:200-240);
gang all-or-nothing per PodGroup minAvailable; overused queues are skipped
(proportion Overused, proportion.go:240-253). Pop semantics follow
allocate.go:205-278 exactly: a popped job places tasks until it either
exhausts its queue, hits a task no node can take (PredicateNodes empty ->
the job breaks for the cycle), or becomes ready with tasks still queued —
in which case it YIELDS and re-enters the job queue, so ready jobs place
one task per pop and interleave with other queues under the dynamically
updated fairness keys (the mechanism behind drf/hdrf convergence; per-job
cursor state persists across pops like the action's pendingTasks map,
allocate.go:184-198).

Documented divergence: score ties break to the lowest node index instead of
rand.Intn (scheduler_helper.go:227) — the reference is nondeterministic there.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp

from ..arrays.affinity import AffinityArrays
from ..arrays.hierarchy import HierarchyArrays
from ..arrays.schema import SnapshotArrays
from . import predicates as P
from . import scoring as S
from .fairshare import drf_job_shares, hdrf_level_keys, namespace_shares
from .select import NEG, best_node, lex_argmin

#: task placement modes in the result arrays
MODE_NONE = 0
MODE_ALLOCATED = 1   # bind now (fits idle)
MODE_PIPELINED = 2   # placed on releasing capacity, no bind yet

#: jobs per fused round (static-keys path: K pre-selected sections; dynamic
#: path: C candidate jobs per launch). Consumed ONLY through
#: :func:`derive_batching` — the single authority for the batching rules
#: that the session's runtime upgrade, the compiled-session conf
#: derivation, and bench all share.
DEFAULT_BATCH_JOBS = 8

#: in-kernel pops per launch on the dynamic-key path (see
#: AllocateConfig.batch_rounds); > batch_jobs because yielded ready jobs
#: re-pop from VMEM-resident candidate data without a fresh launch
DEFAULT_BATCH_ROUNDS = 32


def wave_candidate_depth(wave_width: int) -> int:
    """Candidate-list depth C of the wavefront pre-sweep (ISSUE 16).

    Each wave task carries its top-C feasible nodes by (score desc, index
    asc) out of the shared pre-wave sweep; the in-order commit pass walks
    the list for the first node no earlier wave task touched and exactly
    rescores the touched ones. A wave of W tasks touches at most W - 1
    nodes before task w commits, so C = min(W, 8) makes list exhaustion
    (the only truncation trigger) impossible below W = 8 while bounding
    the per-task candidate state the sweep ships. Shared by the compiled
    cycle, the CPU oracle's wave counter mirror, and the wide shard
    kernel — one authority, identical truncation behavior everywhere.
    """
    return min(max(1, int(wave_width)), 8)


def normalize_wave(cfg: "AllocateConfig") -> "AllocateConfig":
    """THE single authority for legal ``wave_width`` combinations.

    Wavefront waves live INSIDE one popped job section, so every dynamic
    fairness key (drf/hdrf shares, proportion qshare/overused) is frozen
    across a wave by construction — the keys only move at pop boundaries,
    the same static-segment rule ``derive_batching`` leans on for
    ``batch_jobs``. Two features DO mutate mid-section state that a wave's
    row-local conflict rescore cannot see, and force W back to 1:

    - ``enable_pod_affinity``: a commit moves domain-global affinity
      counts, shifting EVERY node's affinity score/mask for the next task;
    - ``enable_host_ports``: the in-cycle port placement buffer is
      append-ordered state read by every subsequent attempt.

    The fused pallas round placers (``use_pallas`` without a mesh) already
    batch whole job sections in-kernel, so ``make_allocate_cycle``
    additionally ignores W there; W takes effect on the plain XLA scan
    path and the sharded shard-local candidate path. W < 1 clamps to 1.
    """
    W = max(1, int(cfg.wave_width))
    if cfg.enable_pod_affinity or cfg.enable_host_ports:
        W = 1
    if W != cfg.wave_width:
        return dataclasses.replace(cfg, wave_width=W)
    return cfg


def derive_batching(cfg: "AllocateConfig", queue_deserved=None,
                    has_proportion: bool = None) -> "AllocateConfig":
    """THE single authority for the auto-batching preconditions.

    Static-keys batching (``batch_jobs`` > 1, one launch of K pre-selected
    job sections) is bit-exact with the sequential pop order ONLY when the
    ordering keys cannot move under any commit: no drf/hdrf dynamic
    ordering AND no finite proportion ``deserved`` anywhere (a 0 counts:
    zero-quota queues flip overused on the first commit). When the keys
    ARE dynamic, the dynamic-key path (``batch_rounds`` > 0) batches
    instead: job selection moves into the kernel, which recomputes the
    drf/proportion keys after every commit (ops/pallas_place._dyn_kernel)
    and stops the launch whenever exactness would be at risk.

    Callers supply the deserved evidence they have: the session passes the
    live ``queue_deserved`` array; the conf-only derivation passes
    ``has_proportion`` (no proportion plugin == deserved stays neutral for
    the whole cycle). Explicit manual settings are respected untouched.
    """
    cfg = normalize_wave(cfg)
    if cfg.batch_jobs != 1 or cfg.batch_rounds:
        return cfg          # manually set — caller owns the precondition
    if has_proportion is None:
        import numpy as np
        has_proportion = bool(np.any(np.isfinite(
            np.asarray(queue_deserved))))
    dynamic = (cfg.drf_job_order or cfg.drf_ns_order or cfg.enable_hdrf
               or has_proportion)
    if dynamic:
        return dataclasses.replace(cfg, batch_jobs=DEFAULT_BATCH_JOBS,
                                   batch_rounds=DEFAULT_BATCH_ROUNDS)
    return dataclasses.replace(cfg, batch_jobs=DEFAULT_BATCH_JOBS)


@dataclass(frozen=True)
class AllocateConfig:
    """Static kernel-composition config (the analog of the conf YAML tiers +
    plugin arguments, pkg/scheduler/conf/scheduler_conf.go:20-82)."""

    binpack_weight: float = 0.0          # binpack.weight (binpack.go:85-151)
    least_allocated_weight: float = 1.0  # nodeorder leastrequested.weight
    most_allocated_weight: float = 0.0   # nodeorder mostrequested.weight
    balanced_weight: float = 1.0         # nodeorder balanced.weight
    taint_prefer_weight: float = 1.0     # nodeorder tainttoleration.weight
    enable_pipelining: bool = True       # allow placement on FutureIdle
    enable_gang: bool = True             # gang all-or-nothing semantics
    #: InterPodAffinity predicate + batch scorer (predicates.go:261-273,
    #: nodeorder.go:273-306). Static so the affinity-free hot path stays
    #: untraced; the session enables it when any task carries terms.
    enable_pod_affinity: bool = False
    #: k8s NodePorts filter (predicates.go:191 wrapping nodeports.New):
    #: hostPort conflicts against node-resident pods AND in-cycle
    #: placements. Static so the port-free hot path carries no port state;
    #: the session enables it when any pending task declares hostPorts.
    enable_host_ports: bool = False
    pod_affinity_weight: float = 1.0     # nodeorder interpodaffinity.weight
    #: Exact hierarchical DRF queue ordering: per-round tree update over
    #: extras.hierarchy with dynamic job allocations (drf.go:230-360).
    enable_hdrf: bool = False
    #: drf JobOrderFn / NamespaceOrderFn with event-updated shares
    #: (drf.go:454-507 + AllocateFunc, drf.go:511-536): recompute the share
    #: keys from the live in-cycle job allocations instead of the static
    #: extras snapshot.
    drf_job_order: bool = False
    drf_ns_order: bool = False
    #: tdm JobOrderFn: non-preemptable jobs schedule first (tdm.go:261-273)
    tdm_job_order: bool = False
    #: sla JobOrderFn: earliest creation+waiting-time deadline first, jobs
    #: without an SLA last (sla.go:104-131); key via extras.job_deadline
    sla_job_order: bool = False
    max_rounds: Optional[int] = None     # cap on outer job iterations
    #: Fused pallas round placer (ops/pallas_place.py): None = auto (TPU
    #: backend, lane-aligned N, fits VMEM), True/False = force,
    #: "interpret" = pallas interpreter (for CPU tests).
    use_pallas: Optional[object] = None
    #: Jobs per fused round (pallas path only). On the static-keys path,
    #: K > 1 runs K consecutive job pops in ONE kernel launch with
    #: in-kernel gang commit/discard; on the dynamic-key path
    #: (batch_rounds > 0) it is the CANDIDATE count whose task data each
    #: launch pre-gathers. Auto-set via :func:`derive_batching` — the one
    #: place the exactness preconditions live; set manually only when you
    #: own them.
    batch_jobs: int = 1
    #: In-kernel pops per launch on the DYNAMIC-key path (drf/hdrf ordering
    #: or finite proportion deserved): job selection moves into the kernel,
    #: which recomputes the dynamic fairness keys after every gang commit
    #: and early-stops whenever the next sequential pop is not provably
    #: available in VMEM (candidate miss, hdrf multi-queue guard). 0 = use
    #: the static-keys path. Auto-set via :func:`derive_batching`.
    batch_rounds: int = 0
    #: Shared-GPU predicate + card accounting (gpu.go:41-56). Static so
    #: GPU-free snapshots skip the per-card kernel state entirely
    #: (decision-neutral when no task requests GPU memory); the session
    #: disables it when the packed gpu_request column is all zero.
    enable_gpu: bool = True
    #: In-graph cycle telemetry (telemetry/cycle.CycleTelemetry): pure
    #: i32/f32 counters carried through the cycle and returned as one
    #: extra output in the packed readback — per-predicate-family
    #: rejection counts, placed/pipelined/discarded counts, argmax ties,
    #: pallas dyn-kernel pop/early-stop counts, unplaced-reason
    #: histogram. Static so the default-off jaxpr stays equation-count-
    #: identical to a build without telemetry (graphcheck family 7);
    #: decisions are bit-identical either way.
    telemetry: bool = False
    #: Wavefront placement width (ISSUE 16): on the XLA scan path and the
    #: sharded candidate path, each iteration over a popped job section
    #: evaluates the next W task attempts against the SAME capacity
    #: snapshot in one batched (W, N) predicate x score sweep, then commits
    #: the conflict-free prefix in strict task order (see the wavefront
    #: block in make_allocate_cycle for the exact commit rule). 1 = today's
    #: per-task sweep, byte-for-byte unchanged. Decisions are identical at
    #: any width by construction; :func:`normalize_wave` (called from
    #: derive_batching) is the single authority for legal W x feature
    #: combinations.
    wave_width: int = 1


@jax.tree_util.register_dataclass
@dataclass
class AllocateExtras:
    """Dynamic per-cycle plugin contributions consumed by the compiled pass.

    Each field is supplied by the plugin named in its comment; the session
    fills neutral defaults for disabled plugins (see :meth:`neutral`).
    """

    job_share: jax.Array        # f32[J] drf JobOrderFn key (drf.go:454-472)
    job_deadline: jax.Array     # f32[J] sla deadline key, +inf = no SLA
    #                             (relative seconds; sla.go:104-131)
    queue_deserved: jax.Array   # f32[Q,R] proportion deserved (proportion.go:140-197)
    ns_share: jax.Array         # f32[S] drf namespace fairness (drf.go:474-507)
    queue_share_extra: jax.Array  # f32[Q] hdrf hierarchical key (drf.go:363-374)
    #: tdm predicate gates (tdm.go:149-167): an ACTIVE-window revocable node
    #: admits only tasks that may use revocable zones; an INACTIVE-window
    #: revocable node admits nothing new at all.
    block_nonrevocable: jax.Array  # bool[N] active-window revocable nodes
    block_all: jax.Array           # bool[N] inactive-window revocable nodes
    task_revocable: jax.Array      # bool[T] task may use revocable nodes
    #                                (volcano.sh/revocable-zone "*",
    #                                job_info.go:88-92)
    tdm_bonus: jax.Array           # f32[N] active-window node-order bonus for
    #                                revocable tasks (MaxNodeScore,
    #                                tdm.go:170-191)
    revocable_node: jax.Array     # bool[N] node carries a revocable zone at
    #                               all (window-independent; the tdm victim
    #                               rule's node filter, tdm.go:210-214)
    task_pref_node: jax.Array     # i32[T] task-topology bucket node (topology.go:344)
    node_locked: jax.Array        # bool[N] reservation locks (reservation.go:56-63)
    target_job: jax.Array         # i32 job exempt from locks (elect.go:29-50)
    affinity: AffinityArrays      # inter-pod affinity encoding (predicates
    #                               plugin contribution, arrays/affinity.py)
    hierarchy: HierarchyArrays    # hdrf tree topology (drf plugin
    #                               contribution, arrays/hierarchy.py)
    #: NodePorts filter inputs (predicates.go:191): per-task hostPorts and
    #: per-node ports already used by resident pods (0 = empty slot);
    #: pe_*0 sizes the in-cycle placement port buffer.
    task_ports: jax.Array         # i32[T, HP]
    node_ports: jax.Array         # i32[N, PS]
    pe_node0: jax.Array           # i32[PE] init -1
    pe_port0: jax.Array           # i32[PE] init 0
    #: volume-binding seam (defaultVolumeBinder, cache.go:240-272):
    #: unbindable claims block a task everywhere; a local-PV claim pins it
    task_volume_ok: jax.Array     # bool[T]
    task_volume_node: jax.Array   # i32[T] pinned node, -1 = any
    #: k8s NodeAffinity preferred-terms score per predicate template
    #: (weighted matched-term sums x nodeaffinity.weight,
    #: nodeorder.go:255-266), host-computed — static over the cycle
    template_na_score: jax.Array  # f32[P, N]
    #: multi-term required node affinity (OR-of-NodeSelectorTerms),
    #: host-computed per distinct OR set (arrays/pack.py note): tasks point
    #: at their group's node mask; -1 = no multi-term affinity
    task_or_group: jax.Array      # i32[T]
    or_feasible: jax.Array        # bool[GR, N]
    #: per-job eviction budget for the preempt path (tdm maxVictims /
    #: getMaxPodEvictNum, tdm.go:304-340): the kernel stops evicting a
    #: job's tasks once the budget is spent. INT32_MAX = unbudgeted.
    job_victim_budget: jax.Array  # i32[J]

    @classmethod
    def neutral(cls, snap: SnapshotArrays) -> "AllocateExtras":
        import numpy as np
        # .shape works on numpy arrays and tracers alike (trace-safe)
        J = snap.jobs.min_available.shape[0]
        Q, R = snap.queues.allocated.shape
        S = snap.namespace_weight.shape[0]
        N = snap.nodes.pod_count.shape[0]
        T = snap.tasks.status.shape[0]
        return cls(
            job_share=np.zeros(J, np.float32),
            job_deadline=np.full(J, np.inf, np.float32),
            queue_deserved=np.full((Q, R), np.inf, np.float32),
            ns_share=np.zeros(S, np.float32),
            queue_share_extra=np.zeros(Q, np.float32),
            block_nonrevocable=np.zeros(N, bool),
            block_all=np.zeros(N, bool),
            task_revocable=np.zeros(T, bool),
            tdm_bonus=np.zeros(N, np.float32),
            revocable_node=np.zeros(N, bool),
            task_pref_node=np.full(T, -1, np.int32),
            node_locked=np.zeros(N, bool),
            target_job=np.int32(-1),
            affinity=AffinityArrays.neutral(N, T),
            hierarchy=HierarchyArrays.neutral(Q, J),
            task_ports=np.zeros((T, 1), np.int32),
            node_ports=np.zeros((N, 1), np.int32),
            pe_node0=np.full(1, -1, np.int32),
            pe_port0=np.zeros(1, np.int32),
            task_volume_ok=np.ones(T, bool),
            task_volume_node=np.full(T, -1, np.int32),
            template_na_score=np.zeros(
                (snap.template_rep.shape[0], N), np.float32),
            task_or_group=np.full(T, -1, np.int32),
            or_feasible=np.ones((1, N), bool),
            job_victim_budget=np.full(J, 2 ** 31 - 1, np.int32),
        )


@jax.tree_util.register_dataclass
@dataclass
class AllocateResult:
    task_node: jax.Array       # i32[T] node index or -1
    task_mode: jax.Array       # i32[T] MODE_*
    task_gpu: jax.Array        # i32[T] assigned GPU card or -1 (gpu.go:41-56)

    def packed_decisions(self) -> jax.Array:
        """i32[3T + 3J (+ telemetry tail)]: all decision outputs in ONE
        array so the host pays a single device->host fetch per cycle (the
        axon tunnel charges ~tens of ms per readback regardless of size).
        Decode with :func:`unpack_decisions`; when cfg.telemetry is on the
        CycleTelemetry block rides the same fetch as an i32 tail
        (telemetry/cycle.unpack_cycle_telemetry)."""
        parts = [
            self.task_node, self.task_mode, self.task_gpu,
            self.job_ready.astype(jnp.int32),
            self.job_pipelined.astype(jnp.int32),
            self.job_attempted.astype(jnp.int32)]
        if self.telemetry is not None:
            parts.append(self.telemetry.packed())
        return jnp.concatenate(parts)
    job_ready: jax.Array       # bool[J] gang became ready (binds emitted)
    job_pipelined: jax.Array   # bool[J] gang holds capacity, no binds
    job_attempted: jax.Array   # bool[J] job was popped this cycle
    idle: jax.Array            # f32[N, R] remaining idle after the pass
    queue_allocated: jax.Array  # f32[Q, R] post-pass queue usage
    #: telemetry/cycle.CycleTelemetry when cfg.telemetry, else None (the
    #: None field is an empty pytree: zero leaves, zero equations)
    telemetry: Optional[object] = None


def unpack_decisions(packed, T: int, J: int):
    """Inverse of AllocateResult.packed_decisions on a host numpy array.
    Accepts the pre-job_attempted 3T+2J layout too (attempted = None)."""
    import numpy as np
    packed = np.asarray(packed)
    task_node = packed[:T]
    task_mode = packed[T:2 * T]
    task_gpu = packed[2 * T:3 * T]
    job_ready = packed[3 * T:3 * T + J].astype(bool)
    job_pipelined = packed[3 * T + J:3 * T + 2 * J].astype(bool)
    if packed.shape[0] >= 3 * T + 3 * J:
        job_attempted = packed[3 * T + 2 * J:3 * T + 3 * J].astype(bool)
    else:
        job_attempted = None
    return (task_node, task_mode, task_gpu, job_ready, job_pipelined,
            job_attempted)


def _score_fn(cfg: AllocateConfig, snap: SnapshotArrays, resreq, idle,
              tol_hash, tol_effect, tol_mode):
    """Weighted additive node score — the PrioritizeNodes reduce
    (scheduler_helper.go:133-195) with plugin weights folded in."""
    nodes = snap.nodes
    used_dyn = nodes.allocatable - idle
    resource_w = jnp.ones_like(resreq)
    score = jnp.zeros(idle.shape[0], jnp.float32)
    if cfg.binpack_weight:
        score += cfg.binpack_weight * S.binpack_score(
            used_dyn, nodes.allocatable, resreq, resource_w)
    if cfg.least_allocated_weight:
        score += cfg.least_allocated_weight * S.least_allocated_score(
            used_dyn, nodes.allocatable, resreq)
    if cfg.most_allocated_weight:
        score += cfg.most_allocated_weight * S.most_allocated_score(
            used_dyn, nodes.allocatable, resreq)
    if cfg.balanced_weight:
        score += cfg.balanced_weight * S.balanced_allocation_score(
            used_dyn, nodes.allocatable, resreq)
    if cfg.taint_prefer_weight:
        score += cfg.taint_prefer_weight * S.taint_prefer_score(
            tol_hash, tol_effect, tol_mode, nodes)
    return score


def _affinity_terms(aff: AffinityArrays, aff_cnt, anti_cnt, t, valid_nodes):
    """InterPodAffinity feasibility mask + normalized score for task ``t``.

    The array program of the k8s plugin the reference wraps
    (predicates.go:261-273 Filter, nodeorder.go:273-306 batch scorer),
    over the NODE-SPACE encoding (arrays/affinity.py): live counts are
    [SK, N+1] rows, so everything here is row selects and vector compares —
    no per-element gathers (TPU gathers serialize and dominated the
    per-task affinity cost in the domain-indexed encoding).

    - required affinity: the node's topology domain must already hold a pod
      matching the term's selector (live counts, so in-cycle placements
      count like the reference's event-handler-maintained pod lister,
      predicates.go:116-160); the k8s first-pod escape applies via the
      cluster-total column.
    - required anti-affinity, both directions: the incoming pod's own
      terms veto domains holding matching pods, and placed pods' terms
      (``anti_cnt[ETA, N]``) veto domains for incoming pods they match.
    - preferred terms: signed weighted count sum, min-max normalized to
      0..100 over schedulable nodes (k8s NormalizeScore; the reference
      normalizes over its filtered set — documented divergence).
    """
    N = aff.sk_domain.shape[1]

    # required affinity
    sk = aff.task_aff_sk[t]                                    # [A]
    act = sk >= 0
    skc = jnp.maximum(sk, 0)
    rows = aff_cnt[skc]                                        # [A, N+1]
    have = rows[:, :N]
    total = rows[:, N]
    dom = aff.sk_domain[skc]                                   # [A, N]
    ok = (have > 0) & (dom >= 0)
    self_ok = (total == 0) & aff.task_match[aff.sk_sel[skc], t]
    ok = ok | (self_ok[:, None] & (dom >= 0))
    aff_ok = jnp.all(ok | ~act[:, None], axis=0)               # [N]

    # required anti-affinity: own terms vs pods already counted
    own = aff.task_anti_term[t]                                # [B]
    bact = own >= 0
    ec = jnp.maximum(own, 0)
    cnt_b = aff_cnt[jnp.maximum(aff.eta_sk[ec], 0)][:, :N]     # [B, N]
    dom_b = aff.eta_domain[ec]                                 # [B, N]
    viol_own = jnp.any(bact[:, None] & (cnt_b > 0) & (dom_b >= 0), axis=0)

    # required anti-affinity: placed pods' terms vs this task (symmetric)
    m = (aff.eta_sel >= 0) & aff.task_match[jnp.maximum(aff.eta_sel, 0), t]
    viol_sym = jnp.any(m[:, None] & (anti_cnt > 0)
                       & (aff.eta_domain >= 0), axis=0)

    feas = aff_ok & ~viol_own & ~viol_sym

    # preferred terms of the incoming task (dynamic counts)
    psk = aff.task_pref_sk[t]                                  # [PP]
    pw = aff.task_pref_w[t]
    pact = psk >= 0
    pskc = jnp.maximum(psk, 0)
    cnt_p = aff_cnt[pskc][:, :N]                               # [PP, N]
    dom_p = aff.sk_domain[pskc]
    raw = jnp.sum(jnp.where(pact[:, None] & (dom_p >= 0),
                            pw[:, None] * cnt_p, 0.0), axis=0)
    # symmetric preferred from snapshot pods (node-space static map)
    mcol = aff.task_match[:, t].astype(jnp.float32)            # [SEL]
    raw = raw + mcol @ aff.static_pref                         # [N]

    # min-max normalize over schedulable nodes -> 0..100 (k8s NormalizeScore)
    big = jnp.float32(3.4e38)
    mx = jnp.max(jnp.where(valid_nodes, raw, -big))
    mn = jnp.min(jnp.where(valid_nodes, raw, big))
    span = mx - mn
    norm = jnp.where(span > 0,
                     (raw - mn) * (100.0 / jnp.maximum(span, 1e-9)), 0.0)
    return feas, norm


def _affinity_place_update(aff: AffinityArrays, aff_cnt, anti_cnt, t, node,
                           placed):
    """Account a placement in the live affinity counts (the analog of the
    reference's AddPod event handler updating the plugin's pod lister,
    predicates.go:116-138): add a domain-membership mask row per (sel,key)
    pair the placed task matches — pure vector compare + add."""
    N = aff.sk_domain.shape[1]
    dom_at = aff.sk_domain[:, node]                            # [SK]
    member = ((aff.sk_domain == dom_at[:, None])
              & (aff.sk_domain >= 0) & (dom_at >= 0)[:, None])  # [SK, N]
    matches = (aff.sk_sel >= 0) & aff.task_match[
        jnp.maximum(aff.sk_sel, 0), t]
    addsk = jnp.where(placed & matches, 1.0, 0.0)              # [SK]
    upd = jnp.concatenate(
        [member, (dom_at >= 0)[:, None]], axis=1).astype(jnp.float32)
    aff_cnt = aff_cnt + upd * addsk[:, None]
    # the task's own required anti terms mark their presence in the domain
    own = aff.task_anti_term[t]                                # [B]
    ec = jnp.maximum(own, 0)
    edom = aff.eta_domain[ec]                                  # [B, N]
    edom_at = edom[:, node]                                    # [B]
    emember = ((edom == edom_at[:, None]) & (edom >= 0)
               & (edom_at >= 0)[:, None])
    eidx = jnp.where((own >= 0) & placed, own, anti_cnt.shape[0])
    anti_cnt = anti_cnt.at[eidx].add(emember.astype(jnp.float32),
                                     mode="drop")
    return aff_cnt, anti_cnt


def make_allocate_cycle(cfg: AllocateConfig, mesh=None):
    """Build the jittable allocate pass for a given static config.

    Returned signature:
        allocate(snap, extras: AllocateExtras) -> AllocateResult
    with all dynamic plugin contributions (drf shares, proportion deserved,
    hdrf keys, tdm gates, topology preferences, reservation locks) in
    ``extras``; use AllocateExtras.neutral(snap) when the plugins are off.

    ``mesh``: when the caller runs this cycle under GSPMD node-axis
    sharding (parallel/sharding.py), pass the 1-D node mesh. With
    ``use_pallas`` requested the cycle then takes the sharded-pallas
    path: the scan branch keeps pops, fairness-key recompute, and
    capacity commits in replicated XLA, and delegates each placement
    attempt's feasibility -> score -> argmax to a shard-local pallas
    launch under shard_map, combined across shards by an in-graph
    argmax (pallas_place.make_shard_candidate_placer). Decisions are
    bit-identical to the unsharded paths.
    """

    def allocate(snap: SnapshotArrays,
                 extras: AllocateExtras) -> AllocateResult:
        snap = jax.tree.map(jnp.asarray, snap)
        extras = jax.tree.map(jnp.asarray, extras)
        job_share = extras.job_share
        queue_deserved = extras.queue_deserved
        ns_share = extras.ns_share
        nodes, tasks, jobs, queues = snap.nodes, snap.tasks, snap.jobs, snap.queues
        N, R = nodes.idle.shape
        T = tasks.resreq.shape[0]
        J, M = jobs.task_table.shape

        G = nodes.gpu_memory.shape[1]

        # ---- fused pallas round placer (ops/pallas_place.py) -------------
        n_templates = snap.template_rep.shape[0]
        GR = extras.or_feasible.shape[0]
        K = max(1, int(cfg.batch_jobs))
        KP = max(0, int(cfg.batch_rounds))
        # one-place config assert backing derive_batching: static-key
        # batching cannot carry dynamic ordering keys (their recompute
        # lives only in the dynamic-key kernel)
        if K > 1 and not KP and (cfg.drf_job_order or cfg.drf_ns_order
                                 or cfg.enable_hdrf):
            raise ValueError(
                "batch_jobs > 1 on the static-keys path requires static "
                "ordering keys (no drf/hdrf dynamic ordering); use "
                "batch_rounds (derive_batching sets it) for dynamic keys")
        aff_shapes = (extras.affinity.sk_domain.shape[0],
                      extras.affinity.eta_domain.shape[0],
                      extras.affinity.task_match.shape[0])
        Q = extras.queue_deserved.shape[0]
        S_ns = extras.ns_share.shape[0]
        if cfg.use_pallas == "interpret":
            use_pallas, interp = True, True
        elif cfg.use_pallas is None:
            from .pallas_place import vmem_estimate_bytes
            # Backend probe must never take down the cycle: when the TPU
            # plugin fails to initialize (dead tunnel and the like),
            # jax.default_backend() raises — fall back to the XLA scan
            # path, which runs on whatever backend jit resolves to.
            try:
                backend = jax.default_backend()
            except Exception:
                backend = "unavailable"
            vmem = vmem_estimate_bytes(
                K, M, N, R, G, n_templates, GR,
                *(aff_shapes if cfg.enable_pod_affinity else (0, 0, 0)),
                J=J if KP else 0, Q=Q if KP else 0)
            # under a mesh the launch is shard-local: the lane-tile
            # check applies to the per-shard row count, not global N
            n_tile = N if mesh is None else N // max(int(mesh.devices.size), 1)
            use_pallas = (backend in ("tpu", "axon") and n_tile % 128 == 0
                          and not cfg.enable_host_ports
                          and vmem < 12 * 2 ** 20)
            interp = False
        else:
            use_pallas, interp = bool(cfg.use_pallas), False
        if use_pallas and cfg.enable_host_ports:
            raise ValueError(
                "use_pallas excludes enable_host_ports: the fused round "
                "placer carries no host-port state")
        if mesh is not None and use_pallas:
            # sharding x pallas composition: GSPMD still has no
            # partitioning rule for a full-axis pallas_call, so the
            # fused round placers stay off — instead the scan branch
            # delegates the per-attempt candidate search to a
            # shard-local launch (see the docstring). Pod affinity's
            # scorer min-max normalizes over the FULL node axis (a
            # cross-shard reduction), so it stays on the pure scan path.
            shard_pl = not cfg.enable_pod_affinity
            use_pallas = False
        else:
            shard_pl = False
        if not use_pallas:
            K = 1
            KP = 0
        dyn = use_pallas and KP > 0
        # In-graph telemetry is a static config bit: with TEL False not one
        # counter equation is traced (the jaxpr is equation-count-identical
        # to a telemetry-free build — graphcheck family 7 guards this).
        TEL = bool(cfg.telemetry)
        if TEL:
            from ..telemetry.cycle import CycleTelemetry

        # ---- wavefront width (ISSUE 16) ------------------------------
        # normalize_wave is the single authority; re-clamp defensively for
        # raw configs that skipped derive_batching, and ignore W on the
        # fused round placers (they batch whole job sections in-kernel).
        W = max(1, int(cfg.wave_width))
        if cfg.enable_pod_affinity or cfg.enable_host_ports:
            W = 1
        if use_pallas:
            W = 1
        WC = wave_candidate_depth(W)

        if use_pallas:
            # node-axis state lives transposed ([R, N] / [G, N] / [1, N]) so
            # the node axis is the TPU lane dimension inside the kernel.
            # No saved_* copies: the v2 kernel commits/discards per job
            # section internally, so the carry IS the committed state.
            init_cap = dict(
                idle=nodes.idle.T,
                pipe_extra=jnp.zeros((R, N), jnp.float32),
                pods_extra=jnp.zeros((1, N), jnp.float32),
                gpu_extra=jnp.zeros((G, N), jnp.float32),
            )
            if cfg.enable_pod_affinity:
                # live inter-pod affinity counts, VMEM-split layout:
                # [SK, N] node-space counts + [SK, 1] cluster totals
                # (the first-pod-escape column of arrays/affinity.cnt0)
                init_cap.update(
                    aff_cnt=extras.affinity.cnt0[:, :N],
                    aff_tot=extras.affinity.cnt0[:, N:],
                    anti_cnt=extras.affinity.anti_cnt0,
                )
        else:
            init_cap = dict(
                idle=nodes.idle,
                pipe_extra=jnp.zeros((N, R), jnp.float32),
                pods_extra=jnp.zeros(N, jnp.int32),
                gpu_extra=jnp.zeros((N, G), jnp.float32),
                saved_idle=nodes.idle,
                saved_pipe=jnp.zeros((N, R), jnp.float32),
                saved_pods=jnp.zeros(N, jnp.int32),
                saved_gpu=jnp.zeros((N, G), jnp.float32),
                # live inter-pod affinity counts (neutral [1,..] when off)
                aff_cnt=extras.affinity.cnt0,
                anti_cnt=extras.affinity.anti_cnt0,
                saved_aff=extras.affinity.cnt0,
                saved_anti=extras.affinity.anti_cnt0,
                # in-cycle hostPort placements (neutral [1] when disabled;
                # the pallas paths exclude enable_host_ports entirely)
                pe_node=extras.pe_node0,
                pe_port=extras.pe_port0,
                pe_cnt=jnp.int32(0),
                saved_pe_node=extras.pe_node0,
                saved_pe_port=extras.pe_port0,
                saved_pe_cnt=jnp.int32(0),
            )
        init = dict(
            task_node=jnp.full(T, -1, jnp.int32),
            task_mode=jnp.zeros(T, jnp.int32),
            task_gpu=jnp.full(T, -1, jnp.int32),
            job_done=jnp.zeros(J, bool),
            job_popped=jnp.zeros(J, bool),
            job_ready=jnp.zeros(J, bool),
            job_pipelined=jnp.zeros(J, bool),
            queue_allocated=queues.allocated,
            # per-job pop state: consumed task-table slots, committed
            # allocations (the dynamic ReadyTaskNum), live drf allocation
            # (event-handler analog, drf.go:511-536)
            job_cursor=jnp.zeros(J, jnp.int32),
            job_alloc_count=jnp.zeros(J, jnp.int32),
            job_alloc_dyn=jobs.allocated,
            rounds=jnp.int32(0),
            # True while rounds keep placing: the capacity-give-up check
            # only runs after a stalled round (zero per-round cost on the
            # saturating hot path)
            progressed=jnp.bool_(True),
            **init_cap,
        )
        if TEL:
            init["telemetry"] = CycleTelemetry.zeros(R)

        # a ready job yields after each placement and re-enters the queue
        # (allocate.go:262-265), so pops are bounded by J + total tasks
        max_rounds = J + T if cfg.max_rounds is None else cfg.max_rounds
        total_cap = snap.cluster_capacity

        # static predicate rows per template, computed once per cycle (the
        # predicate-cache analog, predicates/cache.go:42-90; see
        # P.template_masks). bool[P, N]. The OR-of-terms node-affinity
        # group mask is per TASK (templates merge across different OR sets
        # on the native pack path).
        tmpl_static = P.template_masks(nodes, tasks, snap.template_rep)

        def or_ok_row(t):
            grp = extras.task_or_group[t]
            return jnp.where(grp >= 0,
                             extras.or_feasible[jnp.maximum(grp, 0)], True)

        if use_pallas or shard_pl or W > 1:
            # per-template taint-prefer rows: the one score family with a
            # cross-node reduction (max intolerable count), so the
            # wavefront commit rescore gathers it from this static map
            # exactly like the pallas kernels do
            if cfg.taint_prefer_weight:
                rep = jnp.maximum(snap.template_rep, 0)
                tp_static = cfg.taint_prefer_weight * jax.vmap(
                    lambda ti: S.taint_prefer_score(
                        tasks.tol_hash[ti], tasks.tol_effect[ti],
                        tasks.tol_mode[ti], nodes))(rep)
            else:
                tp_static = jnp.zeros((tmpl_static.shape[0], N), jnp.float32)
        if use_pallas or shard_pl:
            # node-space env arrays shared by the fused round placers and
            # the shard-local candidate kernel ([.., N] with the node
            # axis last = kernel lane dimension)
            alloc_t = nodes.allocatable.T
            cnt_row = nodes.pod_count.astype(jnp.float32)[None, :]
            maxp_row = nodes.max_pods.astype(jnp.float32)[None, :]
            gidle0_t = (nodes.gpu_memory - nodes.gpu_used).T
            # static-per-cycle node maps consumed in-kernel via dynamic
            # sublane row reads (no per-round [M, N] materialization)
            tstat_f = tmpl_static.astype(jnp.float32)
            na_f = extras.template_na_score.astype(jnp.float32)
            blocknr_row = extras.block_nonrevocable.astype(
                jnp.float32)[None, :]
            blockall_row = extras.block_all.astype(jnp.float32)[None, :]
            bonus_row = extras.tdm_bonus.astype(jnp.float32)[None, :]
            locked_row = extras.node_locked.astype(jnp.float32)[None, :]
            orfeas_f = extras.or_feasible.astype(jnp.float32)

        if use_pallas:
            from .pallas_place import (make_dyn_round_placer,
                                       make_round_placer)
            SK, ETA, SEL = aff_shapes
            aff_dims = (SK, ETA) if cfg.enable_pod_affinity else None
            NH = (2 * extras.hierarchy.queue_path.shape[1]
                  if cfg.enable_hdrf else 0)
            if dyn:
                placer = make_dyn_round_placer(
                    cfg, K, KP, M, N, R, G, GR, J, Q, S_ns, NH,
                    aff_dims=aff_dims, interpret=interp)
            else:
                placer = make_round_placer(cfg, K, M, N, R, G, GR,
                                           aff_dims=aff_dims,
                                           interpret=interp)
            relmp_t = (nodes.releasing - nodes.pipelined).T

            def node_env_args():
                out = [tstat_f, tp_static, na_f, blocknr_row, blockall_row,
                       bonus_row, locked_row, orfeas_f, relmp_t, alloc_t,
                       cnt_row, maxp_row]
                if cfg.enable_gpu:
                    out.append(gidle0_t)
                return out

            if cfg.enable_pod_affinity:
                # static affinity maps in kernel layout (arrays/affinity.py
                # node-space encoding; counts split into [SK, N] + totals)
                afa = extras.affinity
                aff_static_args = [
                    (nodes.valid & nodes.schedulable).astype(
                        jnp.float32)[None, :],
                    afa.sk_domain,
                    afa.sk_sel[:, None],
                    afa.eta_sk[None, :],
                    afa.eta_domain,
                    afa.static_pref,
                ]

                def aff_slot_args(flat_ids):
                    """Per-launch slot gathers of the task-side term
                    tables (the only [.., CM] affinity traffic a round
                    ships; everything node-shaped stays resident)."""
                    f32 = jnp.float32
                    return [
                        afa.task_aff_sk[flat_ids].T,
                        afa.task_anti_term[flat_ids].T,
                        afa.task_pref_sk[flat_ids].T,
                        afa.task_pref_w[flat_ids].T,
                        afa.task_match[jnp.maximum(afa.sk_sel, 0)][
                            :, flat_ids].astype(f32),
                        ((afa.eta_sel >= 0)[:, None]
                         & afa.task_match[jnp.maximum(afa.eta_sel, 0)][
                             :, flat_ids]).astype(f32),
                        afa.task_match[:, flat_ids].astype(f32),
                    ]

                def aff_state_args(st):
                    return [st["aff_cnt"], st["aff_tot"], st["anti_cnt"]]
            else:
                def aff_slot_args(flat_ids):
                    return []

                def aff_state_args(st):
                    return []
                aff_static_args = []

        if shard_pl:
            # ---- shard-local pallas candidate search (sharding x pallas) --
            # Each shard launches the candidate kernel over its own node
            # rows (env refs and live capacity arrive pre-sharded, no
            # gather); the per-shard (score, global idx, found, raw ties)
            # columns are reduced by an in-graph argmax combine that is
            # bit-identical to select.best_node/tie_count on the full
            # axis: f32 max is exact, the lowest-global-index tie-break
            # is preserved by min over per-shard minima, and raw tie
            # counts sum only across shards sitting at the global max.
            from jax.experimental.shard_map import shard_map
            from jax.sharding import PartitionSpec as _PS

            from .pallas_place import make_shard_candidate_placer
            axis = mesh.axis_names[0]
            D_sh = int(mesh.devices.size)
            if N % D_sh:
                raise ValueError(
                    f"sharded pallas needs nodes % mesh devices == 0 "
                    f"(N={N}, devices={D_sh})")
            NL_sh = N // D_sh
            rel_t = nodes.releasing.T
            pip_t = nodes.pipelined.T
            _cand = make_shard_candidate_placer(cfg, NL_sh, R, G, GR,
                                                interpret=interp)
            env_sh = [tstat_f, tp_static, na_f, blocknr_row, blockall_row,
                      bonus_row, locked_row, orfeas_f, rel_t, pip_t,
                      alloc_t, cnt_row, maxp_row]
            if cfg.enable_gpu:
                env_sh.append(gidle0_t)
            n_scal = 8 + (1 if cfg.enable_gpu else 0)

            def _cand_region(*flat):
                it = iter(flat)
                rr = next(it)
                gq = next(it) if cfg.enable_gpu else None
                scal = [next(it) for _ in range(7)]
                env = [next(it) for _ in range(len(env_sh))]
                idle_s = next(it)                 # [NL, R]
                pipe_s = next(it)                 # [NL, R]
                pods_s = next(it)                 # [NL] i32
                gpux_s = next(it) if cfg.enable_gpu else None
                off = (jax.lax.axis_index(axis)
                       * jnp.int32(NL_sh)).astype(jnp.int32).reshape(1, 1)
                args = [rr]
                if cfg.enable_gpu:
                    args.append(gq)
                args += scal + [off] + env
                args += [idle_s.T, pipe_s.T,
                         pods_s.astype(jnp.float32)[None, :]]
                if cfg.enable_gpu:
                    args.append(gpux_s.T)
                outs = _cand(*args)
                return tuple(o.reshape(1) for o in outs)

            state_specs = [_PS(axis, None), _PS(axis, None), _PS(axis)]
            if cfg.enable_gpu:
                state_specs.append(_PS(axis, None))
            # check_rep=False: shard_map has no replication rule for
            # pallas_call (the error message prescribes exactly this);
            # out_specs make the sharding explicit anyway
            _cand_sm = shard_map(
                _cand_region, mesh=mesh,
                in_specs=tuple([_PS()] * n_scal
                               + [_PS(None, axis)] * len(env_sh)
                               + state_specs),
                out_specs=(_PS(axis),) * 8,
                check_rep=False)

            def _combine(sc_d, ix_d, fn_d, tie_d):
                """(D,) per-shard candidates -> the global winner
                best_node would return, plus the RAW tie count at the
                global max (tie_count applies ``max(n - 1, 0)``)."""
                fnb = fn_d > 0
                msc = jnp.where(fnb, sc_d, jnp.float32(NEG))
                gmax = jnp.max(msc)
                at = fnb & (msc == gmax)
                found = jnp.any(fnb)
                idx = jnp.min(jnp.where(at, ix_d, jnp.int32(N)))
                idx = jnp.where(found, idx, jnp.int32(0))
                ties_raw = jnp.sum(jnp.where(at, tie_d, 0),
                                   dtype=jnp.int32)
                return idx, found, ties_raw

            def shard_candidates(t, ji, idle, pipe_extra, pods_extra,
                                 gpu_extra):
                i32 = jnp.int32
                scal = [
                    extras.task_pref_node[t].astype(i32).reshape(1, 1),
                    jnp.maximum(tasks.template[t], 0)
                    .astype(i32).reshape(1, 1),
                    extras.task_or_group[t].astype(i32).reshape(1, 1),
                    extras.task_volume_node[t].astype(i32).reshape(1, 1),
                    extras.task_volume_ok[t].astype(i32).reshape(1, 1),
                    extras.task_revocable[t].astype(i32).reshape(1, 1),
                    (ji == extras.target_job).astype(i32).reshape(1, 1),
                ]
                args = [tasks.resreq[t][:, None]]
                if cfg.enable_gpu:
                    args.append(tasks.gpu_request[t]
                                .astype(jnp.float32).reshape(1, 1))
                args += scal + env_sh
                args += [idle, pipe_extra, pods_extra]
                if cfg.enable_gpu:
                    args.append(gpu_extra)
                (sc_n, ix_n, fn_n, tie_n,
                 sc_f, ix_f, fn_f, tie_f) = _cand_sm(*args)
                n_now, found_now, raw_now = _combine(sc_n, ix_n,
                                                     fn_n, tie_n)
                n_fut, found_fut, raw_fut = _combine(sc_f, ix_f,
                                                     fn_f, tie_f)
                return (n_now, found_now, raw_now,
                        n_fut, found_fut, raw_fut)

        if dyn:
            # ---- static per-job inputs of the dynamic-key kernel ---------
            # static key columns in the exact order _dyn_kernel's reader
            # walks them (the dynamic ones — qshare, ready_now, and the
            # drf job/ns shares when enabled — are recomputed in VMEM)
            skey_rows = []
            if not cfg.drf_ns_order:
                skey_rows.append(extras.ns_share[jobs.namespace])
            skey_rows.append(jobs.namespace.astype(jnp.float32))
            skey_rows.append(jobs.queue.astype(jnp.float32))
            skey_rows.append(-jobs.priority.astype(jnp.float32))
            if cfg.tdm_job_order:
                skey_rows.append(jobs.preemptable.astype(jnp.float32))
            if cfg.sla_job_order:
                skey_rows.append(extras.job_deadline)
            if not cfg.drf_job_order:
                skey_rows.append(extras.job_share)
            skey_rows.append(jobs.creation_rank.astype(jnp.float32))
            skeys_mat = jnp.stack(skey_rows).astype(jnp.float32)
            qid_row = jobs.queue.astype(jnp.int32)[None, :]
            qoh_mat = (jnp.arange(Q, dtype=jnp.int32)[:, None]
                       == jobs.queue[None, :]).astype(jnp.float32)
            ns_args = []
            if cfg.drf_ns_order:
                nsm = (jnp.arange(S_ns, dtype=jnp.int32)[:, None]
                       == jobs.namespace[None, :])
                ns_args = [nsm.astype(jnp.float32),
                           (nsm & jobs.valid[None, :]).astype(jnp.float32),
                           snap.namespace_weight.astype(jnp.float32)[None, :]]
            minav_row = jobs.min_available.astype(jnp.int32)[None, :]
            rdy0_row = jobs.ready_num.astype(jnp.int32)[None, :]
            npend_row = jobs.n_pending.astype(jnp.int32)[None, :]
            eligs_row = (jobs.valid & jobs.schedulable).astype(
                jnp.int32)[None, :]
            validf_row = jobs.valid.astype(jnp.float32)[None, :]
            # re-pop fusion flag per job (see the scan path's can_batch):
            # static keys AND no finite deserved on the job's queue
            keys_static_cfg = not (cfg.drf_job_order or cfg.drf_ns_order
                                   or cfg.enable_hdrf)
            canb_row = (jnp.bool_(keys_static_cfg)
                        & ~jnp.any(jnp.isfinite(
                            extras.queue_deserved[jobs.queue]), axis=1)
                        ).astype(jnp.int32)[None, :]
            qex_col = extras.queue_share_extra.astype(jnp.float32)[:, None]
            total_col = total_cap.astype(jnp.float32)[:, None]
            tgt_in = jnp.asarray(extras.target_job,
                                 jnp.int32).reshape(1, 1)

        def eligible(st):
            # Overused queues are skipped (proportion.Overused,
            # proportion.go:240-253): NOT allocated.LessEqual(deserved),
            # i.e. any dim where allocated exceeds deserved.
            overused = jnp.any(st["queue_allocated"] > queue_deserved + 1e-6,
                               axis=-1)
            job_overused = overused[jobs.queue]
            return (jobs.valid & jobs.schedulable & ~st["job_done"]
                    & (st["job_cursor"] < jobs.n_pending) & ~job_overused)

        def cond(st):
            return jnp.any(eligible(st)) & (st["rounds"] < max_rounds)

        # cheapest pending request per job, per dim (static): the give-up
        # bound below compares it against per-dim capacity maxima
        _tbl = jnp.maximum(jobs.task_table, 0)
        _slot_req = tasks.resreq[_tbl]                        # [J, M, R]
        _slot_ok = (jobs.task_table >= 0)[:, :, None]
        jobs_min_req = jnp.min(
            jnp.where(_slot_ok, _slot_req, jnp.inf), axis=1)  # [J, R]
        node_live = (nodes.valid & nodes.schedulable)

        if W > 1:
            # ---- wavefront placement (ISSUE 16) --------------------------
            # Each wave evaluates the next W task attempts of the popped
            # section against the SAME capacity snapshot in one batched
            # (W, N) sweep, reduced per task and capacity view to a top-C
            # candidate list (C = wave_candidate_depth(W), exact
            # (score desc, index asc) order). The commit pass then walks the
            # wave in strict task order, re-resolving each slot's winner at
            # the CURRENT mid-wave state from its list plus an exact O(C*R)
            # rescore of every node the wave already touched. This is
            # decision-identical to the sequential scan because capacity is
            # monotone non-increasing within a section:
            #   - untouched rows keep their pre-wave feasibility AND score
            #     bitwise (every score family is per-node elementwise; the
            #     one cross-node term, taint-prefer's max count, is static
            #     per template — tp_static), so the first untouched list
            #     entry dominates every untouched node that fell off the
            #     list;
            #   - touched rows are re-evaluated exactly at the current
            #     state (scores can RISE under binpack/most-allocated, so
            #     all touched nodes are rescored, on-list or not);
            #   - only when the slot's list is exhausted (every entry
            #     touched AND more feasible nodes existed than the list
            #     held) can the true winner hide off-list: the wave
            #     truncates there and the slot replays next wave. A slot at
            #     wave position 0 has an empty touched set and is always
            #     decidable, so every wave advances >= 1 slot.
            NEGf = jnp.float32(NEG)
            iota_n = jnp.arange(N, dtype=jnp.int32)
            # block width for topc's two-level extraction: the widest
            # divisor of N near sqrt(N), falling back to one block (the
            # degenerate B=1 shape still beats a full-N pass per entry)
            NB = next((c for c in (64, 32, 16, 8, 4, 2)
                       if N % c == 0 and c * c <= 4 * N), N)

            def _wave_rej1(t_idx, ji, idle0, pipe0, pods0, gpux0):
                """Per-family rejection row (telemetry/cycle.PRED_FAMILIES)
                for one attempt against the WINDOW-START state — the
                wave-view analog of the sequential TEL block. ports and
                pod_affinity are structurally zero: both force W == 1."""
                t = jnp.maximum(t_idx, 0)
                resreq = tasks.resreq[t]
                gpu_req = tasks.gpu_request[t]
                live = node_live
                future = jnp.maximum(
                    idle0 + nodes.releasing - nodes.pipelined - pipe0, 0.0)
                fit2 = jnp.all(
                    resreq[None, None, :]
                    <= jnp.stack([idle0, future]) + 1e-5, axis=-1)
                blk_row = ((extras.block_nonrevocable
                            & ~extras.task_revocable[t])
                           | extras.block_all)
                vol_row = (extras.task_volume_ok[t]
                           & ((extras.task_volume_node[t] < 0)
                              | (iota_n == extras.task_volume_node[t])))
                lock_row = (extras.node_locked
                            & ~(ji == extras.target_job))
                return jnp.stack([
                    P.rejection_count(live, tmpl_static[tasks.template[t]]),
                    P.rejection_count(live, ~blk_row),
                    P.rejection_count(live, or_ok_row(t)),
                    P.rejection_count(live, vol_row),
                    P.rejection_count(live, ~lock_row),
                    jnp.int32(0),                  # ports: forces W == 1
                    P.rejection_count(
                        live, P.pod_count_fit(nodes, pods0)),
                    P.rejection_count(
                        live, P.gpu_fit(gpu_req, nodes, gpux0)),
                    P.rejection_count(live, fit2[0]),
                    P.rejection_count(live, fit2[1]),
                    jnp.int32(0),                  # affinity: forces W == 1
                ])

            def _wave_sweep1(t_idx, ji, idle0, pipe0, pods0, gpux0):
                """Pre-wave full-N sweep for ONE slot: the task_step
                feasibility conjunction and score fold, op-for-op, against
                the window-start snapshot — reduced per capacity view to
                the top-WC candidate list plus feasible count and raw tie
                count at the best."""
                t = jnp.maximum(t_idx, 0)
                resreq = tasks.resreq[t]
                gpu_req = tasks.gpu_request[t]
                future = jnp.maximum(
                    idle0 + nodes.releasing - nodes.pipelined - pipe0, 0.0)
                node_ok = (~(extras.block_nonrevocable
                             & ~extras.task_revocable[t])
                           & ~extras.block_all
                           & or_ok_row(t)
                           & extras.task_volume_ok[t]
                           & ((extras.task_volume_node[t] < 0)
                              | (iota_n == extras.task_volume_node[t]))
                           & (~extras.node_locked
                              | (ji == extras.target_job))
                           & tmpl_static[tasks.template[t]])
                shared = node_ok & P.pod_count_fit(nodes, pods0)
                shared &= P.gpu_fit(gpu_req, nodes, gpux0)
                fit2 = jnp.all(
                    resreq[None, None, :]
                    <= jnp.stack([idle0, future]) + 1e-5, axis=-1)
                feas_now = shared & fit2[0]
                feas_fut = shared & fit2[1]
                score = _score_fn(cfg, snap, resreq, idle0,
                                  tasks.tol_hash[t], tasks.tol_effect[t],
                                  tasks.tol_mode[t])
                score += (extras.template_na_score[tasks.template[t]]
                          + jnp.where(extras.task_revocable[t],
                                      extras.tdm_bonus, 0.0))
                score += S.node_preference_score(
                    extras.task_pref_node[t], score.shape[0])

                def topc(feas):
                    masked0 = jnp.where(feas, score, NEGf)
                    best0 = jnp.max(masked0)
                    tie = jnp.sum((masked0 == best0) & feas,
                                  dtype=jnp.int32)
                    n_f = jnp.sum(feas, dtype=jnp.int32)
                    # Blocked iterative extraction: one O(N) block-max
                    # reduce, then WC rounds touching only the winning
                    # block — O(N/NB + NB) each instead of a full-N
                    # argmax pass per entry (the naive WC*N form made
                    # W > 2 a net LOSS at bench scale). -inf masking
                    # keeps the list feasible-only; entry order is still
                    # (score desc, global index asc): lowest block at
                    # the max, then lowest in-block index at the max.
                    # Max is exact over f32, so entry values are bitwise
                    # what the full-N pass produced.
                    ninf = jnp.float32(-jnp.inf)
                    m2 = jnp.where(feas, score, ninf).reshape(N // NB, NB)
                    bm = jnp.max(m2, axis=1)                  # [N/NB]
                    iota_nb = jnp.arange(NB, dtype=jnp.int32)
                    iota_blk = jnp.arange(N // NB, dtype=jnp.int32)
                    e_i, e_v, e_o = [], [], []
                    for _ in range(WC):
                        best = jnp.max(bm)
                        # first-index-at-max via where+min keeps every
                        # index intermediate i32 (argmax mints i64
                        # indices under the x64 audit trace)
                        blk = jnp.min(jnp.where(bm == best, iota_blk,
                                                jnp.int32(N // NB)))
                        row = jax.lax.dynamic_index_in_dim(
                            m2, blk, 0, keepdims=False)       # [NB]
                        within = jnp.min(jnp.where(row == best, iota_nb,
                                                   jnp.int32(NB)))
                        found = best > ninf      # any feasible remaining
                        e_i.append(jnp.where(found, blk * NB + within,
                                             jnp.int32(N)))
                        e_v.append(best)
                        e_o.append(found)
                        row = jnp.where(iota_nb == within, ninf, row)
                        m2 = jax.lax.dynamic_update_index_in_dim(
                            m2, row, blk, 0)
                        bm = bm.at[blk].set(jnp.max(row))
                    return (jnp.stack(e_i), jnp.stack(e_v),
                            jnp.stack(e_o), n_f, tie)

                ein, evn, eon, cntn, tien = topc(feas_now)
                eif, evf, eof, cntf, tief = topc(feas_fut)
                return (ein, evn, eon, cntn, tien,
                        eif, evf, eof, cntf, tief)

            def _wave_rescore(t_idx, ji, rows, idle, pipe_extra,
                              pods_extra, gpu_extra):
                """Exact row-gathered re-evaluation of feasibility + score
                at the CURRENT mid-wave state for the given node rows —
                bitwise-equal to the full-N sweep restricted to those rows
                (see the block comment above). O(len(rows) * R); rows may
                carry the N sentinel (caller masks those results)."""
                t = jnp.maximum(t_idx, 0)
                r = jnp.minimum(jnp.maximum(rows, 0), N - 1)
                resreq = tasks.resreq[t]
                gpu_req = tasks.gpu_request[t]
                idle_r = idle[r]                              # [C, R]
                alloc_r = nodes.allocatable[r]
                fut_r = jnp.maximum(
                    idle_r + nodes.releasing[r] - nodes.pipelined[r]
                    - pipe_extra[r], 0.0)
                node_ok = (~(extras.block_nonrevocable[r]
                             & ~extras.task_revocable[t])
                           & ~extras.block_all[r]
                           & or_ok_row(t)[r]
                           & extras.task_volume_ok[t]
                           & ((extras.task_volume_node[t] < 0)
                              | (r == extras.task_volume_node[t]))
                           & (~extras.node_locked[r]
                              | (ji == extras.target_job))
                           & tmpl_static[tasks.template[t]][r])
                pods_ok = (nodes.pod_count[r] + pods_extra[r]
                           < nodes.max_pods[r])
                gidle_r = (nodes.gpu_memory[r] - nodes.gpu_used[r]
                           - gpu_extra[r])
                gpu_ok = (gpu_req <= 0) | jnp.any(
                    gidle_r >= gpu_req - 1e-5, axis=-1)
                fit_now = jnp.all(resreq[None, :] <= idle_r + 1e-5,
                                  axis=-1)
                fit_fut = jnp.all(resreq[None, :] <= fut_r + 1e-5,
                                  axis=-1)
                # _score_fn's weighted fold, row-shaped, same f32 order;
                # taint-prefer from the static per-template map
                used_r = alloc_r - idle_r
                rw = jnp.ones_like(resreq)
                s = jnp.zeros(r.shape[0], jnp.float32)
                if cfg.binpack_weight:
                    s += cfg.binpack_weight * S.binpack_score(
                        used_r, alloc_r, resreq, rw)
                if cfg.least_allocated_weight:
                    s += cfg.least_allocated_weight \
                        * S.least_allocated_score(used_r, alloc_r, resreq)
                if cfg.most_allocated_weight:
                    s += cfg.most_allocated_weight \
                        * S.most_allocated_score(used_r, alloc_r, resreq)
                if cfg.balanced_weight:
                    s += cfg.balanced_weight \
                        * S.balanced_allocation_score(used_r, alloc_r,
                                                      resreq)
                if cfg.taint_prefer_weight:
                    s += tp_static[tasks.template[t]][r]
                s += (extras.template_na_score[tasks.template[t]][r]
                      + jnp.where(extras.task_revocable[t],
                                  extras.tdm_bonus[r], 0.0))
                pref = extras.task_pref_node[t]
                s += jnp.where((pref >= 0) & (r == pref),
                               jnp.float32(100.0), jnp.float32(0.0))
                ok_shared = node_ok & pods_ok & gpu_ok
                return ok_shared & fit_now, ok_shared & fit_fut, s

            def _wave_resolve(e_i, e_v, e_o, cnt, touched, t_ok, t_s):
                """Winner of the full-N argmax at the current state, from
                the slot's pre-wave top-C list plus the rescored touched
                rows — or decidable=False when the list is exhausted (wave
                truncation). Tie-break is lowest global index at the max,
                exactly select.best_node's."""
                unt = e_o & ~jnp.any(
                    e_i[:, None] == touched[None, :], axis=1)
                has_unt = jnp.any(unt)
                fc = jax.lax.argmax(unt, 0, jnp.int32)
                tset = touched < N
                cand_i = jnp.concatenate([e_i[fc][None], touched])
                cand_v = jnp.concatenate([e_v[fc][None], t_s])
                cand_ok = jnp.concatenate([has_unt[None], tset & t_ok])
                decidable = has_unt | (cnt <= WC)
                mv = jnp.where(cand_ok, cand_v, NEGf)
                mx = jnp.max(mv)
                at = cand_ok & (mv == mx)
                win = jnp.min(jnp.where(at, cand_i, jnp.int32(N)))
                found = jnp.any(cand_ok)
                win = jnp.where(found, win, jnp.int32(0))
                return win, found, decidable

            if shard_pl:
                # wide shard-local sweep: one kernel launch scores all W
                # columns against this shard's rows, the cross-shard merge
                # rebuilds the global top-C per column (the global c-th
                # best row is always within its own shard's top-c)
                from .pallas_place import make_shard_wave_placer
                _wcand = make_shard_wave_placer(cfg, NL_sh, R, G, GR,
                                                W, WC, interpret=interp)

                def _wcand_region(*flat):
                    it = iter(flat)
                    rr = next(it)
                    gq = next(it) if cfg.enable_gpu else None
                    scal = [next(it) for _ in range(7)]
                    env = [next(it) for _ in range(len(env_sh))]
                    idle_s = next(it)             # [NL, R]
                    pipe_s = next(it)             # [NL, R]
                    pods_s = next(it)             # [NL] i32
                    gpux_s = next(it) if cfg.enable_gpu else None
                    off = (jax.lax.axis_index(axis)
                           * jnp.int32(NL_sh)).astype(
                               jnp.int32).reshape(1, 1)
                    args = [rr]
                    if cfg.enable_gpu:
                        args.append(gq)
                    args += scal + [off] + env
                    args += [idle_s.T, pipe_s.T,
                             pods_s.astype(jnp.float32)[None, :]]
                    if cfg.enable_gpu:
                        args.append(gpux_s.T)
                    return _wcand(*args)

                _wcand_sm = shard_map(
                    _wcand_region, mesh=mesh,
                    in_specs=tuple([_PS()] * n_scal
                                   + [_PS(None, axis)] * len(env_sh)
                                   + state_specs),
                    out_specs=(_PS(axis, None),) * 8,
                    check_rep=False)

                def _wave_combine(sc_d, ix_d, cn_d, ti_d):
                    """Stacked per-shard lists ((D*C, W) entries, (D, W)
                    counts/ties) -> the global top-C per column, same
                    (score desc, global index asc) order as the scan
                    sweep, counts summed, raw ties summed across shards
                    sitting at the global max (the narrow _combine rule,
                    entry 0 being each shard's local best)."""
                    erow = jnp.tile(jnp.arange(WC, dtype=jnp.int32),
                                    D_sh)[:, None]            # [D*C, 1]
                    ok = erow < jnp.repeat(cn_d, WC, axis=0)
                    e_i, e_v, e_o = [], [], []
                    for _ in range(WC):
                        mv = jnp.where(ok, sc_d, NEGf)
                        mx = jnp.max(mv, axis=0)              # [W]
                        at = ok & (mv == mx[None, :])
                        fnd = jnp.any(ok, axis=0)
                        pick = jnp.min(jnp.where(at, ix_d, jnp.int32(N)),
                                       axis=0)
                        pick = jnp.where(fnd, pick, jnp.int32(N))
                        e_i.append(pick)
                        e_v.append(mx)
                        e_o.append(fnd)
                        ok = ok & (ix_d != pick[None, :])
                    cnt = jnp.sum(cn_d, axis=0, dtype=jnp.int32)
                    ties = jnp.sum(
                        jnp.where((cn_d > 0)
                                  & (sc_d[0::WC] == e_v[0][None, :]),
                                  ti_d, 0),
                        axis=0, dtype=jnp.int32)
                    return (jnp.stack(e_i, axis=1), jnp.stack(e_v, axis=1),
                            jnp.stack(e_o, axis=1), cnt, ties)

                def wave_sweep(ts, ji, idle0, pipe0, pods0, gpux0):
                    i32 = jnp.int32
                    tcl = jnp.maximum(ts, 0)
                    scal = [
                        extras.task_pref_node[tcl].astype(i32)[None, :],
                        jnp.maximum(tasks.template[tcl], 0)
                        .astype(i32)[None, :],
                        extras.task_or_group[tcl].astype(i32)[None, :],
                        extras.task_volume_node[tcl].astype(i32)[None, :],
                        extras.task_volume_ok[tcl].astype(i32)[None, :],
                        extras.task_revocable[tcl].astype(i32)[None, :],
                        jnp.broadcast_to(
                            (ji == extras.target_job).astype(i32), (1, W)),
                    ]
                    args = [tasks.resreq[tcl].T]              # [R, W]
                    if cfg.enable_gpu:
                        args.append(tasks.gpu_request[tcl]
                                    .astype(jnp.float32)[None, :])
                    args += scal + env_sh
                    args += [idle0, pipe0, pods0]
                    if cfg.enable_gpu:
                        args.append(gpux0)
                    (sc_n, ix_n, cn_n, ti_n,
                     sc_f, ix_f, cn_f, ti_f) = _wcand_sm(*args)
                    return (*_wave_combine(sc_n, ix_n, cn_n, ti_n),
                            *_wave_combine(sc_f, ix_f, cn_f, ti_f))
            else:
                def wave_sweep(ts, ji, idle0, pipe0, pods0, gpux0):
                    return jax.vmap(
                        lambda t: _wave_sweep1(t, ji, idle0, pipe0,
                                               pods0, gpux0))(ts)

        def hopeless_jobs(st, elig):
            """bool[J]: eligible jobs whose CHEAPEST pending request exceeds
            the per-dim maximum of every node's idle AND future idle — no
            task of theirs can place or pipeline now, and capacity is
            non-increasing across rounds (a gang discard restores at most a
            later state), so their eventual pop is guaranteed to fail.
            Marking them done+popped in one round is decision-identical to
            paying a round each; the tail of a saturated cycle collapses
            from O(jobs) rounds to one."""
            if use_pallas:
                idle_t = st["idle"]                           # [R, N]
                fut_t = jnp.maximum(
                    idle_t + relmp_t - st["pipe_extra"], 0.0)
                live = node_live[None, :]
                bound = jnp.max(
                    jnp.where(live, jnp.maximum(idle_t, fut_t), -jnp.inf),
                    axis=1)                                   # [R]
            else:
                idle_a = st["idle"]                           # [N, R]
                fut_a = jnp.maximum(
                    idle_a + nodes.releasing - nodes.pipelined
                    - st["pipe_extra"], 0.0)
                live = node_live[:, None]
                bound = jnp.max(
                    jnp.where(live, jnp.maximum(idle_a, fut_a), -jnp.inf),
                    axis=0)                                   # [R]
            return elig & jnp.any(jobs_min_req > bound + 1e-5, axis=-1)

        def body(st):
            elig = eligible(st)
            give_up = jax.lax.cond(
                st["progressed"],
                lambda: jnp.zeros(J, bool),
                lambda: hopeless_jobs(st, elig))

            # ---- job selection: lexicographic pop of ns->queue->job PQs ----
            # Queue share: max over dims of allocated/deserved (proportion
            # queueOrderFn, proportion.go:198-212); neutral when deserved=inf.
            qshare = jnp.max(
                jnp.where(jnp.isfinite(queue_deserved) & (queue_deserved > 0),
                          st["queue_allocated"] / jnp.maximum(queue_deserved, 1e-9),
                          0.0),
                axis=-1) + extras.queue_share_extra
            job_q = jobs.queue
            job_ns = jobs.namespace
            # drf keys recomputed from live allocations when the plugin's
            # event handlers would have updated them (drf.go:511-536)
            if cfg.drf_ns_order:
                ns_share_k = namespace_shares(
                    st["job_alloc_dyn"], job_ns, jobs.valid,
                    snap.namespace_weight, total_cap)
            else:
                ns_share_k = ns_share
            if cfg.drf_job_order:
                job_share_k = drf_job_shares(st["job_alloc_dyn"], total_cap,
                                             jobs.valid)
            else:
                job_share_k = job_share
            ready_dyn = jobs.ready_num + st["job_alloc_count"]
            ready_now = (ready_dyn >= jobs.min_available) & (jobs.min_available > 0)
            keys = [
                ns_share_k[job_ns],                  # namespace order (drf ns fairness)
                job_ns.astype(jnp.float32),          # namespace tie-break (by name)
                qshare[job_q],                       # queue order (proportion)
            ]
            if cfg.enable_hdrf:
                # hdrf compareQueues walk as lexicographic level columns,
                # recomputed per pop from the live tree (drf.go:182-218)
                hcols = hdrf_level_keys(
                    extras.hierarchy, st["job_alloc_dyn"],
                    jobs.total_request, jobs.valid, total_cap)
                for c in range(int(hcols.shape[1])):
                    keys.append(hcols[:, c][job_q])
            keys += [
                job_q.astype(jnp.float32),           # queue tie-break
                -jobs.priority.astype(jnp.float32),  # priority plugin JobOrderFn
            ]
            if cfg.tdm_job_order:
                # tdm JobOrderFn: preemptable jobs sort later (tdm.go:261-273)
                keys.append(jobs.preemptable.astype(jnp.float32))
            if cfg.sla_job_order:
                # sla JobOrderFn: earliest deadline first (sla.go:104-131)
                keys.append(extras.job_deadline)
            keys += [
                ready_now.astype(jnp.float32),       # gang: ready jobs last
                job_share_k,                         # drf JobOrderFn
                jobs.creation_rank.astype(jnp.float32),  # FIFO fallback
            ]
            # Exact re-pop fusion: a ready job yields so jobs with better
            # keys get the next pop — but when every ordering key is STATIC
            # over this job's own commits, the same job wins the very next
            # pop, so the consecutive single-task pops collapse into one
            # batched round with bit-identical decisions. Keys are static
            # unless a drf/hdrf dynamic flag is on or the job's queue has a
            # finite proportion deserved (its qshare moves with commits).
            # The same static-keys argument makes K-job batching exact
            # (AllocateConfig.batch_jobs): the next K sequential pops are
            # the K lexicographically-smallest eligible jobs right now.
            keys_static = not (cfg.drf_job_order or cfg.drf_ns_order
                               or cfg.enable_hdrf)
            slots = jnp.arange(M, dtype=jnp.int32)

            if dyn:
                # ---- dynamic-key path: up to KP in-kernel pops over K
                # candidate jobs, fairness keys recomputed in VMEM after
                # every commit (pallas_place._dyn_kernel). Candidates are
                # this launch's K lexicographically-smallest eligible
                # jobs; the kernel stops early the moment the true next
                # pop is not one of them, so decisions replay the
                # sequential order exactly.
                jis = []
                elig_k = elig
                jidx = jnp.arange(J, dtype=jnp.int32)
                for _ in range(K):
                    ji_k, found_k = lex_argmin(keys, elig_k)
                    ji_k = jnp.where(found_k, ji_k, -1)
                    jis.append(ji_k)
                    elig_k = elig_k & (jidx != ji_k)
                ji_vec = jnp.stack(jis).astype(jnp.int32)        # [C]
                jsafe = jnp.maximum(ji_vec, 0)
                cslot = jnp.full(J, -1, jnp.int32).at[
                    jnp.where(ji_vec >= 0, jsafe, J)].set(
                    jnp.arange(K, dtype=jnp.int32), mode="drop")[None, :]
                task_ids = jobs.task_table[jsafe]                # [C, M]
                tcl = jnp.maximum(task_ids, 0)
                flat_ids = tcl.reshape(K * M)
                tid_ok = task_ids >= 0
                nbe = ~tasks.best_effort[tcl]
                # cursor-independent suffix: real non-best-effort slots
                # strictly after m (for attempted slots m >= cursor this
                # equals the scan path's open-slot suffix)
                nbreal = tid_ok & nbe
                rc = jnp.cumsum(nbreal[:, ::-1].astype(jnp.int32),
                                axis=1)[:, ::-1]
                suffix_after = rc - nbreal.astype(jnp.int32)
                args = [tasks.resreq[flat_ids].T]
                if cfg.enable_gpu:
                    args.append(tasks.gpu_request[flat_ids][None, :])
                args += [
                    extras.task_pref_node[flat_ids][None, :],
                    suffix_after.reshape(1, K * M),
                    jnp.maximum(tasks.template[flat_ids], 0)[None, :],
                    extras.task_or_group[flat_ids][None, :],
                    extras.task_volume_node[flat_ids][None, :],
                    extras.task_volume_ok[flat_ids][None, :]
                    .astype(jnp.int32),
                    extras.task_revocable[flat_ids][None, :]
                    .astype(jnp.int32),
                    tid_ok.reshape(1, K * M).astype(jnp.int32),
                    nbe.reshape(1, K * M).astype(jnp.int32),
                    ji_vec[None, :],
                    cslot,
                    skeys_mat,
                ]
                if cfg.enable_hdrf:
                    # frozen per-launch hdrf columns (guarded in-kernel)
                    args.append(jnp.stack(
                        [hcols[:, c][job_q]
                         for c in range(int(hcols.shape[1]))]
                    ).astype(jnp.float32))
                kp_req = jnp.minimum(jnp.int32(KP),
                                     max_rounds - st["rounds"]) \
                    .astype(jnp.int32)
                args += [qid_row, qoh_mat] + ns_args + [
                    minav_row, rdy0_row, npend_row, eligs_row,
                    validf_row, canb_row, queue_deserved, qex_col,
                    total_col,
                    kp_req.reshape(1, 1),
                    tgt_in,
                ]
                args += node_env_args()
                args += aff_static_args + aff_slot_args(flat_ids)
                args += [st["idle"], st["pipe_extra"], st["pods_extra"]]
                if cfg.enable_gpu:
                    args.append(st["gpu_extra"])
                args += aff_state_args(st)
                done_in = st["job_done"] | give_up
                popped_in = st["job_popped"] | give_up
                args += [
                    done_in.astype(jnp.int32)[None, :],
                    popped_in.astype(jnp.int32)[None, :],
                    st["job_ready"].astype(jnp.int32)[None, :],
                    st["job_pipelined"].astype(jnp.int32)[None, :],
                    st["job_cursor"][None, :],
                    st["job_alloc_count"][None, :],
                    st["job_alloc_dyn"].T,
                    st["queue_allocated"],
                ]
                outs = placer(*args)
                node_s, mode_s, gpu_s = outs[0][0], outs[1][0], outs[2][0]
                idle, pipe_extra, pods_extra = outs[3], outs[4], outs[5]
                o = 6
                if cfg.enable_gpu:
                    gpu_extra = outs[o]
                    o += 1
                else:
                    gpu_extra = st["gpu_extra"]
                aff_upd = {}
                if cfg.enable_pod_affinity:
                    aff_upd = dict(aff_cnt=outs[o], aff_tot=outs[o + 1],
                                   anti_cnt=outs[o + 2])
                    o += 3
                (done_o, popped_o, ready_o, pipe_o, cursor_o, acount_o,
                 jalloc_o, qalloc_o, pops_o, prog_o) = outs[o:o + 10]
                node_km = node_s.reshape(K, M)
                mode_km = mode_s.reshape(K, M)
                gpu_km = gpu_s.reshape(K, M)
                placed_m = mode_km != MODE_NONE
                widx = jnp.where(tid_ok & placed_m, task_ids, T)
                wflat = widx.reshape(K * M)
                t_node = st["task_node"].at[wflat].set(
                    node_km.reshape(K * M), mode="drop")
                t_mode = st["task_mode"].at[wflat].set(
                    mode_km.reshape(K * M), mode="drop")
                t_gpu = st["task_gpu"].at[wflat].set(
                    gpu_km.reshape(K * M), mode="drop")
                tel_upd = {}
                if TEL:
                    # wrapper-visible dyn-kernel stats: the kernel already
                    # commits/discards internally, so counts here are
                    # COMMITTED placements only (per-family rejections stay
                    # kernel-internal on the pallas paths); the "newly"
                    # guard keeps re-reported slots from double-counting
                    t0 = st["telemetry"]
                    from .pallas_place import dyn_launch_stats
                    pops_inc, early = dyn_launch_stats(pops_o[0, 0], kp_req)
                    prev = st["task_mode"][tcl]
                    newly = tid_ok & (mode_km != MODE_NONE) \
                        & (prev == MODE_NONE)
                    n_new_a = jnp.sum(newly & (mode_km == MODE_ALLOCATED),
                                      dtype=jnp.int32)
                    n_new_p = jnp.sum(newly & (mode_km == MODE_PIPELINED),
                                      dtype=jnp.int32)
                    com_new = jnp.sum(
                        jnp.where(newly[:, :, None], tasks.resreq[tcl],
                                  jnp.float32(0.0)), axis=(0, 1))
                    tel_upd["telemetry"] = dataclasses.replace(
                        t0,
                        placed_now=t0.placed_now + n_new_a,
                        placed_future=t0.placed_future + n_new_p,
                        committed=t0.committed + com_new,
                        rounds=t0.rounds + jnp.int32(1),
                        pops=t0.pops + pops_inc,
                        dyn_launches=t0.dyn_launches + jnp.int32(1),
                        dyn_pops=t0.dyn_pops + pops_inc,
                        dyn_early_stops=t0.dyn_early_stops + early)
                return dict(
                    idle=idle, pipe_extra=pipe_extra,
                    pods_extra=pods_extra, gpu_extra=gpu_extra,
                    task_node=t_node, task_mode=t_mode, task_gpu=t_gpu,
                    **aff_upd,
                    **tel_upd,
                    job_done=done_o[0] > 0,
                    job_popped=popped_o[0] > 0,
                    job_ready=ready_o[0] > 0,
                    job_pipelined=pipe_o[0] > 0,
                    job_cursor=cursor_o[0],
                    job_alloc_count=acount_o[0],
                    job_alloc_dyn=jalloc_o.T,
                    queue_allocated=qalloc_o,
                    # pop-0 forcing guarantees >= 1 pop per launch; the
                    # maximum is a belt-and-braces termination bound
                    rounds=st["rounds"] + jnp.maximum(pops_o[0, 0], 1),
                    progressed=prog_o[0, 0] > 0,
                )

            if use_pallas:
                # ---- K batched pops, one fused kernel launch -------------
                jis = []
                elig_k = elig
                jidx = jnp.arange(J, dtype=jnp.int32)
                for _ in range(K):
                    ji_k, found_k = lex_argmin(keys, elig_k)
                    ji_k = jnp.where(found_k, ji_k, -1)
                    jis.append(ji_k)
                    elig_k = elig_k & (jidx != ji_k)
                ji_vec = jnp.stack(jis).astype(jnp.int32)        # [K]
                secact = ji_vec >= 0
                jsafe = jnp.maximum(ji_vec, 0)
                task_ids = jobs.task_table[jsafe]                # [K, M]
                tcl = jnp.maximum(task_ids, 0)
                curs = st["job_cursor"][jsafe]
                open_slot = ((task_ids >= 0)
                             & (slots[None, :] >= curs[:, None]))
                nb = open_slot & ~tasks.best_effort[tcl]
                rc = jnp.cumsum(nb[:, ::-1].astype(jnp.int32),
                                axis=1)[:, ::-1]
                suffix_after = rc - nb.astype(jnp.int32)
                ready0_vec = (jobs.ready_num[jsafe]
                              + st["job_alloc_count"][jsafe])
                minav_vec = jobs.min_available[jsafe]
                if keys_static:
                    # ANY finite deserved (including 0) disqualifies: a
                    # commit can flip the queue overused (allocated >
                    # deserved + eps), which the sequential order re-checks
                    # before every pop
                    des_rows = queue_deserved[jobs.queue[jsafe]]  # [K, R]
                    canb_vec = ~jnp.any(jnp.isfinite(des_rows), axis=1)
                else:
                    canb_vec = jnp.zeros(K, bool)
                # Self-protection for a mis-set batch_jobs: section k runs
                # this round only if every EARLIER section's commits are
                # provably inert to ordering/eligibility (its can_batch
                # holds). Deactivated sections stay eligible and pop on
                # later rounds, restoring the exact sequential order.
                if K > 1:
                    prefix_ok = jnp.concatenate([
                        jnp.ones(1, bool),
                        jnp.cumprod(canb_vec[:-1].astype(jnp.int32)
                                    ).astype(bool)])
                    secact = secact & prefix_ok
                istgt = ji_vec == extras.target_job

                flat_ids = tcl.reshape(K * M)
                args = [tasks.resreq[flat_ids].T]
                if cfg.enable_gpu:
                    args.append(tasks.gpu_request[flat_ids][None, :])
                args += [
                    extras.task_pref_node[flat_ids][None, :],
                    suffix_after.reshape(1, K * M),
                    # clamped: padded slots carry template -1, and the
                    # kernel reads rows with a dynamic sublane slice
                    jnp.maximum(tasks.template[flat_ids], 0)[None, :],
                    extras.task_or_group[flat_ids][None, :],
                    extras.task_volume_node[flat_ids][None, :],
                    extras.task_volume_ok[flat_ids][None, :]
                    .astype(jnp.int32),
                    extras.task_revocable[flat_ids][None, :]
                    .astype(jnp.int32),
                    nb.reshape(1, K * M).astype(jnp.int32),
                    ready0_vec[None, :], minav_vec[None, :],
                    canb_vec[None, :].astype(jnp.int32),
                    secact[None, :].astype(jnp.int32),
                    istgt[None, :].astype(jnp.int32),
                ]
                args += node_env_args()
                args += aff_static_args + aff_slot_args(flat_ids)
                args += [st["idle"], st["pipe_extra"], st["pods_extra"]]
                if cfg.enable_gpu:
                    args.append(st["gpu_extra"])
                args += aff_state_args(st)
                outs = placer(*args)
                node_s, mode_s, gpu_s = outs[0], outs[1], outs[2]
                idle, pipe_extra, pods_extra = outs[3], outs[4], outs[5]
                o = 6
                if cfg.enable_gpu:
                    gpu_extra = outs[o]
                    o += 1
                else:
                    gpu_extra = st["gpu_extra"]
                aff_upd = {}
                if cfg.enable_pod_affinity:
                    aff_upd = dict(aff_cnt=outs[o], aff_tot=outs[o + 1],
                                   anti_cnt=outs[o + 2])

                node_km = node_s.reshape(K, M)
                mode_km = mode_s.reshape(K, M)
                gpu_km = gpu_s.reshape(K, M)
                placed_m = mode_km != MODE_NONE
                n_alloc_vec = jnp.sum(mode_km == MODE_ALLOCATED,
                                      axis=1, dtype=jnp.int32)
                n_pipe_vec = jnp.sum(mode_km == MODE_PIPELINED,
                                     axis=1, dtype=jnp.int32)
                # gang flags from the kernel's (discard-cleared) modes:
                # a discarded section counts zero, reproducing the XLA
                # finalize's false flags; kept sections carry real counts
                if cfg.enable_gang:
                    ready_vec = (ready0_vec + n_alloc_vec) >= minav_vec
                else:
                    ready_vec = jnp.ones(K, bool)
                pipelined_vec = ((ready0_vec + n_alloc_vec + n_pipe_vec)
                                 >= minav_vec) & ~ready_vec
                keep_vec = ready_vec | pipelined_vec
                # kept-but-unready gangs hold capacity without binding:
                # demote Allocated -> Pipelined (session.go:317-330)
                demote = ((keep_vec & ~ready_vec)[:, None]
                          & (mode_km == MODE_ALLOCATED))
                mode_out = jnp.where(demote, MODE_PIPELINED, mode_km)
                widx = jnp.where((task_ids >= 0) & placed_m, task_ids, T)
                wflat = widx.reshape(K * M)
                t_node = st["task_node"].at[wflat].set(
                    node_km.reshape(K * M), mode="drop")
                t_mode = st["task_mode"].at[wflat].set(
                    mode_out.reshape(K * M), mode="drop")
                t_gpu = st["task_gpu"].at[wflat].set(
                    gpu_km.reshape(K * M), mode="drop")

                # replay yield/break per section from the mode rows
                # (allocate.go:205-278)
                alloc_cum = jnp.cumsum((mode_km == MODE_ALLOCATED)
                                       .astype(jnp.int32), axis=1)
                if cfg.enable_gang:
                    ready_aft = ((ready0_vec[:, None] + alloc_cum)
                                 >= minav_vec[:, None])
                else:
                    ready_aft = jnp.ones((K, M), bool)
                stop_evt = (nb & placed_m & ready_aft
                            & (suffix_after > 0) & ~canb_vec[:, None])
                broke_evt = nb & ~placed_m
                first_stop = jnp.min(
                    jnp.where(stop_evt, slots[None, :], M), axis=1)
                first_broke = jnp.min(
                    jnp.where(broke_evt, slots[None, :], M), axis=1)
                stopped_vec = first_stop < first_broke
                broke_vec = (~stopped_vec) & (first_broke < M)
                boundary = jnp.where(stopped_vec | broke_vec,
                                     jnp.minimum(first_stop, first_broke),
                                     M - 1)
                n_adv = jnp.sum(
                    open_slot & (slots[None, :] <= boundary[:, None]),
                    axis=1, dtype=jnp.int32)
                committed = jnp.sum(
                    jnp.where(placed_m[:, :, None], tasks.resreq[tcl],
                              0.0), axis=1)                       # [K, R]

                jdrop = jnp.where(secact, jsafe, J)
                Q = st["queue_allocated"].shape[0]
                qdrop = jnp.where(secact, jobs.queue[jsafe], Q)
                tel_upd = {}
                if TEL:
                    # the kernel's mode rows are discard-cleared, so these
                    # are COMMITTED counts (kernel-internal discards and
                    # per-family rejections are not visible to the wrapper
                    # on the pallas paths — the scan path carries the full
                    # per-attempt detail)
                    t0 = st["telemetry"]
                    kept = secact & keep_vec
                    tel_upd["telemetry"] = dataclasses.replace(
                        t0,
                        placed_now=t0.placed_now + jnp.sum(
                            jnp.where(kept, n_alloc_vec, jnp.int32(0)),
                            dtype=jnp.int32),
                        placed_future=t0.placed_future + jnp.sum(
                            jnp.where(kept, n_pipe_vec, jnp.int32(0)),
                            dtype=jnp.int32),
                        committed=t0.committed + jnp.sum(
                            jnp.where(secact[:, None], committed,
                                      jnp.float32(0.0)), axis=0),
                        rounds=t0.rounds + jnp.int32(1),
                        pops=t0.pops + jnp.sum(secact, dtype=jnp.int32))
                return dict(
                    idle=idle, pipe_extra=pipe_extra,
                    pods_extra=pods_extra, gpu_extra=gpu_extra,
                    task_node=t_node, task_mode=t_mode, task_gpu=t_gpu,
                    **aff_upd,
                    **tel_upd,
                    job_done=(st["job_done"] | give_up).at[jdrop].set(
                        ~stopped_vec, mode="drop"),
                    job_popped=(st["job_popped"] | give_up).at[jdrop].set(
                        jnp.ones(K, bool), mode="drop"),
                    job_ready=st["job_ready"].at[jdrop].set(
                        ready_vec, mode="drop"),
                    job_pipelined=st["job_pipelined"].at[jdrop].set(
                        pipelined_vec, mode="drop"),
                    job_cursor=st["job_cursor"].at[jdrop].add(
                        n_adv, mode="drop"),
                    job_alloc_count=st["job_alloc_count"].at[jdrop].add(
                        jnp.where(keep_vec, n_alloc_vec, 0), mode="drop"),
                    job_alloc_dyn=st["job_alloc_dyn"].at[jdrop].add(
                        committed, mode="drop"),
                    queue_allocated=st["queue_allocated"].at[qdrop].add(
                        committed, mode="drop"),
                    rounds=st["rounds"] + 1,
                    progressed=(jnp.any(n_alloc_vec > 0)
                                | jnp.any(pipelined_vec)
                                | jnp.any(ready_vec)),
                )

            # ---- scan path: single pop ----------------------------------
            ji, _found = lex_argmin(keys, elig)

            task_ids = jobs.task_table[ji]           # i32[M]
            min_avail = jobs.min_available[ji]
            ready0 = jobs.ready_num[ji] + st["job_alloc_count"][ji]
            cur = st["job_cursor"][ji]
            if keys_static:
                # ANY finite deserved (including 0) disqualifies — see the
                # batched branch above; zero-quota queues flip overused on
                # the first commit
                des_row = queue_deserved[jobs.queue[ji]]
                can_batch = ~jnp.any(jnp.isfinite(des_row))
            else:
                can_batch = jnp.bool_(False)
            open_slot = (task_ids >= 0) & (slots >= cur)
            nb_row = open_slot & ~tasks.best_effort[jnp.maximum(task_ids, 0)]
            # real tasks remaining in the job's queue strictly after slot m
            # (the !tasks.Empty() side of the yield check, allocate.go:262)
            rc = jnp.cumsum(nb_row[::-1].astype(jnp.int32))[::-1]
            suffix_after = rc - nb_row.astype(jnp.int32)

            def task_step(carry, xs):
                if TEL:
                    carry, tel = carry
                (idle, pipe_extra, pods_extra, gpu_extra,
                 t_node, t_mode, t_gpu, n_alloc, n_pipe,
                 aff_cnt, anti_cnt, pe_node, pe_port, pe_cnt,
                 placed_sum, n_adv, stopped, broke) = carry
                t_idx, slot, suffix = xs
                can_run = ((t_idx >= 0) & (slot >= cur) & ~stopped & ~broke)
                active = can_run & ~tasks.best_effort[jnp.maximum(t_idx, 0)]
                t = jnp.maximum(t_idx, 0)
                resreq = tasks.resreq[t]
                gpu_req = tasks.gpu_request[t]
                sel = tasks.selector[t]
                th, te, tm = tasks.tol_hash[t], tasks.tol_effect[t], tasks.tol_mode[t]

                if shard_pl:
                    # sharded pallas: feasibility -> score -> argmax runs
                    # shard-local in the candidate kernel; only the
                    # combined winner returns here. Commits below stay in
                    # replicated XLA, bit-identical to the plain scan.
                    (n_now, found_now, tie_raw_now,
                     n_fut, found_fut, tie_raw_fut) = shard_candidates(
                         t, ji, idle, pipe_extra, pods_extra, gpu_extra)
                else:
                    future = jnp.maximum(
                        idle + nodes.releasing - nodes.pipelined
                        - pipe_extra, 0.0)
                    # tdm: active-window revocable nodes only admit tasks
                    # with a revocable zone; inactive-window revocable
                    # nodes admit nothing new (tdm.go:149-167);
                    # reservation: locked nodes only admit the elected
                    # target job (reserve.go:43-77).
                    node_ok = (~(extras.block_nonrevocable
                                 & ~extras.task_revocable[t])
                               & ~extras.block_all
                               & or_ok_row(t)
                               # volume-binding seam (cache.go:240-272)
                               & extras.task_volume_ok[t]
                               & ((extras.task_volume_node[t] < 0)
                                  | (jnp.arange(N, dtype=jnp.int32)
                                     == extras.task_volume_node[t]))
                               & (~extras.node_locked
                                  | (ji == extras.target_job))
                               & tmpl_static[tasks.template[t]])
                    if cfg.enable_host_ports:
                        # k8s NodePorts filter: conflicts against resident
                        # pods (static) and this cycle's placements (pe_*)
                        tp = extras.task_ports[t]                    # [HP]
                        act_p = tp > 0
                        stat_conf = jnp.any(
                            (extras.node_ports[:, :, None]
                             == tp[None, None, :])
                            & act_p[None, None, :]
                            & (extras.node_ports > 0)[:, :, None],
                            axis=(1, 2))
                        km = jnp.any((pe_port[:, None] == tp[None, :])
                                     & act_p[None, :], axis=1) \
                            & (pe_node >= 0)
                        dyn_conf = jnp.zeros(N, bool).at[
                            jnp.where(km, pe_node, N)].max(km, mode="drop")
                        node_ok &= ~(stat_conf | dyn_conf)
                    # shared (capacity-view-independent) terms computed
                    # once, the idle/future resource fit fused into one
                    # stacked comparison
                    shared = node_ok & P.pod_count_fit(nodes, pods_extra)
                    shared &= P.gpu_fit(gpu_req, nodes, gpu_extra)
                    fit2 = jnp.all(
                        resreq[None, None, :]
                        <= jnp.stack([idle, future]) + 1e-5, axis=-1)
                    feas_now = shared & fit2[0]
                    feas_fut = shared & fit2[1]
                    score = _score_fn(cfg, snap, resreq, idle, th, te, tm)
                    # static per-task extras in ONE addition so the pallas
                    # path can reproduce the exact f32 association:
                    # NodeAffinity preferred terms (nodeorder.go:255-266)
                    # + tdm's revocable steering bonus (tdm.go:170-191)
                    score += (extras.template_na_score[tasks.template[t]]
                              + jnp.where(extras.task_revocable[t],
                                          extras.tdm_bonus, 0.0))
                    # task-topology bucket preference (topology.go:344)
                    score += S.node_preference_score(
                        extras.task_pref_node[t], score.shape[0])
                    if cfg.enable_pod_affinity:
                        aff_feas, aff_score = _affinity_terms(
                            extras.affinity, aff_cnt, anti_cnt, t,
                            nodes.valid & nodes.schedulable)
                        feas_now &= aff_feas
                        feas_fut &= aff_feas
                        score += cfg.pod_affinity_weight * aff_score

                    n_now, found_now = best_node(score, feas_now)
                    n_fut, found_fut = best_node(score, feas_fut)
                can_now = found_now & active
                can_fut = found_fut & active & jnp.bool_(cfg.enable_pipelining)

                do_alloc = can_now
                do_pipe = ~can_now & can_fut
                placed = do_alloc | do_pipe
                node = jnp.where(do_alloc, n_now, n_fut)

                if TEL:
                    # Per-family rejection counts for this attempt, over
                    # live nodes, each family INDEPENDENT (see
                    # telemetry/cycle.PRED_FAMILIES). Masks are recomputed
                    # from the raw inputs (pre-placement capacity view) so
                    # the telemetry=False trace stays byte-identical; XLA
                    # CSE folds the duplicates on the telemetry=True build.
                    from .select import tie_count
                    acti = jnp.where(active, jnp.int32(1), jnp.int32(0))
                    live = node_live
                    if shard_pl:
                        # the decision path skipped the global fit masks;
                        # rebuild them here only for the counters (the
                        # telemetry=False trace carries none of this)
                        future = jnp.maximum(
                            idle + nodes.releasing - nodes.pipelined
                            - pipe_extra, 0.0)
                        fit2 = jnp.all(
                            resreq[None, None, :]
                            <= jnp.stack([idle, future]) + 1e-5, axis=-1)
                    tmpl_row = tmpl_static[tasks.template[t]]
                    blk_row = ((extras.block_nonrevocable
                                & ~extras.task_revocable[t])
                               | extras.block_all)
                    vol_row = (extras.task_volume_ok[t]
                               & ((extras.task_volume_node[t] < 0)
                                  | (jnp.arange(N, dtype=jnp.int32)
                                     == extras.task_volume_node[t])))
                    lock_row = (extras.node_locked
                                & ~(ji == extras.target_job))
                    if cfg.enable_host_ports:
                        ports_rej = P.rejection_count(
                            live, ~(stat_conf | dyn_conf))
                    else:
                        ports_rej = jnp.int32(0)
                    if cfg.enable_pod_affinity:
                        aff_rej = P.rejection_count(live, aff_feas)
                    else:
                        aff_rej = jnp.int32(0)
                    rej = jnp.stack([
                        P.rejection_count(live, tmpl_row),
                        P.rejection_count(live, ~blk_row),
                        P.rejection_count(live, or_ok_row(t)),
                        P.rejection_count(live, vol_row),
                        P.rejection_count(live, ~lock_row),
                        ports_rej,
                        P.rejection_count(
                            live, P.pod_count_fit(nodes, pods_extra)),
                        P.rejection_count(
                            live, P.gpu_fit(gpu_req, nodes, gpu_extra)),
                        P.rejection_count(live, fit2[0]),
                        P.rejection_count(live, fit2[1]),
                        aff_rej,
                    ])
                    if shard_pl:
                        # raw per-shard counts summed at the global max;
                        # tie_count's max(n - 1, 0) applied here
                        ties = jnp.where(
                            do_alloc,
                            jnp.maximum(tie_raw_now - 1, 0),
                            jnp.where(do_pipe,
                                      jnp.maximum(tie_raw_fut - 1, 0),
                                      jnp.int32(0)))
                    else:
                        ties = jnp.where(
                            do_alloc, tie_count(score, feas_now),
                            jnp.where(do_pipe, tie_count(score, feas_fut),
                                      jnp.int32(0)))
                    tel = (tel[0] + rej * acti,
                           tel[1] + acti,
                           tel[2] + jnp.where(do_alloc, jnp.int32(1),
                                              jnp.int32(0)),
                           tel[3] + jnp.where(do_pipe, jnp.int32(1),
                                              jnp.int32(0)),
                           tel[4] + ties)

                delta = jnp.where(do_alloc, jnp.float32(1.0),
                                  jnp.float32(0.0)) * resreq
                idle = idle.at[node].add(-delta)
                pipe_delta = jnp.where(do_pipe, jnp.float32(1.0),
                                       jnp.float32(0.0)) * resreq
                pipe_extra = pipe_extra.at[node].add(pipe_delta)
                pods_extra = pods_extra.at[node].add(
                    jnp.where(placed, jnp.int32(1), jnp.int32(0)))
                # shared-GPU card assignment: lowest fitting card on the chosen
                # node (predicateGPU, gpu.go:41-56), charged for the cycle
                card = P.pick_gpu_row(gpu_req, nodes.gpu_memory[node],
                                      nodes.gpu_used[node], gpu_extra[node])
                charge = placed & (card >= 0)
                gpu_extra = gpu_extra.at[node, jnp.maximum(card, 0)].add(
                    jnp.where(charge, gpu_req, 0.0))
                t_gpu = t_gpu.at[t].set(jnp.where(charge, card, t_gpu[t]))
                t_node = t_node.at[t].set(
                    jnp.where(placed, node, t_node[t]))
                t_mode = t_mode.at[t].set(
                    jnp.where(do_alloc, MODE_ALLOCATED,
                              jnp.where(do_pipe, MODE_PIPELINED, t_mode[t])))
                n_alloc += jnp.where(do_alloc, jnp.int32(1), jnp.int32(0))
                n_pipe += jnp.where(do_pipe, jnp.int32(1), jnp.int32(0))
                placed_sum = placed_sum + jnp.where(
                    placed, jnp.float32(1.0), jnp.float32(0.0)) * resreq
                n_adv += jnp.where(can_run, jnp.int32(1), jnp.int32(0))
                # yield: a ready job with tasks still queued re-enters the
                # job queue after each placement (allocate.go:262-265);
                # break: a task no node can take fails the whole job
                # (allocate.go:210-214 PredicateNodes empty)
                if cfg.enable_gang:
                    ready_aft = (ready0 + n_alloc) >= min_avail
                else:
                    ready_aft = jnp.bool_(True)
                stopped |= (active & placed & ready_aft & (suffix > 0)
                            & ~can_batch)
                broke |= active & ~placed
                if cfg.enable_pod_affinity:
                    aff_cnt, anti_cnt = _affinity_place_update(
                        extras.affinity, aff_cnt, anti_cnt, t, node, placed)
                if cfg.enable_host_ports:
                    # account the placed task's hostPorts (the AddPod event
                    # handler updating UsedPorts, predicates.go:224-239)
                    off = jnp.cumsum(act_p.astype(jnp.int32)) - act_p
                    widx = jnp.where(placed & act_p, pe_cnt + off,
                                     pe_node.shape[0])
                    pe_node = pe_node.at[widx].set(node, mode="drop")
                    pe_port = pe_port.at[widx].set(tp, mode="drop")
                    pe_cnt = pe_cnt + jnp.where(
                        placed, jnp.sum(act_p, dtype=jnp.int32),
                        jnp.int32(0))
                out = (idle, pipe_extra, pods_extra, gpu_extra,
                       t_node, t_mode, t_gpu, n_alloc, n_pipe,
                       aff_cnt, anti_cnt, pe_node, pe_port, pe_cnt,
                       placed_sum, n_adv, stopped, broke)
                if TEL:
                    out = (out, tel)
                return out, None

            carry0 = (st["idle"], st["pipe_extra"], st["pods_extra"],
                      st["gpu_extra"], st["task_node"], st["task_mode"],
                      st["task_gpu"], jnp.int32(0), jnp.int32(0),
                      st["aff_cnt"], st["anti_cnt"],
                      st["pe_node"], st["pe_port"], st["pe_cnt"],
                      jnp.zeros(R, jnp.float32), jnp.int32(0),
                      jnp.bool_(False), jnp.bool_(False))
            if TEL:
                tel0 = st["telemetry"]
                carry0 = (carry0, (tel0.pred_reject, tel0.attempts,
                                   tel0.placed_now, tel0.placed_future,
                                   tel0.argmax_ties))
            if W == 1:
                carry_fin, _ = jax.lax.scan(
                    task_step, carry0, (task_ids, slots, suffix_after),
                    unroll=min(int(M), 16))
                if TEL:
                    carry_fin, tel_fin = carry_fin
            else:
                # ---- wavefront section walk (ISSUE 16) -------------------
                # One while_loop over waves replaces the per-slot scan: a
                # batched pre-wave sweep of the next W slots, then a
                # Python-unrolled in-order commit pass (see the wavefront
                # block above for the exactness argument). The carry is the
                # scan's 18-tuple plus the window cursor (and the TEL
                # tuple + wave counters when telemetry is on).
                if TEL:
                    from ..telemetry.cycle import WAVE_BINS
                    carry0, wtel0 = carry0

                def _wave_cond(wst):
                    stopped, broke = wst["carry"][16], wst["carry"][17]
                    return (wst["pos"] < M) & ~stopped & ~broke

                def _wave_body(wst):
                    (idle, pipe_extra, pods_extra, gpu_extra,
                     t_node, t_mode, t_gpu, n_alloc, n_pipe,
                     aff_cnt, anti_cnt, pe_node, pe_port, pe_cnt,
                     placed_sum, n_adv, stopped, broke) = wst["carry"]
                    pos = wst["pos"]
                    widx = pos + jnp.arange(W, dtype=jnp.int32)
                    in_rng = widx < M
                    wslot = jnp.minimum(widx, M - 1)
                    t_w = jnp.where(in_rng, task_ids[wslot], -1)
                    suf_w = jnp.where(in_rng, suffix_after[wslot], 0)
                    (ein, evn, eon, cntn, tien,
                     eif, evf, eof, cntf, tief) = wave_sweep(
                         t_w, ji, idle, pipe_extra, pods_extra, gpu_extra)
                    if TEL:
                        whist, wcom, wtru, wrep, wnum = wst["wave"]
                        rej_w = jax.vmap(lambda t: _wave_rej1(
                            t, ji, idle, pipe_extra, pods_extra,
                            gpu_extra))(t_w)

                    # ---- optimistic batched commit --------------------
                    # The unrolled in-order commit below is exact but its
                    # per-slot cost is O(W) (rescore over the touched
                    # set), so the wave body grows O(W^2) and the CPU
                    # backend loses the whole sweep win past W=4. The
                    # common wave, though, is conflict-free, and its
                    # outcome is PREDICTABLE from the pre-wave entry
                    # lists in one of two shapes:
                    #   * heterogeneous slots — every slot's entry-0
                    #     differs: each slot commits its own entry-0;
                    #   * shared list (the spread-scoring canon: similar
                    #     tasks see the SAME node ordering) — slot w's
                    #     first w entries are exactly the earlier slots'
                    #     picks, so slot w commits its entry-w.
                    # Either way the predicted picks Pk are pairwise
                    # distinct, so each pick row's live state at any
                    # later slot equals its post-commit state (only its
                    # own slot touched it) — ONE batched rescore of all
                    # picks against all slots reproduces, bitwise, every
                    # per-slot rescore of the sequential walk.  The wave
                    # takes the batched branch of lax.cond only when
                    #   * all W slots are in-window, active,
                    #     non-best-effort, with a valid predicted entry
                    #     (untouched => dec_n holds),
                    #   * no earlier pick beats a later slot's predicted
                    #     entry at the rescored state (strictly, or by
                    #     the lower-node-index tie rule) => the resolve
                    #     winner IS the predicted entry for every slot,
                    #   * no mid-wave gang stop before the last slot
                    #     (a stop at the last slot lands in the carry,
                    #     exactly as the sequential walk leaves it);
                    # anything else replays through the sequential
                    # chain.  The batched state writes then touch the
                    # same rows with the same one-add deltas as the walk
                    # (f32 placed_sum still folds in slot order).
                    t_cl = jnp.maximum(t_w, 0)
                    iw = jnp.arange(W, dtype=jnp.int32)
                    eye_w = iw[:, None] == iw[None, :]
                    ltri = iw[None, :] < iw[:, None]    # [w, v]: v < w
                    d0 = ein[:, 0]
                    use0 = jnp.all(eon[:, 0]) & ~jnp.any(
                        (d0[:, None] == d0[None, :]) & ~eye_w)
                    if W <= WC:
                        shared = (jnp.all(ein[:, :W] == ein[0:1, :W])
                                  & jnp.all(eon[iw, iw]))
                        struct_ok = use0 | shared
                        Pk = jnp.where(use0, d0, ein[iw, iw])
                        EVp = jnp.where(use0, evn[:, 0], evn[iw, iw])
                    else:
                        # the shared-list shape needs W predicted
                        # entries per slot; the candidate depth only
                        # keeps WC < W of them
                        struct_ok = use0
                        Pk = d0
                        EVp = evn[:, 0]
                    req_all = tasks.resreq[t_cl]
                    gpu_all = tasks.gpu_request[t_cl]
                    idle_post = idle.at[Pk].add(-req_all)
                    pods_post = pods_extra.at[Pk].add(jnp.int32(1))
                    card0 = jax.vmap(P.pick_gpu_row)(
                        gpu_all, nodes.gpu_memory[Pk],
                        nodes.gpu_used[Pk], gpu_extra[Pk])
                    charge0 = card0 >= 0
                    gpux_post = gpu_extra.at[
                        Pk, jnp.maximum(card0, 0)].add(
                            jnp.where(charge0, gpu_all, 0.0))
                    okn2, _okf2, s2 = jax.vmap(
                        lambda tt: _wave_rescore(
                            tt, ji, Pk, idle_post, pipe_extra,
                            pods_post, gpux_post))(t_w)     # [W, W]
                    act_all = jnp.all((t_w >= 0)
                                      & ~tasks.best_effort[t_cl])
                    beat = okn2 & ((s2 > EVp[:, None])
                                   | ((s2 == EVp[:, None])
                                      & (Pk[None, :] < Pk[:, None])))
                    nobeat = ~jnp.any(beat & ltri)
                    if cfg.enable_gang:
                        ready_seq = (ready0 + n_alloc + jnp.int32(1)
                                     + jnp.arange(W, dtype=jnp.int32)
                                     ) >= min_avail
                    else:
                        ready_seq = jnp.ones((W,), jnp.bool_)
                    stop_seq = ready_seq & (suf_w > 0) & ~can_batch
                    nostop = ~jnp.any(stop_seq[:-1])
                    fast_ok = struct_ok & act_all & nobeat & nostop

                    cstate = (idle, pipe_extra, pods_extra, gpu_extra,
                              t_node, t_mode, t_gpu, n_alloc, n_pipe,
                              placed_sum, n_adv, stopped, broke)
                    if TEL:
                        cstate = cstate + (wst["tel"],)

                    def _commit_fast(state):
                        if TEL:
                            (idle, pipe_extra, pods_extra, gpu_extra,
                             t_node, t_mode, t_gpu, n_alloc, n_pipe,
                             placed_sum, n_adv, stopped, broke,
                             tel) = state
                        else:
                            (idle, pipe_extra, pods_extra, gpu_extra,
                             t_node, t_mode, t_gpu, n_alloc, n_pipe,
                             placed_sum, n_adv, stopped, broke) = state
                        idle = idle_post
                        pods_extra = pods_post
                        gpu_extra = gpux_post
                        t_gpu = t_gpu.at[t_cl].set(
                            jnp.where(charge0, card0, t_gpu[t_cl]))
                        t_node = t_node.at[t_cl].set(Pk)
                        t_mode = t_mode.at[t_cl].set(
                            jnp.full((W,), MODE_ALLOCATED,
                                     t_mode.dtype))
                        n_alloc = n_alloc + jnp.int32(W)
                        for w in range(W):      # f32 fold in slot order
                            placed_sum = placed_sum + req_all[w]
                        n_adv = n_adv + jnp.int32(W)
                        stopped = stopped | stop_seq[W - 1]
                        ret = (idle, pipe_extra, pods_extra, gpu_extra,
                               t_node, t_mode, t_gpu, n_alloc, n_pipe,
                               placed_sum, n_adv, stopped, broke,
                               pos + jnp.int32(W))
                        if TEL:
                            tel = (tel[0] + jnp.sum(rej_w, axis=0),
                                   tel[1] + jnp.int32(W),
                                   tel[2] + jnp.int32(W),
                                   tel[3],
                                   tel[4] + jnp.sum(
                                       jnp.maximum(
                                           tien - jnp.int32(1),
                                           jnp.int32(0)),
                                       dtype=jnp.int32))
                            wave_t = (
                                whist.at[min(W, WAVE_BINS - 1)].add(1),
                                wcom + jnp.int32(W), wtru, wrep,
                                wnum + jnp.int32(1))
                            ret = ret + (tel, wave_t)
                        return ret

                    def _commit_slow(state):
                        if TEL:
                            (idle, pipe_extra, pods_extra, gpu_extra,
                             t_node, t_mode, t_gpu, n_alloc, n_pipe,
                             placed_sum, n_adv, stopped, broke,
                             tel) = state
                            replays_w = jnp.int32(0)
                        else:
                            (idle, pipe_extra, pods_extra, gpu_extra,
                             t_node, t_mode, t_gpu, n_alloc, n_pipe,
                             placed_sum, n_adv, stopped, broke) = state
                        touched = jnp.full((W,), N, jnp.int32)
                        tcount = jnp.int32(0)
                        trunc = jnp.bool_(False)
                        trunc_pos = jnp.int32(W)
                        for w in range(W):
                            t_idx = t_w[w]
                            can_run = (t_idx >= 0) & ~stopped & ~broke
                            t = jnp.maximum(t_idx, 0)
                            resreq = tasks.resreq[t]
                            gpu_req = tasks.gpu_request[t]
                            active = can_run & ~tasks.best_effort[t]
                            trunc_pre = trunc
                            eligw = active & ~trunc
                            ok_n_t, ok_f_t, s_t = _wave_rescore(
                                t_idx, ji, touched, idle, pipe_extra,
                                pods_extra, gpu_extra)
                            win_n, fnd_n, dec_n = _wave_resolve(
                                ein[w], evn[w], eon[w], cntn[w], touched,
                                ok_n_t, s_t)
                            win_f, fnd_f, dec_f = _wave_resolve(
                                eif[w], evf[w], eof[w], cntf[w], touched,
                                ok_f_t, s_t)
                            do_alloc = eligw & dec_n & fnd_n
                            if cfg.enable_pipelining:
                                do_pipe = (eligw & dec_n & ~fnd_n
                                           & dec_f & fnd_f)
                                conflict = eligw & (~dec_n
                                                    | (dec_n & ~fnd_n
                                                       & ~dec_f))
                            else:
                                do_pipe = jnp.bool_(False)
                                conflict = eligw & ~dec_n
                            placed = do_alloc | do_pipe
                            node = jnp.where(do_alloc, win_n,
                                             jnp.where(do_pipe, win_f, 0))
                            brk = eligw & ~conflict & ~placed
                            proc = can_run & ~trunc_pre & ~conflict

                            if TEL:
                                acti_b = proc & active
                                acti = jnp.where(acti_b, jnp.int32(1),
                                                 jnp.int32(0))
                                ties = jnp.where(
                                    do_alloc,
                                    jnp.maximum(tien[w] - 1, 0),
                                    jnp.where(do_pipe,
                                              jnp.maximum(tief[w] - 1, 0),
                                              jnp.int32(0)))
                                tel = (tel[0] + rej_w[w] * acti,
                                       tel[1] + acti,
                                       tel[2] + jnp.where(do_alloc,
                                                          jnp.int32(1),
                                                          jnp.int32(0)),
                                       tel[3] + jnp.where(do_pipe,
                                                          jnp.int32(1),
                                                          jnp.int32(0)),
                                       tel[4] + ties)
                                replays_w += jnp.where(
                                    active & (trunc_pre | conflict),
                                    jnp.int32(1), jnp.int32(0))

                            # commit bookkeeping — masked exactly like task_step
                            delta = jnp.where(do_alloc, jnp.float32(1.0),
                                              jnp.float32(0.0)) * resreq
                            idle = idle.at[node].add(-delta)
                            pipe_delta = jnp.where(do_pipe, jnp.float32(1.0),
                                                   jnp.float32(0.0)) * resreq
                            pipe_extra = pipe_extra.at[node].add(pipe_delta)
                            pods_extra = pods_extra.at[node].add(
                                jnp.where(placed, jnp.int32(1), jnp.int32(0)))
                            card = P.pick_gpu_row(
                                gpu_req, nodes.gpu_memory[node],
                                nodes.gpu_used[node], gpu_extra[node])
                            charge = placed & (card >= 0)
                            gpu_extra = gpu_extra.at[
                                node, jnp.maximum(card, 0)].add(
                                    jnp.where(charge, gpu_req, 0.0))
                            t_gpu = t_gpu.at[t].set(
                                jnp.where(charge, card, t_gpu[t]))
                            t_node = t_node.at[t].set(
                                jnp.where(placed, node, t_node[t]))
                            t_mode = t_mode.at[t].set(
                                jnp.where(do_alloc, MODE_ALLOCATED,
                                          jnp.where(do_pipe, MODE_PIPELINED,
                                                    t_mode[t])))
                            n_alloc += jnp.where(do_alloc, jnp.int32(1),
                                                 jnp.int32(0))
                            n_pipe += jnp.where(do_pipe, jnp.int32(1),
                                                jnp.int32(0))
                            placed_sum = placed_sum + jnp.where(
                                placed, jnp.float32(1.0),
                                jnp.float32(0.0)) * resreq
                            # a truncated slot advances nothing: it replays at
                            # the head of the next wave's window
                            n_adv += jnp.where(proc, jnp.int32(1),
                                               jnp.int32(0))
                            if cfg.enable_gang:
                                ready_aft = (ready0 + n_alloc) >= min_avail
                            else:
                                ready_aft = jnp.bool_(True)
                            stopped |= (placed & ready_aft & (suf_w[w] > 0)
                                        & ~can_batch)
                            broke |= brk
                            touched = touched.at[
                                jnp.where(placed, tcount, jnp.int32(W))].set(
                                    node, mode="drop")
                            tcount += jnp.where(placed, jnp.int32(1),
                                                jnp.int32(0))
                            trunc_pos = jnp.where(conflict, jnp.int32(w),
                                                  trunc_pos)
                            trunc |= conflict

                        ret = (idle, pipe_extra, pods_extra, gpu_extra,
                               t_node, t_mode, t_gpu, n_alloc, n_pipe,
                               placed_sum, n_adv, stopped, broke,
                               pos + jnp.where(trunc, trunc_pos,
                                               jnp.int32(W)))
                        if TEL:
                            wave_t = (
                                whist.at[jnp.minimum(
                                    tcount, WAVE_BINS - 1)].add(1),
                                wcom + tcount,
                                wtru + jnp.where(trunc, jnp.int32(1),
                                                 jnp.int32(0)),
                                wrep + replays_w,
                                wnum + jnp.int32(1))
                            ret = ret + (tel, wave_t)
                        return ret

                    ret = jax.lax.cond(fast_ok, _commit_fast,
                                       _commit_slow, cstate)
                    (idle, pipe_extra, pods_extra, gpu_extra, t_node,
                     t_mode, t_gpu, n_alloc, n_pipe, placed_sum, n_adv,
                     stopped, broke, pos_new) = ret[:14]
                    out = dict(
                        carry=(idle, pipe_extra, pods_extra, gpu_extra,
                               t_node, t_mode, t_gpu, n_alloc, n_pipe,
                               aff_cnt, anti_cnt, pe_node, pe_port,
                               pe_cnt, placed_sum, n_adv, stopped, broke),
                        pos=pos_new)
                    if TEL:
                        out["tel"] = ret[14]
                        out["wave"] = ret[15]
                    return out

                wst0 = dict(carry=carry0, pos=cur)
                if TEL:
                    t0w = st["telemetry"]
                    wst0["tel"] = wtel0
                    wst0["wave"] = (t0w.wave_hist, t0w.wave_commits,
                                    t0w.wave_truncations,
                                    t0w.wave_replays, t0w.waves)
                wfin = jax.lax.while_loop(_wave_cond, _wave_body, wst0)
                carry_fin = wfin["carry"]
                if TEL:
                    tel_fin = wfin["tel"]
                    wave_fin = wfin["wave"]
            (idle, pipe_extra, pods_extra, gpu_extra, t_node, t_mode,
             t_gpu, n_alloc, n_pipe, aff_cnt, anti_cnt,
             pe_node, pe_port, pe_cnt, placed_sum,
             n_adv, stopped, broke) = carry_fin

            # ---- gang finalize: JobReady / JobPipelined / Discard ---------
            ready = (ready0 + n_alloc) >= min_avail
            pipelined = (ready0 + n_alloc + n_pipe) >= min_avail
            if not cfg.enable_gang:
                ready = jnp.bool_(True)
            keep = ready | pipelined

            # Discard = restore saved state and clear this job's placements
            # (statement.go:352-374 reverse-order undo, here a pure copy-back).
            job_tasks = tasks.job == ji
            idle = jnp.where(keep, idle, st["saved_idle"])
            pipe_extra = jnp.where(keep, pipe_extra, st["saved_pipe"])
            pods_extra = jnp.where(keep, pods_extra, st["saved_pods"])
            gpu_extra = jnp.where(keep, gpu_extra, st["saved_gpu"])
            aff_cnt = jnp.where(keep, aff_cnt, st["saved_aff"])
            anti_cnt = jnp.where(keep, anti_cnt, st["saved_anti"])
            pe_node = jnp.where(keep, pe_node, st["saved_pe_node"])
            pe_port = jnp.where(keep, pe_port, st["saved_pe_port"])
            pe_cnt = jnp.where(keep, pe_cnt, st["saved_pe_cnt"])
            t_node = jnp.where(keep | ~job_tasks, t_node,
                               jnp.full_like(t_node, -1))
            t_mode = jnp.where(keep | ~job_tasks, t_mode,
                               jnp.zeros_like(t_mode))
            t_gpu = jnp.where(keep | ~job_tasks, t_gpu,
                              jnp.full_like(t_gpu, -1))
            # A kept-but-unready gang holds capacity without binding: demote
            # its Allocated placements to Pipelined so MODE_ALLOCATED always
            # means "bind now" (the reference only dispatches binds on Commit
            # when JobReady, session.go:317-330).
            demote = keep & ~ready & job_tasks & (t_mode == MODE_ALLOCATED)
            t_mode = jnp.where(demote, MODE_PIPELINED, t_mode)

            # Commit promotes working state to saved (statement.go:377-395);
            # pipelined jobs also hold their capacity in-session.
            saved_idle = jnp.where(keep, idle, st["saved_idle"])
            saved_pipe = jnp.where(keep, pipe_extra, st["saved_pipe"])
            saved_pods = jnp.where(keep, pods_extra, st["saved_pods"])
            saved_gpu = jnp.where(keep, gpu_extra, st["saved_gpu"])
            saved_aff = jnp.where(keep, aff_cnt, st["saved_aff"])
            saved_anti = jnp.where(keep, anti_cnt, st["saved_anti"])
            saved_pe_node = jnp.where(keep, pe_node, st["saved_pe_node"])
            saved_pe_port = jnp.where(keep, pe_port, st["saved_pe_port"])
            saved_pe_cnt = jnp.where(keep, pe_cnt, st["saved_pe_cnt"])

            # queue + drf accounting for the ordering keys (event handlers
            # on Allocate/Pipeline, proportion.go:281-325, drf.go:511-536);
            # only this pop's placements count, and only when kept
            qi = jobs.queue[ji]
            committed = jnp.where(keep, jnp.float32(1.0),
                                  jnp.float32(0.0)) * placed_sum
            queue_allocated = st["queue_allocated"].at[qi].add(committed)

            tel_upd = {}
            if TEL:
                t0 = st["telemetry"]
                wave_kw = {}
                if W > 1:
                    # wave counters survive a gang discard: they measure
                    # the wave mechanics (the oracle mirrors this)
                    wave_kw = dict(wave_hist=wave_fin[0],
                                   wave_commits=wave_fin[1],
                                   wave_truncations=wave_fin[2],
                                   wave_replays=wave_fin[3],
                                   waves=wave_fin[4])
                tel_upd["telemetry"] = dataclasses.replace(
                    t0,
                    pred_reject=tel_fin[0],
                    attempts=tel_fin[1],
                    placed_now=tel_fin[2],
                    placed_future=tel_fin[3],
                    argmax_ties=tel_fin[4],
                    gang_discarded=t0.gang_discarded + jnp.where(
                        keep, jnp.int32(0), n_alloc + n_pipe),
                    committed=t0.committed + committed,
                    rounds=t0.rounds + jnp.int32(1),
                    pops=t0.pops + jnp.int32(1),
                    **wave_kw)

            return dict(
                **tel_upd,
                idle=idle, pipe_extra=pipe_extra, pods_extra=pods_extra,
                gpu_extra=gpu_extra,
                saved_idle=saved_idle, saved_pipe=saved_pipe,
                saved_pods=saved_pods, saved_gpu=saved_gpu,
                aff_cnt=aff_cnt, anti_cnt=anti_cnt,
                saved_aff=saved_aff, saved_anti=saved_anti,
                pe_node=pe_node, pe_port=pe_port, pe_cnt=pe_cnt,
                saved_pe_node=saved_pe_node, saved_pe_port=saved_pe_port,
                saved_pe_cnt=saved_pe_cnt,
                task_node=t_node, task_mode=t_mode, task_gpu=t_gpu,
                # a yielded (ready, queue non-empty) job is re-pushed; any
                # other outcome finishes it for the cycle; capacity-
                # hopeless jobs batch-finish alongside (give_up)
                job_done=(st["job_done"] | give_up).at[ji].set(~stopped),
                # attempted = popped at least once this cycle, even if a
                # later overused-queue gate or round cap cuts the job off
                # while job_done is still False (yield re-push pending)
                job_popped=(st["job_popped"] | give_up).at[ji].set(True),
                job_ready=st["job_ready"].at[ji].set(ready),
                job_pipelined=st["job_pipelined"].at[ji].set(
                    pipelined & ~ready),
                job_cursor=st["job_cursor"].at[ji].add(n_adv),
                job_alloc_count=st["job_alloc_count"].at[ji].add(
                    jnp.where(keep, n_alloc, 0)),
                job_alloc_dyn=st["job_alloc_dyn"].at[ji].add(committed),
                queue_allocated=queue_allocated,
                rounds=st["rounds"] + 1,
                progressed=(n_alloc > 0) | pipelined | ready,
            )

        final = jax.lax.while_loop(cond, body, init)
        if use_pallas:
            final["idle"] = final["idle"].T
        tel_final = None
        if TEL:
            # end-of-cycle unplaced-reason histogram (the TPU-native
            # unschedule_task_count{reason=...}): classify every pending
            # non-best-effort task that got no placement by its job's fate
            from ..api.types import TaskStatus
            tel_final = final["telemetry"]
            pend = (tasks.valid & ~tasks.best_effort & (tasks.job >= 0)
                    & (tasks.status == jnp.int32(int(TaskStatus.PENDING))))
            tjc = jnp.maximum(tasks.job, 0)
            popped = final["job_popped"][tjc]
            kept = (final["job_ready"] | final["job_pipelined"])[tjc]
            unplaced = pend & (final["task_mode"] == MODE_NONE)
            reason = jnp.where(~popped, jnp.int32(0),
                               jnp.where(kept, jnp.int32(2), jnp.int32(1)))
            n_r = tel_final.unplaced.shape[0]
            hist = jnp.zeros(n_r, jnp.int32).at[
                jnp.where(unplaced, reason, n_r)].add(
                jnp.int32(1), mode="drop")
            tel_final = dataclasses.replace(
                tel_final, unplaced=tel_final.unplaced + hist)
        return AllocateResult(
            task_node=final["task_node"],
            task_mode=final["task_mode"],
            task_gpu=final["task_gpu"],
            job_ready=final["job_ready"],
            job_pipelined=final["job_pipelined"],
            job_attempted=final["job_popped"],
            idle=final["idle"],
            queue_allocated=final["queue_allocated"],
            telemetry=tel_final,
        )

    return allocate
