"""Fused host->device snapshot transfer.

The axon TPU tunnel charges per-transfer latency, and a (snap, extras) pytree
is ~67 leaves — uploading them individually costs more than the bytes do.
This module flattens the pytree host-side into one buffer per dtype family
(f32 / i32 / bool), so a cycle pays 3 uploads, and rebuilds the tree with
static slices inside the jitted program (free: XLA sees constant offsets).

Used by bench.py and the sidecar for the production cycle path; the
per-bucket slice spec is static, so jit caches one program per shape bucket
exactly as before.
"""

from __future__ import annotations

from typing import Any, Callable, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

_GROUPS = ("f", "i", "b")


def _group_of(dtype) -> str:
    kind = np.dtype(dtype).kind
    if kind == "f":
        return "f"
    if kind in ("i", "u"):
        return "i"
    if kind == "b":
        return "b"
    raise TypeError(f"unsupported dtype {dtype}")


def fuse_spec(tree) -> Tuple[Any, List[Tuple[str, int, tuple, Any]]]:
    """(treedef, per-leaf (group, offset, shape, dtype)) for a pytree of
    arrays. Offsets are in elements within the group buffer."""
    leaves, treedef = jax.tree.flatten(tree)
    offsets = {g: 0 for g in _GROUPS}
    spec = []
    for leaf in leaves:
        arr = np.asarray(leaf)
        g = _group_of(arr.dtype)
        spec.append((g, offsets[g], arr.shape, arr.dtype))
        offsets[g] += arr.size
    return treedef, spec


def fuse(tree) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Host-side: pytree -> (f32 buffer, i32 buffer, bool buffer)."""
    leaves = jax.tree.leaves(tree)
    groups = {"f": [], "i": [], "b": []}
    for leaf in leaves:
        arr = np.asarray(leaf)
        g = _group_of(arr.dtype)
        target = {"f": np.float32, "i": np.int32, "b": np.bool_}[g]
        groups[g].append(np.ravel(arr).astype(target, copy=False))
    out = []
    for g in _GROUPS:
        out.append(np.concatenate(groups[g]) if groups[g]
                   else np.zeros(0, {"f": np.float32, "i": np.int32,
                                     "b": np.bool_}[g]))
    return tuple(out)


def make_unfuse(treedef, spec) -> Callable:
    """Device-side: (fbuf, ibuf, bbuf) -> pytree, via static slices."""

    def unfuse(fbuf, ibuf, bbuf):
        bufs = {"f": fbuf, "i": ibuf, "b": bbuf}
        leaves = []
        for g, off, shape, dtype in spec:
            size = int(np.prod(shape)) if shape else 1
            leaf = bufs[g][off:off + size].reshape(shape).astype(dtype)
            leaves.append(leaf)
        return jax.tree.unflatten(treedef, leaves)

    return unfuse


def make_fused_cycle(cycle_fn, example_tree):
    """Wrap a cycle over an argument tuple (e.g. (snap, extras) or the
    sidecar's (snap, hierarchy, base_extras)) into fn(fbuf, ibuf, bbuf)
    with the tree rebuilt on device. Returns (jitted_fn, fuse_inputs)."""
    treedef, spec = fuse_spec(example_tree)
    unfuse = make_unfuse(treedef, spec)

    def _cycle(fbuf, ibuf, bbuf):
        args = unfuse(fbuf, ibuf, bbuf)
        return cycle_fn(*args).packed_decisions()

    # trace-vs-call accounting (telemetry/tracecount): a retrace of the
    # fused cycle on the steady-state path is a production incident the
    # volcano_jit_* gauges must surface
    from ..telemetry import counted_jit
    fn = counted_jit(_cycle, "fused_cycle")

    return fn, fuse


def fused_cycle_cached(cycle_fn, tree, cache: dict, key_extra=None):
    """Shape-signature-memoized make_fused_cycle.

    The single implementation of the (key_extra, per-leaf shape/dtype) cache
    key used by both the Session (framework/session.py) and the sidecar
    (runtime/sidecar.py) so the two callers cannot drift."""
    leaves = jax.tree.leaves(tree)
    key = (key_extra, tuple((np.asarray(l).shape, np.asarray(l).dtype.str)
                            for l in leaves))
    hit = cache.get(key)
    if hit is None:
        hit = make_fused_cycle(cycle_fn, tree)
        cache[key] = hit
    return hit
