"""Fused host->device snapshot transfer, with device-resident deltas.

The axon TPU tunnel charges per-transfer latency, and a (snap, extras) pytree
is ~67 leaves — uploading them individually costs more than the bytes do.
This module flattens the pytree host-side into one buffer per dtype family
(f32 / i32 / bool), so a cycle pays 3 uploads, and rebuilds the tree with
static slices inside the jitted program (free: XLA sees constant offsets).

Two transfer paths share one offset spec (``fuse_spec``), so they cannot
drift:

- **Full** (:func:`fuse` + :func:`make_fused_cycle`): pack the whole tree
  into fresh group buffers and upload all three. Paid on the first cycle of
  a shape bucket and whenever the snapshot changed structurally.
- **Delta** (:class:`DeltaKernel` + :class:`ResidentState`): the three
  group buffers stay RESIDENT on the device across cycles. Each cycle the
  host packs the tree into a scratch buffer, diffs it against the mirror of
  what the device already holds, and ships only packed (indices, values)
  arrays per group; a jitted ``buf.at[idx].set(vals)`` scatter applies them
  in-graph before the cycle runs. Steady-state upload cost is O(changed
  elements) instead of O(N+T). On accelerator backends the resident
  buffers are DONATED through the update+cycle entry, so XLA scatters into
  them in place instead of churning fresh allocations (the CPU backend
  skips donation: XLA executes donated computations inline there, which
  would serialize the pipeline — see :func:`donation_for_backend`). The
  returned buffers become the new residents; consumed handles are
  invalidated within one dispatch (``.delete()``) so any host re-read
  fails fast on every backend — see docs/architecture.md "Steady-state
  pipeline" and the graphcheck ``donation`` family.

The value-level diff makes the delta path self-verifying: whatever the
session's incremental refresh touched (dirty jobs/nodes, queue rows,
aggregates, time-dependent extras), only elements whose packed value
actually changed upload, and a missed dirty mark is impossible by
construction — the diff runs against the mirror of device truth.

Used by the in-process Session, the sidecar, and bench.py; the per-bucket
slice spec is static, so jit caches one program per shape bucket exactly as
before (plus one program per delta-size bucket, bounded by the power-of-two
bucketing below).
"""

from __future__ import annotations

import warnings
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..chaos.inject import seam
from ..telemetry import spans as _spans

_GROUPS = ("f", "i", "b")
_TARGETS = {"f": np.float32, "i": np.int32, "b": np.bool_}

# A backend (or layout) that cannot alias a donated buffer ignores the
# donation and warns per call; the delta path donates unconditionally
# because the invalidation discipline below gives uniform fail-fast
# semantics whether or not the donation was honored.
warnings.filterwarnings(
    "ignore", message="Some donated buffers were not usable")

#: smallest non-empty delta bucket: deltas pad up to a power of two from
#: here so steady-state cycles reuse a handful of compiled programs instead
#: of retracing per delta size
_DELTA_MIN_BUCKET = 256


def _group_of(dtype) -> str:
    kind = np.dtype(dtype).kind
    if kind == "f":
        return "f"
    if kind in ("i", "u"):
        return "i"
    if kind == "b":
        return "b"
    raise TypeError(f"unsupported dtype {dtype}")


def fuse_spec(tree) -> Tuple[Any, List[Tuple[str, int, tuple, Any]]]:
    """(treedef, per-leaf (group, offset, shape, dtype)) for a pytree of
    arrays. Offsets are in elements within the group buffer. The single
    source of truth for BOTH the full and the delta transfer paths."""
    leaves, treedef = jax.tree.flatten(tree)
    offsets = {g: 0 for g in _GROUPS}
    spec = []
    for leaf in leaves:
        arr = np.asarray(leaf)
        g = _group_of(arr.dtype)
        spec.append((g, offsets[g], arr.shape, arr.dtype))
        offsets[g] += arr.size
    return treedef, spec


def group_sizes(spec) -> Tuple[int, int, int]:
    """Total elements per group buffer implied by a fuse_spec."""
    sizes = {g: 0 for g in _GROUPS}
    for g, off, shape, _dtype in spec:
        size = int(np.prod(shape)) if shape else 1
        sizes[g] = max(sizes[g], off + size)
    return tuple(sizes[g] for g in _GROUPS)


def fuse_into(tree, spec, sizes, out=None) -> Tuple[np.ndarray, ...]:
    """Pack ``tree`` into the three group buffers by filling slices from the
    shared spec. ``out`` reuses caller-owned buffers (the delta path's
    scratch); otherwise each group buffer is allocated ONCE and filled —
    no per-leaf ravel+astype copies, no ``np.concatenate``."""
    if out is None:
        out = tuple(np.empty(n, _TARGETS[g])
                    for g, n in zip(_GROUPS, sizes))
    bufs = dict(zip(_GROUPS, out))
    for leaf, (g, off, _shape, _dtype) in zip(jax.tree.leaves(tree), spec):
        arr = np.asarray(leaf)
        # ndarray assignment casts to the group target like astype did
        bufs[g][off:off + arr.size] = arr.ravel()
    return out


def fuse(tree) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Host-side: pytree -> (f32 buffer, i32 buffer, bool buffer)."""
    _treedef, spec = fuse_spec(tree)
    return fuse_into(tree, spec, group_sizes(spec))


def make_unfuse(treedef, spec) -> Callable:
    """Device-side: (fbuf, ibuf, bbuf) -> pytree, via static slices."""

    def unfuse(fbuf, ibuf, bbuf):
        bufs = {"f": fbuf, "i": ibuf, "b": bbuf}
        leaves = []
        for g, off, shape, dtype in spec:
            size = int(np.prod(shape)) if shape else 1
            leaf = bufs[g][off:off + size].reshape(shape).astype(dtype)
            leaves.append(leaf)
        return jax.tree.unflatten(treedef, leaves)

    return unfuse


def make_fused_cycle(cycle_fn, example_tree):
    """Wrap a cycle over an argument tuple (e.g. (snap, extras) or the
    sidecar's (snap, hierarchy, base_extras)) into fn(fbuf, ibuf, bbuf)
    with the tree rebuilt on device. Returns (jitted_fn, fuse_inputs)."""
    treedef, spec = fuse_spec(example_tree)
    unfuse = make_unfuse(treedef, spec)

    def _cycle(fbuf, ibuf, bbuf):
        args = unfuse(fbuf, ibuf, bbuf)
        return cycle_fn(*args).packed_decisions()

    # trace-vs-call accounting (telemetry/tracecount): a retrace of the
    # fused cycle on the steady-state path is a production incident the
    # volcano_jit_* gauges must surface
    from ..telemetry import counted_jit
    fn = counted_jit(_cycle, "fused_cycle")

    return fn, fuse


def fused_cycle_cached(cycle_fn, tree, cache: dict, key_extra=None):
    """Shape-signature-memoized make_fused_cycle.

    The single implementation of the (key_extra, per-leaf shape/dtype) cache
    key used by both the Session (framework/session.py) and the sidecar
    (runtime/sidecar.py) so the two callers cannot drift."""
    key = _shape_key(tree, key_extra)
    hit = cache.get(key)
    if hit is None:
        hit = make_fused_cycle(cycle_fn, tree)
        cache[key] = hit
    return hit


def _shape_key(tree, key_extra=None):
    leaves = jax.tree.leaves(tree)
    return (key_extra, tuple((np.asarray(l).shape, np.asarray(l).dtype.str)
                             for l in leaves))


# --------------------------------------------------------------------------
# Delta path: device-resident buffers, donated update+cycle, O(dirty) upload
# --------------------------------------------------------------------------

def pow2_bucket(n: int, minimum: int = 1) -> int:
    """Smallest power of two >= ``n``, starting from ``minimum`` (0 stays
    0). The shared shape-bucketing rule: delta sizes pad with
    ``minimum=_DELTA_MIN_BUCKET`` and the fleet runtime pads its tenant
    axis with ``minimum=1`` — both bound retraces to O(log n) programs."""
    if n <= 0:
        return 0
    b = int(minimum)
    while b < n:
        b <<= 1
    return b


def delta_bucket(n: int) -> int:
    """Pad a delta of ``n`` elements up to its compile bucket (0 stays 0 —
    a zero-length scatter is a static no-op shape)."""
    return pow2_bucket(n, _DELTA_MIN_BUCKET)


def _pad_delta(idx: np.ndarray, vals: np.ndarray, bucket: int):
    """Pad (idx, vals) to ``bucket`` by repeating the LAST real pair:
    duplicate scatter writes of an identical value are deterministic, so
    padding never perturbs the buffer."""
    pad = bucket - idx.size
    if pad <= 0:
        return idx, vals
    return (np.concatenate([idx, np.full(pad, idx[-1], np.int32)]),
            np.concatenate([vals, np.full(pad, vals[-1], vals.dtype)]))


# --------------------------------------------------------------------------
# Integrity digest: does the device still hold what the mirror says it holds?
# --------------------------------------------------------------------------
# A resident buffer lives on the device for thousands of cycles; a single
# corrupted element (driver fault, aliasing bug, a mirror that drifted from
# device truth) silently poisons every later delta diff. The digest is a
# cheap position-weighted u32 checksum computed IN-GRAPH over the three
# post-scatter group buffers and returned as a 3-word i32 tail riding the
# same packed readback as the decisions (no extra transfer, no callback).
# The host computes the identical formula over its mirror; a mismatch means
# device truth and host truth diverged, and the owner recovers with
# :meth:`DeltaKernel.recover` (full re-fuse from SOURCE truth + recompute).
# u32 multiply/add wrap identically mod 2^32 in XLA and numpy, and the sum
# is order-independent, so the comparison is exact on every backend.

#: digest words appended to the packed readback (one per group buffer)
DIGEST_WORDS = 3
_DIGEST_MUL = np.uint32(2654435761)     # Knuth multiplicative hash constant
_DIGEST_ADD = np.uint32(0x9E3779B9)     # golden-ratio offset: element 0 counts


def host_digest(bufs) -> np.ndarray:
    """u32[3] digest of host group buffers — the mirror half of the check.
    Bit-level: f32/i32 words are reinterpreted, never converted, so NaNs
    and negative zeros digest deterministically."""
    out = np.zeros(DIGEST_WORDS, np.uint32)
    for k, b in enumerate(bufs):
        w = (b.astype(np.uint32) if b.dtype == np.bool_
             else np.ascontiguousarray(b).view(np.uint32))
        idx = np.arange(w.size, dtype=np.uint32)
        out[k] = np.sum(w * (idx * _DIGEST_MUL + _DIGEST_ADD),
                        dtype=np.uint32)
    return out


def _device_digest(fbuf, ibuf, bbuf) -> jax.Array:
    """i32[3] in-graph digest of the resident buffers (bitcast of the u32
    words so the packed readback stays a single i32 array). Pure 32-bit
    arithmetic: traced clean under the graphcheck dtype family."""
    words = []
    for buf in (fbuf, ibuf, bbuf):
        if buf.dtype == jnp.bool_:
            w = buf.astype(jnp.uint32)
        else:
            w = jax.lax.bitcast_convert_type(buf, jnp.uint32)
        idx = jnp.arange(w.shape[0], dtype=jnp.uint32)
        words.append(jnp.sum(w * (idx * _DIGEST_MUL + _DIGEST_ADD),
                             dtype=jnp.uint32))
    return jax.lax.bitcast_convert_type(jnp.stack(words), jnp.int32)


def donation_for_backend(platform: Optional[str] = None,
                         n_residents: int = 3) -> tuple:
    """The donate_argnums the delta update+cycle entry uses on this
    backend: the resident buffers on accelerators, nothing on CPU
    (``n_residents`` is 3 for the flat :class:`DeltaKernel`, 6 for the
    node/rest split of :class:`ShardedDeltaKernel` — the contract is the
    same either way, and pjit threads the donation through per-shard).

    On TPU/GPU, execution is stream-async regardless and donation lets XLA
    scatter into the resident buffers in place — the whole point of
    residency. On the CPU backend, XLA cannot run a computation with
    donated (aliased) buffers asynchronously: the dispatch executes
    INLINE, which serializes the pipelined loop on compute (measured: the
    entire cycle's wall time moved into the dispatch call). CPU buffers
    are host memory, so skipping donation there costs one memcpy per
    updated buffer and buys the async dispatch back."""
    if platform is None:
        import jax
        platform = jax.default_backend()
    return () if platform == "cpu" else tuple(range(n_residents))


class ResidentState:
    """Per-owner device residency for one DeltaKernel shape bucket.

    Holds the host mirror of what the device buffers contain, a ping-pong
    scratch for the next pack, the CURRENT device buffer handles, and the
    RETIRING handles the previous cycle consumed. Ownership rule (the
    invalidation contract): a cycle's input handles are dead no later than
    the NEXT dispatched cycle — immediately when the backend honored the
    donation, at the next :meth:`DeltaKernel.run` otherwise (the depth-1
    pipeline guarantees the consumer was drained by then, so the delete
    cannot block on in-flight compute). Only ``state.device`` may be used,
    and only by passing it back into the next ``run``; host code must
    never ``np.asarray`` a resident buffer — the mirror IS the host view.
    """

    __slots__ = ("mirror", "scratch", "device", "retiring", "full_cycles",
                 "delta_cycles", "last_kind", "last_upload_bytes",
                 "full_upload_bytes", "resharding_copies",
                 "dec_device", "dec_mirror", "last_tail", "dec_epoch")

    def __init__(self):
        self.mirror: Optional[tuple] = None
        self.scratch: Optional[tuple] = None
        self.device: Optional[tuple] = None
        #: handles consumed by the in-flight/last cycle, deleted at the
        #: next dispatch (no-op where donation already killed them)
        self.retiring: tuple = ()
        #: device-resident copy of the previous cycle's packed decisions
        #: (pre-digest) — the diff base for the changed-rows readback tail
        self.dec_device: Optional[Any] = None
        #: host mirror of the decisions the OWNER last drained; None forces
        #: the next drain onto the full-readback path (first cycle, after
        #: recovery, after a discarded speculative cycle)
        self.dec_mirror: Optional[np.ndarray] = None
        #: device handle of the changed-rows tail emitted by the most
        #: recent run (None when the kernel's tail is disabled)
        self.last_tail: Optional[Any] = None
        #: decisions-chain lineage: bumped by every out-of-band dispatch
        #: (recovery re-run, speculative replay) — a pending whose captured
        #: epoch mismatches drains full and leaves dec_mirror alone, so the
        #: tail diff base and the host mirror can never silently diverge
        self.dec_epoch: int = 0
        self.full_cycles = 0
        self.delta_cycles = 0
        #: "full" | "delta" for the most recent cycle
        self.last_kind: Optional[str] = None
        #: bytes actually shipped to the device last cycle
        self.last_upload_bytes = 0
        #: what a full upload of this shape bucket ships (the comparison
        #: column bench records next to the delta bytes)
        self.full_upload_bytes = 0
        #: live transfer probe (ShardedDeltaKernel): number of delta
        #: dispatches whose resident inputs did NOT already carry the
        #: declared in_shardings — each one is a resharding copy pjit
        #: would silently insert. Steady-state contract: stays 0, because
        #: out_shardings == in_shardings across iterations.
        self.resharding_copies = 0


class DeltaKernel:
    """Compiled delta-update + cycle entry over device-resident buffers.

    One instance per (cycle_fn, shape signature); cache via
    :func:`delta_cycle_cached`. The jitted entry takes the three resident
    buffers (DONATED) plus per-group packed (indices, values) deltas,
    scatters the deltas in-graph, runs the cycle on the rebuilt tree, and
    returns the updated buffers together with the packed decisions:

        (fbuf', ibuf', bbuf', packed) = fn(fbuf, ibuf, bbuf,
                                           fidx, fvals, iidx, ivals,
                                           bidx, bvals)

    Decisions are bit-identical to the full-upload path by construction:
    the scatter reproduces exactly the elements the host diff found
    changed, so the rebuilt tree equals the freshly fused one.
    """

    def __init__(self, cycle_fn, example_tree,
                 entry: str = "fused_cycle_delta", integrity: bool = True):
        self.treedef, self.spec = fuse_spec(example_tree)
        self.sizes = group_sizes(self.spec)
        self.entry = entry
        #: i32 words the packed readback carries past the decisions: the
        #: in-graph integrity digest of the post-scatter resident buffers
        #: (see host_digest). Kernel-aware consumers strip it with
        #: :meth:`split_digest` and compare against :meth:`mirror_digest`.
        self.digest_words = DIGEST_WORDS if integrity else 0
        unfuse = make_unfuse(self.treedef, self.spec)
        #: decisions length (elements) of this shape bucket's packed
        #: readback, pre-digest — sized abstractly, no compile
        self.dec_len = 0
        #: changed-rows capacity of the readback tail: the tail indexes up
        #: to ``rb_cap`` decision rows that differ from the previous
        #: cycle's, so steady-state drains transfer O(churn) bytes the way
        #: uploads already do. 0 disables the tail (tiny buckets where the
        #: tail would not beat the full readback keep the old entry
        #: signature bit-for-bit).
        self.rb_cap = 0
        if integrity:
            try:
                shape = jax.eval_shape(
                    lambda t: cycle_fn(*t).packed_decisions(), example_tree)
                self.dec_len = int(shape.shape[0])
            except Exception:
                self.dec_len = 0
            cap = pow2_bucket(max(32, self.dec_len // 16), 32)
            if self.dec_len and 2 * cap + 1 + DIGEST_WORDS < self.dec_len:
                self.rb_cap = cap
        #: resident buffers threaded through the donated entry: the three
        #: fused group buffers, plus the previous-decisions buffer when the
        #: changed-rows tail is enabled
        self.n_residents = 4 if self.rb_cap else 3
        #: backend-dependent donation of the resident buffers (see
        #: donation_for_backend) — the graphcheck ``donation`` family
        #: verifies this matches the platform contract
        self.donate_argnums = donation_for_backend(
            n_residents=self.n_residents)
        rb_cap = self.rb_cap

        if self.rb_cap:
            def _update_cycle(fbuf, ibuf, bbuf, dprev,
                              fidx, fvals, iidx, ivals, bidx, bvals):
                fbuf = fbuf.at[fidx].set(fvals)
                ibuf = ibuf.at[iidx].set(ivals)
                bbuf = bbuf.at[bidx].set(bvals)
                args = unfuse(fbuf, ibuf, bbuf)
                dec = cycle_fn(*args).packed_decisions()
                dig = _device_digest(fbuf, ibuf, bbuf)
                packed = jnp.concatenate([dec, dig])
                # changed-rows tail: [digest | count | idx[cap] | vals[cap]]
                # — fill rows repeat index 0, whose val is row 0's CURRENT
                # value, so applying every pair is exact regardless of count
                diff = dec != dprev
                cnt = jnp.sum(diff, dtype=jnp.int32)
                # first rb_cap changed rows in order (fill 0), built from
                # int32 primitives — jnp.nonzero's platform-default index
                # dtype would leave an x64 intermediate in the graph
                rows = jnp.arange(dec.shape[0], dtype=jnp.int32)
                slot = jnp.where(diff,
                                 jnp.cumsum(diff, dtype=jnp.int32) - 1,
                                 rb_cap)
                idx = jnp.zeros(rb_cap, jnp.int32).at[slot].set(
                    rows, mode="drop")
                tail = jnp.concatenate([dig, cnt[None], idx, dec[idx]])
                return fbuf, ibuf, bbuf, dec, packed, tail
        else:
            def _update_cycle(fbuf, ibuf, bbuf,
                              fidx, fvals, iidx, ivals, bidx, bvals):
                fbuf = fbuf.at[fidx].set(fvals)
                ibuf = ibuf.at[iidx].set(ivals)
                bbuf = bbuf.at[bidx].set(bvals)
                args = unfuse(fbuf, ibuf, bbuf)
                packed = cycle_fn(*args).packed_decisions()
                if integrity:
                    packed = jnp.concatenate(
                        [packed, _device_digest(fbuf, ibuf, bbuf)])
                return fbuf, ibuf, bbuf, packed

        from ..telemetry import counted_jit
        self._fn = counted_jit(_update_cycle, entry,
                               donate_argnums=self.donate_argnums)

    # ---------------------------------------------------------- graphcheck
    @property
    def traceable(self) -> Callable:
        """The raw (unjitted) update+cycle body, for jaxpr-level analysis
        (graphcheck purity/dtype/donation families)."""
        return self._fn.__wrapped__

    def example_delta_args(self, bucket: int = _DELTA_MIN_BUCKET):
        """Concrete example inputs for tracing the entry: full-size zero
        buffers plus ``bucket``-sized no-op deltas per non-empty group."""
        args = [np.zeros(n, _TARGETS[g]) for g, n in zip(_GROUPS, self.sizes)]
        if self.rb_cap:
            args.append(np.zeros(self.dec_len, np.int32))
        for g, n in zip(_GROUPS, self.sizes):
            b = bucket if n else 0
            args.append(np.zeros(b, np.int32))
            args.append(np.zeros(b, _TARGETS[g]))
        return tuple(args)

    def warm(self, bucket: int = 0) -> None:
        """AOT-compile the entry for this shape bucket (the cold-start
        hook: with the persistent compilation cache enabled the restart
        stops paying ``compile_s``). ``bucket=0`` compiles the full-upload
        signature — the program the first cycle after a restart runs."""
        avals = tuple(jax.ShapeDtypeStruct(a.shape, a.dtype)
                      for a in self.example_delta_args(bucket))
        self._fn.lower(*avals).compile()

    # ----------------------------------------------- integrity + recovery
    def split_digest(self, packed: np.ndarray):
        """Split a host readback into (decisions, u32[3] device digest).
        The digest is None when this kernel was built without integrity."""
        if not self.digest_words:
            return packed, None
        tail = np.ascontiguousarray(packed[-self.digest_words:])
        return packed[:-self.digest_words], tail.view(np.uint32)

    def mirror_digest(self, state: "ResidentState"):
        """The host half of the integrity check: digest of the mirror of
        device truth for the cycle most recently dispatched from
        ``state`` (valid until the next dispatch — the depth-1 pipeline
        guarantees the pending cycle is drained first)."""
        if state.mirror is None:
            return None
        return host_digest(state.mirror)

    def recover(self, state: "ResidentState", tree):
        """Integrity recovery: full re-fuse from SOURCE truth + recompute.

        Drops whatever the device holds, re-packs ``tree`` (the exact
        argument tree of the cycle whose digest or readback failed — the
        caller kept it pending until drain, so it is still the dispatched
        cycle's truth) and re-runs the cycle as a forced full upload. This
        heals BOTH divergence directions: a corrupted resident buffer
        (device wrong, mirror right) and a drifted mirror (device right,
        mirror wrong) — re-deriving from the tree never trusts either
        side. The returned packed decisions are what the uncorrupted
        cycle would have produced, so recovery is decision-neutral. If
        the dispatch itself raises (the accelerator is gone, not just a
        buffer), residency is reset and the error propagates to the
        caller's next rung on the degradation ladder (the CPU oracle)."""
        # the suspect residents feed nothing anymore — the failed cycle
        # has been read back, so the deletes are free
        with _spans.span("delta.recover", cat="recovery"):
            if state.device is not None:
                self._invalidate(state.device)
                state.device = None
            state.mirror = None  # force_full below; never diff vs a suspect
            # the drained-decisions mirror is suspect for the same reason:
            # the next drain must read the full packed decisions, and the
            # recovery re-run below is an out-of-band chain dispatch
            state.dec_mirror = None
            state.dec_epoch = getattr(state, "dec_epoch", 0) + 1
            packed = self.run(state, tree, force_full=True)
            state.last_kind = "recovery"
            return packed

    def _reset_state(self, state: "ResidentState") -> None:
        """After a failed dispatch the runtime may or may not have consumed
        the donated inputs — residency is indeterminate. Drop everything so
        the next run pays one clean full upload instead of trusting a
        half-applied scatter."""
        for handles in (state.retiring,
                        state.device if state.device is not None else (),
                        (state.dec_device,)
                        if state.dec_device is not None else ()):
            self._invalidate(handles)
        state.retiring = ()
        state.device = None
        state.mirror = None
        state.scratch = None
        state.dec_device = None
        state.dec_mirror = None
        state.last_tail = None
        state.dec_epoch = getattr(state, "dec_epoch", 0) + 1

    # ------------------------------------------------------------- running
    def _invalidate(self, handles) -> None:
        """Kill any retired input handle the runtime left alive, so a host
        re-read of a resident buffer raises instead of returning stale (or
        TPU-aliased post-scatter) data. Where donation was honored the
        runtime marked the handle deleted at dispatch already (the
        ``is_deleted`` fast path); elsewhere this runs at the NEXT
        dispatch, after the depth-1 contract drained the consumer — never
        right after the consuming dispatch, where ``delete()`` blocks on
        the in-flight computation and serializes the pipeline."""
        for h in handles:
            try:
                if not h.is_deleted():
                    h.delete()
            except Exception:  # already deleted by the runtime
                pass

    def host_tree(self, bufs):
        """Rebuild the dispatched argument tree from HOST group buffers
        (a pending cycle's ``mirror`` capture). The static-slice unfuse is
        numpy-compatible, so this yields real host-side (snap, extras)
        objects — the recovery source for a speculative cycle whose
        original tree has since been refreshed in place."""
        return make_unfuse(self.treedef, self.spec)(*bufs)

    def split_tail(self, tail: np.ndarray):
        """Split a host-read changed-rows tail into
        (u32 device digest, changed count, row indices, row values)."""
        dig = np.ascontiguousarray(
            tail[:DIGEST_WORDS]).view(np.uint32)
        cnt = int(tail[DIGEST_WORDS])
        idx = tail[DIGEST_WORDS + 1:DIGEST_WORDS + 1 + self.rb_cap]
        vals = tail[DIGEST_WORDS + 1 + self.rb_cap:]
        return dig, cnt, idx, vals

    def run(self, state: ResidentState, tree, force_full: bool = False,
            keep_scratch: bool = False):
        """One cycle: pack ``tree``, ship full buffers or deltas, scatter +
        compute on device. Returns the packed-decisions DEVICE array (the
        caller owns the readback, so a pipelined loop can defer it);
        ``state`` is updated in place with the new residency + counters.

        ``keep_scratch`` packs into a FRESH buffer set and leaves the
        ping-pong scratch alone — a depth-k speculative dispatch keeps the
        previous cycle's mirror capture alive in its pending slot, so the
        packer must not recycle it."""
        # fault-injection seam: resident-buffer corruption faults fire
        # here, before this run diffs/dispatches — exactly where a real
        # device-side desync would sit (mirror drift fires at the owner's
        # complete/verify seam instead: a pre-dispatch drift self-heals)
        seam("delta.run", kernel=self, state=state)
        # retire the handles the PREVIOUS cycle consumed: by the depth-1
        # contract that cycle has been drained, so the delete is free — and
        # where donation was honored the runtime killed them at dispatch
        self._invalidate(state.retiring)
        state.retiring = ()
        with _spans.span("delta.pack"):
            bufs = fuse_into(tree, self.spec, self.sizes,
                             out=None if keep_scratch else state.scratch)
        if not keep_scratch:
            state.scratch = None
        full_bytes = int(sum(b.nbytes for b in bufs))
        deltas = None
        if state.mirror is not None and state.device is not None \
                and not force_full:
            with _spans.span("delta.diff"):
                deltas = []
                total = 0
                for new, old in zip(bufs, state.mirror):
                    idx = np.flatnonzero(new != old).astype(np.int32)
                    deltas.append((idx, new[idx]))
                    total += int(idx.size)
            if 2 * total >= sum(self.sizes):
                # a delta this large ships more bytes than the buffers:
                # take the full path (decisions identical either way)
                deltas = None
        if deltas is None:
            if state.device is not None:
                # the old residents are replaced wholesale: they feed no
                # computation, so dropping them NOW is free and keeps TPU
                # memory from holding both generations
                self._invalidate(state.device)
            with _spans.span("delta.upload"):
                dev = tuple(jax.device_put(b) for b in bufs)
            args = []
            for g, n in zip(_GROUPS, self.sizes):
                args += [np.zeros(0, np.int32), np.zeros(0, _TARGETS[g])]
            state.full_cycles += 1
            state.last_kind = "full"
            state.last_upload_bytes = full_bytes
        else:
            dev = state.device
            args = []
            upload = 0
            for idx, vals in deltas:
                pidx, pvals = _pad_delta(idx, vals, delta_bucket(idx.size))
                args += [pidx, pvals]
                upload += int(pidx.nbytes + pvals.nbytes)
            state.delta_cycles += 1
            state.last_kind = "delta"
            state.last_upload_bytes = upload
        state.full_upload_bytes = full_bytes
        try:
            with _spans.span("delta.dispatch", cat="dispatch"):
                if self.rb_cap:
                    dprev = state.dec_device
                    if dprev is None:
                        dprev = jax.device_put(
                            np.zeros(self.dec_len, np.int32))
                        state.dec_mirror = None
                    fnew, inew, bnew, dnew, packed, tail = self._fn(
                        *dev, dprev, *args)
                else:
                    fnew, inew, bnew, packed = self._fn(*dev, *args)
                    dnew = tail = None
        except Exception:
            self._reset_state(state)
            raise
        # the consumed inputs are CONTRACTUALLY dead from here on: honored
        # donation killed them at dispatch; otherwise they retire at the
        # next dispatch (deleting now would block on the in-flight
        # computation and serialize the pipeline)
        state.retiring = dev + ((dprev,) if self.rb_cap else ())
        state.device = (fnew, inew, bnew)
        state.dec_device = dnew
        state.last_tail = tail
        if keep_scratch:
            state.mirror = bufs
        else:
            # ping-pong: the old mirror becomes next cycle's scratch
            state.scratch, state.mirror = state.mirror, bufs
        return packed


def delta_cycle_cached(cycle_fn, tree, cache: Dict, key_extra=None,
                       entry: str = "fused_cycle_delta") -> DeltaKernel:
    """Shape-signature-memoized DeltaKernel, sharing the exact cache-key
    construction with :func:`fused_cycle_cached` (and therefore the same
    bucket-isolation guarantees). Device residency (ResidentState) is the
    CALLER's to hold, keyed by the returned kernel — the kernel itself is
    stateless apart from its compiled programs."""
    key = _shape_key(tree, key_extra)
    hit = cache.get(key)
    if hit is None:
        hit = DeltaKernel(cycle_fn, tree, entry=entry)
        cache[key] = hit
    return hit


# --------------------------------------------------------------------------
# Sharded delta path: node-axis residents over a device mesh (ISSUE 7)
# --------------------------------------------------------------------------
# The flat DeltaKernel assumes one addressable buffer per dtype group; a
# device mesh breaks all three of its contracts at once (the scatter would
# gather, the digest would all-gather, the donation would alias across
# shards). ShardedDeltaKernel re-cuts the residency along the node axis:
#
# - each dtype group splits into a NODE buffer shaped (N, C_g) — row n is
#   the concatenation of every node leaf's row n — sharded
#   ``P(nodes, None)``, plus a flat replicated REST buffer for the
#   task/job/queue leaves (6 residents total);
# - packed (idx, vals) deltas for the node region are ROUTED host-side to
#   the owning shard: a (D, B) array sharded ``P(nodes, None)`` ships each
#   shard only its own rows' updates, and a shard_map scatter applies them
#   with local row offsets (an out-of-shard index maps to the
#   positive-out-of-bounds row so drop-mode discards it — negative indices
#   WRAP in XLA scatter, so they are never used as the discard);
# - the integrity digest becomes a per-shard digest VECTOR: each shard
#   digests its local block with shard-local positions, so verification
#   never all-gathers a node buffer (the (D,) digest words riding the
#   packed readback are O(mesh), not O(nodes));
# - the 6 residents are donated through pjit on accelerator backends
#   (donation_for_backend with n_residents=6), and
#   out_shardings == in_shardings for every resident, so the steady loop
#   never reshard-copies — verified live by the resharding probe
#   (ResidentState.resharding_copies).

def sharded_fuse_spec(tree, node_mask):
    """(treedef, per-leaf (group, region, offset, shape, dtype),
    n_nodes, node_cols{g}, rest_sizes{g}) for a pytree whose leaves are
    flagged node-axis (True) or replicated (False) by ``node_mask``.
    Node offsets are COLUMN offsets into the (N, C_g) node buffer; rest
    offsets are element offsets into the flat rest buffer — the single
    source of truth for the sharded full and delta paths."""
    leaves, treedef = jax.tree.flatten(tree)
    if len(leaves) != len(node_mask):
        raise ValueError(f"node_mask has {len(node_mask)} entries for "
                         f"{len(leaves)} leaves")
    node_cols = {g: 0 for g in _GROUPS}
    rest_off = {g: 0 for g in _GROUPS}
    n_nodes = None
    spec = []
    for leaf, is_node in zip(leaves, node_mask):
        arr = np.asarray(leaf)
        g = _group_of(arr.dtype)
        if is_node:
            if arr.ndim == 0 or arr.shape[0] == 0:
                raise ValueError("node leaf must have a leading node axis")
            if n_nodes is None:
                n_nodes = int(arr.shape[0])
            elif int(arr.shape[0]) != n_nodes:
                raise ValueError("node leaves disagree on the node axis: "
                                 f"{arr.shape[0]} vs {n_nodes}")
            cols = arr.size // n_nodes
            spec.append((g, "node", node_cols[g], arr.shape, arr.dtype))
            node_cols[g] += cols
        else:
            spec.append((g, "rest", rest_off[g], arr.shape, arr.dtype))
            rest_off[g] += arr.size
    if n_nodes is None:
        raise ValueError("node_mask marks no leaves as node-axis")
    return treedef, spec, n_nodes, node_cols, rest_off


class ShardedDeltaKernel:
    """Node-axis sharded delta-update + cycle entry over a device mesh.

    Duck-type compatible with :class:`DeltaKernel` (run / warm / recover /
    split_digest / mirror_digest / traceable / example_delta_args /
    digest_words / donate_argnums), so the Session, the pipelined
    Scheduler, and the sidecar swap it in by construction alone. The
    jitted entry takes the six residents (node f/i/b sharded
    ``P(nodes, None)``, rest f/i/b replicated; all donated on
    accelerators) plus per-group routed node deltas and replicated rest
    deltas:

        (fnode', inode', bnode', frest', irest', brest', packed) = fn(
            fnode, inode, bnode, frest, irest, brest,
            fn_idx, fn_vals, in_idx, in_vals, bn_idx, bn_vals,
            fr_idx, fr_vals, ir_idx, ir_vals, br_idx, br_vals)

    Decisions are bit-identical to the unsharded path by construction:
    the routed scatter reproduces exactly the elements the host diff
    found changed, and GSPMD partitions the same cycle program the
    single-device jit runs.
    """

    def __init__(self, cycle_fn, example_tree, mesh, node_mask,
                 entry: str = "fused_cycle_sharded", integrity: bool = True):
        from jax.sharding import NamedSharding, PartitionSpec
        self.mesh = mesh
        self.axis = mesh.axis_names[0]
        self.n_shards = D = int(np.prod(mesh.devices.shape))
        (self.treedef, self.spec, self.n_nodes, self.node_cols,
         self.rest_sizes) = sharded_fuse_spec(example_tree, node_mask)
        if self.n_nodes % D != 0:
            raise ValueError(
                f"node axis {self.n_nodes} does not divide the "
                f"{D}-device mesh — pick a mesh size via "
                "parallel.sharding.mesh_for_nodes")
        self.rows_per = self.n_nodes // D
        self.entry = entry
        #: i32 words on the packed readback: one digest word per dtype
        #: group PER SHARD for the node residents (compared shard-local —
        #: never an O(N) all-gather) plus the 3 flat rest words
        self.digest_words = (3 * D + DIGEST_WORDS) if integrity else 0
        #: the changed-rows readback tail is a flat-kernel feature; the
        #: sharded path always reads the full packed decisions (its drains
        #: are O(mesh) digest words + decisions either way)
        self.rb_cap = 0
        self.dec_len = 0
        self.n_residents = 6
        self.donate_argnums = donation_for_backend(n_residents=6)
        self._node_sh = NamedSharding(mesh, PartitionSpec(self.axis, None))
        self._rep_sh = NamedSharding(mesh, PartitionSpec())
        #: declared shardings of the six residents, in argument order —
        #: the live resharding probe compares dispatched handles against
        #: exactly these
        self.resident_shardings = (self._node_sh,) * 3 + (self._rep_sh,) * 3
        self._total_elems = int(
            sum(self.n_nodes * self.node_cols[g] + self.rest_sizes[g]
                for g in _GROUPS))
        unfuse = self._make_unfuse()
        scatters = {g: self._make_node_scatter(g) for g in _GROUPS}

        def _update_cycle(fnode, inode, bnode, frest, irest, brest,
                          fn_idx, fn_vals, in_idx, in_vals, bn_idx, bn_vals,
                          fr_idx, fr_vals, ir_idx, ir_vals, br_idx, br_vals):
            fnode, fdig = scatters["f"](fnode, fn_idx, fn_vals)
            inode, idig = scatters["i"](inode, in_idx, in_vals)
            bnode, bdig = scatters["b"](bnode, bn_idx, bn_vals)
            frest = frest.at[fr_idx].set(fr_vals)
            irest = irest.at[ir_idx].set(ir_vals)
            brest = brest.at[br_idx].set(br_vals)
            args = unfuse(fnode, inode, bnode, frest, irest, brest)
            packed = cycle_fn(*args).packed_decisions()
            if integrity:
                node_tail = jax.lax.bitcast_convert_type(
                    jnp.concatenate([fdig, idig, bdig]), jnp.int32)
                packed = jnp.concatenate(
                    [packed, node_tail,
                     _device_digest(frest, irest, brest)])
            return fnode, inode, bnode, frest, irest, brest, packed

        in_sh = (self.resident_shardings
                 + (self._node_sh, self._node_sh) * 3
                 + (self._rep_sh, self._rep_sh) * 3)
        #: out_shardings == in_shardings for every resident — the zero
        #: inter-iteration resharding contract the probe verifies live
        out_sh = self.resident_shardings + (self._rep_sh,)
        from ..telemetry import counted_jit
        self._fn = counted_jit(_update_cycle, entry,
                               donate_argnums=self.donate_argnums,
                               in_shardings=in_sh, out_shardings=out_sh)

    # ------------------------------------------------------------ programs
    def _make_unfuse(self) -> Callable:
        """Device-side: six residents -> pytree. Node leaves are COLUMN
        slices of the (N, C_g) node buffer — a column slice of a
        row-sharded array stays row-sharded, so the cycle's node tensors
        enter GSPMD split exactly as make_sharded_allocate declares."""
        spec, treedef, N = self.spec, self.treedef, self.n_nodes

        def unfuse(fnode, inode, bnode, frest, irest, brest):
            node = {"f": fnode, "i": inode, "b": bnode}
            rest = {"f": frest, "i": irest, "b": brest}
            leaves = []
            for g, region, off, shape, dtype in spec:
                size = int(np.prod(shape)) if shape else 1
                if region == "node":
                    cols = size // N
                    leaf = (node[g][:, off:off + cols]
                            .reshape(shape).astype(dtype))
                else:
                    leaf = (rest[g][off:off + size]
                            .reshape(shape).astype(dtype))
                leaves.append(leaf)
            return jax.tree.unflatten(treedef, leaves)

        return unfuse

    def _make_node_scatter(self, g: str) -> Callable:
        """shard_map scatter + per-shard digest for one node buffer.

        Each shard receives ONLY its routed (1, B) delta rows, rebases the
        global flat indices to local (row, col), and scatters into its
        local block; the per-shard digest uses LOCAL positions so the host
        can recompute it per mirror block without any gather."""
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P
        C, rows_per, axis = self.node_cols[g], self.rows_per, self.axis

        def local(nb, idx, vals):
            idx, vals = idx[0], vals[0]
            if C:
                base = (jax.lax.axis_index(axis) * rows_per).astype(idx.dtype)
                r = idx // C - base
                c = idx % C
                # out-of-shard (and padding) rows map to the positive
                # out-of-bounds row: drop-mode discards them. Negative
                # indices WRAP in XLA scatter — never rely on them to drop.
                r = jnp.where((r >= 0) & (r < rows_per), r, rows_per)
                nb = nb.at[r, c].set(vals, mode="drop")
            if nb.dtype == jnp.bool_:
                w = nb.reshape(-1).astype(jnp.uint32)
            else:
                w = jax.lax.bitcast_convert_type(nb.reshape(-1), jnp.uint32)
            pos = jnp.arange(w.shape[0], dtype=jnp.uint32)
            dig = jnp.sum(w * (pos * _DIGEST_MUL + _DIGEST_ADD),
                          dtype=jnp.uint32)
            return nb, dig[None]

        return shard_map(local, mesh=self.mesh,
                         in_specs=(P(self.axis, None), P(self.axis, None),
                                   P(self.axis, None)),
                         out_specs=(P(self.axis, None), P(self.axis)))

    # --------------------------------------------------------------- fuse
    def _fuse_sharded(self, tree, out=None):
        """Host-side pack into the six buffers (node buffers node-major
        2-D, rest flat); ``out`` reuses the ping-pong scratch."""
        N = self.n_nodes
        if out is None:
            out = tuple(
                [np.empty((N, self.node_cols[g]), _TARGETS[g])
                 for g in _GROUPS]
                + [np.empty(self.rest_sizes[g], _TARGETS[g])
                   for g in _GROUPS])
        node = dict(zip(_GROUPS, out[:3]))
        rest = dict(zip(_GROUPS, out[3:]))
        for leaf, (g, region, off, _shape, _dtype) in zip(
                jax.tree.leaves(tree), self.spec):
            arr = np.asarray(leaf)
            if region == "node":
                cols = arr.size // N
                node[g][:, off:off + cols] = arr.reshape(N, cols)
            else:
                rest[g][off:off + arr.size] = arr.ravel()
        return out

    def _route(self, idx: np.ndarray, vals: np.ndarray, g: str):
        """Route a node-region flat delta to owning shards: (D, B) idx and
        vals arrays whose row s holds ONLY shard s's updates (padded by
        repeating the shard's last real pair, or — for an empty shard —
        by an index that rebases to the local out-of-bounds row, which
        drop-mode discards). Uploaded ``P(nodes, None)``, each device
        receives exactly its own row."""
        D, C, rows_per = self.n_shards, self.node_cols[g], self.rows_per
        if idx.size == 0 or C == 0:
            return (np.zeros((D, 0), np.int32),
                    np.zeros((D, 0), _TARGETS[g]))
        shard = (idx // C) // rows_per
        counts = np.bincount(shard, minlength=D)
        B = delta_bucket(int(counts.max()))
        pidx = np.empty((D, B), np.int32)
        pvals = np.empty((D, B), _TARGETS[g])
        for s in range(D):
            m = shard == s
            si, sv = idx[m], vals[m]
            if si.size:
                fi, fv = _pad_delta(si, sv, B)
            else:
                # local row == rows_per after rebasing -> dropped
                fi = np.full(B, (s + 1) * rows_per * C, np.int32)
                fv = np.zeros(B, _TARGETS[g])
            pidx[s], pvals[s] = fi, fv
        return pidx, pvals

    # ---------------------------------------------------------- graphcheck
    @property
    def traceable(self) -> Callable:
        """The raw (unjitted) update+cycle body, for jaxpr-level analysis."""
        return self._fn.__wrapped__

    def example_delta_args(self, bucket: int = _DELTA_MIN_BUCKET):
        """Concrete example inputs for tracing/compiling the entry:
        zero residents plus ``bucket``-sized no-op deltas per non-empty
        region (``bucket=0`` is the full-upload signature)."""
        N, D = self.n_nodes, self.n_shards
        args = [np.zeros((N, self.node_cols[g]), _TARGETS[g])
                for g in _GROUPS]
        args += [np.zeros(self.rest_sizes[g], _TARGETS[g]) for g in _GROUPS]
        for g in _GROUPS:
            b = bucket if self.node_cols[g] else 0
            args.append(np.zeros((D, b), np.int32))
            args.append(np.zeros((D, b), _TARGETS[g]))
        for g in _GROUPS:
            b = bucket if self.rest_sizes[g] else 0
            args.append(np.zeros(b, np.int32))
            args.append(np.zeros(b, _TARGETS[g]))
        return tuple(args)

    def warm(self, bucket: int = 0) -> None:
        """AOT-compile the sharded entry for this shape bucket."""
        avals = tuple(jax.ShapeDtypeStruct(a.shape, a.dtype)
                      for a in self.example_delta_args(bucket))
        self._fn.lower(*avals).compile()

    # ----------------------------------------------- integrity + recovery
    def split_digest(self, packed: np.ndarray):
        """Split a host readback into (decisions, u32[3D+3] digest
        vector: per-shard node words then the flat rest words)."""
        if not self.digest_words:
            return packed, None
        tail = np.ascontiguousarray(packed[-self.digest_words:])
        return packed[:-self.digest_words], tail.view(np.uint32)

    def mirror_digest(self, state: "ResidentState"):
        """Host half of the per-shard integrity check: digest each
        shard's block of the mirrored node buffers with SHARD-LOCAL
        positions (mirroring the shard_map computation exactly), then the
        flat rest buffers."""
        if state.mirror is None:
            return None
        D, rows_per = self.n_shards, self.rows_per
        words = []
        for nb in state.mirror[:3]:
            for s in range(D):
                blk = nb[s * rows_per:(s + 1) * rows_per].ravel()
                w = (blk.astype(np.uint32) if blk.dtype == np.bool_
                     else np.ascontiguousarray(blk).view(np.uint32))
                pos = np.arange(w.size, dtype=np.uint32)
                words.append(np.sum(w * (pos * _DIGEST_MUL + _DIGEST_ADD),
                                    dtype=np.uint32))
        return np.concatenate([np.array(words, np.uint32),
                               host_digest(state.mirror[3:])])

    def recover(self, state: "ResidentState", tree):
        """Integrity recovery: full re-fuse from SOURCE truth +
        recompute, same contract as :meth:`DeltaKernel.recover` (heals
        both a corrupted shard and a drifted mirror; decision-neutral)."""
        with _spans.span("delta.recover", cat="recovery"):
            if state.device is not None:
                self._invalidate(state.device)
                state.device = None
            state.mirror = None
            packed = self.run(state, tree, force_full=True)
            state.last_kind = "recovery"
            return packed

    _reset_state = DeltaKernel._reset_state
    _invalidate = DeltaKernel._invalidate

    # ------------------------------------------------------------- running
    def _probe_resharding(self, state: "ResidentState") -> None:
        """Live transfer probe: a resident about to be re-dispatched whose
        device sharding is not the declared in_sharding means pjit will
        insert a resharding copy this cycle. Counted, never raised — the
        cycle is still correct, just not zero-copy."""
        copies = 0
        for h, sh in zip(state.device, self.resident_shardings):
            try:
                if not h.sharding.is_equivalent_to(sh, h.ndim):
                    copies += 1
            except Exception:  # non-array handle: let the dispatch decide
                pass
        if copies:
            state.resharding_copies += copies
            from ..metrics import METRICS
            METRICS.inc("sharded_resharding_copies_total", copies)

    def run(self, state: ResidentState, tree, force_full: bool = False,
            keep_scratch: bool = False):
        """One sharded cycle: pack ``tree``, ship full residents (explicit
        device_put per declared sharding) or routed deltas, shard-local
        scatter + cycle on device. Same residency/invalidate/ping-pong
        contract as :meth:`DeltaKernel.run` (``keep_scratch`` likewise
        packs fresh buffers so a pending slot's mirror capture survives)."""
        seam("delta.run", kernel=self, state=state)
        self._invalidate(state.retiring)
        state.retiring = ()
        with _spans.span("delta.pack"):
            bufs = self._fuse_sharded(
                tree, out=None if keep_scratch else state.scratch)
        if not keep_scratch:
            state.scratch = None
        full_bytes = int(sum(b.nbytes for b in bufs))
        deltas = None
        if state.mirror is not None and state.device is not None \
                and not force_full:
            with _spans.span("delta.diff"):
                deltas = []
                total = 0
                for new, old in zip(bufs, state.mirror):
                    idx = np.flatnonzero(new.ravel() != old.ravel()) \
                            .astype(np.int32)
                    deltas.append((idx, new.ravel()[idx]))
                    total += int(idx.size)
            if 2 * total >= self._total_elems:
                deltas = None
        if deltas is None:
            if state.device is not None:
                self._invalidate(state.device)
            with _spans.span("delta.upload"):
                dev = tuple(jax.device_put(b, sh)
                            for b, sh in zip(bufs, self.resident_shardings))
            args = []
            for g in _GROUPS:
                args += [np.zeros((self.n_shards, 0), np.int32),
                         np.zeros((self.n_shards, 0), _TARGETS[g])]
            for g in _GROUPS:
                args += [np.zeros(0, np.int32), np.zeros(0, _TARGETS[g])]
            state.full_cycles += 1
            state.last_kind = "full"
            state.last_upload_bytes = full_bytes
        else:
            self._probe_resharding(state)
            dev = state.device
            args = []
            upload = 0
            with _spans.span("delta.route"):
                for (idx, vals), g in zip(deltas[:3], _GROUPS):
                    pidx, pvals = self._route(idx, vals, g)
                    args += [pidx, pvals]
                    upload += int(pidx.nbytes + pvals.nbytes)
                for (idx, vals) in deltas[3:]:
                    pidx, pvals = _pad_delta(idx, vals,
                                             delta_bucket(idx.size))
                    args += [pidx, pvals]
                    upload += int(pidx.nbytes + pvals.nbytes)
            state.delta_cycles += 1
            state.last_kind = "delta"
            state.last_upload_bytes = upload
        state.full_upload_bytes = full_bytes
        try:
            with _spans.span("delta.dispatch", cat="dispatch"):
                out = self._fn(*dev, *args)
        except Exception:
            self._reset_state(state)
            raise
        packed = out[-1]
        state.retiring = dev
        state.device = tuple(out[:-1])
        if keep_scratch:
            state.mirror = bufs
        else:
            state.scratch, state.mirror = state.mirror, bufs
        return packed


def sharded_delta_cycle_cached(cycle_fn, tree, mesh, node_mask, cache: Dict,
                               key_extra=None,
                               entry: str = "fused_cycle_sharded"
                               ) -> ShardedDeltaKernel:
    """Shape-signature-memoized ShardedDeltaKernel; the cache key extends
    :func:`_shape_key` with the mesh's device identity so two meshes never
    share a kernel (their shardings — and compiled programs — differ)."""
    mesh_key = tuple(d.id for d in mesh.devices.ravel())
    key = _shape_key(tree, (key_extra, mesh_key))
    hit = cache.get(key)
    if hit is None:
        hit = ShardedDeltaKernel(cycle_fn, tree, mesh, node_mask,
                                 entry=entry)
        cache[key] = hit
    return hit
