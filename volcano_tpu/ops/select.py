"""Selection and ordering primitives.

Replaces SelectBestNode's argmax + rand.Intn tie-break
(pkg/scheduler/util/scheduler_helper.go:213-228) with a deterministic
lowest-index tie-break (documented divergence: the reference is
nondeterministic on ties, SURVEY.md section 7 hard part 4), and the four nested
container/heap priority queues (pkg/scheduler/util/priority_queue.go:36-94)
with lexicographic masked argmin over key vectors.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp

NEG = -1e30


def best_node(score: jax.Array, feasible: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """(index i32, found bool): argmax of score over feasible nodes,
    first-index tie-break (lax.argmax returns the first maximum; the
    index dtype is pinned so the graph stays 32-bit under any x64 config
    — graphcheck dtype discipline)."""
    masked = jnp.where(feasible, score, jnp.float32(NEG))
    idx = jax.lax.argmax(masked, 0, jnp.int32)
    return idx, jnp.any(feasible)


def tie_count(score: jax.Array, feasible: jax.Array) -> jax.Array:
    """i32: how many feasible nodes BEYOND the winner share the winning
    score — the telemetry counter behind the documented lowest-index
    tie-break divergence (the reference rolls rand.Intn over the tied set,
    scheduler_helper.go:227; this counts how often that die would have
    been rolled). 0 when no node is feasible."""
    masked = jnp.where(feasible, score, jnp.float32(NEG))
    mx = jnp.max(masked)
    n = jnp.sum((masked == mx) & feasible, dtype=jnp.int32)
    return jnp.maximum(n - jnp.int32(1), jnp.int32(0))


def lex_argmin(keys: Sequence[jax.Array], mask: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Lexicographic masked argmin.

    ``keys`` is an ordered list of f32/i32 vectors (most significant first);
    returns (index of the lexicographically smallest masked entry, any-valid
    flag). This is the kernel replacement for popping nested priority queues
    ordered by tiered LessFns (framework/session_plugins.go:440-554).
    """
    m = mask
    for k in keys:
        k = k.astype(jnp.float32)
        kmin = jnp.min(jnp.where(m, k, jnp.float32(jnp.inf)))
        m = m & (k <= kmin + 0.0)
    # first surviving index
    idx = jax.lax.argmax(m, 0, jnp.int32)
    return idx, jnp.any(mask)


def sort_order(keys: Sequence[jax.Array], mask: jax.Array) -> jax.Array:
    """i32[n]: indices sorted lexicographically by ``keys`` (most significant
    first), masked-out entries last. Stable, so equal keys keep index order."""
    n = keys[0].shape[0]
    order = jnp.arange(n, dtype=jnp.int32)

    def _argsort_i32(k):
        # stable ascending argsort with a pinned i32 index payload
        # (jnp.argsort's index dtype follows the x64 config; lax.sort
        # with an iota payload is the same sort, 32-bit by construction)
        iota = jnp.arange(k.shape[0], dtype=jnp.int32)
        _, idx = jax.lax.sort((k, iota), num_keys=1, is_stable=True)
        return idx

    # lexsort: apply stable sorts from least-significant key to most
    for k in reversed(list(keys)):
        k = jnp.where(mask, k.astype(jnp.float32), jnp.float32(jnp.inf))
        order = order[_argsort_i32(k[order])]
    # push masked entries to the end while keeping relative order
    masked_last = _argsort_i32(~mask[order])
    return order[masked_last]
