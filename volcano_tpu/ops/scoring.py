"""Additive node-score kernels.

TPU re-design of the reference's scoring plugins: binpack
(pkg/scheduler/plugins/binpack/binpack.go:196-260), nodeorder's wrapped k8s
scorers least/most-allocated and balanced-allocation
(pkg/scheduler/plugins/nodeorder/nodeorder.go:219-271), the tainttoleration
PreferNoSchedule score, and tdm's revocable-node bonus
(pkg/scheduler/plugins/tdm/tdm.go:296). Each kernel returns f32[N]; the
session sums them with configured weights, replacing the PrioritizeNodes
map/reduce (pkg/scheduler/util/scheduler_helper.go:133-195).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..arrays.labels import EFFECT_PREFER_NO_SCHEDULE
from ..arrays.schema import NodeArrays

_EPS = 1e-9


@jax.named_scope("volcano/score/binpack")
def binpack_score(used: jax.Array, allocatable: jax.Array, resreq: jax.Array,
                  resource_weights: jax.Array) -> jax.Array:
    """Best-fit score, higher = fuller node after placement.

    Reference: BinPackingScore (binpack.go:196-260) — for each resource in the
    task's request with a configured weight w_r:
    ``score += (used_r + req_r) / allocatable_r * w_r``, normalized by the sum
    of participating weights, scaled to 0-100.
    used/allocatable f32[N,R], resreq f32[R], resource_weights f32[R].
    """
    applicable = (resreq > 0)[None, :] & (allocatable > 0) \
        & (resource_weights > 0)[None, :]
    frac = jnp.where(applicable, (used + resreq[None, :]) / jnp.maximum(allocatable, _EPS), 0.0)
    over = frac > 1.0 + 1e-6  # request overflows this dim -> score 0 like reference
    w = resource_weights[None, :] * applicable
    wsum = jnp.sum(w, axis=-1)
    raw = jnp.sum(frac * w, axis=-1) / jnp.maximum(wsum, _EPS)
    raw = jnp.where(jnp.any(over, axis=-1), 0.0, raw)
    return raw * 100.0


@jax.named_scope("volcano/score/least-allocated")
def least_allocated_score(used: jax.Array, allocatable: jax.Array,
                          resreq: jax.Array) -> jax.Array:
    """Spread score, higher = emptier node after placement (k8s
    NodeResourcesLeastAllocated as wrapped at nodeorder.go:219-240)."""
    cap = jnp.maximum(allocatable, _EPS)
    free_frac = (allocatable - used - resreq[None, :]) / cap
    counted = allocatable > 0
    # dtype pins: integer/bool sums follow the x64 default int otherwise
    n = jnp.maximum(jnp.sum(counted, axis=-1, dtype=jnp.int32), 1)
    return jnp.sum(jnp.clip(free_frac, 0.0, 1.0) * counted, axis=-1) / n * 100.0


@jax.named_scope("volcano/score/most-allocated")
def most_allocated_score(used: jax.Array, allocatable: jax.Array,
                         resreq: jax.Array) -> jax.Array:
    """Packing score via k8s NodeResourcesMostAllocated (nodeorder.go)."""
    cap = jnp.maximum(allocatable, _EPS)
    used_frac = (used + resreq[None, :]) / cap
    counted = allocatable > 0
    n = jnp.maximum(jnp.sum(counted, axis=-1, dtype=jnp.int32), 1)
    return jnp.sum(jnp.clip(used_frac, 0.0, 1.0) * counted, axis=-1) / n * 100.0


@jax.named_scope("volcano/score/balanced-allocation")
def balanced_allocation_score(used: jax.Array, allocatable: jax.Array,
                              resreq: jax.Array) -> jax.Array:
    """100 - 100*std(resource fractions): k8s NodeResourcesBalancedAllocation
    (nodeorder.go:241-260). Penalizes skewed cpu-vs-memory usage."""
    cap = jnp.maximum(allocatable, _EPS)
    frac = jnp.clip((used + resreq[None, :]) / cap, 0.0, 1.0)
    counted = (allocatable > 0).astype(frac.dtype)
    n = jnp.maximum(jnp.sum(counted, axis=-1), 1.0)
    mean = jnp.sum(frac * counted, axis=-1) / n
    var = jnp.sum(((frac - mean[:, None]) ** 2) * counted, axis=-1) / n
    return (1.0 - jnp.sqrt(var)) * 100.0


@jax.named_scope("volcano/score/taint-prefer")
def taint_prefer_score(tol_hash: jax.Array, tol_effect: jax.Array,
                       tol_mode: jax.Array, nodes: NodeArrays) -> jax.Array:
    """Fewer intolerable PreferNoSchedule taints = higher score (k8s
    TaintToleration scorer as wrapped at nodeorder.go:219-271)."""
    from .predicates import toleration_covers
    covered = toleration_covers(tol_hash, tol_effect, tol_mode, nodes)
    prefer = nodes.taint_effect == EFFECT_PREFER_NO_SCHEDULE
    intolerable = jnp.sum(prefer & ~covered, axis=-1, dtype=jnp.int32)
    max_count = jnp.maximum(jnp.max(intolerable), 1)
    return (1.0 - intolerable / max_count) * 100.0


@jax.named_scope("volcano/score/node-preference")
def node_preference_score(preferred_node: jax.Array, n_nodes: int) -> jax.Array:
    """One-hot bonus for a specific node — used by task-topology's bucket
    preference (pkg/scheduler/plugins/task-topology/topology.go:344) and the
    reservation plugin's locked nodes."""
    idx = jnp.arange(n_nodes, dtype=jnp.int32)
    return jnp.where((preferred_node >= 0) & (idx == preferred_node),
                     jnp.float32(100.0), jnp.float32(0.0))
