"""Preempt and reclaim passes as compiled kernels.

TPU re-design of pkg/scheduler/actions/preempt/preempt.go:42-291 (intra-queue
preemption for starving gangs) and pkg/scheduler/actions/reclaim/
reclaim.go:40-191 (cross-queue reclaim for underserved queues). The tiered
Preemptable/Reclaimable victim intersection (framework/session_plugins.go:
131-215) becomes a conjunction of victim-eligibility masks:

- gang: a job may only lose tasks above its minAvailable surplus
  (gang.go:83-107),
- priority: victims' job priority must be lower than the preemptor's
  (priority.go:114),
- drf: the victim job's dominant share must stay >= the preemptor's
  (drf.go:330-360; evaluated statically per cycle — documented approximation),
- conformance / tdm: host-supplied veto mask (conformance.go:30-68).

ValidateVictims' capacity check (util/scheduler_helper.go:240-255) is the
``future idle + evictable >= request`` test; the lowest-priority-first victim
eviction is a bounded inner while-loop; gang commit/discard works exactly as
in the allocate kernel (keep iff JobPipelined).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from ..api.types import TaskStatus
from ..arrays.schema import SnapshotArrays
from . import predicates as P
from .allocate_scan import MODE_PIPELINED, AllocateConfig, AllocateExtras, _score_fn
from .select import NEG, lex_argmin

_OCCUPYING = (int(TaskStatus.ALLOCATED), int(TaskStatus.BINDING),
              int(TaskStatus.BOUND), int(TaskStatus.RUNNING))


@dataclass(frozen=True)
class PreemptConfig:
    mode: str = "preempt"               # "preempt" | "reclaim"
    scoring: AllocateConfig = AllocateConfig()
    enable_priority_rule: bool = True   # priority plugin victim filter
    enable_drf_rule: bool = False       # drf share victim filter
    max_victims_per_task: int = 16      # bound on the eviction loop


@jax.tree_util.register_dataclass
@dataclass
class PreemptResult:
    task_node: jax.Array      # i32[T] pipelined placement of preemptor tasks
    task_mode: jax.Array      # i32[T] MODE_PIPELINED where placed
    evicted: jax.Array        # bool[T] victims to evict
    job_pipelined: jax.Array  # bool[J] preemptor gangs that got capacity
    job_attempted: jax.Array  # bool[J]


def make_preempt_cycle(cfg: PreemptConfig):
    """Build the jittable preempt/reclaim pass.

    Signature: fn(snap, extras, victim_veto bool[T]) -> PreemptResult.
    ``extras`` reuses the allocate inputs (job/ns/queue shares, deserved).
    """
    reclaim = cfg.mode == "reclaim"

    def preempt(snap: SnapshotArrays, extras: AllocateExtras,
                victim_veto: jax.Array) -> PreemptResult:
        snap = jax.tree.map(jnp.asarray, snap)
        extras = jax.tree.map(jnp.asarray, extras)
        victim_veto = jnp.asarray(victim_veto)
        nodes, tasks, jobs, queues = snap.nodes, snap.tasks, snap.jobs, snap.queues
        N, R = nodes.idle.shape
        T = tasks.resreq.shape[0]
        J, M = jobs.task_table.shape
        queue_deserved = extras.queue_deserved

        occupying = jnp.zeros(T, bool)
        for s in _OCCUPYING:
            occupying |= tasks.status == s
        occupying &= tasks.valid & (tasks.node >= 0)

        # gang surplus: occupying count above minAvailable per job
        occ_per_job = jax.ops.segment_sum(
            occupying.astype(jnp.int32), jnp.maximum(tasks.job, 0),
            num_segments=J)
        surplus0 = jnp.maximum(occ_per_job - jobs.min_available, 0)

        waiting0 = jax.ops.segment_sum(
            (tasks.status == int(TaskStatus.PIPELINED)).astype(jnp.int32),
            jnp.maximum(tasks.job, 0), num_segments=J)

        # starving gangs are the preemptors (gang JobStarving, gang.go:150-155)
        starving = (jobs.valid & jobs.schedulable
                    & (jobs.ready_num + waiting0 < jobs.min_available)
                    & (jobs.n_pending > 0))

        # reclaim only serves underserved queues (reclaim.go:80-100)
        qshare = jnp.max(
            jnp.where(jnp.isfinite(queue_deserved) & (queue_deserved > 0),
                      queues.allocated / jnp.maximum(queue_deserved, 1e-9),
                      0.0), axis=-1)
        if reclaim:
            starving &= qshare[jobs.queue] < 1.0 - 1e-6

        future0 = nodes.future_idle()

        # static predicate rows per template (predicate-cache analog,
        # predicates/cache.go:42-90)
        tmpl_static = P.template_masks(nodes, tasks, snap.template_rep)

        init = dict(
            extra_idle=jnp.zeros((N, R), jnp.float32),   # from evictions
            pipe_extra=jnp.zeros((N, R), jnp.float32),   # new pipelines
            evicted=jnp.zeros(T, bool),
            surplus=surplus0,
            task_node=jnp.full(T, -1, jnp.int32),
            task_mode=jnp.zeros(T, jnp.int32),
            job_done=jnp.zeros(J, bool),
            job_pipelined=jnp.zeros(J, bool),
            saved=None,  # replaced below
            rounds=jnp.int32(0),
        )
        saved_keys = ("extra_idle", "pipe_extra", "evicted", "surplus",
                      "task_node", "task_mode")
        init["saved"] = {k: init[k] for k in saved_keys}

        def eligible(st):
            return starving & ~st["job_done"]

        def cond(st):
            return jnp.any(eligible(st)) & (st["rounds"] < J)

        def body(st):
            elig = eligible(st)
            keys = [
                extras.ns_share[jobs.namespace],
                jobs.namespace.astype(jnp.float32),
                qshare[jobs.queue] + extras.queue_share_extra[jobs.queue],
                jobs.queue.astype(jnp.float32),
                -jobs.priority.astype(jnp.float32),
                extras.job_share,
                jobs.creation_rank.astype(jnp.float32),
            ]
            ji, _ = lex_argmin(keys, elig)
            task_ids = jobs.task_table[ji]
            preemptor_prio = jobs.priority[ji]
            preemptor_share = extras.job_share[ji]
            preemptor_queue = jobs.queue[ji]

            def victim_ok(evicted, surplus):
                ok = occupying & ~evicted & ~victim_veto
                ok &= surplus[jnp.maximum(tasks.job, 0)] > 0
                if reclaim:
                    # cross-queue, victim queue reclaimable and overused
                    # (proportion Reclaimable, proportion.go:213-239)
                    vq = jobs.queue[jnp.maximum(tasks.job, 0)]
                    ok &= vq != preemptor_queue
                    ok &= queues.reclaimable[vq]
                    overused = jnp.any(
                        queues.allocated > queue_deserved + 1e-6, axis=-1)
                    ok &= overused[vq]
                else:
                    ok &= jobs.queue[jnp.maximum(tasks.job, 0)] == preemptor_queue
                    ok &= tasks.job != ji
                if cfg.enable_priority_rule:
                    ok &= jobs.priority[jnp.maximum(tasks.job, 0)] < preemptor_prio
                if cfg.enable_drf_rule:
                    ok &= extras.job_share[jnp.maximum(tasks.job, 0)] \
                        >= preemptor_share
                return ok

            def task_step(carry, t_idx):
                (extra_idle, pipe_extra, evicted, surplus,
                 t_node, t_mode, n_pipe) = carry
                active = (t_idx >= 0) & ~tasks.best_effort[jnp.maximum(t_idx, 0)]
                t = jnp.maximum(t_idx, 0)
                resreq = tasks.resreq[t]
                # GPU predicate runs with current card usage like the other
                # predicates do in the reference's preempt PredicateNodes
                # (preempt.go:216 -> ssn.PredicateFn -> gpu.go:27-56); the
                # static half comes from the per-template mask rows.
                base = (tmpl_static[tasks.template[t]]
                        & P.capacity_feasible(
                            nodes, jnp.zeros_like(resreq),
                            future0 + extra_idle, None,
                            gpu_request=tasks.gpu_request[t]))

                vok = victim_ok(evicted, surplus)
                evictable = jax.ops.segment_sum(
                    jnp.where(vok[:, None], tasks.resreq, 0.0),
                    jnp.where(vok, tasks.node, N), num_segments=N + 1)[:N]

                avail = future0 + extra_idle - pipe_extra
                enough = jnp.all(resreq[None, :] <= avail + evictable + 1e-5,
                                 axis=-1)
                feas = base & enough & active
                score = _score_fn(cfg.scoring, snap, resreq, nodes.idle,
                                  tasks.tol_hash[t], tasks.tol_effect[t],
                                  tasks.tol_mode[t])
                node = jnp.argmax(jnp.where(feas, score, NEG)).astype(jnp.int32)
                found = jnp.any(feas)

                # evict victims on `node`, lowest job/task priority first,
                # until the task fits future idle (preempt.go:240-278)
                def evict_cond(ec):
                    extra_idle, _evicted, _surplus, k = ec
                    fits = jnp.all(
                        resreq <= (extra_idle - pipe_extra + future0)[node] + 1e-5)
                    return found & ~fits & (k < cfg.max_victims_per_task)

                def evict_body(ec):
                    extra_idle, evicted, surplus, k = ec
                    vok_now = victim_ok(evicted, surplus) & (tasks.node == node)
                    vkeys = [
                        jobs.priority[jnp.maximum(tasks.job, 0)].astype(jnp.float32),
                        tasks.priority.astype(jnp.float32),
                    ]
                    vt, vfound = lex_argmin(vkeys, vok_now)
                    doit = vfound
                    extra_idle = extra_idle.at[node].add(
                        jnp.where(doit, 1.0, 0.0) * tasks.resreq[vt])
                    evicted = evicted.at[vt].set(evicted[vt] | doit)
                    surplus = surplus.at[jnp.maximum(tasks.job[vt], 0)].add(
                        jnp.where(doit, -1, 0))
                    return (extra_idle, evicted, surplus,
                            jnp.where(doit, k + 1, cfg.max_victims_per_task))

                extra_idle, evicted, surplus, _ = jax.lax.while_loop(
                    evict_cond, evict_body,
                    (extra_idle, evicted, surplus, jnp.int32(0)))

                fits = found & jnp.all(
                    resreq <= (extra_idle - pipe_extra + future0)[node] + 1e-5)
                pipe_extra = pipe_extra.at[node].add(
                    jnp.where(fits, 1.0, 0.0) * resreq)
                t_node = t_node.at[t].set(jnp.where(fits, node, t_node[t]))
                t_mode = t_mode.at[t].set(
                    jnp.where(fits, MODE_PIPELINED, t_mode[t]))
                n_pipe += jnp.where(fits, 1, 0)
                return (extra_idle, pipe_extra, evicted, surplus,
                        t_node, t_mode, n_pipe), None

            carry0 = (st["extra_idle"], st["pipe_extra"], st["evicted"],
                      st["surplus"], st["task_node"], st["task_mode"],
                      jnp.int32(0))
            (extra_idle, pipe_extra, evicted, surplus, t_node, t_mode,
             n_pipe), _ = jax.lax.scan(task_step, carry0, task_ids)

            pipelined = (jobs.ready_num[ji] + waiting0[ji] + n_pipe
                         >= jobs.min_available[ji])
            keep = pipelined

            new = dict(extra_idle=extra_idle, pipe_extra=pipe_extra,
                       evicted=evicted, surplus=surplus, task_node=t_node,
                       task_mode=t_mode)
            saved = st["saved"]
            job_tasks = tasks.job == ji
            merged = {}
            for k in saved_keys:
                if k in ("task_node", "task_mode"):
                    cleared = jnp.where(job_tasks, saved[k], new[k])
                    merged[k] = jnp.where(keep, new[k], cleared)
                else:
                    merged[k] = jnp.where(keep, new[k], saved[k])
            new_saved = {k: merged[k] for k in saved_keys}

            return dict(
                **merged,
                job_done=st["job_done"].at[ji].set(True),
                job_pipelined=st["job_pipelined"].at[ji].set(pipelined),
                saved=new_saved,
                rounds=st["rounds"] + 1,
            )

        final = jax.lax.while_loop(cond, body, init)
        return PreemptResult(
            task_node=final["task_node"],
            task_mode=final["task_mode"],
            evicted=final["evicted"],
            job_pipelined=final["job_pipelined"],
            job_attempted=final["job_done"],
        )

    return preempt
