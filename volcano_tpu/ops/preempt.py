"""Preempt and reclaim passes as compiled kernels.

TPU re-design of pkg/scheduler/actions/preempt/preempt.go:42-291 (intra-queue
preemption for starving gangs) and pkg/scheduler/actions/reclaim/
reclaim.go:40-191 (cross-queue reclaim for underserved queues).

Victim dispatch implements the reference's TIERED intersection exactly
(framework/session_plugins.go:131-215): within a tier, each enabled plugin
with a registered victim fn contributes a candidate set and the sets
intersect; the FIRST tier whose intersection is non-empty decides. Because
the reference calls Preemptable/Reclaimable once per (preemptor, node), the
winning tier is chosen PER NODE, and the resulting victim set is frozen for
that preemptor's eviction loop (preempt.go:218-258).

Per-plugin victim rules (all evaluated against LIVE in-cycle allocations,
the event-handler analog):

- priority: victim's job priority < preemptor's (priority.go:85-113),
- gang: same comparison in this fork (gang.go:83-103),
- drf: the victim job's dominant share after removal must stay >= the
  preemptor job's share after adding the preemptor task, within shareDelta
  (drf.go:336-358); shares recompute per eviction via the tracked
  job_alloc_dyn (AllocateFunc/DeallocateFunc, drf.go:511-561),
- conformance: host-supplied veto mask (critical pods / kube-system,
  conformance.go:45-63),
- tdm (preempt): a preemptable (or revocable-zone) preemptor gets an EMPTY
  set — poisoning its whole tier; otherwise candidates are preemptable
  Running tasks on non-revocable nodes (tdm.go:193-229). The per-job
  maxVictims disruption budget (tdm.go:219-229 -> getMaxPodEvictNum,
  tdm.go:304-340) is enforced in the eviction loop via the carried
  per-victim budget view (extras.job_victim_budget); the periodic
  victimTasks sweep applies the same cap host-side,
- proportion (reclaim): what-if queue arithmetic — victim only if its
  queue's allocation after removal still covers the queue's deserved share
  (proportion.go:213-239), against the live queue_alloc_dyn,
- drf hierarchy (reclaim): clone-tree what-if — add the reclaimer's
  request, subtract the candidate's, and keep the candidate only if the
  reclaimer's queue still orders strictly before the victim's in the hdrf
  comparison (drf.go:377-449).

The drf rule also implements the namespace-order pre-stage when enabled
(drf.go:285-334): cross-namespace candidates decide by weighted namespace
shares after the what-if move (tracked live in ns_alloc_dyn), falling to
the job rule within shareDelta.

Mode "preempt_intra" is the second phase of the preempt action
(preempt.go:145-186): each under-request job's pending tasks preempt
lower-task-priority Running tasks OF THE SAME JOB, committing per
preemptor task; phase-1 pipelined preemptors are excluded via the
``skip_tasks`` input (their status already left Pending in the
reference's session).

ValidateVictims' capacity check (util/scheduler_helper.go:240-255) is the
``future idle + evictable >= request`` test; victims evict lowest task
priority first (the inverted TaskOrderFn queue, preempt.go:228-233) until
the preemptor fits FutureIdle, then the preemptor pipelines. Documented
divergence: node ties break to the lowest index (the reference walks
nodes in sorted-score order with unstable ties).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Tuple

import jax
import jax.numpy as jnp

from ..api.types import TaskStatus
from ..arrays.schema import SnapshotArrays
from . import predicates as P
from .allocate_scan import MODE_PIPELINED, AllocateConfig, AllocateExtras, _score_fn
from .fairshare import dominant_share, hdrf_level_keys
from .select import NEG, lex_argmin

_DELTA = 1e-6  # drf shareDelta (drf.go:37)

#: victims selected per evict-loop iteration (exact prefix commit keeps
#: the one-per-iteration victim order/set; loop iterations cost ~hundreds
#: of microseconds on the axon platform regardless of body size)
EVICT_BATCH = 4


@dataclass(frozen=True)
class PreemptConfig:
    mode: str = "preempt"     # "preempt" | "preempt_intra" | "reclaim"
    scoring: AllocateConfig = AllocateConfig()
    #: victim-rule tiers (session_plugins.go:131-215): per tier, the names
    #: of plugins whose victim fn is registered AND enabled for this mode.
    #: Names: "priority", "gang", "drf", "conformance", "tdm" (preempt);
    #: "gang", "proportion", "drf_hdrf", "conformance" (reclaim).
    tiers: Tuple[Tuple[str, ...], ...] = (("priority", "gang"), ("drf",))
    #: tdm JobStarvingFn: preemptable jobs never preempt (tdm.go:292-298)
    tdm_starving: bool = False
    #: hdrf queue ordering for the preemptor pop (the drf queueOrderFn
    #: registered under hierarchy, drf.go:362-375), recomputed from the
    #: live job allocations each round
    enable_hdrf: bool = False
    max_victims_per_task: int = 16
    #: in-graph counter block (telemetry/cycle.PreemptTelemetry) appended
    #: to the result. Static, default off: the off-build's jaxpr carries
    #: zero telemetry equations (graphcheck family 7).
    telemetry: bool = False


@jax.tree_util.register_dataclass
@dataclass
class PreemptResult:
    task_node: jax.Array      # i32[T] pipelined placement of preemptor tasks
    task_mode: jax.Array      # i32[T] MODE_PIPELINED where placed
    evicted: jax.Array        # bool[T] victims to evict
    job_pipelined: jax.Array  # bool[J] preemptor gangs that got capacity
    job_attempted: jax.Array  # bool[J]
    #: telemetry/cycle.PreemptTelemetry when cfg.telemetry, else None
    telemetry: object = None


def _lex_row_less(kl: jax.Array, kr: jax.Array) -> jax.Array:
    """bool: key row kl orders strictly before kr (first differing column
    decides — the compareQueues walk over level keys)."""
    neq = kl != kr
    first = jnp.argmax(neq)
    return jnp.any(neq) & (kl[first] < kr[first])


def make_preempt_cycle(cfg: PreemptConfig):
    """Build the jittable preempt/reclaim pass.

    Signature: fn(snap, extras, victim_veto bool[T]) -> PreemptResult.
    ``extras`` reuses the allocate inputs (deserved shares, tdm masks, hdrf
    tree); ``victim_veto`` is the conformance rule's host-computed veto.
    """
    reclaim = cfg.mode == "reclaim"
    intra = cfg.mode == "preempt_intra"
    rule_names = [r for tier in cfg.tiers for r in tier]
    use_hdrf_rule = "drf_hdrf" in rule_names
    # the tdm Preemptable fn caps victims per preemptee job through the
    # disruption budget (tdm.go:219-229 maxVictims); enforce it in-kernel
    # whenever the tdm rule participates
    use_budget = "tdm" in rule_names

    def preempt(snap: SnapshotArrays, extras: AllocateExtras,
                victim_veto: jax.Array,
                skip_tasks=None) -> PreemptResult:
        snap = jax.tree.map(jnp.asarray, snap)
        extras = jax.tree.map(jnp.asarray, extras)
        victim_veto = jnp.asarray(victim_veto)
        if skip_tasks is None:
            skip = jnp.zeros(victim_veto.shape[0], bool)
        else:
            skip = jnp.asarray(skip_tasks)
        nodes, tasks, jobs, queues = snap.nodes, snap.tasks, snap.jobs, snap.queues
        N, R = nodes.idle.shape
        T = tasks.resreq.shape[0]
        J, M = jobs.task_table.shape
        queue_deserved = extras.queue_deserved
        total_cap = snap.cluster_capacity
        vjob = jnp.maximum(tasks.job, 0)
        vqueue = jobs.queue[vjob]
        # static per-victim projections hoisted out of the round loop:
        # [T]-index gathers serialize on TPU (~ms each at 100k tasks), so
        # anything constant per cycle gathers once here and anything that
        # moves with evictions rides the carry as a [T, R] view
        vprio = jobs.priority[vjob]
        vns = jobs.namespace[vjob]
        S_ns = snap.namespace_weight.shape[0]
        Q_q = queues.allocated.shape[0]
        # one-hot matmul views replace [T]-index gathers from small tables
        # (MXU-friendly; a [T] gather serializes)
        vns_onehot = (vns[:, None]
                      == jnp.arange(S_ns, dtype=jnp.int32)[None, :]
                      ).astype(jnp.float32)
        vq_onehot = (vqueue[:, None]
                     == jnp.arange(Q_q, dtype=jnp.int32)[None, :]
                     ).astype(jnp.float32)
        vdes = queue_deserved[vqueue]
        vreclaimable = queues.reclaimable[vqueue]
        # [T] per-victim remaining-eviction budget of its job (one hoisted
        # gather; maintained incrementally like valloc — the budget drops
        # by one per eviction under both budget flavors, tdm.go:304-340)
        vbudget0 = extras.job_victim_budget[vjob]
        vrevocable = extras.revocable_node[jnp.maximum(tasks.node, 0)]

        # victims must be Running with a real request (preempt.go:116-123,
        # reclaim.go:129-136)
        running = (tasks.status == int(TaskStatus.RUNNING)) & tasks.valid \
            & (tasks.node >= 0) & ~tasks.best_effort

        waiting0 = jax.ops.segment_sum(
            (tasks.status == int(TaskStatus.PIPELINED)).astype(jnp.int32),
            vjob, num_segments=J)

        qshare = jnp.max(
            jnp.where(jnp.isfinite(queue_deserved) & (queue_deserved > 0),
                      queues.allocated / jnp.maximum(queue_deserved, 1e-9),
                      0.0), axis=-1)
        overused = jnp.any(queues.allocated > queue_deserved + 1e-6, axis=-1)

        if reclaim:
            # reclaim serves jobs with pending tasks in non-overused queues
            # (reclaim.go:72-81, 94-97)
            starving = (jobs.valid & jobs.schedulable & (jobs.n_pending > 0)
                        & ~overused[jobs.queue])
        else:
            # preempt + preempt_intra share the underRequest criterion
            # (preempt.go:70-81)
            # gang JobStarving (gang.go:150-155)
            starving = (jobs.valid & jobs.schedulable
                        & (jobs.ready_num + waiting0 < jobs.min_available)
                        & (jobs.n_pending > 0))
            if cfg.tdm_starving:
                # tdm JobStarvingFn: preemptable jobs never preempt
                starving &= ~jobs.preemptable

        future0 = nodes.future_idle()

        # static predicate rows per template (predicate-cache analog,
        # predicates/cache.go:42-90)
        tmpl_static = P.template_masks(nodes, tasks, snap.template_rep)

        def or_ok_row(t):
            # per-task OR-of-terms node-affinity mask (arrays/pack.py note)
            grp = extras.task_or_group[t]
            return jnp.where(grp >= 0,
                             extras.or_feasible[jnp.maximum(grp, 0)], True)

        S = snap.namespace_weight.shape[0]
        ns_alloc0 = jax.ops.segment_sum(
            jnp.where(jobs.valid[:, None], jobs.allocated, 0.0),
            jnp.where(jobs.valid, jobs.namespace, S),
            num_segments=S + 1)[:S]
        init = dict(
            extra_idle=jnp.zeros((N, R), jnp.float32),   # from evictions
            pipe_extra=jnp.zeros((N, R), jnp.float32),   # new pipelines
            evicted=jnp.zeros(T, bool),
            task_node=jnp.full(T, -1, jnp.int32),
            task_mode=jnp.zeros(T, jnp.int32),
            job_done=jnp.zeros(J, bool),
            job_pipelined=jnp.zeros(J, bool),
            # live drf/proportion state (event handlers, drf.go:511-561,
            # proportion.go:281-325)
            job_alloc_dyn=jobs.allocated,
            # [T, R] per-victim view of its job's live allocation: the
            # job_alloc_dyn[vjob] gather hoisted to one trace-time gather
            # and maintained incrementally (a per-step [T] gather
            # serializes on TPU)
            valloc=jobs.allocated[vjob],
            queue_alloc_dyn=queues.allocated,
            ns_alloc_dyn=ns_alloc0,
            vbudget=vbudget0,
            saved=None,  # replaced below
            rounds=jnp.int32(0),
        )
        saved_keys = ("extra_idle", "pipe_extra", "evicted",
                      "task_node", "task_mode", "job_alloc_dyn",
                      "queue_alloc_dyn", "ns_alloc_dyn", "valloc",
                      "vbudget")
        init["saved"] = {k: init[k] for k in saved_keys}

        def eligible(st):
            return starving & ~st["job_done"]

        def cond(st):
            return jnp.any(eligible(st)) & (st["rounds"] < J)

        def victim_rule(name, t, ji, evicted, job_alloc_dyn, queue_alloc_dyn,
                        ns_alloc_dyn, valloc):
            """bool[T] candidate mask of one plugin's victim fn.

            ``valloc`` is the carried [T, R] per-victim view of its job's
            live allocation (the job_alloc_dyn[vjob] gather, maintained
            incrementally because a [T] gather serializes on TPU)."""
            pprio = jobs.priority[ji]
            if name == "priority" and intra:
                # same-job branch: task priorities (priority.go:99-107)
                return tasks.priority < tasks.priority[t]
            if name in ("priority", "gang"):
                return vprio < pprio
            if name == "conformance":
                return ~victim_veto
            if name == "tdm":
                # preemptable preemptors never preempt via tdm
                # (tdm.go:193-197); victims are preemptable Running tasks
                # on non-revocable nodes (tdm.go:199-218)
                abstain = tasks.preemptable[t]
                mask = tasks.preemptable & ~vrevocable
                return mask & ~abstain
            if name == "drf":
                hi = jax.lax.Precision.HIGHEST
                ls = dominant_share(
                    job_alloc_dyn[ji] + tasks.resreq[t], total_cap)
                rs = dominant_share(valloc - tasks.resreq, total_cap)
                job_rule = (ls < rs) | (jnp.abs(ls - rs) <= _DELTA)
                if not cfg.scoring.drf_ns_order:
                    return job_rule
                # namespace-share pre-stage (drf.go:285-334): cross-ns
                # candidates decide by weighted ns shares after the what-if
                # move; within shareDelta they fall through to the job rule
                nsw = jnp.maximum(snap.namespace_weight, 1.0)
                p_ns = jobs.namespace[ji]
                lns = dominant_share(
                    ns_alloc_dyn[p_ns] + tasks.resreq[t],
                    total_cap) / nsw[p_ns]
                # HIGHEST precision: the one-hot matmul is a row select,
                # and default TPU matmul precision (bf16 inputs) would
                # round the allocations the shareDelta compares
                rns = dominant_share(
                    jnp.matmul(vns_onehot, ns_alloc_dyn, precision=hi)
                    - tasks.resreq, total_cap) / nsw[vns]
                same_ns = vns == p_ns
                return jnp.where(
                    same_ns, job_rule,
                    (lns < rns)
                    | (((lns - rns) <= _DELTA) & job_rule))
            if name == "proportion":
                # queue what-if (proportion.go:217-236): enough allocation
                # to subtract, and deserved still covered afterwards
                # HIGHEST precision: row select must stay exact (the
                # 1e-6-tolerance coverage check below)
                q_alloc = jnp.matmul(vq_onehot, queue_alloc_dyn,
                                     precision=jax.lax.Precision.HIGHEST)
                des = vdes
                after = q_alloc - tasks.resreq
                has = ~jnp.all(q_alloc < tasks.resreq, axis=-1)
                covered = jnp.all(
                    jnp.where(jnp.isfinite(des), des <= after + 1e-6, True),
                    axis=-1)
                return has & covered
            raise ValueError(f"unknown victim rule {name!r}")

        def hdrf_rule(t, ji, job_alloc_dyn, pre):
            """drf_hdrf: clone-tree what-if (drf.go:377-449) — reclaimer
            added, candidate removed, reclaimer's queue must order strictly
            first in the hdrf comparison. Each what-if is a full tree
            solve, so it runs LAST in its tier and only for the first
            ``K`` candidates surviving the cheaper rules, in eviction-
            preference (task priority) order — exact whenever a node holds
            at most K candidates (bounded divergence, documented)."""
            K = min(64, T)
            base_alloc = job_alloc_dyn.at[ji].add(tasks.resreq[t])
            lq = jobs.queue[ji]
            order = jnp.argsort(
                jnp.where(pre, tasks.priority.astype(jnp.float32), jnp.inf))
            idx = order[:K]

            def what_if(v):
                alloc_v = base_alloc.at[tasks.job[v]].add(-tasks.resreq[v])
                keys = hdrf_level_keys(
                    extras.hierarchy, alloc_v, jobs.total_request,
                    jobs.valid, total_cap)
                return _lex_row_less(keys[lq], keys[vqueue[v]])

            ok = jax.vmap(what_if)(idx) & pre[idx]
            return jnp.zeros(T, bool).at[idx].set(ok)

        def victim_tier_masks(t, ji, evicted, job_alloc_dyn, queue_alloc_dyn,
                              ns_alloc_dyn, valloc):
            """Per-tier candidate masks [K_tiers x bool[T]] for one
            preemptor task (tiered dispatch, session_plugins.go:131-215).
            The per-NODE first-non-empty-tier selection happens lazily in
            the candidate-node walk — the old global scatter to [K, N]
            cost ~ms per task step on TPU."""
            vbase = running & ~evicted
            if reclaim:
                vbase &= (vqueue != jobs.queue[ji]) & vreclaimable
            elif intra:
                # phase 2: victims within the preemptor's own job
                # (preempt.go:168-175 filter)
                vbase &= tasks.job == ji
            else:
                vbase &= (vqueue == jobs.queue[ji]) & (tasks.job != ji)
            if not any(len(tier) for tier in cfg.tiers):
                # no plugin registered a victim fn: the reference dispatch
                # returns nil -> no victims at all (session_plugins.go:131)
                return jnp.zeros((1,) + vbase.shape, bool)
            tier_masks = []
            for tier in cfg.tiers:
                if not tier:
                    continue
                m = vbase
                for name in tier:
                    if name == "drf_hdrf":
                        continue     # expensive rule intersects last
                    m &= victim_rule(name, t, ji, evicted, job_alloc_dyn,
                                     queue_alloc_dyn, ns_alloc_dyn, valloc)
                if "drf_hdrf" in tier:
                    m = hdrf_rule(t, ji, job_alloc_dyn, m)
                tier_masks.append(m)
            return jnp.stack(tier_masks)                       # [K, T]

        def body(st):
            elig = eligible(st)
            keys = [
                extras.ns_share[jobs.namespace],
                jobs.namespace.astype(jnp.float32),
                qshare[jobs.queue] + extras.queue_share_extra[jobs.queue],
            ]
            if cfg.enable_hdrf:
                # hdrf compareQueues on the live tree (drf.go:362-375)
                hcols = hdrf_level_keys(
                    extras.hierarchy, st["job_alloc_dyn"],
                    jobs.total_request, jobs.valid, total_cap)
                for c in range(int(hcols.shape[1])):
                    keys.append(hcols[:, c][jobs.queue])
            keys += [
                jobs.queue.astype(jnp.float32),
                -jobs.priority.astype(jnp.float32),
                extras.job_share,
                jobs.creation_rank.astype(jnp.float32),
            ]
            ji, _ = lex_argmin(keys, elig)
            task_ids = jobs.task_table[ji]

            # ---- per-round, per-node evictable upper bound -------------
            # The t-INDEPENDENT relaxation of the tiered victim rules
            # (t-dependent rules — drf shares, proportion what-ifs, tdm
            # abstention, intra task-priority — relax to true), unioned
            # over tiers and summed per node: a sound over-approximation
            # of what any preemptor task of this job could ever free on a
            # node. One segment-sum per ROUND (a [T] scatter costs ~ms on
            # this chip, unaffordable per task step), decremented exactly
            # as evictions land.
            pprio_r = jobs.priority[ji]
            vbase_r = running & ~st["evicted"]
            if reclaim:
                vbase_r &= (vqueue != jobs.queue[ji]) & vreclaimable
            elif intra:
                vbase_r &= tasks.job == ji
            else:
                vbase_r &= (vqueue == jobs.queue[ji]) & (tasks.job != ji)
            ub_mask = jnp.zeros_like(vbase_r)
            any_tier = False
            for tier in cfg.tiers:
                if not tier:
                    continue
                any_tier = True
                m = vbase_r
                for name in tier:
                    if name in ("priority", "gang"):
                        if intra and name == "priority":
                            continue        # task-level rule: relax
                        m &= vprio < pprio_r
                    elif name == "conformance":
                        m &= ~victim_veto
                    elif name == "tdm":
                        m &= tasks.preemptable & ~vrevocable
                    # drf / proportion / drf_hdrf: t-dependent -> relax
                ub_mask |= m
            if not any_tier:
                ub_mask = jnp.zeros_like(vbase_r)
            ub_node0 = jax.ops.segment_sum(
                jnp.where(ub_mask[:, None], tasks.resreq, 0.0),
                jnp.where(ub_mask, tasks.node, N),
                num_segments=N + 1)[:N]

            # ---- round-level feasibility gate --------------------------
            # If NO pending slot of this job can fit any node even with
            # the full upper bound freed, every task step would fail
            # (exactly as the reference's per-task PredicateNodes walk
            # would) — skip the whole scan under one cond. This is what
            # keeps adversarial scale (hundreds of starving gangs that
            # cannot be served) from paying M task steps per hopeless job.
            slot_valid = task_ids >= 0
            t_m = jnp.maximum(task_ids, 0)
            resreq_m = tasks.resreq[t_m]                     # [M, R]
            stat_m = tmpl_static[tasks.template[t_m]]        # [M, N]
            or_m = jax.vmap(or_ok_row)(t_m)                  # [M, N]
            avail0_r = future0 + st["extra_idle"] - st["pipe_extra"]
            fit_m = jnp.all(
                resreq_m[:, None, :]
                <= (avail0_r + ub_node0)[None, :, :] + 1e-5, axis=-1)
            slot_ok = slot_valid & ~tasks.best_effort[t_m] & ~skip[t_m] \
                & jnp.any(stat_m & or_m & fit_m, axis=1)
            job_possible = jnp.any(slot_ok)

            def task_step(carry, t_idx):
                (extra_idle, pipe_extra, evicted, t_node, t_mode,
                 job_alloc_dyn, queue_alloc_dyn, ns_alloc_dyn, valloc,
                 vbudget, ub_node, n_pipe, broke) = carry
                active = (t_idx >= 0) & ~tasks.best_effort[jnp.maximum(t_idx, 0)]
                active &= ~skip[jnp.maximum(t_idx, 0)]
                if intra:
                    # phase 2 stops the job at the first unassigned task
                    # (preempt.go:181-184)
                    active &= ~broke
                if not reclaim and not intra:
                    # the preemptor loop stops once the job is no longer
                    # starving (preempt.go:99-101): pipelined tasks count
                    # toward the gang's waiting number
                    still_starving = (jobs.ready_num[ji] + waiting0[ji]
                                      + n_pipe < jobs.min_available[ji])
                    active &= still_starving
                t = jnp.maximum(t_idx, 0)
                resreq = tasks.resreq[t]
                # GPU predicate runs with current card usage like the other
                # predicates do in the reference's preempt PredicateNodes
                # (preempt.go:216 -> ssn.PredicateFn -> gpu.go:27-56); the
                # static half comes from the per-template mask rows.
                base = (tmpl_static[tasks.template[t]]
                        & or_ok_row(t)
                        & P.capacity_feasible(
                            nodes, jnp.zeros_like(resreq),
                            future0 + extra_idle, None,
                            gpu_request=tasks.gpu_request[t]))

                # the victim set is FROZEN for this preemptor's eviction
                # loop (preempt.go:218-233 builds it once per node)
                stacked = victim_tier_masks(t, ji, evicted, job_alloc_dyn,
                                            queue_alloc_dyn, ns_alloc_dyn,
                                            valloc)
                avail = future0 + extra_idle - pipe_extra
                score = _score_fn(cfg.scoring, snap, resreq, nodes.idle,
                                  tasks.tol_hash[t], tasks.tol_effect[t],
                                  tasks.tol_mode[t])

                def node_victims(n):
                    """Victim mask + freeable sum on candidate node n: the
                    first tier with any candidate on n wins, candidates
                    intersect within it (session_plugins.go:131-215)."""
                    on_n = tasks.node == n
                    t_has = jnp.any(stacked & on_n[None, :], axis=1)
                    ktier = jax.lax.argmax(t_has, 0, jnp.int32)
                    chosen = jnp.zeros_like(on_n)
                    for kk in range(stacked.shape[0]):
                        chosen = jnp.where(ktier == kk, stacked[kk], chosen)
                    vok_n = chosen & on_n & jnp.any(t_has)
                    ev_n = jnp.sum(
                        jnp.where(vok_n[:, None], tasks.resreq, 0.0), axis=0)
                    return vok_n, ev_n

                # Score-ordered candidate walk with early exit: the first
                # node (argmax, lowest-index ties) whose frozen victim set
                # plus available capacity covers the request — exactly the
                # `base & enough` argmax the old global segment-sum
                # computed, without its per-step [T]->[N] scatters. Walks
                # one node in the common case. Candidates are pruned by the
                # round's PER-NODE evictable upper bound (ub_node carry):
                # a node that cannot fit the request even with everything
                # evictable on it freed is never probed, so infeasible
                # tasks cost zero walk iterations instead of exhausting
                # the 64-iteration cap. The cap still hands any residue to
                # the exact global segment-sum path under lax.cond.
                iota_n = jnp.arange(N, dtype=jnp.int32)
                possible = base & jnp.all(
                    resreq[None, :] <= avail + ub_node + 1e-5, axis=-1)

                def cand_cond(c):
                    tried, found, _node, k = c
                    return ((~found) & jnp.any(possible & ~tried) & active
                            & (k < 64))

                def cand_body(c):
                    tried, _found, node0, k = c
                    cand = jax.lax.argmax(jnp.where(
                        possible & ~tried, score, jnp.float32(NEG)),
                        0, jnp.int32)
                    _vok_c, ev_c = node_victims(cand)
                    fits_c = jnp.all(resreq <= avail[cand] + ev_c + 1e-5)
                    return (tried | (iota_n == cand), fits_c,
                            jnp.where(fits_c, cand, node0), k + 1)

                tried, found, node, _k = jax.lax.while_loop(
                    cand_cond, cand_body,
                    (jnp.zeros(N, bool), jnp.bool_(False), jnp.int32(0),
                     jnp.int32(0)))

                def _exact_pick(args):
                    """Global per-node tier dispatch + victim aggregation
                    (the segment-sum path) — the walk's cap was hit, so
                    finish with one exact global argmax over the
                    untried candidates."""
                    tried, found0, node0 = args
                    node_idx = jnp.where(stacked, tasks.node[None, :], N)
                    n_tiers = stacked.shape[0]
                    node_any = jnp.zeros((n_tiers, N + 1), bool)
                    node_any = node_any.at[
                        jnp.arange(n_tiers, dtype=jnp.int32)[:, None],
                        node_idx].set(
                            True)[:, :N]
                    first_tier = jax.lax.argmax(node_any, 0, jnp.int32)
                    has_tier = jnp.any(node_any, axis=0)
                    pick = first_tier[jnp.maximum(tasks.node, 0)]
                    chosen = jnp.take_along_axis(
                        stacked, pick[None, :], axis=0)[0]
                    vok_g = chosen & has_tier[jnp.maximum(tasks.node, 0)]
                    evictable = jax.ops.segment_sum(
                        jnp.where(vok_g[:, None], tasks.resreq, 0.0),
                        jnp.where(vok_g, tasks.node, N),
                        num_segments=N + 1)[:N]
                    enough = jnp.all(
                        resreq[None, :] <= avail + evictable + 1e-5, axis=-1)
                    feas = possible & ~tried & enough
                    nd = jax.lax.argmax(
                        jnp.where(feas, score, jnp.float32(NEG)),
                        0, jnp.int32)
                    fnd = jnp.any(feas)
                    return (fnd, jnp.where(fnd, nd, node0))

                found, node = jax.lax.cond(
                    active & ~found & jnp.any(possible & ~tried),
                    _exact_pick, lambda a: (a[1], a[2]),
                    (tried, found, node))
                vok, _ = node_victims(node)

                # evict victims on `node`, lowest task priority first (the
                # inverted TaskOrderFn queue, preempt.go:228-233), until
                # the preemptor fits future idle. Batched: each while
                # iteration selects up to EVICT_BATCH victims in exact
                # order, committing only the prefix needed to fit — same
                # victim set and order as one-per-iteration, ~4x fewer
                # loop iterations (iterations cost ~hundreds of us on
                # this platform regardless of body size).
                def evict_cond(ec):
                    extra_idle = ec[0]
                    k = ec[-1]
                    fits = jnp.all(
                        resreq <= (extra_idle[node] - pipe_extra[node]
                                   + future0[node]) + 1e-5)
                    return found & ~fits & (k < cfg.max_victims_per_task)

                def evict_some(ec, go):
                    (extra_idle, evicted, job_alloc_dyn, queue_alloc_dyn,
                     ns_alloc_dyn, valloc, vbudget, ub_node, k) = ec
                    progressed = jnp.bool_(False)
                    for _b in range(EVICT_BATCH):
                        avail_n = (extra_idle[node] - pipe_extra[node]
                                   + future0[node])
                        fits_now = jnp.all(resreq <= avail_n + 1e-5)
                        vok_now = vok & ~evicted & (tasks.node == node)
                        if use_budget:
                            vok_now &= vbudget > 0
                        vt, vfound = lex_argmin(
                            [tasks.priority.astype(jnp.float32)], vok_now)
                        doit = (go & vfound & ~fits_now
                                & (k < cfg.max_victims_per_task))
                        dres = jnp.where(doit, jnp.float32(1.0),
                                         jnp.float32(0.0)) \
                            * tasks.resreq[vt]
                        extra_idle = extra_idle.at[node].add(dres)
                        ub_node = ub_node.at[node].add(-dres)
                        evicted = evicted.at[vt].set(evicted[vt] | doit)
                        # DeallocateFunc analog: live shares drop with the
                        # eviction (drf.go:537-561, proportion.go:300-325)
                        job_alloc_dyn = job_alloc_dyn.at[
                            tasks.job[vt]].add(-dres)
                        queue_alloc_dyn = queue_alloc_dyn.at[
                            vqueue[vt]].add(-dres)
                        ns_alloc_dyn = ns_alloc_dyn.at[
                            jobs.namespace[jnp.maximum(tasks.job[vt],
                                                       0)]].add(-dres)
                        valloc = valloc - (vjob == tasks.job[vt])[:, None] \
                            * dres
                        if use_budget:
                            vbudget = vbudget - (
                                (vjob == tasks.job[vt]) & doit)
                        k = k + jnp.where(doit, jnp.int32(1),
                                          jnp.int32(0))
                        progressed |= doit
                    # no victim found and still unfit: bail out exactly
                    # like the one-per-iteration loop did
                    k = jnp.where(progressed, k, cfg.max_victims_per_task)
                    return (extra_idle, evicted, job_alloc_dyn,
                            queue_alloc_dyn, ns_alloc_dyn, valloc,
                            vbudget, ub_node, k)

                (extra_idle, evicted, job_alloc_dyn, queue_alloc_dyn,
                 ns_alloc_dyn, valloc, vbudget, ub_node, _) = \
                    jax.lax.while_loop(
                        evict_cond,
                        lambda x: evict_some(x, jnp.bool_(True)),
                        (extra_idle, evicted, job_alloc_dyn,
                         queue_alloc_dyn, ns_alloc_dyn, valloc, vbudget,
                         ub_node, jnp.int32(0)))

                fits = found & jnp.all(
                    resreq <= (extra_idle - pipe_extra + future0)[node] + 1e-5)
                pipe_extra = pipe_extra.at[node].add(
                    jnp.where(fits, jnp.float32(1.0),
                              jnp.float32(0.0)) * resreq)
                # AllocateFunc analog for the pipelined preemptor
                pres = jnp.where(fits, jnp.float32(1.0),
                                 jnp.float32(0.0)) * resreq
                job_alloc_dyn = job_alloc_dyn.at[ji].add(pres)
                queue_alloc_dyn = queue_alloc_dyn.at[jobs.queue[ji]].add(pres)
                ns_alloc_dyn = ns_alloc_dyn.at[jobs.namespace[ji]].add(pres)
                valloc = valloc + (vjob == ji)[:, None] * pres
                t_node = t_node.at[t].set(jnp.where(fits, node, t_node[t]))
                t_mode = t_mode.at[t].set(
                    jnp.where(fits, MODE_PIPELINED, t_mode[t]))
                n_pipe += jnp.where(fits, jnp.int32(1), jnp.int32(0))
                broke |= active & ~fits
                return (extra_idle, pipe_extra, evicted, t_node, t_mode,
                        job_alloc_dyn, queue_alloc_dyn, ns_alloc_dyn,
                        valloc, vbudget, ub_node, n_pipe, broke), None

            carry0 = (st["extra_idle"], st["pipe_extra"], st["evicted"],
                      st["task_node"], st["task_mode"],
                      st["job_alloc_dyn"], st["queue_alloc_dyn"],
                      st["ns_alloc_dyn"], st["valloc"], st["vbudget"],
                      ub_node0, jnp.int32(0), jnp.bool_(False))

            def _run_scan(c0):
                out, _ = jax.lax.scan(task_step, c0, task_ids,
                                      unroll=min(int(M), 16))
                return out

            # hopeless jobs (no slot can fit even with the full bound
            # freed) skip the scan: identical to every task step failing
            (extra_idle, pipe_extra, evicted, t_node, t_mode,
             job_alloc_dyn, queue_alloc_dyn, ns_alloc_dyn, valloc,
             vbudget, _ub, n_pipe, _broke) = jax.lax.cond(
                job_possible, _run_scan, lambda c0: c0, carry0)

            pipelined = (jobs.ready_num[ji] + waiting0[ji] + n_pipe
                         >= jobs.min_available[ji])
            # phase 2 commits per preemptor task unconditionally
            # (preempt.go:177-180 stmt.Commit with no pipelined gate)
            keep = jnp.bool_(True) if intra else pipelined

            new = dict(extra_idle=extra_idle, pipe_extra=pipe_extra,
                       evicted=evicted, task_node=t_node, task_mode=t_mode,
                       job_alloc_dyn=job_alloc_dyn,
                       queue_alloc_dyn=queue_alloc_dyn,
                       ns_alloc_dyn=ns_alloc_dyn, valloc=valloc,
                       vbudget=vbudget)
            saved = st["saved"]
            job_tasks = tasks.job == ji
            merged = {}
            for k in saved_keys:
                if k in ("task_node", "task_mode"):
                    cleared = jnp.where(job_tasks, saved[k], new[k])
                    merged[k] = jnp.where(keep, new[k], cleared)
                else:
                    merged[k] = jnp.where(keep, new[k], saved[k])
            new_saved = {k: merged[k] for k in saved_keys}

            return dict(
                **merged,
                job_done=st["job_done"].at[ji].set(True),
                job_pipelined=st["job_pipelined"].at[ji].set(pipelined),
                saved=new_saved,
                rounds=st["rounds"] + 1,
            )

        final = jax.lax.while_loop(cond, body, init)
        tel = None
        if cfg.telemetry:
            # counts derived from the final decision arrays — still
            # in-graph (one fetch with the result), no extra carry state
            from ..telemetry.cycle import PreemptTelemetry
            tel = PreemptTelemetry(
                evicted=jnp.sum(final["evicted"], dtype=jnp.int32),
                pipelined_tasks=jnp.sum(
                    final["task_mode"] == MODE_PIPELINED, dtype=jnp.int32),
                attempted_jobs=jnp.sum(final["job_done"], dtype=jnp.int32),
                pipelined_jobs=jnp.sum(final["job_pipelined"],
                                       dtype=jnp.int32),
                rounds=final["rounds"].astype(jnp.int32))
        return PreemptResult(
            task_node=final["task_node"],
            task_mode=final["task_mode"],
            evicted=final["evicted"],
            job_pipelined=final["job_pipelined"],
            job_attempted=final["job_done"],
            telemetry=tel,
        )

    return preempt
