"""Backfill pass: place zero-request (BestEffort) pending tasks.

TPU re-design of pkg/scheduler/actions/backfill/backfill.go:40-93: every
pending task with an empty resource request is placed on any node passing
predicates (the reference has no scoring here — "TODO" in source); placement
is immediate, with no gang transaction. Divergence: the reference iterates a
Go map (nondeterministic node order); we take the lowest feasible node index.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..arrays.schema import SnapshotArrays
from . import predicates as P


def make_backfill_pass(telemetry: bool = False):
    """Returns backfill(snap, task_or_group=None, or_feasible=None) ->
    (task_node i32[T], placed bool[T]). The optional pair is the
    OR-of-terms node-affinity group mask (arrays/pack.py note) — required
    affinity binds best-effort tasks too (backfill.go runs the same
    PredicateFn).

    ``telemetry`` (static, default off) appends an in-graph
    BackfillTelemetry counter block (telemetry/cycle.py) as a third
    output; the off-build traces not one extra equation."""

    def backfill(snap: SnapshotArrays, task_or_group=None, or_feasible=None):
        snap = jax.tree.map(jnp.asarray, snap)
        if task_or_group is None:
            task_or_group = jnp.full(snap.tasks.status.shape[0], -1,
                                     jnp.int32)
            or_feasible = jnp.ones((1, snap.nodes.pod_count.shape[0]), bool)
        else:
            task_or_group = jnp.asarray(task_or_group)
            or_feasible = jnp.asarray(or_feasible)
        nodes, tasks, jobs = snap.nodes, snap.tasks, snap.jobs
        T = tasks.resreq.shape[0]
        N = nodes.idle.shape[0]

        from ..api.types import TaskStatus
        candidate = (tasks.valid & tasks.best_effort
                     & (tasks.status == int(TaskStatus.PENDING))
                     & jobs.schedulable[jnp.maximum(tasks.job, 0)]
                     & (tasks.job >= 0))

        # per-template static predicate rows (predicate-cache analog)
        tmpl_static = P.template_masks(nodes, tasks, snap.template_rep)

        def step(carry, t):
            pods_extra, t_node, placed = carry
            grp = task_or_group[t]
            or_ok = jnp.where(grp >= 0, or_feasible[jnp.maximum(grp, 0)],
                              True)
            feas = (tmpl_static[tasks.template[t]] & or_ok
                    & P.capacity_feasible(nodes, tasks.resreq[t], nodes.idle,
                                          pods_extra))
            node = jax.lax.argmax(feas, 0, jnp.int32)  # lowest feasible index
            ok = candidate[t] & jnp.any(feas)
            pods_extra = pods_extra.at[node].add(
                jnp.where(ok, jnp.int32(1), jnp.int32(0)))
            t_node = t_node.at[t].set(jnp.where(ok, node, -1))
            placed = placed.at[t].set(ok)
            return (pods_extra, t_node, placed), None

        init = (jnp.zeros(N, jnp.int32), jnp.full(T, -1, jnp.int32),
                jnp.zeros(T, bool))
        (_, t_node, placed), _ = jax.lax.scan(
            step, init, jnp.arange(T, dtype=jnp.int32))
        if telemetry:
            from ..telemetry.cycle import BackfillTelemetry
            tel = BackfillTelemetry(
                candidates=jnp.sum(candidate, dtype=jnp.int32),
                placed=jnp.sum(placed, dtype=jnp.int32))
            return t_node, placed, tel
        return t_node, placed

    return backfill
