"""Feasibility-mask kernels: one task vs all nodes, vectorized.

TPU re-design of the reference's predicate plugins
(pkg/scheduler/plugins/predicates/predicates.go:181-288 wrapping the k8s
filters NodeUnschedulable, NodeAffinity, TaintToleration + pod count) and
of the parallel PredicateNodes helper
(pkg/scheduler/util/scheduler_helper.go:74-130): the 16-goroutine fan-out
becomes a single masked vector op over the node axis. The NodePorts filter
(predicates.go:191) and the volume-binding seam live in the allocate
kernel itself (ops/allocate_scan.py) because both need in-cycle placement
state; InterPodAffinity is the affinity encoding (arrays/affinity.py).

All functions are shape-polymorphic jittable JAX; none contain Python control
flow on traced values.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..arrays.labels import (EFFECT_NO_EXECUTE, EFFECT_NO_SCHEDULE, TOL_EQUAL,
                             TOL_EXISTS_ALL, TOL_EXISTS_KEY)
from ..arrays.schema import NodeArrays

_EPS = 1e-5


def resource_fit(resreq: jax.Array, avail: jax.Array) -> jax.Array:
    """bool[N]: does ``resreq`` f32[R] fit into ``avail`` f32[N, R]?

    Matches Resource.LessEqual zero semantics (resource_info.go:376-414):
    absent dims are zero in the packed vectors, so plain <= suffices.
    """
    return jnp.all(resreq[None, :] <= avail + _EPS, axis=-1)


def selector_match(selector: jax.Array, node_labels: jax.Array) -> jax.Array:
    """bool[N]: every nonzero required hash present in the node's label set.

    Kernel form of nodeaffinity/nodeselector matching (predicates.go: the
    NodeAffinity filter); selector i32[K], node_labels i32[N, L].
    """
    # present[n, k] = any_l labels[n, l] == selector[k]
    present = jnp.any(node_labels[:, None, :] == selector[None, :, None], axis=-1)
    return jnp.all((selector == 0)[None, :] | present, axis=-1)


def toleration_covers(tol_hash: jax.Array, tol_effect: jax.Array,
                      tol_mode: jax.Array, nodes: NodeArrays) -> jax.Array:
    """bool[N, E]: does any of the task's tolerations cover taint e of node n?

    Shared by the hard-taint filter below and the PreferNoSchedule scorer
    (scoring.taint_prefer_score) so filter and scorer can never disagree.
    """
    kv, key, eff = nodes.taint_kv, nodes.taint_key, nodes.taint_effect
    # match[n, e, o]: toleration o covers taint e of node n
    m_all = (tol_mode == TOL_EXISTS_ALL)[None, None, :]
    m_key = ((tol_mode == TOL_EXISTS_KEY)[None, None, :]
             & (key[:, :, None] == tol_hash[None, None, :]))
    m_eq = ((tol_mode == TOL_EQUAL)[None, None, :]
            & (kv[:, :, None] == tol_hash[None, None, :]))
    eff_ok = ((tol_effect == 0)[None, None, :]
              | (tol_effect[None, None, :] == eff[:, :, None]))
    return jnp.any((m_all | m_key | m_eq) & eff_ok, axis=-1)


def taints_tolerated(tol_hash: jax.Array, tol_effect: jax.Array,
                     tol_mode: jax.Array, nodes: NodeArrays) -> jax.Array:
    """bool[N]: no hard-effect node taint left untolerated.

    Kernel form of the TaintToleration filter: a taint with effect NoSchedule
    or NoExecute blocks unless some toleration matches it;
    PreferNoSchedule never blocks (it only scores, see scoring.py).
    tol_* are i32[O]; taint tensors are i32[N, E].
    """
    eff = nodes.taint_effect
    covered = toleration_covers(tol_hash, tol_effect, tol_mode, nodes)
    hard = (eff == EFFECT_NO_SCHEDULE) | (eff == EFFECT_NO_EXECUTE)
    return jnp.all(~hard | covered, axis=-1)


def pod_count_fit(nodes: NodeArrays, extra: jax.Array | None = None) -> jax.Array:
    """bool[N]: node has pod slots left (the CheckNodeUnschedulable +
    pod-number predicate, predicates.go:213-230). ``extra`` i32[N] adds
    in-cycle placements."""
    count = nodes.pod_count if extra is None else nodes.pod_count + extra
    return count < nodes.max_pods


def gpu_fit(gpu_request: jax.Array, nodes: NodeArrays,
            gpu_extra: jax.Array | None = None) -> jax.Array:
    """bool[N]: some single GPU card has enough idle memory for the request.

    Kernel form of the GPU-sharing predicate (checkNodeGPUSharingPredicate +
    predicateGPU, pkg/scheduler/plugins/predicates/gpu.go:27-56): a shared-GPU
    task must fit on ONE card, not in the node's aggregate GPU memory.
    ``gpu_extra`` f32[N, G] adds in-cycle placements.
    """
    idle = nodes.gpu_memory - nodes.gpu_used
    if gpu_extra is not None:
        idle = idle - gpu_extra
    return (gpu_request <= 0) | jnp.any(idle >= gpu_request - _EPS, axis=-1)


def pick_gpu(gpu_request: jax.Array, nodes: NodeArrays,
             gpu_extra: jax.Array | None = None) -> jax.Array:
    """i32[N]: per node, the lowest card id fitting the request, -1 if none
    (or no GPU requested). Reference: predicateGPU scans devID ascending
    (gpu.go:46-55)."""
    idle = nodes.gpu_memory - nodes.gpu_used
    if gpu_extra is not None:
        idle = idle - gpu_extra
    fits = idle >= gpu_request - _EPS
    first = jax.lax.argmax(fits, fits.ndim - 1, jnp.int32)
    ok = jnp.any(fits, axis=-1) & (gpu_request > 0)
    return jnp.where(ok, first, -1)


def static_feasible(nodes: NodeArrays, selector: jax.Array,
                    tol_hash: jax.Array, tol_effect: jax.Array,
                    tol_mode: jax.Array) -> jax.Array:
    """bool[N]: the capacity-independent predicate conjunction for one
    selector/toleration signature — everything in :func:`feasible` that does
    not depend on in-cycle idle/pod-count/GPU state."""
    return (nodes.valid
            & nodes.schedulable
            & selector_match(selector, nodes.labels)
            & taints_tolerated(tol_hash, tol_effect, tol_mode, nodes))


def template_masks(nodes: NodeArrays, tasks, template_rep: jax.Array) -> jax.Array:
    """bool[P, N]: static feasibility per predicate template, computed once
    per cycle.

    The TPU analog of the reference's predicate cache (plugins/predicates/
    cache.go:42-90): tasks sharing a pod template share the static predicate
    result; here the "cache fill" is one vmapped pass over template
    representatives and the "cache hit" is a row gather in the allocate scan.
    Unlike the reference's never-invalidated map, this recomputes from the
    fresh snapshot every cycle, so it cannot go stale.
    """
    rep = jnp.maximum(jnp.asarray(template_rep), 0)
    sel = jnp.asarray(tasks.selector)
    th = jnp.asarray(tasks.tol_hash)
    te = jnp.asarray(tasks.tol_effect)
    tm = jnp.asarray(tasks.tol_mode)

    def one(ti):
        return static_feasible(nodes, sel[ti], th[ti], te[ti], tm[ti])

    return jax.vmap(one)(rep)


def capacity_feasible(nodes: NodeArrays, resreq: jax.Array, avail: jax.Array,
                      extra_pods: jax.Array | None = None,
                      gpu_request: jax.Array | None = None,
                      gpu_extra: jax.Array | None = None) -> jax.Array:
    """bool[N]: the capacity-dependent half of :func:`feasible` (resource
    fit, pod slots, single-card GPU fit) — AND with a template_masks row to
    reconstruct the full conjunction."""
    mask = pod_count_fit(nodes, extra_pods) & resource_fit(resreq, avail)
    if gpu_request is not None:
        mask &= gpu_fit(gpu_request, nodes, gpu_extra)
    return mask


def pick_gpu_row(gpu_request: jax.Array, mem_row: jax.Array,
                 used_row: jax.Array, extra_row: jax.Array) -> jax.Array:
    """i32 scalar: lowest fitting card on ONE node's card row (O(G), for the
    allocate inner scan where only the chosen node's pick is needed)."""
    idle = mem_row - used_row - extra_row
    fits = idle >= gpu_request - _EPS
    first = jax.lax.argmax(fits, 0, jnp.int32)
    ok = jnp.any(fits) & (gpu_request > 0)
    return jnp.where(ok, first, -1)


def rejection_count(live: jax.Array, ok: jax.Array) -> jax.Array:
    """i32: live (valid AND schedulable) nodes that FAIL predicate mask
    ``ok`` — the per-family rejection counter primitive of the in-graph
    cycle telemetry (telemetry/cycle.PRED_FAMILIES). Families are counted
    independently: each family's count is over its own mask alone, so one
    node failing three families contributes to all three (the aggregate
    analog of the reference's per-plugin predicate error strings)."""
    return jnp.sum(live & ~ok, dtype=jnp.int32)


def feasible(nodes: NodeArrays, resreq: jax.Array, selector: jax.Array,
             tol_hash: jax.Array, tol_effect: jax.Array, tol_mode: jax.Array,
             avail: jax.Array, extra_pods: jax.Array | None = None,
             gpu_request: jax.Array | None = None,
             gpu_extra: jax.Array | None = None) -> jax.Array:
    """bool[N]: full predicate conjunction for one task against every node.

    ``avail`` chooses the capacity view: current idle for immediate
    allocation, future idle for pipelining (allocate.go:200-240 candidate
    split vs Idle/FutureIdle).
    """
    mask = (nodes.valid
            & nodes.schedulable
            & pod_count_fit(nodes, extra_pods)
            & resource_fit(resreq, avail)
            & selector_match(selector, nodes.labels)
            & taints_tolerated(tol_hash, tol_effect, tol_mode, nodes))
    if gpu_request is not None:
        mask &= gpu_fit(gpu_request, nodes, gpu_extra)
    return mask
