"""Pallas TPU kernel: one fused placement round of the allocate pass.

The hot inner loop of the cycle places the M pending tasks of the selected
gang one by one (capacity feedback between placements is what makes the pass
exact, SURVEY.md section 7 hard part 1). The pure-XLA path runs it as a
``lax.scan`` whose every step issues ~40 small HLO ops over [N]-shaped
arrays; this kernel fuses the WHOLE round into one ``pl.pallas_call`` with
the capacity state (idle, pipelined-extra, pod counts, per-GPU-card usage)
resident in VMEM across all M placements — one kernel launch per round
instead of M x ~40.

Layout: node-axis tensors are transposed to [R, N] / [G, N] so the node axis
is the 128-lane dimension (R/G are tiny; [N, R] would waste 32x lanes).

Semantics are bit-identical to the scan path in allocate_scan.task_step
(asserted by tests/test_pallas_place.py): same feasibility conjunction, same
score formulas (ops/scoring.py), same lowest-index argmax tie-break
(ops/select.py best_node), same lowest-fitting-card GPU pick
(ops/predicates.py pick_gpu_row).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .allocate_scan import MODE_ALLOCATED, MODE_NONE, MODE_PIPELINED

_EPS_FIT = 1e-5     # predicates._EPS
_EPS_DIV = 1e-9     # scoring._EPS
NEG = -1e30         # select.NEG


def _dyn_score(cfg, idle, alloc_t, rr_col):
    """Idle-dependent score terms in [R, N] layout — transposed but
    float-op-for-float-op identical to ops/scoring.py (reductions run over
    the same R elements in the same order, so f32 results match bitwise)."""
    used = alloc_t - idle
    N = idle.shape[1]
    score = jnp.zeros((1, N), jnp.float32)
    if cfg.binpack_weight:
        applicable = (rr_col > 0) & (alloc_t > 0)   # weights all-ones
        frac = jnp.where(applicable,
                         (used + rr_col) / jnp.maximum(alloc_t, _EPS_DIV), 0.0)
        over = frac > 1.0 + 1e-6
        w = 1.0 * applicable
        wsum = jnp.sum(w, axis=0, keepdims=True)
        raw = jnp.sum(frac * w, axis=0, keepdims=True) \
            / jnp.maximum(wsum, _EPS_DIV)
        raw = jnp.where(jnp.any(over, axis=0, keepdims=True), 0.0, raw)
        score += cfg.binpack_weight * raw * 100.0
    if cfg.least_allocated_weight:
        cap = jnp.maximum(alloc_t, _EPS_DIV)
        free_frac = (alloc_t - used - rr_col) / cap
        counted = alloc_t > 0
        n = jnp.maximum(jnp.sum(counted, axis=0, keepdims=True), 1)
        score += cfg.least_allocated_weight * (
            jnp.sum(jnp.clip(free_frac, 0.0, 1.0) * counted, axis=0,
                    keepdims=True) / n * 100.0)
    if cfg.most_allocated_weight:
        cap = jnp.maximum(alloc_t, _EPS_DIV)
        used_frac = (used + rr_col) / cap
        counted = alloc_t > 0
        n = jnp.maximum(jnp.sum(counted, axis=0, keepdims=True), 1)
        score += cfg.most_allocated_weight * (
            jnp.sum(jnp.clip(used_frac, 0.0, 1.0) * counted, axis=0,
                    keepdims=True) / n * 100.0)
    if cfg.balanced_weight:
        cap = jnp.maximum(alloc_t, _EPS_DIV)
        frac = jnp.clip((used + rr_col) / cap, 0.0, 1.0)
        counted = (alloc_t > 0).astype(frac.dtype)
        n = jnp.maximum(jnp.sum(counted, axis=0, keepdims=True), 1.0)
        mean = jnp.sum(frac * counted, axis=0, keepdims=True) / n
        var = jnp.sum(((frac - mean) ** 2) * counted, axis=0,
                      keepdims=True) / n
        score += cfg.balanced_weight * (1.0 - jnp.sqrt(var)) * 100.0
    return score


def _round_kernel(cfg, M, N, R, G,
                  # inputs
                  resreq_t_ref, gpu_req_ref, active_ref, pref_ref,
                  suffix_ref, meta_ref, sfeas_ref,
                  sscore_ref, sscore2_ref, relmp_ref, alloc_t_ref, cnt_ref,
                  maxp_ref, gidle0_ref, idle_ref, pipe_ref, podsx_ref,
                  gpux_ref,
                  # outputs
                  node_ref, mode_ref, gpu_ref,
                  idle_o_ref, pipe_o_ref, podsx_o_ref, gpux_o_ref):
    relmp = relmp_ref[:]
    alloc_t = alloc_t_ref[:]
    cnt = cnt_ref[:]
    maxp = maxp_ref[:]
    gidle0 = gidle0_ref[:]
    resreq_t = resreq_t_ref[:]      # [R, M]
    gpu_req = gpu_req_ref[:]        # [1, M]
    active_v = active_ref[:]        # [1, M] int32
    pref_v = pref_ref[:]            # [1, M] int32
    suffix_v = suffix_ref[:]        # [1, M] i32 queued tasks after slot m
    meta_v = meta_ref[:]            # [1, M] i32: [0]=ready0, [1]=min_avail
    # sfeas/sscore/sscore2 [M, N] stay in their refs: the per-task row comes
    # out as a dynamic SUBLANE slice below instead of a one-hot [M, N]
    # reduction (which re-read the whole matrix every task — 3 x M x N x 4B
    # per round of avoidable VMEM traffic)
    iota_n = jax.lax.broadcasted_iota(jnp.int32, (1, N), 1)
    iota_g = jax.lax.broadcasted_iota(jnp.int32, (G, 1), 0)
    iota_m = jax.lax.broadcasted_iota(jnp.int32, (1, M), 1)
    iota_m_col = jax.lax.broadcasted_iota(jnp.int32, (M, 1), 0)
    ready0 = jnp.sum(jnp.where(iota_m == 0, meta_v, 0))
    min_avail = jnp.sum(jnp.where(iota_m == 1, meta_v, 0))
    can_batch = jnp.sum(jnp.where(iota_m == 2, meta_v, 0)) > 0

    def body(m, carry):
        # mosaic has no dynamic lane/sublane indexing, so the per-task row
        # selections are one-hot reductions
        (idle, pipe, podsx, gpux, node_v, mode_v, gpuc_v,
         n_allocs, stopped, broke) = carry
        sel_m = (iota_m == m).astype(jnp.float32)            # [1,M]
        rr_col = jnp.sum(resreq_t * sel_m, axis=1, keepdims=True)   # [R,1]
        gr = jnp.sum(gpu_req * sel_m, axis=1, keepdims=True)        # [1,1]
        act = jnp.sum(active_v * sel_m.astype(jnp.int32), axis=1,
                      keepdims=True)                                # [1,1]
        pref = jnp.sum(pref_v * sel_m.astype(jnp.int32), axis=1,
                       keepdims=True)                               # [1,1]
        suffix = jnp.sum(jnp.where(iota_m == m, suffix_v, 0))       # scalar
        row = (pl.dslice(m, 1), slice(None))
        sfeas_m = sfeas_ref[row]                                    # [1,N]
        sscore_m = sscore_ref[row]
        sscore2_m = sscore2_ref[row]

        future = jnp.maximum(idle + relmp - pipe, 0.0)
        pods_ok = (cnt + podsx) < maxp
        gidle = gidle0 - gpux
        gpu_ok = (gr <= 0) | jnp.any(gidle >= gr - _EPS_FIT, axis=0,
                                     keepdims=True)
        shared = (sfeas_m > 0) & pods_ok & gpu_ok
        fit_now = jnp.all(rr_col <= idle + _EPS_FIT, axis=0, keepdims=True)
        fit_fut = jnp.all(rr_col <= future + _EPS_FIT, axis=0, keepdims=True)
        feas_now = shared & fit_now
        feas_fut = shared & fit_fut

        # addition order matches allocate_scan exactly (float associativity):
        # dyn terms (binpack..balanced), then taint-static, then the
        # combined nodeaffinity+tdm static term, then preference
        score = _dyn_score(cfg, idle, alloc_t, rr_col)
        score = score + sscore_m
        score = score + sscore2_m
        score = score + jnp.where((pref >= 0) & (iota_n == pref),
                                  100.0, 0.0)

        def pick(feas):
            # scalar reductions go through int32 (mosaic cannot squeeze
            # bool arrays to scalars)
            masked = jnp.where(feas, score, NEG)
            best = jnp.max(masked)
            idx = jnp.min(jnp.where(masked == best, iota_n, N))
            found = jnp.max(feas.astype(jnp.int32)) > 0
            return idx, found

        n_now, found_now = pick(feas_now)
        n_fut, found_fut = pick(feas_fut)
        # yield/break state gates the attempt (allocate.go:205-266): after a
        # ready-job yield or an unplaceable task, remaining slots are no-ops
        active = (act[0, 0] > 0) & ~stopped & ~broke
        can_now = found_now & active
        can_fut = found_fut & active & bool(cfg.enable_pipelining)
        do_alloc = can_now
        do_pipe = (~can_now) & can_fut
        placed = do_alloc | do_pipe
        node = jnp.where(do_alloc, n_now, n_fut)

        onehot = (iota_n == node).astype(jnp.float32)               # [1,N]
        idle = idle - jnp.where(do_alloc, 1.0, 0.0) * rr_col * onehot
        pipe = pipe + jnp.where(do_pipe, 1.0, 0.0) * rr_col * onehot
        podsx = podsx + jnp.where(placed, 1.0, 0.0) * onehot

        # lowest fitting card on the chosen node (pick_gpu_row)
        gcol = jnp.sum(gidle * onehot, axis=1, keepdims=True)       # [G,1]
        gfits = gcol >= gr - _EPS_FIT
        card = jnp.min(jnp.where(gfits, iota_g, G))
        gpu_ok_pick = (jnp.max(gfits.astype(jnp.int32)) > 0) & (gr[0, 0] > 0)
        card = jnp.where(gpu_ok_pick, card, -1)
        charge = placed & (card >= 0)
        gpux = gpux + (jnp.where(charge, 1.0, 0.0) * gr
                       * (iota_g == jnp.maximum(card, 0)) * onehot)

        mode = jnp.where(do_alloc, MODE_ALLOCATED,
                         jnp.where(do_pipe, MODE_PIPELINED, MODE_NONE))
        is_m = iota_m == m
        node_v = jnp.where(is_m, jnp.where(placed, node, -1), node_v)
        mode_v = jnp.where(is_m, mode, mode_v)
        gpuc_v = jnp.where(is_m, jnp.where(charge, card, -1), gpuc_v)
        n_allocs = n_allocs + jnp.where(do_alloc, 1, 0)
        if cfg.enable_gang:
            ready_aft = (ready0 + n_allocs) >= min_avail
        else:
            ready_aft = True
        stopped = stopped | (placed & ready_aft & (suffix > 0) & ~can_batch)
        broke = broke | (active & ~placed)
        return (idle, pipe, podsx, gpux, node_v, mode_v, gpuc_v,
                n_allocs, stopped, broke)

    neg1 = jnp.full((1, M), -1, jnp.int32)
    (idle, pipe, podsx, gpux, node_v, mode_v, gpuc_v,
     _n_allocs, _stopped, _broke) = jax.lax.fori_loop(
        0, M, body,
        (idle_ref[:], pipe_ref[:], podsx_ref[:], gpux_ref[:],
         neg1, jnp.zeros((1, M), jnp.int32), neg1,
         jnp.int32(0), jnp.bool_(False), jnp.bool_(False)))
    node_ref[:] = node_v
    mode_ref[:] = mode_v
    gpu_ref[:] = gpuc_v
    idle_o_ref[:] = idle
    pipe_o_ref[:] = pipe
    podsx_o_ref[:] = podsx
    gpux_o_ref[:] = gpux


def make_round_placer(cfg, M: int, N: int, R: int, G: int,
                      interpret: bool = False):
    """Build the fused round placer.

    Returns place(resreq_t [R,M], gpu_req [1,M], active [1,M], pref [1,M],
    suffix [1,M] (queued tasks after each slot), meta [1,M] ([0]=ready
    count, [1]=minAvailable, [2]=can-batch flag), sfeas [M,N],
    sscore [M,N] (taint-static), sscore2 [M,N] (nodeaffinity+tdm static),
    relmp [R,N], alloc_t [R,N], cnt [1,N], maxp [1,N], gidle0 [G,N],
    idle [R,N], pipe [R,N], podsx [1,N], gpux [G,N])
    -> (node [M], mode [M], gpu [M], idle', pipe', podsx', gpux').
    """
    kernel = functools.partial(_round_kernel, cfg, M, N, R, G)
    f32 = jnp.float32

    def place(resreq_t, gpu_req, active, pref, suffix, meta, sfeas, sscore,
              sscore2, relmp, alloc_t, cnt, maxp, gidle0, idle, pipe, podsx,
              gpux):
        outs = pl.pallas_call(
            kernel,
            out_shape=(
                jax.ShapeDtypeStruct((1, M), jnp.int32),   # node
                jax.ShapeDtypeStruct((1, M), jnp.int32),   # mode
                jax.ShapeDtypeStruct((1, M), jnp.int32),   # gpu
                jax.ShapeDtypeStruct((R, N), f32),         # idle'
                jax.ShapeDtypeStruct((R, N), f32),         # pipe'
                jax.ShapeDtypeStruct((1, N), f32),         # podsx'
                jax.ShapeDtypeStruct((G, N), f32),         # gpux'
            ),
            interpret=interpret,
        )(resreq_t, gpu_req, active, pref, suffix, meta, sfeas, sscore,
          sscore2, relmp, alloc_t, cnt, maxp, gidle0, idle, pipe, podsx,
          gpux)
        node, mode, gpu, idle2, pipe2, podsx2, gpux2 = outs
        return (node[0], mode[0], gpu[0], idle2, pipe2, podsx2, gpux2)

    return place


def vmem_estimate_bytes(M: int, N: int, R: int, G: int) -> int:
    """Rough VMEM footprint of the kernel's live values."""
    per_n = (4 * R * 6 + 4 * G * 3 + 4 * 4) * N     # [R,N]/[G,N]/[1,N] f32
    per_mn = (4 + 4 + 4) * M * N                    # sfeas + sscore + sscore2
    return per_n + per_mn
