"""Pallas TPU kernels: fused placement rounds of the allocate pass.

The hot inner loop of the cycle places the pending tasks of selected gangs
one by one (capacity feedback between placements is what makes the pass
exact, SURVEY.md section 7 hard part 1). The pure-XLA path runs it as a
``lax.scan`` whose every step issues ~40 small HLO ops over [N]-shaped
arrays; these kernels fuse WHOLE placement rounds into one
``pl.pallas_call`` with the capacity state (idle, pipelined-extra, pod
counts, per-GPU-card usage — and, new in v3, the live inter-pod affinity
counts) resident in VMEM across all placements.

v2 design (on top of the round-fused v1):

- **In-kernel template gathers.** Per-task static feasibility/score rows are
  read from the per-TEMPLATE matrices ([P, N] — the predicate-cache analog,
  predicates/cache.go:42-90) with dynamic sublane slices inside the kernel,
  instead of materializing [M, N] gather outputs in XLA every round. A round
  now ships only O(M) scalars per task plus the (static-per-cycle) template
  maps.
- **K-job batched rounds** (``K`` static): one launch runs K job sections
  sequentially with per-section gang commit/discard (JobReady /
  JobPipelined / Statement.Discard, statement.go:352-395) INSIDE the kernel,
  so the committed capacity flows section to section without a host/XLA
  round-trip. Batching K > 1 is bit-exact with the sequential pop order iff
  the job-ordering keys are static over commits — no drf/hdrf dynamic
  ordering and no finite proportion ``deserved`` (see
  allocate_scan.derive_batching, the single authority for the rule).
- **Optional GPU path** (``enable_gpu`` static): snapshots with no shared-GPU
  requests skip the per-card state entirely (decision-neutral: a zero
  gpu_request never charges a card, gpu.go:41-56).

v3 design (this round):

- **Affinity state in VMEM** (``enable_pod_affinity`` static): the live
  inter-pod affinity counts (``arrays/affinity.py`` node-space encoding,
  split as cnt[SK, N] + cluster-total[SK, 1] and anti_cnt[ETA, N]) are
  kernel loop state with per-section commit/discard, and the dynamic
  affinity predicate + preferred-term scorer run in-kernel — config-5
  cycles stop re-materializing [M, N] gathers in XLA every round. All
  affinity accumulations are integer-valued counts/weights, so f32 sums
  are exact in any order and the kernel matches the scan path bitwise.
- **Dynamic-key batched pops** (``_dyn_kernel`` / make_dyn_round_placer):
  for configs whose job-ordering keys move with commits (drf/hdrf dynamic
  ordering, finite proportion deserved), job SELECTION moves into the
  kernel: each launch runs up to KP sequential pops, recomputing the
  dynamic fairness keys (drf job dominant share, drf namespace share,
  proportion qshare/overused — the ops/fairshare.py share math ported to
  VMEM layouts) from the live in-kernel allocation state after every gang
  commit, exactly as the scan path recomputes them per pop. Task data for
  the C candidate jobs is pre-gathered by XLA; a pop whose
  lexicographic argmin is NOT one of the candidates stops the launch
  early and hands back to XLA (which re-selects candidates from the
  committed state), so decisions are bit-identical to the sequential pop
  order by construction. hdrf level keys are the one component NOT
  recomputed in-kernel (the tree update is a multi-level segment
  reduction, measured off-budget in VMEM): they are frozen per launch and
  guarded — a pop after any commit proceeds only while the eligible set
  spans a single queue (then the frozen per-queue columns are constant
  across all contenders and cannot affect the argmin); otherwise the
  launch stops. See docs/architecture.md "Batched dynamic-key rounds".

Layout: node-axis tensors are transposed to [R, N] / [G, N] / [P, N] so the
node axis is the 128-lane dimension (R/G/P are small; [N, R] would waste 32x
lanes). Per-job key state is [R, J] / [1, J] (J lanes); per-queue state is
[Q, R] (queue on sublanes so per-queue reductions land in [Q, 1] columns).

Semantics are bit-identical to the scan path in allocate_scan.task_step
(asserted by tests/test_pallas_place.py): same feasibility conjunction, same
score formulas (ops/scoring.py) in the same f32 addition order, same
lowest-index argmax tie-break (ops/select.py best_node), same
lowest-fitting-card GPU pick (ops/predicates.py pick_gpu_row).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .allocate_scan import MODE_ALLOCATED, MODE_NONE, MODE_PIPELINED

_EPS_FIT = 1e-5     # predicates._EPS
_EPS_DIV = 1e-9     # scoring._EPS
NEG = -1e30         # select.NEG
_BIG = 3.4e38   # allocate_scan._affinity_terms normalize (python float:
#                 a jnp scalar here would be a captured constant in pallas)


class _NS:
    """Plain namespace for kernel-side loaded refs/values."""


def _dyn_score(cfg, idle, alloc_t, rr_col):
    """Idle-dependent score terms in [R, N] layout — transposed but
    float-op-for-float-op identical to ops/scoring.py (reductions run over
    the same R elements in the same order, so f32 results match bitwise)."""
    used = alloc_t - idle
    N = idle.shape[1]
    score = jnp.zeros((1, N), jnp.float32)
    if cfg.binpack_weight:
        applicable = (rr_col > 0) & (alloc_t > 0)   # weights all-ones
        frac = jnp.where(applicable,
                         (used + rr_col) / jnp.maximum(alloc_t, _EPS_DIV), 0.0)
        over = frac > 1.0 + 1e-6
        w = applicable.astype(jnp.float32)
        wsum = jnp.sum(w, axis=0, keepdims=True)
        raw = jnp.sum(frac * w, axis=0, keepdims=True) \
            / jnp.maximum(wsum, _EPS_DIV)
        raw = jnp.where(jnp.any(over, axis=0, keepdims=True), 0.0, raw)
        score += cfg.binpack_weight * raw * 100.0
    if cfg.least_allocated_weight:
        cap = jnp.maximum(alloc_t, _EPS_DIV)
        free_frac = (alloc_t - used - rr_col) / cap
        counted = alloc_t > 0
        n = jnp.maximum(jnp.sum(counted, axis=0, keepdims=True,
                                dtype=jnp.int32), 1)
        score += cfg.least_allocated_weight * (
            jnp.sum(jnp.clip(free_frac, 0.0, 1.0) * counted, axis=0,
                    keepdims=True) / n * 100.0)
    if cfg.most_allocated_weight:
        cap = jnp.maximum(alloc_t, _EPS_DIV)
        used_frac = (used + rr_col) / cap
        counted = alloc_t > 0
        n = jnp.maximum(jnp.sum(counted, axis=0, keepdims=True,
                                dtype=jnp.int32), 1)
        score += cfg.most_allocated_weight * (
            jnp.sum(jnp.clip(used_frac, 0.0, 1.0) * counted, axis=0,
                    keepdims=True) / n * 100.0)
    if cfg.balanced_weight:
        cap = jnp.maximum(alloc_t, _EPS_DIV)
        frac = jnp.clip((used + rr_col) / cap, 0.0, 1.0)
        counted = (alloc_t > 0).astype(frac.dtype)
        n = jnp.maximum(jnp.sum(counted, axis=0, keepdims=True), 1.0)
        mean = jnp.sum(frac * counted, axis=0, keepdims=True) / n
        var = jnp.sum(((frac - mean) ** 2) * counted, axis=0,
                      keepdims=True) / n
        score += cfg.balanced_weight * (1.0 - jnp.sqrt(var)) * 100.0
    return score


def _seli(row, idx, iota):
    """mosaic has no dynamic lane indexing: scalar = one-hot reduce."""
    return jnp.sum(jnp.where(iota == idx, row, 0), dtype=jnp.int32)


def _self(row, idx, iota):
    return jnp.sum(jnp.where(iota == idx, row, 0.0))


# --------------------------------------------------------------------------
# shared ref readers — the builder functions emit args in EXACTLY this order
# --------------------------------------------------------------------------

def _read_slot_env(cfg, nxt, env):
    """Per-slot ([1, CM] / [R, CM]) rows shared by both kernels."""
    env.resreq_t = nxt()[:]                       # [R, CM]
    env.gpu_req = nxt()[:] if env.gpu else None   # [1, CM]
    env.pref_v = nxt()[:]                         # [1, CM] i32
    env.suffix_v = nxt()[:]                       # [1, CM] i32
    env.tmpl_v = nxt()[:]                         # [1, CM] i32 (clamped)
    env.grp_v = nxt()[:]                          # [1, CM] i32 (-1 none)
    env.voln_v = nxt()[:]                         # [1, CM] i32 (-1 any)
    env.volok_v = nxt()[:]                        # [1, CM] i32
    env.rev_v = nxt()[:]                          # [1, CM] i32


def _read_node_env(cfg, nxt, env):
    """Static node-space maps shared by both kernels."""
    env.tstat_ref = nxt()      # [P, N] f32 template static feasibility
    env.tscore_ref = nxt()     # [P, N] f32 taint-prefer static score
    env.nascore_ref = nxt()    # [P, N] f32 NodeAffinity preferred score
    env.blocknr = nxt()[:] > 0   # [1, N] tdm block-nonrevocable
    env.blockall = nxt()[:] > 0  # [1, N] tdm block-all
    env.bonus = nxt()[:]         # [1, N] f32 tdm revocable bonus
    env.locked = nxt()[:] > 0    # [1, N] reservation node locks
    env.orfeas_ref = nxt()     # [GR, N] f32 OR-of-terms group feasibility
    env.relmp = nxt()[:]       # [R, N] releasing - pipelined
    env.alloc_t = nxt()[:]     # [R, N]
    env.cnt = nxt()[:]         # [1, N]
    env.maxp = nxt()[:]        # [1, N]
    env.gidle0 = nxt()[:] if env.gpu else None    # [G, N]


def _read_aff_env(nxt, env):
    """Inter-pod affinity refs (only when cfg.enable_pod_affinity)."""
    a = _NS()
    a.live = nxt()[:] > 0      # [1, N] valid & schedulable nodes
    a.skdom_ref = nxt()        # [SK, N] i32 node's domain per (sel,key)
    a.sk_sel_col = nxt()[:]    # [SK, 1] i32
    a.eta_sk_row = nxt()[:]    # [1, ETA] i32
    a.eta_dom_ref = nxt()      # [ETA, N] i32
    a.static_pref = nxt()[:]   # [SEL, N] f32 symmetric preferred map
    a.aff_sk_ref = nxt()       # [A, CM] i32 required-affinity pair slots
    a.anti_ref = nxt()         # [B, CM] i32 own required-anti term slots
    a.prefsk_ref = nxt()       # [PP, CM] i32 preferred pair slots
    a.prefw_ref = nxt()        # [PP, CM] f32 preferred weights
    a.skm_ref = nxt()          # [SK, CM] f32 task_match[sk_sel] per slot
    a.etm_ref = nxt()          # [ETA, CM] f32 (eta_sel>=0)&match per slot
    a.selm_ref = nxt()         # [SEL, CM] f32 task_match per slot
    a.SK = a.skdom_ref.shape[0]
    a.ETA = a.eta_dom_ref.shape[0]
    a.SEL = a.static_pref.shape[0]
    a.A = a.aff_sk_ref.shape[0]
    a.B = a.anti_ref.shape[0]
    a.PP = a.prefsk_ref.shape[0]
    a.iota_eta = jax.lax.broadcasted_iota(jnp.int32, (1, a.ETA), 1)
    a.iota_eta_sub = jax.lax.broadcasted_iota(jnp.int32, (a.ETA, 1), 0)
    a.iota_sk_sub = jax.lax.broadcasted_iota(jnp.int32, (a.SK, 1), 0)
    env.aff = a


def _aff_eval(cfg, env, sel_s, aff_state):
    """InterPodAffinity feasibility mask + normalized score for slot
    ``sel_s`` against the LIVE in-kernel counts — the VMEM port of
    allocate_scan._affinity_terms (same conjunctions; the weighted count
    sums are integer-valued so f32 accumulation order cannot change them).
    """
    a = env.aff
    aff_cnt, aff_tot, anti_cnt = aff_state
    N = env.N

    def row_at(mat, idx, iota_sub):
        # dynamic sublane pick from a loop-carried VALUE (refs take
        # pl.dslice, values don't): one-hot select-reduce, exact because
        # exactly one row contributes
        return jnp.sum(jnp.where(iota_sub == idx, mat, 0.0), axis=0,
                       keepdims=True)

    # required affinity: domain must already hold a matching pod; k8s
    # first-pod escape via the cluster-total column
    ok_acc = jnp.ones((1, N), bool)
    for i in range(a.A):
        ska = jnp.sum(a.aff_sk_ref[(pl.dslice(i, 1), slice(None))]
                      * sel_s.astype(jnp.int32), dtype=jnp.int32)
        act_a = ska >= 0
        skc = jnp.maximum(ska, 0)
        have = row_at(aff_cnt, skc, a.iota_sk_sub)            # [1, N]
        tot = jnp.sum(aff_tot * (a.iota_sk_sub == skc))
        dom = a.skdom_ref[(pl.dslice(skc, 1), slice(None))]   # [1, N]
        match_a = jnp.sum(a.skm_ref[(pl.dslice(skc, 1), slice(None))]
                          * sel_s) > 0
        ok = (have > 0) & (dom >= 0)
        ok = ok | ((tot == 0) & match_a & (dom >= 0))
        ok_acc &= ok | ~act_a
    aff_ok = ok_acc

    # required anti-affinity: own terms vs pods already counted
    viol_own = jnp.zeros((1, N), bool)
    for i in range(a.B):
        etab = jnp.sum(a.anti_ref[(pl.dslice(i, 1), slice(None))]
                       * sel_s.astype(jnp.int32), dtype=jnp.int32)
        bact = etab >= 0
        ec = jnp.maximum(etab, 0)
        eskb = jnp.maximum(jnp.sum(jnp.where(a.iota_eta == ec,
                                             a.eta_sk_row, 0),
                                   dtype=jnp.int32), 0)
        cnt_b = row_at(aff_cnt, eskb, a.iota_sk_sub)          # [1, N]
        dom_b = a.eta_dom_ref[(pl.dslice(ec, 1), slice(None))]
        viol_own |= bact & (cnt_b > 0) & (dom_b >= 0)

    # required anti-affinity: placed pods' terms vs this task (symmetric)
    m_eta = jnp.sum(jnp.where(sel_s > 0, a.etm_ref[:], 0.0),
                    axis=1, keepdims=True)                    # [ETA, 1]
    viol_sym = jnp.any((m_eta > 0) & (anti_cnt > 0)
                       & (a.eta_dom_ref[:] >= 0), axis=0, keepdims=True)

    feas = aff_ok & ~viol_own & ~viol_sym

    # preferred terms of the incoming task (dynamic counts); stacked then
    # summed like the scan path's jnp.sum over the PP axis — exact either
    # way (integer-valued addends)
    rows = []
    for i in range(a.PP):
        pskp = jnp.sum(a.prefsk_ref[(pl.dslice(i, 1), slice(None))]
                       * sel_s.astype(jnp.int32), dtype=jnp.int32)
        pw = jnp.sum(a.prefw_ref[(pl.dslice(i, 1), slice(None))] * sel_s)
        pact = pskp >= 0
        pskc = jnp.maximum(pskp, 0)
        cnt_p = row_at(aff_cnt, pskc, a.iota_sk_sub)
        dom_p = a.skdom_ref[(pl.dslice(pskc, 1), slice(None))]
        rows.append(jnp.where(pact & (dom_p >= 0), pw * cnt_p, 0.0))
    raw = rows[0]
    for r in rows[1:]:
        raw = raw + r
    # symmetric preferred from snapshot pods (node-space static map)
    mcol = jnp.sum(jnp.where(sel_s > 0, a.selm_ref[:], 0.0),
                   axis=1, keepdims=True)                     # [SEL, 1]
    raw = raw + jnp.sum(mcol * a.static_pref, axis=0, keepdims=True)

    # min-max normalize over schedulable nodes -> 0..100 (k8s NormalizeScore)
    mx = jnp.max(jnp.where(a.live, raw, -_BIG))
    mn = jnp.min(jnp.where(a.live, raw, _BIG))
    span = mx - mn
    norm = jnp.where(span > 0,
                     (raw - mn) * (100.0 / jnp.maximum(span, 1e-9)), 0.0)
    return feas, norm


def _aff_commit(env, sel_s, node_onehot, placed, aff_state):
    """Account a placement in the live counts — the VMEM port of
    allocate_scan._affinity_place_update (domain-membership mask adds)."""
    a = env.aff
    aff_cnt, aff_tot, anti_cnt = aff_state
    skdom = a.skdom_ref[:]                                    # [SK, N]
    # node_onehot selects exactly one lane; masked lanes contribute 0 and a
    # missing key is -1, so select via sum of (value + 1) - 1 to keep -1
    dom_at = jnp.sum(jnp.where(node_onehot > 0, skdom + 1, 0),
                     axis=1, keepdims=True, dtype=jnp.int32) - 1  # [SK, 1]
    member = (skdom == dom_at) & (skdom >= 0) & (dom_at >= 0)
    matchc = jnp.sum(jnp.where(sel_s > 0, a.skm_ref[:], 0.0),
                     axis=1, keepdims=True) > 0               # [SK, 1]
    addsk = jnp.where(placed & (a.sk_sel_col >= 0) & matchc,
                      jnp.float32(1.0), jnp.float32(0.0))
    aff_cnt = aff_cnt + member.astype(jnp.float32) * addsk
    aff_tot = aff_tot + (dom_at >= 0).astype(jnp.float32) * addsk
    # the task's own required anti terms mark their presence in the domain
    for i in range(a.B):
        etab = jnp.sum(a.anti_ref[(pl.dslice(i, 1), slice(None))]
                       * sel_s.astype(jnp.int32), dtype=jnp.int32)
        ec = jnp.maximum(etab, 0)
        edom = a.eta_dom_ref[(pl.dslice(ec, 1), slice(None))]  # [1, N]
        edom_at = jnp.sum(jnp.where(node_onehot > 0, edom + 1, 0),
                          dtype=jnp.int32) - 1
        emember = (edom == edom_at) & (edom >= 0) & (edom_at >= 0)
        g = jnp.where((etab >= 0) & placed, jnp.float32(1.0),
                      jnp.float32(0.0))
        anti_cnt = anti_cnt + (g * emember.astype(jnp.float32)
                               * (a.iota_eta_sub == ec))
    return aff_cnt, aff_tot, anti_cnt


def _make_attempt(cfg, env):
    """Shared single-placement step: feasibility -> score -> pick ->
    capacity/output updates for slot scalar ``s`` — the in-kernel mirror of
    allocate_scan.task_step's per-task body. ``active``/``is_tgt`` gates are
    caller-supplied; returns the updated state plus the event flags the
    caller needs for yield/break/gang bookkeeping."""
    gpu = env.gpu
    N = env.N
    iota_n = env.iota_n
    iota_km = env.iota_km
    iota_g = env.iota_g

    def attempt(s, active, is_tgt, cap, aff_state, outs):
        idle, pipe, podsx, gpux = cap
        node_v, mode_v, gpuc_v = outs
        sel_s = (iota_km == s).astype(jnp.float32)            # [1, CM]
        sel_i = sel_s.astype(jnp.int32)
        rr_col = jnp.sum(env.resreq_t * sel_s, axis=1, keepdims=True)
        pref = jnp.sum(env.pref_v * sel_i, dtype=jnp.int32)
        tmpl = jnp.sum(env.tmpl_v * sel_i, dtype=jnp.int32)
        grp = jnp.sum(env.grp_v * sel_i, dtype=jnp.int32)
        voln = jnp.sum(env.voln_v * sel_i, dtype=jnp.int32)
        volok = jnp.sum(env.volok_v * sel_i, dtype=jnp.int32) > 0
        rev = jnp.sum(env.rev_v * sel_i, dtype=jnp.int32) > 0

        # static feasibility row: template mask + per-cycle node gates
        # (the node_ok conjunction of allocate_scan.task_step)
        trow = (pl.dslice(tmpl, 1), slice(None))
        sfeas = env.tstat_ref[trow] > 0                       # [1, N]
        sfeas &= ~(env.blocknr & ~rev) & ~env.blockall
        orrow = env.orfeas_ref[(pl.dslice(jnp.maximum(grp, 0), 1),
                                slice(None))] > 0
        sfeas &= orrow | (grp < 0)
        sfeas &= volok & ((voln < 0) | (iota_n == voln))
        sfeas &= ~env.locked | is_tgt

        future = jnp.maximum(idle + env.relmp - pipe, 0.0)
        pods_ok = (env.cnt + podsx) < env.maxp
        shared = sfeas & pods_ok
        if gpu:
            gr = jnp.sum(env.gpu_req * sel_s, axis=1, keepdims=True)
            gidle = env.gidle0 - gpux
            gpu_ok = (gr <= 0) | jnp.any(gidle >= gr - _EPS_FIT,
                                         axis=0, keepdims=True)
            shared &= gpu_ok
        fit_now = jnp.all(rr_col <= idle + _EPS_FIT, axis=0,
                          keepdims=True)
        fit_fut = jnp.all(rr_col <= future + _EPS_FIT, axis=0,
                          keepdims=True)
        feas_now = shared & fit_now
        feas_fut = shared & fit_fut

        # f32 addition order matches allocate_scan exactly:
        # dyn terms, then taint-static, then (nodeaffinity + rev*bonus),
        # then task-topology preference, then the affinity scorer
        score = _dyn_score(cfg, idle, env.alloc_t, rr_col)
        score = score + env.tscore_ref[trow]
        score = score + (env.nascore_ref[trow]
                         + jnp.where(rev, env.bonus, 0.0))
        score = score + jnp.where((pref >= 0) & (iota_n == pref),
                                  jnp.float32(100.0), jnp.float32(0.0))
        if cfg.enable_pod_affinity:
            aff_feas, aff_score = _aff_eval(cfg, env, sel_s, aff_state)
            feas_now &= aff_feas
            feas_fut &= aff_feas
            score = score + cfg.pod_affinity_weight * aff_score

        def pick(feas):
            masked = jnp.where(feas, score, NEG)
            best = jnp.max(masked)
            idx = jnp.min(jnp.where(masked == best, iota_n, N))
            found = jnp.max(feas.astype(jnp.int32)) > 0
            return idx, found

        n_now, found_now = pick(feas_now)
        n_fut, found_fut = pick(feas_fut)
        can_now = found_now & active
        can_fut = found_fut & active & bool(cfg.enable_pipelining)
        do_alloc = can_now
        do_pipe = (~can_now) & can_fut
        placed = do_alloc | do_pipe
        node = jnp.where(do_alloc, n_now, n_fut)

        onehot = (iota_n == node).astype(jnp.float32)         # [1, N]
        one, zero = jnp.float32(1.0), jnp.float32(0.0)
        idle = idle - jnp.where(do_alloc, one, zero) * rr_col * onehot
        pipe = pipe + jnp.where(do_pipe, one, zero) * rr_col * onehot
        podsx = podsx + jnp.where(placed, one, zero) * onehot

        if gpu:
            # lowest fitting card on the chosen node (pick_gpu_row)
            gcol = jnp.sum(gidle * onehot, axis=1, keepdims=True)  # [G, 1]
            gfits = gcol >= gr - _EPS_FIT
            card = jnp.min(jnp.where(gfits, iota_g, env.G))
            ok_pick = (jnp.max(gfits.astype(jnp.int32)) > 0) \
                & (gr[0, 0] > 0)
            card = jnp.where(ok_pick, card, -1)
            charge = placed & (card >= 0)
            gpux = gpux + (jnp.where(charge, one, zero) * gr
                           * (iota_g == jnp.maximum(card, 0)) * onehot)
        else:
            card = jnp.int32(-1)
            charge = jnp.bool_(False)

        mode = jnp.where(do_alloc, jnp.int32(MODE_ALLOCATED),
                         jnp.where(do_pipe, jnp.int32(MODE_PIPELINED),
                                   jnp.int32(MODE_NONE)))
        is_s = iota_km == s
        node_v = jnp.where(is_s, jnp.where(placed, node, -1), node_v)
        mode_v = jnp.where(is_s, mode, mode_v)
        gpuc_v = jnp.where(is_s, jnp.where(charge, card, -1), gpuc_v)

        if cfg.enable_pod_affinity:
            aff_state = _aff_commit(env, sel_s, onehot, placed, aff_state)

        return ((idle, pipe, podsx, gpux), aff_state,
                (node_v, mode_v, gpuc_v),
                placed, do_alloc, do_pipe, rr_col)

    return attempt


# --------------------------------------------------------------------------
# static-key kernel: K pre-selected job sections per launch
# --------------------------------------------------------------------------

def _batch_kernel(cfg, K, M, N, R, G, GR, refs):
    """K job sections x M placements, all in VMEM.

    ``refs`` is the flat ref list in the order built by make_round_placer;
    unpacked here to keep the signature manageable.
    """
    gpu = bool(cfg.enable_gpu)
    aff = bool(cfg.enable_pod_affinity)
    it = iter(refs)

    def nxt():
        return next(it)

    env = _NS()
    env.gpu = gpu
    env.N, env.M, env.R, env.G = N, M, R, G
    KM = K * M
    env.iota_n = jax.lax.broadcasted_iota(jnp.int32, (1, N), 1)
    env.iota_g = (jax.lax.broadcasted_iota(jnp.int32, (G, 1), 0)
                  if gpu else None)
    env.iota_km = jax.lax.broadcasted_iota(jnp.int32, (1, KM), 1)
    iota_k = jax.lax.broadcasted_iota(jnp.int32, (1, K), 1)

    _read_slot_env(cfg, nxt, env)
    active_ref = nxt()        # [1, KM] i32 (open & not best-effort)
    ready0_ref = nxt()        # [1, K] i32
    minav_ref = nxt()         # [1, K] i32
    canb_ref = nxt()          # [1, K] i32 can-batch (re-pop fusion) flag
    secact_ref = nxt()        # [1, K] i32 section active (ji >= 0)
    istgt_ref = nxt()         # [1, K] i32 section job == reservation target
    _read_node_env(cfg, nxt, env)
    if aff:
        _read_aff_env(nxt, env)
    idle_ref = nxt()          # [R, N] in
    pipe_ref = nxt()          # [R, N] in
    podsx_ref = nxt()         # [1, N] in
    gpux_ref = nxt() if gpu else None           # [G, N] in
    if aff:
        affc_ref = nxt()      # [SK, N] in
        afft_ref = nxt()      # [SK, 1] in
        antic_ref = nxt()     # [ETA, N] in
    node_o = nxt()            # [1, KM] out
    mode_o = nxt()            # [1, KM] out
    gpu_o = nxt()             # [1, KM] out
    idle_o = nxt()            # [R, N] out
    pipe_o = nxt()            # [R, N] out
    podsx_o = nxt()           # [1, N] out
    gpux_o = nxt() if gpu else None             # [G, N] out
    if aff:
        affc_o = nxt()
        afft_o = nxt()
        antic_o = nxt()

    active_v = active_ref[:]
    suffix_v = env.suffix_v
    ready0_v = ready0_ref[:]
    minav_v = minav_ref[:]
    canb_v = canb_ref[:]
    secact_v = secact_ref[:]
    istgt_v = istgt_ref[:]

    attempt = _make_attempt(cfg, env)

    def job_body(k, jcarry):
        # committed (post gang-finalize) state from prior sections
        (ccap, caff, outs) = jcarry
        ready0 = _seli(ready0_v, k, iota_k)
        min_avail = _seli(minav_v, k, iota_k)
        can_batch = _seli(canb_v, k, iota_k) > 0
        sec_act = _seli(secact_v, k, iota_k) > 0
        is_tgt = _seli(istgt_v, k, iota_k) > 0

        def task_body(m, tcarry):
            (cap, aff_st, outs, n_allocs, n_pipes, stopped, broke) = tcarry
            s = k * M + m
            sel_i = (env.iota_km == s).astype(jnp.int32)
            act = jnp.sum(active_v * sel_i, dtype=jnp.int32) > 0
            suffix = jnp.sum(suffix_v * sel_i, dtype=jnp.int32)
            # yield/break state gates the attempt (allocate.go:205-266)
            active = act & sec_act & ~stopped & ~broke
            (cap, aff_st, outs, placed, do_alloc, do_pipe,
             _rr) = attempt(s, active, is_tgt, cap, aff_st, outs)
            n_allocs = n_allocs + jnp.where(do_alloc, jnp.int32(1),
                                            jnp.int32(0))
            n_pipes = n_pipes + jnp.where(do_pipe, jnp.int32(1),
                                          jnp.int32(0))
            if cfg.enable_gang:
                ready_aft = (ready0 + n_allocs) >= min_avail
            else:
                ready_aft = True
            stopped = stopped | (placed & ready_aft & (suffix > 0)
                                 & ~can_batch)
            broke = broke | (active & ~placed)
            return (cap, aff_st, outs, n_allocs, n_pipes, stopped, broke)

        (cap, aff_st, outs, n_allocs, n_pipes, _stopped,
         _broke) = jax.lax.fori_loop(
            jnp.int32(0), jnp.int32(M), task_body,
            (ccap, caff, outs, jnp.int32(0), jnp.int32(0),
             jnp.bool_(False), jnp.bool_(False)))

        # ---- gang finalize in-kernel (JobReady/JobPipelined/Discard) ------
        if cfg.enable_gang:
            ready = (ready0 + n_allocs) >= min_avail
        else:
            ready = jnp.bool_(True)
        pipelined = (ready0 + n_allocs + n_pipes) >= min_avail
        keep = ready | pipelined
        sec = (env.iota_km >= k * M) & (env.iota_km < (k + 1) * M)
        node_v, mode_v, gpuc_v = outs
        node_v = jnp.where(keep | ~sec, node_v, -1)
        mode_v = jnp.where(keep | ~sec, mode_v, MODE_NONE)
        gpuc_v = jnp.where(keep | ~sec, gpuc_v, -1)
        idle, pipe, podsx, gpux = cap
        cidle, cpipe, cpods, cgpux = ccap
        idle = jnp.where(keep, idle, cidle)
        pipe = jnp.where(keep, pipe, cpipe)
        podsx = jnp.where(keep, podsx, cpods)
        if gpu:
            gpux = jnp.where(keep, gpux, cgpux)
        if aff:
            ac, at, an = aff_st
            cac, cat, can = caff
            aff_st = (jnp.where(keep, ac, cac), jnp.where(keep, at, cat),
                      jnp.where(keep, an, can))
        return ((idle, pipe, podsx, gpux), aff_st,
                (node_v, mode_v, gpuc_v))

    neg1 = jnp.full((1, KM), -1, jnp.int32)
    gpux0 = gpux_ref[:] if gpu else jnp.zeros((1, 1), jnp.float32)
    aff0 = ((affc_ref[:], afft_ref[:], antic_ref[:]) if aff
            else (jnp.zeros((1, 1), jnp.float32),) * 3)
    (cap, aff_st, outs) = jax.lax.fori_loop(
        jnp.int32(0), jnp.int32(K), job_body,
        ((idle_ref[:], pipe_ref[:], podsx_ref[:], gpux0), aff0,
         (neg1, jnp.zeros((1, KM), jnp.int32), neg1)))
    node_o[:], mode_o[:], gpu_o[:] = outs
    idle_o[:], pipe_o[:], podsx_o[:] = cap[0], cap[1], cap[2]
    if gpu:
        gpux_o[:] = cap[3]
    if aff:
        affc_o[:], afft_o[:], antic_o[:] = aff_st


def make_round_placer(cfg, K: int, M: int, N: int, R: int, G: int,
                      GR: int, aff_dims=None, interpret: bool = False):
    """Build the fused batched-round placer (static ordering keys).

    Returns place(args...) with the input order documented in
    _batch_kernel; outputs (node [KM], mode [KM], gpu [KM], idle', pipe',
    podsx'[, gpux'][, aff_cnt', aff_tot', anti_cnt']). GPU refs are absent
    when cfg.enable_gpu is False; affinity refs/state only exist when
    cfg.enable_pod_affinity (``aff_dims`` = (SK, ETA) then sizes them).
    """
    kernel = functools.partial(_batch_kernel, cfg, K, M, N, R, G, GR)
    f32 = jnp.float32
    KM = K * M
    gpu = bool(cfg.enable_gpu)
    aff = bool(cfg.enable_pod_affinity)

    out_shape = [
        jax.ShapeDtypeStruct((1, KM), jnp.int32),   # node
        jax.ShapeDtypeStruct((1, KM), jnp.int32),   # mode
        jax.ShapeDtypeStruct((1, KM), jnp.int32),   # gpu
        jax.ShapeDtypeStruct((R, N), f32),          # idle'
        jax.ShapeDtypeStruct((R, N), f32),          # pipe'
        jax.ShapeDtypeStruct((1, N), f32),          # podsx'
    ]
    if gpu:
        out_shape.append(jax.ShapeDtypeStruct((G, N), f32))  # gpux'
    if aff:
        SK, ETA = aff_dims
        out_shape += [jax.ShapeDtypeStruct((SK, N), f32),    # aff_cnt'
                      jax.ShapeDtypeStruct((SK, 1), f32),    # aff_tot'
                      jax.ShapeDtypeStruct((ETA, N), f32)]   # anti_cnt'

    def place(*args):
        # launch-boundary trace annotation (name-stack metadata only -
        # zero equations, decisions and jaxpr counts untouched)
        with jax.named_scope("volcano/pallas/static_rounds"):
            outs = pl.pallas_call(
                lambda *refs: kernel(refs),
                out_shape=tuple(out_shape),
                interpret=interpret,
            )(*args)
        node, mode, gpuc = outs[0][0], outs[1][0], outs[2][0]
        return (node, mode, gpuc) + tuple(outs[3:])

    return place


# --------------------------------------------------------------------------
# dynamic-key kernel: in-kernel job selection + fairness-key recompute
# --------------------------------------------------------------------------

def _dyn_kernel(cfg, C, KP, M, N, R, G, GR, J, Q, S, NH, refs):
    """Up to KP sequential pops per launch over C candidate jobs, with the
    dynamic ordering keys recomputed IN-KERNEL after every gang commit —
    the exact mirror of the scan path's per-pop key recompute
    (allocate_scan body: qshare / namespace_shares / drf_job_shares /
    ready_now), so K-batched rounds stay bit-identical to the sequential
    pop order even when commits move the keys. See the module docstring
    for the candidate-set early stop and the hdrf frozen-cols guard."""
    gpu = bool(cfg.enable_gpu)
    aff = bool(cfg.enable_pod_affinity)
    it = iter(refs)

    def nxt():
        return next(it)

    env = _NS()
    env.gpu = gpu
    env.N, env.M, env.R, env.G = N, M, R, G
    CM = C * M
    env.iota_n = jax.lax.broadcasted_iota(jnp.int32, (1, N), 1)
    env.iota_g = (jax.lax.broadcasted_iota(jnp.int32, (G, 1), 0)
                  if gpu else None)
    env.iota_km = jax.lax.broadcasted_iota(jnp.int32, (1, CM), 1)
    iota_j = jax.lax.broadcasted_iota(jnp.int32, (1, J), 1)
    iota_c = jax.lax.broadcasted_iota(jnp.int32, (1, C), 1)
    iota_q_sub = jax.lax.broadcasted_iota(jnp.int32, (Q, 1), 0)
    iota_rr_s = jax.lax.broadcasted_iota(jnp.int32, (R, R), 0)
    iota_rr_l = jax.lax.broadcasted_iota(jnp.int32, (R, R), 1)

    _read_slot_env(cfg, nxt, env)
    tidok_ref = nxt()         # [1, CM] i32 task slot holds a real task
    nbe_ref = nxt()           # [1, CM] i32 task is NOT best-effort
    cand_ref = nxt()          # [1, C] i32 candidate job ids (-1 pad)
    cslot_ref = nxt()         # [1, J] i32 job -> candidate slot (-1)
    skeys_ref = nxt()         # [NKS, J] f32 static key columns
    hcols_ref = nxt() if NH else None   # [NH, J] f32 frozen hdrf columns
    qid_ref = nxt()           # [1, J] i32 job -> queue
    qoh_ref = nxt()           # [Q, J] f32 queue one-hot
    if cfg.drf_ns_order:
        nsm_ref = nxt()       # [S, J] f32 ns membership (key mapping)
        nsc_ref = nxt()       # [S, J] f32 ns contribution mask (valid jobs)
        nsw_ref = nxt()       # [1, S] f32 namespace weights
    minav_ref = nxt()         # [1, J] i32
    rdy0_ref = nxt()          # [1, J] i32 snapshot ready_num
    npend_ref = nxt()         # [1, J] i32
    eligs_ref = nxt()         # [1, J] i32 valid & schedulable
    validf_ref = nxt()        # [1, J] f32 jobs.valid (drf share masking)
    canb_ref = nxt()          # [1, J] i32 re-pop fusion flag per job
    des_ref = nxt()           # [Q, R] f32 proportion deserved
    qex_ref = nxt()           # [Q, 1] f32 queue_share_extra
    total_ref = nxt()         # [R, 1] f32 cluster capacity
    kmax_ref = nxt()          # [1, 1] i32 pop budget this launch
    tgt_ref = nxt()           # [1, 1] i32 reservation target job
    _read_node_env(cfg, nxt, env)
    if aff:
        _read_aff_env(nxt, env)
    idle_ref = nxt()
    pipe_ref = nxt()
    podsx_ref = nxt()
    gpux_ref = nxt() if gpu else None
    if aff:
        affc_ref = nxt()
        afft_ref = nxt()
        antic_ref = nxt()
    done_ref = nxt()          # [1, J] i32 in
    popped_ref = nxt()        # [1, J] i32 in
    jready_ref = nxt()        # [1, J] i32 in
    jpipe_ref = nxt()         # [1, J] i32 in
    cursor_ref = nxt()        # [1, J] i32 in
    acount_ref = nxt()        # [1, J] i32 in
    jalloc_ref = nxt()        # [R, J] f32 in (live drf allocations)
    qalloc_ref = nxt()        # [Q, R] f32 in (live queue allocations)
    node_o = nxt()
    mode_o = nxt()
    gpu_o = nxt()
    idle_o = nxt()
    pipe_o = nxt()
    podsx_o = nxt()
    gpux_o = nxt() if gpu else None
    if aff:
        affc_o = nxt()
        afft_o = nxt()
        antic_o = nxt()
    done_o = nxt()
    popped_o = nxt()
    jready_o = nxt()
    jpipe_o = nxt()
    cursor_o = nxt()
    acount_o = nxt()
    jalloc_o = nxt()
    qalloc_o = nxt()
    pops_o = nxt()            # [1, 1] i32
    prog_o = nxt()            # [1, 1] i32

    tidok_v = tidok_ref[:]
    nbe_v = nbe_ref[:]
    suffix_v = env.suffix_v
    cand_v = cand_ref[:]
    cslot_v = cslot_ref[:]
    skeys = skeys_ref[:]
    hcols = hcols_ref[:] if NH else None
    qid_v = qid_ref[:]
    qid_f = qid_v.astype(jnp.float32)
    qoh = qoh_ref[:]
    minav_v = minav_ref[:]
    rdy0_v = rdy0_ref[:]
    npend_v = npend_ref[:]
    eligs_v = eligs_ref[:] > 0
    valid_f = validf_ref[:]
    canb_v = canb_ref[:] > 0
    des = des_ref[:]
    qex = qex_ref[:]
    total = total_ref[:]
    kmax = jnp.sum(kmax_ref[:], dtype=jnp.int32)
    tgt = jnp.sum(tgt_ref[:], dtype=jnp.int32)
    cand0 = _seli(cand_v, 0, iota_c)

    attempt = _make_attempt(cfg, env)
    inf = jnp.float32(jnp.inf)

    # static key column cursor: the builder packs the static columns in the
    # same flag-dependent order this reader walks (mirror of the scan
    # path's `keys` list construction)
    def skey(i):
        return skeys[i:i + 1, :]

    def pop_body(p, carry):
        (stop, pops, kept_any, prog, cap, aff_st, outs,
         done, popped, jready, jpipe, cursor, acount,
         jalloc, qalloc) = carry

        # ---- eligibility (mirror of allocate_scan.eligible) --------------
        over_col = jnp.max(
            jnp.where(qalloc > des + 1e-6, jnp.float32(1.0),
                      jnp.float32(0.0)), axis=1,
            keepdims=True)                                    # [Q, 1]
        over_j = jnp.sum(qoh * over_col, axis=0, keepdims=True) > 0
        elig = (eligs_v & (done == 0) & (cursor < npend_v) & ~over_j)
        any_elig = jnp.any(elig)

        # ---- hdrf guard: frozen per-queue columns are exact only while
        # every contender shares one queue once any commit has moved the
        # tree (see module docstring) -------------------------------------
        if NH:
            qmn = jnp.min(jnp.where(elig, qid_f, inf))
            qmx = jnp.max(jnp.where(elig, qid_f, -inf))
            guard_stop = kept_any & (qmn != qmx)
        else:
            guard_stop = jnp.bool_(False)

        # ---- dynamic keys (the fairshare.py share math, VMEM layout) -----
        qshare_col = jnp.max(
            jnp.where(jnp.isfinite(des) & (des > 0),
                      qalloc / jnp.maximum(des, 1e-9), 0.0),
            axis=1, keepdims=True) + qex                      # [Q, 1]
        qshare_j = jnp.sum(qoh * qshare_col, axis=0, keepdims=True)
        si = iter(range(skeys.shape[0]))
        keys = []
        if cfg.drf_ns_order:
            # namespace_shares: dominant share of the ns member sum / weight
            ns_key = jnp.zeros((1, J), jnp.float32)
            for s_ in range(S):
                member = nsm_ref[(pl.dslice(s_, 1), slice(None))]  # [1, J]
                contrib = nsc_ref[(pl.dslice(s_, 1), slice(None))]
                alloc_s = jnp.sum(jnp.where(contrib > 0, jalloc, 0.0),
                                  axis=1, keepdims=True)      # [R, 1]
                frac = jnp.where(total > 0,
                                 alloc_s / jnp.maximum(total, 1e-9), 0.0)
                share_s = jnp.max(frac)
                w_s = jnp.sum(jnp.where(
                    jax.lax.broadcasted_iota(jnp.int32, (1, S), 1) == s_,
                    nsw_ref[:], 0.0))
                share_s = share_s / jnp.maximum(w_s, 1.0)
                ns_key = jnp.where(member > 0, share_s, ns_key)
            keys.append(ns_key)
        else:
            keys.append(skey(next(si)))
        keys.append(skey(next(si)))                           # job_ns
        keys.append(qshare_j)
        if NH:
            for c_ in range(NH):
                keys.append(hcols[c_:c_ + 1, :])
        keys.append(skey(next(si)))                           # job_q
        keys.append(skey(next(si)))                           # -priority
        if cfg.tdm_job_order:
            keys.append(skey(next(si)))
        if cfg.sla_job_order:
            keys.append(skey(next(si)))
        ready_now = ((rdy0_v + acount >= minav_v)
                     & (minav_v > 0)).astype(jnp.float32)
        keys.append(ready_now)
        if cfg.drf_job_order:
            # drf_job_shares: dominant share over live allocations
            frac = jnp.where(total > 0,
                             jalloc / jnp.maximum(total, 1e-9), 0.0)
            jshare = jnp.max(frac, axis=0, keepdims=True)
            keys.append(jnp.where(valid_f > 0, jshare, inf))
        else:
            keys.append(skey(next(si)))
        keys.append(skey(next(si)))                           # creation_rank

        # ---- lexicographic argmin (ops/select.lex_argmin mirror) ---------
        m = elig
        for k_ in keys:
            kmin = jnp.min(jnp.where(m, k_, inf))
            m = m & (k_ <= kmin)
        jsel = jnp.min(jnp.where(m, iota_j, J))
        # pop 0 is the launch's XLA-selected argmin (same state, same
        # keys): forcing it guarantees >= 1 pop per launch (termination)
        jstar = jnp.where(p == 0, cand0, jsel)
        cslot = _seli(cslot_v, jstar, iota_j)
        ok = ((~stop) & (p < kmax) & any_elig & (~guard_stop)
              & (cslot >= 0) & (jstar >= 0) & (jstar < J))
        stop = stop | ~ok

        onehot_j = iota_j == jstar                            # [1, J]
        cur0 = jnp.sum(jnp.where(onehot_j, cursor, 0), dtype=jnp.int32)
        ready0_dyn = jnp.sum(jnp.where(onehot_j, rdy0_v + acount, 0),
                             dtype=jnp.int32)
        min_avail = jnp.sum(jnp.where(onehot_j, minav_v, 0),
                            dtype=jnp.int32)
        can_batch = jnp.sum(jnp.where(onehot_j, canb_v.astype(jnp.int32),
                                      0), dtype=jnp.int32) > 0
        is_tgt = jstar == tgt
        q_j = jnp.sum(jnp.where(onehot_j, qid_v, 0), dtype=jnp.int32)
        off = cslot * M

        # ---- the M-placement section (mirror of the scan task loop) ------
        def task_body(m_, tcarry):
            (cap, aff_st, outs, n_allocs, n_pipes, n_adv,
             stopped, broke) = tcarry
            s = off + m_
            sel_i = (env.iota_km == s).astype(jnp.int32)
            tid_ok = jnp.sum(tidok_v * sel_i, dtype=jnp.int32) > 0
            nbe = jnp.sum(nbe_v * sel_i, dtype=jnp.int32) > 0
            suffix = jnp.sum(suffix_v * sel_i, dtype=jnp.int32)
            can_run = (tid_ok & (m_ >= cur0) & ~stopped & ~broke & ok)
            active = can_run & nbe
            (cap, aff_st, outs, placed, do_alloc, do_pipe,
             _rr) = attempt(s, active, is_tgt, cap, aff_st, outs)
            n_allocs = n_allocs + jnp.where(do_alloc, jnp.int32(1),
                                            jnp.int32(0))
            n_pipes = n_pipes + jnp.where(do_pipe, jnp.int32(1),
                                          jnp.int32(0))
            n_adv = n_adv + jnp.where(can_run, jnp.int32(1), jnp.int32(0))
            if cfg.enable_gang:
                ready_aft = (ready0_dyn + n_allocs) >= min_avail
            else:
                ready_aft = True
            stopped = stopped | (placed & ready_aft & (suffix > 0)
                                 & ~can_batch)
            broke = broke | (active & ~placed)
            return (cap, aff_st, outs, n_allocs, n_pipes, n_adv,
                    stopped, broke)

        (ncap, naff, nouts, n_allocs, n_pipes, n_adv, stopped,
         _broke) = jax.lax.fori_loop(
            jnp.int32(0), jnp.int32(M), task_body,
            (cap, aff_st, outs, jnp.int32(0), jnp.int32(0), jnp.int32(0),
             jnp.bool_(False), jnp.bool_(False)))

        # ---- gang finalize + key-state commit ----------------------------
        if cfg.enable_gang:
            ready = (ready0_dyn + n_allocs) >= min_avail
        else:
            ready = jnp.bool_(True)
        pipelined = ((ready0_dyn + n_allocs + n_pipes) >= min_avail) \
            & ~ready
        keep = (ready | pipelined) & ok
        sec = (env.iota_km >= off + cur0) & (env.iota_km < off + M)
        node_v, mode_v, gpuc_v = nouts
        onode, omode, ogpu = outs
        # only THIS pop's section slots may change: the task loop walks all
        # M slots and writes neutral values at the already-consumed ones
        # (m < cur0), which would clobber earlier pops' committed
        # placements in the carry — restore everything outside the section
        node_v = jnp.where(sec & ok, node_v, onode)
        mode_v = jnp.where(sec & ok, mode_v, omode)
        gpuc_v = jnp.where(sec & ok, gpuc_v, ogpu)
        # discard clears only THIS pop's slot writes (>= the pop-start
        # cursor; earlier pops of the job were committed — a kept gang
        # never discards later, see the module docstring)
        disc = sec & ok & ~keep
        node_v = jnp.where(disc, -1, node_v)
        mode_v = jnp.where(disc, MODE_NONE, mode_v)
        gpuc_v = jnp.where(disc, -1, gpuc_v)
        # kept-but-unready gang: capacity held, no binds — demote this
        # pop's Allocated placements to Pipelined (session.go:317-330)
        demote = (keep & ~ready) & sec & (mode_v == MODE_ALLOCATED)
        mode_v = jnp.where(demote, MODE_PIPELINED, mode_v)

        def merge(new, old):
            return jax.tree.map(
                lambda a, b: jnp.where(keep, a, b), new, old)

        cap = merge(ncap, cap)
        aff_st = merge(naff, aff_st)

        # committed resources of this pop, accumulated in slot order like
        # the scan path's placed_sum (f32 adds in the same sequence)
        placed_m = (mode_v != MODE_NONE) & sec
        sel_rows = jnp.where(placed_m, jnp.float32(1.0), jnp.float32(0.0))
        placed_col = jnp.sum(env.resreq_t * sel_rows, axis=1,
                             keepdims=True)                   # [R, 1]
        commit_col = jnp.where(keep, placed_col, jnp.float32(0.0))
        # [R, 1] -> [1, R] exact transpose via one-hot diagonal
        commit_row = jnp.sum(
            jnp.where(iota_rr_s == iota_rr_l, commit_col, jnp.float32(0.0)),
            axis=0, keepdims=True)                            # [1, R]

        upd = onehot_j & ok
        i1, i0 = jnp.int32(1), jnp.int32(0)
        done = jnp.where(upd, jnp.where(stopped, i0, i1), done)
        popped = jnp.where(upd, i1, popped)
        jready = jnp.where(upd, jnp.where(ready & keep, i1, i0), jready)
        jpipe = jnp.where(upd, jnp.where(pipelined & keep, i1, i0), jpipe)
        cursor = jnp.where(upd, cursor + n_adv, cursor)
        acount = jnp.where(upd & keep, acount + n_allocs, acount)
        jalloc = jalloc + jnp.where(upd, commit_col, jnp.float32(0.0))
        qalloc = qalloc + jnp.where(iota_q_sub == q_j, jnp.float32(1.0),
                                    jnp.float32(0.0)) \
            * commit_row * jnp.where(ok, jnp.float32(1.0), jnp.float32(0.0))
        kept_any = kept_any | (keep & ((n_allocs + n_pipes) > 0))
        prog = prog | (ok & ((n_allocs > 0) | pipelined | ready))
        pops = pops + jnp.where(ok, i1, i0)
        return (stop, pops, kept_any, prog, cap, aff_st,
                (node_v, mode_v, gpuc_v),
                done, popped, jready, jpipe, cursor, acount,
                jalloc, qalloc)

    neg1 = jnp.full((1, CM), -1, jnp.int32)
    gpux0 = gpux_ref[:] if gpu else jnp.zeros((1, 1), jnp.float32)
    aff0 = ((affc_ref[:], afft_ref[:], antic_ref[:]) if aff
            else (jnp.zeros((1, 1), jnp.float32),) * 3)
    init = (jnp.bool_(False), jnp.int32(0), jnp.bool_(False),
            jnp.bool_(False),
            (idle_ref[:], pipe_ref[:], podsx_ref[:], gpux0), aff0,
            (neg1, jnp.zeros((1, CM), jnp.int32), neg1),
            done_ref[:], popped_ref[:], jready_ref[:], jpipe_ref[:],
            cursor_ref[:], acount_ref[:], jalloc_ref[:], qalloc_ref[:])
    (stop, pops, kept_any, prog, cap, aff_st, outs,
     done, popped, jready, jpipe, cursor, acount,
     jalloc, qalloc) = jax.lax.fori_loop(jnp.int32(0), jnp.int32(KP),
                                         pop_body, init)
    node_o[:], mode_o[:], gpu_o[:] = outs
    idle_o[:], pipe_o[:], podsx_o[:] = cap[0], cap[1], cap[2]
    if gpu:
        gpux_o[:] = cap[3]
    if aff:
        affc_o[:], afft_o[:], antic_o[:] = aff_st
    done_o[:] = done
    popped_o[:] = popped
    jready_o[:] = jready
    jpipe_o[:] = jpipe
    cursor_o[:] = cursor
    acount_o[:] = acount
    jalloc_o[:] = jalloc
    qalloc_o[:] = qalloc
    pops_o[:] = jnp.full((1, 1), 1, jnp.int32) * pops
    prog_o[:] = jnp.full((1, 1), 1, jnp.int32) * prog.astype(jnp.int32)


def make_dyn_round_placer(cfg, C: int, KP: int, M: int, N: int, R: int,
                          G: int, GR: int, J: int, Q: int, S: int,
                          NH: int = 0, aff_dims=None,
                          interpret: bool = False):
    """Build the dynamic-key batched placer: KP in-kernel pops per launch
    over C candidate jobs. Input order as read by _dyn_kernel; outputs
    (node [CM], mode [CM], gpu [CM], idle', pipe', podsx'[, gpux']
    [, aff'...], done', popped', ready', pipelined', cursor', acount',
    job_alloc', queue_alloc', pops, progressed)."""
    kernel = functools.partial(_dyn_kernel, cfg, C, KP, M, N, R, G, GR,
                               J, Q, S, NH)
    f32, i32 = jnp.float32, jnp.int32
    CM = C * M
    gpu = bool(cfg.enable_gpu)
    aff = bool(cfg.enable_pod_affinity)

    out_shape = [
        jax.ShapeDtypeStruct((1, CM), i32),     # node
        jax.ShapeDtypeStruct((1, CM), i32),     # mode
        jax.ShapeDtypeStruct((1, CM), i32),     # gpu
        jax.ShapeDtypeStruct((R, N), f32),      # idle'
        jax.ShapeDtypeStruct((R, N), f32),      # pipe'
        jax.ShapeDtypeStruct((1, N), f32),      # podsx'
    ]
    if gpu:
        out_shape.append(jax.ShapeDtypeStruct((G, N), f32))
    if aff:
        SK, ETA = aff_dims
        out_shape += [jax.ShapeDtypeStruct((SK, N), f32),
                      jax.ShapeDtypeStruct((SK, 1), f32),
                      jax.ShapeDtypeStruct((ETA, N), f32)]
    out_shape += [
        jax.ShapeDtypeStruct((1, J), i32),      # done'
        jax.ShapeDtypeStruct((1, J), i32),      # popped'
        jax.ShapeDtypeStruct((1, J), i32),      # ready'
        jax.ShapeDtypeStruct((1, J), i32),      # pipelined'
        jax.ShapeDtypeStruct((1, J), i32),      # cursor'
        jax.ShapeDtypeStruct((1, J), i32),      # acount'
        jax.ShapeDtypeStruct((R, J), f32),      # job_alloc'
        jax.ShapeDtypeStruct((Q, R), f32),      # queue_alloc'
        jax.ShapeDtypeStruct((1, 1), i32),      # pops
        jax.ShapeDtypeStruct((1, 1), i32),      # progressed
    ]

    def place(*args):
        # launch-boundary trace annotation (name-stack metadata only -
        # zero equations, decisions and jaxpr counts untouched)
        with jax.named_scope("volcano/pallas/dyn_rounds"):
            return pl.pallas_call(
                lambda *refs: kernel(refs),
                out_shape=tuple(out_shape),
                interpret=interpret,
            )(*args)

    return place


def dyn_launch_stats(pops, requested):
    """(pops_clamped i32, early_stop i32) for one dyn-kernel launch: the
    telemetry decomposition of the kernel's pops output. Every launch
    counts at least one pop (pop-0 forcing), and a launch that returned
    fewer pops than its requested budget early-stopped (candidate miss,
    hdrf frozen-column guard, or simply no more eligible work)."""
    import jax.numpy as jnp
    p = jnp.maximum(pops, jnp.int32(1))
    early = jnp.where(pops < requested, jnp.int32(1), jnp.int32(0))
    return p, early


def vmem_estimate_bytes(K: int, M: int, N: int, R: int, G: int,
                        P: int, GR: int, SK: int = 0, ETA: int = 0,
                        SEL: int = 0, J: int = 0, Q: int = 0) -> int:
    """Rough VMEM footprint of the kernel's live values (both kernels; the
    dynamic-key path adds the per-job key state, the affinity path the
    node-space count maps — keep in sync with _read_*_env)."""
    per_n = 4 * N * (R * 6          # relmp/alloc/idle/pipe + committed pair
                     + G * 3        # gidle0 + gpux pair
                     + 3 * P        # template feasibility/score maps
                     + GR + 8)      # OR groups + block/bonus/lock/cnt rows
    per_km = 4 * K * M * (R + 10)   # per-task rows
    per_aff = 4 * N * (SK * 3       # sk_domain + live/committed counts
                       + ETA * 3    # eta_domain + anti counts pair
                       + SEL)       # static preferred map
    per_aff += 4 * K * M * (SK + ETA + SEL + 8)
    per_j = 4 * J * (R * 2 + 24) + 4 * Q * R * 3
    return per_n + per_km + per_aff + per_j


# --------------------------------------------------------------------------
# shard-local candidate kernel: one placement attempt per shard per launch
# --------------------------------------------------------------------------

def _shard_cand_kernel(cfg, NL, R, G, GR, refs):
    """One placement attempt over this shard's NL node rows.

    The sharded scan branch (allocate_scan, ``mesh`` passed) keeps pops,
    fairness-key recompute, and capacity commits in replicated XLA and
    only delegates the per-attempt feasibility -> score -> local-argmax to
    this kernel, launched under shard_map with every node-axis ref already
    shard-local. Outputs are the (1, 1) candidate tuple per pick kind —
    (best score, lowest GLOBAL row index at best, found flag, raw tie
    count) — that the in-graph cross-shard argmax combine reduces to the
    same winner ``select.best_node`` returns on the full row axis.

    Bitwise notes: ``future`` uses the scan association
    ``((idle + releasing) - pipelined) - pipe_extra`` (NOT the fused
    kernels' precomputed relmp), and the tie count is the RAW lane count
    at the local best so the combine can sum raw counts at the global max
    before applying tie_count's ``max(n - 1, 0)``.
    """
    gpu = bool(cfg.enable_gpu)
    it = iter(refs)
    nxt = lambda: next(it)

    rr_ref = nxt()                      # [R, 1] f32 resource request
    gq_ref = nxt() if gpu else None     # [1, 1] f32 gpu request
    pref_ref = nxt()                    # [1, 1] i32 preferred node (-1)
    tmpl_ref = nxt()                    # [1, 1] i32 template id (clamped)
    grp_ref = nxt()                     # [1, 1] i32 OR-group id (-1 none)
    voln_ref = nxt()                    # [1, 1] i32 volume node pin (-1)
    volok_ref = nxt()                   # [1, 1] i32 volume feasible
    rev_ref = nxt()                     # [1, 1] i32 revocable flag
    istgt_ref = nxt()                   # [1, 1] i32 job == resv target
    off_ref = nxt()                     # [1, 1] i32 shard global row base
    tstat_ref = nxt()                   # [P, NL] template feasibility
    tscore_ref = nxt()                  # [P, NL] taint-prefer score
    nascore_ref = nxt()                 # [P, NL] NodeAffinity score
    blocknr = nxt()[:] > 0              # [1, NL] tdm block-nonrevocable
    blockall = nxt()[:] > 0             # [1, NL] tdm block-all
    bonus = nxt()[:]                    # [1, NL] f32 tdm revocable bonus
    locked = nxt()[:] > 0               # [1, NL] reservation locks
    orfeas_ref = nxt()                  # [GR, NL] OR-group feasibility
    rel_ref = nxt()                     # [R, NL] releasing
    pip_ref = nxt()                     # [R, NL] pipelined
    alo_ref = nxt()                     # [R, NL] allocatable capacity
    cnt_ref = nxt()                     # [1, NL] pod counts
    maxp_ref = nxt()                    # [1, NL] max pods
    gid0_ref = nxt() if gpu else None   # [G, NL] gpu idle baseline
    idle_ref = nxt()                    # [R, NL] live idle
    pipe_ref = nxt()                    # [R, NL] live pipe_extra
    podsx_ref = nxt()                   # [1, NL] f32 pods this cycle
    gpux_ref = nxt() if gpu else None   # [G, NL] gpu charged this cycle
    scn_o, ixn_o, fnn_o, tien_o = nxt(), nxt(), nxt(), nxt()
    scf_o, ixf_o, fnf_o, tief_o = nxt(), nxt(), nxt(), nxt()

    off = jnp.sum(off_ref[:], dtype=jnp.int32)
    iota_n = jax.lax.broadcasted_iota(jnp.int32, (1, NL), 1) + off
    rr_col = rr_ref[:]
    pref = jnp.sum(pref_ref[:], dtype=jnp.int32)
    tmpl = jnp.sum(tmpl_ref[:], dtype=jnp.int32)
    grp = jnp.sum(grp_ref[:], dtype=jnp.int32)
    voln = jnp.sum(voln_ref[:], dtype=jnp.int32)
    volok = jnp.sum(volok_ref[:], dtype=jnp.int32) > 0
    rev = jnp.sum(rev_ref[:], dtype=jnp.int32) > 0
    is_tgt = jnp.sum(istgt_ref[:], dtype=jnp.int32) > 0

    idle = idle_ref[:]
    pipe = pipe_ref[:]
    podsx = podsx_ref[:]

    # static feasibility row: the node_ok conjunction of the scan branch
    trow = (pl.dslice(tmpl, 1), slice(None))
    sfeas = tstat_ref[trow] > 0                               # [1, NL]
    sfeas &= ~(blocknr & ~rev) & ~blockall
    orrow = orfeas_ref[(pl.dslice(jnp.maximum(grp, 0), 1),
                        slice(None))] > 0
    sfeas &= orrow | (grp < 0)
    sfeas &= volok & ((voln < 0) | (iota_n == voln))
    sfeas &= ~locked | is_tgt

    # scan association: ((idle + releasing) - pipelined) - pipe_extra
    future = jnp.maximum(idle + rel_ref[:] - pip_ref[:] - pipe, 0.0)
    pods_ok = (cnt_ref[:] + podsx) < maxp_ref[:]
    shared = sfeas & pods_ok
    if gpu:
        gr = gq_ref[:]                                        # [1, 1]
        gidle = gid0_ref[:] - gpux_ref[:]
        gpu_ok = (gr <= 0) | jnp.any(gidle >= gr - _EPS_FIT,
                                     axis=0, keepdims=True)
        shared &= gpu_ok
    fit_now = jnp.all(rr_col <= idle + _EPS_FIT, axis=0, keepdims=True)
    fit_fut = jnp.all(rr_col <= future + _EPS_FIT, axis=0, keepdims=True)
    feas_now = shared & fit_now
    feas_fut = shared & fit_fut

    # f32 addition order matches allocate_scan exactly (see _make_attempt)
    score = _dyn_score(cfg, idle, alo_ref[:], rr_col)
    score = score + tscore_ref[trow]
    score = score + (nascore_ref[trow] + jnp.where(rev, bonus, 0.0))
    score = score + jnp.where((pref >= 0) & (iota_n == pref),
                              jnp.float32(100.0), jnp.float32(0.0))

    big_i = off + jnp.int32(NL)         # sentinel past this shard's rows

    def pick(feas):
        masked = jnp.where(feas, score, NEG)
        best = jnp.max(masked, axis=1, keepdims=True)
        idx = jnp.min(jnp.where(masked == best, iota_n, big_i),
                      axis=1, keepdims=True)
        fn = jnp.max(feas.astype(jnp.int32), axis=1, keepdims=True)
        tie = jnp.sum(((masked == best) & feas).astype(jnp.int32),
                      axis=1, keepdims=True)
        return best, idx, fn, tie

    scn_o[:], ixn_o[:], fnn_o[:], tien_o[:] = pick(feas_now)
    scf_o[:], ixf_o[:], fnf_o[:], tief_o[:] = pick(feas_fut)


def make_shard_candidate_placer(cfg, NL: int, R: int, G: int, GR: int,
                                interpret: bool = False):
    """Build the shard-local candidate placer (sharding x pallas path).

    Returns place(args...) with the input order documented in
    _shard_cand_kernel; outputs the 8-tuple of (1, 1) candidates
    (score/idx/found/ties for now, then for future). GPU refs are absent
    when cfg.enable_gpu is False. ``NL`` is the SHARD-LOCAL row count —
    the caller launches this under shard_map, so block shapes never
    exceed the rows a shard owns (graphcheck family 9 audits this).
    """
    kernel = functools.partial(_shard_cand_kernel, cfg, NL, R, G, GR)
    f32, i32 = jnp.float32, jnp.int32
    out_shape = [
        jax.ShapeDtypeStruct((1, 1), f32),    # score_now
        jax.ShapeDtypeStruct((1, 1), i32),    # idx_now (global row)
        jax.ShapeDtypeStruct((1, 1), i32),    # found_now
        jax.ShapeDtypeStruct((1, 1), i32),    # ties_now (raw)
        jax.ShapeDtypeStruct((1, 1), f32),    # score_fut
        jax.ShapeDtypeStruct((1, 1), i32),    # idx_fut
        jax.ShapeDtypeStruct((1, 1), i32),    # found_fut
        jax.ShapeDtypeStruct((1, 1), i32),    # ties_fut
    ]

    def place(*args):
        # launch-boundary trace annotation (name-stack metadata only -
        # zero equations, decisions and jaxpr counts untouched)
        with jax.named_scope("volcano/pallas/shard_candidates"):
            return pl.pallas_call(
                lambda *refs: kernel(refs),
                out_shape=tuple(out_shape),
                interpret=interpret,
            )(*args)

    return place


# --------------------------------------------------------------------------
# wide wavefront candidate kernel: W placement attempts per shard per launch
# --------------------------------------------------------------------------

def _shard_wave_kernel(cfg, NL, R, G, GR, W, C, refs):
    """W placement attempts over this shard's NL node rows, one launch.

    The wavefront sweep (allocate_scan, ``wave_width`` > 1 under a mesh)
    evaluates the next W task attempts of a popped job section against the
    SAME capacity snapshot; this kernel is the shard-local sweep. Per task
    column it reproduces _shard_cand_kernel's feasibility conjunction and
    f32 score fold exactly, then extracts the column's top-C feasible rows
    by (score desc, global index asc) via C masked (max, min-index-at-max)
    reductions — the per-shard candidate lists the in-graph cross-shard
    merge (allocate_scan._wave_combine) reduces to the global top-C, which
    is exact because the global c-th best row is always within its own
    shard's top-c. Env/state refs are identical to _shard_cand_kernel;
    the per-task scalars widen to [1, W] ([R, W] for the request).

    Outputs per capacity view: (C, W) entry scores (NEG-filled past the
    shard's feasible count), (C, W) global row indices (the shard
    sentinel off+NL past them), and (1, W) feasible-count and
    raw-tie-at-local-best rows.
    """
    gpu = bool(cfg.enable_gpu)
    it = iter(refs)
    nxt = lambda: next(it)

    rr_ref = nxt()                      # [R, W] f32 resource requests
    gq_ref = nxt() if gpu else None     # [1, W] f32 gpu requests
    pref_ref = nxt()                    # [1, W] i32 preferred node (-1)
    tmpl_ref = nxt()                    # [1, W] i32 template id (clamped)
    grp_ref = nxt()                     # [1, W] i32 OR-group id (-1 none)
    voln_ref = nxt()                    # [1, W] i32 volume node pin (-1)
    volok_ref = nxt()                   # [1, W] i32 volume feasible
    rev_ref = nxt()                     # [1, W] i32 revocable flag
    istgt_ref = nxt()                   # [1, W] i32 job == resv target
    off_ref = nxt()                     # [1, 1] i32 shard global row base
    tstat_ref = nxt()                   # [P, NL] template feasibility
    tscore_ref = nxt()                  # [P, NL] taint-prefer score
    nascore_ref = nxt()                 # [P, NL] NodeAffinity score
    blocknr = nxt()[:] > 0              # [1, NL] tdm block-nonrevocable
    blockall = nxt()[:] > 0             # [1, NL] tdm block-all
    bonus = nxt()[:]                    # [1, NL] f32 tdm revocable bonus
    locked = nxt()[:] > 0               # [1, NL] reservation locks
    orfeas_ref = nxt()                  # [GR, NL] OR-group feasibility
    rel_ref = nxt()                     # [R, NL] releasing
    pip_ref = nxt()                     # [R, NL] pipelined
    alo_ref = nxt()                     # [R, NL] allocatable capacity
    cnt_ref = nxt()                     # [1, NL] pod counts
    maxp_ref = nxt()                    # [1, NL] max pods
    gid0_ref = nxt() if gpu else None   # [G, NL] gpu idle baseline
    idle_ref = nxt()                    # [R, NL] live idle
    pipe_ref = nxt()                    # [R, NL] live pipe_extra
    podsx_ref = nxt()                   # [1, NL] f32 pods this cycle
    gpux_ref = nxt() if gpu else None   # [G, NL] gpu charged this cycle
    scn_o, ixn_o, cnn_o, tin_o = nxt(), nxt(), nxt(), nxt()
    scf_o, ixf_o, cnf_o, tif_o = nxt(), nxt(), nxt(), nxt()

    off = jnp.sum(off_ref[:], dtype=jnp.int32)
    iota_n = jax.lax.broadcasted_iota(jnp.int32, (1, NL), 1) + off
    big_i = off + jnp.int32(NL)         # sentinel past this shard's rows
    idle = idle_ref[:]
    pipe = pipe_ref[:]
    podsx = podsx_ref[:]
    alo = alo_ref[:]
    rr_all = rr_ref[:]
    prefs, tmpls, grps = pref_ref[:], tmpl_ref[:], grp_ref[:]
    volns, voloks = voln_ref[:], volok_ref[:]
    revs, istgts = rev_ref[:], istgt_ref[:]
    if gpu:
        gqs = gq_ref[:]
        gidle = gid0_ref[:] - gpux_ref[:]

    # wave-shared (task-independent) capacity terms, computed once
    future = jnp.maximum(idle + rel_ref[:] - pip_ref[:] - pipe, 0.0)
    pods_ok = (cnt_ref[:] + podsx) < maxp_ref[:]

    outs = {k: [] for k in ("scn", "ixn", "cnn", "tin",
                            "scf", "ixf", "cnf", "tif")}
    for w in range(W):
        pref = jnp.sum(prefs[:, w:w + 1], dtype=jnp.int32)
        tmpl = jnp.sum(tmpls[:, w:w + 1], dtype=jnp.int32)
        grp = jnp.sum(grps[:, w:w + 1], dtype=jnp.int32)
        voln = jnp.sum(volns[:, w:w + 1], dtype=jnp.int32)
        volok = jnp.sum(voloks[:, w:w + 1], dtype=jnp.int32) > 0
        rev = jnp.sum(revs[:, w:w + 1], dtype=jnp.int32) > 0
        is_tgt = jnp.sum(istgts[:, w:w + 1], dtype=jnp.int32) > 0
        rr_col = rr_all[:, w:w + 1]                           # [R, 1]

        trow = (pl.dslice(tmpl, 1), slice(None))
        sfeas = tstat_ref[trow] > 0                           # [1, NL]
        sfeas &= ~(blocknr & ~rev) & ~blockall
        orrow = orfeas_ref[(pl.dslice(jnp.maximum(grp, 0), 1),
                            slice(None))] > 0
        sfeas &= orrow | (grp < 0)
        sfeas &= volok & ((voln < 0) | (iota_n == voln))
        sfeas &= ~locked | is_tgt
        shared = sfeas & pods_ok
        if gpu:
            gr = gqs[:, w:w + 1]                              # [1, 1]
            gpu_ok = (gr <= 0) | jnp.any(gidle >= gr - _EPS_FIT,
                                         axis=0, keepdims=True)
            shared &= gpu_ok
        fit_now = jnp.all(rr_col <= idle + _EPS_FIT, axis=0, keepdims=True)
        fit_fut = jnp.all(rr_col <= future + _EPS_FIT, axis=0,
                          keepdims=True)
        feas_now = shared & fit_now
        feas_fut = shared & fit_fut

        # f32 addition order matches allocate_scan exactly
        score = _dyn_score(cfg, idle, alo, rr_col)
        score = score + tscore_ref[trow]
        score = score + (nascore_ref[trow] + jnp.where(rev, bonus, 0.0))
        score = score + jnp.where((pref >= 0) & (iota_n == pref),
                                  jnp.float32(100.0), jnp.float32(0.0))

        def topc(feas):
            masked0 = jnp.where(feas, score, NEG)
            best0 = jnp.max(masked0, axis=1, keepdims=True)
            tie = jnp.sum(((masked0 == best0) & feas).astype(jnp.int32),
                          axis=1, keepdims=True)
            n_f = jnp.sum(feas.astype(jnp.int32), axis=1, keepdims=True)
            f = feas
            sc_e, ix_e = [], []
            for _ in range(C):
                masked = jnp.where(f, score, NEG)
                best = jnp.max(masked, axis=1, keepdims=True)
                idx = jnp.min(jnp.where((masked == best) & f,
                                        iota_n, big_i),
                              axis=1, keepdims=True)
                sc_e.append(best)
                ix_e.append(idx)
                f = f & (iota_n != idx)
            return (jnp.concatenate(sc_e, axis=0),            # [C, 1]
                    jnp.concatenate(ix_e, axis=0), n_f, tie)

        sc, ix, n_f, tie = topc(feas_now)
        outs["scn"].append(sc)
        outs["ixn"].append(ix)
        outs["cnn"].append(n_f)
        outs["tin"].append(tie)
        sc, ix, n_f, tie = topc(feas_fut)
        outs["scf"].append(sc)
        outs["ixf"].append(ix)
        outs["cnf"].append(n_f)
        outs["tif"].append(tie)

    scn_o[:] = jnp.concatenate(outs["scn"], axis=1)
    ixn_o[:] = jnp.concatenate(outs["ixn"], axis=1)
    cnn_o[:] = jnp.concatenate(outs["cnn"], axis=1)
    tin_o[:] = jnp.concatenate(outs["tin"], axis=1)
    scf_o[:] = jnp.concatenate(outs["scf"], axis=1)
    ixf_o[:] = jnp.concatenate(outs["ixf"], axis=1)
    cnf_o[:] = jnp.concatenate(outs["cnf"], axis=1)
    tif_o[:] = jnp.concatenate(outs["tif"], axis=1)


def make_shard_wave_placer(cfg, NL: int, R: int, G: int, GR: int,
                           W: int, C: int, interpret: bool = False):
    """Build the wide wavefront candidate placer (sharding x wavefront).

    Returns place(args...) with the input order documented in
    _shard_wave_kernel; outputs the 8-tuple of per-view candidate lists:
    (C, W) scores, (C, W) global indices, (1, W) feasible counts, (1, W)
    raw ties for the now view, then the same for the future view. GPU
    refs are absent when cfg.enable_gpu is False. ``NL`` is the
    SHARD-LOCAL row count, ``W`` the wave width, ``C`` the candidate
    depth (allocate_scan.wave_candidate_depth).
    """
    kernel = functools.partial(_shard_wave_kernel, cfg, NL, R, G, GR, W, C)
    f32, i32 = jnp.float32, jnp.int32
    out_shape = [
        jax.ShapeDtypeStruct((C, W), f32),    # entry scores, now view
        jax.ShapeDtypeStruct((C, W), i32),    # entry global rows, now
        jax.ShapeDtypeStruct((1, W), i32),    # feasible count, now
        jax.ShapeDtypeStruct((1, W), i32),    # raw ties at best, now
        jax.ShapeDtypeStruct((C, W), f32),    # entry scores, future view
        jax.ShapeDtypeStruct((C, W), i32),    # entry global rows, future
        jax.ShapeDtypeStruct((1, W), i32),    # feasible count, future
        jax.ShapeDtypeStruct((1, W), i32),    # raw ties at best, future
    ]

    def place(*args):
        with jax.named_scope("volcano/pallas/shard_wave_candidates"):
            return pl.pallas_call(
                lambda *refs: kernel(refs),
                out_shape=tuple(out_shape),
                interpret=interpret,
            )(*args)

    return place
