"""Pallas TPU kernel: fused placement rounds of the allocate pass.

The hot inner loop of the cycle places the pending tasks of selected gangs
one by one (capacity feedback between placements is what makes the pass
exact, SURVEY.md section 7 hard part 1). The pure-XLA path runs it as a
``lax.scan`` whose every step issues ~40 small HLO ops over [N]-shaped
arrays; this kernel fuses WHOLE placement rounds into one ``pl.pallas_call``
with the capacity state (idle, pipelined-extra, pod counts, per-GPU-card
usage) resident in VMEM across all placements.

v2 design (on top of the round-fused v1):

- **In-kernel template gathers.** Per-task static feasibility/score rows are
  read from the per-TEMPLATE matrices ([P, N] — the predicate-cache analog,
  predicates/cache.go:42-90) with dynamic sublane slices inside the kernel,
  instead of materializing [M, N] gather outputs in XLA every round. A round
  now ships only O(M) scalars per task plus the (static-per-cycle) template
  maps.
- **K-job batched rounds** (``K`` static): one launch runs K job sections
  sequentially with per-section gang commit/discard (JobReady /
  JobPipelined / Statement.Discard, statement.go:352-395) INSIDE the kernel,
  so the committed capacity flows section to section without a host/XLA
  round-trip. Batching K > 1 is bit-exact with the sequential pop order iff
  the job-ordering keys are static over commits — no drf/hdrf dynamic
  ordering and no finite proportion ``deserved`` (see
  AllocateConfig.batch_jobs; the session only enables it when those hold).
- **Optional GPU path** (``enable_gpu`` static): snapshots with no shared-GPU
  requests skip the per-card state entirely (decision-neutral: a zero
  gpu_request never charges a card, gpu.go:41-56).

Layout: node-axis tensors are transposed to [R, N] / [G, N] / [P, N] so the
node axis is the 128-lane dimension (R/G/P are small; [N, R] would waste 32x
lanes).

Semantics are bit-identical to the scan path in allocate_scan.task_step
(asserted by tests/test_pallas_place.py): same feasibility conjunction, same
score formulas (ops/scoring.py) in the same f32 addition order, same
lowest-index argmax tie-break (ops/select.py best_node), same
lowest-fitting-card GPU pick (ops/predicates.py pick_gpu_row).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .allocate_scan import MODE_ALLOCATED, MODE_NONE, MODE_PIPELINED

_EPS_FIT = 1e-5     # predicates._EPS
_EPS_DIV = 1e-9     # scoring._EPS
NEG = -1e30         # select.NEG


def _dyn_score(cfg, idle, alloc_t, rr_col):
    """Idle-dependent score terms in [R, N] layout — transposed but
    float-op-for-float-op identical to ops/scoring.py (reductions run over
    the same R elements in the same order, so f32 results match bitwise)."""
    used = alloc_t - idle
    N = idle.shape[1]
    score = jnp.zeros((1, N), jnp.float32)
    if cfg.binpack_weight:
        applicable = (rr_col > 0) & (alloc_t > 0)   # weights all-ones
        frac = jnp.where(applicable,
                         (used + rr_col) / jnp.maximum(alloc_t, _EPS_DIV), 0.0)
        over = frac > 1.0 + 1e-6
        w = 1.0 * applicable
        wsum = jnp.sum(w, axis=0, keepdims=True)
        raw = jnp.sum(frac * w, axis=0, keepdims=True) \
            / jnp.maximum(wsum, _EPS_DIV)
        raw = jnp.where(jnp.any(over, axis=0, keepdims=True), 0.0, raw)
        score += cfg.binpack_weight * raw * 100.0
    if cfg.least_allocated_weight:
        cap = jnp.maximum(alloc_t, _EPS_DIV)
        free_frac = (alloc_t - used - rr_col) / cap
        counted = alloc_t > 0
        n = jnp.maximum(jnp.sum(counted, axis=0, keepdims=True), 1)
        score += cfg.least_allocated_weight * (
            jnp.sum(jnp.clip(free_frac, 0.0, 1.0) * counted, axis=0,
                    keepdims=True) / n * 100.0)
    if cfg.most_allocated_weight:
        cap = jnp.maximum(alloc_t, _EPS_DIV)
        used_frac = (used + rr_col) / cap
        counted = alloc_t > 0
        n = jnp.maximum(jnp.sum(counted, axis=0, keepdims=True), 1)
        score += cfg.most_allocated_weight * (
            jnp.sum(jnp.clip(used_frac, 0.0, 1.0) * counted, axis=0,
                    keepdims=True) / n * 100.0)
    if cfg.balanced_weight:
        cap = jnp.maximum(alloc_t, _EPS_DIV)
        frac = jnp.clip((used + rr_col) / cap, 0.0, 1.0)
        counted = (alloc_t > 0).astype(frac.dtype)
        n = jnp.maximum(jnp.sum(counted, axis=0, keepdims=True), 1.0)
        mean = jnp.sum(frac * counted, axis=0, keepdims=True) / n
        var = jnp.sum(((frac - mean) ** 2) * counted, axis=0,
                      keepdims=True) / n
        score += cfg.balanced_weight * (1.0 - jnp.sqrt(var)) * 100.0
    return score


def _batch_kernel(cfg, K, M, N, R, G, GR, refs):
    """K job sections x M placements, all in VMEM.

    ``refs`` is the flat ref list in the order built by make_round_placer;
    unpacked here to keep the signature manageable.
    """
    gpu = bool(cfg.enable_gpu)
    it = iter(refs)

    def nxt():
        return next(it)

    resreq_t_ref = nxt()      # [R, KM]
    gpu_req_ref = nxt() if gpu else None        # [1, KM]
    active_ref = nxt()        # [1, KM] i32 (open & not best-effort)
    pref_ref = nxt()          # [1, KM] i32
    suffix_ref = nxt()        # [1, KM] i32
    tmpl_ref = nxt()          # [1, KM] i32 template id (clamped)
    grp_ref = nxt()           # [1, KM] i32 OR-group id (-1 none)
    voln_ref = nxt()          # [1, KM] i32 volume pin node (-1 any)
    volok_ref = nxt()         # [1, KM] i32 volume-bindable flag
    rev_ref = nxt()           # [1, KM] i32 task revocable flag
    ready0_ref = nxt()        # [1, K] i32
    minav_ref = nxt()         # [1, K] i32
    canb_ref = nxt()          # [1, K] i32 can-batch (re-pop fusion) flag
    secact_ref = nxt()        # [1, K] i32 section active (ji >= 0)
    istgt_ref = nxt()         # [1, K] i32 section job == reservation target
    tstat_ref = nxt()         # [P, N] f32 template static feasibility
    tscore_ref = nxt()        # [P, N] f32 taint-prefer static score
    nascore_ref = nxt()       # [P, N] f32 NodeAffinity preferred score
    blocknr_ref = nxt()       # [1, N] f32 tdm block-nonrevocable
    blockall_ref = nxt()      # [1, N] f32 tdm block-all
    bonus_ref = nxt()         # [1, N] f32 tdm revocable bonus
    locked_ref = nxt()        # [1, N] f32 reservation node locks
    orfeas_ref = nxt()        # [GR, N] f32 OR-of-terms group feasibility
    relmp_ref = nxt()         # [R, N] releasing - pipelined
    alloc_t_ref = nxt()       # [R, N]
    cnt_ref = nxt()           # [1, N]
    maxp_ref = nxt()          # [1, N]
    gidle0_ref = nxt() if gpu else None         # [G, N]
    idle_ref = nxt()          # [R, N] in
    pipe_ref = nxt()          # [R, N] in
    podsx_ref = nxt()         # [1, N] in
    gpux_ref = nxt() if gpu else None           # [G, N] in
    node_o = nxt()            # [1, KM] out
    mode_o = nxt()            # [1, KM] out
    gpu_o = nxt()             # [1, KM] out
    idle_o = nxt()            # [R, N] out
    pipe_o = nxt()            # [R, N] out
    podsx_o = nxt()           # [1, N] out
    gpux_o = nxt() if gpu else None             # [G, N] out

    KM = K * M
    relmp = relmp_ref[:]
    alloc_t = alloc_t_ref[:]
    cnt = cnt_ref[:]
    maxp = maxp_ref[:]
    resreq_t = resreq_t_ref[:]
    active_v = active_ref[:]
    pref_v = pref_ref[:]
    suffix_v = suffix_ref[:]
    tmpl_v = tmpl_ref[:]
    grp_v = grp_ref[:]
    voln_v = voln_ref[:]
    volok_v = volok_ref[:]
    rev_v = rev_ref[:]
    ready0_v = ready0_ref[:]
    minav_v = minav_ref[:]
    canb_v = canb_ref[:]
    secact_v = secact_ref[:]
    istgt_v = istgt_ref[:]
    blocknr = blocknr_ref[:] > 0
    blockall = blockall_ref[:] > 0
    bonus = bonus_ref[:]
    locked = locked_ref[:] > 0
    if gpu:
        gpu_req = gpu_req_ref[:]
        gidle0 = gidle0_ref[:]

    iota_n = jax.lax.broadcasted_iota(jnp.int32, (1, N), 1)
    iota_g = jax.lax.broadcasted_iota(jnp.int32, (G, 1), 0) if gpu else None
    iota_km = jax.lax.broadcasted_iota(jnp.int32, (1, KM), 1)
    iota_k = jax.lax.broadcasted_iota(jnp.int32, (1, K), 1)

    def seli(row, idx, iota):
        # mosaic has no dynamic lane indexing: scalar = one-hot reduce
        return jnp.sum(jnp.where(iota == idx, row, 0))

    def job_body(k, jcarry):
        # committed (post gang-finalize) state from prior sections
        (cidle, cpipe, cpods, cgpux, node_v, mode_v, gpuc_v) = jcarry
        ready0 = seli(ready0_v, k, iota_k)
        min_avail = seli(minav_v, k, iota_k)
        can_batch = seli(canb_v, k, iota_k) > 0
        sec_act = seli(secact_v, k, iota_k) > 0
        is_tgt = seli(istgt_v, k, iota_k) > 0

        def task_body(m, tcarry):
            (idle, pipe, podsx, gpux, node_v, mode_v, gpuc_v,
             n_allocs, n_pipes, stopped, broke) = tcarry
            s = k * M + m
            sel_s = (iota_km == s).astype(jnp.float32)          # [1, KM]
            sel_i = sel_s.astype(jnp.int32)
            rr_col = jnp.sum(resreq_t * sel_s, axis=1, keepdims=True)  # [R,1]
            act = jnp.sum(active_v * sel_i) > 0
            pref = jnp.sum(pref_v * sel_i)
            suffix = jnp.sum(suffix_v * sel_i)
            tmpl = jnp.sum(tmpl_v * sel_i)
            grp = jnp.sum(grp_v * sel_i)
            voln = jnp.sum(voln_v * sel_i)
            volok = jnp.sum(volok_v * sel_i) > 0
            rev = jnp.sum(rev_v * sel_i) > 0

            # static feasibility row: template mask + per-cycle node gates
            # (the node_ok conjunction of allocate_scan.task_step)
            trow = (pl.dslice(tmpl, 1), slice(None))
            sfeas = tstat_ref[trow] > 0                          # [1, N]
            sfeas &= ~(blocknr & ~rev) & ~blockall
            orrow = orfeas_ref[(pl.dslice(jnp.maximum(grp, 0), 1),
                                slice(None))] > 0
            sfeas &= orrow | (grp < 0)
            sfeas &= volok & ((voln < 0) | (iota_n == voln))
            sfeas &= ~locked | is_tgt

            future = jnp.maximum(idle + relmp - pipe, 0.0)
            pods_ok = (cnt + podsx) < maxp
            shared = sfeas & pods_ok
            if gpu:
                gr = jnp.sum(gpu_req * sel_s, axis=1, keepdims=True)  # [1,1]
                gidle = gidle0 - gpux
                gpu_ok = (gr <= 0) | jnp.any(gidle >= gr - _EPS_FIT,
                                             axis=0, keepdims=True)
                shared &= gpu_ok
            fit_now = jnp.all(rr_col <= idle + _EPS_FIT, axis=0,
                              keepdims=True)
            fit_fut = jnp.all(rr_col <= future + _EPS_FIT, axis=0,
                              keepdims=True)
            feas_now = shared & fit_now
            feas_fut = shared & fit_fut

            # f32 addition order matches allocate_scan exactly:
            # dyn terms, then taint-static, then (nodeaffinity + rev*bonus),
            # then task-topology preference
            score = _dyn_score(cfg, idle, alloc_t, rr_col)
            score = score + tscore_ref[trow]
            score = score + (nascore_ref[trow]
                             + jnp.where(rev, bonus, 0.0))
            score = score + jnp.where((pref >= 0) & (iota_n == pref),
                                      100.0, 0.0)

            def pick(feas):
                masked = jnp.where(feas, score, NEG)
                best = jnp.max(masked)
                idx = jnp.min(jnp.where(masked == best, iota_n, N))
                found = jnp.max(feas.astype(jnp.int32)) > 0
                return idx, found

            n_now, found_now = pick(feas_now)
            n_fut, found_fut = pick(feas_fut)
            # yield/break state gates the attempt (allocate.go:205-266)
            active = act & sec_act & ~stopped & ~broke
            can_now = found_now & active
            can_fut = found_fut & active & bool(cfg.enable_pipelining)
            do_alloc = can_now
            do_pipe = (~can_now) & can_fut
            placed = do_alloc | do_pipe
            node = jnp.where(do_alloc, n_now, n_fut)

            onehot = (iota_n == node).astype(jnp.float32)        # [1, N]
            idle = idle - jnp.where(do_alloc, 1.0, 0.0) * rr_col * onehot
            pipe = pipe + jnp.where(do_pipe, 1.0, 0.0) * rr_col * onehot
            podsx = podsx + jnp.where(placed, 1.0, 0.0) * onehot

            if gpu:
                # lowest fitting card on the chosen node (pick_gpu_row)
                gcol = jnp.sum(gidle * onehot, axis=1, keepdims=True)  # [G,1]
                gfits = gcol >= gr - _EPS_FIT
                card = jnp.min(jnp.where(gfits, iota_g, G))
                ok_pick = (jnp.max(gfits.astype(jnp.int32)) > 0) \
                    & (gr[0, 0] > 0)
                card = jnp.where(ok_pick, card, -1)
                charge = placed & (card >= 0)
                gpux = gpux + (jnp.where(charge, 1.0, 0.0) * gr
                               * (iota_g == jnp.maximum(card, 0)) * onehot)
            else:
                card = jnp.int32(-1)
                charge = jnp.bool_(False)

            mode = jnp.where(do_alloc, MODE_ALLOCATED,
                             jnp.where(do_pipe, MODE_PIPELINED, MODE_NONE))
            is_s = iota_km == s
            node_v = jnp.where(is_s, jnp.where(placed, node, -1), node_v)
            mode_v = jnp.where(is_s, mode, mode_v)
            gpuc_v = jnp.where(is_s, jnp.where(charge, card, -1), gpuc_v)
            n_allocs = n_allocs + jnp.where(do_alloc, 1, 0)
            n_pipes = n_pipes + jnp.where(do_pipe, 1, 0)
            if cfg.enable_gang:
                ready_aft = (ready0 + n_allocs) >= min_avail
            else:
                ready_aft = True
            stopped = stopped | (placed & ready_aft & (suffix > 0)
                                 & ~can_batch)
            broke = broke | (active & ~placed)
            return (idle, pipe, podsx, gpux, node_v, mode_v, gpuc_v,
                    n_allocs, n_pipes, stopped, broke)

        (idle, pipe, podsx, gpux, node_v, mode_v, gpuc_v,
         n_allocs, n_pipes, _stopped, _broke) = jax.lax.fori_loop(
            0, M, task_body,
            (cidle, cpipe, cpods, cgpux, node_v, mode_v, gpuc_v,
             jnp.int32(0), jnp.int32(0), jnp.bool_(False), jnp.bool_(False)))

        # ---- gang finalize in-kernel (JobReady/JobPipelined/Discard) ------
        if cfg.enable_gang:
            ready = (ready0 + n_allocs) >= min_avail
        else:
            ready = jnp.bool_(True)
        pipelined = (ready0 + n_allocs + n_pipes) >= min_avail
        keep = ready | pipelined
        sec = (iota_km >= k * M) & (iota_km < (k + 1) * M)
        node_v = jnp.where(keep | ~sec, node_v, -1)
        mode_v = jnp.where(keep | ~sec, mode_v, MODE_NONE)
        gpuc_v = jnp.where(keep | ~sec, gpuc_v, -1)
        idle = jnp.where(keep, idle, cidle)
        pipe = jnp.where(keep, pipe, cpipe)
        podsx = jnp.where(keep, podsx, cpods)
        if gpu:
            gpux = jnp.where(keep, gpux, cgpux)
        return (idle, pipe, podsx, gpux, node_v, mode_v, gpuc_v)

    neg1 = jnp.full((1, KM), -1, jnp.int32)
    gpux0 = gpux_ref[:] if gpu else jnp.zeros((1, 1), jnp.float32)
    (idle, pipe, podsx, gpux, node_v, mode_v, gpuc_v) = jax.lax.fori_loop(
        0, K, job_body,
        (idle_ref[:], pipe_ref[:], podsx_ref[:], gpux0,
         neg1, jnp.zeros((1, KM), jnp.int32), neg1))
    node_o[:] = node_v
    mode_o[:] = mode_v
    gpu_o[:] = gpuc_v
    idle_o[:] = idle
    pipe_o[:] = pipe
    podsx_o[:] = podsx
    if gpu:
        gpux_o[:] = gpux


def make_round_placer(cfg, K: int, M: int, N: int, R: int, G: int,
                      GR: int, interpret: bool = False):
    """Build the fused batched-round placer.

    Returns place(args...) with the input order documented in
    _batch_kernel; outputs (node [KM], mode [KM], gpu [KM], idle', pipe',
    podsx'[, gpux']). GPU refs are absent when cfg.enable_gpu is False.
    """
    kernel = functools.partial(_batch_kernel, cfg, K, M, N, R, G, GR)
    f32 = jnp.float32
    KM = K * M
    gpu = bool(cfg.enable_gpu)

    out_shape = [
        jax.ShapeDtypeStruct((1, KM), jnp.int32),   # node
        jax.ShapeDtypeStruct((1, KM), jnp.int32),   # mode
        jax.ShapeDtypeStruct((1, KM), jnp.int32),   # gpu
        jax.ShapeDtypeStruct((R, N), f32),          # idle'
        jax.ShapeDtypeStruct((R, N), f32),          # pipe'
        jax.ShapeDtypeStruct((1, N), f32),          # podsx'
    ]
    if gpu:
        out_shape.append(jax.ShapeDtypeStruct((G, N), f32))  # gpux'

    def place(*args):
        outs = pl.pallas_call(
            lambda *refs: kernel(refs),
            out_shape=tuple(out_shape),
            interpret=interpret,
        )(*args)
        node, mode, gpuc = outs[0][0], outs[1][0], outs[2][0]
        return (node, mode, gpuc) + tuple(outs[3:])

    return place


def vmem_estimate_bytes(K: int, M: int, N: int, R: int, G: int,
                        P: int, GR: int) -> int:
    """Rough VMEM footprint of the kernel's live values."""
    per_n = 4 * N * (R * 6          # relmp/alloc/idle/pipe + committed pair
                     + G * 3        # gidle0 + gpux pair
                     + 3 * P        # template feasibility/score maps
                     + GR + 8)      # OR groups + block/bonus/lock/cnt rows
    per_km = 4 * K * M * (R + 10)   # per-task rows
    return per_n + per_km
