"""Fair-share solvers: proportion water-filling, DRF, hierarchical DRF.

TPU re-design of the reference's fairness plugins:
- proportion's iterative deserved-share water-filling
  (pkg/scheduler/plugins/proportion/proportion.go:140-197) becomes a bounded
  ``lax.while_loop`` over dense [Q, R] arrays with branchless clamping.
- drf's dominant-resource shares (pkg/scheduler/plugins/drf/drf.go:104-131,
  calcShare) become one masked max-reduce per job.
- the fork's hierarchical DRF (drf.go:42-87, 230-360) is computed over the
  packed parent-pointer queue tree by propagating subtree allocations up a
  fixed number of levels.

All solvers run inside the same jit as the allocate pass.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..api.resource import MIN_RESOURCE
from ..arrays.schema import QueueArrays

_EPS = 1e-9


def proportion_deserved(queues: QueueArrays, total: jax.Array,
                        max_iters: int = 16) -> jax.Array:
    """f32[Q, R]: each queue's deserved share by weighted water-filling.

    Exact port of the fixed point computed by proportion.go:140-197:
    repeatedly hand each unmet queue ``remaining * w_q / sum(unmet weights)``,
    clamp elementwise by capability and request (all three branches of the Go
    code reduce to ``min(deserved', capability?, request)`` with capability
    applied only when exceeded — the min is a no-op otherwise, so the
    branchless form is identical), mark queues meeting their request or
    capability, and recycle the clamped-off amount into ``remaining``.
    """
    Q, R = queues.allocated.shape
    weight = jnp.where(queues.valid, queues.weight, 0.0)
    request = queues.request
    capability = queues.capability

    def cond(st):
        deserved, remaining, meet, prev_remaining, it = st
        total_w = jnp.sum(jnp.where(meet, 0.0, weight))
        changed = jnp.any(jnp.abs(remaining - prev_remaining) > _EPS)
        nonempty = jnp.any(remaining >= MIN_RESOURCE)
        return (total_w > 0) & nonempty & changed & (it < max_iters)

    def body(st):
        deserved, remaining, meet, _prev, it = st
        total_w = jnp.sum(jnp.where(meet, 0.0, weight))
        frac = jnp.where(meet, 0.0, weight) / jnp.maximum(total_w, _EPS)
        proposed = deserved + remaining[None, :] * frac[:, None]
        cap_exceeded = ~jnp.all(proposed <= capability + _EPS, axis=-1)
        new_deserved = jnp.minimum(jnp.minimum(proposed, capability), request)
        new_deserved = jnp.where(meet[:, None], deserved, new_deserved)
        new_meet = meet | cap_exceeded | jnp.all(request <= proposed + _EPS,
                                                 axis=-1)
        delta = jnp.sum(new_deserved - deserved, axis=0)
        return (new_deserved, remaining - delta, new_meet, remaining, it + 1)

    init = (jnp.zeros((Q, R), jnp.float32), total.astype(jnp.float32),
            ~queues.valid, total.astype(jnp.float32) + 1.0, jnp.int32(0))
    deserved, *_ = jax.lax.while_loop(cond, body, init)
    return deserved


def dominant_share(allocated: jax.Array, total: jax.Array) -> jax.Array:
    """f32[...]: max over resource dims of allocated/total — the DRF share
    (drf.go calcShare; dims with zero cluster capacity are ignored)."""
    frac = jnp.where(total > 0, allocated / jnp.maximum(total, _EPS), 0.0)
    return jnp.max(frac, axis=-1)


def drf_job_shares(job_allocated: jax.Array, total: jax.Array,
                   valid: jax.Array) -> jax.Array:
    """f32[J]: per-job dominant-resource share used as the drf JobOrderFn key
    (drf.go:454-472) and preemption fairness test (drf.go:330-360)."""
    return jnp.where(valid, dominant_share(job_allocated, total), jnp.inf)


def namespace_shares(job_allocated: jax.Array, job_namespace: jax.Array,
                     job_valid: jax.Array, ns_weight: jax.Array,
                     total: jax.Array) -> jax.Array:
    """f32[S]: weighted namespace dominant share (drf namespaceOrderFn,
    drf.go:474-507): share(ns) = dominantShare(sum of member jobs) / weight."""
    S = ns_weight.shape[0]
    contrib = jnp.where(job_valid[:, None], job_allocated, 0.0)
    ns_alloc = jax.ops.segment_sum(contrib, job_namespace, num_segments=S)
    return dominant_share(ns_alloc, total) / jnp.maximum(ns_weight, 1.0)


def hierarchical_shares(queues: QueueArrays, total: jax.Array,
                        hierarchy_weight: jax.Array,
                        max_depth: int = 8) -> jax.Array:
    """f32[Q]: hdrf-style queue ordering key over the parent-pointer tree.

    The fork's hdrf (drf.go:230-360) water-fills dominant shares level by
    level down the queue hierarchy. Here each queue's key is the maximum
    weighted dominant share along its ancestor chain — a queue whose subtree
    (or any ancestor's subtree) is over-served sorts later. Subtree
    allocations are accumulated by propagating ``allocated`` up ``max_depth``
    parent steps.
    """
    Q = queues.allocated.shape[0]
    parent = queues.parent

    def step(carry, _):
        subtree, cursor = carry
        has_anc = cursor >= 0
        idx = jnp.where(has_anc, cursor, 0)
        contrib = jnp.where(has_anc[:, None], queues.allocated, 0.0)
        subtree = subtree + jax.ops.segment_sum(contrib, idx, num_segments=Q)
        cursor = jnp.where(has_anc, parent[idx], -1)
        return (subtree, cursor), None

    (subtree, _), _ = jax.lax.scan(step, (queues.allocated, parent),
                                   None, length=max_depth)
    # subtree[q] = own allocation + all descendants' (within max_depth);
    # a queue orders by the worst weighted share along its own subtree.
    return dominant_share(subtree, total) / jnp.maximum(hierarchy_weight, 1.0)
