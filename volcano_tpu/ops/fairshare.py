"""Fair-share solvers: proportion water-filling, DRF, hierarchical DRF.

TPU re-design of the reference's fairness plugins:
- proportion's iterative deserved-share water-filling
  (pkg/scheduler/plugins/proportion/proportion.go:140-197) becomes a bounded
  ``lax.while_loop`` over dense [Q, R] arrays with branchless clamping.
- drf's dominant-resource shares (pkg/scheduler/plugins/drf/drf.go:104-131,
  calcShare) become one masked max-reduce per job.
- the fork's hierarchical DRF (drf.go:42-87, 230-360) is computed over the
  packed parent-pointer queue tree by propagating subtree allocations up a
  fixed number of levels.

All solvers run inside the same jit as the allocate pass.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..api.resource import MIN_RESOURCE
from ..arrays.schema import QueueArrays

_EPS = 1e-9


def proportion_deserved(queues: QueueArrays, total: jax.Array,
                        max_iters: int = 16) -> jax.Array:
    """f32[Q, R]: each queue's deserved share by weighted water-filling.

    Exact port of the fixed point computed by proportion.go:140-197:
    repeatedly hand each unmet queue ``remaining * w_q / sum(unmet weights)``,
    clamp elementwise by capability and request (all three branches of the Go
    code reduce to ``min(deserved', capability?, request)`` with capability
    applied only when exceeded — the min is a no-op otherwise, so the
    branchless form is identical), mark queues meeting their request or
    capability, and recycle the clamped-off amount into ``remaining``.
    """
    Q, R = queues.allocated.shape
    weight = jnp.where(queues.valid, queues.weight, 0.0)
    request = queues.request
    capability = queues.capability

    def cond(st):
        deserved, remaining, meet, prev_remaining, it = st
        total_w = jnp.sum(jnp.where(meet, 0.0, weight))
        changed = jnp.any(jnp.abs(remaining - prev_remaining) > _EPS)
        nonempty = jnp.any(remaining >= MIN_RESOURCE)
        return (total_w > 0) & nonempty & changed & (it < max_iters)

    def body(st):
        deserved, remaining, meet, _prev, it = st
        total_w = jnp.sum(jnp.where(meet, 0.0, weight))
        frac = jnp.where(meet, 0.0, weight) / jnp.maximum(total_w, _EPS)
        proposed = deserved + remaining[None, :] * frac[:, None]
        cap_exceeded = ~jnp.all(proposed <= capability + _EPS, axis=-1)
        new_deserved = jnp.minimum(jnp.minimum(proposed, capability), request)
        new_deserved = jnp.where(meet[:, None], deserved, new_deserved)
        new_meet = meet | cap_exceeded | jnp.all(request <= proposed + _EPS,
                                                 axis=-1)
        delta = jnp.sum(new_deserved - deserved, axis=0)
        return (new_deserved, remaining - delta, new_meet, remaining, it + 1)

    init = (jnp.zeros((Q, R), jnp.float32), total.astype(jnp.float32),
            ~queues.valid, total.astype(jnp.float32) + 1.0, jnp.int32(0))
    deserved, *_ = jax.lax.while_loop(cond, body, init)
    return deserved


def dominant_share(allocated: jax.Array, total: jax.Array) -> jax.Array:
    """f32[...]: max over resource dims of allocated/total — the DRF share
    (drf.go calcShare; dims with zero cluster capacity are ignored)."""
    frac = jnp.where(total > 0, allocated / jnp.maximum(total, _EPS), 0.0)
    return jnp.max(frac, axis=-1)


def drf_job_shares(job_allocated: jax.Array, total: jax.Array,
                   valid: jax.Array) -> jax.Array:
    """f32[J]: per-job dominant-resource share used as the drf JobOrderFn key
    (drf.go:454-472) and preemption fairness test (drf.go:330-360)."""
    return jnp.where(valid, dominant_share(job_allocated, total), jnp.inf)


def namespace_shares(job_allocated: jax.Array, job_namespace: jax.Array,
                     job_valid: jax.Array, ns_weight: jax.Array,
                     total: jax.Array) -> jax.Array:
    """f32[S]: weighted namespace dominant share (drf namespaceOrderFn,
    drf.go:474-507): share(ns) = dominantShare(sum of member jobs) / weight."""
    S = ns_weight.shape[0]
    contrib = jnp.where(job_valid[:, None], job_allocated, 0.0)
    ns_alloc = jax.ops.segment_sum(contrib, job_namespace, num_segments=S)
    return dominant_share(ns_alloc, total) / jnp.maximum(ns_weight, 1.0)


def _seg_sum(vals, idx, mask, num):
    """Masked segment sum: masked-out rows are dropped (index -> num)."""
    if vals.ndim > mask.ndim:
        sel = jnp.where(mask[..., None], vals, 0.0)
    else:
        sel = jnp.where(mask, vals, 0.0)
    return jax.ops.segment_sum(sel, jnp.where(mask, idx, num),
                               num_segments=num + 1)[:num]


def _seg_min(vals, idx, mask, num):
    sel = jnp.where(mask, vals, jnp.inf)
    return jax.ops.segment_min(sel, jnp.where(mask, idx, num),
                               num_segments=num + 1)[:num]


def hdrf_tree_state(hier, job_alloc: jax.Array, job_request: jax.Array,
                    job_valid: jax.Array, total: jax.Array):
    """Exact bottom-up hdrf tree update (drf.go:693-767).

    Level-synchronous re-design of ``updateHierarchicalShare``: for each
    depth from the deepest up, every internal node rescales its unsaturated
    children's allocations to the minimum dominant share among them
    (``mdr / child.share``, drf.go:704-745), sums them, and recomputes its
    own dominant share; a node is saturated when ALL its children are.
    Job leaves saturate per ``resourceSaturated`` (drf.go:90-103): any
    resource where the job's allocation meets its request, or where it
    requests a resource the cluster has fully allocated.

    Inputs: ``hier`` HierarchyArrays (arrays/hierarchy.py), per-job live
    allocation/request ([J, R]), validity, cluster totals f32[R].
    Returns (share f32[H], saturated bool[H], allocated f32[H, R]).
    """
    H = hier.parent.shape[0]
    D = hier.queue_path.shape[1]
    jmask = job_valid & (hier.job_leaf >= 0)
    leaf = jnp.maximum(hier.job_leaf, 0)
    job_share = dominant_share(job_alloc, total)
    total_alloc = jnp.sum(jnp.where(jmask[:, None], job_alloc, 0.0), axis=0)
    demanding = total_alloc < total                       # bool[R]
    job_sat = jnp.any(
        ((job_alloc > _EPS) & (job_request > _EPS)
         & (job_alloc >= job_request - _EPS))
        | (~demanding[None, :] & (job_request > _EPS)), axis=-1)
    job_depth = hier.depth[leaf]

    share = jnp.zeros(H, jnp.float32)
    sat = jnp.ones(H, bool)
    alloc = jnp.zeros((H, total.shape[0]), jnp.float32)
    parent = jnp.maximum(hier.parent, 0)

    for d in reversed(range(D)):
        child = hier.valid & (hier.depth == d + 1)
        jat = jmask & (job_depth == d)
        # minimum dominant share over contributing (non-empty, unsaturated)
        # children (drf.go:704-719)
        mdr = jnp.minimum(
            _seg_min(share, parent, child & (share > _EPS) & ~sat, H),
            _seg_min(job_share, leaf, jat & (job_share > _EPS) & ~job_sat, H))
        mdr = jnp.minimum(mdr, 1.0)
        # rescaled allocation sum: saturated children unscaled, unsaturated
        # scaled by mdr/share, empty children skipped (drf.go:724-743)
        c_scale = jnp.where(share > _EPS,
                            jnp.where(sat, 1.0,
                                      mdr[parent] / jnp.maximum(share, _EPS)),
                            0.0)
        j_scale = jnp.where(job_share > _EPS,
                            jnp.where(job_sat, 1.0,
                                      mdr[leaf] / jnp.maximum(job_share, _EPS)),
                            0.0)
        new_alloc = (_seg_sum(alloc * c_scale[:, None], parent, child, H)
                     + _seg_sum(job_alloc * j_scale[:, None], leaf, jat, H))
        unsat = (_seg_sum((~sat).astype(jnp.float32), parent, child, H)
                 + _seg_sum((~job_sat).astype(jnp.float32), leaf, jat, H))
        at_d = hier.valid & (hier.depth == d)
        share = jnp.where(at_d, dominant_share(new_alloc, total), share)
        sat = jnp.where(at_d, unsat == 0, sat)
        alloc = jnp.where(at_d[:, None], new_alloc, alloc)
    return share, sat, alloc


def hdrf_level_keys(hier, job_alloc: jax.Array, job_request: jax.Array,
                    job_valid: jax.Array, total: jax.Array) -> jax.Array:
    """f32[Q, 2D]: per-queue lexicographic hdrf ordering key columns.

    ``compareQueues`` (drf.go:182-218) walks both queues' paths from root:
    at each level an unsaturated node beats a saturated one, then the lower
    ``share/weight`` wins, ties descend. That is a lexicographic compare
    over per-level (saturated, share/weight) pairs — emitted here as
    interleaved columns for :func:`~volcano_tpu.ops.select.lex_argmin`.
    Levels past a queue's path end emit -1 (the reference treats exhausted
    common prefixes as a tie and falls back to heap order; -1 keeps shorter
    paths first on full-prefix ties — documented divergence).
    """
    share, sat, _ = hdrf_tree_state(hier, job_alloc, job_request, job_valid,
                                    total)
    D = hier.queue_path.shape[1]
    path = hier.queue_path                                 # [Q, D]
    on_path = path >= 0
    node = jnp.maximum(path, 0)
    sat_col = jnp.where(on_path, sat[node].astype(jnp.float32), -1.0)
    share_col = jnp.where(
        on_path, share[node] / jnp.maximum(hier.weight[node], 1.0), -1.0)
    cols = jnp.stack([sat_col, share_col], axis=-1)        # [Q, D, 2]
    return cols.reshape(path.shape[0], 2 * D)
