"""Enqueue pass: gate Pending PodGroups into Inqueue phase.

TPU re-design of the enqueue action (pkg/scheduler/actions/enqueue/
enqueue.go:43-102) and its JobEnqueueable voters: proportion's queue-quota
test — permit iff ``minResources + allocated + inqueue <= capability``,
always permit when the queue declares no capability
(proportion.go:254-280) — overcommit's cluster-factor test
(pkg/scheduler/plugins/overcommit/overcommit.go:28-124), and sla's
waiting-deadline override (pkg/scheduler/plugins/sla/sla.go:146-148).

Like the reference, admission is sequential — each admitted job's
MinResources immediately counts against its queue for the next candidate —
so the pass is a scan over jobs in queue/priority/FIFO order.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from ..arrays.schema import SnapshotArrays
from .select import sort_order

_EPS = 1e-5


@dataclass(frozen=True)
class EnqueueConfig:
    enable_proportion_gate: bool = True
    enable_overcommit_gate: bool = False
    overcommit_factor: float = 1.2   # overcommit.go default
    # sla override: jobs whose waiting time exceeded the SLA are always
    # admitted (sla.go:146-148); wait flags are computed host-side.


def make_enqueue_pass(cfg: EnqueueConfig):
    """Returns enqueue(snap, sla_waiting) -> bool[J] newly admitted
    (Pending -> Inqueue) jobs. ``sla_waiting`` bool[J] marks jobs past their
    SLA waiting deadline."""

    def enqueue(snap: SnapshotArrays,
                sla_waiting: jax.Array) -> jax.Array:
        snap = jax.tree.map(jnp.asarray, snap)
        jobs, queues, nodes = snap.jobs, snap.queues, snap.nodes
        J = jobs.min_available.shape[0]
        Q, R = queues.allocated.shape

        candidate = (jobs.valid & jobs.pending_phase
                     & queues.open[jobs.queue] & queues.valid[jobs.queue])
        order = sort_order([
            jobs.queue.astype(jnp.float32),
            -jobs.priority.astype(jnp.float32),
            jobs.creation_rank.astype(jnp.float32),
        ], candidate)

        total_idle = jnp.sum(jnp.where(nodes.valid[:, None], nodes.idle, 0.0),
                             axis=0)
        total_alloc = jnp.sum(
            jnp.where(nodes.valid[:, None], nodes.allocatable, 0.0), axis=0)

        def step(carry, ji):
            q_inqueue, cluster_inqueue, admitted = carry
            ok = candidate[ji]
            qi = jobs.queue[ji]
            minres = jobs.min_resources[ji]

            permit = jnp.bool_(True)
            if cfg.enable_proportion_gate:
                # permit iff minReq + allocated + inqueue <= capability;
                # unset capability dims are +inf -> always permit
                # (proportion.go:254-280)
                used = minres + queues.allocated[qi] + q_inqueue[qi]
                permit &= jnp.all(used <= queues.capability[qi] + _EPS)
            if cfg.enable_overcommit_gate:
                head = (total_alloc * cfg.overcommit_factor
                        - (total_alloc - total_idle) - cluster_inqueue)
                permit &= jnp.all(minres <= head + _EPS)
            permit = permit | sla_waiting[ji]
            admit = ok & permit

            upd = jnp.where(admit, jnp.float32(1.0), jnp.float32(0.0)) \
                * minres
            q_inqueue = q_inqueue.at[qi].add(upd)
            cluster_inqueue = cluster_inqueue + upd
            admitted = admitted.at[ji].set(admit)
            return (q_inqueue, cluster_inqueue, admitted), None

        init = (queues.inqueue_minres, jnp.sum(queues.inqueue_minres, axis=0),
                jnp.zeros(J, bool))
        (_, _, admitted), _ = jax.lax.scan(step, init, order)
        return admitted

    return enqueue
