"""Device-mesh parallelism for the scheduling cycle."""

from .sharding import (make_sharded_allocate, make_sharded_preempt,
                       node_sharding_specs,
                       scheduler_mesh)

__all__ = ["make_sharded_allocate", "make_sharded_preempt",
           "node_sharding_specs", "scheduler_mesh"]
