"""Device-mesh parallelism for the scheduling cycle."""

from .sharding import (make_sharded_allocate, make_sharded_delta,
                       make_sharded_preempt, mesh_for_nodes, node_leaf_mask,
                       node_sharding_specs, scheduler_mesh,
                       sharded_delta_allocate_cached)

__all__ = ["make_sharded_allocate", "make_sharded_delta",
           "make_sharded_preempt", "mesh_for_nodes", "node_leaf_mask",
           "node_sharding_specs", "scheduler_mesh",
           "sharded_delta_allocate_cached"]
