"""Device-mesh parallelism for the scheduling cycle."""

from .sharding import (make_sharded_allocate, node_sharding_specs,
                       scheduler_mesh)

__all__ = ["make_sharded_allocate", "node_sharding_specs", "scheduler_mesh"]
