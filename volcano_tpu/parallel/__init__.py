"""Device-mesh parallelism for the scheduling cycle."""

from .distributed import (host_shard_range, initialize_distributed,
                          mask_foreign_shards)
from .health import HEALTH, DeviceHealthRegistry, failed_devices
from .sharding import (invalidate_mesh_cache, make_sharded_allocate,
                       make_sharded_delta, make_sharded_preempt,
                       mesh_for_nodes, node_leaf_mask, node_sharding_specs,
                       scheduler_mesh, sharded_delta_allocate_cached)

__all__ = ["HEALTH", "DeviceHealthRegistry", "failed_devices",
           "host_shard_range", "initialize_distributed",
           "invalidate_mesh_cache", "mask_foreign_shards",
           "make_sharded_allocate", "make_sharded_delta",
           "make_sharded_preempt", "mesh_for_nodes", "node_leaf_mask",
           "node_sharding_specs", "scheduler_mesh",
           "sharded_delta_allocate_cached"]
