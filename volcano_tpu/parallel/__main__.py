"""``python -m volcano_tpu.parallel --bench`` — the multichip bench probe.

Runs the SAME multi-cycle churned scheduler workload once unsharded and
once per requested device count on the node-axis sharded backend
(``sharding: true``), and prints one JSON report:

- per-device-count steady-state cycle p50 (warm delta cycles only),
- ``decisions_equal_unsharded`` — the sha over every cycle's decision
  digest must match the unsharded run bit-for-bit,
- ``resharding_copies`` — the live transfer-counter probe's total over
  the steady cycles; the zero-copy out==in contract means 0,
- ``pallas`` — the same sharded workload with the shard-local pallas
  candidate kernel in interpret mode (ISSUE 14): steady p50 next to the
  scan column plus its own ``decisions_equal`` identity gate,
- ``scaling_efficiency`` — p50(1dev) / (D * p50(Ddev)) on the sharded
  scan runs; 1.0 is perfect strong scaling.

bench.py shells out to this module (fail-soft, BENCH_SKIP_MULTICHIP=1
skips) so a GSPMD-poisoned compile can never take the bench record down
with it; the CLI is equally usable standalone on a real TPU pod slice.
Exit 0 with the report on stdout; exit 2 on harness error.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import sys
import time


def _run_variant(conf_text: str, base, cycles: int, pipeline: bool):
    from ..framework.conf import parse_conf
    from ..runtime.fake_cluster import FakeCluster
    from ..runtime.driver import step_cycle
    from ..runtime.scheduler import Scheduler
    from ..chaos.probe import _churn, _cycle_digest
    cluster = FakeCluster(base.clone())
    sched = Scheduler(cluster, conf=parse_conf(conf_text), pipeline=pipeline)
    digests, wall_ms = [], []
    for c in range(cycles):
        t0 = time.perf_counter()
        rec = step_cycle(sched, now=1000.0 + c)
        wall_ms.append((time.perf_counter() - t0) * 1e3)
        digests.append(_cycle_digest(rec))
        _churn(cluster, c)
    sha = hashlib.sha256(repr(digests).encode()).hexdigest()[:16]
    flight = sched.flight.snapshots()
    steady = sorted(ms for c, ms in enumerate(wall_ms) if c >= 2)
    return {
        "decisions_sha": sha,
        "steady_p50_ms": (round(steady[len(steady) // 2], 2)
                          if steady else None),
        "delta_cycles": sum(1 for e in flight
                            if e.get("cycle_kind") == "delta"),
        "mesh_devices": next(
            (int(e["mesh_devices"]) for e in reversed(flight)
             if e.get("mesh_devices") is not None), None),
        "resharding_copies": sum(
            int(e["resharding_copies"]) for e in flight
            if e.get("resharding_copies") is not None),
    }


def run_multichip(device_counts, cycles: int = 6, n_nodes: int = 16,
                  pipeline: bool = False) -> dict:
    """The comparison matrix: unsharded oracle + one sharded run per
    device count, all over identical churned clusters."""
    import jax

    from ..chaos.probe import _small_cluster
    base = _small_cluster(n_nodes=n_nodes, n_jobs=12, tasks_per_job=3)
    body = """
actions: "enqueue, allocate"
tiers:
- plugins:
  - name: priority
  - name: gang
- plugins:
  - name: predicates
  - name: proportion
  - name: binpack
"""
    oracle = _run_variant(body, base, cycles, pipeline)
    per_device = {}
    for d in device_counts:
        if d > jax.device_count():
            per_device[str(d)] = {"skipped": f"only {jax.device_count()} "
                                             "devices visible"}
            continue
        r = _run_variant(f"sharding: true\nsharding_devices: {d}\n" + body,
                         base, cycles, pipeline)
        r["decisions_equal_unsharded"] = (
            r.pop("decisions_sha") == oracle["decisions_sha"])
        # the shard-local pallas leg (ISSUE 14): same sharded workload
        # with the candidate kernel in interpret mode — identity is the
        # gate, p50 the comparison column. Fail-soft per leg: a pallas
        # harness failure must not take the scan columns down with it.
        try:
            p = _run_variant(
                f"sharding: true\nsharding_devices: {d}\n"
                f"use_pallas: interpret\n" + body, base, cycles, pipeline)
            r["pallas"] = {
                "steady_p50_ms": p["steady_p50_ms"],
                "decisions_equal": (p["decisions_sha"]
                                    == oracle["decisions_sha"]),
                "resharding_copies": p["resharding_copies"],
            }
        except Exception as e:
            print(f"multichip pallas leg failed at {d} devices: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
            r["pallas"] = {"error": f"{type(e).__name__}: {e}"}
        per_device[str(d)] = r
    # strong-scaling efficiency of the sharded scan p50 relative to the
    # 1-device sharded run: p50(1) / (D * p50(D)); 1.0 = perfect
    base_p50 = per_device.get("1", {}).get("steady_p50_ms")
    for d in device_counts:
        rec = per_device.get(str(d), {})
        p50 = rec.get("steady_p50_ms")
        if base_p50 and p50:
            rec["scaling_efficiency"] = round(base_p50 / (d * p50), 3)
        elif "skipped" not in rec:
            rec["scaling_efficiency"] = None
    return {
        "cycles": cycles,
        "n_nodes": n_nodes,
        "pipeline": pipeline,
        "devices_visible": jax.device_count(),
        "unsharded_steady_p50_ms": oracle["steady_p50_ms"],
        "unsharded_sha": oracle["decisions_sha"],
        "per_device_count": per_device,
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="multichip probe: sharded cycle vs unsharded oracle")
    parser.add_argument("--bench", action="store_true",
                        help="run the comparison matrix and print JSON")
    parser.add_argument("--devices", default="1,2,8",
                        help="comma-separated device counts to try")
    parser.add_argument("--cycles", type=int, default=6)
    parser.add_argument("--nodes", type=int, default=16)
    parser.add_argument("--pipeline", action="store_true",
                        help="drive the pipelined loop instead of sync")
    args = parser.parse_args(argv)
    counts = [int(d) for d in args.devices.split(",") if d.strip()]
    try:
        report = run_multichip(counts, cycles=args.cycles,
                               n_nodes=args.nodes, pipeline=args.pipeline)
    except Exception as e:  # harness failure, not a measurement
        print(json.dumps({"error": f"{type(e).__name__}: {e}"}))
        return 2
    print(json.dumps(report, indent=2))
    ok = all(r.get("decisions_equal_unsharded", True)
             and r.get("resharding_copies", 0) == 0
             and r.get("pallas", {}).get("decisions_equal", True)
             is not False
             for r in report["per_device_count"].values())
    if not ok:
        print("multichip probe FAILED: sharded decisions diverged or "
              "steady cycles paid resharding copies", file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
