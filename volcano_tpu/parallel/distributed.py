"""Multi-host mesh groundwork (pod-slice scale-out, ISSUE 14).

Single-process CPU meshes exercise every sharded code path today; this
module adds the two pieces a REAL pod slice needs, shaped so the driver
is the only missing part:

- :func:`initialize_distributed` — the ``jax.distributed.initialize``
  entry point, conf (``mesh_hosts``) / env driven, and a strict no-op in
  a single-process run: nothing in the single-host paths changes by
  importing or calling it. It never raises on missing coordination env;
  it reports what it did (or why it didn't) in its summary dict so the
  runtime can log it.
- per-host delta routing — :func:`host_shard_range` and
  :func:`mask_foreign_shards` layer on the existing (D, B) shard-routed
  upload (ops/fused_io.ShardedDeltaKernel._route): each process keeps
  ONLY its own hosts' shard rows as real updates and rewrites every
  foreign row to the router's drop encoding (the positive out-of-bounds
  index drop-mode discards), so no host materializes another host's
  delta content. The union of all hosts' masked uploads applies exactly
  the full routed delta — the unit tests in tests/test_distributed.py
  prove this equivalence.

Environment contract (all optional; absent -> single-process no-op):

- ``VOLCANO_MESH_HOSTS``       number of host processes (conf
  ``mesh_hosts`` wins when both are set)
- ``VOLCANO_COORDINATOR``      ``host:port`` of process 0
- ``VOLCANO_PROCESS_ID``       this process's rank in [0, n_hosts)
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np

__all__ = ["initialize_distributed", "host_shard_range",
           "mask_foreign_shards"]


def _env_int(name: str) -> Optional[int]:
    v = os.environ.get(name)
    if v is None or v == "":
        return None
    try:
        return int(v)
    except ValueError:
        return None


def initialize_distributed(conf=None) -> dict:
    """Initialize JAX multi-process coordination when (and only when)
    the run is actually multi-host.

    ``conf`` is a SchedulerConfiguration (or anything with a
    ``mesh_hosts`` attribute) — ``mesh_hosts`` > 1 plus the coordinator
    env vars select the multi-process path; everything else is a no-op.
    Never raises on missing/partial configuration: the summary dict's
    ``reason`` says why initialization was skipped, and the runtime
    keeps its single-process behavior bit-for-bit.

    Returns ``{"initialized", "n_hosts", "process_id", "reason"}``.
    """
    n_hosts = getattr(conf, "mesh_hosts", None) if conf is not None else None
    if n_hosts is None:
        n_hosts = _env_int("VOLCANO_MESH_HOSTS")
    n_hosts = int(n_hosts) if n_hosts else 1
    summary = {"initialized": False, "n_hosts": n_hosts, "process_id": 0,
               "reason": ""}
    if n_hosts <= 1:
        summary["reason"] = "single-process (mesh_hosts <= 1)"
        return summary
    coordinator = os.environ.get("VOLCANO_COORDINATOR")
    process_id = _env_int("VOLCANO_PROCESS_ID")
    if not coordinator or process_id is None:
        summary["reason"] = ("mesh_hosts > 1 but VOLCANO_COORDINATOR / "
                             "VOLCANO_PROCESS_ID are not set; staying "
                             "single-process")
        return summary
    import jax
    try:
        jax.distributed.initialize(coordinator_address=coordinator,
                                   num_processes=n_hosts,
                                   process_id=process_id)
    except Exception as e:  # already-initialized or backend refusal:
        # fail soft, the single-process paths stay fully functional
        summary["reason"] = f"jax.distributed.initialize failed: {e}"
        return summary
    summary.update(initialized=True, process_id=process_id,
                   reason="jax.distributed.initialize ok")
    return summary


def host_shard_range(n_shards: int, n_hosts: int,
                     host_id: int) -> Tuple[int, int]:
    """Contiguous [lo, hi) shard rows owned by ``host_id``.

    Shards split as evenly as possible with the remainder spread over
    the leading hosts (the same contiguous-block rule a (hosts, local
    devices) reshape of the 1-D node mesh produces, so shard ownership
    matches device locality on a real slice). The union over hosts is
    exactly [0, n_shards) with no overlap — asserted by the routing
    equivalence tests."""
    if not 0 <= host_id < n_hosts:
        raise ValueError(f"host_id {host_id} outside [0, {n_hosts})")
    base, rem = divmod(n_shards, n_hosts)
    lo = host_id * base + min(host_id, rem)
    hi = lo + base + (1 if host_id < rem else 0)
    return lo, hi


def mask_foreign_shards(pidx: np.ndarray, pvals: np.ndarray,
                        rows_per: int, n_cols: int,
                        lo: int, hi: int):
    """Per-host view of a (D, B) shard-routed delta: rows in [lo, hi)
    pass through untouched; every foreign row is rewritten to the
    router's empty-shard drop encoding (``(s + 1) * rows_per * C``
    rebases to the local out-of-bounds row, which the scatter's
    drop-mode discards) with zero values.

    This is the per-host upload contract: a process feeds its own rows
    real content and ships inert rows for everyone else, so the full
    (D, B) shape (and therefore the compiled entry) is identical on
    every host while no host materializes foreign delta content."""
    D, B = pidx.shape
    out_idx = pidx.copy()
    out_vals = pvals.copy()
    if B == 0 or n_cols == 0:
        return out_idx, out_vals
    for s in range(D):
        if lo <= s < hi:
            continue
        out_idx[s] = (s + 1) * rows_per * n_cols
        out_vals[s] = 0
    return out_idx, out_vals
